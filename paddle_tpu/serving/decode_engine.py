"""Continuous decode batching: the LLM-serving request type (ISSUE 7).

Iteration-level (Orca-style) scheduling over N decode replicas: each
replica owns a model adapter plus ONE paged KV-cache
(ops/paged_kv.PagedKVCache) and runs a supervised iteration loop —
every iteration, NEW sequences join the running batch (prompt KV
prefilled into fresh pages), ONE decode step runs for the whole batch
(ops.pallas_kernels.flash_decode over the shared page pool), and
FINISHED sequences retire (pages freed, Request future answered) —
the batch composition changes every token, not every request.

The request path reuses the PR-6 serving discipline verbatim:

  - admission: the same ``AdmissionController`` — bounded queue, typed
    shedding (OverloadedError / DeadlineExpiredError / ShutdownError /
    ReplicaFailedError), every ADMITTED sequence answered EXACTLY once
    (request-id accounting);
  - deadlines: shed at submit, before joining the batch, and checked
    every iteration mid-generation (a typed expiry carries whatever
    compute was already spent — the reply is typed either way);
  - drain: stop admitting, let running sequences finish, answer
    leftovers with the typed ShutdownError; after drain every replica
    cache must satisfy ``free + in_use == num_pages`` with
    ``in_use == 0`` — ZERO page leaks (the chaos soak asserts it);
  - failover: a replica killed mid-step (faultinject msg type
    ``serving_decode``) pushes its live sequences — full token history
    — onto an unbounded retry lane; a survivor re-prefills them from
    history and generation continues.  The dead replica's cache is
    reset (all pages back to free), so a kill can corrupt nothing and
    leak nothing.
  - pool pressure: a batch that cannot take one more page PREEMPTS a
    sequence back to the retry lane (tokens-so-far preserved) instead
    of corrupting the pool — vLLM-style preemption as the
    backpressure of paging.  The victim policy is DEADLINE-AWARE
    (ISSUE 11 satellite): scanning youngest -> oldest, the first
    sequence whose deadline could afford a re-prefill is evicted; a
    sequence that would miss its deadline if re-prefilled is spared
    while a less constrained one exists, and when every candidate is
    at risk the youngest goes (the pinned legacy tie-break).

Decode speed act II (ISSUE 11), three legs, each behind its own
default-off typed flag with the repo's bit-parity discipline:

  - CHUNKED PREFILL (flag ``prefill_chunk`` / DecodeConfig knob): a
    prompt longer than the chunk joins incrementally — ONE fixed-size
    chunk of projections + page writes per iteration (chunk shape
    padded to exactly the chunk size: one compile), interleaved with
    the running batch's decode steps, so a 32k-token join never
    stretches running streams' inter-token p99 (the PR-10
    ``decode_inter_token`` SLO is the acceptance instrument).
    Chunked output is bit-identical to whole-prefill.
  - PREFIX SHARING (flag ``kv_share``): prompt prefill consults the
    cache's radix tree first — the longest already-cached full-page
    prefix is SHARED (refcounted, zero projections, zero writes), so
    N requests behind one system prompt pay its prefill once.
  - LOSSLESS SPECULATIVE DECODING (flag ``spec_k``): a small draft
    model (its own paged cache per replica) proposes k tokens, ONE
    batched q-len-(k+1) flash_decode verify step scores them,
    ``decode.spec_accept_length`` takes the longest agreeing prefix,
    and rejection is a page-pointer rewind (PagedKVCache.truncate)
    through the atomic free path — speculative greedy output is
    token-for-token identical to non-speculative greedy (asserted).

Disaggregated prefill/decode tiers (ISSUE 14, flag
``disagg_prefill``): the server splits into a PREFILL pool
(compute-bound prompt projections + page writes;
``n_prefill_replicas`` workers) and the decode pool behind the SAME
admission plane, every decode replica reading ONE shared page pool.
A finished prefill reaches the decode tier as a PAGE-LIST handoff
(``PagedKVCache.detach``/``adopt`` — block-table entries + per-page
refcounts, zero K/V device bytes moved), with a typed
``HandoffError`` terminal code, deadline propagation across the tier
boundary (expiry in transit releases the pages and answers typed),
and exactly-once accounting when a replica on EITHER side dies
mid-handoff: a prefill kill after allocation aborts the handoff and
re-prefills on a survivor; a decode kill after adoption frees only
its slots on the shared pool (never a wholesale reset) and the
prefill tier re-prefills from token history.  Fault point
``serving_prefill`` sits exactly in the post-allocation /
pre-adoption window (``chaos_soak --mode disagg`` pins kills in both
windows).  docs/SERVING.md has the handoff state machine.

Model adapter protocol (duck-typed; ``TinyDecodeLM`` is the built-in
used by tests, the load generator and the bench):

    model.vocab / num_heads / head_dim      (ints)
    model.qkv(tokens [N] int32) -> (q, k, v) each [N, H, d]
    model.logits(attn_out [N, H, d]) -> [N, vocab]

The engine is greedy (argmax) per step; eos or max_new_tokens retires
a sequence.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time

import numpy as np

from paddle_tpu.concurrency import BoundedQueue, Supervisor
from paddle_tpu.distributed import faultinject
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _obs_metrics
from paddle_tpu.observability import tracing as _trace
from paddle_tpu.observability.export import (MetricsHTTPServer,
                                             metrics_port_from_env)
from paddle_tpu.ops.epilogue import greedy_logits_tail
from paddle_tpu.ops.paged_kv import OutOfPagesError, PagedKVCache
from paddle_tpu.serving.admission import (AdmissionController,
                                          DeadlineExpiredError,
                                          HandoffError,
                                          ReplicaFailedError,
                                          ShutdownError)
from paddle_tpu.serving.replica_pool import ReplicaKilled, ReplyLost

__all__ = ["MSG_DECODE", "MSG_PREFILL", "TinyDecodeLM",
           "DecodeConfig", "DecodeServer"]

MSG_DECODE = faultinject.register_msg_type("serving_decode")
# disaggregated prefill tier (ISSUE 14): one faultinject decision per
# prefill, consulted AFTER the pages are allocated and detached into
# the handoff — the kill-mid-handoff window the chaos soak seeds
MSG_PREFILL = faultinject.register_msg_type("serving_prefill")

_M_DECODE = _obs_metrics.counter(
    "paddle_tpu_decode_events_total",
    "decode-server transitions (iterations / tokens_out / prefills / "
    "prefill_chunks / kills / step_faults / failovers / preemptions / "
    "retires / spec_proposed / spec_accepted), by event")
_M_STEP_MS = _obs_metrics.histogram(
    "paddle_tpu_decode_inter_token_seconds",
    "per-sequence inter-token latency")
_M_PAGE_UTIL = _obs_metrics.gauge(
    "paddle_tpu_decode_page_utilization",
    "in_use / num_pages of each replica's page pool, by replica "
    "index", max_series=64)
_M_ACTIVE = _obs_metrics.gauge(
    "paddle_tpu_decode_active_seqs",
    "sequences in the running batch, by replica index",
    max_series=64)
# disaggregated-tier instruments (ISSUE 14 satellite): handoff
# outcomes + latency (exemplar-capable per PR 12 — the p99 bucket
# names a sampled trace) + per-tier replica/page gauges, all embedded
# in the serving_load / chaos_soak one-JSON-line outputs
_M_HANDOFFS = _obs_metrics.counter(
    "paddle_tpu_disagg_handoffs_total",
    "prefill->decode page-list handoffs by outcome (offered / "
    "adopted / lost / expired / orphaned / killed)")
_M_HANDOFF_SECONDS = _obs_metrics.histogram(
    "paddle_tpu_disagg_handoff_seconds",
    "prefill-complete -> decode-adoption latency of page-list "
    "handoffs")
_G_TIER_REPLICAS = _obs_metrics.gauge(
    "paddle_tpu_disagg_tier_replicas",
    "live replicas per disaggregated tier (prefill / decode)",
    max_series=8)
_G_TIER_PAGES = _obs_metrics.gauge(
    "paddle_tpu_disagg_pages",
    "shared-pool page occupancy of the disaggregated server "
    "(in_use / in_transit / free)", max_series=8)


class TinyDecodeLM:
    """Deterministic seeded single-layer attention LM — the built-in
    model adapter (tests / tools/serving_load.py --mode decode / the
    bench decode leg).  Positionless on purpose: logits depend on the
    full cached prefix through attention only, so correct paged
    attention (and ONLY correct paged attention) reproduces the dense
    decode exactly."""

    def __init__(self, vocab=128, d_model=64, num_heads=4, head_dim=16,
                 seed=0, dtype=None):
        import jax
        import jax.numpy as jnp

        self.vocab = int(vocab)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        dtype = dtype or jnp.float32
        rng = np.random.RandomState(seed)
        hd = self.num_heads * self.head_dim

        def w(*shape):
            return jnp.asarray(
                (rng.randn(*shape) * 0.3).astype(np.float32), dtype)

        self.embed = w(self.vocab, d_model)
        self.wq = w(d_model, hd)
        self.wk = w(d_model, hd)
        self.wv = w(d_model, hd)
        self.wo = w(hd, self.vocab)

        def _qkv(tokens):
            e = self.embed[tokens]
            shp = (tokens.shape[0], self.num_heads, self.head_dim)
            return ((e @ self.wq).reshape(shp),
                    (e @ self.wk).reshape(shp),
                    (e @ self.wv).reshape(shp))

        def _logits(attn_out):
            flat = attn_out.reshape(attn_out.shape[0], hd)
            return flat.astype(self.wo.dtype) @ self.wo

        # the pure functions are public so a caller building its own
        # jitted decode step (bench.py _build_llm_decode, the lowering
        # gate) can inline them under one jit
        self.qkv_fn = _qkv
        self.logits_fn = _logits
        self._qkv_jit = jax.jit(_qkv)
        self._logits_jit = jax.jit(_logits)

    def qkv(self, tokens):
        import jax.numpy as jnp

        return self._qkv_jit(jnp.asarray(np.asarray(tokens, np.int32)))

    def logits(self, attn_out):
        return self._logits_jit(attn_out)


class DecodeConfig:
    """Decode-server knobs (docs/DECODE.md env-knob table)."""

    def __init__(self, max_batch=8, max_new_tokens=32, num_pages=None,
                 page_size=16, queue_capacity=None,
                 default_deadline_s=30.0, n_replicas=1,
                 restart_dead=True, max_attempts=None, eos_id=1,
                 kv_int8=None, head_pack=None, drain_timeout_s=30.0,
                 impl=None, metrics_port=None, trace_sample=None,
                 prefill_chunk=None, kv_share=None, spec_k=None,
                 draft_factory=None, preempt_slack_s=0.25,
                 collector=None, disagg_prefill=None,
                 n_prefill_replicas=1):
        from paddle_tpu.flags import get_flag

        self.max_batch = int(max_batch)
        self.max_new_tokens = int(max_new_tokens)
        self.page_size = int(page_size)
        # default pool: room for max_batch sequences of ~4 pages plus
        # one page of growth each — tight enough that the preemption
        # path is reachable, roomy enough that steady state never
        # preempts
        self.num_pages = int(num_pages) if num_pages is not None \
            else 5 * self.max_batch
        self.queue_capacity = int(queue_capacity) \
            if queue_capacity is not None else 4 * self.max_batch
        self.default_deadline_s = float(default_deadline_s)
        self.n_replicas = int(n_replicas)
        self.restart_dead = bool(restart_dead)
        self.max_attempts = int(max_attempts) \
            if max_attempts is not None else 2 * self.n_replicas + 1
        self.eos_id = int(eos_id)
        self.kv_int8 = kv_int8      # None -> the typed flag
        self.head_pack = head_pack  # None -> the typed flag
        self.drain_timeout_s = float(drain_timeout_s)
        self.impl = impl            # flash_decode impl (None = auto)
        # observability (ISSUE 9): /metrics + /varz on this server
        # (None -> PADDLE_TPU_METRICS_PORT -> off; 0 = ephemeral)
        if metrics_port is None:
            metrics_port = metrics_port_from_env(None)
        self.metrics_port = None if metrics_port is None \
            else int(metrics_port)
        # head-based trace sampling (ISSUE 10; same contract as
        # ServingConfig.trace_sample)
        if trace_sample is not None:
            trace_sample = float(trace_sample)
            if not 0.0 <= trace_sample <= 1.0:
                raise ValueError("trace_sample must be in [0.0, 1.0]")
        self.trace_sample = trace_sample
        # decode speed act II (ISSUE 11): None defers to the typed
        # flags, resolved once here (0 / False = the validated PR-7
        # paths, zero behavior change)
        self.prefill_chunk = int(get_flag("prefill_chunk")) \
            if prefill_chunk is None else int(prefill_chunk)
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        self.kv_share = kv_share    # None -> the typed flag (cache)
        self.spec_k = int(get_flag("spec_k")) if spec_k is None \
            else int(spec_k)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        # draft_factory(i) -> draft model adapter (spec_k > 0 only);
        # None = a small TinyDecodeLM over the target's vocab
        self.draft_factory = draft_factory
        # deadline-aware preemption: a victim needs at least this much
        # deadline slack (plus a per-history-token allowance) to be
        # considered re-prefillable
        self.preempt_slack_s = float(preempt_slack_s)
        # fleet collector (ISSUE 12; same contract as
        # ServingConfig.collector): None -> PADDLE_TPU_COLLECTOR -> off
        if collector is None:
            from paddle_tpu.observability.collector import \
                collector_endpoint

            collector = collector_endpoint()
        self.collector = collector
        # disaggregated prefill/decode tiers (ISSUE 14): None defers
        # to the typed flag.  Off = the validated single-tier engine
        # (zero behavior change).  On: every decode replica reads ONE
        # shared page pool, prompt prefill runs on a separate
        # compute-bound pool of n_prefill_replicas workers, and a
        # finished prefill reaches the decode tier as a page-list
        # handoff (PagedKVCache.detach/adopt — block-table entries +
        # refcounts, zero K/V bytes moved)
        self.disagg_prefill = bool(get_flag("disagg_prefill")) \
            if disagg_prefill is None else bool(disagg_prefill)
        self.n_prefill_replicas = int(n_prefill_replicas)
        if self.n_prefill_replicas < 1:
            raise ValueError("n_prefill_replicas must be >= 1")
        if self.disagg_prefill and self.spec_k:
            raise ValueError(
                "disagg_prefill and spec_k are mutually exclusive "
                "(the speculative verify window stays single-tier "
                "for now — docs/SERVING.md)")


class _Seq:
    """One admitted sequence: request + full token history (the
    failover unit — a survivor re-prefills from ``history``)."""

    __slots__ = ("req", "prompt", "generated", "max_new", "attempts",
                 "slot", "draft_slot", "chunk_pos", "last_token",
                 "last_emit_t", "trace")

    def __init__(self, req, prompt, max_new):
        self.req = req
        self.prompt = list(int(t) for t in prompt)
        self.generated = []
        self.max_new = int(max_new)
        self.attempts = 0
        self.slot = None
        self.draft_slot = None       # spec decode: the draft cache's
        self.chunk_pos = 0           # chunked prefill: prefix tokens
        #                              already written to the caches
        self.last_token = None
        self.last_emit_t = None
        self.trace = req.trace       # join/step/retire chain onto it

    def history(self):
        return self.prompt + self.generated


class _PrefillReplica:
    """One prefill-tier worker (ISSUE 14): a model adapter computing
    prompt projections + page writes into the SHARED pool — the
    compute-bound half of disaggregated serving.  No decode state; a
    kill loses only the handoff in flight (aborted, pages freed,
    sequence re-prefilled by a survivor)."""

    __slots__ = ("index", "model", "alive", "busy", "prefills",
                 "handoffs")

    def __init__(self, index, model):
        self.index = index
        self.model = model
        self.alive = True
        self.busy = False
        self.prefills = 0
        self.handoffs = 0


class _Handoff:
    """One in-flight prefill->decode transfer: the sequence, the
    detached page-list handle (host metadata only — physical page ids
    + token length), and the offer timestamp the adoption-latency
    histogram reads."""

    __slots__ = ("seq", "handle", "offered_t")

    def __init__(self, seq, handle, offered_t):
        self.seq = seq
        self.handle = handle
        self.offered_t = offered_t


class _DecodeReplica:
    """Model + paged cache (+ draft model and ITS paged cache under
    spec_k) + the sequences currently riding it.  Under disaggregated
    serving every decode replica shares ONE pool (``cache`` injected,
    ``owns_cache`` False) so a prefill-tier page list is adoptable by
    any of them with zero byte movement."""

    def __init__(self, index, model, cfg, draft_model=None,
                 cache=None):
        self.index = index
        self.model = model
        self.cfg = cfg
        self.alive = True
        self.owns_cache = cache is None
        self.cache = cache if cache is not None else PagedKVCache(
            num_pages=cfg.num_pages, page_size=cfg.page_size,
            num_heads=model.num_heads, head_dim=model.head_dim,
            kv_int8=cfg.kv_int8, kv_share=cfg.kv_share)
        self.draft_model = draft_model
        self.draft_cache = None
        if draft_model is not None:
            self.draft_cache = PagedKVCache(
                num_pages=cfg.num_pages, page_size=cfg.page_size,
                num_heads=draft_model.num_heads,
                head_dim=draft_model.head_dim,
                kv_int8=cfg.kv_int8, kv_share=cfg.kv_share)
        self.active = []            # [_Seq], admission order
        self.prefilling = []        # [_Seq] mid-chunked-prefill
        self.iterations = 0
        self.tokens_out = 0


class DecodeServer:
    """Continuous-batching decode server over N model replicas.

    model_factory(i) -> a model adapter for replica i (default:
    ``TinyDecodeLM`` per replica, same seed — replicas must agree so a
    failed-over sequence continues the same distribution)."""

    def __init__(self, model_factory=None, config=None):
        import jax.numpy as jnp  # noqa: F401 — decode runs on device

        self.config = cfg = config or DecodeConfig()
        factory = model_factory or (lambda i: TinyDecodeLM())
        self.admission = AdmissionController(
            capacity=cfg.queue_capacity,
            default_deadline_s=cfg.default_deadline_s)
        # failover/preemption lane: unbounded on purpose — the PR-6
        # single-survivor-deadlock lesson (total sequences stay bounded
        # by admission capacity + max_batch * n_replicas)
        self._retry = BoundedQueue()
        # disaggregated tiers (ISSUE 14): ONE shared page pool all
        # decode replicas read and the prefill tier writes, so the
        # handoff is a pure page-list move; the handoff queue is the
        # tier boundary (unbounded — sequences in it already consumed
        # admission capacity)
        self._disagg = bool(cfg.disagg_prefill)
        self._shared_cache = None
        self._handoff_q = BoundedQueue()
        if self._disagg:
            probe_model = factory(0)
            self._shared_cache = PagedKVCache(
                num_pages=cfg.num_pages, page_size=cfg.page_size,
                num_heads=probe_model.num_heads,
                head_dim=probe_model.head_dim,
                kv_int8=cfg.kv_int8, kv_share=cfg.kv_share)
        self.replicas = []
        for i in range(cfg.n_replicas):
            model = probe_model if self._disagg and i == 0 \
                else factory(i)
            draft = None
            if cfg.spec_k > 0:
                # replicas must agree on the draft too: a failed-over
                # sequence continues the same proposal distribution
                draft = cfg.draft_factory(i) if cfg.draft_factory \
                    else TinyDecodeLM(vocab=model.vocab, d_model=32,
                                      num_heads=2, head_dim=16,
                                      seed=0)
            self.replicas.append(_DecodeReplica(
                i, model, cfg, draft, cache=self._shared_cache))
        # prefill tier: model adapters at offset indices (the factory
        # contract — same-seed TinyDecodeLM defaults agree with the
        # decode tier, which failover re-prefill depends on)
        self.prefill_replicas = []
        if self._disagg:
            self.prefill_replicas = [
                _PrefillReplica(i, factory(cfg.n_replicas + i))
                for i in range(cfg.n_prefill_replicas)]
        self._sup = Supervisor(restart_backoff=0.02, max_backoff=0.5)
        for rep in self.replicas:
            self._sup.add_worker("decode-%d" % rep.index,
                                 self._make_worker(rep),
                                 restart=cfg.restart_dead)
        for prep in self.prefill_replicas:
            self._sup.add_worker("prefill-%d" % prep.index,
                                 self._make_prefill_worker(prep),
                                 restart=cfg.restart_dead)
        self._meta = {}             # req.id -> max_new
        self._lock = threading.Lock()
        self._counters = {"iterations": 0, "tokens_out": 0,
                          "prefills": 0, "prefill_chunks": 0,
                          "kills": 0, "step_faults": 0,
                          "failovers": 0, "preemptions": 0,
                          "spec_proposed": 0, "spec_accepted": 0,
                          "handoffs_offered": 0, "handoffs_adopted": 0,
                          "handoffs_lost": 0, "handoffs_expired": 0,
                          "prefill_kills": 0}
        self._step_ms = []          # bounded rolling inter-token record
        self.metrics_server = None
        self.collector_pusher = None
        self._started = False
        self._stopped = False

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if not self._started:
            self._started = True
            if self.config.trace_sample is not None:
                _trace.set_sample_rate(self.config.trace_sample)
            if self.config.metrics_port is not None:
                try:
                    self.metrics_server = MetricsHTTPServer(
                        port=self.config.metrics_port).start()
                except OSError:
                    self.metrics_server = None
            if self.config.collector:
                from paddle_tpu.observability.collector import \
                    CollectorPusher

                self.collector_pusher = CollectorPusher(
                    self.config.collector, role="decode").start()
            self._sup.start()
            self._export_tier_gauges()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request path -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, deadline_s=None,
               request_id=None):
        """Admit a decode request (prompt token ids, 1-D int array) or
        raise a typed ServingError.  The Request future resolves to
        ``[generated_tokens]`` (np.int32, <= max_new_tokens, eos
        included when emitted).

        When tracing is on, this is the ROOT span of the sequence's
        trace (``decode.submit``); join -> step -> retire spans carry
        its trace id."""
        if _trace._tracer is not None:
            with _trace._tracer.span("decode.submit",
                                     request_id=request_id):
                return self._submit_inner(prompt_ids, max_new_tokens,
                                          deadline_s, request_id)
        return self._submit_inner(prompt_ids, max_new_tokens,
                                  deadline_s, request_id)

    def _submit_inner(self, prompt_ids, max_new_tokens, deadline_s,
                      request_id):
        if not self._started or self._stopped:
            self.admission._count("rejected_shutdown")
            raise ShutdownError("decode server not running")
        if not any(r.alive for r in self.replicas):
            self.admission._count("rejected_overloaded")
            raise ReplicaFailedError("no live decode replicas")
        ids = np.asarray(prompt_ids)
        if ids.ndim != 1 or ids.size == 0 or \
                not np.issubdtype(ids.dtype, np.integer):
            raise ValueError(
                "prompt_ids must be a non-empty 1-D integer array, "
                "got shape %s dtype %s" % (ids.shape, ids.dtype))
        vocab = self.replicas[0].model.vocab
        if ids.min() < 0 or ids.max() >= vocab:
            raise ValueError("prompt token out of range [0, %d)"
                             % vocab)
        max_new = int(max_new_tokens) if max_new_tokens is not None \
            else self.config.max_new_tokens
        cache0 = self.replicas[0].cache
        # spec decoding transiently appends k+1 tokens before the
        # rejection rewind — the capacity check carries that margin
        margin = self.config.spec_k + 1 if self.config.spec_k else 0
        if cache0.pages_for(ids.size + max_new + margin) > \
                cache0.num_pages:
            raise ValueError(
                "prompt+max_new needs %d pages; the pool only has %d"
                % (cache0.pages_for(ids.size + max_new + margin),
                   cache0.num_pages))
        req = self.admission.submit({"ids": ids.astype(np.int32)},
                                    deadline_s=deadline_s,
                                    request_id=request_id)
        with self._lock:
            self._meta[req.id] = max_new
        return req

    def decode(self, prompt_ids, max_new_tokens=None, deadline_s=None,
               timeout=None):
        """Synchronous convenience: submit + result -> np token array."""
        req = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          deadline_s=deadline_s)
        return req.result(timeout=timeout)[0]

    # -- the iteration loop -------------------------------------------------
    def _make_worker(self, rep):
        def loop():
            # a supervisor relaunch IS the replica restart
            # (restart_dead=True); the cache was reset at kill time
            if not rep.alive and self.config.restart_dead:
                rep.alive = True
            while self._sup.running:
                if not rep.alive:
                    return
                self._admit(rep)
                if not rep.active and not rep.prefilling:
                    if self.admission.draining and \
                            self._retry.empty():
                        time.sleep(0.002)
                    time.sleep(0.001)
                    continue
                try:
                    self._iterate(rep)
                except ReplicaKilled:
                    raise     # worker dies; supervisor may relaunch
                except Exception:
                    # a step that failed for any other reason fails
                    # over its sequences rather than dying silently
                    self._fail_over(rep)
                    raise

        return loop

    def _next_seq(self):
        """Pop the next sequence needing (re-)prefill: the failover /
        preemption lane first, then fresh admissions."""
        try:
            return self._retry.get_nowait()
        except queue_mod.Empty:
            req = self.admission.take(timeout=0.0005)
            if req is None:
                return None
            with self._lock:
                max_new = self._meta.get(req.id,
                                         self.config.max_new_tokens)
            return _Seq(req, np.asarray(req.feeds["ids"]), max_new)

    def _admit(self, rep):
        """Join new + failed-over sequences into this replica's batch
        (iteration-level batching: called every step).  Under
        disaggregated serving the decode tier joins ONLY adopted
        handoffs — raw admissions and re-prefills belong to the
        prefill tier."""
        if self._disagg:
            return self._admit_handoffs(rep)
        cfg = self.config
        while len(rep.active) + len(rep.prefilling) < cfg.max_batch:
            seq = self._next_seq()
            if seq is None:
                return
            now = time.monotonic()
            if seq.req.done():
                continue            # answered elsewhere (drain sweep)
            if seq.req.expired(now):
                seq.req.fail(DeadlineExpiredError(
                    "request %s: deadline passed before joining the "
                    "decode batch" % seq.req.id))
                continue
            if seq.attempts >= cfg.max_attempts:
                seq.req.fail(ReplicaFailedError(
                    "sequence failed after %d attempts"
                    % seq.attempts))
                continue
            try:
                ready = self._prefill(rep, seq)
            except OutOfPagesError:
                # no room: back on the lane for later / for a less
                # loaded replica (not an attempt — nothing failed)
                self._retry.put(seq)
                return
            if _trace._tracer is not None:
                sp = _trace._tracer.instant(
                    "decode.join", parent=seq.trace,
                    request_id=seq.req.id, replica=rep.index,
                    prompt_len=len(seq.prompt),
                    attempt=seq.attempts,
                    chunked=not ready)
                if seq.trace is not None:
                    seq.trace = sp.ctx
            _flight.record("decode", "join", request_id=seq.req.id,
                           replica=rep.index,
                           prompt_len=len(seq.prompt),
                           chunked=not ready)
            (rep.active if ready else rep.prefilling).append(seq)

    # -- disaggregated tiers (ISSUE 14) -------------------------------------
    def _admit_handoffs(self, rep):
        """Decode-tier join: adopt offered page-list handoffs into
        this replica's running batch.  Adoption is pure bookkeeping on
        the shared pool (PagedKVCache.adopt — block-table entries
        reinstated on a fresh slot, zero device bytes moved).  The
        deadline PROPAGATES across the tier boundary: a handoff whose
        request expired in transit is released (pages freed) and
        answered with the typed expiry, never silently parked."""
        cfg = self.config
        while len(rep.active) < cfg.max_batch:
            try:
                h = self._handoff_q.get_nowait()
            except queue_mod.Empty:
                return
            seq = h.seq
            now = time.monotonic()
            with rep.cache.lock:
                if seq.req.done():
                    rep.cache.release_in_transit(h.handle)
                    self._count_handoff("orphaned")
                    continue
                if seq.req.expired(now):
                    rep.cache.release_in_transit(h.handle)
                    self._count_handoff("expired")
                    self._count(handoffs_expired=1)
                    seq.req.fail(DeadlineExpiredError(
                        "request %s: deadline passed in the "
                        "prefill->decode handoff" % seq.req.id))
                    continue
                try:
                    seq.slot = rep.cache.adopt(h.handle)
                except OutOfPagesError:
                    # no free sequence slot right now: the handle
                    # stays in transit, re-offered for a later
                    # iteration / another replica
                    self._handoff_q.put(h)
                    return
            seq.last_token = int(seq.history()[-1])
            seq.last_emit_t = now
            self._count(handoffs_adopted=1)
            self._count_handoff("adopted", latency_s=now - h.offered_t,
                                trace=seq.trace)
            if _trace._tracer is not None:
                sp = _trace._tracer.instant(
                    "decode.adopt", parent=seq.trace,
                    request_id=seq.req.id, replica=rep.index,
                    pages=len(h.handle["pages"]),
                    handoff_ms=round((now - h.offered_t) * 1e3, 3))
                if seq.trace is not None:
                    seq.trace = sp.ctx
            _flight.record("decode", "handoff_adopted",
                           request_id=seq.req.id, replica=rep.index,
                           pages=len(h.handle["pages"]))
            rep.active.append(seq)

    def _make_prefill_worker(self, prep):
        """Prefill-tier worker loop (ISSUE 14): take a sequence from
        the retry lane / admission, write its prompt K/V into the
        shared pool, detach the pages into a handoff, offer it to the
        decode tier."""
        def loop():
            if not prep.alive and self.config.restart_dead:
                prep.alive = True
            while self._sup.running:
                if not prep.alive:
                    return
                seq = self._next_seq()
                if seq is None:
                    time.sleep(0.001)
                    continue
                now = time.monotonic()
                if seq.req.done():
                    continue            # answered elsewhere
                if seq.req.expired(now):
                    seq.req.fail(DeadlineExpiredError(
                        "request %s: deadline passed before prefill"
                        % seq.req.id))
                    continue
                if seq.attempts >= self.config.max_attempts:
                    seq.req.fail(HandoffError(
                        "request %s: handoff/prefill failed after %d "
                        "attempts" % (seq.req.id, seq.attempts)))
                    continue
                prep.busy = True
                try:
                    self._prefill_handoff(prep, seq)
                finally:
                    prep.busy = False
        return loop

    def _prefill_handoff(self, prep, seq):
        """ONE prefill: project the prompt prefix, write it into the
        shared pool, detach the page list, consult the fault plan
        (MSG_PREFILL — the after-allocation/before-adoption window),
        offer the handoff.  Raises ReplicaKilled on an injected kill
        (the worker dies; the sequence re-prefills elsewhere)."""
        cache = self._shared_cache
        hist = seq.history()
        prefix = hist[:-1]
        # projections OUTSIDE the pool lock (the compute-bound half);
        # page writes + detach inside it
        if prefix:
            shared = cache.shared_prefix_tokens(prefix)
            tail = prefix[shared:]
            if tail:
                k, v = self._proj_pow2(prep.model, tail)
            else:
                k = v = np.zeros((0, prep.model.num_heads,
                                  prep.model.head_dim), np.float32)
        try:
            with cache.lock:
                if prefix:
                    slot = cache.prefill(
                        k, v,
                        tokens=prefix if cache.kv_share else None)
                else:
                    slot = cache.alloc(1)
                handle = cache.detach(slot)
        except OutOfPagesError:
            # pool pressure: nothing allocated (prefill is atomic) —
            # back on the lane until decode retires free pages
            self._retry.put(seq)
            time.sleep(0.002)
            return
        except ValueError:
            # kv_share race: another prefill registered more shared
            # pages between our radix walk and the locked write, so
            # our projected tail no longer matches — recompute
            self._retry.put(seq)
            return
        prep.prefills += 1
        self._count(prefills=1)
        # seeded fault point: pages are allocated and in transit, the
        # decode tier has NOT adopted — the exact window the chaos
        # soak kills (ISSUE 14 satellite)
        inj = faultinject.maybe_injector()
        if inj is not None:
            act = inj.decide(MSG_PREFILL)
            if act is not None:
                for kind, arg in faultinject.steps_of(act):
                    if kind == "delay":
                        time.sleep(arg)
                        continue
                    with cache.lock:
                        cache.release_in_transit(handle)
                    seq.attempts += 1
                    if kind == "kill":
                        prep.alive = False
                        self._count(kills=1, prefill_kills=1)
                        self._count_handoff("killed")
                        self._requeue_or_fail_handoff(seq)
                        self._export_tier_gauges()
                        _flight.record(
                            "decode", "prefill_replica_killed",
                            replica=prep.index,
                            request_id=seq.req.id)
                        _flight.dump(reason="prefill_replica_death")
                        raise ReplicaKilled(
                            "prefill replica %d killed mid-handoff "
                            "(fault injection)" % prep.index)
                    # close / drop / truncate: the handoff is LOST in
                    # transit — pages freed, the sequence re-prefills
                    # (the re-prefill fallback; exactly-once holds
                    # because only the Request future answers)
                    self._count(handoffs_lost=1)
                    self._count_handoff("lost")
                    self._requeue_or_fail_handoff(seq)
                    return
        h = _Handoff(seq, handle, time.monotonic())
        prep.handoffs += 1
        self._count(handoffs_offered=1)
        self._count_handoff("offered")
        _flight.record("decode", "handoff_offered",
                       request_id=seq.req.id, replica=prep.index,
                       pages=len(handle["pages"]),
                       tokens=handle["length"])
        self._handoff_q.put(h)
        self._export_tier_gauges()

    def _requeue_or_fail_handoff(self, seq):
        """Re-prefill fallback bookkeeping: the sequence goes back on
        the lane unless its attempt budget is spent (typed
        HandoffError — never silence)."""
        if seq.req.done():
            return
        if seq.attempts >= self.config.max_attempts:
            seq.req.fail(HandoffError(
                "request %s: handoff lost %d times; giving up"
                % (seq.req.id, seq.attempts)))
        else:
            self._count(failovers=1)
            self._retry.put(seq)

    def _count_handoff(self, outcome, latency_s=None, trace=None):
        _M_HANDOFFS.inc(outcome=outcome)
        if latency_s is not None:
            exemplar = None
            if _trace._tracer is not None and trace is not None \
                    and _trace._tracer._verdict(trace[0]):
                exemplar = trace[0]
            _M_HANDOFF_SECONDS.observe(latency_s, exemplar=exemplar)

    def _export_tier_gauges(self):
        if not self._disagg:
            return
        _G_TIER_REPLICAS.set(
            sum(1 for p in self.prefill_replicas if p.alive),
            tier="prefill")
        _G_TIER_REPLICAS.set(
            sum(1 for r in self.replicas if r.alive), tier="decode")
        c = self._shared_cache
        _G_TIER_PAGES.set(c.in_use_pages(), kind="in_use")
        _G_TIER_PAGES.set(c.in_transit_pages(), kind="in_transit")
        _G_TIER_PAGES.set(c.free_pages(), kind="free")

    @staticmethod
    def _proj_pow2(model, toks):
        """Whole-prefill projections: pow2-pad the span (ragged
        lengths would retrace the jitted qkv per length), slice the
        real rows — the validated PR-7 path, byte-for-byte."""
        plen = len(toks)
        pp = 1
        while pp < plen:
            pp *= 2
        padded = np.zeros((pp,), np.int32)
        padded[:plen] = toks
        _, k, v = model.qkv(padded)
        return k[:plen], v[:plen]

    @staticmethod
    def _proj_chunk(model, toks, chunk):
        """Chunked-prefill projections: every chunk call runs at
        EXACTLY the chunk shape (the compile-once discipline — the
        final partial chunk pads up to it)."""
        plen = len(toks)
        padded = np.zeros((chunk,), np.int32)
        padded[:plen] = toks
        _, k, v = model.qkv(padded)
        return k[:plen], v[:plen]

    def _release_seq(self, rep, seq):
        """Free whatever cache state the sequence holds on this
        replica (both caches under spec_k); resets the chunk cursor so
        a re-prefill starts clean.  Runs under the cache lock — the
        disaggregated tiers share one pool across worker threads."""
        with rep.cache.lock:
            if seq.slot is not None:
                rep.cache.free(seq.slot)
                seq.slot = None
        if seq.draft_slot is not None and rep.draft_cache is not None:
            rep.draft_cache.free(seq.draft_slot)
        seq.draft_slot = None
        seq.chunk_pos = 0

    def _prefill(self, rep, seq):
        """Write KV for history[:-1] into fresh pages (BOTH caches
        under spec_k); the last history token becomes the pending
        input of the next iteration.  Returns True when the sequence
        is decode-ready, False when its prompt continues chunk-by-
        chunk in _advance_prefill (ISSUE 11a).  Under kv_share the
        already-cached full-page prefix is shared instead of projected
        or written (ISSUE 11b)."""
        cfg = self.config
        hist = seq.history()
        prefix = hist[:-1]
        try:
            if not prefix:
                seq.slot = rep.cache.alloc(1)
                if rep.draft_cache is not None:
                    seq.draft_slot = rep.draft_cache.alloc(1)
            else:
                shared = rep.cache.shared_prefix_tokens(prefix)
                chunk = cfg.prefill_chunk
                if chunk and len(prefix) - shared > chunk:
                    span = prefix[:shared + chunk]
                else:
                    span = prefix
                tail = span[shared:]
                if not tail:
                    # fully shared: zero projections, zero writes —
                    # the amortized-to-zero prefill of a cached prompt
                    k = v = np.zeros((0, rep.model.num_heads,
                                      rep.model.head_dim), np.float32)
                elif chunk:
                    # every chunked projection runs at the one fixed
                    # chunk shape (tail <= chunk by the span cap)
                    k, v = self._proj_chunk(rep.model, tail, chunk)
                else:
                    k, v = self._proj_pow2(rep.model, tail)
                seq.slot = rep.cache.prefill(
                    k, v, tokens=span if rep.cache.kv_share else None)
                if rep.draft_cache is not None:
                    dm = rep.draft_cache.shared_prefix_tokens(span)
                    kd, vd = self._proj_pow2(rep.draft_model,
                                             span[dm:]) \
                        if len(span) > dm else \
                        (np.zeros((0, rep.draft_model.num_heads,
                                   rep.draft_model.head_dim),
                                  np.float32),) * 2
                    seq.draft_slot = rep.draft_cache.prefill(
                        kd, vd,
                        tokens=span if rep.draft_cache.kv_share
                        else None)
                if len(span) < len(prefix):
                    seq.chunk_pos = len(span)
                    self._count(prefill_chunks=1)
                    return False
        except OutOfPagesError:
            self._release_seq(rep, seq)
            raise
        seq.chunk_pos = 0
        seq.last_token = int(hist[-1])
        seq.last_emit_t = time.monotonic()
        self._count(prefills=1)
        return True

    def _advance_prefill(self, rep):
        """One fixed-size prefill chunk per iteration for the OLDEST
        joining sequence (ISSUE 11a): the cost a long prompt adds to
        every running stream's inter-token time is bounded by one
        chunk, whatever the prompt length."""
        if not rep.prefilling:
            return
        cfg = self.config
        seq = rep.prefilling[0]
        prefix = seq.history()[:-1]
        span = prefix[seq.chunk_pos:seq.chunk_pos + cfg.prefill_chunk]
        try:
            k, v = self._proj_chunk(rep.model, span, cfg.prefill_chunk)
            rep.cache.extend(
                seq.slot, k, v,
                tokens=prefix[:seq.chunk_pos + len(span)]
                if rep.cache.kv_share else None)
            if rep.draft_cache is not None:
                kd, vd = self._proj_chunk(rep.draft_model, span,
                                          cfg.prefill_chunk)
                rep.draft_cache.extend(
                    seq.draft_slot, kd, vd,
                    tokens=prefix[:seq.chunk_pos + len(span)]
                    if rep.draft_cache.kv_share else None)
        except OutOfPagesError:
            # pool pressure mid-prefill: whole sequence back on the
            # lane (pages freed — nothing half-joined)
            rep.prefilling.pop(0)
            self._release_seq(rep, seq)
            self._retry.put(seq)
            return
        seq.chunk_pos += len(span)
        self._count(prefill_chunks=1)
        if seq.chunk_pos >= len(prefix):
            rep.prefilling.pop(0)
            seq.chunk_pos = 0
            seq.last_token = int(seq.history()[-1])
            seq.last_emit_t = time.monotonic()
            self._count(prefills=1)
            rep.active.append(seq)

    def _iterate(self, rep):
        """ONE iteration: advance at most one prefill chunk, then one
        decode step (plain or speculative) for the whole running
        batch."""
        cfg = self.config
        # seeded fault point — consulted BEFORE any cache mutation so
        # kill/close/drop can never half-apply a step
        inj = faultinject.maybe_injector()
        if inj is not None:
            act = inj.decide(MSG_DECODE)
            if act is not None:
                for kind, arg in faultinject.steps_of(act):
                    if kind == "delay":
                        time.sleep(arg)
                    elif kind == "kill":
                        self._count(kills=1)
                        self._fail_over(rep)
                        raise ReplicaKilled(
                            "decode replica %d killed mid-step "
                            "(fault injection)" % rep.index)
                    else:   # close / drop / truncate: lost step —
                        # transient, nothing mutated yet, no token
                        # emitted this iteration; the next one retries
                        self._count(step_faults=1)
                        return
        now = time.monotonic()
        # deadline / externally-answered sweep before spending compute
        # (joining chunked sequences expire mid-prefill the same way)
        for lane_name in ("active", "prefilling"):
            lane = getattr(rep, lane_name)
            keep = []
            for s in lane:
                if s.req.done():
                    self._release_seq(rep, s)
                elif s.req.expired(now):
                    self._release_seq(rep, s)
                    s.req.fail(DeadlineExpiredError(
                        "request %s: deadline passed mid-generation "
                        "(%d/%d tokens emitted)"
                        % (s.req.id, len(s.generated), s.max_new)))
                else:
                    keep.append(s)
            setattr(rep, lane_name, keep)
        self._advance_prefill(rep)
        if not rep.active:
            return
        if cfg.spec_k > 0:
            self._step_spec(rep)
        else:
            self._step(rep)
        st = rep.cache.stats()
        _M_PAGE_UTIL.set(
            st["in_use_pages"] / float(max(1, st["num_pages"])),
            replica=rep.index)
        _M_ACTIVE.set(len(rep.active), replica=rep.index)

    def _preempt_victim(self, rep, now):
        """Deadline-aware victim index (ISSUE 11 satellite): youngest
        -> oldest, the first sequence whose deadline can absorb a
        re-prefill (slack > preempt_slack_s + 1 ms/history-token); a
        sequence that would miss its deadline if evicted is spared
        while a less constrained — possibly older — one exists.  Every
        candidate at risk -> the youngest (the pinned legacy
        tie-break)."""
        slack = self.config.preempt_slack_s
        for idx in range(len(rep.active) - 1, -1, -1):
            s = rep.active[idx]
            if s.req.remaining(now) > slack + \
                    0.001 * len(s.history()):
                return idx
        return len(rep.active) - 1

    def _preempt_one(self, rep):
        """Evict one sequence under pool pressure (full history
        preserved on the retry lane); returns False when the batch is
        down to a lone unservable sequence (typed failure, step
        abandoned)."""
        if len(rep.active) == 1:
            s = rep.active.pop()
            self._release_seq(rep, s)
            s.req.fail(ReplicaFailedError(
                "request %s: page pool too small even for a "
                "lone sequence" % s.req.id))
            return False
        s = rep.active.pop(self._preempt_victim(rep,
                                                time.monotonic()))
        self._release_seq(rep, s)
        self._count(preemptions=1)
        _flight.record("decode", "preempt",
                       request_id=s.req.id,
                       replica=rep.index,
                       tokens_so_far=len(s.generated))
        self._retry.put(s)
        return True

    def _table_bucket(self, cache, slots):
        """pow2 bucket of the table width: at most log2(max) distinct
        (batch, table) shapes ever reach the compiler."""
        mp_need = max(cache.pages_for(cache.seq_len(s_) or 1)
                      for s_ in slots)
        mp = 1
        while mp < mp_need:
            mp *= 2
        # a long sequence's pow2 rounding can overshoot the table
        # itself; clamping keeps the kernel's page sweep bounded (a
        # sequence can never hold more than max_pages_per_seq pages,
        # so the clamp is always >= mp_need)
        return min(mp, cache.max_pages_per_seq)

    def _step(self, rep):
        """ONE decode step for the whole running batch."""
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas_kernels import flash_decode

        cfg = self.config
        # compile-once shape discipline (the PR-6 bucket-cache story
        # applied to decode): the device step always runs at the FIXED
        # batch shape max_batch (dummy rows: sink-page writes, length
        # 0 -> zero attention output) and at a pow2-bucketed block
        # table width — iteration-level batching changes the batch
        # every token, and unpadded shapes would retrace the jitted
        # step per composition (measured: ~300 ms/step of pure
        # recompile on the CPU harness)
        n_pad = cfg.max_batch
        while True:
            tokens = np.zeros((n_pad,), np.int32)
            tokens[:len(rep.active)] = [s.last_token
                                        for s in rep.active]
            q, k, v = rep.model.qkv(tokens)
            slots = [s.slot for s in rep.active]
            try:
                with rep.cache.lock:
                    rep.cache.append(slots, k, v)
                break
            except OutOfPagesError:
                # paging backpressure: preempt (deadline-aware) and
                # retry the step
                if not self._preempt_one(rep):
                    return
        with rep.cache.lock:
            mp = self._table_bucket(rep.cache, slots)
            tables = rep.cache.tables_for(slots, max_pages=mp,
                                          pad_to=n_pad)
            lens = rep.cache.lens_for(slots, pad_to=n_pad)
        out = flash_decode(
            q, rep.cache.k_pages, rep.cache.v_pages, tables, lens,
            impl=cfg.impl, head_pack=cfg.head_pack,
            kv_scales=rep.cache.kv_scales() if rep.cache.kv_int8
            else None)
        logits = rep.model.logits(out)
        # the greedy head is the logits-tail `argmax` stage of the
        # epilogue grammar — one definition for engine, draft and
        # verify sweeps
        next_tokens = np.asarray(greedy_logits_tail(logits))
        t_emit = time.monotonic()
        rep.iterations += 1
        still = []
        for s, tok in zip(rep.active, next_tokens):
            retired = self._commit_tokens(rep, s, [int(tok)], t_emit)
            if not retired:
                still.append(s)
        rep.active = still
        self._count(iterations=1, tokens_out=len(next_tokens))

    def _commit_tokens(self, rep, s, toks, t_emit):
        """Append emitted tokens to a sequence's bookkeeping (never
        touches the caches); returns True when the sequence retired
        (pages freed, future answered)."""
        cfg = self.config
        tr = _trace._tracer
        per_tok_ms = None
        if s.last_emit_t is not None:
            per_tok_ms = (t_emit - s.last_emit_t) * 1000.0 / len(toks)
        done = False
        for tok in toks:
            s.generated.append(tok)
            s.last_token = tok
            if per_tok_ms is not None:
                self._record_step_ms(per_tok_ms)
            rep.tokens_out += 1
            if tr is not None:
                tr.instant("decode.step", parent=s.trace,
                           request_id=s.req.id, replica=rep.index,
                           token=tok, n=len(s.generated))
            if tok == cfg.eos_id or len(s.generated) >= s.max_new:
                done = True
        s.last_emit_t = t_emit
        if done:
            self._release_seq(rep, s)
            if tr is not None:
                tr.instant("decode.retire", parent=s.trace,
                           request_id=s.req.id,
                           replica=rep.index,
                           tokens=len(s.generated))
            _flight.record("decode", "retire",
                           request_id=s.req.id,
                           replica=rep.index,
                           tokens=len(s.generated))
            self._count(retires=1)
            s.req.complete([np.asarray(s.generated, np.int32)])
        return done

    def _step_spec(self, rep):
        """ONE speculative iteration (ISSUE 11c): k draft proposals,
        one q-len-(k+1) verify sweep, longest-agreeing-prefix
        acceptance, page-pointer rewind of the rejected tail.  Any
        OutOfPagesError mid-round rewinds BOTH caches to the
        iteration's start state (truncate through the atomic free
        path), preempts one sequence, and retries — the same
        backpressure contract as the plain step."""
        while True:
            if not rep.active:
                return
            base = [(s, rep.cache.seq_len(s.slot),
                     rep.draft_cache.seq_len(s.draft_slot))
                    for s in rep.active]
            try:
                self._spec_round(rep)
                return
            except OutOfPagesError:
                for s, main_len, draft_len in base:
                    if s.slot is not None and \
                            rep.cache.seq_len(s.slot) > main_len:
                        rep.cache.truncate(s.slot, main_len)
                    if s.draft_slot is not None and \
                            rep.draft_cache.seq_len(s.draft_slot) > \
                            draft_len:
                        rep.draft_cache.truncate(s.draft_slot,
                                                 draft_len)
                if not self._preempt_one(rep):
                    return

    def _spec_round(self, rep):
        import jax.numpy as jnp

        from paddle_tpu.decode import spec_accept_length
        from paddle_tpu.ops.pallas_kernels import flash_decode

        cfg = self.config
        kk = cfg.spec_k
        n_pad = cfg.max_batch
        live = rep.active
        n = len(live)
        draft = rep.draft_model
        dcache = rep.draft_cache
        # --- draft phase: k sequential q-len-1 proposals on the
        # draft replica's own paged cache (fixed shapes throughout)
        pending = np.zeros((n_pad,), np.int32)
        pending[:n] = [s.last_token for s in live]
        dslots = [s.draft_slot for s in live]
        proposals = np.zeros((n_pad, kk), np.int32)
        cur = pending.copy()
        for j in range(kk):
            q, dk, dv = draft.qkv(cur)
            dcache.append(dslots, dk, dv)
            mp = self._table_bucket(dcache, dslots)
            tables = dcache.tables_for(dslots, max_pages=mp,
                                       pad_to=n_pad)
            lens = dcache.lens_for(dslots, pad_to=n_pad)
            out = flash_decode(
                q, dcache.k_pages, dcache.v_pages, tables, lens,
                impl=cfg.impl, head_pack=cfg.head_pack,
                kv_scales=dcache.kv_scales() if dcache.kv_int8
                else None)
            cur = np.asarray(greedy_logits_tail(draft.logits(out))) \
                .astype(np.int32)
            proposals[:, j] = cur
        # --- verify phase: ONE batched q-len-(k+1) target sweep over
        # [pending, d_1..d_k] — the whole window appends first (the
        # speculative pages), then every row scores in one kernel pass
        r = kk + 1
        window = np.zeros((n_pad, r), np.int32)
        window[:n, 0] = pending[:n]
        window[:n, 1:] = proposals[:n]
        h, d = rep.model.num_heads, rep.model.head_dim
        q, mk, mv = rep.model.qkv(window.reshape(-1))
        q = jnp.reshape(q, (n_pad, r, h, d))
        mk = jnp.reshape(mk, (n_pad, r, h, d))
        mv = jnp.reshape(mv, (n_pad, r, h, d))
        slots = [s.slot for s in live]
        rep.cache.append(slots, mk, mv)
        mp = self._table_bucket(rep.cache, slots)
        tables = rep.cache.tables_for(slots, max_pages=mp,
                                      pad_to=n_pad)
        lens = rep.cache.lens_for(slots, pad_to=n_pad)
        out = flash_decode(
            q, rep.cache.k_pages, rep.cache.v_pages, tables, lens,
            impl=cfg.impl, head_pack=cfg.head_pack,
            kv_scales=rep.cache.kv_scales() if rep.cache.kv_int8
            else None)
        logits = rep.model.logits(jnp.reshape(out, (n_pad * r, h, d)))
        targets = np.asarray(greedy_logits_tail(logits)) \
            .reshape(n_pad, r)
        # --- acceptance + cache rewind (still abortable: seq
        # bookkeeping is untouched until the commit loop below)
        plan = []
        catch_up = []
        for i, s in enumerate(live):
            m = spec_accept_length(proposals[i], targets[i])
            emitted = [int(t) for t in targets[i, :m + 1]]
            room = s.max_new - len(s.generated)
            if len(emitted) > room:
                emitted = emitted[:room]
            if cfg.eos_id in emitted:
                emitted = emitted[:emitted.index(cfg.eos_id) + 1]
            n_emit = len(emitted)
            plan.append((s, emitted, m))
            base_main = rep.cache.seq_len(s.slot) - r
            rep.cache.truncate(s.slot, base_main + n_emit)
            base_draft = dcache.seq_len(s.draft_slot) - kk
            dcache.truncate(s.draft_slot,
                            min(base_draft + kk, base_draft + n_emit))
            if n_emit == kk + 1:
                # full acceptance: the draft cache is one row short
                # (d_k was proposed but never appended draft-side)
                catch_up.append((s, int(proposals[i, kk - 1])))
        if catch_up:
            toks = np.zeros((n_pad,), np.int32)
            toks[:len(catch_up)] = [t for _, t in catch_up]
            _, dk, dv = draft.qkv(toks)
            dcache.append([s.draft_slot for s, _ in catch_up], dk, dv)
        # --- commit (never raises): emitted tokens, timers, retires
        t_emit = time.monotonic()
        rep.iterations += 1
        total = 0
        accepted = 0
        still = []
        for s, emitted, m in plan:
            total += len(emitted)
            # acceptance counts draft AGREEMENT (the draft-quality /
            # speedup signal), not emission — eos and max_new caps
            # discard agreed tokens without saying anything about the
            # draft
            accepted += m
            retired = self._commit_tokens(rep, s, emitted, t_emit)
            if not retired:
                still.append(s)
        rep.active = still
        self._count(iterations=1, tokens_out=total,
                    spec_proposed=kk * n, spec_accepted=accepted)

    def _fail_over(self, rep):
        """Kill path: every live sequence — full token history — onto
        the retry lane; the dead replica's cache state is released
        (all its pages freed, accounting intact).  A replica that OWNS
        its cache resets it wholesale; a disaggregated replica shares
        the pool with live tiers, so only ITS sequences' slots are
        freed — a decode kill right after adoption frees the adopted
        pages and the prefill tier re-prefills from token history."""
        rep.alive = False
        moved = rep.active + rep.prefilling
        rep.active = []
        rep.prefilling = []
        if rep.owns_cache:
            rep.cache.reset()
        else:
            for s in moved:
                self._release_seq(rep, s)
        if rep.draft_cache is not None:
            rep.draft_cache.reset()
        self._export_tier_gauges()
        _flight.record("decode", "replica_killed", replica=rep.index,
                       live_seqs=len(moved))
        # post-mortem: the ring holds the chaos action + the kill +
        # every join/preempt that led here — dump the narrative
        _flight.dump(reason="decode_replica_death")
        survivors = [r for r in self.replicas
                     if r.alive and r is not rep] \
            or ([rep] if self.config.restart_dead else [])
        for s in moved:
            s.slot = None
            s.draft_slot = None
            s.chunk_pos = 0
            s.attempts += 1
            if s.req.done():
                continue
            if not survivors and s.attempts >= \
                    self.config.max_attempts:
                s.req.fail(ReplicaFailedError(
                    "replica died; no survivors after %d attempts"
                    % s.attempts))
            else:
                self._count(failovers=1)
                self._retry.put(s)

    # -- shutdown -----------------------------------------------------------
    def drain(self, timeout=None):
        """Stop admitting; run every admitted sequence to completion
        (or typed expiry); answer whatever remains at the timeout with
        the typed ShutdownError.  Returns the shutdown-failed count."""
        timeout = self.config.drain_timeout_s if timeout is None \
            else float(timeout)
        self.admission.start_drain()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            busy = any(r.active or r.prefilling
                       for r in self.replicas) \
                or not self._retry.empty() \
                or not self._handoff_q.empty() \
                or any(p.busy for p in self.prefill_replicas) \
                or self.admission.outstanding_count() > 0
            if not busy:
                break
            time.sleep(0.005)
        leftovers = self.admission.outstanding()
        for req in leftovers.values():
            req.fail(ShutdownError(
                "request %s: decode server drained before completion"
                % req.id))
        return len(leftovers)

    def stop(self, drain_timeout=None):
        if self._stopped:
            return 0
        leftovers = self.drain(timeout=drain_timeout)
        self._stopped = True
        self._sup.stop(join_timeout=2.0)
        # post-drain page sweep: sequences answered by the drain fail
        # above still hold pages until their worker notices — workers
        # are stopped now, so release here; the accounting check runs
        # AFTER this (a real leak — a page owned by no sequence — is
        # not maskable by it)
        for rep in self.replicas:
            for s in rep.active + rep.prefilling:
                self._release_seq(rep, s)
            rep.active = []
            rep.prefilling = []
        # disagg sweep: handoffs never adopted (their requests were
        # shutdown-failed by the drain above) still hold pages —
        # release every queued offer and any in-transit straggler so
        # the zero-leak invariant holds post-stop
        if self._shared_cache is not None:
            while True:
                try:
                    h = self._handoff_q.get_nowait()
                except queue_mod.Empty:
                    break
                with self._shared_cache.lock:
                    self._shared_cache.release_in_transit(h.handle)
            with self._shared_cache.lock:
                self._shared_cache.release_in_transit()
            self._export_tier_gauges()
        if self.collector_pusher is not None:
            self.collector_pusher.stop(final_push=True)
            self.collector_pusher = None
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        return leftovers

    # -- observability ------------------------------------------------------
    def _count(self, **incs):
        with self._lock:
            for k_, v_ in incs.items():
                # registry-only events (retires) keep the public
                # counters dict shape frozen (docs/DECODE.md)
                if k_ in self._counters:
                    self._counters[k_] += v_
        for k_, v_ in incs.items():
            _M_DECODE.inc(v_, event=k_)

    def _record_step_ms(self, ms):
        with self._lock:
            self._step_ms.append(ms)
            if len(self._step_ms) > 10000:
                del self._step_ms[:5000]
        _M_STEP_MS.observe(ms / 1000.0)

    def inter_token_ms(self):
        """(p50, p99) inter-token latency over the rolling record."""
        with self._lock:
            lat = sorted(self._step_ms)
        if not lat:
            return None, None
        return (lat[min(len(lat) - 1, int(0.50 * len(lat)))],
                lat[min(len(lat) - 1, int(0.99 * len(lat)))])

    def page_accounting(self):
        """(ok, detail) over every replica cache — the zero-leak
        invariant (`allocated == in_use + free`, and in_use == 0 after
        drain)."""
        for rep in self.replicas:
            ok, detail = rep.cache.check_accounting()
            if not ok:
                return False, "replica %d: %s" % (rep.index, detail)
            if rep.draft_cache is not None:
                ok, detail = rep.draft_cache.check_accounting()
                if not ok:
                    return False, ("replica %d draft cache: %s"
                                   % (rep.index, detail))
        return True, ""

    def stats(self):
        c = self.admission.counters()
        answered = sum(v for k_, v in c.items()
                       if k_.startswith("answered_"))
        with self._lock:
            counters = dict(self._counters)
        p50, p99 = self.inter_token_ms()
        acceptance = None
        if counters.get("spec_proposed"):
            acceptance = round(counters["spec_accepted"]
                               / counters["spec_proposed"], 4)
        disagg = None
        if self._disagg:
            sc = self._shared_cache
            disagg = {
                "prefill_replicas": {
                    p.index: {"alive": p.alive,
                              "prefills": p.prefills,
                              "handoffs": p.handoffs}
                    for p in self.prefill_replicas},
                "handoff_queue": self._handoff_q.qsize(),
                "handoffs_offered": counters["handoffs_offered"],
                "handoffs_adopted": counters["handoffs_adopted"],
                "handoffs_lost": counters["handoffs_lost"],
                "handoffs_expired": counters["handoffs_expired"],
                "prefill_kills": counters["prefill_kills"],
                "in_transit_pages": sc.in_transit_pages(),
                "shared_pool": sc.stats(),
            }
        return {
            "spec_acceptance_rate": acceptance,
            "disagg": disagg,
            "admission": c,
            "outstanding": self.admission.outstanding_count(),
            "answered": answered,
            "accounted": answered + self.admission.outstanding_count()
            == c["admitted"],
            "decode": counters,
            "inter_token_p50_ms": p50,
            "inter_token_p99_ms": p99,
            "retry_depth": self._retry.qsize(),
            "replicas": {
                rep.index: {"alive": rep.alive,
                            "active_seqs": len(rep.active),
                            "prefilling_seqs": len(rep.prefilling),
                            "iterations": rep.iterations,
                            "tokens_out": rep.tokens_out,
                            "cache": rep.cache.stats(),
                            **({"draft_cache":
                                rep.draft_cache.stats()}
                               if rep.draft_cache is not None
                               else {})}
                for rep in self.replicas},
            "draining": self.admission.draining,
        }
