"""Structural Program/Block/Op verifier (docs/ANALYSIS.md).

The IR invariants every transpiler pass must preserve, checked in one
O(ops) walk with typed diagnostics that name block / op-index / var:

  * ``unknown-op``        — every op type resolves in the registry
                            (incl. synthesized ``<fwd>_grad`` defs).
  * ``unregistered-attr`` — op attrs are exactly the registered attr
                            schema: no attr outside the op def, no
                            REQUIRED attr missing (a rewrite that
                            invents an attr the kernel never reads is
                            caught here, not at trace time).
  * ``unknown-slot``      — input/output slot names belong to the op
                            def (grad ops validate against their
                            synthesized grad def).
  * ``undefined-input``   — block-0 op inputs resolve to a VarDesc via
                            the parent-block chain (host-only ops are
                            exempt: they read runtime scope vars; sub-
                            block ops likewise — RPC-filled section
                            vars live only in the scope).
  * ``use-before-def``    — block-0 ordering: a non-persistable,
                            non-data var whose only producers come
                            LATER in the block cannot be consumed
                            (in-place writes to persistables are the
                            legal exception).
  * ``duplicate-output``  — one op listing the same var twice in one
                            output slot (two writes, undefined order).
  * ``misparented-var``   — every ``block.vars[name]`` has
                            ``v.name == name`` and ``v.block is
                            block`` (clone/from_dict bookkeeping).
  * ``grad-pairing``      — ``<X>_grad`` ops: X registered and
                            differentiable, and the op carries the
                            backward role.
  * ``feed-missing`` / ``fetch-missing`` — caller-declared feed/fetch
                            targets exist in the program.
  * ``roundtrip``         — opt-in: to_dict/from_dict and clone()
                            preserve the program fingerprint
                            (serialization loses nothing the jit
                            cache keys on).

``verify`` returns the diagnostic list (and raises ``VerifierError``
on any error-severity diagnostic unless ``raise_=False``).  Warnings
(e.g. ``orphan-var``) never raise: transpilers legally strand the
VarDescs of fused-away intermediates.
"""

from __future__ import annotations

from paddle_tpu.core.program import BlockRef
from paddle_tpu.core.registry import REQUIRED, get_op_def, has_op_def

_ERROR = "error"
_WARNING = "warning"

# op roles a grad op may legally carry (backward.py always stamps
# BACKWARD; clones/pipeline cuts preserve it)
_GRAD_ROLES = ("backward",)


class Diagnostic:
    """One typed verifier finding, locating block / op-index / var."""

    __slots__ = ("rule", "severity", "block_idx", "op_idx", "op_type",
                 "var", "message")

    def __init__(self, rule, message, severity=_ERROR, block_idx=None,
                 op_idx=None, op_type=None, var=None):
        self.rule = rule
        self.severity = severity
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.var = var
        self.message = message

    def __repr__(self):
        return f"Diagnostic({self!s})"

    def __str__(self):
        loc = []
        if self.block_idx is not None:
            loc.append(f"block {self.block_idx}")
        if self.op_idx is not None:
            loc.append(f"op {self.op_idx}")
        if self.op_type is not None:
            loc.append(f"({self.op_type})")
        if self.var is not None:
            loc.append(f"var '{self.var}'")
        where = " ".join(loc)
        return f"[{self.rule}] {where}: {self.message}" if where else \
            f"[{self.rule}] {self.message}"


class VerifierError(RuntimeError):
    """Raised by ``verify`` when any error-severity diagnostic fires.
    ``.diagnostics`` holds the full typed list (warnings included)."""

    code = "ir_verify"

    def __init__(self, diagnostics, label=""):
        self.diagnostics = list(diagnostics)
        self.label = label
        errors = [d for d in self.diagnostics if d.severity == _ERROR]
        head = f"IR verification failed{f' ({label})' if label else ''}: " \
               f"{len(errors)} error(s)"
        super().__init__(
            "\n  ".join([head] + [str(d) for d in self.diagnostics]))


def _visible_in_ancestors(block, name):
    b = block.parent
    while b is not None:
        if name in b.vars:
            return True
        b = b.parent
    return False


def _check_block(block, diags):
    bidx = block.idx
    # -- var table bookkeeping -------------------------------------------
    for name, v in block.vars.items():
        if v.name != name:
            diags.append(Diagnostic(
                "misparented-var",
                f"vars[{name!r}] holds a VarDesc named {v.name!r}",
                block_idx=bidx, var=name))
        if v.block is not block:
            diags.append(Diagnostic(
                "misparented-var",
                "VarDesc.block does not point at its containing block",
                block_idx=bidx, var=name))

    # first producer index per var name (this block only)
    first_def = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            first_def.setdefault(n, i)

    referenced = set()
    for i, op in enumerate(block.ops):
        known = has_op_def(op.type)
        if not known:
            diags.append(Diagnostic(
                "unknown-op",
                f"op type {op.type!r} is not registered",
                block_idx=bidx, op_idx=i, op_type=op.type))
        op_def = None
        if known:
            try:
                op_def = get_op_def(op.type)
            except KeyError as e:
                # X_grad whose forward X is registered but not
                # differentiable: the synthesized grad def refuses
                diags.append(Diagnostic(
                    "grad-pairing", str(e),
                    block_idx=bidx, op_idx=i, op_type=op.type))

        # -- attr schema -------------------------------------------------
        if op_def is not None:
            extra = set(op.attrs) - set(op_def.attrs)
            if extra:
                diags.append(Diagnostic(
                    "unregistered-attr",
                    f"attrs {sorted(extra)} are not in the registered "
                    f"schema {sorted(op_def.attrs)}",
                    block_idx=bidx, op_idx=i, op_type=op.type))
            for aname, default in op_def.attrs.items():
                if default is REQUIRED and aname not in op.attrs:
                    diags.append(Diagnostic(
                        "unregistered-attr",
                        f"required attr {aname!r} missing",
                        block_idx=bidx, op_idx=i, op_type=op.type))
            # -- epilogue stage list ------------------------------------
            # ops carrying a fused stage list (conv2d_epilogue,
            # conv2d_bn_train, fc_epilogue, conv2d_int8, ...) declare
            # an "epilogue" attr; a non-empty value must parse against
            # the stage grammar (ops/epilogue.py) — transpilers build
            # it via spec_attr so this only fires on hand-edited IR
            ep = op.attrs.get("epilogue", "")
            if ep:
                from paddle_tpu.ops.epilogue import EpilogueSpec

                try:
                    EpilogueSpec.from_attr(ep).validate()
                except ValueError as e:
                    diags.append(Diagnostic(
                        "epilogue-spec",
                        f"attr 'epilogue' {ep!r} is not a valid stage "
                        f"list: {e}",
                        block_idx=bidx, op_idx=i, op_type=op.type))
            # -- slot validity ------------------------------------------
            for slot in op.inputs:
                if slot not in op_def.inputs:
                    diags.append(Diagnostic(
                        "unknown-slot",
                        f"input slot {slot!r} is not in the op def "
                        f"{tuple(op_def.inputs)}",
                        block_idx=bidx, op_idx=i, op_type=op.type))
            for slot in op.outputs:
                if slot not in op_def.outputs:
                    diags.append(Diagnostic(
                        "unknown-slot",
                        f"output slot {slot!r} is not in the op def "
                        f"{tuple(op_def.outputs)}",
                        block_idx=bidx, op_idx=i, op_type=op.type))

        # -- sub-block references ---------------------------------------
        for aname, aval in op.attrs.items():
            if isinstance(aval, BlockRef) and not (
                    0 <= aval.idx < len(block.program.blocks)):
                diags.append(Diagnostic(
                    "block-ref",
                    f"attr {aname!r} references block {aval.idx} but "
                    f"the program has {len(block.program.blocks)} "
                    "block(s)",
                    block_idx=bidx, op_idx=i, op_type=op.type))

        # -- grad pairing ------------------------------------------------
        if op.type.endswith("_grad"):
            if op.op_role not in _GRAD_ROLES:
                diags.append(Diagnostic(
                    "grad-pairing",
                    f"grad op carries op_role {op.op_role!r} "
                    f"(expected one of {_GRAD_ROLES})",
                    severity=_WARNING,
                    block_idx=bidx, op_idx=i, op_type=op.type))

        # -- dataflow ----------------------------------------------------
        produced_here = set(op.output_names())
        for n in op.input_names():
            referenced.add(n)
            in_block = n in block.vars
            if not in_block and not _visible_in_ancestors(block, n):
                host_ok = op_def is not None and op_def.host_only
                if bidx == 0 and not host_ok:
                    diags.append(Diagnostic(
                        "undefined-input",
                        "input var is declared in no block "
                        "(dangling name)",
                        block_idx=bidx, op_idx=i, op_type=op.type,
                        var=n))
                continue
            if bidx != 0:
                # sub-blocks run under control-flow/section semantics:
                # ordering is the runtime's business, existence was
                # checked above
                continue
            v = block.vars.get(n)
            if v is None or v.persistable or v.is_data:
                continue
            fd = first_def.get(n)
            if fd is not None and fd > i and n not in produced_here:
                diags.append(Diagnostic(
                    "use-before-def",
                    f"first producer is op {fd}, after this use",
                    block_idx=bidx, op_idx=i, op_type=op.type, var=n))
        for slot, names in op.outputs.items():
            referenced.update(names)
            seen = set()
            for n in names:
                if n in seen:
                    diags.append(Diagnostic(
                        "duplicate-output",
                        f"var listed twice in output slot {slot!r}",
                        block_idx=bidx, op_idx=i, op_type=op.type,
                        var=n))
                seen.add(n)

    # -- orphan vars (warning only: fuse passes legally strand the
    # VarDescs of erased intermediates) --------------------------------
    for name, v in block.vars.items():
        if name in referenced or v.persistable or v.is_data:
            continue
        diags.append(Diagnostic(
            "orphan-var",
            "var is referenced by no op in its block",
            severity=_WARNING, block_idx=bidx, var=name))


def verify(program, feeds=None, fetches=None, roundtrip=False,
           raise_=True, label=""):
    """Run every structural rule over ``program``.

    feeds/fetches: optional iterables of var names (or VarDescs) that
    must exist in the program — the executor/predictor feed+fetch
    contract, checked statically.  roundtrip=True additionally asserts
    to_dict/from_dict and clone() fingerprint stability (O(program)
    serialization — gate/test use, not per-pass use).

    Returns the list of Diagnostics; raises VerifierError iff any has
    error severity and ``raise_`` (warnings never raise).
    """
    diags = []
    for block in program.blocks:
        if block.idx != program.blocks.index(block):
            diags.append(Diagnostic(
                "misparented-var",
                f"block list position {program.blocks.index(block)} "
                f"holds block.idx {block.idx}", block_idx=block.idx))
        if block.parent_idx >= 0 and not (
                0 <= block.parent_idx < len(program.blocks)):
            diags.append(Diagnostic(
                "misparented-var",
                f"parent_idx {block.parent_idx} out of range",
                block_idx=block.idx))
        _check_block(block, diags)

    def _name(t):
        return t if isinstance(t, str) else t.name

    gb = program.global_block()
    for t in (feeds or ()):
        n = _name(t)
        if not gb.has_var(n):
            diags.append(Diagnostic(
                "feed-missing", "declared feed target does not exist",
                block_idx=0, var=n))
    for t in (fetches or ()):
        n = _name(t)
        if not gb.has_var(n):
            diags.append(Diagnostic(
                "fetch-missing",
                "declared fetch target does not exist",
                block_idx=0, var=n))

    if roundtrip:
        diags.extend(verify_roundtrip(program, raise_=False))

    if raise_ and any(d.severity == _ERROR for d in diags):
        raise VerifierError(diags, label=label)
    return diags


def verify_roundtrip(program, raise_=True, label=""):
    """to_dict/from_dict and clone() must preserve the program
    fingerprint — the jit-cache / registry-dedupe key.  A pass whose
    rewrite survives in memory but not through serialization corrupts
    every consumer of the saved form (model registry, elastic resume,
    pserver programs on the wire)."""
    from paddle_tpu.core.compiler import program_fingerprint
    from paddle_tpu.core.program import Program

    diags = []
    try:
        fp = program_fingerprint(program)
    except TypeError as e:
        # an attr the fingerprint can't hash can't serialize either
        diags.append(Diagnostic(
            "roundtrip",
            f"program does not fingerprint: TypeError: {e}"))
        if raise_:
            raise VerifierError(diags, label=label)
        return diags
    try:
        restored = Program.parse_from_bytes(program.to_bytes())
    except (TypeError, ValueError) as e:
        diags.append(Diagnostic(
            "roundtrip",
            f"program does not serialize: {type(e).__name__}: {e}"))
        restored = None
    if restored is not None and program_fingerprint(restored) != fp:
        diags.append(Diagnostic(
            "roundtrip",
            "to_bytes/parse_from_bytes changed the program "
            f"fingerprint ({fp} -> {program_fingerprint(restored)})"))
    cloned = program.clone()
    if program_fingerprint(cloned) != fp:
        diags.append(Diagnostic(
            "roundtrip",
            "clone() changed the program fingerprint "
            f"({fp} -> {program_fingerprint(cloned)})"))
    if raise_ and any(d.severity == _ERROR for d in diags):
        raise VerifierError(diags, label=label)
    return diags
