"""Static whole-program shape/dtype inference + sharding checker.

Shape half: walk block 0 in op order re-running the registry's
``infer_shapes`` (the same jax.eval_shape machinery append_op uses)
over the DECLARED VarDesc shapes, and flag every declared-vs-inferred
mismatch with a typed diagnostic naming op-index / slot / var.  A
transpiler that rewrites an op chain but leaves a stale VarDesc shape
behind is caught here at transpile time instead of at trace time (or
on chip).  Unknown dims (-1) compare loose; inference failures mark
the op's outputs unknown rather than guessing.

Sharding half (GSPMD, Xu et al., 2021): validate every
``VarDesc.sharding`` annotation against a ``MeshPlan`` statically —
axis names exist in the plan, no axis is used twice in one spec, the
spec is no longer than the var rank, and every sharded dim divides
evenly by the product of its axis sizes (ZeRO x tp composition: a
("tp","dp") dim must divide by tp*dp).  Also closes the two escapes
the GSPMD rounds found dynamically:

  * the silent shard_map divisibility fallback — a flash_attention op
    tagged with gspmd axes whose batch/head extents don't divide the
    plan falls back to the unsharded kernel at trace time with no
    signal; here it is a typed diagnostic at annotate time;
  * the untagged-grad-op escape — a tagged flash_attention whose
    flash_attention_grad sibling lost its tags re-traces the kernel
    inside shard_map's partitioner ("Mosaic kernels cannot be
    automatically partitioned", caught once at the export gate, at
    zero chip cost only by luck).

docs/ANALYSIS.md has the rule table.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.analysis.verifier import Diagnostic, VerifierError

_ERROR = "error"
_WARNING = "warning"


class ShapeCheckError(VerifierError):
    """Static shape/dtype inference found declared-vs-inferred
    mismatches."""

    code = "shape_check"


class ShardingCheckError(VerifierError):
    """A VarDesc.sharding annotation is illegal for the MeshPlan."""

    code = "sharding_check"


def _spec_of(var):
    import jax

    if var is None or var.shape is None or var.dtype is None:
        return None
    return jax.ShapeDtypeStruct(tuple(var.shape), np.dtype(var.dtype))


def infer_program_shapes(program):
    """Re-infer every block-0 op's output shapes/dtypes from the
    declared inputs.  Returns (env, diags): env maps var name ->
    ShapeDtypeStruct for every var whose shape inference succeeded
    (declared shapes seed the walk; inferred shapes flow forward),
    diags carries ``shape-mismatch`` / ``dtype-mismatch`` /
    ``infer-failed`` diagnostics."""
    import jax

    from paddle_tpu.core import registry

    block = program.global_block()
    diags = []
    env = {}
    for name, v in block.vars.items():
        spec = _spec_of(v)
        if spec is not None:
            env[name] = spec

    for i, op in enumerate(block.ops):
        if not registry.has_op_def(op.type):
            continue  # the structural verifier owns unknown-op
        try:
            op_def = registry.get_op_def(op.type)
        except KeyError:
            continue
        if op_def.host_only:
            continue
        ins_specs = {}
        ok = True
        for slot, names in op.inputs.items():
            specs = []
            for n in names:
                spec = env.get(n)
                if spec is None:
                    ok = False
                    break
                specs.append(spec)
            if not ok:
                break
            if slot in op_def.duplicable:
                ins_specs[slot] = specs
            elif specs:
                ins_specs[slot] = specs[0]
        if not ok:
            continue
        try:
            out = registry.infer_shapes(
                op_def, ins_specs, op.attrs, strict=True,
                var_names={s: list(ns) for s, ns in op.inputs.items()})
        except registry.InferShapeError as e:
            diags.append(Diagnostic(
                "infer-failed", str(e), severity=_WARNING,
                block_idx=0, op_idx=i, op_type=op.type))
            continue
        if out is None:
            continue
        for slot, names in op.outputs.items():
            if slot not in out:
                continue
            specs = out[slot]
            if not isinstance(specs, list):
                specs = [specs]
            for n, spec in zip(names, specs):
                declared = env.get(n)
                v = block.vars.get(n)
                if v is not None and v.shape is not None and \
                        declared is not None:
                    if len(declared.shape) != len(spec.shape) or any(
                            dd not in (-1, di) and di != -1
                            for dd, di in zip(declared.shape,
                                              spec.shape)):
                        diags.append(Diagnostic(
                            "shape-mismatch",
                            f"slot {slot!r}: declared shape "
                            f"{tuple(declared.shape)} but inference "
                            f"gives {tuple(spec.shape)}",
                            block_idx=0, op_idx=i, op_type=op.type,
                            var=n))
                    elif str(np.dtype(declared.dtype)) != \
                            str(np.dtype(spec.dtype)):
                        # f32 <-> bf16 divergence is the AMP contract:
                        # rewrite_program casts op INPUTS and lets XLA
                        # type-propagate, leaving intermediates'
                        # declared dtypes f32 by design (bf16_transpile
                        # relies on exactly this) — warning, not error.
                        # Any OTHER dtype divergence (int8 vs f32, int
                        # vs float) is a stale rewrite.
                        pair = {str(np.dtype(declared.dtype)),
                                str(np.dtype(spec.dtype))}
                        # ... and 64->32-bit truncation pairs: the
                        # declared IR is platform-independent (int64
                        # labels), while eval_shape runs under this
                        # process's x64-disabled jax config
                        amp_loose = pair in ({"float32", "bfloat16"},
                                             {"int64", "int32"},
                                             {"float64", "float32"})
                        diags.append(Diagnostic(
                            "dtype-mismatch",
                            f"slot {slot!r}: declared dtype "
                            f"{np.dtype(declared.dtype)} but "
                            f"inference gives {np.dtype(spec.dtype)}"
                            + (" (amp-legal pair)" if amp_loose
                               else ""),
                            severity=_WARNING if amp_loose else _ERROR,
                            block_idx=0, op_idx=i, op_type=op.type,
                            var=n))
                # inferred shapes flow forward (filling -1 dims where
                # inference pinned them keeps downstream ops checked)
                merged = spec
                if declared is not None and \
                        len(declared.shape) == len(spec.shape):
                    merged = jax.ShapeDtypeStruct(
                        tuple(di if di != -1 else dd
                              for dd, di in zip(declared.shape,
                                                spec.shape)),
                        spec.dtype)
                env[n] = merged
    return env, diags


def check_shapes(program, raise_=True, label=""):
    """Static shape/dtype check of block 0.  Returns diagnostics;
    raises ShapeCheckError on any error-severity one."""
    _, diags = infer_program_shapes(program)
    if raise_ and any(d.severity == _ERROR for d in diags):
        raise ShapeCheckError(diags, label=label)
    return diags


# ---------------------------------------------------------------------------
# sharding checker
# ---------------------------------------------------------------------------

def _axes_of(entry):
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def check_sharding(program, plan, raise_=True, label=""):
    """Validate every VarDesc.sharding annotation (and the gspmd
    attention tags) against ``plan`` (parallel/gspmd.MeshPlan).
    Returns diagnostics; raises ShardingCheckError on errors."""
    diags = []
    for block in program.blocks:
        for name, v in block.vars.items():
            spec = v.sharding
            if spec is None:
                continue
            if v.shape is None:
                diags.append(Diagnostic(
                    "sharding-unknown-shape",
                    "sharded var has no declared shape",
                    severity=_WARNING, block_idx=block.idx, var=name))
                continue
            if len(spec) > len(v.shape):
                diags.append(Diagnostic(
                    "sharding-rank",
                    f"spec {spec!r} is longer than the var rank "
                    f"{len(v.shape)}",
                    block_idx=block.idx, var=name))
                continue
            used = []
            for dim, entry in enumerate(spec):
                axes = _axes_of(entry)
                factor = 1
                for a in axes:
                    if a not in plan.axes:
                        diags.append(Diagnostic(
                            "sharding-unknown-axis",
                            f"dim {dim}: axis {a!r} is not in the "
                            f"plan {plan!r}",
                            block_idx=block.idx, var=name))
                        continue
                    if a in used:
                        diags.append(Diagnostic(
                            "sharding-axis-reuse",
                            f"dim {dim}: axis {a!r} already shards "
                            "another dim of this var (GSPMD forbids "
                            "axis reuse within one spec)",
                            block_idx=block.idx, var=name))
                    used.append(a)
                    factor *= plan.axis_size(a)
                extent = v.shape[dim]
                if extent is not None and extent >= 0 and factor > 1 \
                        and extent % factor != 0:
                    diags.append(Diagnostic(
                        "sharding-indivisible",
                        f"dim {dim}: extent {extent} is not divisible "
                        f"by {'x'.join(_axes_of(entry))} = {factor}",
                        block_idx=block.idx, var=name))

    # attention tag rules: divisibility must hold statically (the
    # trace-time fallback is silent) and fwd/grad tags must pair
    gb = program.global_block()
    tagged = []
    for i, op in enumerate(gb.ops):
        if op.type not in ("flash_attention", "flash_attention_grad"):
            continue
        ba = op.attrs.get("gspmd_batch_axis") or None
        ha = op.attrs.get("gspmd_head_axis") or None
        if op.type == "flash_attention":
            tagged.append((i, op, ba or ha))
        if ba is None and ha is None:
            continue
        qname = (op.inputs.get("Q") or [None])[0]
        qvar = gb.vars.get(qname) if qname else None
        if qvar is None or qvar.shape is None or len(qvar.shape) != 4:
            continue
        B, H = qvar.shape[0], qvar.shape[1]
        for axis, extent, what in ((ba, B, "batch"), (ha, H, "head")):
            if axis is None:
                continue
            if axis not in plan.axes:
                diags.append(Diagnostic(
                    "sharding-unknown-axis",
                    f"gspmd_{what}_axis {axis!r} is not in the plan "
                    f"{plan!r}",
                    block_idx=0, op_idx=i, op_type=op.type))
            elif extent >= 0 and extent % plan.axis_size(axis) != 0:
                diags.append(Diagnostic(
                    "sharding-indivisible",
                    f"gspmd_{what}_axis {axis!r}: {what} extent "
                    f"{extent} is not divisible by "
                    f"{plan.axis_size(axis)} — shard_map would fall "
                    "back to the unsharded kernel SILENTLY at trace "
                    "time",
                    block_idx=0, op_idx=i, op_type=op.type))
    if any(t[2] for t in tagged):
        for i, op in enumerate(gb.ops):
            if op.type != "flash_attention_grad":
                continue
            if not (op.attrs.get("gspmd_batch_axis") or
                    op.attrs.get("gspmd_head_axis")):
                diags.append(Diagnostic(
                    "sharding-untagged-grad",
                    "flash_attention ops are gspmd-tagged but this "
                    "grad op is not: the vjp re-traces the forward "
                    "under the GRAD op's attrs, so the kernel lands "
                    "inside the SPMD partitioner untagged ('Mosaic "
                    "kernels cannot be automatically partitioned')",
                    block_idx=0, op_idx=i, op_type=op.type))

    if raise_ and any(d.severity == _ERROR for d in diags):
        raise ShardingCheckError(diags, label=label)
    return diags
