"""Static program analysis: IR verifier + shape/dtype/sharding checker.

MLIR-style always-verifiable IR (Lattner et al., 2021) applied to the
Program/Block/Op IR: every transpiler pass can be bracketed by a
structural verify (``checked_pass``), whole programs get a static
shape/dtype inference pass built on ``core.registry.infer_shapes``,
and GSPMD sharding annotations are validated against a ``MeshPlan``
before any compile spends chip time on them.  All diagnostics are
typed and name block / op-index / var (docs/ANALYSIS.md).

Everything is gated by the typed flag ``ir_verify`` (default "off" —
zero behavior change; "on" = structural verify before+after every
transpiler pass; "full" = "on" plus the static shape check after each
pass).  The test suite forces "on" (tests/conftest.py) so every parity
test doubles as a verifier soak.
"""

from paddle_tpu.analysis.verifier import (  # noqa: F401
    Diagnostic, VerifierError, verify, verify_roundtrip)
from paddle_tpu.analysis.shape_check import (  # noqa: F401
    ShapeCheckError, ShardingCheckError, check_shapes, check_sharding,
    infer_program_shapes)
from paddle_tpu.analysis.passes import (  # noqa: F401
    checked_pass, verify_enabled, verify_level)

__all__ = [
    "Diagnostic", "VerifierError", "verify", "verify_roundtrip",
    "ShapeCheckError", "ShardingCheckError", "check_shapes",
    "check_sharding", "infer_program_shapes",
    "checked_pass", "verify_enabled", "verify_level",
]
