"""``checked_pass`` — verify-before/verify-after around every
transpiler pass, behind the typed flag ``ir_verify``.

Levels (flag values):
  * "off"  — default: the wrapper is ONE flag read + one IR-mutation-
             counter bump per pass call and the pass runs untouched
             (flag-off graph bit-identical, asserted in
             tests/test_ir_verifier.py; the bump invalidates the
             program-fingerprint memo at the pass boundary — passes
             that edit op dicts in place otherwise leave a stale
             cached fingerprint behind, see checked_pass);
  * "on"   — structural ``verifier.verify`` runs over every Program
             argument before AND after the pass; a pass that receives
             broken IR raises ``VerifierError`` labeled
             ``<pass>:before``, a pass that breaks IR raises labeled
             ``<pass>:after`` — so the diagnostic names the guilty
             pass, not the next consumer;
  * "full" — "on" plus the static shape/dtype check
             (shape_check.check_shapes) after the pass.

The test suite forces "on" (tests/conftest.py) so every parity test
doubles as a verifier soak; tools/verifier_sweep.py runs the gate
workloads under "full".
"""

from __future__ import annotations

import functools

from paddle_tpu import flags

_LEVELS = ("off", "on", "full")


def verify_level() -> str:
    """Current ir_verify level, normalized ('off'|'on'|'full')."""
    v = str(flags.get_flag("ir_verify")).lower()
    if v in ("1", "true", "yes"):
        return "on"
    return v if v in _LEVELS else "off"


def verify_enabled() -> bool:
    return verify_level() != "off"


def _programs_in(args, kwargs):
    from paddle_tpu.core.program import Program

    out = []
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, Program) and a not in out:
            out.append(a)
    return out


def checked_pass(name):
    """Decorator bracketing an IR-mutating pass entry point with the
    structural verifier (and, at level "full", the static shape
    check).  Every ``Program`` found in the call's arguments is
    verified before the pass and re-verified after it."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            # Pass boundary = fingerprint-memo boundary, at EVERY
            # level including "off": several passes legally edit op
            # dicts in place (layout attrs, memory-opt renames) below
            # the granularity the memo token sees, so a fingerprint
            # cached before the pass would be served stale after it —
            # the jit cache / model registry would key on pre-pass IR.
            # (Found by the ISSUE 15 round-trip property test; the
            # bump only invalidates a private memo, recomputed values
            # are unchanged, so flag-off behavior stays bit-identical.)
            from paddle_tpu.core.program import _bump_ir_mutation

            level = verify_level()
            if level == "off":
                try:
                    return fn(*args, **kwargs)
                finally:
                    _bump_ir_mutation()
            from paddle_tpu.analysis import shape_check, verifier

            programs = _programs_in(args, kwargs)
            for p in programs:
                verifier.verify(p, label=f"{name}:before")
            try:
                out = fn(*args, **kwargs)
            finally:
                _bump_ir_mutation()
            # passes that BUILD programs (pserver/trainer program
            # factories) return them: verify those too, labeled so
            # the diagnostic names the producing pass
            out_programs = _programs_in(
                out if isinstance(out, (list, tuple)) else (out,), {})
            for p in programs:
                verifier.verify(p, label=f"{name}:after")
                if level == "full":
                    shape_check.check_shapes(p, label=f"{name}:after")
            for p in out_programs:
                if p in programs:
                    continue
                verifier.verify(p, label=f"{name}:output")
                if level == "full":
                    shape_check.check_shapes(p, label=f"{name}:output")
            return out

        wrapper.__wrapped_pass__ = name
        return wrapper

    return deco
