"""Device-worker descriptors (reference python/paddle/fluid/device_worker.py:18
DeviceWorker / Hogwild / DownpourSGD / Section + DeviceWorkerFactory, backing
framework/device_worker.h:50 and hogwild_worker.cc / downpour_worker.cc /
section_worker.cc).

The reference's workers are per-CPU-thread interpreters; on TPU the interpreter
is one compiled XLA program, so these descriptors only carry the loop policy
into `Executor.train_from_dataset`:

- Hogwild     -> plain synchronous-compute loop over the dataset feeder.
- DownpourSGD -> same loop with sparse pull/push handled by the PS ops that
                 the fleet transpiler already planted in the program.
- Section     -> delegates to the pipeline section runner
                 (parallel/pipeline.py PipelineRunner) via program._pipeline_opt.
"""

from __future__ import annotations

__all__ = ["DeviceWorker", "Hogwild", "DownpourSGD", "Section",
           "DeviceWorkerFactory"]


class DeviceWorker:
    """reference device_worker.py:18."""

    def __init__(self):
        self._program = None
        self._infer = False
        self._fleet_desc = None

    def _set_infer(self, infer=False):
        self._infer = bool(infer)

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program

    def _gen_worker_desc(self, trainer_desc):
        raise NotImplementedError(
            "DeviceWorker should use an implementation like "
            "Hogwild/DownpourSGD/Section")


class Hogwild(DeviceWorker):
    """Lock-free local worker (reference device_worker.py:71,
    hogwild_worker.cc:137 TrainFiles)."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "HogwildWorker"
        # the reference skips feed ops when inferring; our executor feeds
        # by name so there is nothing to skip, but keep the field for parity
        trainer_desc.skip_ops = ["feed"] if self._infer else []


class DownpourSGD(DeviceWorker):
    """Sparse-PS worker (reference device_worker.py:96,
    downpour_worker.cc:369): collects the sparse/dense table config from
    program._fleet_opt so the trainer knows which vars ride the PS."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "DownpourWorker"
        if self._program is None:
            raise RuntimeError(
                "program of current device worker is not configured")
        opt_info = getattr(self._program, "_fleet_opt", None) or {}
        trainer_desc.sparse_tables = list(opt_info.get("sparse_tables", []))
        trainer_desc.dense_tables = list(opt_info.get("dense_tables", []))
        trainer_desc.skip_ops = list(opt_info.get("skip_ops", []))


class Section(DeviceWorker):
    """Pipeline stage worker (reference device_worker.py:184,
    section_worker.cc:141): publishes the section plan recorded by
    PipelineOptimizer.minimize (program._pipeline_opt) on the trainer."""

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc.device_worker_name = "SectionWorker"
        popt = getattr(self._program, "_pipeline_opt", None)
        if popt is None:
            raise RuntimeError(
                "Section worker needs PipelineOptimizer.minimize to have "
                "run on this program (no _pipeline_opt found)")
        trainer_desc.section_num = len(popt["sections"])
        trainer_desc.num_microbatches = popt.get("num_microbatches", 1)
        trainer_desc.queue_size = popt.get("queue_size",
                                           trainer_desc.num_microbatches)
        trainer_desc.start_cpu_core_id = popt.get("start_cpu_core_id", 0)


class DeviceWorkerFactory:
    """reference device_worker.py:236."""

    def _create_device_worker(self, worker_type):
        classes = {c.__name__: c for c in
                   (Hogwild, DownpourSGD, Section)}
        if worker_type not in classes:
            raise ValueError(f"unknown device worker type {worker_type!r}; "
                             f"choose from {sorted(classes)}")
        return classes[worker_type]()
