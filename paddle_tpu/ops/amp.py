"""AMP support ops: gradient unscale/finite-check and dynamic loss scaling.

Reference parity: the mixed-precision decorator's machinery at
/root/reference/python/paddle/fluid/contrib/mixed_precision/decorator.py:27-194
(scale loss, isfinite reduction over grads, conditional loss-scale update)
and /root/reference/paddle/fluid/operators/isfinite_op.cc.  The reference
composes these from isfinite/scale/cond ops in Python; here they are two
fused ops, which XLA keeps on-device without host round-trips.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


@register_op("check_finite_and_unscale",
             inputs=("X", "Scale"), outputs=("Out", "FoundInfinite"),
             duplicable=("X", "Out"), differentiable=False,
             attrs={"zero_on_inf": True})
def check_finite_and_unscale(ins, attrs):
    """Divide every grad by Scale; FoundInfinite = any non-finite element.
    With zero_on_inf the unscaled grads are zeroed on overflow so the
    optimizer step becomes a no-op for SGD-family updates — the
    XLA-friendly analog of the reference's skip-update conditional (no
    divergent control flow on TPU)."""
    scale = ins["Scale"].reshape(()).astype(jnp.float32)
    xs = ins["X"]
    found = jnp.zeros((), bool)
    for x in xs:
        found = found | ~jnp.all(jnp.isfinite(x))
    outs = []
    for x in xs:
        y = (x.astype(jnp.float32) / scale).astype(x.dtype)
        if attrs["zero_on_inf"]:
            y = jnp.where(found, jnp.zeros_like(y), y)
        outs.append(y)
    return {"Out": outs, "FoundInfinite": found.reshape((1,))}


@register_op("update_loss_scaling",
             inputs=("FoundInfinite", "PrevLossScaling", "InGoodSteps",
                     "InBadSteps"),
             outputs=("LossScaling", "OutGoodSteps", "OutBadSteps"),
             differentiable=False,
             in_place={"LossScaling": "PrevLossScaling",
                       "OutGoodSteps": "InGoodSteps",
                       "OutBadSteps": "InBadSteps"},
             attrs={"incr_every_n_steps": 1000,
                    "decr_every_n_nan_or_inf": 2,
                    "incr_ratio": 2.0, "decr_ratio": 0.8})
def update_loss_scaling(ins, attrs):
    """Dynamic loss-scaling state machine (reference decorator.py
    update_loss_scaling): grow scale after N clean steps, shrink after M
    overflowing ones."""
    found = ins["FoundInfinite"].reshape(()).astype(bool)
    scale = ins["PrevLossScaling"].reshape(()).astype(jnp.float32)
    good = ins["InGoodSteps"].reshape(()).astype(jnp.int32)
    bad = ins["InBadSteps"].reshape(()).astype(jnp.int32)

    good = jnp.where(found, 0, good + 1)
    bad = jnp.where(found, bad + 1, 0)

    grow = good >= attrs["incr_every_n_steps"]
    shrink = bad >= attrs["decr_every_n_nan_or_inf"]
    scale = jnp.where(grow, scale * attrs["incr_ratio"], scale)
    scale = jnp.where(shrink,
                      jnp.maximum(scale * attrs["decr_ratio"], 1.0), scale)
    good = jnp.where(grow, 0, good)
    bad = jnp.where(shrink, 0, bad)
    return {"LossScaling": scale.reshape((1,)),
            "OutGoodSteps": good.reshape((1,)),
            "OutBadSteps": bad.reshape((1,))}
