"""Paged KV-cache: block-table paging over one preallocated HBM pool.

Capability anchor (ROADMAP New-directions #2, ISSUE 7): autoregressive
LLM decode over many concurrent sequences of ragged length.  A dense
per-sequence KV-cache must reserve ``max_len`` tokens per sequence, so
a serving batch of S ragged streams wastes (max_len - len_i) slots per
stream — at 4k context and 90% raggedness that is ~10x the HBM the
live tokens need.  Here the cache is the vLLM PagedAttention shape:

  * ONE preallocated page pool per replica —
    ``[num_pages, H, page_size, d]`` for K and V.  The page is the
    allocation unit; the head axis rides AHEAD of the token axis so a
    flash-decode kernel block slices ``(1, hpb, page_size, d)`` with
    Mosaic-legal trailing dims (page_size, d) — a token-major
    ``[num_pages, page_size, H, d]`` layout would put a size-1 head
    slice in the block's sublane position, the exact construct class
    Mosaic rejected in PR 1/PR 2 (the [1, bq] lse lesson).
  * per-sequence BLOCK TABLES (host int32 [max_seqs, max_pages_per_seq])
    mapping logical page i of a sequence to its physical pool page, so
    thousands of sequences share the pool with zero copy on
    alloc/retire and external fragmentation bounded by one page per
    live sequence.

The allocator is host-side (free-list + tables); the pools are device
arrays updated functionally (one fused scatter per decode step for the
whole running batch).  ``ops.pallas_kernels.flash_decode`` consumes
(pools, tables, lens) directly — K/V stream page-by-page through the
block table, never gathered into a dense [B, T, H, d] copy.

int8 KV storage (flag ``kv_int8``) rides the PR-5 per-channel
requantize contract: pages hold ``q = clip(round(x / s * 127), -127,
127)`` int8 with per-(head, dim) abs-max scales, and the kernel
dequantizes in VMEM (``x_hat = q * s / 127``) — the tensor that
streams from HBM per decode step is int8.  Scales are calibrated on
the first prefill (or given explicitly), the same static-scale story
as the PR-5 activation path.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.observability import device_trace as _obs_device
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _obs_metrics
from paddle_tpu.observability import tracing as _obs_trace

__all__ = ["OutOfPagesError", "PagedKVCache", "quantize_kv",
           "dequantize_kv", "kv_scales_of"]

_M_PAGES = _obs_metrics.counter(
    "paddle_tpu_paged_kv_pages_total",
    "page-pool transitions (alloc / free) summed over every cache in "
    "the process, by event")
_M_OOP = _obs_metrics.counter(
    "paddle_tpu_paged_kv_out_of_pages_total",
    "OutOfPagesError raises (the paging backpressure signal)")

_INT8_BOUND = 127.0  # mirrors ops/quant.py _quantize bit_length=8


class OutOfPagesError(RuntimeError):
    """The pool has no free page (admission backpressure signal: the
    serving tier defers the sequence instead of corrupting the pool)."""


def kv_scales_of(x, floor=1e-8):
    """Per-channel (head, dim) abs-max scale of ``x`` [T, H, d] — the
    PR-5 calibration shape (observed-all-zero channels floor at 1e-8 so
    a zero scale can never read as 'uncalibrated' downstream)."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=0)
    return jnp.maximum(s, floor)


def quantize_kv(x, scale):
    """f32/bf16 [..., H, d] -> int8 under per-channel ``scale`` [H, d]
    (q = clip(round(x/s*127), -127, 127) — ops/quant.py contract)."""
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale * _INT8_BOUND),
                 -_INT8_BOUND, _INT8_BOUND)
    return q.astype(jnp.int8)


def dequantize_kv(q, scale):
    """int8 [..., H, d] -> f32 (x_hat = q * s / 127)."""
    return q.astype(jnp.float32) * (scale / _INT8_BOUND)


def _scatter_token(pool, page_ids, offsets, vals):
    """Write one token's K or V per sequence into the pool:
    pool [P, H, ps, d]; page_ids/offsets [N]; vals [N, H, d]."""
    return pool.at[page_ids, :, offsets, :].set(vals)


_scatter_token_jit = jax.jit(_scatter_token)


class PagedKVCache:
    """Block-table paged K/V pool for one decode replica.

    Host side: free-list page allocator + per-sequence block tables +
    lengths.  Device side: the two pools (functionally updated).  The
    accounting invariant the chaos soak asserts: at every moment
    ``free_pages + in_use_pages == num_pages`` and after drain
    ``in_use_pages == 0`` (zero leaks).
    """

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 dtype=jnp.float32, max_seqs=None,
                 max_pages_per_seq=None, kv_int8=None, kv_scales=None):
        from paddle_tpu.flags import get_flag

        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.kv_int8 = bool(get_flag("kv_int8")) if kv_int8 is None \
            else bool(kv_int8)
        self.dtype = jnp.dtype(dtype)
        store = jnp.int8 if self.kv_int8 else self.dtype
        # one extra SINK page rides past the allocatable pool: batch
        # writes padded to a fixed size (the decode engine's
        # compile-once shape discipline) scatter their dummy rows
        # there — never a free-list page, never in the accounting
        self.sink_page = self.num_pages
        shape = (self.num_pages + 1, self.num_heads, self.page_size,
                 self.head_dim)
        self.k_pages = jnp.zeros(shape, store)
        self.v_pages = jnp.zeros(shape, store)
        # per-channel dequant scales (kv_int8 only): calibrated on the
        # first prefill unless given — the PR-5 static-scale story
        self.k_scale = None
        self.v_scale = None
        if kv_scales is not None:
            self.k_scale = jnp.asarray(kv_scales[0], jnp.float32)
            self.v_scale = jnp.asarray(kv_scales[1], jnp.float32)
        self.max_seqs = int(max_seqs) if max_seqs is not None \
            else self.num_pages
        self.max_pages_per_seq = int(max_pages_per_seq) \
            if max_pages_per_seq is not None else self.num_pages
        # host-side allocator state.  Padded/free table entries point
        # at physical page 0 (always a VALID index): the kernel masks
        # their contribution by seq_len, so a gather through a padded
        # entry reads garbage it then multiplies by zero — never OOB.
        self._tables = np.zeros((self.max_seqs,
                                 self.max_pages_per_seq), np.int32)
        self._lens = np.zeros((self.max_seqs,), np.int32)
        self._free_pages = list(range(self.num_pages - 1, -1, -1))
        self._free_slots = list(range(self.max_seqs - 1, -1, -1))
        self._live = set()          # live slot ids
        self._pages_of = {}         # slot -> [page ids] (alloc order)
        self._peak_in_use = 0

    # -- geometry -----------------------------------------------------------
    def pages_for(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.page_size))

    # -- allocation ---------------------------------------------------------
    def _take_page(self, slot):
        if not self._free_pages:
            _M_OOP.inc()
            raise OutOfPagesError(
                "page pool exhausted (%d pages, %d live seqs)"
                % (self.num_pages, len(self._live)))
        pages = self._pages_of[slot]
        if len(pages) >= self.max_pages_per_seq:
            _M_OOP.inc()
            raise OutOfPagesError(
                "sequence at max_pages_per_seq=%d"
                % self.max_pages_per_seq)
        pid = self._free_pages.pop()
        self._tables[slot, len(pages)] = pid
        pages.append(pid)
        _M_PAGES.inc(event="alloc")
        self._peak_in_use = max(self._peak_in_use, self.in_use_pages())
        return pid

    def alloc(self, n_tokens):
        """Reserve a sequence slot with page capacity for ``n_tokens``;
        returns the slot id.  Raises OutOfPagesError (nothing partially
        allocated) when the pool can't hold it."""
        need = self.pages_for(n_tokens)
        if len(self._free_pages) < need:
            _M_OOP.inc()
            raise OutOfPagesError(
                "need %d pages, %d free (of %d)"
                % (need, len(self._free_pages), self.num_pages))
        if not self._free_slots:
            _M_OOP.inc()
            raise OutOfPagesError("no free sequence slot (max_seqs=%d)"
                                  % self.max_seqs)
        slot = self._free_slots.pop()
        self._live.add(slot)
        self._pages_of[slot] = []
        self._lens[slot] = 0
        for _ in range(need):
            self._take_page(slot)
        _flight.record("paged_kv", "alloc", slot=int(slot),
                       pages=need)
        return slot

    def free(self, slot):
        """Retire a sequence: every page back on the free list."""
        if slot not in self._live:
            raise KeyError("slot %r is not live" % (slot,))
        self._live.discard(slot)
        pages = self._pages_of.pop(slot)
        for pid in pages:
            self._free_pages.append(pid)
        _M_PAGES.inc(len(pages), event="free")
        _flight.record("paged_kv", "free", slot=int(slot),
                       pages=len(pages))
        self._tables[slot, :] = 0
        self._lens[slot] = 0
        self._free_slots.append(slot)

    def reset(self):
        """Drop every sequence (replica relaunch path)."""
        for slot in list(self._live):
            self.free(slot)

    # -- writes -------------------------------------------------------------
    def _maybe_calibrate(self, k, v):
        if self.kv_int8 and self.k_scale is None:
            self.k_scale = kv_scales_of(k)
            self.v_scale = kv_scales_of(v)

    def _store(self, x, scale):
        return quantize_kv(x, scale) if self.kv_int8 \
            else jnp.asarray(x, self.dtype)

    def prefill(self, k, v):
        """Admit a sequence whose prompt K/V is already computed:
        k/v [T, H, d].  Allocates slot + pages, writes page-by-page,
        sets the length.  Returns the slot id."""
        k = jnp.asarray(k)
        t = int(k.shape[0])
        slot = self.alloc(t)
        self._maybe_calibrate(k, v)
        ks = self._store(k, self.k_scale)
        vs = self._store(jnp.asarray(v), self.v_scale)
        ps = self.page_size
        for i, pid in enumerate(self._pages_of[slot]):
            chunk_k = ks[i * ps:(i + 1) * ps]
            chunk_v = vs[i * ps:(i + 1) * ps]
            n = int(chunk_k.shape[0])
            # [n, H, d] -> [H, n, d] (head-major pages)
            self.k_pages = self.k_pages.at[pid, :, :n, :].set(
                jnp.transpose(chunk_k, (1, 0, 2)))
            self.v_pages = self.v_pages.at[pid, :, :n, :].set(
                jnp.transpose(chunk_v, (1, 0, 2)))
        self._lens[slot] = t
        return slot

    def append(self, slots, k, v):
        """Append ONE token per sequence for the whole running batch:
        slots [N] ints, k/v [N_pad, H, d] with N_pad >= N — rows past
        len(slots) are batch padding and scatter into the sink page
        (fixed-shape calls = one compile).  One fused device scatter;
        new pages are taken from the free list as sequences cross a
        page boundary (OutOfPagesError leaves lengths untouched)."""
        if _obs_trace._tracer is not None:
            # device-time attribution (ISSUE 10): the batched append
            # scatter is a decode-step hot spot worth its own lane
            with _obs_device.annotate("paged_kv_append"):
                return self._append_inner(slots, k, v)
        return self._append_inner(slots, k, v)

    def _append_inner(self, slots, k, v):
        slots = list(slots)
        self._maybe_calibrate(jnp.asarray(k), jnp.asarray(v))
        page_ids, offsets = [], []
        taken = []          # rollback on mid-batch exhaustion
        try:
            for s in slots:
                ln = int(self._lens[s])
                if ln % self.page_size == 0 and \
                        ln // self.page_size >= \
                        len(self._pages_of[s]):
                    taken.append((s, self._take_page(s)))
                page_ids.append(self._tables[s, ln // self.page_size])
                offsets.append(ln % self.page_size)
        except OutOfPagesError:
            for s, pid in taken:
                self._pages_of[s].remove(pid)
                self._tables[s, len(self._pages_of[s])] = 0
                self._free_pages.append(pid)
            raise
        ks = self._store(jnp.asarray(k), self.k_scale)
        vs = self._store(jnp.asarray(v), self.v_scale)
        n_pad = int(ks.shape[0]) - len(slots)
        if n_pad:
            page_ids = page_ids + [self.sink_page] * n_pad
            offsets = offsets + [0] * n_pad
        pid_a = jnp.asarray(np.asarray(page_ids, np.int32))
        off_a = jnp.asarray(np.asarray(offsets, np.int32))
        self.k_pages = _scatter_token_jit(self.k_pages, pid_a, off_a,
                                          ks)
        self.v_pages = _scatter_token_jit(self.v_pages, pid_a, off_a,
                                          vs)
        for s in slots:
            self._lens[s] += 1

    # -- reads --------------------------------------------------------------
    def seq_len(self, slot):
        return int(self._lens[slot])

    def tables_for(self, slots, max_pages=None, pad_to=None):
        """Device block-table view [N(_pad), max_pages] int32 for a
        batch of slots (padded COLUMNS point at valid page 0 — the
        kernel masks by length; ``pad_to`` adds dummy ROWS of zeros
        for fixed-batch-shape callers, masked the same way by their
        zero length)."""
        n = max_pages if max_pages is not None else max(
            1, max(self.pages_for(int(self._lens[s])) for s in slots))
        t = self._tables[np.asarray(slots), :n]
        if pad_to is not None and pad_to > t.shape[0]:
            t = np.concatenate(
                [t, np.zeros((pad_to - t.shape[0], n), np.int32)])
        return jnp.asarray(t)

    def lens_for(self, slots, pad_to=None):
        """Device lengths [N(_pad)] int32 (dummy rows length 0 — the
        kernel emits zeros for them)."""
        ln = self._lens[np.asarray(slots)]
        if pad_to is not None and pad_to > ln.shape[0]:
            ln = np.concatenate(
                [ln, np.zeros((pad_to - ln.shape[0],), np.int32)])
        return jnp.asarray(ln)

    def kv_scales(self):
        """(k_scale, v_scale) per-channel [H, d] dequant scales (int8
        mode; None otherwise)."""
        return self.k_scale, self.v_scale

    # -- accounting ---------------------------------------------------------
    def in_use_pages(self):
        return sum(len(p) for p in self._pages_of.values())

    def free_pages(self):
        return len(self._free_pages)

    def stats(self):
        """Allocator + fragmentation stats (the chaos soak's audit
        surface).  ``accounted`` is the leak invariant: every pool page
        is either free or owned by exactly one live sequence."""
        in_use = self.in_use_pages()
        owned = [p for pages in self._pages_of.values() for p in pages]
        live_tokens = int(sum(self._lens[s] for s in self._live))
        capacity = in_use * self.page_size
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "free_pages": self.free_pages(),
            "in_use_pages": in_use,
            "peak_in_use_pages": self._peak_in_use,
            "live_seqs": len(self._live),
            "accounted": (self.free_pages() + in_use == self.num_pages
                          and len(owned) == len(set(owned))),
            # internal fragmentation: tail slack of the last page of
            # each live sequence (the only waste paging permits)
            "internal_frag_pct": round(
                100.0 * (capacity - live_tokens) / capacity, 2)
            if capacity else 0.0,
            "kv_int8": self.kv_int8,
        }

    def check_accounting(self):
        """(ok, detail) — free + in_use == num_pages, no page owned
        twice, no freed page still owned."""
        st = self.stats()
        if not st["accounted"]:
            return False, ("page accounting broken: free=%d in_use=%d "
                           "pool=%d" % (st["free_pages"],
                                        st["in_use_pages"],
                                        st["num_pages"]))
        owned = {p for pages in self._pages_of.values() for p in pages}
        both = owned & set(self._free_pages)
        if both:
            return False, "pages both free and owned: %s" % sorted(both)
        return True, ""
