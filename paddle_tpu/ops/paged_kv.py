"""Paged KV-cache: block-table paging over one preallocated HBM pool.

Capability anchor (ROADMAP New-directions #2, ISSUE 7): autoregressive
LLM decode over many concurrent sequences of ragged length.  A dense
per-sequence KV-cache must reserve ``max_len`` tokens per sequence, so
a serving batch of S ragged streams wastes (max_len - len_i) slots per
stream — at 4k context and 90% raggedness that is ~10x the HBM the
live tokens need.  Here the cache is the vLLM PagedAttention shape:

  * ONE preallocated page pool per replica —
    ``[num_pages, H, page_size, d]`` for K and V.  The page is the
    allocation unit; the head axis rides AHEAD of the token axis so a
    flash-decode kernel block slices ``(1, hpb, page_size, d)`` with
    Mosaic-legal trailing dims (page_size, d) — a token-major
    ``[num_pages, page_size, H, d]`` layout would put a size-1 head
    slice in the block's sublane position, the exact construct class
    Mosaic rejected in PR 1/PR 2 (the [1, bq] lse lesson).
  * per-sequence BLOCK TABLES (host int32 [max_seqs, max_pages_per_seq])
    mapping logical page i of a sequence to its physical pool page, so
    thousands of sequences share the pool with zero copy on
    alloc/retire and external fragmentation bounded by one page per
    live sequence.

The allocator is host-side (free-list + tables); the pools are device
arrays updated functionally (one fused scatter per decode step for the
whole running batch).  ``ops.pallas_kernels.flash_decode`` consumes
(pools, tables, lens) directly — K/V stream page-by-page through the
block table, never gathered into a dense [B, T, H, d] copy.

int8 KV storage (flag ``kv_int8``) rides the PR-5 per-channel
requantize contract: pages hold ``q = clip(round(x / s * 127), -127,
127)`` int8 with per-(head, dim) abs-max scales, and the kernel
dequantizes in VMEM (``x_hat = q * s / 127``) — the tensor that
streams from HBM per decode step is int8.  Scales are calibrated on
the first prefill (or given explicitly), the same static-scale story
as the PR-5 activation path.

Copy-on-write prefix sharing (flag ``kv_share``, ISSUE 11b): every
page carries a REFCOUNT and the cache keeps a radix tree (page-granular
token trie) over the FULL pages it has written, so

  * two requests whose prompts share a token prefix share the physical
    pages of that prefix (``prefill(..., tokens=...)`` looks the
    prefix up; ``shared_prefix_tokens`` lets the caller skip the
    projections for the shared span entirely — a common system prompt
    amortizes its prefill to zero);
  * beams share everything at ``fork`` time (all pages refcounted up,
    block table copied);
  * a write landing in a page with refcount > 1 COPIES-ON-WRITE
    through the same atomic take-a-free-page path (the page bytes are
    duplicated device-side, the writer's table repoints, the shared
    original is untouched).

Only FULL pages enter the radix tree — a full page is immutable (later
appends go to later pages; a COW replaces the writer's pointer, never
the bytes), which is what makes sharing sound.  The zero-leak
invariant generalizes to ``free + unique(in_use) == num_pages`` with
``ref[p] == number of sequences holding p`` — ``check_accounting``
verifies both, and the chaos soak asserts them after every drain.
Shared-decode output is bit-identical (array_equal) to unshared: the
kernel reads the same physical bytes through a different table.
"""

from __future__ import annotations

import itertools
import math
import threading
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.observability import device_trace as _obs_device
from paddle_tpu.observability import flight_recorder as _flight
from paddle_tpu.observability import metrics as _obs_metrics
from paddle_tpu.observability import tracing as _obs_trace

__all__ = ["OutOfPagesError", "PagedKVCache", "quantize_kv",
           "dequantize_kv", "kv_scales_of"]

_M_PAGES = _obs_metrics.counter(
    "paddle_tpu_paged_kv_pages_total",
    "page-pool transitions (alloc / share / cow / free) summed over "
    "every cache in the process, by event")
_M_OOP = _obs_metrics.counter(
    "paddle_tpu_paged_kv_out_of_pages_total",
    "OutOfPagesError raises (the paging backpressure signal)")
# page-pressure gauges (ISSUE 11 satellite): /metrics and the
# serving_load / chaos_soak JSON embeds show pool state next to
# tokens/s, by per-process cache index
_G_FREE = _obs_metrics.gauge(
    "paddle_tpu_paged_kv_pages_free",
    "free pages of each cache's pool, by cache index", max_series=64)
_G_IN_USE = _obs_metrics.gauge(
    "paddle_tpu_paged_kv_pages_in_use",
    "unique owned pages of each cache's pool, by cache index",
    max_series=64)
_G_SHARED = _obs_metrics.gauge(
    "paddle_tpu_paged_kv_pages_shared",
    "pages with refcount > 1 (prefix-shared / forked), by cache index",
    max_series=64)
_G_FRAG = _obs_metrics.gauge(
    "paddle_tpu_paged_kv_internal_frag_pct",
    "tail slack of live sequences' last pages as % of owned capacity, "
    "by cache index", max_series=64)
_G_TRANSIT = _obs_metrics.gauge(
    "paddle_tpu_paged_kv_pages_in_transit",
    "pages held by detached handoff handles (prefill -> decode tier "
    "transfer, ISSUE 14), by cache index", max_series=64)

_INT8_BOUND = 127.0  # mirrors ops/quant.py _quantize bit_length=8

_CACHE_INDEX = itertools.count()


class OutOfPagesError(RuntimeError):
    """The pool has no free page (admission backpressure signal: the
    serving tier defers the sequence instead of corrupting the pool)."""


def kv_scales_of(x, floor=1e-8):
    """Per-channel (head, dim) abs-max scale of ``x`` [T, H, d] — the
    PR-5 calibration shape (observed-all-zero channels floor at 1e-8 so
    a zero scale can never read as 'uncalibrated' downstream)."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=0)
    return jnp.maximum(s, floor)


def quantize_kv(x, scale):
    """f32/bf16 [..., H, d] -> int8 under per-channel ``scale`` [H, d]
    (q = clip(round(x/s*127), -127, 127) — ops/quant.py contract)."""
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale * _INT8_BOUND),
                 -_INT8_BOUND, _INT8_BOUND)
    return q.astype(jnp.int8)


def dequantize_kv(q, scale):
    """int8 [..., H, d] -> f32 (x_hat = q * s / 127)."""
    return q.astype(jnp.float32) * (scale / _INT8_BOUND)


def _scatter_token(pool, page_ids, offsets, vals):
    """Write one token's K or V per sequence into the pool:
    pool [P, H, ps, d]; page_ids/offsets [N]; vals [N, H, d]."""
    return pool.at[page_ids, :, offsets, :].set(vals)


_scatter_token_jit = jax.jit(_scatter_token)


def _copy_pages(pool, old_ids, new_ids):
    """Duplicate whole pages device-side (the COW byte copy):
    pool [P, H, ps, d]; old_ids/new_ids [N] int32."""
    return pool.at[new_ids].set(pool[old_ids])


_copy_pages_jit = jax.jit(_copy_pages)


class PagedKVCache:
    """Block-table paged K/V pool for one decode replica.

    Host side: free-list page allocator + per-page refcounts +
    per-sequence block tables + lengths (+ the full-page radix tree
    under ``kv_share``).  Device side: the two pools (functionally
    updated).  The accounting invariant the chaos soak asserts: at
    every moment ``free_pages + unique in_use_pages == num_pages``
    with every page's refcount equal to the number of sequences
    holding it, and after drain ``in_use_pages == 0`` (zero leaks).
    """

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 dtype=jnp.float32, max_seqs=None,
                 max_pages_per_seq=None, kv_int8=None, kv_scales=None,
                 kv_share=None):
        from paddle_tpu.flags import get_flag

        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.kv_int8 = bool(get_flag("kv_int8")) if kv_int8 is None \
            else bool(kv_int8)
        self.kv_share = bool(get_flag("kv_share")) if kv_share is None \
            else bool(kv_share)
        self.dtype = jnp.dtype(dtype)
        store = jnp.int8 if self.kv_int8 else self.dtype
        # one extra SINK page rides past the allocatable pool: batch
        # writes padded to a fixed size (the decode engine's
        # compile-once shape discipline) scatter their dummy rows
        # there — never a free-list page, never in the accounting
        self.sink_page = self.num_pages
        shape = (self.num_pages + 1, self.num_heads, self.page_size,
                 self.head_dim)
        self.k_pages = jnp.zeros(shape, store)
        self.v_pages = jnp.zeros(shape, store)
        # per-channel dequant scales (kv_int8 only): calibrated on the
        # first prefill unless given — the PR-5 static-scale story
        self.k_scale = None
        self.v_scale = None
        if kv_scales is not None:
            self.k_scale = jnp.asarray(kv_scales[0], jnp.float32)
            self.v_scale = jnp.asarray(kv_scales[1], jnp.float32)
        self.max_seqs = int(max_seqs) if max_seqs is not None \
            else self.num_pages
        self.max_pages_per_seq = int(max_pages_per_seq) \
            if max_pages_per_seq is not None else self.num_pages
        # host-side allocator state.  Padded/free table entries point
        # at physical page 0 (always a VALID index): the kernel masks
        # their contribution by seq_len, so a gather through a padded
        # entry reads garbage it then multiplies by zero — never OOB.
        self._tables = np.zeros((self.max_seqs,
                                 self.max_pages_per_seq), np.int32)
        self._lens = np.zeros((self.max_seqs,), np.int32)
        self._free_pages = list(range(self.num_pages - 1, -1, -1))
        self._free_slots = list(range(self.max_seqs - 1, -1, -1))
        self._live = set()          # live slot ids
        self._pages_of = {}         # slot -> [page ids] (logical order)
        # per-page refcount (ISSUE 11b): number of sequences whose
        # block table holds the page.  1 everywhere unless kv_share.
        self._ref = np.zeros((self.num_pages,), np.int32)
        self._n_shared = 0          # pages with ref > 1
        # radix tree over FULL pages: root children keyed by the
        # page_size-token tuple; node = {"page": pid, "children": {}}.
        # _radix_of_page maps pid -> (parent_children_dict, key) for
        # O(1) detach when the page's refcount reaches zero.
        self._radix_root = {"children": {}}
        self._radix_of_page = {}
        # radix insertion cursor per slot (chunked prefill registers
        # full pages incrementally as extend() completes them)
        self._radix_cursor = {}
        self._peak_in_use = 0
        self._peak_shared = 0
        # detached page-list handoffs (ISSUE 14): handle id ->
        # {"pages": [...], "length": n}.  Pages in transit are OWNED
        # (never on the free list) but belong to no slot — the
        # disaggregated prefill->decode transfer window.  The
        # accounting invariant counts them as in-use.
        self._in_transit = {}
        self._handoff_ids = itertools.count(1)
        # tier-shared pools (disaggregated serving) mutate this cache
        # from prefill AND decode workers; single-tier callers pay one
        # uncontended RLock acquire per op
        self.lock = threading.RLock()
        self._label = str(next(_CACHE_INDEX))

    # -- geometry -----------------------------------------------------------
    def pages_for(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.page_size))

    # -- allocation ---------------------------------------------------------
    def _take_page(self, slot):
        if not self._free_pages:
            _M_OOP.inc()
            raise OutOfPagesError(
                "page pool exhausted (%d pages, %d live seqs)"
                % (self.num_pages, len(self._live)))
        pages = self._pages_of[slot]
        if len(pages) >= self.max_pages_per_seq:
            _M_OOP.inc()
            raise OutOfPagesError(
                "sequence at max_pages_per_seq=%d"
                % self.max_pages_per_seq)
        pid = self._free_pages.pop()
        self._ref[pid] = 1
        self._tables[slot, len(pages)] = pid
        pages.append(pid)
        _M_PAGES.inc(event="alloc")
        self._peak_in_use = max(self._peak_in_use, self._owned_count())
        return pid

    def _untake_page(self, slot, pid):
        """Inverse of _take_page (the atomic rollback path): pid must
        be the slot's LAST page."""
        pages = self._pages_of[slot]
        assert pages and pages[-1] == pid
        pages.pop()
        self._tables[slot, len(pages)] = 0
        self._ref[pid] = 0
        self._free_pages.append(pid)

    def _share_page(self, slot, pid):
        """Point ``slot``'s next logical page at an already-owned
        physical page (prefix sharing / fork)."""
        pages = self._pages_of[slot]
        if len(pages) >= self.max_pages_per_seq:
            _M_OOP.inc()
            raise OutOfPagesError(
                "sequence at max_pages_per_seq=%d"
                % self.max_pages_per_seq)
        self._ref[pid] += 1
        if self._ref[pid] == 2:
            self._n_shared += 1
            self._peak_shared = max(self._peak_shared, self._n_shared)
        self._tables[slot, len(pages)] = pid
        pages.append(pid)
        _M_PAGES.inc(event="share")

    def _deref_page(self, pid):
        """Drop one reference; returns True when the page went back to
        the free list (refcount hit zero)."""
        self._ref[pid] -= 1
        if self._ref[pid] == 1:
            self._n_shared -= 1
        if self._ref[pid] == 0:
            self._free_pages.append(pid)
            self._radix_detach(pid)
            return True
        return False

    def alloc(self, n_tokens):
        """Reserve a sequence slot with page capacity for ``n_tokens``;
        returns the slot id.  Raises OutOfPagesError (nothing partially
        allocated) when the pool can't hold it."""
        need = self.pages_for(n_tokens)
        if len(self._free_pages) < need:
            _M_OOP.inc()
            raise OutOfPagesError(
                "need %d pages, %d free (of %d)"
                % (need, len(self._free_pages), self.num_pages))
        slot = self._take_slot()
        for _ in range(need):
            self._take_page(slot)
        _flight.record("paged_kv", "alloc", slot=int(slot),
                       pages=need)
        self._export_gauges()
        return slot

    def _take_slot(self):
        if not self._free_slots:
            _M_OOP.inc()
            raise OutOfPagesError("no free sequence slot (max_seqs=%d)"
                                  % self.max_seqs)
        slot = self._free_slots.pop()
        self._live.add(slot)
        self._pages_of[slot] = []
        self._lens[slot] = 0
        return slot

    def free(self, slot):
        """Retire a sequence: every reference dropped; pages whose
        refcount reaches zero go back on the free list."""
        if slot not in self._live:
            raise KeyError("slot %r is not live" % (slot,))
        self._live.discard(slot)
        pages = self._pages_of.pop(slot)
        n_freed = 0
        for pid in pages:
            if self._deref_page(pid):
                n_freed += 1
        _M_PAGES.inc(n_freed, event="free")
        _flight.record("paged_kv", "free", slot=int(slot),
                       pages=len(pages))
        self._tables[slot, :] = 0
        self._lens[slot] = 0
        self._radix_cursor.pop(slot, None)
        self._free_slots.append(slot)
        self._export_gauges()

    def reset(self):
        """Drop every sequence AND in-transit handoff (replica
        relaunch path)."""
        for slot in list(self._live):
            self.free(slot)
        self.release_in_transit()

    def fork(self, slot):
        """Beam fork (ISSUE 11b): a NEW slot sharing every page of
        ``slot`` (refcounts up, block table copied, same length) —
        zero bytes copied now; the first divergent append to a shared
        page copies-on-write.  Needs ``kv_share``."""
        if not self.kv_share:
            raise RuntimeError("fork() needs kv_share=True (copy-on-"
                               "write is what makes aliased pages "
                               "sound)")
        if slot not in self._live:
            raise KeyError("slot %r is not live" % (slot,))
        new = self._take_slot()
        try:
            for pid in self._pages_of[slot]:
                self._share_page(new, pid)
        except OutOfPagesError:
            for pid in list(self._pages_of[new]):
                self._deref_page(pid)
            self._pages_of.pop(new)
            self._tables[new, :] = 0
            self._live.discard(new)
            self._free_slots.append(new)
            raise
        self._lens[new] = self._lens[slot]
        _flight.record("paged_kv", "fork", slot=int(slot),
                       child=int(new),
                       pages=len(self._pages_of[new]))
        self._export_gauges()
        return new

    def truncate(self, slot, new_len):
        """Rewind a sequence to ``new_len`` tokens (the speculative-
        decoding rejection path, ISSUE 11c): pages wholly past the new
        length are dereferenced through the same atomic free path —
        rejection is a page-pointer rewind, never a byte rewrite."""
        if slot not in self._live:
            raise KeyError("slot %r is not live" % (slot,))
        new_len = int(new_len)
        cur = int(self._lens[slot])
        if not 0 <= new_len <= cur:
            raise ValueError("truncate to %d outside [0, %d]"
                             % (new_len, cur))
        keep = self.pages_for(new_len)   # >= 1: alloc's one-page floor
        pages = self._pages_of[slot]
        dropped = pages[keep:]
        del pages[keep:]
        for pid in dropped:
            self._deref_page(pid)
        self._tables[slot, keep:keep + len(dropped)] = 0
        self._lens[slot] = new_len
        if dropped:
            _M_PAGES.inc(len(dropped), event="rewind")
        self._export_gauges()

    # -- page-list handoff (disaggregated prefill -> decode, ISSUE 14) ------
    def detach(self, slot):
        """Detach a live sequence into a PAGE-LIST handoff handle: the
        slot id is released but its pages stay owned (refcounts
        unchanged — the handle holds the slot's references), parked in
        the in-transit set until ``adopt`` re-attaches them to a new
        slot or ``release_in_transit`` frees them.

        This is the disaggregated prefill->decode transfer: the handle
        carries ONLY host metadata — the physical page ids (the
        block-table entries) and the token length — never K/V bytes.
        Zero device copies on this path (the pool arrays are untouched;
        asserted by the handoff tests via array identity)."""
        if slot not in self._live:
            raise KeyError("slot %r is not live" % (slot,))
        pages = self._pages_of.pop(slot)
        length = int(self._lens[slot])
        self._live.discard(slot)
        self._tables[slot, :] = 0
        self._lens[slot] = 0
        self._radix_cursor.pop(slot, None)
        self._free_slots.append(slot)
        hid = next(self._handoff_ids)
        self._in_transit[hid] = {"pages": list(pages),
                                 "length": length}
        handle = {"id": hid, "pages": list(pages), "length": length}
        _M_PAGES.inc(len(pages), event="detach")
        _flight.record("paged_kv", "detach", slot=int(slot),
                       handoff=hid, pages=len(pages), tokens=length)
        self._export_gauges()
        return handle

    def adopt(self, handle):
        """Adopt an in-transit page list onto a fresh slot (the decode
        tier's side of the handoff): block-table entries reinstated,
        length restored, refcounts untouched — the handle's references
        become the slot's.  Raises OutOfPagesError (handle STAYS in
        transit — the caller may retry or release) when no sequence
        slot is free or the list exceeds the table width; KeyError for
        an unknown/already-settled handle."""
        hid = handle["id"] if isinstance(handle, dict) else int(handle)
        ent = self._in_transit.get(hid)
        if ent is None:
            raise KeyError("handoff %r is not in transit" % (hid,))
        pages = ent["pages"]
        if len(pages) > self.max_pages_per_seq:
            _M_OOP.inc()
            raise OutOfPagesError(
                "handoff of %d pages exceeds max_pages_per_seq=%d"
                % (len(pages), self.max_pages_per_seq))
        if not self._free_slots:
            _M_OOP.inc()
            raise OutOfPagesError("no free sequence slot (max_seqs=%d)"
                                  % self.max_seqs)
        del self._in_transit[hid]
        slot = self._take_slot()
        self._pages_of[slot] = list(pages)
        self._tables[slot, :len(pages)] = np.asarray(pages, np.int32)
        self._lens[slot] = ent["length"]
        _M_PAGES.inc(len(pages), event="adopt")
        _flight.record("paged_kv", "adopt", slot=int(slot),
                       handoff=hid, pages=len(pages),
                       tokens=ent["length"])
        self._export_gauges()
        return slot

    def release_in_transit(self, handle=None):
        """Drop an in-transit handle's page references (the
        kill-mid-handoff / expiry abort path) — pages whose refcount
        reaches zero return to the free list, exactly like ``free``.
        With no argument, releases EVERY in-transit handle (server
        stop sweep).  Returns the number of pages freed."""
        if handle is None:
            n = 0
            for hid in list(self._in_transit):
                n += self.release_in_transit(hid)
            return n
        hid = handle["id"] if isinstance(handle, dict) else int(handle)
        ent = self._in_transit.pop(hid, None)
        if ent is None:
            return 0
        n_freed = 0
        for pid in ent["pages"]:
            if self._deref_page(pid):
                n_freed += 1
        _M_PAGES.inc(n_freed, event="free")
        _flight.record("paged_kv", "handoff_released", handoff=hid,
                       pages=len(ent["pages"]))
        self._export_gauges()
        return n_freed

    def in_transit_pages(self):
        """Pages currently held by detached handoff handles."""
        return sum(len(e["pages"])
                   for e in self._in_transit.values())

    # -- prefix sharing (radix tree over full pages) ------------------------
    @staticmethod
    def _page_key(tokens, i, ps):
        return tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])

    def _radix_walk(self, tokens, max_pages=None):
        """Longest chain of radix nodes matching ``tokens``' full
        pages; returns the node list (possibly empty)."""
        ps = self.page_size
        n_full = len(tokens) // ps
        if max_pages is not None:
            n_full = min(n_full, max_pages)
        cur, chain = self._radix_root, []
        for i in range(n_full):
            node = cur["children"].get(self._page_key(tokens, i, ps))
            if node is None:
                break
            chain.append(node)
            cur = node
        return chain

    def shared_prefix_tokens(self, tokens):
        """Number of leading tokens of ``tokens`` whose pages the pool
        already holds (a multiple of page_size; 0 unless kv_share).
        The caller may skip computing K/V for that span entirely —
        this is where a shared system prompt's prefill amortizes to
        zero."""
        if not self.kv_share or tokens is None:
            return 0
        return len(self._radix_walk(tokens)) * self.page_size

    def _radix_register(self, slot, tokens, first_page, pages):
        """Insert newly WRITTEN full pages into the tree.  ``tokens``
        is the slot's full token history; pages[i] backs logical page
        first_page + i and every one of them is full.  A key conflict
        (another sequence registered the same content concurrently)
        keeps the existing node — our copy stays private."""
        ps = self.page_size
        cur = self._radix_cursor.get(slot)
        if cur is None:
            chain = self._radix_walk(tokens, max_pages=first_page)
            if len(chain) < first_page:
                # ancestors unregistered (e.g. COW'd writer): the tree
                # only holds chains rooted at page 0, so stop here
                return
            cur = chain[-1] if chain else self._radix_root
        for i, pid in enumerate(pages):
            key = self._page_key(tokens, first_page + i, ps)
            node = cur["children"].get(key)
            if node is None:
                node = {"page": int(pid), "children": {}}
                cur["children"][key] = node
                self._radix_of_page[int(pid)] = (cur["children"], key)
            cur = node
        self._radix_cursor[slot] = cur

    def _radix_detach(self, pid):
        """Remove a dead page's node (and its — necessarily dead —
        descendants) from the tree."""
        ent = self._radix_of_page.pop(pid, None)
        if ent is None:
            return
        parent_children, key = ent
        node = parent_children.pop(key, None)
        stack = [node] if node is not None else []
        while stack:
            n = stack.pop()
            self._radix_of_page.pop(n["page"], None)
            stack.extend(n["children"].values())
            n["children"] = {}

    # -- writes -------------------------------------------------------------
    def _maybe_calibrate(self, k, v):
        if self.kv_int8 and self.k_scale is None:
            self.k_scale = kv_scales_of(k)
            self.v_scale = kv_scales_of(v)

    def _store(self, x, scale):
        return quantize_kv(x, scale) if self.kv_int8 \
            else jnp.asarray(x, self.dtype)

    def prefill(self, k, v, tokens=None):
        """Admit a sequence whose prompt K/V is already computed:
        k/v [T, H, d].  Allocates slot + pages, writes page-by-page,
        sets the length.  Returns the slot id.

        With ``kv_share`` and ``tokens`` (the prompt token ids): the
        longest already-cached full-page prefix is SHARED instead of
        written (refcounts up, zero device writes, zero projection
        work needed for it), and k/v may cover either the full prompt
        or only the unshared tail ``tokens[shared_prefix_tokens():]``.
        Newly written full pages register in the radix tree so later
        prompts can share them."""
        share = self.kv_share and tokens is not None
        if share:
            t = len(tokens)
            shared_nodes = self._radix_walk(tokens)
            m = len(shared_nodes) * self.page_size
        else:
            t = int(jnp.asarray(k).shape[0])
            shared_nodes, m = [], 0
        need_new = self.pages_for(t) - len(shared_nodes) if t else 1
        if len(self._free_pages) < max(0, need_new):
            _M_OOP.inc()
            raise OutOfPagesError(
                "need %d pages, %d free (of %d)"
                % (need_new, len(self._free_pages), self.num_pages))
        k = jnp.asarray(k)
        v = jnp.asarray(v)
        if share:
            if int(k.shape[0]) == t:
                k, v = k[m:], v[m:]
            elif int(k.shape[0]) != t - m:
                raise ValueError(
                    "k/v must cover the full prompt (%d tokens) or "
                    "the unshared tail (%d); got %d"
                    % (t, t - m, int(k.shape[0])))
        slot = self._take_slot()
        try:
            for node in shared_nodes:
                self._share_page(slot, node["page"])
            self._lens[slot] = m
            if shared_nodes:
                self._radix_cursor[slot] = shared_nodes[-1]
            if t - m:
                self._write_tokens(slot, k, v,
                                   tokens=tokens if share else None)
            elif not self._pages_of[slot]:
                self._take_page(slot)   # alloc's >= 1 page floor
            self._lens[slot] = t
        except OutOfPagesError:
            # atomic: nothing partially allocated survives a failure
            for pid in list(self._pages_of[slot]):
                self._deref_page(pid)
            self._pages_of.pop(slot)
            self._tables[slot, :] = 0
            self._lens[slot] = 0
            self._live.discard(slot)
            self._radix_cursor.pop(slot, None)
            self._free_slots.append(slot)
            raise
        _flight.record("paged_kv", "alloc", slot=int(slot),
                       pages=len(self._pages_of[slot]),
                       shared=len(shared_nodes))
        self._export_gauges()
        return slot

    def extend(self, slot, k, v, tokens=None):
        """Append T tokens' K/V to one sequence (the chunked-prefill
        write path, ISSUE 11a): k/v [T, H, d] land at the slot's
        current length, taking pages as needed — atomic (nothing
        written, no page kept on OutOfPagesError).  ``tokens`` (the
        slot's FULL token history including these T) lets newly
        completed full pages register for prefix sharing."""
        if slot not in self._live:
            raise KeyError("slot %r is not live" % (slot,))
        self._maybe_calibrate(jnp.asarray(k), jnp.asarray(v))
        self._write_tokens(slot, jnp.asarray(k), jnp.asarray(v),
                           tokens=tokens)
        self._export_gauges()

    def _write_tokens(self, slot, k, v, tokens=None):
        """Shared write engine for prefill tails and extend: plan the
        page takes/COWs for T tokens at the current length (undo
        journal => atomic), then one device write per touched page."""
        t = int(k.shape[0])
        if t == 0:
            return
        self._maybe_calibrate(k, v)
        ps = self.page_size
        start = int(self._lens[slot])
        journal = []
        cow_pairs = []
        try:
            for pos in range(start, start + t):
                idx = pos // ps
                pages = self._pages_of[slot]
                if idx >= len(pages):
                    pid = self._take_page(slot)
                    journal.append(("take", pid))
                elif self.kv_share and self._ref[pages[idx]] > 1:
                    old = pages[idx]
                    pid = self._cow_page(slot, idx)
                    journal.append(("cow", idx, old, pid))
                    cow_pairs.append((old, pid))
        except OutOfPagesError:
            self._undo(slot, journal)
            raise
        self._apply_cow(cow_pairs)
        ks = self._store(k, self.k_scale)
        vs = self._store(v, self.v_scale)
        first_new_full = []
        pages = self._pages_of[slot]
        off0 = start % ps
        w = 0
        idx = start // ps
        while w < t:
            n = min(ps - off0, t - w)
            pid = pages[idx]
            self.k_pages = self.k_pages.at[
                pid, :, off0:off0 + n, :].set(
                jnp.transpose(ks[w:w + n], (1, 0, 2)))
            self.v_pages = self.v_pages.at[
                pid, :, off0:off0 + n, :].set(
                jnp.transpose(vs[w:w + n], (1, 0, 2)))
            if off0 + n == ps:
                first_new_full.append((idx, pid))
            w += n
            off0 = 0
            idx += 1
        self._lens[slot] = start + t
        if self.kv_share and tokens is not None and first_new_full:
            # register the completed full pages (contiguous by
            # construction) for prefix sharing
            i0 = first_new_full[0][0]
            self._radix_register(
                slot, tokens, i0, [p for _, p in first_new_full])

    def _cow_page(self, slot, idx):
        """Copy-on-write: repoint logical page ``idx`` of ``slot`` at
        a fresh physical page (bytes duplicated by _apply_cow); the
        shared original keeps its other holders."""
        if not self._free_pages:
            _M_OOP.inc()
            raise OutOfPagesError(
                "page pool exhausted during copy-on-write (%d pages, "
                "%d live seqs)" % (self.num_pages, len(self._live)))
        old = self._pages_of[slot][idx]
        new = self._free_pages.pop()
        self._ref[new] = 1
        self._ref[old] -= 1
        if self._ref[old] == 1:
            self._n_shared -= 1
        self._pages_of[slot][idx] = new
        self._tables[slot, idx] = new
        _M_PAGES.inc(event="cow")
        self._peak_in_use = max(self._peak_in_use, self._owned_count())
        return new

    def _undo(self, slot, journal):
        for step in reversed(journal):
            if step[0] == "take":
                self._untake_page(slot, step[1])
            else:
                _, idx, old, new = step
                self._pages_of[slot][idx] = old
                self._tables[slot, idx] = old
                self._ref[old] += 1
                if self._ref[old] == 2:
                    self._n_shared += 1
                self._ref[new] = 0
                self._free_pages.append(new)

    def _apply_cow(self, cow_pairs):
        if not cow_pairs:
            return
        olds = jnp.asarray(np.asarray([o for o, _ in cow_pairs],
                                      np.int32))
        news = jnp.asarray(np.asarray([n for _, n in cow_pairs],
                                      np.int32))
        self.k_pages = _copy_pages_jit(self.k_pages, olds, news)
        self.v_pages = _copy_pages_jit(self.v_pages, olds, news)

    def append(self, slots, k, v):
        """Append tokens for the whole running batch: slots [N] ints;
        k/v [N_pad, H, d] (ONE token per sequence) or
        [N_pad, R, H, d] (R tokens per sequence — the speculative
        verify write, ISSUE 11c).  Rows past len(slots) are batch
        padding and scatter into the sink page (fixed-shape calls =
        one compile).  One fused device scatter; new pages come off
        the free list as sequences cross page boundaries, shared
        pages copy-on-write first, and OutOfPagesError leaves
        lengths, tables and refcounts untouched (atomic)."""
        if _obs_trace._tracer is not None:
            # device-time attribution (ISSUE 10): the batched append
            # scatter is a decode-step hot spot worth its own lane
            with _obs_device.annotate("paged_kv_append"):
                return self._append_inner(slots, k, v)
        return self._append_inner(slots, k, v)

    def _append_inner(self, slots, k, v):
        slots = list(slots)
        k = jnp.asarray(k)
        v = jnp.asarray(v)
        r = 1 if k.ndim == 3 else int(k.shape[1])
        self._maybe_calibrate(k.reshape((-1,) + k.shape[-2:]),
                              v.reshape((-1,) + v.shape[-2:]))
        page_ids, offsets = [], []
        journal = {}            # slot -> undo journal
        cow_pairs = []
        try:
            for s in slots:
                ln = int(self._lens[s])
                jr = journal.setdefault(s, [])
                for j in range(r):
                    pos = ln + j
                    idx = pos // self.page_size
                    pages = self._pages_of[s]
                    if idx >= len(pages):
                        pid = self._take_page(s)
                        jr.append(("take", pid))
                    else:
                        pid = pages[idx]
                        if self.kv_share and self._ref[pid] > 1:
                            new = self._cow_page(s, idx)
                            jr.append(("cow", idx, pid, new))
                            cow_pairs.append((pid, new))
                            pid = new
                    page_ids.append(pid)
                    offsets.append(pos % self.page_size)
        except OutOfPagesError:
            for s, jr in journal.items():
                self._undo(s, jr)
            raise
        self._apply_cow(cow_pairs)
        ks = self._store(k.reshape((-1,) + k.shape[-2:]), self.k_scale)
        vs = self._store(v.reshape((-1,) + v.shape[-2:]), self.v_scale)
        n_pad = int(ks.shape[0]) - len(slots) * r
        if n_pad:
            page_ids = page_ids + [self.sink_page] * n_pad
            offsets = offsets + [0] * n_pad
        pid_a = jnp.asarray(np.asarray(page_ids, np.int32))
        off_a = jnp.asarray(np.asarray(offsets, np.int32))
        self.k_pages = _scatter_token_jit(self.k_pages, pid_a, off_a,
                                          ks)
        self.v_pages = _scatter_token_jit(self.v_pages, pid_a, off_a,
                                          vs)
        for s in slots:
            self._lens[s] += r
        self._export_gauges()

    # -- reads --------------------------------------------------------------
    def seq_len(self, slot):
        return int(self._lens[slot])

    def tables_for(self, slots, max_pages=None, pad_to=None):
        """Device block-table view [N(_pad), max_pages] int32 for a
        batch of slots (padded COLUMNS point at valid page 0 — the
        kernel masks by length; ``pad_to`` adds dummy ROWS of zeros
        for fixed-batch-shape callers, masked the same way by their
        zero length)."""
        n = max_pages if max_pages is not None else max(
            1, max(self.pages_for(int(self._lens[s])) for s in slots))
        t = self._tables[np.asarray(slots), :n]
        if t.shape[1] < n:
            # a requested width past the stored table (a pow2 bucket
            # rounding above max_pages_per_seq) pads COLUMNS with
            # page 0 — masked by seq_len like every padded entry
            t = np.concatenate(
                [t, np.zeros((t.shape[0], n - t.shape[1]), np.int32)],
                axis=1)
        if pad_to is not None and pad_to > t.shape[0]:
            t = np.concatenate(
                [t, np.zeros((pad_to - t.shape[0], n), np.int32)])
        return jnp.asarray(t)

    def lens_for(self, slots, pad_to=None):
        """Device lengths [N(_pad)] int32 (dummy rows length 0 — the
        kernel emits zeros for them)."""
        ln = self._lens[np.asarray(slots)]
        if pad_to is not None and pad_to > ln.shape[0]:
            ln = np.concatenate(
                [ln, np.zeros((pad_to - ln.shape[0],), np.int32)])
        return jnp.asarray(ln)

    def kv_scales(self):
        """(k_scale, v_scale) per-channel [H, d] dequant scales (int8
        mode; None otherwise)."""
        return self.k_scale, self.v_scale

    # -- accounting ---------------------------------------------------------
    def _owned_count(self):
        """Cheap unique-owned count (free-list complement); the audit
        surface (in_use_pages / check_accounting) recomputes it
        independently from the tables."""
        return self.num_pages - len(self._free_pages)

    def _holder_page_lists(self):
        """Every holder's page list: live slots + in-transit handoff
        handles (a handle holds references exactly like a slot)."""
        return list(self._pages_of.values()) + \
            [e["pages"] for e in self._in_transit.values()]

    def in_use_pages(self):
        """UNIQUE pages owned by live sequences or in-transit handoffs
        (the generalized invariant counts each shared page once)."""
        return len({p for pages in self._holder_page_lists()
                    for p in pages})

    def shared_pages(self):
        """Pages held by more than one sequence (refcount > 1)."""
        return self._n_shared

    def free_pages(self):
        return len(self._free_pages)

    def _export_gauges(self):
        free = len(self._free_pages)
        _G_FREE.set(free, cache=self._label)
        _G_IN_USE.set(self.num_pages - free, cache=self._label)
        _G_SHARED.set(self._n_shared, cache=self._label)
        owned = self.num_pages - free
        live_tokens = int(sum(self._lens[s] for s in self._live))
        logical = sum(len(p) for p in self._pages_of.values())
        cap = logical * self.page_size
        _G_FRAG.set(
            round(100.0 * (cap - live_tokens) / cap, 2) if cap
            else 0.0, cache=self._label)
        _G_TRANSIT.set(self.in_transit_pages(), cache=self._label)
        del owned

    def stats(self):
        """Allocator + fragmentation stats (the chaos soak's audit
        surface).  ``accounted`` is the generalized leak invariant:
        every pool page is either free or held by >= 1 live sequence,
        each shared page counted ONCE, and every page's refcount
        equals the number of holding sequences."""
        owned = [p for pages in self._holder_page_lists()
                 for p in pages]
        cnt = Counter(owned)
        in_use = len(cnt)
        live_tokens = int(sum(self._lens[s] for s in self._live)) \
            + sum(e["length"] for e in self._in_transit.values())
        capacity = len(owned) * self.page_size
        ref_ok = all(int(self._ref[p]) == c for p, c in cnt.items()) \
            and int((self._ref > 0).sum()) == in_use
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "free_pages": self.free_pages(),
            "in_use_pages": in_use,
            "in_transit_pages": self.in_transit_pages(),
            "in_transit_handoffs": len(self._in_transit),
            "shared_pages": sum(1 for c in cnt.values() if c > 1),
            "logical_pages": len(owned),
            "peak_in_use_pages": self._peak_in_use,
            "peak_shared_pages": self._peak_shared,
            "live_seqs": len(self._live),
            "accounted": (self.free_pages() + in_use == self.num_pages
                          and ref_ok),
            # internal fragmentation: tail slack of the last page of
            # each live sequence (the only waste paging permits)
            "internal_frag_pct": round(
                100.0 * (capacity - live_tokens) / capacity, 2)
            if capacity else 0.0,
            "kv_int8": self.kv_int8,
            "kv_share": self.kv_share,
        }

    def check_accounting(self):
        """(ok, detail) — the generalized zero-leak invariant:
        free + unique(in_use) == num_pages, refcounts equal holder
        counts, no freed page still held, every radix page owned."""
        st = self.stats()
        if not st["accounted"]:
            return False, ("page accounting broken: free=%d in_use=%d "
                           "pool=%d refcounts_consistent=%s"
                           % (st["free_pages"], st["in_use_pages"],
                              st["num_pages"],
                              st["free_pages"] + st["in_use_pages"]
                              == st["num_pages"]))
        owned = {p for pages in self._holder_page_lists()
                 for p in pages}
        both = owned & set(self._free_pages)
        if both:
            return False, "pages both free and owned: %s" % sorted(both)
        dead_radix = set(self._radix_of_page) - owned
        if dead_radix:
            return False, ("radix tree holds dead pages: %s"
                           % sorted(dead_radix))
        return True, ""
