"""Vision op family: interpolation, 3-D conv/pool, samplers, and the
pixel-rearrangement zoo.

Reference parity (all paths under /root/reference/paddle/fluid/operators/):
  interpolate_op.cc/.h (bilinear_interp, nearest_interp — exact
  align_corners/align_mode arithmetic from interpolate_op.h:50-135),
  conv_op.cc (conv3d), conv_transpose_op.cc (conv3d_transpose,
  depthwise_conv2d_transpose), pool_op.cc (pool3d),
  pool_with_index_op.cc (max_pool2d/3d_with_index),
  grid_sampler_op.cc/.h, affine_grid_op.cc, affine_channel_op.cc,
  crop_op.cc, random_crop_op.cc, pad_constant_like_op.cc,
  pixel_shuffle_op.cc, shuffle_channel_op.cc, space_to_depth_op.cc,
  maxout_op.cc, unpool_op.cc, spp_op.cc, temporal_shift_op.cc,
  prelu_op.cc, unfold_op.cc, conv_shift_op.cc, row_conv_op.cc,
  fsp_op.cc, add_position_encoding_op.cc.

TPU-first notes: everything is expressed as gather/reduce_window/
conv_general_dilated so XLA can tile onto the MXU/VPU; index-typed
outputs (argmax pools) are flat int64 indices like the reference so
unpool can consume them.  No scalar loops; interpolation weights are
precomputed host-side numpy constants (static shapes) baked into the
trace, matching the reference's precomputed vy/vx tables.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import REQUIRED, register_op


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (list(v) * 3)[:3]) if len(v) < 3 \
            else tuple(int(x) for x in v[:3])
    return (int(v),) * 3


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1])) if len(v) >= 2 \
            else (int(v[0]),) * 2
    return (int(v),) * 2


# ---------------------------------------------------------------------------
# interpolation (interpolate_op.h)
# ---------------------------------------------------------------------------

def _interp_out_hw(in_h, in_w, attrs, ins):
    if ins.get("OutSize") is not None:
        # OutSize is a 2-element tensor; static shapes demand the attr
        # path under trace — the layer front-end resolves it, keeping
        # the op static (re-spec of the dynamic OutSize input).
        raise ValueError(
            "interp: dynamic OutSize tensor is not supported under XLA "
            "static shapes; pass out_h/out_w or scale attrs instead")
    scale = float(attrs.get("scale") or 0.0)
    if scale > 0:
        return int(in_h * scale), int(in_w * scale)
    return int(attrs["out_h"]), int(attrs["out_w"])


def _interp_ratio(in_sz, out_sz, align_corners):
    if out_sz <= 1:
        return 0.0
    if align_corners:
        return (in_sz - 1.0) / (out_sz - 1.0)
    return float(in_sz) / out_sz


def _bilinear_weights(in_sz, out_sz, align_corners, align_mode):
    """Exact reference arithmetic (interpolate_op.h:70-84): returns
    (lo_idx, hi_idx, d_lo, d_hi) numpy vectors of length out_sz."""
    ratio = _interp_ratio(in_sz, out_sz, align_corners)
    k = np.arange(out_sz)
    align_flag = (align_mode == 0 and not align_corners)
    if align_flag:
        lo = (ratio * (k + 0.5) - 0.5).astype(np.int64)
    else:
        lo = (ratio * k).astype(np.int64)
    lo = np.maximum(lo, 0)
    hi = np.minimum(lo + 1, in_sz - 1)
    idx_src = np.maximum(ratio * (k + 0.5) - 0.5, 0.0)
    d_lo = (idx_src - lo) if align_flag else (ratio * k - lo)
    d_hi = 1.0 - d_lo
    return lo, hi, d_lo.astype(np.float32), d_hi.astype(np.float32)


@register_op("bilinear_interp", inputs=("X", "OutSize"), outputs=("Out",),
             optional=("OutSize",),
             attrs={"out_h": -1, "out_w": -1, "scale": 0.0,
                    "interp_method": "bilinear", "align_corners": True,
                    "align_mode": 1})
def bilinear_interp(ins, attrs):
    x = ins["X"]
    n, c, in_h, in_w = x.shape
    out_h, out_w = _interp_out_hw(in_h, in_w, attrs, ins)
    ac, am = bool(attrs["align_corners"]), int(attrs["align_mode"])
    yn, ys, dn, ds = _bilinear_weights(in_h, out_h, ac, am)
    xw, xe, dw, de = _bilinear_weights(in_w, out_w, ac, am)
    rows_n = x[:, :, yn, :]                    # [N, C, OH, W]
    rows_s = x[:, :, ys, :]
    # interpolate along W for both row sets, then blend along H
    def wmix(rows):
        return (rows[:, :, :, xw] * de[None, None, None, :]
                + rows[:, :, :, xe] * dw[None, None, None, :])
    out = (wmix(rows_n) * ds[None, None, :, None]
           + wmix(rows_s) * dn[None, None, :, None])
    return {"Out": out.astype(x.dtype)}


@register_op("nearest_interp", inputs=("X", "OutSize"), outputs=("Out",),
             optional=("OutSize",),
             attrs={"out_h": -1, "out_w": -1, "scale": 0.0,
                    "interp_method": "nearest", "align_corners": True,
                    "align_mode": 1})
def nearest_interp(ins, attrs):
    """interpolate_op.h:29-47 NearestNeighborInterpolate."""
    x = ins["X"]
    n, c, in_h, in_w = x.shape
    out_h, out_w = _interp_out_hw(in_h, in_w, attrs, ins)
    ac = bool(attrs["align_corners"])
    rh = _interp_ratio(in_h, out_h, ac)
    rw = _interp_ratio(in_w, out_w, ac)
    k = np.arange(out_h)
    l = np.arange(out_w)
    iy = (rh * k + 0.5).astype(np.int64) if ac else (rh * k).astype(
        np.int64)
    ix = (rw * l + 0.5).astype(np.int64) if ac else (rw * l).astype(
        np.int64)
    iy = np.clip(iy, 0, in_h - 1)
    ix = np.clip(ix, 0, in_w - 1)
    return {"Out": x[:, :, iy, :][:, :, :, ix]}


# ---------------------------------------------------------------------------
# 3-D conv / pool family
# ---------------------------------------------------------------------------

@register_op("conv3d", inputs=("Input", "Filter"), outputs=("Output",),
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1,
                    "data_format": "NCDHW", "use_cudnn": True})
def conv3d(ins, attrs):
    """conv_op.cc Conv3DOpMaker."""
    x, w = ins["Input"], ins["Filter"]
    s, p, d = (_triple(attrs["strides"]), _triple(attrs["paddings"]),
               _triple(attrs["dilations"]))
    fmt = attrs.get("data_format", "NCDHW")
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (fmt, "OIDHW", fmt))
    out = lax.conv_general_dilated(
        x, w, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
        rhs_dilation=d, dimension_numbers=dn,
        feature_group_count=attrs["groups"])
    return {"Output": out}


@register_op("conv3d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1,
                    "output_size": [], "data_format": "NCDHW"})
def conv3d_transpose(ins, attrs):
    """conv_transpose_op.cc Conv3DTransposeOpMaker: fractionally-strided
    conv via lhs_dilation (XLA's native transposed-conv form)."""
    x, w = ins["Input"], ins["Filter"]  # w: [in, out/groups, kd, kh, kw]
    s, p = _triple(attrs["strides"]), _triple(attrs["paddings"])
    d = _triple(attrs["dilations"])
    ks = [(w.shape[i + 2] - 1) * d[i] + 1 for i in range(3)]
    pad = [(ks[i] - 1 - p[i], ks[i] - 1 - p[i]) for i in range(3)]
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCDHW", "IODHW", "NCDHW"))
    out = lax.conv_general_dilated(
        x, jnp.flip(w, axis=(2, 3, 4)), window_strides=(1, 1, 1),
        padding=pad, lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=dn, feature_group_count=attrs["groups"])
    return {"Output": out}


@register_op("depthwise_conv2d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "output_size": [], "data_format": "NCHW"})
def depthwise_conv2d_transpose(ins, attrs):
    x, w = ins["Input"], ins["Filter"]
    s, p = _pair(attrs["strides"]), _pair(attrs["paddings"])
    d = _pair(attrs["dilations"])
    groups = attrs["groups"] or x.shape[1]
    kh = (w.shape[2] - 1) * d[0] + 1
    kw = (w.shape[3] - 1) * d[1] + 1
    pad = [(kh - 1 - p[0], kh - 1 - p[0]),
           (kw - 1 - p[1], kw - 1 - p[1])]
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "IOHW", "NCHW"))
    out = lax.conv_general_dilated(
        x, jnp.flip(w, axis=(2, 3)), window_strides=(1, 1),
        padding=pad, lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=dn, feature_group_count=groups)
    return {"Output": out}


@register_op("pool3d", inputs=("X",), outputs=("Out",),
             attrs={"pooling_type": "max", "ksize": REQUIRED,
                    "global_pooling": False, "strides": [1, 1, 1],
                    "paddings": [0, 0, 0], "exclusive": True,
                    "adaptive": False, "ceil_mode": False,
                    "data_format": "NCDHW"})
def pool3d(ins, attrs):
    x = ins["X"]
    if attrs["global_pooling"]:
        k, s, p = x.shape[2:5], x.shape[2:5], (0, 0, 0)
    else:
        k = _triple(attrs["ksize"])
        s = _triple(attrs["strides"])
        p = _triple(attrs["paddings"])
    window = (1, 1) + tuple(k)
    strides = (1, 1) + tuple(s)
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if attrs["pooling_type"] == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                pads)
        return {"Out": out}
    out = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if attrs["exclusive"] and any(p):
        ones = jnp.ones(x.shape[2:5], x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, k, s,
                                tuple((pi, pi) for pi in p))
        out = out / cnt[None, None]
    else:
        out = out / float(np.prod(k))
    return {"Out": out}


def _max_pool_with_index(x, k, s, p, spatial_ndim):
    """reference pool_with_index_op: returns (max, flat int64 index into
    the flattened spatial dims of x).  The max comes from the ordinary
    (differentiable) reduce_window; the index from a variadic
    reduce_window under stop_gradient — its select-pair combinator has no
    transpose rule, so it must stay out of the autodiff graph."""
    window = (1, 1) + tuple(k)
    strides = (1, 1) + tuple(s)
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)

    def index_of_max(xs):
        spatial = xs.shape[2:]
        flat_idx = jnp.arange(int(np.prod(spatial)),
                              dtype=jnp.int64).reshape(spatial)
        idx = jnp.broadcast_to(flat_idx, xs.shape)

        def sel(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

        _, oidx = lax.reduce_window(
            (xs, idx), (jnp.asarray(-jnp.inf, xs.dtype),
                        jnp.asarray(-1, jnp.int64)),
            sel, window, strides, pads)
        return oidx

    oidx = index_of_max(lax.stop_gradient(x))
    return out, oidx


@register_op("max_pool2d_with_index", inputs=("X",),
             outputs=("Out", "Mask"),
             attrs={"ksize": REQUIRED, "global_pooling": False,
                    "strides": [1, 1], "paddings": [0, 0],
                    "adaptive": False})
def max_pool2d_with_index(ins, attrs):
    x = ins["X"]
    if attrs["global_pooling"]:
        k, s, p = x.shape[2:4], (1, 1), (0, 0)
    else:
        k, s, p = (_pair(attrs["ksize"]), _pair(attrs["strides"]),
                   _pair(attrs["paddings"]))
    out, mask = _max_pool_with_index(x, k, s, p, 2)
    return {"Out": out, "Mask": mask}


@register_op("max_pool3d_with_index", inputs=("X",),
             outputs=("Out", "Mask"),
             attrs={"ksize": REQUIRED, "global_pooling": False,
                    "strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "adaptive": False})
def max_pool3d_with_index(ins, attrs):
    x = ins["X"]
    if attrs["global_pooling"]:
        k, s, p = x.shape[2:5], (1, 1, 1), (0, 0, 0)
    else:
        k, s, p = (_triple(attrs["ksize"]), _triple(attrs["strides"]),
                   _triple(attrs["paddings"]))
    out, mask = _max_pool_with_index(x, k, s, p, 3)
    return {"Out": out, "Mask": mask}


@register_op("unpool", inputs=("X", "Indices"), outputs=("Out",),
             attrs={"ksize": REQUIRED, "strides": [1, 1],
                    "paddings": [0, 0], "unpooling_type": "max"})
def unpool(ins, attrs):
    """unpool_op.cc: scatter pooled values back to the argmax positions
    recorded by max_pool2d_with_index."""
    x, idx = ins["X"], ins["Indices"]
    n, c, h, w = x.shape
    k, s, p = (_pair(attrs["ksize"]), _pair(attrs["strides"]),
               _pair(attrs["paddings"]))
    oh = (h - 1) * s[0] - 2 * p[0] + k[0]
    ow = (w - 1) * s[1] - 2 * p[1] + k[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(x.reshape(n, c, -1), mode="drop")
    return {"Out": out.reshape(n, c, oh, ow)}


@register_op("spp", inputs=("X",), outputs=("Out",),
             attrs={"pyramid_height": REQUIRED, "pooling_type": "max"})
def spp(ins, attrs):
    """spp_op.cc spatial pyramid pooling: levels l=0..H-1 pool to
    2^l x 2^l bins (kernel=ceil(in/bins), pad so bins*kernel >= in),
    flattened and concatenated along channels."""
    x = ins["X"]
    n, c, h, w = x.shape
    outs = []
    for lvl in range(int(attrs["pyramid_height"])):
        bins = 2 ** lvl
        kh, kw = -(-h // bins), -(-w // bins)
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        window, strides = (1, 1, kh, kw), (1, 1, kh, kw)
        pads = ((0, 0), (0, 0), (ph, kh * bins - h - ph),
                (pw, kw * bins - w - pw))
        if attrs["pooling_type"] == "max":
            o = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                  pads)
        else:
            o = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                  pads) / (kh * kw)
        outs.append(o.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


# ---------------------------------------------------------------------------
# samplers / affine
# ---------------------------------------------------------------------------

@register_op("affine_grid", inputs=("Theta", "OutputShape"),
             outputs=("Output",), optional=("OutputShape",),
             attrs={"use_cudnn": True, "output_shape": []})
def affine_grid(ins, attrs):
    """affine_grid_op.cc: Theta [N,2,3] -> sampling grid [N,H,W,2] over
    the normalized [-1,1] mesh (align_corners=True semantics)."""
    theta = ins["Theta"]
    shape = [int(v) for v in attrs["output_shape"]]
    if len(shape) != 4:
        raise ValueError("affine_grid: output_shape attr [N,C,H,W] "
                         "required (static shapes)")
    n, _, h, w = shape
    ys = np.linspace(-1.0, 1.0, h, dtype=np.float32)
    xs = np.linspace(-1.0, 1.0, w, dtype=np.float32)
    gx, gy = np.meshgrid(xs, ys)              # [H, W]
    base = jnp.asarray(
        np.stack([gx, gy, np.ones_like(gx)], axis=-1))  # [H, W, 3]
    out = jnp.einsum("hwk,njk->nhwj", base, theta)
    return {"Output": out}


@register_op("grid_sampler", inputs=("X", "Grid"), outputs=("Output",),
             attrs={"use_cudnn": True})
def grid_sampler(ins, attrs):
    """grid_sampler_op.h: bilinear sample of X [N,C,H,W] at Grid
    [N,H,W,2] normalized coords; x=(gx+1)*(W-1)/2 (align-corners),
    zero padding outside."""
    x, grid = ins["X"], ins["Grid"]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * 0.5 * (w - 1)   # [N, Hg, Wg]
    gy = (grid[..., 1] + 1.0) * 0.5 * (h - 1)
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    dx, dy = gx - x0, gy - y0

    def gather(yy, xx):
        yi = yy.astype(jnp.int32)
        xi = xx.astype(jnp.int32)
        valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
        yc = jnp.clip(yi, 0, h - 1)
        xc = jnp.clip(xi, 0, w - 1)
        vals = x[jnp.arange(n)[:, None, None, None],
                 jnp.arange(c)[None, :, None, None],
                 yc[:, None], xc[:, None]]       # [N, C, Hg, Wg]
        return vals * valid[:, None].astype(x.dtype)

    out = (gather(y0, x0) * ((1 - dy) * (1 - dx))[:, None]
           + gather(y0, x0 + 1) * ((1 - dy) * dx)[:, None]
           + gather(y0 + 1, x0) * (dy * (1 - dx))[:, None]
           + gather(y0 + 1, x0 + 1) * (dy * dx)[:, None])
    return {"Output": out}


@register_op("affine_channel", inputs=("X", "Scale", "Bias"),
             outputs=("Out",),
             attrs={"data_layout": "NCHW"})
def affine_channel(ins, attrs):
    x, scale, bias = ins["X"], ins["Scale"], ins["Bias"]
    if attrs["data_layout"] == "NHWC":
        return {"Out": x * scale + bias}
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return {"Out": x * scale.reshape(shape) + bias.reshape(shape)}


# ---------------------------------------------------------------------------
# crop / pad
# ---------------------------------------------------------------------------

@register_op("crop", inputs=("X", "Y", "Offsets"), outputs=("Out",),
             optional=("Y", "Offsets"),
             attrs={"offsets": [], "shape": []})
def crop(ins, attrs):
    """crop_op.cc: static offsets/shape attrs (the Offsets tensor input
    is resolved by the layer; XLA needs static slices)."""
    x = ins["X"]
    shape = [int(v) for v in attrs["shape"]] or \
        (list(ins["Y"].shape) if ins.get("Y") is not None else None)
    if shape is None:
        raise ValueError("crop: need shape attr or Y input")
    offsets = [int(v) for v in (attrs["offsets"] or [0] * x.ndim)]
    return {"Out": lax.slice(
        x, offsets, [o + s for o, s in zip(offsets, shape)])}


@register_op("random_crop", inputs=("X", "Seed"),
             outputs=("Out", "SeedOut"), optional=("Seed",),
             differentiable=False,
             attrs={"shape": REQUIRED, "startup_seed": 0})
def random_crop(ins, attrs):
    """random_crop_op.cc: uniform random offsets in the trailing dims
    matching len(shape); the evolving Seed tensor is threaded through
    like the reference's SeedOut."""
    x = ins["X"]
    crop_shape = [int(v) for v in attrs["shape"]]
    seed = ins.get("Seed")
    if seed is None:
        seed = jnp.asarray([attrs["startup_seed"]], jnp.int64)
    from paddle_tpu.ops.rng import fold_seed_offset

    key = fold_seed_offset(jax.random.PRNGKey(0), seed)
    k = len(crop_shape)
    lead = x.ndim - k
    maxs = np.array([x.shape[lead + i] - crop_shape[i]
                     for i in range(k)], np.int32)
    offs = jax.random.randint(key, (k,), 0, jnp.asarray(maxs) + 1)
    starts = jnp.concatenate(
        [jnp.zeros((lead,), jnp.int32), offs.astype(jnp.int32)])
    out = lax.dynamic_slice(x, list(starts),
                            list(x.shape[:lead]) + crop_shape)
    # 32-bit LCG step (minstd) — int64 literals overflow when jax
    # runs with x64 disabled
    new_seed = (seed * 48271 + 1) % 2147483647
    return {"Out": out, "SeedOut": new_seed}


@register_op("pad_constant_like", inputs=("X", "Y"), outputs=("Out",),
             attrs={"pad_value": 0.0})
def pad_constant_like(ins, attrs):
    """pad_constant_like_op.cc: pad Y up to X's shape with pad_value."""
    x, y = ins["X"], ins["Y"]
    pads = [(0, x.shape[i] - y.shape[i]) for i in range(y.ndim)]
    return {"Out": jnp.pad(y, pads,
                           constant_values=attrs["pad_value"])}


# ---------------------------------------------------------------------------
# pixel rearrangement zoo
# ---------------------------------------------------------------------------

@register_op("pixel_shuffle", inputs=("X",), outputs=("Out",),
             attrs={"upscale_factor": REQUIRED})
def pixel_shuffle(ins, attrs):
    x = ins["X"]
    r = int(attrs["upscale_factor"])
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3)
    return {"Out": out.reshape(n, c // (r * r), h * r, w * r)}


@register_op("shuffle_channel", inputs=("X",), outputs=("Out",),
             attrs={"group": 1})
def shuffle_channel(ins, attrs):
    x = ins["X"]
    g = int(attrs["group"])
    n, c, h, w = x.shape
    return {"Out": x.reshape(n, g, c // g, h, w).transpose(
        0, 2, 1, 3, 4).reshape(n, c, h, w)}


@register_op("space_to_depth", inputs=("X",), outputs=("Out",),
             attrs={"blocksize": REQUIRED})
def space_to_depth(ins, attrs):
    """space_to_depth_op.cc (blocksize b): [N,C,H,W] ->
    [N,C*b*b,H/b,W/b]."""
    x = ins["X"]
    b = int(attrs["blocksize"])
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // b, b, w // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": out.reshape(n, c * b * b, h // b, w // b)}


@register_op("maxout", inputs=("X",), outputs=("Out",),
             attrs={"groups": REQUIRED})
def maxout(ins, attrs):
    """maxout_op.cc: out channels = C/groups; max over each group of
    `groups` consecutive channels."""
    x = ins["X"]
    g = int(attrs["groups"])
    n, c = x.shape[:2]
    rest = x.shape[2:]
    return {"Out": jnp.max(x.reshape((n, c // g, g) + rest), axis=2)}


@register_op("temporal_shift", inputs=("X",), outputs=("Out",),
             attrs={"seg_num": REQUIRED, "shift_ratio": 0.25})
def temporal_shift(ins, attrs):
    """temporal_shift_op.cc: within each segment of T frames, shift the
    first C*ratio channels back one frame, the next C*ratio forward."""
    x = ins["X"]
    t = int(attrs["seg_num"])
    ratio = float(attrs["shift_ratio"])
    nt, c, h, w = x.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    v = x.reshape(n, t, c, h, w)
    pad_past = jnp.concatenate(
        [v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
    pad_future = jnp.concatenate(
        [jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([pad_past, pad_future, v[:, :, c2:]], axis=2)
    return {"Out": out.reshape(nt, c, h, w)}


# ---------------------------------------------------------------------------
# misc nets
# ---------------------------------------------------------------------------

@register_op("prelu", inputs=("X", "Alpha"), outputs=("Out",),
             attrs={"mode": "all"})
def prelu(ins, attrs):
    """prelu_op.cc modes: all (one alpha), channel (per C), element."""
    x, alpha = ins["X"], ins["Alpha"]
    mode = attrs["mode"]
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    else:
        alpha = alpha.reshape(())
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register_op("unfold", inputs=("X",), outputs=("Y",),
             attrs={"kernel_sizes": REQUIRED, "strides": [1, 1],
                    "paddings": [0, 0, 0, 0], "dilations": [1, 1]})
def unfold(ins, attrs):
    """unfold_op.cc (im2col): [N,C,H,W] -> [N, C*kh*kw, L]."""
    x = ins["X"]
    kh, kw = _pair(attrs["kernel_sizes"])
    sh, sw = _pair(attrs["strides"])
    d = _pair(attrs["dilations"])
    p = [int(v) for v in attrs["paddings"]]
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
    eh = (h + p[0] + p[2] - (d[0] * (kh - 1) + 1)) // sh + 1
    ew = (w + p[1] + p[3] - (d[1] * (kw - 1) + 1)) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = lax.slice(
                xp, (0, 0, i * d[0], j * d[1]),
                (n, c, i * d[0] + (eh - 1) * sh + 1,
                 j * d[1] + (ew - 1) * sw + 1),
                (1, 1, sh, sw))
            cols.append(patch)
    out = jnp.stack(cols, axis=2)            # [N, C, kh*kw, eh, ew]
    return {"Y": out.reshape(n, c * kh * kw, eh * ew)}


@register_op("conv_shift", inputs=("X", "Y"), outputs=("Out",))
def conv_shift(ins, attrs):
    """conv_shift_op.cc circular convolution: X [B,M], Y [B,N] (N odd,
    N <= M): out[i] = sum_j X[(i+j-N/2) mod M] * Y[j]."""
    x, y = ins["X"], ins["Y"]
    b, m = x.shape
    nsz = y.shape[1]
    half = nsz // 2
    shifts = np.arange(nsz) - half
    idx = (np.arange(m)[None, :] + shifts[:, None]) % m   # [N, M]
    gathered = x[:, idx]                                  # [B, N, M]
    return {"Out": jnp.einsum("bnm,bn->bm", gathered, y)}


@register_op("row_conv", inputs=("X", "Filter"), outputs=("Out",))
def row_conv(ins, attrs):
    """row_conv_op.cc lookahead conv: X [N,T,D] (batched re-spec of the
    LoD form), Filter [future_context, D]:
    out[t] = sum_{j=0..fc-1} x[t+j] * filter[j]."""
    x, f = ins["X"], ins["Filter"]
    fc = f.shape[0]
    n, t, ddim = x.shape
    xp = jnp.pad(x, ((0, 0), (0, fc - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for j in range(fc):
        out = out + xp[:, j:j + t, :] * f[j][None, None, :]
    return {"Out": out}


@register_op("fsp", inputs=("X", "Y"), outputs=("Out",))
def fsp(ins, attrs):
    """fsp_op.cc (flow of solution procedure, distillation): X
    [N,C1,H,W], Y [N,C2,H,W] -> [N,C1,C2] = x.y^T / (H*W)."""
    x, y = ins["X"], ins["Y"]
    h, w = x.shape[2], x.shape[3]
    return {"Out": jnp.einsum("nahw,nbhw->nab", x, y) / (h * w)}


@register_op("add_position_encoding", inputs=("X",), outputs=("Out",),
             attrs={"alpha": 1.0, "beta": 1.0})
def add_position_encoding(ins, attrs):
    """add_position_encoding_op.cc: out = alpha*x + beta*sinusoid
    (transformer PE over [N,T,D])."""
    x = ins["X"]
    n, t, dim = x.shape
    half = dim // 2
    pos = np.arange(t, dtype=np.float32)[:, None]
    div = np.power(10000.0, np.arange(half, dtype=np.float32) / half)
    pe = np.zeros((t, dim), np.float32)
    pe[:, :half] = np.sin(pos / div)
    pe[:, half:2 * half] = np.cos(pos / div)
    return {"Out": attrs["alpha"] * x
            + attrs["beta"] * jnp.asarray(pe)[None]}


@register_op("polygon_box_transform", inputs=("Input",),
             outputs=("Output",), differentiable=False)
def polygon_box_transform(ins, attrs):
    """polygon_box_transform_op.cc (EAST OCR): even channels hold x
    offsets, odd channels y offsets; out = 4*grid_coord - in."""
    x = ins["Input"]
    n, c, h, w = x.shape
    gx = np.broadcast_to(np.arange(w, dtype=np.float32), (h, w))
    gy = np.broadcast_to(np.arange(h, dtype=np.float32)[:, None], (h, w))
    grid = np.zeros((c, h, w), np.float32)
    grid[0::2] = gx
    grid[1::2] = gy
    return {"Output": 4.0 * jnp.asarray(grid)[None] - x}


@register_op("similarity_focus", inputs=("X",), outputs=("Out",),
             differentiable=False,
             attrs={"axis": REQUIRED, "indexes": REQUIRED})
def similarity_focus(ins, attrs):
    """similarity_focus_op.cc: for each selected index along `axis`,
    greedily mark (row, col) argmax cells; output is a 0/1 mask
    broadcast over channels.  Re-specified TPU-statically: the mask
    marks, per selected slice, every cell that is the max of BOTH its
    row and its column (the fixed point of the reference's greedy
    selection for distinct values)."""
    x = ins["X"]
    axis = int(attrs["axis"])
    indexes = [int(i) for i in attrs["indexes"]]
    n = x.shape[0]
    mask = jnp.zeros_like(x, dtype=x.dtype)
    for idx in indexes:
        sl = jnp.take(x, idx, axis=axis)      # [N, d1, d2]
        row_max = sl == sl.max(axis=2, keepdims=True)
        col_max = sl == sl.max(axis=1, keepdims=True)
        m = (row_max | col_max).astype(x.dtype)  # [N, d1, d2]
        mask = jnp.maximum(mask, jnp.expand_dims(m, axis))
    return {"Out": mask}


@register_op("deformable_conv",
             inputs=("Input", "Offset", "Mask", "Filter"),
             outputs=("Output",), optional=("Mask",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "deformable_groups": 1, "im2col_step": 64})
def deformable_conv(ins, attrs):
    """deformable_conv_op.cc (v2 when Mask given, v1 otherwise):
    bilinear-sample the input at kernel positions shifted by learned
    offsets, then convolve.  Input [N,C,H,W]; Offset
    [N, 2*dg*kh*kw, Ho, Wo]; Mask [N, dg*kh*kw, Ho, Wo];
    Filter [O, C/groups, kh, kw].  Implemented as deformed im2col
    (gather + bilinear weights, all differentiable) followed by a
    grouped matmul — the MXU-friendly formulation."""
    x, off, w = ins["Input"], ins["Offset"], ins["Filter"]
    mask = ins.get("Mask")
    n, c, h, wd = x.shape
    o, cg, kh, kw = w.shape
    sh, sw = _pair(attrs["strides"])
    ph, pw = _pair(attrs["paddings"])
    dh, dw = _pair(attrs["dilations"])
    dg = int(attrs["deformable_groups"])
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (wd + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    # base sampling grid: for each (ky,kx,ho,wo) the ungated position
    ys = (jnp.arange(ho) * sh - ph)[:, None, None, None] + \
        (jnp.arange(kh) * dh)[None, None, :, None]        # [ho,1,kh,1]
    xs = (jnp.arange(wo) * sw - pw)[None, :, None, None] + \
        (jnp.arange(kw) * dw)[None, None, None, :]        # [1,wo,1,kw]
    ys = jnp.broadcast_to(ys, (ho, wo, kh, kw)).astype(x.dtype)
    xs = jnp.broadcast_to(xs, (ho, wo, kh, kw)).astype(x.dtype)

    off = off.reshape(n, dg, kh, kw, 2, ho, wo)
    oy = jnp.transpose(off[:, :, :, :, 0], (0, 1, 4, 5, 2, 3))
    ox = jnp.transpose(off[:, :, :, :, 1], (0, 1, 4, 5, 2, 3))
    py = ys[None, None] + oy                              # [n,dg,ho,wo,kh,kw]
    px = xs[None, None] + ox
    if mask is not None:
        mm = mask.reshape(n, dg, kh, kw, ho, wo)
        mm = jnp.transpose(mm, (0, 1, 4, 5, 2, 3))
    else:
        mm = jnp.ones_like(py)

    def bil(img, yy, xx):
        """img [cper,H,W]; yy/xx [...]; bilinear with zero padding."""
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = yy - y0
        wx = xx - x0
        vals = 0.0
        for (yo, wyy) in ((y0, 1 - wy), (y0 + 1, wy)):
            for (xo, wxx) in ((x0, 1 - wx), (x0 + 1, wx)):
                inb = (yo >= 0) & (yo < h) & (xo >= 0) & (xo < wd)
                yi = jnp.clip(yo, 0, h - 1).astype(jnp.int32)
                xi = jnp.clip(xo, 0, wd - 1).astype(jnp.int32)
                v = img[:, yi, xi]                        # [cper, ...]
                vals = vals + v * (wyy * wxx * inb)[None]
        return vals

    cper = c // dg

    def per_image(xi, pyi, pxi, mi):
        # per deformable group sample its channels
        def per_group(img_g, py_g, px_g, m_g):
            s = bil(img_g, py_g, px_g)                    # [cper,ho,wo,kh,kw]
            return s * m_g[None]
        xg = xi.reshape(dg, cper, h, wd)
        cols = jax.vmap(per_group)(xg, pyi, pxi, mi)      # [dg,cper,...]
        return cols.reshape(c, ho, wo, kh, kw)

    cols = jax.vmap(per_image)(x, py, px, mm)             # [n,c,ho,wo,kh,kw]
    g = int(attrs["groups"])
    cols = cols.reshape(n, g, cg, ho, wo, kh, kw)
    wg = w.reshape(g, o // g, cg, kh, kw)
    out = jnp.einsum("ngchwyx,gocyx->ngohw", cols, wg)
    return {"Output": out.reshape(n, o, ho, wo)}


@register_op("psroi_pool", inputs=("X", "ROIs"), outputs=("Out",),
             attrs={"output_channels": REQUIRED, "spatial_scale": 1.0,
                    "pooled_height": REQUIRED, "pooled_width": REQUIRED})
def psroi_pool(ins, attrs):
    """psroi_pool_op.cc (R-FCN position-sensitive ROI pooling): input
    channels are output_channels * ph * pw; bin (i,j) of output channel
    k average-pools input channel k*ph*pw + i*pw + j over the bin.
    ROIs re-spec: [R, 5] (batch_idx, x1, y1, x2, y2)."""
    x, rois = ins["X"], ins["ROIs"]
    oc = int(attrs["output_channels"])
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = attrs["spatial_scale"]
    n, c, h, w = x.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * scale
        y1 = roi[2] * scale
        x2 = roi[3] * scale
        y2 = roi[4] * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ph, rw / pw
        img = x[b].reshape(oc, ph, pw, h, w)

        iy = jnp.arange(h)
        ix = jnp.arange(w)

        def bin_val(k, i, j):
            ys0 = y1 + i * bh
            ys1 = y1 + (i + 1) * bh
            xs0 = x1 + j * bw
            xs1 = x1 + (j + 1) * bw
            my = (iy >= jnp.floor(ys0)) & (iy < jnp.ceil(ys1))
            mx = (ix >= jnp.floor(xs0)) & (ix < jnp.ceil(xs1))
            m = my[:, None] & mx[None, :]
            cnt = jnp.maximum(m.sum(), 1)
            return jnp.sum(img[k, i, j] * m) / cnt

        ks, is_, js = jnp.meshgrid(jnp.arange(oc), jnp.arange(ph),
                                   jnp.arange(pw), indexing="ij")
        vals = jax.vmap(bin_val)(ks.reshape(-1), is_.reshape(-1),
                                 js.reshape(-1))
        return vals.reshape(oc, ph, pw)

    return {"Out": jax.vmap(one)(rois)}


@register_op("tree_conv", inputs=("NodesVector", "EdgeSet", "Filter"),
             outputs=("Out",),
             attrs={"max_depth": 2})
def tree_conv(ins, attrs):
    """tree_conv_op.cc (TBCNN tree-based convolution) re-spec: nodes
    [N, M, F], edges [N, E, 2] (parent, child; 0-padded), filter
    [F, 3, out] or [F, 3, out, num_filters] (reference shape).  Each
    node aggregates its depth<=max_depth descendants with the TBCNN
    eta_t/eta_l/eta_r position coefficients; padding nodes (no edges)
    contribute zero.  No activation (the layer applies act, like the
    reference)."""
    nodes, edges, w = ins["NodesVector"], ins["EdgeSet"], ins["Filter"]
    n, m, f = nodes.shape
    depth = int(attrs["max_depth"])

    def per_tree(nv, es):
        parent = es[:, 0].astype(jnp.int32)
        child = es[:, 1].astype(jnp.int32)
        valid = (parent != child)
        adj = jnp.zeros((m, m), nodes.dtype)
        adj = adj.at[parent, child].add(
            jnp.where(valid, 1.0, 0.0))
        # reachability within `depth` hops (incl. self at depth 0)
        reach = jnp.eye(m, dtype=nodes.dtype)
        hop = jnp.eye(m, dtype=nodes.dtype)
        depths = jnp.zeros((m, m), nodes.dtype)
        for d in range(1, depth):
            hop = jnp.minimum(hop @ adj, 1.0)
            depths = depths + hop * d * (depths == 0) * \
                (1 - jnp.eye(m, dtype=nodes.dtype))
            reach = jnp.minimum(reach + hop, 1.0)
        # eta coefficients (TBCNN): top by depth, left/right by sibling
        # position approximated by node index order among descendants
        eta_t = jnp.where(reach > 0, (depth - 1 - depths) /
                          max(depth - 1, 1), 0.0)
        pos = jnp.broadcast_to(
            jnp.arange(m, dtype=nodes.dtype)[None, :], (m, m))
        denom = jnp.maximum(reach.sum(1, keepdims=True) - 1.0, 1.0)
        rank = (pos - jnp.arange(m, dtype=nodes.dtype)[:, None])
        eta_r = jnp.where(reach > 0, (1 - eta_t) *
                          jnp.clip(rank / denom, 0.0, 1.0), 0.0)
        eta_l = jnp.where(reach > 0, (1 - eta_t) * (1 - jnp.clip(
            rank / denom, 0.0, 1.0)), 0.0)
        agg_t = eta_t @ nv
        agg_l = eta_l @ nv
        agg_r = eta_r @ nv
        if w.ndim == 4:  # [F, 3, out, num_filters]
            return (jnp.einsum("mf,fon->mon", agg_t, w[:, 0])
                    + jnp.einsum("mf,fon->mon", agg_l, w[:, 1])
                    + jnp.einsum("mf,fon->mon", agg_r, w[:, 2]))
        return (agg_t @ w[:, 0] + agg_l @ w[:, 1] + agg_r @ w[:, 2])

    return {"Out": jax.vmap(per_tree)(nodes, edges)}


@register_op("deformable_psroi_pooling",
             inputs=("Input", "ROIs", "Trans"),
             outputs=("Output", "TopCount"),
             optional=("Trans",),
             attrs={"output_dim": REQUIRED, "spatial_scale": 1.0,
                    "pooled_height": REQUIRED, "pooled_width": REQUIRED,
                    "group_size": [1, 1], "part_size": [0, 0],
                    "sample_per_part": 4, "trans_std": 0.1,
                    "no_trans": False})
def deformable_psroi_pooling(ins, attrs):
    """deformable_psroi_pooling_op.cc (Deformable R-FCN): psroi pooling
    with learned per-bin offsets (Trans [R, 2, ph, pw] scaled by
    trans_std), bilinear sampling inside each shifted bin."""
    x, rois = ins["Input"], ins["ROIs"]
    trans = ins.get("Trans")
    oc = int(attrs["output_dim"])
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    scale = attrs["spatial_scale"]
    spp = int(attrs["sample_per_part"])
    tstd = attrs["trans_std"]
    n, cin, h, w = x.shape

    def one(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1, y1 = roi[1] * scale - 0.5, roi[2] * scale - 0.5
        x2, y2 = roi[3] * scale + 0.5, roi[4] * scale + 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ph, rw / pw
        img = x[b].reshape(oc, ph, pw, h, w)

        def bin_val(k, i, j):
            off_y = tr[0, i, j] * tstd * rh if tr is not None else 0.0
            off_x = tr[1, i, j] * tstd * rw if tr is not None else 0.0
            ys = y1 + i * bh + off_y + (jnp.arange(spp) + 0.5) / spp * bh
            xs = x1 + j * bw + off_x + (jnp.arange(spp) + 0.5) / spp * bw
            yy = jnp.clip(ys, 0, h - 1.001)
            xx = jnp.clip(xs, 0, w - 1.001)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            fy = yy - y0
            fx = xx - x0
            plane = img[k, i, j]
            vals = 0.0
            for dy, wy in ((0, 1 - fy), (1, fy)):
                for dx, wx in ((0, 1 - fx), (1, fx)):
                    v = plane[jnp.clip(y0 + dy, 0, h - 1)[:, None],
                              jnp.clip(x0 + dx, 0, w - 1)[None, :]]
                    vals = vals + v * wy[:, None] * wx[None, :]
            return jnp.mean(vals)

        ks, is_, js = jnp.meshgrid(jnp.arange(oc), jnp.arange(ph),
                                   jnp.arange(pw), indexing="ij")
        vals = jax.vmap(bin_val)(ks.reshape(-1), is_.reshape(-1),
                                 js.reshape(-1))
        return vals.reshape(oc, ph, pw)

    if trans is None:
        out = jax.vmap(lambda r: one(r, None))(rois)
    else:
        out = jax.vmap(one)(rois, trans)
    cnt = jnp.full((rois.shape[0], oc, ph, pw), float(spp * spp))
    return {"Output": out, "TopCount": cnt}


@register_op("roi_perspective_transform",
             inputs=("X", "ROIs"),
             outputs=("Out", "Mask", "TransformMatrix"),
             attrs={"transformed_height": REQUIRED,
                    "transformed_width": REQUIRED,
                    "spatial_scale": 1.0},
             differentiable=False)
def roi_perspective_transform(ins, attrs):
    """roi_perspective_transform_op.cc (OCR east-detection): each ROI
    is a quadrilateral [R, 9] (batch_idx + 4 corner points); warp it to
    a transformed_height x transformed_width rectangle via the
    homography through the 4 point pairs, bilinear-sampled."""
    x, rois = ins["X"], ins["ROIs"]
    th = int(attrs["transformed_height"])
    tw = int(attrs["transformed_width"])
    scale = attrs["spatial_scale"]
    n, c, h, w = x.shape

    def homography(src, dst):
        """src/dst [4,2]: solve 8x8 for the projective transform."""
        rows = []
        rhs = []
        for (sx, sy), (dx, dy) in zip(src, dst):
            rows.append([sx, sy, 1, 0, 0, 0, -dx * sx, -dx * sy])
            rows.append([0, 0, 0, sx, sy, 1, -dy * sx, -dy * sy])
            rhs.extend([dx, dy])
        A = jnp.asarray(rows)
        bv = jnp.asarray(rhs)
        sol = jnp.linalg.solve(A, bv)
        return jnp.concatenate([sol, jnp.ones((1,))]).reshape(3, 3)

    ys, xs = jnp.meshgrid(jnp.arange(th), jnp.arange(tw), indexing="ij")
    grid = jnp.stack([xs.reshape(-1), ys.reshape(-1),
                      jnp.ones(th * tw)], axis=0)          # [3, th*tw]

    def one(roi):
        b = roi[0].astype(jnp.int32)
        quad = (roi[1:9] * scale).reshape(4, 2)
        dst = jnp.asarray([[0.0, 0.0], [tw - 1.0, 0.0],
                           [tw - 1.0, th - 1.0], [0.0, th - 1.0]])
        # transform maps OUTPUT rect -> INPUT quad
        m = homography(dst, quad)
        p = m @ grid
        px = p[0] / p[2]
        py = p[1] / p[2]
        inb = (px >= 0) & (px <= w - 1) & (py >= 0) & (py <= h - 1)
        x0 = jnp.clip(jnp.floor(px), 0, w - 1).astype(jnp.int32)
        y0 = jnp.clip(jnp.floor(py), 0, h - 1).astype(jnp.int32)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        fx = px - x0
        fy = py - y0
        img = x[b]                                         # [C,H,W]
        v = (img[:, y0, x0] * (1 - fy) * (1 - fx)
             + img[:, y0, x1] * (1 - fy) * fx
             + img[:, y1, x0] * fy * (1 - fx)
             + img[:, y1, x1] * fy * fx)                   # [C, th*tw]
        v = jnp.where(inb[None], v, 0.0)
        return v.reshape(c, th, tw), inb.reshape(th, tw), m.reshape(9)

    outs, masks, mats = jax.vmap(one)(rois)
    return {"Out": outs, "Mask": masks.astype(jnp.int32),
            "TransformMatrix": mats}
