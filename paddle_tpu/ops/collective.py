"""Collective ops (`c_*` family).

Reference parity: /root/reference/paddle/fluid/operators/collective/
  c_allreduce_op.h (sum/max/min/prod), c_allgather_op.cc,
  c_reducescatter_op.cc, c_broadcast_op.cc, c_comm_init_op.cc,
  c_gen_nccl_id_op.cc; plus platform/nccl_helper.h NCCLContextMap.

TPU-first difference: these lower to XLA collectives (lax.psum etc.) that
ride the ICI mesh when the op runs inside shard_map/pjit with a bound mesh
axis; there is no NCCL communicator bootstrap (c_comm_init / gen_nccl_id
become no-ops — the JAX distributed runtime owns device bootstrap).  The
`ring_id` attr maps to a mesh axis name via the parallel env
(paddle_tpu/parallel/env.py ring registry).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op


def _axis_for_ring(ring_id):
    from paddle_tpu.parallel import env

    return env.ring_axis(ring_id)


def _in_spmd_context(axis):
    try:
        lax.axis_index(axis)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _register_allreduce(name, op):
    @register_op(name, inputs=("X",), outputs=("Out",),
                 attrs={"ring_id": 0, "use_calc_stream": True},
                 differentiable=False, in_place={"Out": "X"})
    def _fn(ins, attrs, op=op):
        axis = _axis_for_ring(attrs["ring_id"])
        if axis is None or not _in_spmd_context(axis):
            return {"Out": ins["X"]}  # single-participant ring
        if op == "sum":
            return {"Out": lax.psum(ins["X"], axis)}
        if op == "max":
            return {"Out": lax.pmax(ins["X"], axis)}
        if op == "min":
            return {"Out": lax.pmin(ins["X"], axis)}
        if op == "prod":
            # exact for negatives/zeros (log-psum NaNs on them):
            # one all_gather then a local product
            return {"Out": jnp.prod(lax.all_gather(ins["X"], axis),
                                    axis=0)}
    return _fn


_register_allreduce("c_allreduce_sum", "sum")
_register_allreduce("c_allreduce_max", "max")
_register_allreduce("c_allreduce_min", "min")
_register_allreduce("c_allreduce_prod", "prod")


@register_op("c_allgather", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1}, differentiable=False)
def c_allgather(ins, attrs):
    axis = _axis_for_ring(attrs["ring_id"])
    if axis is None or not _in_spmd_context(axis):
        return {"Out": ins["X"]}
    return {"Out": lax.all_gather(ins["X"], axis, tiled=True)}


@register_op("c_reducescatter", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "nranks": 1}, differentiable=False)
def c_reducescatter(ins, attrs):
    axis = _axis_for_ring(attrs["ring_id"])
    if axis is None or not _in_spmd_context(axis):
        return {"Out": ins["X"]}
    return {"Out": lax.psum_scatter(ins["X"], axis, tiled=True)}


@register_op("c_broadcast", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "root": 0}, differentiable=False)
def c_broadcast(ins, attrs):
    axis = _axis_for_ring(attrs["ring_id"])
    if axis is None or not _in_spmd_context(axis):
        return {"Out": ins["X"]}
    x = ins["X"]
    idx = lax.axis_index(axis)
    src = jnp.where(idx == attrs["root"], x, jnp.zeros_like(x))
    return {"Out": lax.psum(src, axis)}


@register_op("c_sync_calc_stream", inputs=("X",), outputs=("Out",),
             differentiable=False)
def c_sync_calc_stream(ins, attrs):
    return {"Out": ins["X"]}  # XLA programs are ordered; no stream sync


@register_op("c_sync_comm_stream", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0}, differentiable=False)
def c_sync_comm_stream(ins, attrs):
    return {"Out": ins["X"]}


@register_op("c_comm_init", inputs=(), outputs=(),
             attrs={"ring_id": 0, "nranks": 1, "rank": 0, "device_id": 0},
             differentiable=False, host_only=True)
def c_comm_init(ins, attrs):
    return {}


@register_op("c_gen_nccl_id", inputs=(), outputs=("Out",),
             attrs={"rank": 0, "endpoint": "", "other_endpoints": []},
             differentiable=False, host_only=True)
def c_gen_nccl_id(ins, attrs):
    return {"Out": jnp.zeros((1,), jnp.int32)}  # bootstrap handled by JAX


@register_op("all_to_all", inputs=("X",), outputs=("Out",),
             attrs={"ring_id": 0, "split_axis": 0, "concat_axis": 0},
             differentiable=False)
def all_to_all(ins, attrs):
    axis = _axis_for_ring(attrs["ring_id"])
    if axis is None or not _in_spmd_context(axis):
        return {"Out": ins["X"]}
    return {"Out": lax.all_to_all(
        ins["X"], axis, attrs["split_axis"], attrs["concat_axis"],
        tiled=True)}


@register_op("allreduce", inputs=("X",), outputs=("Out",),
             attrs={"reduce_type": 0, "sync_mode": False},
             differentiable=False, in_place={"Out": "X"})
def allreduce(ins, attrs):
    """distributed_ops/allreduce_op.cc (the legacy in-program collective;
    reduce_type 0..3 = sum/max/min/prod like RedType).  Rides the ring-0
    mesh axis; identity outside an SPMD context."""
    axis = _axis_for_ring(0)
    x = ins["X"]
    if axis is None or not _in_spmd_context(axis):
        return {"Out": x}
    rt = int(attrs["reduce_type"])
    if rt == 0:
        return {"Out": lax.psum(x, axis)}
    if rt == 1:
        return {"Out": lax.pmax(x, axis)}
    if rt == 2:
        return {"Out": lax.pmin(x, axis)}
    if rt == 3:
        return {"Out": jnp.prod(lax.all_gather(x, axis), axis=0)}
    raise ValueError(f"unknown reduce_type {rt}")


@register_op("broadcast", inputs=("X",), outputs=("Out",),
             attrs={"root": 0, "sync_mode": False},
             differentiable=False, in_place={"Out": "X"})
def broadcast_op(ins, attrs):
    """distributed_ops/broadcast_op.cc: every participant takes rank
    `root`'s value.  all_gather + slice keeps it one XLA collective."""
    axis = _axis_for_ring(0)
    x = ins["X"]
    if axis is None or not _in_spmd_context(axis):
        return {"Out": x}
    gathered = lax.all_gather(x, axis)
    return {"Out": gathered[int(attrs["root"])]}
