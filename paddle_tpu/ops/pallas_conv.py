"""Pallas TPU kernel: fused convolution + epilogue (bias/residual/ReLU).

Capability anchor: the 2026-08-01 rn50 diagnosis (tools/hlo_traffic.py,
VERDICT round 5) showed the ResNet-50 train step is HBM-bound with
~9.3 GB/step of residual-add/ReLU/bias elementwise glue that XLA will
NOT fuse into its convolution custom-calls — every bottleneck block
writes the conv result to HBM, reads it back for the add, writes the
sum, reads it back for the ReLU.  This kernel computes

    out = act(conv(x, w) + bias + residual)

in ONE VMEM-resident pass: the conv accumulator never leaves VMEM
between the matmul and the epilogue, so the glue bytes disappear from
the HBM roofline entirely.

Layout: NHWC activations (the TPU fast path nhwc_transpile produces),
OIHW filters (the repo's layout-independent param convention; the
transpose to HWIO is folded by XLA into the weight layout).  The
kernel grid is (N, Cout/bco): each cell holds one image's padded input
and one Cout tile of the filter in VMEM and runs the KH*KW tap loop as
static MXU dot_generals over [OH*OW, Cin] patches — im2col without the
materialization (taps are strided VMEM slices of the resident image).
Stride is handled by strided slicing inside VMEM; padding is applied
once in XLA before the call.

Backward: `jax.custom_vjp`.  The epilogue backward is closed-form
(mask by the saved post-ReLU output, reduce for the bias), and dx/dw
reuse the existing XLA conv gradients via jax.vjp of the plain conv
core — under jit the unused primal is DCE'd, leaving exactly the two
transposed convolutions XLA already runs for the unfused graph.

Dispatch is behind the typed flag ``conv_epilogue`` (flags.py, default
"off"): ops/nn.py conv2d routes NHWC convs here when the flag is on,
and transpiler.fuse_conv_epilogue rewrites conv+bias+residual+ReLU IR
chains onto the registered ``conv2d_epilogue`` op.  ``interpret=True``
(impl="interpret") runs the same kernel under the Pallas interpreter
for CPU-parity tests (tests/test_pallas_conv.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; support
# both so the kernel lowers under the CI jax as well as the chip
# host's (the seed's TPU cross-lowering tests failed on exactly this
# drift)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# VMEM budget for the compiled kernel: one image block + filter tile +
# accumulator + residual tile, doubled for Pallas' input double
# buffering, must fit comfortably in ~16 MB/core.  Shapes over budget
# fall back to the XLA composite (still correct, just unfused).
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_DEFAULT_BLOCK_CO = 256


# ---------------------------------------------------------------------------
# reference (XLA) implementation — also the fallback path
# ---------------------------------------------------------------------------

def _conv_core(x, w, strides, padding):
    """Plain NHWC conv with OIHW filters — the op the unfused graph
    runs and the backward's gradient source."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "OIHW", "NHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=list(padding),
        dimension_numbers=dn)


def _epilogue_xla(y, bias, residual, act):
    if bias is not None:
        y = y + bias.astype(y.dtype)
    if residual is not None:
        y = y + residual.astype(y.dtype)
    if act == "relu":
        y = jnp.maximum(y, 0)
    return y


def _reference(x, w, bias, residual, strides, padding, act):
    """Unfused composite: exactly the op sequence the IR runs when the
    flag is off (conv -> bias add -> residual add -> act)."""
    return _epilogue_xla(_conv_core(x, w, strides, padding), bias,
                         residual, act)


# ---------------------------------------------------------------------------
# pallas forward kernel
# ---------------------------------------------------------------------------

def _conv_ep_kernel(*refs, kh, kw, sh, sw, oh, ow, act, has_bias,
                    has_res):
    """One grid cell = one (image, Cout-tile): full KH*KW*Cin reduction
    plus the whole epilogue, accumulator resident in VMEM throughout.

    refs: x[1,HP,WP,Cin], w[KH,KW,Cin,bco], (bias[1,bco]),
    (residual[1,OH,OW,bco]), out[1,OH,OW,bco]."""
    x_ref, w_ref = refs[0], refs[1]
    i = 2
    b_ref = refs[i] if has_bias else None
    i += int(has_bias)
    r_ref = refs[i] if has_res else None
    o_ref = refs[-1]

    x = x_ref[0]                                   # [HP, WP, Cin]
    cin = x.shape[-1]
    bco = o_ref.shape[-1]
    ct = jnp.promote_types(x_ref.dtype, w_ref.dtype)
    acc = jnp.zeros((oh * ow, bco), jnp.float32)
    # static tap loop: each (i, j) filter tap is a VMEM slice of the
    # resident image — [OH, OW, Cin] flattened onto the MXU as an
    # [OH*OW, Cin] x [Cin, bco] contraction (im2col with no
    # materialized patch matrix).  Stride > 1 is a contiguous slice +
    # reshape + unit-index, NOT a strided slice: Mosaic's
    # vector.extract_strided_slice only allows strides in [1, 2)
    # (caught by tools/tpu_lowering_check.py cross-lowering — never
    # cost a chip window)
    for ti in range(kh):
        for tj in range(kw):
            p = lax.slice(x, (ti, tj, 0),
                          (ti + oh * sh - (sh - 1),
                           tj + ow * sw - (sw - 1), cin))
            if sh > 1:
                # pad the tail so rows split evenly, then keep phase 0
                p = jnp.pad(p, ((0, sh - 1), (0, 0), (0, 0)))
                p = p.reshape(oh, sh, p.shape[1], cin)[:, 0]
            if sw > 1:
                p = jnp.pad(p, ((0, 0), (0, sw - 1), (0, 0)))
                p = p.reshape(oh, ow, sw, cin)[:, :, 0]
            acc = acc + lax.dot_general(
                p.reshape(oh * ow, cin).astype(ct),
                w_ref[ti, tj].astype(ct),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    if has_bias:
        acc = acc + b_ref[0].astype(jnp.float32)[None, :]
    if has_res:
        acc = acc + r_ref[0].reshape(oh * ow, bco).astype(jnp.float32)
    if act == "relu":
        acc = jnp.maximum(acc, 0.0)
    o_ref[0] = acc.reshape(oh, ow, bco).astype(o_ref.dtype)


def _out_spatial(h, w, kh, kw, sh, sw, padding):
    (ph0, ph1), (pw0, pw1) = padding
    oh = (h + ph0 + ph1 - kh) // sh + 1
    ow = (w + pw0 + pw1 - kw) // sw + 1
    return oh, ow


def _block_co(cout):
    if cout <= _DEFAULT_BLOCK_CO:
        return cout
    return _DEFAULT_BLOCK_CO


def _vmem_estimate(xp_shape, w_shape, oh, ow, bco, has_res, x_itemsize,
                   w_itemsize, o_itemsize):
    _, hp, wp, cin = xp_shape
    kh, kw = w_shape[0], w_shape[1]
    x_b = hp * wp * cin * x_itemsize
    w_b = kh * kw * cin * bco * w_itemsize
    o_b = oh * ow * bco * o_itemsize
    r_b = oh * ow * bco * o_itemsize if has_res else 0
    acc_b = oh * ow * bco * 4
    # inputs/outputs are double buffered by the pipeline; the
    # accumulator lives once
    return 2 * (x_b + w_b + o_b + r_b) + acc_b


def _conv_ep_pallas(x, w, bias, residual, strides, padding, act,
                    interpret=False):
    """x: [N,H,W,Cin] NHWC; w: [O,Cin,KH,KW] OIHW."""
    n, h, wd, cin = x.shape
    cout, _, kh, kw = w.shape
    sh, sw = strides
    oh, ow = _out_spatial(h, wd, kh, kw, sh, sw, padding)
    (ph0, _), (pw0, _) = padding
    # pad once in XLA to exactly the span the tap loop reads:
    # HP = (OH-1)*sh + KH (bottom/right padding beyond what the conv
    # needs is sliced off so kernel slices stay in bounds)
    hp = (oh - 1) * sh + kh
    wp = (ow - 1) * sw + kw
    xp = jnp.pad(x, ((0, 0),
                     (ph0, max(hp - h - ph0, 0)),
                     (pw0, max(wp - wd - pw0, 0)),
                     (0, 0)))[:, :hp, :wp, :]
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))        # [KH,KW,Cin,O]
    out_dtype = jnp.promote_types(x.dtype, w.dtype)

    bco = _block_co(cout)
    if not interpret:
        est = _vmem_estimate(xp.shape, (kh, kw), oh, ow, bco,
                             residual is not None, xp.dtype.itemsize,
                             w_hwio.dtype.itemsize,
                             jnp.dtype(out_dtype).itemsize)
        if est > _VMEM_BUDGET_BYTES:
            return _reference(x, w, bias, residual, strides, padding,
                              act)

    grid = (n, pl.cdiv(cout, bco))
    in_specs = [
        pl.BlockSpec((1, hp, wp, cin), lambda ni, co: (ni, 0, 0, 0)),
        pl.BlockSpec((kh, kw, cin, bco), lambda ni, co: (0, 0, 0, co)),
    ]
    operands = [xp, w_hwio]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, bco), lambda ni, co: (0, co)))
        operands.append(bias.reshape(1, cout))
    if residual is not None:
        in_specs.append(pl.BlockSpec((1, oh, ow, bco),
                                     lambda ni, co: (ni, 0, 0, co)))
        operands.append(residual)
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    kernel = functools.partial(
        _conv_ep_kernel, kh=kh, kw=kw, sh=sh, sw=sw, oh=oh, ow=ow,
        act=act, has_bias=bias is not None,
        has_res=residual is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, oh, ow, bco),
                               lambda ni, co: (ni, 0, 0, co)),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, cout), out_dtype),
        interpret=interpret,
        **params,
    )(*operands)


# ---------------------------------------------------------------------------
# public differentiable entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _conv_ep(x, w, bias, residual, strides, padding, act, impl):
    if impl in ("pallas", "interpret"):
        return _conv_ep_pallas(x, w, bias, residual, strides, padding,
                               act, interpret=impl == "interpret")
    return _reference(x, w, bias, residual, strides, padding, act)


def _conv_ep_fwd(x, w, bias, residual, strides, padding, act, impl):
    y = _conv_ep(x, w, bias, residual, strides, padding, act, impl)
    return y, (x, w, bias, residual, y)


def _conv_ep_bwd(strides, padding, act, impl, res, g):
    x, w, bias, residual, y = res
    gf = g
    if act == "relu":
        # the saved output IS post-ReLU: y > 0 <=> pre-activation > 0
        gf = jnp.where(y > 0, g, jnp.zeros_like(g))
    # dx/dw via the existing XLA conv gradients: vjp of the plain conv
    # core — the unused primal conv is DCE'd under jit, leaving the
    # same transposed convs the unfused graph runs
    ct = jnp.promote_types(x.dtype, w.dtype)
    _, vjp = jax.vjp(
        lambda a, b: _conv_core(a, b, strides, padding), x, w)
    dx, dw = vjp(gf.astype(ct))
    db = None
    if bias is not None:
        db = jnp.sum(gf.astype(jnp.float32),
                     axis=(0, 1, 2)).astype(bias.dtype)
    dres = None
    if residual is not None:
        dres = gf.astype(residual.dtype)
    return dx, dw, db, dres


_conv_ep.defvjp(_conv_ep_fwd, _conv_ep_bwd)


def _norm_padding(paddings):
    """[ph, pw] or ((ph0,ph1),(pw0,pw1)) -> ((ph0,ph1),(pw0,pw1))."""
    p = tuple(paddings)
    if len(p) == 2 and not isinstance(p[0], (tuple, list)):
        return ((int(p[0]), int(p[0])), (int(p[1]), int(p[1])))
    return tuple((int(a), int(b)) for a, b in p)


def conv2d_epilogue(x, w, bias=None, residual=None, *, strides=(1, 1),
                    paddings=(0, 0), act=None, impl=None):
    """Fused NHWC conv + bias + residual + act in one VMEM pass.

    x: [N, H, W, Cin]; w: [O, Cin, KH, KW] (OIHW); bias: [O];
    residual: [N, OH, OW, O]; act: None or "relu".

    impl: None (auto: pallas on TPU, XLA composite elsewhere),
    "pallas", "interpret" (Pallas interpreter, for CPU tests), or
    "xla" (the unfused composite — the exact op sequence the flag-off
    graph runs).  Differentiable in x/w/bias/residual via custom_vjp;
    dx/dw reuse the XLA conv gradients.
    """
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    strides = tuple(int(s) for s in strides)
    padding = _norm_padding(paddings)
    return _conv_ep(x, w, bias, residual, strides, padding,
                    act or "", impl)


def _on_tpu():
    from paddle_tpu.ops.pallas_kernels import _on_tpu as _chip

    return _chip()


def _impl_from_flag():
    """Map the conv_epilogue flag to an impl name ("off" still returns
    a correct impl — the op may exist in a program loaded under a
    different flag state)."""
    from paddle_tpu.flags import get_flag

    mode = get_flag("conv_epilogue")
    if mode in ("pallas", "interpret", "xla"):
        return mode
    if mode == "on":
        return None                     # auto: pallas on TPU else xla
    return "xla"                        # "off" (or unknown): unfused


# ---------------------------------------------------------------------------
# IR op registration — the target of transpiler.fuse_conv_epilogue
# ---------------------------------------------------------------------------

from paddle_tpu.core.registry import register_op  # noqa: E402


@register_op("conv2d_epilogue",
             inputs=("Input", "Filter", "Bias", "Residual"),
             outputs=("Output",),
             optional=("Bias", "Residual"),
             attrs={"strides": [1, 1], "paddings": [0, 0], "act": "",
                    "groups": 1, "data_format": "NCHW"})
def _conv2d_epilogue_op(ins, attrs):
    """conv2d + channel bias + residual add + activation as ONE op.
    NCHW programs are normalized to NHWC internally (the layout
    transpiler rewrites the op to native NHWC on the TPU path, making
    these transposes vanish)."""
    x, w = ins["Input"], ins["Filter"]
    bias = ins.get("Bias")
    residual = ins.get("Residual")
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
        if residual is not None:
            residual = jnp.transpose(residual, (0, 2, 3, 1))
    out = conv2d_epilogue(
        x, w, bias, residual,
        strides=attrs.get("strides", [1, 1]),
        paddings=attrs.get("paddings", [0, 0]),
        act=attrs.get("act") or None,
        impl=_impl_from_flag())
    if fmt == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return {"Output": out}
