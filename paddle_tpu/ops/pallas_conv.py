"""Pallas TPU kernel: fused convolution + epilogue (bias/residual/ReLU).

Capability anchor: the 2026-08-01 rn50 diagnosis (tools/hlo_traffic.py,
VERDICT round 5) showed the ResNet-50 train step is HBM-bound with
~9.3 GB/step of residual-add/ReLU/bias elementwise glue that XLA will
NOT fuse into its convolution custom-calls — every bottleneck block
writes the conv result to HBM, reads it back for the add, writes the
sum, reads it back for the ReLU.  This kernel computes

    out = act(conv(x, w) + bias + residual)

in ONE VMEM-resident pass: the conv accumulator never leaves VMEM
between the matmul and the epilogue, so the glue bytes disappear from
the HBM roofline entirely.

Layout: NHWC activations (the TPU fast path nhwc_transpile produces),
OIHW filters (the repo's layout-independent param convention; the
transpose to HWIO is folded by XLA into the weight layout).  The
kernel grid is (N, Cout/bco): each cell holds one image's padded input
and one Cout tile of the filter in VMEM and runs the KH*KW tap loop as
static MXU dot_generals over [OH*OW, Cin] patches — im2col without the
materialization (taps are strided VMEM slices of the resident image).
Stride is handled by strided slicing inside VMEM; padding is applied
once in XLA before the call.

Backward: `jax.custom_vjp`.  The epilogue backward is closed-form
(mask by the saved post-ReLU output, reduce for the bias), and dx/dw
reuse the existing XLA conv gradients via jax.vjp of the plain conv
core — under jit the unused primal is DCE'd, leaving exactly the two
transposed convolutions XLA already runs for the unfused graph.

Dispatch is behind the typed flag ``conv_epilogue`` (flags.py, default
"off"): ops/nn.py conv2d routes NHWC convs here when the flag is on,
and transpiler.fuse_conv_epilogue rewrites conv+bias+residual+ReLU IR
chains onto the registered ``conv2d_epilogue`` op.  ``interpret=True``
(impl="interpret") runs the same kernel under the Pallas interpreter
for CPU-parity tests (tests/test_pallas_conv.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.observability import device_trace as _obs_device
from paddle_tpu.observability import tracing as _obs_trace

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; support
# both so the kernel lowers under the CI jax as well as the chip
# host's (the seed's TPU cross-lowering tests failed on exactly this
# drift)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# VMEM budget for the compiled kernel: one image block + filter tile +
# accumulator + residual tile, doubled for Pallas' input double
# buffering, must fit comfortably in ~16 MB/core.  Shapes over budget
# fall back to the XLA composite (still correct, just unfused).
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_DEFAULT_BLOCK_CO = 256


# ---------------------------------------------------------------------------
# reference (XLA) implementation — also the fallback path
# ---------------------------------------------------------------------------

def _conv_core(x, w, strides, padding):
    """Plain NHWC conv with OIHW filters — the op the unfused graph
    runs and the backward's gradient source."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "OIHW", "NHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=list(padding),
        dimension_numbers=dn)


def _epilogue_xla(y, bias, residual, act):
    from paddle_tpu.ops.epilogue import apply_chain_stages

    return apply_chain_stages(y, bias=bias, residual=residual, act=act)


def _reference(x, w, bias, residual, strides, padding, act):
    """Unfused composite: exactly the op sequence the IR runs when the
    flag is off (conv -> bias add -> residual add -> act)."""
    return _epilogue_xla(_conv_core(x, w, strides, padding), bias,
                         residual, act)


# ---------------------------------------------------------------------------
# pallas forward kernel
# ---------------------------------------------------------------------------

def _conv_ep_kernel(*refs, kh, kw, sh, sw, oh, ow, act, has_bias,
                    has_res):
    """One grid cell = one (image, Cout-tile): full KH*KW*Cin reduction
    plus the whole epilogue, accumulator resident in VMEM throughout.

    refs: x[1,HP,WP,Cin], w[KH,KW,Cin,bco], (bias[1,bco]),
    (residual[1,OH,OW,bco]), out[1,OH,OW,bco]."""
    x_ref, w_ref = refs[0], refs[1]
    i = 2
    b_ref = refs[i] if has_bias else None
    i += int(has_bias)
    r_ref = refs[i] if has_res else None
    o_ref = refs[-1]

    x = x_ref[0]                                   # [HP, WP, Cin]
    cin = x.shape[-1]
    bco = o_ref.shape[-1]
    ct = jnp.promote_types(x_ref.dtype, w_ref.dtype)
    acc = jnp.zeros((oh * ow, bco), jnp.float32)
    # static tap loop: each (i, j) filter tap is a VMEM slice of the
    # resident image — [OH, OW, Cin] flattened onto the MXU as an
    # [OH*OW, Cin] x [Cin, bco] contraction (im2col with no
    # materialized patch matrix).  Stride > 1 is a contiguous slice +
    # reshape + unit-index, NOT a strided slice: Mosaic's
    # vector.extract_strided_slice only allows strides in [1, 2)
    # (caught by tools/tpu_lowering_check.py cross-lowering — never
    # cost a chip window)
    for ti in range(kh):
        for tj in range(kw):
            p = lax.slice(x, (ti, tj, 0),
                          (ti + oh * sh - (sh - 1),
                           tj + ow * sw - (sw - 1), cin))
            if sh > 1:
                # pad the tail so rows split evenly, then keep phase 0
                p = jnp.pad(p, ((0, sh - 1), (0, 0), (0, 0)))
                p = p.reshape(oh, sh, p.shape[1], cin)[:, 0]
            if sw > 1:
                p = jnp.pad(p, ((0, 0), (0, sw - 1), (0, 0)))
                p = p.reshape(oh, ow, sw, cin)[:, :, 0]
            acc = acc + lax.dot_general(
                p.reshape(oh * ow, cin).astype(ct),
                w_ref[ti, tj].astype(ct),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    # the accumulator-order epilogue (ops/epilogue.py): bias/residual
    # in f32 on the resident accumulator, act, ONE cast at the end
    from paddle_tpu.ops.epilogue import apply_acc_stages

    acc = apply_acc_stages(
        acc,
        bias=b_ref[0][None, :] if has_bias else None,
        residual=r_ref[0].reshape(oh * ow, bco) if has_res else None,
        act=act)
    o_ref[0] = acc.reshape(oh, ow, bco).astype(o_ref.dtype)


def _out_spatial(h, w, kh, kw, sh, sw, padding):
    (ph0, ph1), (pw0, pw1) = padding
    oh = (h + ph0 + ph1 - kh) // sh + 1
    ow = (w + pw0 + pw1 - kw) // sw + 1
    return oh, ow


def _block_co(cout):
    if cout <= _DEFAULT_BLOCK_CO:
        return cout
    return _DEFAULT_BLOCK_CO


def _vmem_estimate(xp_shape, w_shape, oh, ow, bco, has_res, x_itemsize,
                   w_itemsize, o_itemsize):
    _, hp, wp, cin = xp_shape
    kh, kw = w_shape[0], w_shape[1]
    x_b = hp * wp * cin * x_itemsize
    w_b = kh * kw * cin * bco * w_itemsize
    o_b = oh * ow * bco * o_itemsize
    r_b = oh * ow * bco * o_itemsize if has_res else 0
    acc_b = oh * ow * bco * 4
    # inputs/outputs are double buffered by the pipeline; the
    # accumulator lives once
    return 2 * (x_b + w_b + o_b + r_b) + acc_b


def _conv_ep_pallas(x, w, bias, residual, strides, padding, act,
                    interpret=False):
    """x: [N,H,W,Cin] NHWC; w: [O,Cin,KH,KW] OIHW."""
    n, h, wd, cin = x.shape
    cout, _, kh, kw = w.shape
    sh, sw = strides
    oh, ow = _out_spatial(h, wd, kh, kw, sh, sw, padding)
    (ph0, _), (pw0, _) = padding
    # pad once in XLA to exactly the span the tap loop reads:
    # HP = (OH-1)*sh + KH (bottom/right padding beyond what the conv
    # needs is sliced off so kernel slices stay in bounds)
    hp = (oh - 1) * sh + kh
    wp = (ow - 1) * sw + kw
    xp = jnp.pad(x, ((0, 0),
                     (ph0, max(hp - h - ph0, 0)),
                     (pw0, max(wp - wd - pw0, 0)),
                     (0, 0)))[:, :hp, :wp, :]
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))        # [KH,KW,Cin,O]
    out_dtype = jnp.promote_types(x.dtype, w.dtype)

    bco = _block_co(cout)
    if not interpret:
        est = _vmem_estimate(xp.shape, (kh, kw), oh, ow, bco,
                             residual is not None, xp.dtype.itemsize,
                             w_hwio.dtype.itemsize,
                             jnp.dtype(out_dtype).itemsize)
        if est > _VMEM_BUDGET_BYTES:
            return _reference(x, w, bias, residual, strides, padding,
                              act)

    grid = (n, pl.cdiv(cout, bco))
    in_specs = [
        pl.BlockSpec((1, hp, wp, cin), lambda ni, co: (ni, 0, 0, 0)),
        pl.BlockSpec((kh, kw, cin, bco), lambda ni, co: (0, 0, 0, co)),
    ]
    operands = [xp, w_hwio]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, bco), lambda ni, co: (0, co)))
        operands.append(bias.reshape(1, cout))
    if residual is not None:
        in_specs.append(pl.BlockSpec((1, oh, ow, bco),
                                     lambda ni, co: (ni, 0, 0, co)))
        operands.append(residual)
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    kernel = functools.partial(
        _conv_ep_kernel, kh=kh, kw=kw, sh=sh, sw=sw, oh=oh, ow=ow,
        act=act, has_bias=bias is not None,
        has_res=residual is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, oh, ow, bco),
                               lambda ni, co: (ni, 0, 0, co)),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, cout), out_dtype),
        interpret=interpret,
        **params,
    )(*operands)


# ---------------------------------------------------------------------------
# public differentiable entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _conv_ep(x, w, bias, residual, strides, padding, act, impl):
    if impl in ("pallas", "interpret"):
        return _conv_ep_pallas(x, w, bias, residual, strides, padding,
                               act, interpret=impl == "interpret")
    return _reference(x, w, bias, residual, strides, padding, act)


def _conv_ep_fwd(x, w, bias, residual, strides, padding, act, impl):
    y = _conv_ep(x, w, bias, residual, strides, padding, act, impl)
    return y, (x, w, bias, residual, y)


def _conv_ep_bwd(strides, padding, act, impl, res, g):
    x, w, bias, residual, y = res
    gf = g
    if act == "relu":
        # the saved output IS post-ReLU: y > 0 <=> pre-activation > 0
        gf = jnp.where(y > 0, g, jnp.zeros_like(g))
    # dx/dw via the existing XLA conv gradients: vjp of the plain conv
    # core — the unused primal conv is DCE'd under jit, leaving the
    # same transposed convs the unfused graph runs
    ct = jnp.promote_types(x.dtype, w.dtype)
    _, vjp = jax.vjp(
        lambda a, b: _conv_core(a, b, strides, padding), x, w)
    dx, dw = vjp(gf.astype(ct))
    db = None
    if bias is not None:
        db = jnp.sum(gf.astype(jnp.float32),
                     axis=(0, 1, 2)).astype(bias.dtype)
    dres = None
    if residual is not None:
        dres = gf.astype(residual.dtype)
    return dx, dw, db, dres


_conv_ep.defvjp(_conv_ep_fwd, _conv_ep_bwd)


def _norm_padding(paddings):
    """[ph, pw] or ((ph0,ph1),(pw0,pw1)) -> ((ph0,ph1),(pw0,pw1))."""
    p = tuple(paddings)
    if len(p) == 2 and not isinstance(p[0], (tuple, list)):
        return ((int(p[0]), int(p[0])), (int(p[1]), int(p[1])))
    return tuple((int(a), int(b)) for a, b in p)


# ---------------------------------------------------------------------------
# conv + BN-stats sibling outputs (the TRAIN-chain fusion, ISSUE 4)
#
# The train graph can't use the epilogue kernel's full fusion because
# BN *batch* statistics sit between the conv and the residual add: the
# unfused chain re-reads the whole conv output once for the moments
# reduction and once for the normalize.  Here the conv kernel emits
# per-channel partial sum(y)/sum(y*y) as SIBLING outputs while the
# accumulator is still VMEM-resident — each grid cell reduces its own
# [OH*OW, bco] tile, so the stats cost no extra HBM read at all — and a
# second one-pass kernel applies normalize+scale/shift+residual+ReLU.
# Together the activation is touched exactly once per kernel instead of
# three times.
# ---------------------------------------------------------------------------

def _conv_stats_kernel(*refs, kh, kw, sh, sw, oh, ow, has_bias):
    """The epilogue kernel's tap loop, plus per-grid-cell partial BN
    stats: s1[ni, co-tile] = sum over this image's OH*OW of y,
    s2 = sum of y*y, both f32, reduced from the VMEM-resident
    accumulator AFTER the cast to the output dtype (the unfused graph's
    BN sees the conv output post-cast, so the stats must too).
    refs: x[1,HP,WP,Cin], w[KH,KW,Cin,bco], (bias[1,bco]),
    y[1,OH,OW,bco], s1[1,bco], s2[1,bco]."""
    x_ref, w_ref = refs[0], refs[1]
    b_ref = refs[2] if has_bias else None
    o_ref, s1_ref, s2_ref = refs[-3], refs[-2], refs[-1]

    x = x_ref[0]
    cin = x.shape[-1]
    bco = o_ref.shape[-1]
    ct = jnp.promote_types(x_ref.dtype, w_ref.dtype)
    acc = jnp.zeros((oh * ow, bco), jnp.float32)
    for ti in range(kh):
        for tj in range(kw):
            p = lax.slice(x, (ti, tj, 0),
                          (ti + oh * sh - (sh - 1),
                           tj + ow * sw - (sw - 1), cin))
            if sh > 1:
                p = jnp.pad(p, ((0, sh - 1), (0, 0), (0, 0)))
                p = p.reshape(oh, sh, p.shape[1], cin)[:, 0]
            if sw > 1:
                p = jnp.pad(p, ((0, 0), (0, sw - 1), (0, 0)))
                p = p.reshape(oh, ow, sw, cin)[:, :, 0]
            acc = acc + lax.dot_general(
                p.reshape(oh * ow, cin).astype(ct),
                w_ref[ti, tj].astype(ct),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    from paddle_tpu.ops.epilogue import apply_acc_stages

    acc = apply_acc_stages(
        acc, bias=b_ref[0][None, :] if has_bias else None)
    y = acc.reshape(oh, ow, bco).astype(o_ref.dtype)
    o_ref[0] = y
    yf = y.reshape(oh * ow, bco).astype(jnp.float32)
    # the stat blocks are (1, 8, bco): f32 blocks need a sublane dim
    # divisible by 8 to lower under Mosaic (the [1, bq] lse lesson —
    # a bare (1, bco) spec is rejected), so the per-cell partials are
    # written sublane-replicated x8 and the host reads row 0
    s1_ref[0] = jnp.broadcast_to(jnp.sum(yf, axis=0)[None, :],
                                 (8, bco))
    s2_ref[0] = jnp.broadcast_to(jnp.sum(yf * yf, axis=0)[None, :],
                                 (8, bco))


def _conv_stats_pallas(x, w, bias, strides, padding, interpret=False):
    """Fused conv (+bias) with per-image partial-stat sibling outputs.

    Returns (y[N,OH,OW,Cout], s1[N,Cout] f32, s2[N,Cout] f32) with
    s1[n] = sum over (OH,OW) of y[n] and s2[n] the same for y*y.  The
    partials are finalized to mean/var on the host side of the call
    (one tiny [N,C] reduction XLA fuses); keeping the grid fully
    parallel beats sequentializing the N dimension for an in-kernel
    cross-step accumulator.  Falls back to the XLA composite when the
    VMEM estimate exceeds budget (same rule as the epilogue kernel)."""
    n, h, wd, cin = x.shape
    cout, _, kh, kw = w.shape
    sh, sw = strides
    oh, ow = _out_spatial(h, wd, kh, kw, sh, sw, padding)
    (ph0, _), (pw0, _) = padding
    hp = (oh - 1) * sh + kh
    wp = (ow - 1) * sw + kw
    xp = jnp.pad(x, ((0, 0),
                     (ph0, max(hp - h - ph0, 0)),
                     (pw0, max(wp - wd - pw0, 0)),
                     (0, 0)))[:, :hp, :wp, :]
    w_hwio = jnp.transpose(w, (2, 3, 1, 0))
    out_dtype = jnp.promote_types(x.dtype, w.dtype)

    bco = _block_co(cout)
    if not interpret:
        est = _vmem_estimate(xp.shape, (kh, kw), oh, ow, bco, False,
                             xp.dtype.itemsize, w_hwio.dtype.itemsize,
                             jnp.dtype(out_dtype).itemsize)
        # the stats blocks ride in the same budget (2 x (1, bco) f32,
        # double buffered)
        est += 4 * bco * 4 * 2
        if est > _VMEM_BUDGET_BYTES:
            return _conv_stats_xla(x, w, bias, strides, padding)

    grid = (n, pl.cdiv(cout, bco))
    in_specs = [
        pl.BlockSpec((1, hp, wp, cin), lambda ni, co: (ni, 0, 0, 0)),
        pl.BlockSpec((kh, kw, cin, bco), lambda ni, co: (0, 0, 0, co)),
    ]
    operands = [xp, w_hwio]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, bco), lambda ni, co: (0, co)))
        operands.append(bias.reshape(1, cout))
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    kernel = functools.partial(
        _conv_stats_kernel, kh=kh, kw=kw, sh=sh, sw=sw, oh=oh, ow=ow,
        has_bias=bias is not None)
    # stat arrays ride as [N, 8, Cout] (sublane-replicated x8 — see the
    # kernel comment); the finalization reads row 0
    stat_spec = pl.BlockSpec((1, 8, bco), lambda ni, co: (ni, 0, co))
    y, s1, s2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, oh, ow, bco), lambda ni, co: (ni, 0, 0, co)),
            stat_spec,
            stat_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, oh, ow, cout), out_dtype),
            jax.ShapeDtypeStruct((n, 8, cout), jnp.float32),
            jax.ShapeDtypeStruct((n, 8, cout), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(*operands)
    return y, s1[:, 0, :], s2[:, 0, :]


def _conv_stats_xla(x, w, bias, strides, padding):
    """XLA fallback with the kernel's stat semantics: plain conv, then
    per-image partial sums of the (cast) output — multi-output fused by
    XLA into one read pass over y."""
    y = _conv_core(x, w, strides, padding)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    yf = y.astype(jnp.float32)
    return y, jnp.sum(yf, axis=(1, 2)), jnp.sum(yf * yf, axis=(1, 2))


def _finalize_stats(s1, s2, m):
    """[N, C] partial sums -> per-channel (mean, var), f32.  Raw-moment
    finalization: var = E[y^2] - mean^2, clamped at 0.  The f32
    accumulation is over the already-rounded conv output, so the
    classic |mean| >> std cancellation only bites for channels far
    outside BN's operating regime (the unfused fallback keeps the
    shifted `_moments_1pass` for those paths)."""
    mean = jnp.sum(s1, axis=0) / m
    e2 = jnp.sum(s2, axis=0) / m
    return mean, jnp.maximum(e2 - mean * mean, 0.0)


def conv2d_bn_stats(x, w, bias=None, *, strides=(1, 1), paddings=(0, 0),
                    impl=None):
    """NHWC conv (+bias) that also returns the per-channel BN batch
    statistics of its output: (y, mean, var), stats f32.

    The stats are SIBLING outputs of the conv kernel — each grid cell
    reduces its VMEM-resident accumulator tile, so the moments cost no
    extra pass over y (the unfused train graph re-reads the whole conv
    output for `_moments_1pass`).  impl as in conv2d_epilogue."""
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    strides = tuple(int(s) for s in strides)
    padding = _norm_padding(paddings)
    if impl in ("pallas", "interpret"):
        y, s1, s2 = _conv_stats_pallas(x, w, bias, strides, padding,
                                       interpret=impl == "interpret")
    else:
        y, s1, s2 = _conv_stats_xla(x, w, bias, strides, padding)
    m = float(y.shape[0] * y.shape[1] * y.shape[2])
    mean, var = _finalize_stats(s1, s2, m)
    return y, mean, var


# ------------------------- fused normalize + residual + ReLU kernel --


def _bn_apply_kernel(*refs, act, has_res):
    """One elementwise pass: out = act(((y - mean) * rstd) * scale +
    shift [+ residual]).  Normalize math in f32, cast to the output
    dtype BEFORE the residual add — the exact op order (and rounding
    points) of the unfused batch_norm -> elementwise_add -> relu chain,
    so interpret-mode parity vs that chain is bit-exact given the same
    stats.  refs: y[1,bh,OW,bc], mean[1,bc], rstd[1,bc], scale[1,bc],
    shift[1,bc], (res[1,bh,OW,bc]), out[1,bh,OW,bc]."""
    y_ref, m_ref, r_ref, s_ref, b_ref = refs[:5]
    res_ref = refs[5] if has_res else None
    o_ref = refs[-1]
    from paddle_tpu.ops.epilogue import apply_bn_tail

    yf = y_ref[0].astype(jnp.float32)              # [bh, OW, bc]
    t = (yf - m_ref[0][None, None, :]) * r_ref[0][None, None, :]
    t = t * s_ref[0][None, None, :] + b_ref[0][None, None, :]
    o_ref[0] = apply_bn_tail(t, o_ref.dtype,
                             res_ref[0] if has_res else None, act)


def _bn_apply_rows(oh, ow, bc, itemsize, n_bufs):
    """Largest spatial row-block that keeps the pipeline's double
    buffers under the VMEM budget."""
    per_row = ow * bc * itemsize * 2 * n_bufs      # double buffered
    bh = max(1, _VMEM_BUDGET_BYTES // max(per_row, 1))
    return min(oh, bh)


def _bn_apply_pallas(y, mean, rstd, scale, shift, residual, act,
                     interpret=False):
    n, oh, ow, c = y.shape
    bc = min(c, _DEFAULT_BLOCK_CO)
    bh = _bn_apply_rows(oh, ow, bc, jnp.dtype(y.dtype).itemsize,
                        3 if residual is not None else 2)
    grid = (n, pl.cdiv(oh, bh), pl.cdiv(c, bc))
    row_spec = pl.BlockSpec((1, bh, ow, bc),
                            lambda ni, hi, ci: (ni, hi, 0, ci))
    ch_spec = pl.BlockSpec((1, bc), lambda ni, hi, ci: (0, ci))
    in_specs = [row_spec, ch_spec, ch_spec, ch_spec, ch_spec]
    f32 = jnp.float32
    operands = [y, mean.astype(f32).reshape(1, c),
                rstd.astype(f32).reshape(1, c),
                scale.astype(f32).reshape(1, c),
                shift.astype(f32).reshape(1, c)]
    if residual is not None:
        in_specs.append(row_spec)
        operands.append(residual)
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel"))
    kernel = functools.partial(_bn_apply_kernel, act=act,
                               has_res=residual is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
        interpret=interpret,
        **params,
    )(*operands)


def _bn_apply_xla(y, mean, rstd, scale, shift, residual, act):
    """The unfused chain's exact op order: normalize in f32, cast to
    y.dtype, add the residual in that dtype, relu last."""
    from paddle_tpu.ops.epilogue import apply_bn_tail

    f32 = jnp.float32
    shape = (1, 1, 1, y.shape[-1])
    t = (y.astype(f32) - mean.astype(f32).reshape(shape)) \
        * rstd.astype(f32).reshape(shape)
    t = t * scale.astype(f32).reshape(shape) \
        + shift.astype(f32).reshape(shape)
    return apply_bn_tail(t, y.dtype, residual, act)


def bn_normalize_epilogue(y, mean, var, scale, shift, residual=None, *,
                          epsilon=1e-5, act=None, impl=None):
    """Normalize + scale/shift + residual-add + act in ONE pass over y.

    y: [N, H, W, C] (NHWC); mean/var/scale/shift: [C]; residual:
    y-shaped or None.  The unfused train chain runs three elementwise
    passes over the activation here (normalize, add, relu) plus the
    moments re-read; paired with conv2d_bn_stats this touches y exactly
    once.  impl as in conv2d_epilogue."""
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    rstd = lax.rsqrt(var.astype(jnp.float32) + epsilon)
    if impl in ("pallas", "interpret"):
        return _bn_apply_pallas(y, mean, rstd, scale, shift, residual,
                                act or "", interpret=impl == "interpret")
    return _bn_apply_xla(y, mean, rstd, scale, shift, residual,
                         act or "")


def conv2d_epilogue(x, w, bias=None, residual=None, *, strides=(1, 1),
                    paddings=(0, 0), act=None, impl=None):
    """Fused NHWC conv + bias + residual + act in one VMEM pass.

    x: [N, H, W, Cin]; w: [O, Cin, KH, KW] (OIHW); bias: [O];
    residual: [N, OH, OW, O]; act: None or "relu".

    impl: None (auto: pallas on TPU, XLA composite elsewhere),
    "pallas", "interpret" (Pallas interpreter, for CPU tests), or
    "xla" (the unfused composite — the exact op sequence the flag-off
    graph runs).  Differentiable in x/w/bias/residual via custom_vjp;
    dx/dw reuse the XLA conv gradients.
    """
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    strides = tuple(int(s) for s in strides)
    padding = _norm_padding(paddings)
    if _obs_trace._tracer is not None:
        # device-time attribution (ISSUE 10): annotation at runtime,
        # named_scope inside a jit trace — one module-global check off
        with _obs_device.annotate("conv2d_epilogue"):
            return _conv_ep(x, w, bias, residual, strides, padding,
                            act or "", impl)
    return _conv_ep(x, w, bias, residual, strides, padding,
                    act or "", impl)


def _conv_bn_unfused(x, w, bias, scale, shift, residual, strides,
                     padding, act, eps):
    """The EXACT op sequence the flag-off train graph runs: conv ->
    `_moments_1pass` batch stats -> normalize (f32, cast) -> residual
    add -> relu.  A program rewritten onto conv2d_bn_train but executed
    with conv_bn_stats off must be bit-identical to the never-rewritten
    graph, so this path mirrors ops/nn.py batch_norm term for term."""
    from paddle_tpu.ops.nn import _moments_1pass

    y = _conv_core(x, w, strides, padding)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    xf = y.astype(scale.dtype)
    mean, var = _moments_1pass(xf, (0, 1, 2))
    shape = (1, 1, 1, y.shape[-1])
    t = (xf - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + eps) \
        * scale.reshape(shape) + shift.reshape(shape)
    t = t.astype(y.dtype)
    if residual is not None:
        t = t + residual
    if act == "relu":
        t = jnp.maximum(t, 0)
    return t, mean, var, y


def _conv_bn_core(x, w, bias, scale, shift, residual, strides, padding,
                  act, eps, impl):
    """Dispatch for the fused train chain; returns (out, mean, var,
    y_conv)."""
    if impl in ("pallas", "interpret"):
        interp = impl == "interpret"
        y, s1, s2 = _conv_stats_pallas(x, w, bias, strides, padding,
                                       interpret=interp)
        m = float(y.shape[0] * y.shape[1] * y.shape[2])
        mean, var = _finalize_stats(s1, s2, m)
        rstd = lax.rsqrt(var + eps)
        out = _bn_apply_pallas(y, mean, rstd, scale.astype(jnp.float32),
                               shift.astype(jnp.float32), residual, act,
                               interpret=interp)
        return out, mean, var, y
    return _conv_bn_unfused(x, w, bias, scale, shift, residual, strides,
                            padding, act, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _conv_bn_act(x, w, bias, scale, shift, residual, strides, padding,
                 act, eps, impl):
    out, mean, var, _y = _conv_bn_core(x, w, bias, scale, shift,
                                       residual, strides, padding, act,
                                       eps, impl)
    return out, mean, var


def _conv_bn_act_fwd(x, w, bias, scale, shift, residual, strides,
                     padding, act, eps, impl):
    out, mean, var, y = _conv_bn_core(x, w, bias, scale, shift,
                                      residual, strides, padding, act,
                                      eps, impl)
    return (out, mean, var), (x, w, bias, scale, residual, y, mean, var,
                              out)


def _conv_bn_act_bwd(strides, padding, act, eps, impl, res, cts):
    """Closed-form BN-train backward, term-for-term the hand-written
    ops/nn.py batch_norm_grad formula evaluated on the SAVED batch
    stats (no moments recompute), composed with the ReLU mask from the
    saved post-act output and the existing XLA conv gradients via
    jax.vjp of the plain conv core — given equal stats the grads are
    bit-identical to the unfused graph's.  The mean/var sibling
    outputs' own cotangents (non-zero only when something downstream
    consumes SavedMean/SavedVariance) are folded in analytically:
    d mean/d y = 1/m, d var/d y = 2 (y - mean)/m."""
    x, w, bias, scale, residual, y, mean, var = res[:8]
    out = res[8]
    g, g_mean, g_var = cts
    if act == "relu":
        g = jnp.where(out > 0, g, jnp.zeros_like(g))
    dres = None
    if residual is not None:
        dres = g.astype(residual.dtype)
    f32 = scale.dtype
    m = float(y.shape[0] * y.shape[1] * y.shape[2])
    shape = (1, 1, 1, y.shape[-1])
    axes = (0, 1, 2)
    yf = y.astype(f32)
    dyf = g.astype(f32)
    rstd = lax.rsqrt(var + eps)
    x_hat = (yf - mean.reshape(shape)) * rstd.reshape(shape)
    dshift = jnp.sum(dyf, axis=axes)
    dscale = jnp.sum(dyf * x_hat, axis=axes)
    dy = (scale * rstd).reshape(shape) * (
        dyf - (dshift / m).reshape(shape)
        - x_hat * (dscale / m).reshape(shape))
    # sibling-stat cotangents (usually symbolic zeros, DCE'd)
    dy = dy + (g_mean / m).reshape(shape) \
        + (yf - mean.reshape(shape)) * (2.0 / m * g_var).reshape(shape)
    dy = dy.astype(y.dtype)
    ct = jnp.promote_types(x.dtype, w.dtype)
    _, vjp = jax.vjp(
        lambda a, b: _conv_core(a, b, strides, padding), x, w)
    dx, dw = vjp(dy.astype(ct))
    db = None
    if bias is not None:
        db = jnp.sum(dy.astype(jnp.float32),
                     axis=(0, 1, 2)).astype(bias.dtype)
    return dx, dw, db, dscale.astype(scale.dtype), \
        dshift.astype(scale.dtype), dres


_conv_bn_act.defvjp(_conv_bn_act_fwd, _conv_bn_act_bwd)


def conv2d_bn_act(x, w, scale, shift, bias=None, residual=None, *,
                  strides=(1, 1), paddings=(0, 0), act=None,
                  epsilon=1e-5, impl=None):
    """Fused NHWC conv + train-mode BN + residual + act: TWO one-pass
    kernels (conv with Σy/Σy² sibling outputs; normalize+add+ReLU)
    replacing the five-pass unfused chain.  Returns (out, batch_mean,
    batch_var) — the stats ride out so the caller can update running
    stats / emit SavedMean.  Differentiable in x/w/bias/scale/shift/
    residual via custom_vjp; dx/dw reuse the XLA conv gradients.

    x: [N, H, W, Cin]; w: [O, Cin, KH, KW]; scale/shift: [O] (BN
    gamma/beta, f32); bias: optional conv channel bias [O]; residual:
    [N, OH, OW, O] or None; act: None or "relu".  impl: None (auto:
    pallas on TPU, the exact unfused composite elsewhere), "pallas",
    "interpret", or "xla"."""
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    strides = tuple(int(s) for s in strides)
    padding = _norm_padding(paddings)
    if _obs_trace._tracer is not None:
        with _obs_device.annotate("conv2d_bn_act"):
            return _conv_bn_act(x, w, bias, scale, shift, residual,
                                strides, padding, act or "",
                                float(epsilon), impl)
    return _conv_bn_act(x, w, bias, scale, shift, residual, strides,
                        padding, act or "", float(epsilon), impl)


def _on_tpu():
    from paddle_tpu.ops.pallas_kernels import _on_tpu as _chip

    return _chip()


def _impl_from_flag():
    """Map the conv_epilogue flag to an impl name ("off" still returns
    a correct impl — the op may exist in a program loaded under a
    different flag state)."""
    from paddle_tpu.flags import get_flag

    mode = get_flag("conv_epilogue")
    if mode in ("pallas", "interpret", "xla"):
        return mode
    if mode == "on":
        return None                     # auto: pallas on TPU else xla
    return "xla"                        # "off" (or unknown): unfused


# ---------------------------------------------------------------------------
# IR op registration — the target of transpiler.fuse_conv_epilogue
# ---------------------------------------------------------------------------

from paddle_tpu.core.registry import register_op  # noqa: E402


@register_op("conv2d_epilogue",
             inputs=("Input", "Filter", "Bias", "Residual"),
             outputs=("Output",),
             optional=("Bias", "Residual"),
             attrs={"strides": [1, 1], "paddings": [0, 0], "act": "",
                    "groups": 1, "data_format": "NCHW",
                    "epilogue": ""})
def _conv2d_epilogue_op(ins, attrs):
    """conv2d + channel bias + residual add + activation as ONE op.
    NCHW programs are normalized to NHWC internally (the layout
    transpiler rewrites the op to native NHWC on the TPU path, making
    these transposes vanish)."""
    x, w = ins["Input"], ins["Filter"]
    bias = ins.get("Bias")
    residual = ins.get("Residual")
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
        if residual is not None:
            residual = jnp.transpose(residual, (0, 2, 3, 1))
    out = conv2d_epilogue(
        x, w, bias, residual,
        strides=attrs.get("strides", [1, 1]),
        paddings=attrs.get("paddings", [0, 0]),
        act=attrs.get("act") or None,
        impl=_impl_from_flag())
    if fmt == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return {"Output": out}


def _bn_impl_from_flag():
    """Map the conv_bn_stats flag to an impl name ("off" still returns
    the exact unfused composite — a rewritten program loaded under a
    different flag state must stay bit-identical to the original)."""
    from paddle_tpu.flags import get_flag

    mode = get_flag("conv_bn_stats")
    if mode in ("pallas", "interpret", "xla"):
        return mode
    if mode == "on":
        return None                     # auto: pallas on TPU else xla
    return "xla"                        # "off" (or unknown): unfused


@register_op("conv2d_bn_train",
             inputs=("Input", "Filter", "Bias", "Scale", "BNBias",
                     "Mean", "Variance", "Residual"),
             outputs=("Output", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance"),
             optional=("Bias", "Residual"),
             attrs={"strides": [1, 1], "paddings": [0, 0], "act": "",
                    "groups": 1, "epsilon": 1e-5, "momentum": 0.9,
                    "data_format": "NCHW", "epilogue": ""})
def _conv2d_bn_train_op(ins, attrs):
    """conv2d + train-mode batch_norm + residual add + activation as
    ONE op — the target of transpiler.fuse_conv_bn_train.  Outputs
    mirror batch_norm's contract (MeanOut/VarianceOut wired back onto
    the running-stat vars; SavedMean = batch mean, SavedVariance =
    1/sqrt(var+eps)), so the rewrite preserves every BN output the rest
    of the graph may consume.  NCHW programs are normalized to NHWC
    internally (the layout transpiler rewrites the op to native NHWC
    on the TPU path)."""
    x, w = ins["Input"], ins["Filter"]
    bias = ins.get("Bias")
    scale, shift = ins["Scale"], ins["BNBias"]
    mean_in, var_in = ins["Mean"], ins["Variance"]
    residual = ins.get("Residual")
    eps, mom = attrs["epsilon"], attrs["momentum"]
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
        if residual is not None:
            residual = jnp.transpose(residual, (0, 2, 3, 1))
    out, mean, var = conv2d_bn_act(
        x, w, scale, shift, bias, residual,
        strides=attrs.get("strides", [1, 1]),
        paddings=attrs.get("paddings", [0, 0]),
        act=attrs.get("act") or None, epsilon=eps,
        impl=_bn_impl_from_flag())
    if fmt == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    mean_out = mean_in * mom + lax.stop_gradient(mean) * (1 - mom)
    var_out = var_in * mom + lax.stop_gradient(var) * (1 - mom)
    saved_var = 1.0 / jnp.sqrt(var + eps)
    return {"Output": out, "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": mean, "SavedVariance": saved_var}
