"""Metric ops (reference: /root/reference/paddle/fluid/operators/metrics/
accuracy_op.cc, auc_op.cc, precision_recall_op.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"),
             differentiable=False)
def accuracy(ins, attrs):
    """Indices: [N, k] top-k predictions; Label: [N, 1]."""
    idx, label = ins["Indices"], ins["Label"]
    lab = label.reshape(-1, 1)
    correct = jnp.any(idx == lab, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(idx.shape[0], jnp.int64)
    return {
        "Accuracy": num_correct / idx.shape[0],
        "Correct": num_correct.astype(jnp.int64),
        "Total": total,
    }


@register_op("auc", inputs=("Predict", "Label", "StatPos", "StatNeg"),
             outputs=("AUC", "StatPosOut", "StatNegOut"),
             attrs={"num_thresholds": 4095, "curve": "ROC"},
             differentiable=False,
             in_place={"StatPosOut": "StatPos", "StatNegOut": "StatNeg"})
def auc(ins, attrs):
    """Streaming AUC via threshold buckets (reference auc_op.cc)."""
    pred, label = ins["Predict"], ins["Label"]
    pos_hist, neg_hist = ins["StatPos"], ins["StatNeg"]
    n = attrs["num_thresholds"]
    p1 = pred[:, -1] if pred.ndim == 2 else pred.reshape(-1)
    bucket = jnp.clip((p1 * n).astype(jnp.int32), 0, n)
    lab = label.reshape(-1).astype(jnp.bool_)
    pos_hist = pos_hist.at[bucket].add(lab.astype(pos_hist.dtype))
    neg_hist = neg_hist.at[bucket].add((~lab).astype(neg_hist.dtype))
    # integrate over descending threshold
    pos_cum = jnp.cumsum(pos_hist[::-1])
    neg_cum = jnp.cumsum(neg_hist[::-1])
    tot_pos = pos_cum[-1]
    tot_neg = neg_cum[-1]
    # trapezoid on (fpr, tpr)
    tpr = pos_cum / jnp.maximum(tot_pos, 1)
    fpr = neg_cum / jnp.maximum(tot_neg, 1)
    auc_val = jnp.sum(
        (fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0
    ) + fpr[0] * tpr[0] / 2.0
    return {"AUC": auc_val, "StatPosOut": pos_hist, "StatNegOut": neg_hist}


@register_op("precision_recall",
             inputs=("MaxProbs", "Indices", "Labels", "StatesInfo"),
             outputs=("BatchMetrics", "AccumMetrics", "AccumStatesInfo"),
             optional=("StatesInfo",),
             attrs={"class_number": REQUIRED}, differentiable=False)
def precision_recall(ins, attrs):
    import jax

    c = attrs["class_number"]
    idx = ins["Indices"].reshape(-1).astype(jnp.int32)
    lab = ins["Labels"].reshape(-1).astype(jnp.int32)
    tp = jax.ops.segment_sum(
        (idx == lab).astype(jnp.float64), lab, num_segments=c
    )
    pred_cnt = jax.ops.segment_sum(
        jnp.ones_like(idx, jnp.float64), idx, num_segments=c
    )
    lab_cnt = jax.ops.segment_sum(
        jnp.ones_like(lab, jnp.float64), lab, num_segments=c
    )
    fp = pred_cnt - tp
    fn = lab_cnt - tp
    states = jnp.stack([tp, fp, fn, jnp.zeros_like(tp)], axis=1)
    if "StatesInfo" in ins:
        states = states + ins["StatesInfo"]
    def metrics(tp, fp, fn):
        precision = jnp.where(tp + fp > 0, tp / (tp + fp), 0.0)
        recall = jnp.where(tp + fn > 0, tp / (tp + fn), 0.0)
        f1 = jnp.where(precision + recall > 0,
                       2 * precision * recall / (precision + recall), 0.0)
        return jnp.asarray([jnp.mean(precision), jnp.mean(recall),
                            jnp.mean(f1),
                            jnp.sum(tp) / jnp.maximum(
                                jnp.sum(tp + fp), 1.0),
                            jnp.sum(tp) / jnp.maximum(
                                jnp.sum(tp + fn), 1.0),
                            0.0])
    batch = metrics(tp, fp, fn)
    acc = metrics(states[:, 0], states[:, 1], states[:, 2])
    return {"BatchMetrics": batch, "AccumMetrics": acc,
            "AccumStatesInfo": states}
