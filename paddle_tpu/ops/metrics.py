"""Metric ops (reference: /root/reference/paddle/fluid/operators/metrics/
accuracy_op.cc, auc_op.cc, precision_recall_op.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"),
             differentiable=False)
def accuracy(ins, attrs):
    """Indices: [N, k] top-k predictions; Label: [N, 1]."""
    idx, label = ins["Indices"], ins["Label"]
    lab = label.reshape(-1, 1)
    correct = jnp.any(idx == lab, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    int_t = jax.dtypes.canonicalize_dtype(jnp.int64)
    total = jnp.asarray(idx.shape[0], int_t)
    return {
        "Accuracy": num_correct / idx.shape[0],
        "Correct": num_correct.astype(int_t),
        "Total": total,
    }


@register_op("auc", inputs=("Predict", "Label", "StatPos", "StatNeg"),
             outputs=("AUC", "StatPosOut", "StatNegOut"),
             attrs={"num_thresholds": 4095, "curve": "ROC"},
             differentiable=False,
             in_place={"StatPosOut": "StatPos", "StatNegOut": "StatNeg"})
def auc(ins, attrs):
    """Streaming AUC via threshold buckets (reference auc_op.cc)."""
    pred, label = ins["Predict"], ins["Label"]
    pos_hist, neg_hist = ins["StatPos"], ins["StatNeg"]
    n = attrs["num_thresholds"]
    p1 = pred[:, -1] if pred.ndim == 2 else pred.reshape(-1)
    bucket = jnp.clip((p1 * n).astype(jnp.int32), 0, n)
    lab = label.reshape(-1).astype(jnp.bool_)
    pos_hist = pos_hist.at[bucket].add(lab.astype(pos_hist.dtype))
    neg_hist = neg_hist.at[bucket].add((~lab).astype(neg_hist.dtype))
    # integrate over descending threshold
    pos_cum = jnp.cumsum(pos_hist[::-1])
    neg_cum = jnp.cumsum(neg_hist[::-1])
    tot_pos = pos_cum[-1]
    tot_neg = neg_cum[-1]
    # trapezoid on (fpr, tpr)
    tpr = pos_cum / jnp.maximum(tot_pos, 1)
    fpr = neg_cum / jnp.maximum(tot_neg, 1)
    auc_val = jnp.sum(
        (fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0
    ) + fpr[0] * tpr[0] / 2.0
    return {"AUC": auc_val, "StatPosOut": pos_hist, "StatNegOut": neg_hist}


@register_op("precision_recall",
             inputs=("MaxProbs", "Indices", "Labels", "StatesInfo"),
             outputs=("BatchMetrics", "AccumMetrics", "AccumStatesInfo"),
             optional=("StatesInfo",),
             attrs={"class_number": REQUIRED}, differentiable=False)
def precision_recall(ins, attrs):
    import jax

    c = attrs["class_number"]
    idx = ins["Indices"].reshape(-1).astype(jnp.int32)
    lab = ins["Labels"].reshape(-1).astype(jnp.int32)
    tp = jax.ops.segment_sum(
        (idx == lab).astype(jnp.float64), lab, num_segments=c
    )
    pred_cnt = jax.ops.segment_sum(
        jnp.ones_like(idx, jnp.float64), idx, num_segments=c
    )
    lab_cnt = jax.ops.segment_sum(
        jnp.ones_like(lab, jnp.float64), lab, num_segments=c
    )
    fp = pred_cnt - tp
    fn = lab_cnt - tp
    states = jnp.stack([tp, fp, fn, jnp.zeros_like(tp)], axis=1)
    if "StatesInfo" in ins:
        states = states + ins["StatesInfo"]
    def metrics(tp, fp, fn):
        precision = jnp.where(tp + fp > 0, tp / (tp + fp), 0.0)
        recall = jnp.where(tp + fn > 0, tp / (tp + fn), 0.0)
        f1 = jnp.where(precision + recall > 0,
                       2 * precision * recall / (precision + recall), 0.0)
        return jnp.asarray([jnp.mean(precision), jnp.mean(recall),
                            jnp.mean(f1),
                            jnp.sum(tp) / jnp.maximum(
                                jnp.sum(tp + fp), 1.0),
                            jnp.sum(tp) / jnp.maximum(
                                jnp.sum(tp + fn), 1.0),
                            0.0])
    batch = metrics(tp, fp, fn)
    acc = metrics(states[:, 0], states[:, 1], states[:, 2])
    return {"BatchMetrics": batch, "AccumMetrics": acc,
            "AccumStatesInfo": states}


# ---------------------------------------------------------------------------
# chunk_eval (reference operators/chunk_eval_op.h: GetSegments/ChunkBegin/
# ChunkEnd).  Chunk decoding is data-dependent sequential control flow, so it
# runs on host (host_only) like the reference's CPU-only kernel; padded
# [B, T](+SeqLength) replaces the LoD input.
# ---------------------------------------------------------------------------

_CHUNK_SCHEMES = {
    # scheme: (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_segments(seq, scheme, num_chunk_types):
    """Decode one tag sequence into [(begin, end, type)] chunks
    (reference chunk_eval_op.h:41 GetSegments)."""
    num_tag, t_begin, t_inside, t_end, t_single = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types

    def chunk_end(ptag, ptype, tag, typ):
        # reference chunk_eval_op.h:83 ChunkEnd
        if ptype == other:
            return False
        if typ == other or typ != ptype:
            return True
        return ptag in (t_end, t_single) or (
            ptag in (t_begin, t_inside) and tag in (t_begin, t_single))

    def chunk_begin(ptag, ptype, tag, typ):
        # reference chunk_eval_op.h:96 ChunkBegin
        if ptype == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptype:
            return True
        if tag == t_begin or tag == t_single:
            return True
        if tag in (t_inside, t_end):
            return ptag in (t_end, t_single)
        return False

    segments = []
    tag = typ = -1
    in_chunk = False
    start = 0
    for i, lab in enumerate(seq):
        ptag, ptype = tag, typ
        lab = int(lab)
        tag = lab % num_tag
        typ = lab // num_tag
        if in_chunk and chunk_end(ptag, ptype, tag, typ):
            segments.append((start, i - 1, ptype))
            in_chunk = False
        if chunk_begin(ptag, ptype, tag, typ):
            start = i
            in_chunk = True
    if in_chunk:
        segments.append((start, len(seq) - 1, typ))
    return segments


@register_op("chunk_eval",
             inputs=("Inference", "Label", "SeqLength"),
             outputs=("Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"),
             optional=("SeqLength",),
             attrs={"num_chunk_types": REQUIRED, "chunk_scheme": "IOB",
                    "excluded_chunk_types": []},
             differentiable=False, host_only=True)
def chunk_eval(ins, attrs):
    """Precision/recall/F1 of chunk detection over IOB/IOE/IOBES/plain
    tagging (reference chunk_eval_op.h:109 Compute)."""
    import numpy as np

    scheme = attrs["chunk_scheme"]
    if scheme not in _CHUNK_SCHEMES:
        raise ValueError(f"Unknown chunk scheme {scheme!r}")
    nct = int(attrs["num_chunk_types"])
    excluded = set(attrs.get("excluded_chunk_types") or [])
    inf = np.asarray(ins["Inference"]).reshape(
        np.asarray(ins["Inference"]).shape[0], -1)
    lab = np.asarray(ins["Label"]).reshape(inf.shape[0], -1)
    seq_len = ins.get("SeqLength")
    lens = (np.full((inf.shape[0],), inf.shape[1], np.int64)
            if seq_len is None else np.asarray(seq_len).reshape(-1))
    n_inf = n_lab = n_correct = 0
    for b in range(inf.shape[0]):
        L = int(lens[b])
        inf_seg = [s for s in _chunk_segments(inf[b, :L], scheme, nct)
                   if s[2] not in excluded]
        lab_seg = [s for s in _chunk_segments(lab[b, :L], scheme, nct)
                   if s[2] not in excluded]
        n_inf += len(inf_seg)
        n_lab += len(lab_seg)
        n_correct += len(set(inf_seg) & set(lab_seg))
    precision = n_correct / n_inf if n_inf else 0.0
    recall = n_correct / n_lab if n_lab else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if n_correct else 0.0)
    return {"Precision": np.asarray([precision], np.float32),
            "Recall": np.asarray([recall], np.float32),
            "F1-Score": np.asarray([f1], np.float32),
            "NumInferChunks": np.asarray([n_inf], np.int64),
            "NumLabelChunks": np.asarray([n_lab], np.int64),
            "NumCorrectChunks": np.asarray([n_correct], np.int64)}


@register_op("positive_negative_pair",
             inputs=("Score", "Label", "QueryID",
                     "AccumulatePositivePair", "AccumulateNegativePair",
                     "AccumulateNeutralPair", "Weight"),
             outputs=("PositivePair", "NegativePair", "NeutralPair"),
             optional=("AccumulatePositivePair",
                       "AccumulateNegativePair",
                       "AccumulateNeutralPair", "Weight"),
             attrs={"column": -1},
             differentiable=False, host_only=True)
def positive_negative_pair(ins, attrs):
    """positive_negative_pair_op.h: per-query ranking pair counts —
    for every doc pair with different labels, score order agreeing with
    label order counts positive, disagreeing negative, ties neutral;
    pair weight = mean of the two doc weights.  Host metric op (hash-map
    grouping) like the reference's CPU-only kernel."""
    import numpy as np

    score = np.asarray(ins["Score"])
    col = int(attrs.get("column", -1))
    if score.ndim > 1:
        width = score.shape[1]
        if col < 0:
            col += width
        score = score[:, col]
    score = score.reshape(-1)
    label = np.asarray(ins["Label"]).reshape(-1)
    query = np.asarray(ins["QueryID"]).reshape(-1)
    weight = ins.get("Weight")
    weight = (np.ones_like(score) if weight is None
              else np.asarray(weight).reshape(-1))
    pos = neg = neu = 0.0
    acc = ins.get("AccumulatePositivePair")
    if acc is not None:
        pos = float(np.asarray(acc).ravel()[0])
        neg = float(np.asarray(
            ins["AccumulateNegativePair"]).ravel()[0])
        neu = float(np.asarray(
            ins["AccumulateNeutralPair"]).ravel()[0])
    by_query = {}
    for i in range(score.shape[0]):
        by_query.setdefault(int(query[i]), []).append(
            (float(score[i]), float(label[i]), float(weight[i])))
    for docs in by_query.values():
        for a in range(len(docs)):
            for b in range(a + 1, len(docs)):
                s1, l1, w1 = docs[a]
                s2, l2, w2 = docs[b]
                if l1 == l2:
                    continue
                w = 0.5 * (w1 + w2)
                # reference parity (positive_negative_pair_op.h:94-99):
                # a tie adds to neutral AND falls through the ternary
                # into negative — deliberately no elif here
                if s1 == s2:
                    neu += w
                if (s1 - s2) * (l1 - l2) > 0.0:
                    pos += w
                else:
                    neg += w
    return {"PositivePair": np.asarray([pos], np.float32),
            "NegativePair": np.asarray([neg], np.float32),
            "NeutralPair": np.asarray([neu], np.float32)}
