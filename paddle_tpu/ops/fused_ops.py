"""Fused op family (reference /root/reference/paddle/fluid/operators/fused/).

On TPU these exist for *program-level parity*: XLA fuses elementwise
chains into matmuls on its own, so each op here is simply the
mathematical composition, registered so reference programs (and the
inference fusion passes) can target the same op types:
  fused_elemwise_activation_op.cc (binary/unary compounds),
  fused_embedding_seq_pool_op.cc, fused_embedding_fc_lstm_op.cc,
  fusion_seqconv_eltadd_relu_op.cc, fusion_seqpool_concat_op.cc,
  fusion_repeated_fc_relu_op.cc, fusion_squared_mat_sub_op.cc,
  fusion_transpose_flatten_concat_op.cc, conv2d_fusion_op.cc,
  fusion_seqexpand_concat_fc_op.cc.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import REQUIRED, get_op_def, register_op

_UNARY = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "identity": lambda x: x,
}
_BINARY = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
}


def _functor(name, attrs):
    if name == "scale":
        s = float(attrs.get("scale", 1.0))
        return lambda x: x * s
    if name in _UNARY:
        return _UNARY[name]
    return None


@register_op("fused_elemwise_activation", inputs=("X", "Y"),
             outputs=("Out", "IntermediateOut"),
             attrs={"functor_list": REQUIRED, "scale": 1.0, "axis": -1,
                    "save_intermediate_out": False})
def fused_elemwise_activation(ins, attrs):
    """fused_elemwise_activation_op.h: functor_list of two.
    {unary, binary} -> out = unary(binary(x, y))  (unary compound);
    {binary, unary} -> out = binary(x, unary(y))  (binary compound)."""
    x, y = ins["X"], ins["Y"]
    f0, f1 = list(attrs["functor_list"])
    if f0 in _BINARY:       # binary compound
        inter = _functor(f1, attrs)(y)
        out = _BINARY[f0](x, inter)
    else:                   # unary compound
        inter = _BINARY[f1](x, y)
        out = _functor(f0, attrs)(inter)
    return {"Out": out, "IntermediateOut": inter}


@register_op("fused_embedding_seq_pool", inputs=("W", "Ids"),
             outputs=("Out",),
             attrs={"combiner": "sum", "is_sparse": False,
                    "padding_idx": -1})
def fused_embedding_seq_pool(ins, attrs):
    """fused_embedding_seq_pool_op.cc: embedding lookup + sum pool over
    the sequence axis; Ids padded [B, T, 1] with padding_idx rows
    contributing zero (the LoD re-spec)."""
    w, ids = ins["W"], ins["Ids"]
    b = ids.shape[0]
    flat = ids.reshape(b, -1).astype(jnp.int32)
    emb = w[flat]                          # [B, T, D]
    pad = int(attrs["padding_idx"])
    if pad >= 0:
        emb = emb * (flat != pad)[..., None].astype(emb.dtype)
    return {"Out": emb.sum(axis=1)}


@register_op("fused_embedding_fc_lstm",
             inputs=("Ids", "Embeddings", "WeightH", "Bias", "H0", "C0"),
             outputs=("Hidden", "Cell"),
             optional=("H0", "C0"),
             attrs={"use_peepholes": False, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"})
def fused_embedding_fc_lstm(ins, attrs):
    """fused_embedding_fc_lstm_op.cc: Embeddings is the PRE-PROJECTED
    table (V x 4D, embedding folded into the x->gates fc), so lookup
    directly yields gate pre-activations; then the lstm scan."""
    ids = ins["Ids"]
    b = ids.shape[0]
    flat = ids.reshape(b, -1).astype(jnp.int32)
    gates = ins["Embeddings"][flat]        # [B, T, 4D]
    sub = {"Input": gates, "Weight": ins["WeightH"],
           "Bias": ins["Bias"]}
    for k in ("H0", "C0"):
        if ins.get(k) is not None:
            sub[k] = ins[k]
    lstm = get_op_def("lstm")
    return lstm.compute(sub, lstm.canonical_attrs(
        {k: attrs[k] for k in
         ("use_peepholes", "is_reverse", "gate_activation",
          "cell_activation", "candidate_activation")}))


@register_op("fusion_seqconv_eltadd_relu",
             inputs=("X", "Filter", "Bias"), outputs=("Out",),
             attrs={"contextLength": REQUIRED, "contextStart": 0,
                    "contextStride": 1})
def fusion_seqconv_eltadd_relu(ins, attrs):
    """fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias + relu on
    padded [B, T, D]; Filter [ctx*D, M]."""
    x, f, bias = ins["X"], ins["Filter"], ins["Bias"]
    b, t, d = x.shape
    ctx = int(attrs["contextLength"])
    start = int(attrs["contextStart"])
    cols = []
    for j in range(ctx):
        off = start + j
        if off < 0:
            sl = jnp.pad(x[:, :max(t + off, 0)],
                         ((0, 0), (min(-off, t), 0), (0, 0)))
        else:
            sl = jnp.pad(x[:, off:], ((0, 0), (0, min(off, t)), (0, 0)))
        cols.append(sl)
    col = jnp.concatenate(cols, axis=2)     # [B, T, ctx*D]
    out = col @ f + bias.reshape(1, 1, -1)
    return {"Out": jax.nn.relu(out)}


@register_op("fusion_seqpool_concat", inputs=("X",), outputs=("Out",),
             duplicable=("X",),
             attrs={"pooltype": "SUM", "axis": 1})
def fusion_seqpool_concat(ins, attrs):
    """fusion_seqpool_concat_op.cc: pool each padded [B, T, D_i] over T
    then concat on features."""
    outs = []
    for x in ins["X"]:
        if attrs["pooltype"] == "SUM":
            outs.append(x.sum(axis=1))
        elif attrs["pooltype"] == "AVERAGE":
            outs.append(x.mean(axis=1))
        else:  # SQRT
            outs.append(x.sum(axis=1) / np.sqrt(x.shape[1]))
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op("fusion_repeated_fc_relu", inputs=("X", "W", "Bias"),
             outputs=("Out",), duplicable=("W", "Bias"))
def fusion_repeated_fc_relu(ins, attrs):
    """fusion_repeated_fc_relu_op.cc: x -> relu(fc) repeated."""
    x = ins["X"]
    for w, b in zip(ins["W"], ins["Bias"]):
        x = jax.nn.relu(x @ w + b.reshape(1, -1))
    return {"Out": x}


@register_op("fusion_squared_mat_sub", inputs=("X", "Y"),
             outputs=("SquaredX", "SquaredY", "SquaredXY", "Out"),
             attrs={"scalar": 1.0})
def fusion_squared_mat_sub(ins, attrs):
    """fusion_squared_mat_sub_op.cc: scalar * ((XY)^2 - X^2 Y^2)."""
    x, y = ins["X"], ins["Y"]
    sx, sy = x * x, y * y
    sxy = (x @ y) ** 2
    return {"SquaredX": sx, "SquaredY": sy, "SquaredXY": sxy,
            "Out": attrs["scalar"] * (sxy - sx @ sy)}


@register_op("fusion_transpose_flatten_concat", inputs=("X",),
             outputs=("Out",), duplicable=("X",),
             attrs={"trans_axis": REQUIRED, "flatten_axis": REQUIRED,
                    "concat_axis": REQUIRED})
def fusion_transpose_flatten_concat(ins, attrs):
    """fusion_transpose_flatten_concat_op.cc."""
    ta = [int(a) for a in attrs["trans_axis"]]
    fa = int(attrs["flatten_axis"])
    outs = []
    for x in ins["X"]:
        x = jnp.transpose(x, ta)
        lead = int(np.prod(x.shape[:fa])) if fa else 1
        outs.append(x.reshape(lead, -1))
    return {"Out": jnp.concatenate(outs, axis=int(attrs["concat_axis"]))}


@register_op("conv2d_fusion",
             inputs=("Input", "Filter", "Bias", "ResidualData"),
             outputs=("Output",), optional=("Bias", "ResidualData"),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "activation": "relu", "data_format": "NCHW"})
def conv2d_fusion(ins, attrs):
    """conv2d_fusion_op.cc: conv + bias + (residual add) + act."""
    conv = get_op_def("conv2d")
    out = conv.compute(
        {"Input": ins["Input"], "Filter": ins["Filter"]},
        conv.canonical_attrs({k: attrs[k] for k in
                              ("strides", "paddings", "dilations",
                               "groups", "data_format")}))["Output"]
    if ins.get("Bias") is not None:
        out = out + ins["Bias"].reshape(1, -1, 1, 1)
    if ins.get("ResidualData") is not None:
        out = out + ins["ResidualData"]
    act = _UNARY.get(attrs["activation"], lambda x: x)
    return {"Output": act(out)}


@register_op("fusion_seqexpand_concat_fc",
             inputs=("X", "FCWeight", "FCBias"), outputs=("Out",),
             duplicable=("X",), optional=("FCBias",),
             attrs={"fc_activation": "relu"})
def fusion_seqexpand_concat_fc(ins, attrs):
    """fusion_seqexpand_concat_fc_op.cc: X[0] is [B, T, D0]; the rest
    are [B, D_i] broadcast (seq-expanded) over T; concat on features,
    then fc + activation."""
    xs = ins["X"]
    base = xs[0]
    b, t, _ = base.shape
    feats = [base] + [
        jnp.broadcast_to(x[:, None, :], (b, t, x.shape[-1]))
        for x in xs[1:]]
    cat = jnp.concatenate(feats, axis=2)
    out = cat @ ins["FCWeight"]
    if ins.get("FCBias") is not None:
        out = out + ins["FCBias"].reshape(1, 1, -1)
    return {"Out": _UNARY.get(attrs["fc_activation"],
                              lambda x: x)(out)}


@register_op("conv2d_inception_fusion",
             inputs=("Input", "Filter", "Bias"), outputs=("Output",),
             duplicable=("Filter", "Bias"),
             attrs={"pooling_type": "max", "exclude_padding": True,
                    "activation": "relu"})
def conv2d_inception_fusion(ins, attrs):
    """conv2d_inception_fusion_op.cc: 4-branch inception block —
    1x1 conv | 1x1->3x3 | 1x1->3x3->3x3 | pool->1x1, channel concat.
    Filter/Bias lists follow the reference's branch order."""
    x = ins["Input"]
    fs, bs = ins["Filter"], ins["Bias"]
    act = _UNARY.get(attrs["activation"], lambda v: v)
    conv = get_op_def("conv2d")

    def c(inp, w, b, pad):
        o = conv.compute(
            {"Input": inp, "Filter": w},
            conv.canonical_attrs({"paddings": [pad, pad]}))["Output"]
        return act(o + b.reshape(1, -1, 1, 1))

    branches = []
    branches.append(c(x, fs[0], bs[0], 0))
    b1 = c(x, fs[1], bs[1], 0)
    branches.append(c(b1, fs[2], bs[2], 1))
    b2 = c(x, fs[3], bs[3], 0)
    b2 = c(b2, fs[4], bs[4], 1)
    branches.append(c(b2, fs[5], bs[5], 1))
    pooled = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 1, 1),
        ((0, 0), (0, 0), (1, 1), (1, 1)))
    branches.append(c(pooled, fs[6], bs[6], 0))
    return {"Output": jnp.concatenate(branches, axis=1)}


@register_op("fc", inputs=("Input", "W", "Bias"), outputs=("Out",),
             optional=("Bias",),
             attrs={"in_num_col_dims": 1, "activation_type": ""})
def fc_fused(ins, attrs):
    """fc_op.cc (the fused FC the fc_fuse_pass produces): flatten ->
    matmul -> bias -> act in one op.  layers.fc builds mul+add (like
    the reference python layer); this op is the fusion target."""
    x, w = ins["Input"], ins["W"]
    k = int(attrs["in_num_col_dims"])
    lead = x.shape[:k]
    xm = x.reshape((int(np.prod(lead)), -1))
    out = xm @ w
    if ins.get("Bias") is not None:
        out = out + ins["Bias"].reshape(1, -1)
    act = attrs["activation_type"]
    if act == "relu":
        out = jax.nn.relu(out)
    elif act:
        out = getattr(jax.nn, act)(out)
    return {"Out": out.reshape(lead + (w.shape[1],))}


@register_op("attention_lstm",
             inputs=("X", "C0", "H0", "AttentionWeight", "AttentionBias",
                     "AttentionScalar", "AttentionScalarBias",
                     "LSTMWeight", "LSTMBias"),
             outputs=("Hidden", "Cell"),
             optional=("H0", "AttentionBias", "AttentionScalar",
                       "AttentionScalarBias"),
             attrs={"gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"})
def attention_lstm(ins, attrs):
    """attention_lstm_op.cc: at each step, attention over the input
    sequence conditioned on the previous cell state produces the lstm
    input.  X [B, T, M]; AttentionWeight [M+D, 1]; LSTMWeight
    [M+D, 4D]; LSTMBias [1, 4D]; gate order c,i,f,o like the fused
    lstm."""
    x = ins["X"]
    c0 = ins["C0"]
    h0 = ins.get("H0")
    b, t, m = x.shape
    d = c0.shape[-1]
    aw = ins["AttentionWeight"]
    ab = ins.get("AttentionBias")
    asc = ins.get("AttentionScalar")
    asb = ins.get("AttentionScalarBias")
    lw, lb = ins["LSTMWeight"], ins["LSTMBias"]
    act = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
           "relu": jax.nn.relu, "identity": lambda v: v}
    g_act = act[attrs["gate_activation"]]
    c_act = act[attrs["cell_activation"]]
    cand = act[attrs["candidate_activation"]]
    if h0 is None:
        h0 = jnp.zeros_like(c0)

    def step(carry, _t):
        h, c = carry
        # attention: score each time step given current cell state
        cexp = jnp.broadcast_to(c[:, None, :], (b, t, d))
        att_in = jnp.concatenate([x, cexp], axis=-1)      # [B,T,M+D]
        e = att_in @ aw                                    # [B,T,1]
        if ab is not None:
            e = e + ab.reshape(1, 1, -1)
        if asc is not None:
            e = e * asc.reshape(())
        if asb is not None:
            e = e + asb.reshape(())
        a = jax.nn.softmax(e[..., 0], axis=-1)             # [B,T]
        ctx_vec = jnp.einsum("bt,btm->bm", a, x)           # [B,M]
        z = jnp.concatenate([ctx_vec, h], axis=-1) @ lw + lb.reshape(-1)
        zc, zi, zf, zo = jnp.split(z, 4, axis=-1)
        c_new = g_act(zi) * cand(zc) + g_act(zf) * c
        h_new = g_act(zo) * c_act(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), jnp.arange(t))
    return {"Hidden": jnp.transpose(hs, (1, 0, 2)),
            "Cell": jnp.transpose(cs, (1, 0, 2))}


@register_op("alloc_continuous_space",
             inputs=("Input",), outputs=("Output", "FusedOutput"),
             duplicable=("Input", "Output"),
             attrs={"copy_data": True, "set_constant": False,
                    "constant": 0.0},
             differentiable=False)
def alloc_continuous_space(ins, attrs):
    """alloc_continuous_space_for_grad_pass / coalesce-grads buffer op:
    flatten+concat the inputs into one fused buffer (XLA owns aliasing;
    functionally the outputs are the inputs, the fused view is the
    concat)."""
    xs = ins["Input"]
    flat = [jnp.ravel(x) for x in xs]
    fused = jnp.concatenate(flat) if flat else jnp.zeros((0,))
    if attrs["set_constant"]:
        fused = jnp.full_like(fused, attrs["constant"])
        outs = []
        off = 0
        for x in xs:
            n = int(np.prod(x.shape))
            outs.append(fused[off:off + n].reshape(x.shape))
            off += n
        return {"Output": outs, "FusedOutput": fused}
    return {"Output": list(xs), "FusedOutput": fused}
