"""Parameter-server ops: send / recv / barriers / listen_and_serv.

Reference parity (SURVEY.md §2.4 DP strategy C):
  - send/recv/send_barrier/fetch_barrier ops:
    /root/reference/paddle/fluid/operators/distributed_ops/send_op.cc,
    recv_op.cc, send_barrier_op.cc, fetch_barrier_op.cc
  - listen_and_serv event loop: listen_and_serv_op.cc:109 RunSyncLoop
    (barrier -> run optimize blocks -> barrier), :225 RunAsyncLoop
    (grad name -> block, applied on arrival)
  - row-sliced send: parameter_send.cc / slice_variable

TPU-first: these are host control-plane ops over the socket RPC layer
(distributed/rpc.py); the dense compute inside each optimize block still
runs through the normal op registry (JAX on the pserver host).  Values
crossing the wire are numpy arrays.
"""

from __future__ import annotations

import threading

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.executor import register_special_op
from paddle_tpu.core.registry import REQUIRED, register_op
from paddle_tpu.distributed.rpc import (global_rpc_client,
                                         make_rpc_server)


def _structural(ins, attrs):  # pragma: no cover
    raise RuntimeError("PS op must run via the executor (host op)")


# registry entries so append_op validates attrs + programs serialize
register_op("send", inputs=("X",), outputs=(),
            attrs={"epmap": [], "section_names": [], "sections": [],
                   "trainer_idx": -1},
            differentiable=False, host_only=True)(_structural)
register_op("recv", inputs=(), outputs=("Out",),
            attrs={"epmap": [], "section_names": [], "sections": [],
                   "trainer_idx": -1},
            differentiable=False, host_only=True)(_structural)
register_op("send_barrier", inputs=(), outputs=(),
            attrs={"endpoints": [], "peer_id": ""},
            differentiable=False, host_only=True)(_structural)
register_op("fetch_barrier", inputs=(), outputs=(),
            attrs={"endpoints": [], "peer_id": ""},
            differentiable=False, host_only=True)(_structural)
register_op("listen_and_serv", inputs=(), outputs=(),
            attrs={"endpoint": REQUIRED, "Fanin": 1, "sync_mode": True,
                   "grad_blocks": [], "lr_names": [],
                   "sparse_grad_blocks": [],
                   "dc_pairs": [],
                   "heartbeat_timeout": 10.0,
                   "barrier_timeout": 0.0},
            differentiable=False, host_only=True)(_structural)
register_op("ps_sync_init", inputs=("X",), outputs=(),
            duplicable=("X",), optional=("X",),
            attrs={"endpoints": [], "push_plan": [], "is_pusher": False},
            differentiable=False, host_only=True)(_structural)
register_op("checkpoint_notify", inputs=(), outputs=(),
            attrs={"endpoints": [], "dirname": ""},
            differentiable=False, host_only=True)(_structural)
register_op("heartbeat_start", inputs=(), outputs=(),
            attrs={"endpoints": [], "peer_id": REQUIRED,
                   "interval": 1.0},
            differentiable=False, host_only=True)(_structural)
register_op("prefetch", inputs=("Ids",), outputs=("Out",),
            attrs={"epmap": [], "table_names": [], "sections": [],
                   "padding_idx": -1, "emb_dim": REQUIRED},
            differentiable=False, host_only=True)(_structural)
register_op("send_sparse_grad", inputs=("Ids", "Grad"), outputs=(),
            attrs={"epmap": [], "section_names": [], "sections": [],
                   "padding_idx": -1},
            differentiable=False, host_only=True)(_structural)


@register_op("sparse_sgd",
             inputs=("Param", "Rows", "Grad", "LearningRate"),
             outputs=("ParamOut",), differentiable=False,
             in_place={"Param": "ParamOut"})
def sparse_sgd(ins, attrs):
    """Row-wise SGD on a sharded lookup table (reference
    operators/optimizers/sgd_op.h SelectedRows branch: update only the
    touched rows).  Duplicate rows accumulate via scatter-add, matching
    the SelectedRows sum semantics."""
    w, rows, g = ins["Param"], ins["Rows"], ins["Grad"]
    lr = jnp.reshape(ins["LearningRate"], ())
    if rows.shape[0] == 0:
        return {"ParamOut": w}
    return {"ParamOut": w.at[rows.astype(jnp.int32)].add(
        (-lr * g).astype(w.dtype))}


def _np(v):
    return np.asarray(v)


@register_special_op("heartbeat_start")
def heartbeat_start_op(op, block, scope, ctx):
    """Idempotent: spawn one HeartbeatSender daemon per (endpoint,
    peer_id); the trainer program carries this op at step 0 position so
    the first exe.run announces the trainer to every pserver's
    HeartbeatMonitor (the survivor-continue counterpart of
    listen_and_serv's effective_fanin).  RPCClient.send_complete stops
    the senders again, so completed jobs don't leak beat threads."""
    from paddle_tpu.distributed.rpc import start_shared_heartbeat

    peer = op.attrs["peer_id"]
    for ep in op.attrs["endpoints"]:
        start_shared_heartbeat(ep, peer,
                               interval=float(
                                   op.attrs.get("interval", 1.0)))


def _tid(op):
    """trainer_idx attr -> int, or None when unset (-1 sentinel)."""
    tid = op.attrs.get("trainer_idx", -1)
    return None if tid is None or int(tid) < 0 else int(tid)


@register_special_op("send")
def send_op(op, block, scope, ctx):
    """Row-sliced send of a var's sections to their pservers
    (reference parameter_send.cc)."""
    client = global_rpc_client()
    tid = _tid(op)
    x = _np(scope.find_var(op.inputs["X"][0]).get())
    for ep, name, (s, e) in zip(op.attrs["epmap"],
                                op.attrs["section_names"],
                                op.attrs["sections"]):
        sec = x if s == 0 and e == -1 else x[s:e]
        client.send_var(ep, name, np.ascontiguousarray(sec),
                        trainer_idx=tid)


@register_special_op("recv")
def recv_op(op, block, scope, ctx):
    client = global_rpc_client()
    tid = _tid(op)
    parts = []
    for ep, name, _sec in zip(op.attrs["epmap"],
                              op.attrs["section_names"],
                              op.attrs["sections"]):
        parts.append(client.get_var(ep, name, trainer_idx=tid))
    val = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
    scope.var(op.outputs["Out"][0]).set(jnp.asarray(val))


@register_special_op("send_barrier")
def send_barrier_op(op, block, scope, ctx):
    client = global_rpc_client()
    peer = op.attrs.get("peer_id") or None
    for ep in op.attrs["endpoints"]:
        client.send_barrier(ep, peer_id=peer)


@register_special_op("fetch_barrier")
def fetch_barrier_op(op, block, scope, ctx):
    client = global_rpc_client()
    peer = op.attrs.get("peer_id") or None
    for ep in op.attrs["endpoints"]:
        client.fetch_barrier(ep, peer_id=peer)


@register_special_op("prefetch")
def prefetch_op(op, block, scope, ctx):
    """Distributed-lookup-table forward: split ids by table section, ask
    each owning pserver for its rows, reassemble in id order (reference
    operators/distributed/parameter_prefetch.cc:1 prefetch + split_ids +
    merge_ids)."""
    client = global_rpc_client()
    ids = _np(scope.find_var(op.inputs["Ids"][0]).get())
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    flat = (ids[..., 0] if squeeze else ids).reshape(-1).astype(np.int64)
    emb_dim = int(op.attrs["emb_dim"])
    out_name = op.outputs["Out"][0]
    dtype = np.dtype(block.var(out_name).dtype) \
        if block.has_var(out_name) and block.var(out_name).dtype \
        else np.dtype(np.float32)
    out = np.zeros((flat.shape[0], emb_dim), dtype)
    for ep, tname, (s, e) in zip(op.attrs["epmap"],
                                 op.attrs["table_names"],
                                 op.attrs["sections"]):
        mask = (flat >= s) & (flat < e)
        if not mask.any():
            continue
        local = flat[mask] - s
        rows = client.call(ep, "prefetch_rows",
                           (tname, np.ascontiguousarray(local)))
        out[mask] = rows
    pad = int(op.attrs["padding_idx"])
    if pad >= 0:
        out[flat == pad] = 0.0
    shape = (ids.shape[:-1] if squeeze else ids.shape) + (emb_dim,)
    scope.var(out_name).set(jnp.asarray(out.reshape(shape)))


@register_special_op("send_sparse_grad")
def send_sparse_grad_op(op, block, scope, ctx):
    """Distributed-lookup-table backward: push (rows, grad-rows) of the
    table gradient to the owning pservers (reference split_ids_op.cc +
    the SelectedRows send path of parameter_send.cc)."""
    client = global_rpc_client()
    ids = _np(scope.find_var(op.inputs["Ids"][0]).get())
    grad = _np(scope.find_var(op.inputs["Grad"][0]).get())
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    flat = (ids[..., 0] if squeeze else ids).reshape(-1).astype(np.int64)
    g = grad.reshape(flat.shape[0], -1)
    pad = int(op.attrs["padding_idx"])
    if pad >= 0:
        keep = flat != pad
        flat, g = flat[keep], g[keep]
    for ep, gsec, (s, e) in zip(op.attrs["epmap"],
                                op.attrs["section_names"],
                                op.attrs["sections"]):
        mask = (flat >= s) & (flat < e)
        if not mask.any():
            continue  # sync merge divides by fanin, so skipping is safe
        local = flat[mask] - s
        client.call(ep, "send_sparse",
                    (gsec, np.ascontiguousarray(local),
                     np.ascontiguousarray(g[mask])))


@register_special_op("checkpoint_notify")
def checkpoint_notify_op(op, block, scope, ctx):
    """Trainer asks every pserver to checkpoint its shards (reference
    checkpoint_notify_op.cc -> pserver checkpoint block)."""
    client = global_rpc_client()
    for ep in op.attrs["endpoints"]:
        client.call(ep, "checkpoint_notify", op.attrs["dirname"])


@register_special_op("ps_sync_init")
def ps_sync_init_op(op, block, scope, ctx):
    """Initial-parameter sync: trainer 0 pushes its initialized param
    sections to the pservers and signals init-done; other trainers wait
    (gives every trainer/pserver bit-identical initial params — the
    reference gets this by initializing on the pserver and having all
    trainers recv before step 1)."""
    client = global_rpc_client()
    if op.attrs["is_pusher"]:
        for var_name, ep, sec_name, s, e in op.attrs["push_plan"]:
            x = _np(scope.find_var(var_name).get())
            sec = x if s == 0 and e == -1 else x[s:e]
            client.send_var(ep, sec_name, np.ascontiguousarray(sec))
        for ep in op.attrs["endpoints"]:
            client.call(ep, "init_done")
    else:
        for ep in op.attrs["endpoints"]:
            client.call(ep, "init_wait")


@register_special_op("listen_and_serv")
def listen_and_serv_op(op, block, scope, ctx):
    """Pserver event loop.  Blocks until every trainer sent Complete.

    sync mode  (reference RunSyncLoop,  listen_and_serv_op.cc:109):
      accumulate grads per name; on the send barrier, one handler thread
      averages each grad's sections and runs its optimize block; the
      fetch barrier closes the round.
    async mode (reference RunAsyncLoop, :225): each arriving grad runs
      its block immediately under the update lock (Hogwild-ish, the
      Downpour staleness model).
    """
    attrs = op.attrs
    fanin = int(attrs["Fanin"])
    sync = bool(attrs["sync_mode"])
    grad_blocks = [(g, int(b)) for g, b in attrs["grad_blocks"]]
    grad_block_map = dict(grad_blocks)
    sparse_blocks = [(g, int(b))
                     for g, b in attrs.get("sparse_grad_blocks", [])]
    sparse_block_map = dict(sparse_blocks)

    server = make_rpc_server(attrs["endpoint"])
    buffers: dict = {}
    sparse_buffers: dict = {}
    lock = threading.Lock()
    stop = threading.Event()
    init_evt = threading.Event()
    ncomplete = [0]

    # DC-ASGD (reference _append_dc_asgd_ops + RequestGetHandler's
    # dc_asgd branch): per-trainer param backups, snapshotted when the
    # trainer pulls; primed lazily so a pre-first-pull gradient gets
    # zero correction instead of w - 0
    dc_pairs = {g: p for g, p in attrs.get("dc_pairs", [])}
    dc_secs = set(dc_pairs.values())
    dc_primed: set = set()

    def _dc_prime(sec, tid):
        if (sec, tid) in dc_primed:
            return
        pv = scope.find_var(sec)
        if pv is not None and pv.get() is not None:
            scope.var(f"{sec}.bak.{tid}").set(pv.get())
            # marked primed only on a REAL snapshot: an early grad
            # before the init push lands must retry, or the backup
            # stays zero and g + g*g*(w - 0) overcorrects forever
            dc_primed.add((sec, tid))

    def _apply_sparse(gsec, rows, vals):
        scope.var(gsec + ".rows").set(jnp.asarray(rows))
        scope.var(gsec + ".values").set(jnp.asarray(vals))
        ctx.run_block(sparse_block_map[gsec], scope)

    def on_send_var(payload):
        name, val = payload[0], payload[1]
        tid = payload[2] if len(payload) > 2 else None
        peer = None if tid is None else f"trainer{int(tid)}"
        with lock:
            if sync and name in grad_block_map:
                # tagged with the sender so a peer fenced between push
                # and merge can be excluded from the round
                buffers.setdefault(name, []).append((peer, val))
            else:
                scope.var(name).set(jnp.asarray(val))
                if name in grad_block_map:   # async: apply on arrival
                    if name in dc_pairs:
                        k = int(tid) if tid is not None else 0
                        _dc_prime(dc_pairs[name], k)
                        scope.var("@TRAINER_ID@").set(
                            jnp.asarray([k], jnp.int32))
                    ctx.run_block(grad_block_map[name], scope)

    def _fenced_peer(peer):
        # a fenced-but-still-alive trainer must not participate: it was
        # excluded from effective_fanin, so letting it join would
        # release barriers early and desync the true survivors
        if peer is None:
            return False
        with live_lock:
            return str(peer) in fenced

    def _alive(peer_str):
        with live_lock:
            return peer_str not in fenced

    def _reject_fenced(peer):
        if _fenced_peer(peer):
            # loud: a zombie trainer must crash, not free-run
            # unsynchronized while its stale grads contaminate rounds
            raise RuntimeError(
                f"trainer '{peer}' was declared dead (missed "
                "heartbeats) and is fenced from this cluster")

    # barrier deadline: 0.0 -> env PADDLE_TPU_BARRIER_TIMEOUT (600s
    # default) — a wedged round raises a BarrierTimeoutError naming the
    # barrier + waiters at every party instead of hanging the job
    barrier_timeout = float(attrs.get("barrier_timeout", 0.0)) or None

    def on_send_barrier(peer):
        if not sync:
            return
        _reject_fenced(peer)
        lead = server.barrier_dynamic("send", effective_fanin,
                                      peer=peer, alive_fn=_alive,
                                      timeout=barrier_timeout)
        if lead == 0:
            with lock:
                for gname, bidx in grad_blocks:
                    vals = buffers.pop(gname, None)
                    if vals:  # drop entries a fenced peer pushed
                        vals = [v for p, v in vals
                                if p is None or _alive(p)]
                    if not vals:
                        continue
                    merged = vals[0] if len(vals) == 1 else \
                        np.mean(np.stack(vals), axis=0)
                    scope.var(gname).set(jnp.asarray(merged))
                    ctx.run_block(bidx, scope)
                for gsec, _bidx in sparse_blocks:
                    parts = sparse_buffers.pop(gsec, None)
                    if not parts:
                        continue
                    rows = np.concatenate([r for r, _ in parts])
                    # mean over trainers: live fanin, except a trainer
                    # that pushed THEN died still counts for this round
                    # (trainers with no ids skip the push, so a bare
                    # len(parts) would over-scale)
                    vals2 = np.concatenate([v for _, v in parts]) \
                        / float(max(len(parts), effective_fanin()))
                    if rows.size:
                        _apply_sparse(gsec, rows, vals2)
        server.barrier_dynamic("send_done", effective_fanin,
                               peer=peer, alive_fn=_alive,
                               timeout=barrier_timeout)

    def on_get_var(payload):
        name, tid = (payload, None) if isinstance(payload, str) \
            else (payload[0], payload[1])
        with lock:
            var = scope.find_var(name)
            if var is None or var.get() is None:
                raise KeyError(f"pserver has no var '{name}'")
            val = _np(var.get())
            if tid is not None and name in dc_secs:
                # the pull snapshot this trainer's future delayed
                # grads will be corrected against
                scope.var(f"{name}.bak.{int(tid)}").set(
                    jnp.asarray(val))
                dc_primed.add((name, int(tid)))
            return val

    def on_prefetch_rows(payload):
        """Lookup rows of a table shard (reference: the pserver-side
        lookup block, distribute_transpiler.py:1583).  Rows are gathered
        on-device before the host copy — never materialize the whole
        shard per RPC."""
        tname, rows = payload
        with lock:
            var = scope.find_var(tname)
            if var is None or var.get() is None:
                raise KeyError(f"pserver has no table shard '{tname}'")
            picked = jnp.take(var.get(),
                              jnp.asarray(rows.astype(np.int64)), axis=0)
        return np.ascontiguousarray(_np(picked))

    def on_send_sparse(payload):
        gsec, rows, vals = payload
        with lock:
            if sync:
                sparse_buffers.setdefault(gsec, []).append((rows, vals))
            elif rows.size:
                _apply_sparse(gsec, rows, vals)

    def on_fetch_barrier(peer):
        if not sync:
            return
        _reject_fenced(peer)
        server.barrier_dynamic("fetch", effective_fanin, peer=peer,
                               alive_fn=_alive,
                               timeout=barrier_timeout)

    def on_complete(peer):
        if peer is not None:
            with live_lock:
                completed.add(str(peer))
                fenced.discard(str(peer))
            hb_monitor.forget(peer)  # retired, not dead
        with lock:
            ncomplete[0] += 1
            if ncomplete[0] >= outstanding_completions():
                stop.set()

    def on_reregister(peer):
        """Elastic resume (distributed/elastic.py): a relaunched
        trainer re-joins under its old peer id — un-fence it (its crash
        got it declared dead), un-retire it, and reset its liveness
        clock so effective_fanin counts it again.  Idempotent and
        retry-safe; returns the fanin the caller rejoins."""
        if peer is not None:
            with live_lock:
                fenced.discard(str(peer))
                completed.discard(str(peer))
            hb_monitor.forget(peer)
        return effective_fanin()

    def on_init_done(_):
        init_evt.set()

    def on_init_wait(_):
        if not init_evt.wait(timeout=120.0):
            raise TimeoutError(
                "init_wait: trainer 0 never pushed initial params "
                "(is it up? did ps_sync_init run?)")

    def on_profile(payload):
        """Remote profiling trigger (reference
        send_recv.proto.in:81 VariableMessage.profile: a trainer flips
        profiling on across the cluster; the server dumps a profile
        when it flips back off).  payload: "start" | ("stop", path)."""
        from paddle_tpu import profiler as _prof

        if payload == "start" or payload == 1:
            _prof.start_profiler()
            return "profiling"
        cmd, path = payload if isinstance(payload, tuple) else \
            (payload, None)
        if cmd in ("stop", 2):
            path = path or ("/tmp/profile_ps_%s" %
                            attrs["endpoint"].replace(":", "_"))
            _prof.stop_profiler(sorted_key="total", profile_path=path)
            return path
        raise ValueError(f"unknown profile command {payload!r}")

    def _ckpt_step_dir(dirname, step):
        import os
        ep_san = attrs["endpoint"].replace(":", "_").replace("/", "_")
        return os.path.join(str(dirname), "ps_%s" % ep_san,
                            "step_%d" % int(step))

    def on_checkpoint(payload):
        """Snapshot the WHOLE pserver scope — param sections AND the
        optimizer accumulators the optimize blocks created (momentum
        velocities, Adam moments) — so ElasticTrainer.resume() is exact
        under stateful pserver optimizers (ROADMAP open item from
        PR 3).  payload: a plain dirname (legacy flat snapshot) or
        (dirname, step) — then the snapshot lands in a per-endpoint
        per-step subdir, written to a tmp dir and atomically renamed so
        a crash mid-snapshot can never leave a torn step dir a later
        restore would half-load.  A MANIFEST.json maps files back to
        var names ('/' is mangled in filenames)."""
        import json as _json
        import os
        stepped = isinstance(payload, (tuple, list))
        dirname = _ckpt_step_dir(*payload) if stepped else str(payload)
        # per-thread tmp suffix: a transparently retried notify must
        # never race the original onto the same staging dir
        outdir = "%s.tmp%d" % (dirname, threading.get_ident()) \
            if stepped else dirname
        os.makedirs(outdir, exist_ok=True)
        manifest = {}
        with lock:
            for name, var in scope.vars.items():
                v = var.get()
                if v is not None and hasattr(v, "dtype"):
                    fname = name.replace("/", "_") + ".npy"
                    np.save(os.path.join(outdir, fname), _np(v))
                    manifest[fname] = name
        with open(os.path.join(outdir, "MANIFEST.json"), "w") as f:
            _json.dump(manifest, f)
        if stepped:
            import shutil
            if os.path.isdir(dirname):
                shutil.rmtree(dirname)
            os.replace(outdir, dirname)
        return len(manifest)

    def on_checkpoint_restore(payload):
        """Load a (dirname, step) snapshot back into the scope: params
        roll back to the checkpoint cut AND the optimizer state comes
        with them.  Returns the number of vars restored; 0 when no such
        snapshot exists (the caller falls back to the params-only
        push).  Idempotent."""
        import json as _json
        import os
        dirname = _ckpt_step_dir(*payload)
        man_path = os.path.join(dirname, "MANIFEST.json")
        if not os.path.isdir(dirname) or not os.path.exists(man_path):
            return 0
        with open(man_path) as f:
            manifest = _json.load(f)
        n = 0
        with lock:
            for fname, name in manifest.items():
                path = os.path.join(dirname, fname)
                if not os.path.exists(path):
                    continue
                scope.var(name).set(jnp.asarray(np.load(path)))
                n += 1
        return n

    # elastic liveness: trainers heartbeat; sync barriers re-count to
    # the live non-completed trainer set so survivors CONTINUE when a
    # trainer dies mid-step (round-3 verdict weak #4: detection without
    # reaction is a dashboard — this is the reaction)
    from paddle_tpu.distributed.rpc import HeartbeatMonitor

    hb_monitor = HeartbeatMonitor(
        timeout=float(attrs.get("heartbeat_timeout", 10.0)))
    fenced: set = set()     # once declared dead, STAYS out: a peer
    completed: set = set()  # resuming beats must not desync barriers
    live_lock = threading.Lock()

    def effective_fanin():
        # peers that ever heartbeat and then went silent are fenced
        # permanently; completed peers are retired cleanly (forget());
        # with no heartbeats configured this degrades to fixed fanin
        with live_lock:
            fenced.update(hb_monitor.dead_peers())
            return max(1, fanin - len(fenced | completed))

    def outstanding_completions():
        with live_lock:
            fenced.update(hb_monitor.dead_peers())
            return fanin - len(fenced)
    server.register_handler("heartbeat", hb_monitor.beat)
    server.register_handler("live_trainers",
                            lambda _: hb_monitor.live_peers())
    server.register_handler("dead_trainers",
                            lambda _: hb_monitor.dead_peers())
    server.register_handler("send_var", on_send_var)
    server.register_handler("send_barrier", on_send_barrier)
    server.register_handler("get_var", on_get_var)
    server.register_handler("prefetch_rows", on_prefetch_rows)
    server.register_handler("send_sparse", on_send_sparse)
    server.register_handler("fetch_barrier", on_fetch_barrier)
    server.register_handler("complete", on_complete)
    server.register_handler("reregister", on_reregister)
    server.register_handler("init_done", on_init_done)
    server.register_handler("init_wait", on_init_wait)
    server.register_handler("checkpoint_notify", on_checkpoint)
    server.register_handler("checkpoint_restore", on_checkpoint_restore)
    server.register_handler("profile", on_profile)

    # observability surface (ISSUE 9): a 'varz' RPC returning the
    # process metrics snapshot (wire-encodable dict), and — when
    # metrics_port attr / PADDLE_TPU_METRICS_PORT is set — the
    # /metrics + /varz HTTP endpoint mounted for scrapers
    from paddle_tpu.observability import metrics as _obs_metrics
    from paddle_tpu.observability.export import (MetricsHTTPServer,
                                                 metrics_port_from_env)

    server.register_handler(
        "varz", lambda _=None: _obs_metrics.registry().snapshot())
    mport = int(attrs.get("metrics_port", -1))
    if mport < 0:
        mport = metrics_port_from_env(-1)
    metrics_http = None
    if mport is not None and mport >= 0:
        try:
            metrics_http = MetricsHTTPServer(port=mport).start()
        except OSError:
            metrics_http = None   # port taken: a scrape endpoint is
            #                       an optimization, never a crash
    server.start()
    try:
        while not stop.wait(timeout=0.25):
            # trainers dying must not wedge shutdown: completion only
            # required of peers that are neither fenced nor completed
            # (covers the every-trainer-crashed case: 0 outstanding)
            with lock:
                if ncomplete[0] >= outstanding_completions():
                    stop.set()
    finally:
        if metrics_http is not None:
            metrics_http.stop()
        server.stop()


@register_op("split_ids", inputs=("Ids",), outputs=("Out",),
             duplicable=("Ids", "Out"),
             attrs={"sections": []},
             differentiable=False)
def split_ids_op_compute(ins, attrs):
    """split_ids_op.cc re-spec: partition a flat id vector by contiguous
    row sections [[s,e],...] (the reference hashes by id % n_shard; our
    tables shard by contiguous ranges like slice_variable).  Fixed-shape
    outputs: each section output has the full length with non-members
    masked to -1 (LoD-free re-spec; the PS prefetch handler compacts)."""
    ids = ins["Ids"][0].reshape(-1)
    outs = []
    for s, e in attrs["sections"]:
        member = (ids >= s) & (ids < e)
        outs.append(jnp.where(member, ids, -1))
    return {"Out": outs}


@register_op("merge_ids", inputs=("Ids", "Rows", "X"), outputs=("Out",),
             duplicable=("Ids", "Rows", "X"),
             attrs={}, differentiable=False)
def merge_ids_op_compute(ins, attrs):
    """merge_ids_op.cc re-spec: scatter per-section embedding rows back
    into the original id order.  Ids: original flat ids [N]; Rows: the
    masked per-section id vectors from split_ids ([N] each, -1 = not
    mine); X: per-section embedding results [N, D] (rows for masked-out
    ids are ignored)."""
    ids = ins["Ids"][0].reshape(-1)
    out = jnp.zeros((ids.shape[0], ins["X"][0].shape[-1]),
                    ins["X"][0].dtype)
    for rows, x in zip(ins["Rows"], ins["X"]):
        member = rows.reshape(-1) >= 0
        out = jnp.where(member[:, None], x, out)
    return {"Out": out}


@register_op("split_byref", inputs=("X",), outputs=("Out",),
             duplicable=("Out",),
             attrs={"sections": []}, differentiable=False)
def split_byref_op_compute(ins, attrs):
    """split_byref_op.cc: split rows into contiguous sections (the
    by-ref aliasing is an XLA buffer concern; functionally a row
    split)."""
    x = ins["X"]
    outs, start = [], 0
    for n in attrs["sections"]:
        outs.append(x[start:start + int(n)])
        start += int(n)
    return {"Out": outs}


@register_op("split_selected_rows", inputs=("X",), outputs=("Out",),
             duplicable=("Out",),
             attrs={"height_sections": []}, differentiable=False,
             host_only=True)
def _split_selected_rows_structural(ins, attrs):
    raise RuntimeError("split_selected_rows runs via the executor")


@register_special_op("split_selected_rows")
def split_selected_rows_op(op, block, scope, ctx):
    """split_selected_rows_op.cc: partition a SelectedRows by row
    ranges."""
    from paddle_tpu.core.scope import SelectedRows

    x = scope.find_var(op.inputs["X"][0]).get()
    secs = op.attrs["height_sections"]
    bounds = np.cumsum([0] + [int(s) for s in secs])
    rows = np.asarray(x.rows)
    vals = np.asarray(x.values)
    for i, name in enumerate(op.outputs["Out"]):
        lo, hi = bounds[i], bounds[i + 1]
        m = (rows >= lo) & (rows < hi)
        scope.var(name).set(SelectedRows(
            rows=jnp.asarray(rows[m] - lo),
            values=jnp.asarray(vals[m]),
            height=int(hi - lo)))


@register_op("lookup_sparse_table", inputs=("W", "Ids"),
             outputs=("Out",),
             attrs={"padding_idx": -1, "auto_grown_table": True},
             differentiable=False)
def lookup_sparse_table(ins, attrs):
    """lookup_sparse_table_op.cc: the pserver-side table lookup block's
    op — rows gathered from the local shard (auto-grow is a no-op in
    the dense-shard re-spec; unseen ids read zeros via clipping)."""
    w, ids = ins["W"], ins["Ids"]
    flat = ids.reshape(-1).astype(jnp.int32)
    valid = (flat >= 0) & (flat < w.shape[0])
    picked = jnp.take(w, jnp.clip(flat, 0, w.shape[0] - 1), axis=0)
    return {"Out": jnp.where(valid[:, None], picked, 0.0)}


@register_op("fake_init", inputs=(), outputs=("Out",),
             attrs={"shape": REQUIRED, "dtype": "float32"},
             differentiable=False, host_only=True)
def _fake_init_structural(ins, attrs):
    raise RuntimeError("fake_init runs via the executor")


@register_special_op("fake_init")
def fake_init_op(op, block, scope, ctx):
    """fake_init_op.cc: mark a trainer-side var 'initialized' without
    real content (its value lives on the pserver); zeros stand in."""
    shape = [int(s) for s in op.attrs["shape"]]
    scope.var(op.outputs["Out"][0]).set(
        jnp.zeros(shape, np.dtype(op.attrs["dtype"])))


@register_op("ref_by_trainer_id", inputs=("X", "TrainerId"),
             outputs=("Out",), duplicable=("X",),
             differentiable=False)
def ref_by_trainer_id(ins, attrs):
    """distributed_ops/ref_by_trainer_id_op.cc: Out = X[trainer_id] —
    pserver DC-ASGD blocks pick their per-trainer state this way.
    Static-rank select via lax.switch keeps it jittable."""
    xs = ins["X"]
    tid = ins["TrainerId"]
    idx = jnp.clip(jnp.asarray(tid).reshape(()).astype(jnp.int32), 0,
                   len(xs) - 1)
    from jax import lax as _lax

    return {"Out": _lax.switch(idx, [lambda x=x: x for x in xs])}
