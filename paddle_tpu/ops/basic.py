"""Elementwise / math / tensor-manipulation ops.

Reference parity (op names and attr semantics follow the reference):
  - elementwise family: /root/reference/paddle/fluid/operators/elementwise/
    (axis-broadcast semantics per elementwise_op_function.h)
  - reduce family: /root/reference/paddle/fluid/operators/reduce_ops/
  - activations: /root/reference/paddle/fluid/operators/activation_op.cc
  - tensor manipulation: reshape_op.cc, transpose_op.cc, concat_op.cc,
    split_op.cc, gather_op.cc, scatter_op.cc, slice_op.cc, stack_op.cc...
  - fill/init ops: fill_constant_op.cc, gaussian_random_op.cc,
    uniform_random_op.cc (startup-program initializers)
  - matmul_op.cc, mul_op.cc, softmax_op.cc, cross_entropy_op.cc,
    softmax_with_cross_entropy_op.cc, lookup_table_op.cc, top_k_op.cc
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _bcast_y(x, y, axis):
    """Fluid elementwise broadcast: Y's dims align to X's dims starting at
    `axis` (default -1 = trailing-aligned).  Reference:
    operators/elementwise/elementwise_op_function.h."""
    if y.ndim == x.ndim or y.ndim == 0:
        return y
    if y.ndim > x.ndim:
        return y  # let jnp broadcasting handle / raise
    a = x.ndim - y.ndim if axis == -1 else axis
    trailing = x.ndim - a - y.ndim
    if trailing > 0:
        y = y.reshape(y.shape + (1,) * trailing)
    return y


def _reduce_dims(attrs, ndim):
    if attrs.get("reduce_all") or not attrs.get("dim"):
        return tuple(range(ndim))
    return tuple(d % ndim for d in attrs["dim"])


def _np_rng(seed):
    if seed:
        return np.random.RandomState(seed)
    return np.random


# ---------------------------------------------------------------------------
# fill / random init ops (run in the startup program; host RNG is fine there,
# reference initializers are ops too: python/paddle/fluid/initializer.py:76)
# ---------------------------------------------------------------------------

@register_op("fill_constant", inputs=(), outputs=("Out",),
             attrs={"shape": REQUIRED, "dtype": "float32", "value": 0.0},
             differentiable=False)
def fill_constant(ins, attrs):
    return {"Out": jnp.full(tuple(attrs["shape"]), attrs["value"],
                            dtype=attrs["dtype"])}


@register_op("gaussian_random", inputs=(), outputs=("Out",),
             attrs={"shape": REQUIRED, "mean": 0.0, "std": 1.0, "seed": 0,
                    "dtype": "float32"},
             differentiable=False)
def gaussian_random(ins, attrs):
    rng = _np_rng(attrs["seed"])
    x = rng.normal(attrs["mean"], attrs["std"], size=tuple(attrs["shape"]))
    return {"Out": jnp.asarray(x.astype(attrs["dtype"]))}


@register_op("truncated_gaussian_random", inputs=(), outputs=("Out",),
             attrs={"shape": REQUIRED, "mean": 0.0, "std": 1.0, "seed": 0,
                    "dtype": "float32"},
             differentiable=False)
def truncated_gaussian_random(ins, attrs):
    rng = _np_rng(attrs["seed"])
    shape = tuple(attrs["shape"])
    x = rng.normal(attrs["mean"], attrs["std"], size=shape)
    lo, hi = attrs["mean"] - 2 * attrs["std"], attrs["mean"] + 2 * attrs["std"]
    bad = (x < lo) | (x > hi)
    while bad.any():
        x[bad] = rng.normal(attrs["mean"], attrs["std"], size=int(bad.sum()))
        bad = (x < lo) | (x > hi)
    return {"Out": jnp.asarray(x.astype(attrs["dtype"]))}


@register_op("uniform_random", inputs=(), outputs=("Out",),
             attrs={"shape": REQUIRED, "min": -1.0, "max": 1.0, "seed": 0,
                    "dtype": "float32"},
             differentiable=False)
def uniform_random(ins, attrs):
    rng = _np_rng(attrs["seed"])
    x = rng.uniform(attrs["min"], attrs["max"], size=tuple(attrs["shape"]))
    return {"Out": jnp.asarray(x.astype(attrs["dtype"]))}


@register_op("assign_value", inputs=(), outputs=("Out",),
             attrs={"values": REQUIRED, "dtype": None},
             differentiable=False)
def assign_value(ins, attrs):
    arr = np.asarray(attrs["values"])
    if attrs["dtype"]:
        arr = arr.astype(attrs["dtype"])
    return {"Out": jnp.asarray(arr)}


@register_op("assign", inputs=("X",), outputs=("Out",))
def assign(ins, attrs):
    return {"Out": ins["X"]}


@register_op("shape", inputs=("Input",), outputs=("Out",),
             differentiable=False)
def shape_op(ins, attrs):
    return {"Out": jnp.asarray(np.asarray(ins["Input"].shape, np.int64))}


@register_op("fill_constant_batch_size_like", inputs=("Input",),
             outputs=("Out",),
             attrs={"shape": REQUIRED, "dtype": "float32", "value": 0.0,
                    "input_dim_idx": 0, "output_dim_idx": 0},
             differentiable=False)
def fill_constant_batch_size_like(ins, attrs):
    shape = list(attrs["shape"])
    shape[attrs["output_dim_idx"]] = ins["Input"].shape[
        attrs["input_dim_idx"]
    ]
    return {"Out": jnp.full(tuple(shape), attrs["value"],
                            dtype=attrs["dtype"])}


@register_op("fill_zeros_like", inputs=("X",), outputs=("Out",),
             differentiable=False)
def fill_zeros_like(ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"])}


@register_op("cast", inputs=("X",), outputs=("Out",),
             attrs={"out_dtype": REQUIRED})
def cast(ins, attrs):
    return {"Out": ins["X"].astype(attrs["out_dtype"])}


@register_op("scale", inputs=("X",), outputs=("Out",),
             attrs={"scale": 1.0, "bias": 0.0, "bias_after_scale": True})
def scale(ins, attrs):
    x = ins["X"]
    if attrs["bias_after_scale"]:
        return {"Out": x * attrs["scale"] + attrs["bias"]}
    return {"Out": (x + attrs["bias"]) * attrs["scale"]}


@register_op("increment", inputs=("X",), outputs=("Out",),
             attrs={"step": 1.0}, differentiable=False,
             in_place={"Out": "X"})
def increment(ins, attrs):
    x = ins["X"]
    return {"Out": x + jnp.asarray(attrs["step"], x.dtype)}


# ---------------------------------------------------------------------------
# elementwise binary family (reference operators/elementwise/)
# ---------------------------------------------------------------------------

def _register_elementwise(name, fn, differentiable=True):
    @register_op(name, inputs=("X", "Y"), outputs=("Out",),
                 attrs={"axis": -1}, differentiable=differentiable)
    def _op(ins, attrs, fn=fn):
        x, y = ins["X"], ins["Y"]
        return {"Out": fn(x, _bcast_y(x, y, attrs["axis"]))}
    return _op


_register_elementwise("elementwise_add", lambda x, y: x + y)
_register_elementwise("elementwise_sub", lambda x, y: x - y)
_register_elementwise("elementwise_mul", lambda x, y: x * y)
_register_elementwise("elementwise_div", lambda x, y: x / y)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_pow", jnp.power)
_register_elementwise("elementwise_mod", jnp.mod, differentiable=False)
_register_elementwise("elementwise_floordiv", jnp.floor_divide,
                      differentiable=False)


@register_op("sum", inputs=("X",), outputs=("Out",), duplicable=("X",))
def sum_op(ins, attrs):
    """Var-arity add; used for gradient accumulation (reference sum_op.cc,
    backward.py _addup_repetitive_outputs_)."""
    xs = ins["X"]
    from paddle_tpu.core.scope import SelectedRows

    if any(isinstance(x, SelectedRows) for x in xs):
        dense = [x.to_dense() if isinstance(x, SelectedRows) else x
                 for x in xs]
        xs = dense
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean", inputs=("X",), outputs=("Out",))
def mean(ins, attrs):
    return {"Out": jnp.mean(ins["X"])}


# ---------------------------------------------------------------------------
# matmul / mul
# ---------------------------------------------------------------------------

@register_op("matmul", inputs=("X", "Y"), outputs=("Out",),
             attrs={"transpose_X": False, "transpose_Y": False,
                    "alpha": 1.0})
def matmul(ins, attrs):
    x, y = ins["X"], ins["Y"]
    if attrs["transpose_X"]:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs["transpose_Y"]:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    if attrs["alpha"] != 1.0:
        out = out * attrs["alpha"]
    return {"Out": out}


@register_op("mul", inputs=("X", "Y"), outputs=("Out",),
             attrs={"x_num_col_dims": 1, "y_num_col_dims": 1})
def mul(ins, attrs):
    """reference mul_op.cc: flattens X to 2-D at x_num_col_dims, Y at
    y_num_col_dims, then matmul; output keeps the unflattened dims."""
    x, y = ins["X"], ins["Y"]
    xnc, ync = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
    x2 = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    y2 = y.reshape((int(np.prod(y.shape[:ync])), -1))
    out = x2 @ y2
    return {"Out": out.reshape(x.shape[:xnc] + y.shape[ync:])}


# ---------------------------------------------------------------------------
# reductions (reference operators/reduce_ops/)
# ---------------------------------------------------------------------------

def _register_reduce(name, fn, differentiable=True):
    @register_op(name, inputs=("X",), outputs=("Out",),
                 attrs={"dim": [0], "keep_dim": False, "reduce_all": False},
                 differentiable=differentiable)
    def _op(ins, attrs, fn=fn):
        x = ins["X"]
        dims = _reduce_dims(attrs, x.ndim)
        return {"Out": fn(x, axis=dims, keepdims=attrs["keep_dim"])}
    return _op


_register_reduce("reduce_sum", jnp.sum)
_register_reduce("reduce_mean", jnp.mean)
_register_reduce("reduce_max", jnp.max)
_register_reduce("reduce_min", jnp.min)
_register_reduce("reduce_prod", jnp.prod)
_register_reduce("reduce_all", jnp.all, differentiable=False)
_register_reduce("reduce_any", jnp.any, differentiable=False)


# ---------------------------------------------------------------------------
# activations (reference activation_op.cc)
# ---------------------------------------------------------------------------

def _register_act(name, fn, differentiable=True, extra_attrs=None):
    @register_op(name, inputs=("X",), outputs=("Out",),
                 attrs=dict(extra_attrs or {}),
                 differentiable=differentiable)
    def _op(ins, attrs, fn=fn):
        return {"Out": fn(ins["X"], attrs)}
    return _op


_register_act("relu", lambda x, a: jax.nn.relu(x))
_register_act("relu6", lambda x, a: jnp.clip(x, 0.0, a["threshold"]),
              extra_attrs={"threshold": 6.0})
_register_act("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a["alpha"]),
              extra_attrs={"alpha": 0.02})
_register_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_register_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_register_act("tanh", lambda x, a: jnp.tanh(x))
_register_act("exp", lambda x, a: jnp.exp(x))
_register_act("log", lambda x, a: jnp.log(x))
_register_act("sqrt", lambda x, a: jnp.sqrt(x))
_register_act("rsqrt", lambda x, a: jax.lax.rsqrt(x))
_register_act("abs", lambda x, a: jnp.abs(x))
_register_act("square", lambda x, a: jnp.square(x))
_register_act("reciprocal", lambda x, a: 1.0 / x)
_register_act("softplus", lambda x, a: jax.nn.softplus(x))
_register_act("softsign", lambda x, a: jax.nn.soft_sign(x))
_register_act("gelu", lambda x, a: jax.nn.gelu(x, approximate=a["approximate"]),
              extra_attrs={"approximate": False})
_register_act("elu", lambda x, a: jax.nn.elu(x, a["alpha"]),
              extra_attrs={"alpha": 1.0})
_register_act("selu", lambda x, a: jax.nn.selu(x))
_register_act("swish", lambda x, a: x * jax.nn.sigmoid(a["beta"] * x),
              extra_attrs={"beta": 1.0})
_register_act("hard_sigmoid",
              lambda x, a: jnp.clip(a["slope"] * x + a["offset"], 0.0, 1.0),
              extra_attrs={"slope": 0.2, "offset": 0.5})
_register_act("hard_swish",
              lambda x, a: x * jnp.clip(x + a["offset"], 0.0, a["threshold"])
              / a["scale"],
              extra_attrs={"threshold": 6.0, "scale": 6.0, "offset": 3.0})
_register_act("floor", lambda x, a: jnp.floor(x), differentiable=False)
_register_act("ceil", lambda x, a: jnp.ceil(x), differentiable=False)
_register_act("round", lambda x, a: jnp.round(x), differentiable=False)
_register_act("sin", lambda x, a: jnp.sin(x))
_register_act("cos", lambda x, a: jnp.cos(x))
_register_act("erf", lambda x, a: jax.scipy.special.erf(x))
_register_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_register_act("softshrink",
              lambda x, a: jnp.where(x > a["lambda"], x - a["lambda"],
                                     jnp.where(x < -a["lambda"],
                                               x + a["lambda"], 0.0)),
              extra_attrs={"lambda": 0.5})
_register_act("hard_shrink",
              lambda x, a: jnp.where(jnp.abs(x) > a["threshold"], x, 0.0),
              extra_attrs={"threshold": 0.5})
_register_act("thresholded_relu",
              lambda x, a: jnp.where(x > a["threshold"], x, 0.0),
              extra_attrs={"threshold": 1.0})
_register_act("stanh",
              lambda x, a: a["scale_b"] * jnp.tanh(a["scale_a"] * x),
              extra_attrs={"scale_a": 0.67, "scale_b": 1.7159})


@register_op("pow", inputs=("X",), outputs=("Out",),
             attrs={"factor": 1.0})
def pow_op(ins, attrs):
    return {"Out": jnp.power(ins["X"], attrs["factor"])}


@register_op("clip", inputs=("X",), outputs=("Out",),
             attrs={"min": REQUIRED, "max": REQUIRED})
def clip_op(ins, attrs):
    return {"Out": jnp.clip(ins["X"], attrs["min"], attrs["max"])}


@register_op("clip_by_norm", inputs=("X",), outputs=("Out",),
             attrs={"max_norm": REQUIRED})
def clip_by_norm(ins, attrs):
    x = ins["X"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(attrs["max_norm"] / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale}


# ---------------------------------------------------------------------------
# softmax / losses
# ---------------------------------------------------------------------------

@register_op("softmax", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1})
def softmax(ins, attrs):
    return {"Out": jax.nn.softmax(ins["X"], axis=attrs["axis"])}


@register_op("log_softmax", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1})
def log_softmax(ins, attrs):
    return {"Out": jax.nn.log_softmax(ins["X"], axis=attrs["axis"])}


@register_op("cross_entropy", inputs=("X", "Label"), outputs=("Y",),
             attrs={"soft_label": False, "ignore_index": -100})
def cross_entropy(ins, attrs):
    """X are probabilities (post-softmax), reference cross_entropy_op.cc."""
    x, label = ins["X"], ins["Label"]
    eps = jnp.asarray(1e-12, x.dtype)
    if attrs["soft_label"]:
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1,
                        keepdims=True)
    else:
        lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
            else label
        picked = jnp.take_along_axis(
            x, lab[..., None].astype(jnp.int32), axis=-1
        )
        loss = -jnp.log(jnp.maximum(picked, eps))
        if attrs["ignore_index"] >= 0:
            mask = (lab[..., None] != attrs["ignore_index"])
            loss = jnp.where(mask, loss, 0.0)
    return {"Y": loss}


@register_op("softmax_with_cross_entropy", inputs=("Logits", "Label"),
             outputs=("Softmax", "Loss"),
             attrs={"soft_label": False, "ignore_index": -100, "axis": -1,
                    "numeric_stable_mode": True})
def softmax_with_cross_entropy(ins, attrs):
    logits, label = ins["Logits"], ins["Label"]
    axis = attrs["axis"]
    logp = jax.nn.log_softmax(logits, axis=axis)
    if attrs["soft_label"]:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = label
        if lab.ndim == logits.ndim and lab.shape[axis] == 1:
            lab = jnp.squeeze(lab, axis=axis)
        picked = jnp.take_along_axis(
            logp, lab[..., None].astype(jnp.int32), axis=axis
        )
        loss = -picked
        if attrs["ignore_index"] >= 0:
            loss = jnp.where(lab[..., None] != attrs["ignore_index"],
                             loss, 0.0)
    return {"Softmax": jnp.exp(logp), "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits",
             inputs=("X", "Label"), outputs=("Out",),
             attrs={"ignore_index": -100, "normalize": False})
def sigmoid_cross_entropy_with_logits(ins, attrs):
    x, label = ins["X"], ins["Label"]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if attrs["ignore_index"] >= 0:
        mask = (label != attrs["ignore_index"]).astype(x.dtype)
        loss = loss * mask
        if attrs["normalize"]:
            loss = loss / jnp.maximum(jnp.sum(mask), 1.0)
    return {"Out": loss}


@register_op("square_error_cost", inputs=("X", "Y"), outputs=("Out",))
def square_error_cost(ins, attrs):
    return {"Out": jnp.square(ins["X"] - ins["Y"])}


@register_op("huber_loss", inputs=("X", "Y"), outputs=("Out", "Residual"),
             attrs={"delta": 1.0})
def huber_loss(ins, attrs):
    d = attrs["delta"]
    r = ins["Y"] - ins["X"]
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * r * r, d * (ar - 0.5 * d))
    return {"Out": loss, "Residual": r}


@register_op("log_loss", inputs=("Predicted", "Labels"), outputs=("Loss",),
             attrs={"epsilon": 1e-4})
def log_loss(ins, attrs):
    p, y = ins["Predicted"], ins["Labels"]
    eps = attrs["epsilon"]
    return {"Loss": -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)}


# ---------------------------------------------------------------------------
# embeddings
# ---------------------------------------------------------------------------

@register_op("lookup_table", inputs=("W", "Ids"), outputs=("Out",),
             attrs={"padding_idx": -1, "is_sparse": False,
                    "is_distributed": False})
def lookup_table(ins, attrs):
    """reference lookup_table_op.cc.  Ids [..., 1] int64 -> Out [..., D].
    padding_idx rows return zeros.  The sparse-grad (SelectedRows) path is
    realised via a custom grad op in layers/backward when is_sparse."""
    w, ids = ins["W"], ins["Ids"]
    squeeze = ids.ndim >= 2 and ids.shape[-1] == 1
    idx = ids[..., 0] if squeeze else ids
    out = jnp.take(w, idx.astype(jnp.int32), axis=0)
    if attrs["padding_idx"] >= 0:
        mask = (idx != attrs["padding_idx"])[..., None]
        out = jnp.where(mask, out, 0.0)
    return {"Out": out}


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

@register_op("reshape2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"shape": REQUIRED})
def reshape2(ins, attrs):
    x = ins["X"]
    shape = list(attrs["shape"])
    for i, d in enumerate(shape):
        if d == 0:
            shape[i] = x.shape[i]
    return {"Out": x.reshape(shape),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("transpose2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axis": REQUIRED})
def transpose2(ins, attrs):
    x = ins["X"]
    return {"Out": jnp.transpose(x, attrs["axis"]),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("flatten2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axis": 1})
def flatten2(ins, attrs):
    x = ins["X"]
    a = attrs["axis"]
    lead = int(np.prod(x.shape[:a])) if a > 0 else 1
    return {"Out": x.reshape((lead, -1)),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("squeeze2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axes": []})
def squeeze2(ins, attrs):
    x = ins["X"]
    axes = attrs["axes"] or [i for i, d in enumerate(x.shape) if d == 1]
    axes = [a % x.ndim for a in axes if x.shape[a % x.ndim] == 1]
    return {"Out": jnp.squeeze(x, axis=tuple(axes)),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("unsqueeze2", inputs=("X",), outputs=("Out", "XShape"),
             attrs={"axes": REQUIRED})
def unsqueeze2(ins, attrs):
    x = ins["X"]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("concat", inputs=("X",), outputs=("Out",), duplicable=("X",),
             attrs={"axis": 0})
def concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs["axis"])}


@register_op("split", inputs=("X",), outputs=("Out",), duplicable=("Out",),
             attrs={"num": 0, "sections": [], "axis": 0})
def split(ins, attrs):
    x = ins["X"]
    axis = attrs["axis"]
    if attrs["sections"]:
        idx = np.cumsum(attrs["sections"])[:-1].tolist()
        return {"Out": jnp.split(x, idx, axis=axis)}
    return {"Out": jnp.split(x, attrs["num"], axis=axis)}


@register_op("stack", inputs=("X",), outputs=("Y",), duplicable=("X",),
             attrs={"axis": 0})
def stack(ins, attrs):
    return {"Y": jnp.stack(ins["X"], axis=attrs["axis"])}


@register_op("unstack", inputs=("X",), outputs=("Y",), duplicable=("Y",),
             attrs={"axis": 0, "num": 0})
def unstack(ins, attrs):
    x = ins["X"]
    parts = jnp.split(x, x.shape[attrs["axis"]], axis=attrs["axis"])
    return {"Y": [jnp.squeeze(p, axis=attrs["axis"]) for p in parts]}


@register_op("slice", inputs=("Input",), outputs=("Out",),
             attrs={"axes": REQUIRED, "starts": REQUIRED, "ends": REQUIRED})
def slice_op(ins, attrs):
    x = ins["Input"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": x[tuple(idx)]}


@register_op("strided_slice", inputs=("Input",), outputs=("Out",),
             attrs={"axes": REQUIRED, "starts": REQUIRED, "ends": REQUIRED,
                    "strides": REQUIRED})
def strided_slice(ins, attrs):
    x = ins["Input"]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                           attrs["strides"]):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register_op("gather", inputs=("X", "Index"), outputs=("Out",))
def gather(ins, attrs):
    return {"Out": jnp.take(ins["X"], ins["Index"].astype(jnp.int32),
                            axis=0)}


@register_op("gather_nd", inputs=("X", "Index"), outputs=("Out",))
def gather_nd(ins, attrs):
    x, index = ins["X"], ins["Index"]
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return {"Out": x[idx]}


@register_op("scatter", inputs=("X", "Ids", "Updates"), outputs=("Out",),
             attrs={"overwrite": True})
def scatter(ins, attrs):
    x, ids, upd = ins["X"], ins["Ids"].astype(jnp.int32), ins["Updates"]
    if attrs["overwrite"]:
        return {"Out": x.at[ids].set(upd)}
    return {"Out": x.at[ids].add(upd)}


@register_op("scatter_nd_add", inputs=("X", "Index", "Updates"),
             outputs=("Out",))
def scatter_nd_add(ins, attrs):
    x, index, upd = ins["X"], ins["Index"].astype(jnp.int32), ins["Updates"]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return {"Out": x.at[idx].add(upd)}


@register_op("expand", inputs=("X",), outputs=("Out",),
             attrs={"expand_times": REQUIRED})
def expand(ins, attrs):
    return {"Out": jnp.tile(ins["X"], attrs["expand_times"])}


@register_op("pad", inputs=("X",), outputs=("Out",),
             attrs={"paddings": REQUIRED, "pad_value": 0.0})
def pad(ins, attrs):
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(len(p) // 2)]
    return {"Out": jnp.pad(ins["X"], pads, constant_values=attrs["pad_value"])}


@register_op("pad2d", inputs=("X",), outputs=("Out",),
             attrs={"paddings": REQUIRED, "mode": "constant",
                    "pad_value": 0.0, "data_format": "NCHW"})
def pad2d(ins, attrs):
    p = attrs["paddings"]  # [top, bottom, left, right]
    if attrs["data_format"] == "NCHW":
        pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pads = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    mode = {"constant": "constant", "reflect": "reflect",
            "edge": "edge"}[attrs["mode"]]
    if mode == "constant":
        return {"Out": jnp.pad(ins["X"], pads,
                               constant_values=attrs["pad_value"])}
    return {"Out": jnp.pad(ins["X"], pads, mode=mode)}


@register_op("reverse", inputs=("X",), outputs=("Out",),
             attrs={"axis": REQUIRED})
def reverse(ins, attrs):
    return {"Out": jnp.flip(ins["X"], axis=tuple(attrs["axis"]))}


@register_op("tile", inputs=("X",), outputs=("Out",),
             attrs={"repeat_times": REQUIRED})
def tile(ins, attrs):
    return {"Out": jnp.tile(ins["X"], attrs["repeat_times"])}


@register_op("cumsum", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "exclusive": False, "reverse": False})
def cumsum(ins, attrs):
    x = ins["X"]
    axis = attrs["axis"]
    if attrs["reverse"]:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs["exclusive"]:
        out = out - x
    if attrs["reverse"]:
        out = jnp.flip(out, axis)
    return {"Out": out}


@register_op("one_hot", inputs=("X",), outputs=("Out",),
             attrs={"depth": REQUIRED, "dtype": "float32"},
             differentiable=False)
def one_hot(ins, attrs):
    x = ins["X"]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return {"Out": jax.nn.one_hot(x.astype(jnp.int32), attrs["depth"],
                                  dtype=attrs["dtype"])}


@register_op("range", inputs=(), outputs=("Out",),
             attrs={"start": REQUIRED, "end": REQUIRED, "step": 1,
                    "dtype": "int64"},
             differentiable=False)
def range_op(ins, attrs):
    return {"Out": jnp.arange(attrs["start"], attrs["end"], attrs["step"],
                              dtype=attrs["dtype"])}


@register_op("linspace", inputs=(), outputs=("Out",),
             attrs={"start": REQUIRED, "stop": REQUIRED, "num": REQUIRED,
                    "dtype": "float32"},
             differentiable=False)
def linspace(ins, attrs):
    return {"Out": jnp.linspace(attrs["start"], attrs["stop"], attrs["num"],
                                dtype=attrs["dtype"])}


# ---------------------------------------------------------------------------
# comparison / logical / selection
# ---------------------------------------------------------------------------

def _register_cmp(name, fn):
    @register_op(name, inputs=("X", "Y"), outputs=("Out",),
                 attrs={"axis": -1}, differentiable=False)
    def _op(ins, attrs, fn=fn):
        x, y = ins["X"], ins["Y"]
        return {"Out": fn(x, _bcast_y(x, y, attrs["axis"]))}
    return _op


_register_cmp("equal", jnp.equal)
_register_cmp("not_equal", jnp.not_equal)
_register_cmp("less_than", jnp.less)
_register_cmp("less_equal", jnp.less_equal)
_register_cmp("greater_than", jnp.greater)
_register_cmp("greater_equal", jnp.greater_equal)
_register_cmp("logical_and", jnp.logical_and)
_register_cmp("logical_or", jnp.logical_or)
_register_cmp("logical_xor", jnp.logical_xor)


@register_op("logical_not", inputs=("X",), outputs=("Out",),
             differentiable=False)
def logical_not(ins, attrs):
    return {"Out": jnp.logical_not(ins["X"])}


@register_op("where", inputs=("Condition", "X", "Y"), outputs=("Out",))
def where_op(ins, attrs):
    return {"Out": jnp.where(ins["Condition"], ins["X"], ins["Y"])}


@register_op("isfinite", inputs=("X",), outputs=("Out",),
             differentiable=False)
def isfinite(ins, attrs):
    return {"Out": jnp.all(jnp.isfinite(ins["X"]))}


# ---------------------------------------------------------------------------
# sorting / topk / argmax
# ---------------------------------------------------------------------------

@register_op("top_k", inputs=("X",), outputs=("Out", "Indices"),
             attrs={"k": 1}, differentiable=False)
def top_k(ins, attrs):
    vals, idx = jax.lax.top_k(ins["X"], attrs["k"])
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("arg_max", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "keepdims": False, "dtype": "int64"},
             differentiable=False)
def arg_max(ins, attrs):
    out = jnp.argmax(ins["X"], axis=attrs["axis"],
                     keepdims=attrs["keepdims"])
    return {"Out": out.astype(attrs["dtype"])}


@register_op("arg_min", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "keepdims": False, "dtype": "int64"},
             differentiable=False)
def arg_min(ins, attrs):
    out = jnp.argmin(ins["X"], axis=attrs["axis"],
                     keepdims=attrs["keepdims"])
    return {"Out": out.astype(attrs["dtype"])}


@register_op("argsort", inputs=("X",), outputs=("Out", "Indices"),
             attrs={"axis": -1, "descending": False}, differentiable=False)
def argsort(ins, attrs):
    x = ins["X"]
    axis = attrs["axis"]
    idx = jnp.argsort(-x if attrs["descending"] else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


# ---------------------------------------------------------------------------
# dropout (explicit seed-offset input keeps randomness jit-deterministic
# per step; reference dropout_op.cc uses a per-call host seed)
# ---------------------------------------------------------------------------

@register_op("dropout", inputs=("X", "SeedOffset"),
             outputs=("Out", "Mask"),
             optional=("SeedOffset",),
             attrs={"dropout_prob": 0.5, "is_test": False, "seed": 0,
                    "dropout_implementation": "downgrade_in_infer"})
def dropout(ins, attrs):
    x = ins["X"]
    p = attrs["dropout_prob"]
    upscale = attrs["dropout_implementation"] == "upscale_in_train"
    if attrs["is_test"]:
        out = x if upscale else x * (1.0 - p)
        return {"Out": out, "Mask": jnp.ones_like(x)}
    key = jax.random.key(attrs["seed"] or 42)
    off = ins.get("SeedOffset")
    if off is not None:
        from paddle_tpu.ops.rng import fold_seed_offset

        key = fold_seed_offset(key, off)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    out = x * mask
    if upscale and p < 1.0:
        out = out / (1.0 - p)
    return {"Out": out, "Mask": mask}


@register_op("label_smooth", inputs=("X",), outputs=("Out",),
             attrs={"epsilon": 0.0})
def label_smooth(ins, attrs):
    x = ins["X"]
    eps = attrs["epsilon"]
    k = x.shape[-1]
    return {"Out": x * (1.0 - eps) + eps / k}


@register_op("l2_normalize", inputs=("X",), outputs=("Out", "Norm"),
             attrs={"axis": -1, "epsilon": 1e-10})
def l2_normalize(ins, attrs):
    x = ins["X"]
    sq = jnp.sum(jnp.square(x), axis=attrs["axis"], keepdims=True)
    norm = jnp.sqrt(jnp.maximum(sq, attrs["epsilon"]))
    return {"Out": x / norm, "Norm": norm}


@register_op("norm", inputs=("X",), outputs=("Out", "Norm"),
             attrs={"axis": -1, "epsilon": 1e-10})
def norm_op(ins, attrs):
    x = ins["X"]
    norm = jnp.sqrt(
        jnp.sum(jnp.square(x), axis=attrs["axis"], keepdims=True)
        + attrs["epsilon"]
    )
    return {"Out": x / norm, "Norm": norm}


@register_op("swapaxes", inputs=("X",), outputs=("Out",),
             attrs={"axis1": 0, "axis2": 1})
def swapaxes(ins, attrs):
    """Rank-agnostic axis swap (time-major <-> batch-major flips in
    DynamicRNN; unlike transpose2 it needs no full permutation, so it
    works when the var's rank isn't statically recorded)."""
    return {"Out": jnp.swapaxes(ins["X"], attrs["axis1"],
                                attrs["axis2"])}


@register_op("flip", inputs=("X",), outputs=("Out",),
             attrs={"axis": [0]})
def flip_op(ins, attrs):
    return {"Out": jnp.flip(ins["X"], axis=tuple(attrs["axis"]))}
