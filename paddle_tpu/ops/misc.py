"""Misc op wave: tensor aliases, CTR helpers, accumulators and the
SelectedRows plumbing ops.

Reference parity (/root/reference/paddle/fluid/operators/):
  sign_op.cc, diag_op.cc, size_op.cc, fill_op.cc, minus_op.cc,
  is_empty_op.cc, flatten_op.cc (flatten), reshape_op.cc (reshape),
  squeeze_op.cc / unsqueeze_op.cc (non-2 variants), transpose_op.cc,
  fill_zeros_like_op.cc (fill_zeros_like2), cross_entropy_op.cc
  (cross_entropy2), multiplex_op.cc, mean_iou_op.h,
  bilinear_tensor_product_op.h, cvm_op.h, sampling_id_op.cc,
  uniform_random_batch_size_like_op.cc,
  gaussian_random_batch_size_like_op.cc, average_accumulates_op.h,
  lod_reset_op.cc, get_tensor_from_selected_rows_op.cc,
  merge_selected_rows_op.cc.

The non-"2" shape ops (flatten/reshape/squeeze/unsqueeze/transpose)
are the legacy single-output forms; the *2 forms with XShape side
outputs live in ops/basic.py.  Both exist in the reference registry,
so both are registered here for program-level parity.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op
from paddle_tpu.core.scope import SelectedRows


# ---------------------------------------------------------------------------
# tiny tensor ops
# ---------------------------------------------------------------------------

@register_op("sign", inputs=("X",), outputs=("Out",))
def sign(ins, attrs):
    return {"Out": jnp.sign(ins["X"])}


@register_op("diag", inputs=("Diagonal",), outputs=("Out",),
             differentiable=False)
def diag(ins, attrs):
    """diag_op.cc: vector [N] -> diagonal matrix [N, N]."""
    return {"Out": jnp.diag(ins["Diagonal"])}


@register_op("size", inputs=("Input",), outputs=("Out",),
             differentiable=False)
def size(ins, attrs):
    return {"Out": jnp.asarray(
        int(np.prod(ins["Input"].shape) if ins["Input"].shape else 1),
        jax.dtypes.canonicalize_dtype(jnp.int64)).reshape(1)}


@register_op("fill", inputs=(), outputs=("Out",), differentiable=False,
             attrs={"value": REQUIRED, "shape": REQUIRED,
                    "dtype": "float32", "force_cpu": False})
def fill(ins, attrs):
    """fill_op.cc: fill Out with the explicit per-element value list."""
    vals = np.asarray(attrs["value"], np.dtype(attrs["dtype"]))
    return {"Out": jnp.asarray(vals.reshape(
        [int(s) for s in attrs["shape"]]))}


@register_op("minus", inputs=("X", "Y"), outputs=("Out",))
def minus(ins, attrs):
    return {"Out": ins["X"] - ins["Y"]}


@register_op("is_empty", inputs=("X",), outputs=("Out",),
             differentiable=False)
def is_empty(ins, attrs):
    return {"Out": jnp.asarray(
        int(np.prod(ins["X"].shape)) == 0).reshape(())}


# legacy single-output shape ops ------------------------------------------

@register_op("flatten", inputs=("X",), outputs=("Out",),
             attrs={"axis": 1})
def flatten(ins, attrs):
    x = ins["X"]
    ax = int(attrs["axis"])
    lead = int(np.prod(x.shape[:ax])) if ax else 1
    return {"Out": x.reshape(lead, -1)}


@register_op("reshape", inputs=("X", "Shape"), outputs=("Out",),
             optional=("Shape",), attrs={"shape": REQUIRED})
def reshape(ins, attrs):
    return {"Out": ins["X"].reshape(
        [int(s) for s in attrs["shape"]])}


@register_op("squeeze", inputs=("X",), outputs=("Out",),
             attrs={"axes": []})
def squeeze(ins, attrs):
    x = ins["X"]
    axes = [int(a) for a in attrs["axes"]]
    if not axes:
        axes = [i for i, s in enumerate(x.shape) if s == 1]
    axes = [a for a in axes if x.shape[a] == 1]
    return {"Out": jnp.squeeze(x, axis=tuple(axes))}


@register_op("unsqueeze", inputs=("X",), outputs=("Out",),
             attrs={"axes": REQUIRED})
def unsqueeze(ins, attrs):
    x = ins["X"]
    for a in sorted(int(a) for a in attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("transpose", inputs=("X",), outputs=("Out",),
             attrs={"axis": REQUIRED})
def transpose(ins, attrs):
    return {"Out": jnp.transpose(ins["X"],
                                 [int(a) for a in attrs["axis"]])}


@register_op("fill_zeros_like2", inputs=("X",), outputs=("Out",),
             differentiable=False, attrs={"dtype": -1})
def fill_zeros_like2(ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"])}


@register_op("cross_entropy2", inputs=("X", "Label"),
             outputs=("Y", "MatchX"),
             attrs={"ignore_index": -100})
def cross_entropy2(ins, attrs):
    """cross_entropy_op.cc CrossEntropyOp2: hard-label CE over
    probabilities; MatchX caches the picked probability for the
    backward."""
    x, label = ins["X"], ins["Label"]
    n = x.shape[0]
    lbl = label.reshape(n).astype(jnp.int32)
    picked = jnp.take_along_axis(
        x.reshape(n, -1), lbl[:, None], axis=1)
    ignore = (lbl == attrs["ignore_index"])[:, None]
    y = jnp.where(ignore, 0.0,
                  -jnp.log(jnp.maximum(picked, 1e-20)))
    return {"Y": y, "MatchX": picked}


# ---------------------------------------------------------------------------
# selection / metrics / CTR
# ---------------------------------------------------------------------------

@register_op("multiplex", inputs=("X", "Ids"), outputs=("Out",),
             duplicable=("X",))
def multiplex(ins, attrs):
    """multiplex_op.cc: Ids [N,1] picks, per row n, row n of candidate
    X[ids[n]]."""
    xs = ins["X"]
    ids = ins["Ids"].reshape(-1).astype(jnp.int32)
    stacked = jnp.stack(xs, axis=0)          # [K, N, ...]
    n = stacked.shape[1]
    return {"Out": stacked[ids, jnp.arange(n)]}


@register_op("mean_iou",
             inputs=("Predictions", "Labels", "InWrongs", "InCorrects",
                     "InMeanIou"),
             outputs=("OutMeanIou", "OutWrong", "OutCorrect"),
             duplicable=("InWrongs", "InCorrects", "InMeanIou"),
             optional=("InWrongs", "InCorrects", "InMeanIou"),
             differentiable=False,
             attrs={"num_classes": REQUIRED})
def mean_iou(ins, attrs):
    """mean_iou_op.h: per-class correct/wrong counts; iou_c =
    correct_c/(correct_c+wrong_c); mean over classes present."""
    nc = int(attrs["num_classes"])
    pred = ins["Predictions"].reshape(-1).astype(jnp.int32)
    lbl = ins["Labels"].reshape(-1).astype(jnp.int32)
    hit = pred == lbl
    correct = jnp.zeros(nc, jnp.int32).at[lbl].add(
        hit.astype(jnp.int32), mode="drop")
    wrong = jnp.zeros(nc, jnp.int32).at[lbl].add(
        (~hit).astype(jnp.int32), mode="drop")
    wrong = wrong.at[pred].add((~hit).astype(jnp.int32), mode="drop")
    for w in ins.get("InWrongs") or []:
        wrong = wrong + w
    for c in ins.get("InCorrects") or []:
        correct = correct + c
    denom = wrong + correct
    valid = denom > 0
    iou = jnp.where(valid, correct / jnp.maximum(denom, 1), 0.0)
    miou = iou.sum() / jnp.maximum(valid.sum(), 1)
    for m in ins.get("InMeanIou") or []:
        miou = miou + m.reshape(())
    return {"OutMeanIou": miou.reshape(1).astype(jnp.float32),
            "OutWrong": wrong, "OutCorrect": correct}


@register_op("bilinear_tensor_product",
             inputs=("X", "Y", "Weight", "Bias"), outputs=("Out",),
             optional=("Bias",))
def bilinear_tensor_product(ins, attrs):
    """bilinear_tensor_product_op.h: out[n,k] = x[n] @ W[k] @ y[n]."""
    x, y, w = ins["X"], ins["Y"], ins["Weight"]
    out = jnp.einsum("ni,kij,nj->nk", x, w, y)
    if ins.get("Bias") is not None:
        out = out + ins["Bias"]
    return {"Out": out}


@register_op("cvm", inputs=("X", "CVM"), outputs=("Y",),
             optional=("CVM",), attrs={"use_cvm": True})
def cvm(ins, attrs):
    """cvm_op.h: first two features are show/click counters; use_cvm
    log-transforms them in place, else they are dropped."""
    x = ins["X"]
    if attrs["use_cvm"]:
        f0 = jnp.log(x[:, 0:1] + 1.0)
        f1 = jnp.log(x[:, 1:2] + 1.0) - f0
        return {"Y": jnp.concatenate([f0, f1, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


@register_op("sampling_id", inputs=("X", "SeedOffset"),
             outputs=("Out",), optional=("SeedOffset",),
             differentiable=False,
             attrs={"min": 0.0, "max": 1.0, "seed": 0})
def sampling_id(ins, attrs):
    """sampling_id_op.cc: sample a column index per row of the prob
    matrix X (categorical draw).  Optional SeedOffset tensor is folded
    into the key (the dropout-op pattern) so draws inside a lax.scan
    vary per step — a bare attr seed is traced once and would repeat
    the same draw every iteration.

    SeedOffset contract: a small non-negative integer scalar (a step
    position).  With jax x64 disabled an int64 offset silently narrows
    to int32, so a negative value would wrap differently per x64 mode;
    the clamp below pins the behavior (negatives fold as 0)."""
    x = ins["X"]
    key = jax.random.PRNGKey(attrs["seed"] or 0)
    off = ins.get("SeedOffset")
    if off is not None:
        from paddle_tpu.ops.rng import fold_seed_offset

        key = fold_seed_offset(key, off)
    u = jax.random.uniform(key, (x.shape[0], 1), x.dtype,
                           attrs["min"], attrs["max"])
    cdf = jnp.cumsum(x, axis=1)
    idx = jnp.sum((cdf < u).astype(jnp.int64), axis=1)
    return {"Out": jnp.clip(idx, 0, x.shape[1] - 1)}


@register_op("uniform_random_batch_size_like", inputs=("Input",),
             outputs=("Out",), differentiable=False, host_only=True,
             attrs={"shape": REQUIRED, "input_dim_idx": 0,
                    "output_dim_idx": 0, "min": -1.0, "max": 1.0,
                    "seed": 0, "dtype": "float32"})
def uniform_random_batch_size_like(ins, attrs):
    """uniform_random_batch_size_like_op.cc: host-side init (like
    uniform_random) with the batch dim copied from Input."""
    shape = [int(s) for s in attrs["shape"]]
    shape[int(attrs["output_dim_idx"])] = \
        ins["Input"].shape[int(attrs["input_dim_idx"])]
    rng = np.random.RandomState(attrs["seed"] or None)
    return {"Out": jnp.asarray(rng.uniform(
        attrs["min"], attrs["max"], shape).astype(attrs["dtype"]))}


@register_op("gaussian_random_batch_size_like", inputs=("Input",),
             outputs=("Out",), differentiable=False, host_only=True,
             attrs={"shape": REQUIRED, "input_dim_idx": 0,
                    "output_dim_idx": 0, "mean": 0.0, "std": 1.0,
                    "seed": 0, "dtype": "float32"})
def gaussian_random_batch_size_like(ins, attrs):
    shape = [int(s) for s in attrs["shape"]]
    shape[int(attrs["output_dim_idx"])] = \
        ins["Input"].shape[int(attrs["input_dim_idx"])]
    rng = np.random.RandomState(attrs["seed"] or None)
    return {"Out": jnp.asarray(
        (rng.randn(*shape) * attrs["std"] + attrs["mean"]).astype(
            attrs["dtype"]))}


@register_op("average_accumulates",
             inputs=("param", "in_sum_1", "in_sum_2", "in_sum_3",
                     "in_num_accumulates", "in_old_num_accumulates",
                     "in_num_updates"),
             outputs=("out_sum_1", "out_sum_2", "out_sum_3",
                      "out_num_accumulates", "out_old_num_accumulates",
                      "out_num_updates"),
             differentiable=False,
             in_place={"out_sum_1": "in_sum_1",
                       "out_sum_2": "in_sum_2",
                       "out_sum_3": "in_sum_3",
                       "out_num_accumulates": "in_num_accumulates",
                       "out_old_num_accumulates":
                           "in_old_num_accumulates",
                       "out_num_updates": "in_num_updates"},
             attrs={"average_window": 0.0,
                    "max_average_window": REQUIRED,
                    "min_average_window": 10000})
def average_accumulates(ins, attrs):
    """average_accumulates_op.h: ModelAverage accumulator rotation with
    the 16384-step precision spill and window-restart conditions,
    expressed as where-selects so it jits."""
    k_max = 16384
    p = ins["param"]
    s1 = ins["in_sum_1"] + p
    s2 = ins["in_sum_2"]
    s3 = ins["in_sum_3"]
    num_acc = ins["in_num_accumulates"].reshape(()) + 1
    old_acc = ins["in_old_num_accumulates"].reshape(())
    num_upd = ins["in_num_updates"].reshape(()) + 1
    spill = (num_upd % k_max) == 0
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.asarray(float(attrs["max_average_window"])),
        num_upd.astype(jnp.float32) * attrs["average_window"])
    restart = ((num_acc >= int(attrs["min_average_window"]))
               & (num_acc.astype(jnp.float32) >= window))
    s3 = jnp.where(restart, s1 + s2, s3)
    s1 = jnp.where(restart, jnp.zeros_like(s1), s1)
    s2 = jnp.where(restart, jnp.zeros_like(s2), s2)
    old_acc = jnp.where(restart, num_acc, old_acc)
    num_acc = jnp.where(restart, jnp.zeros_like(num_acc), num_acc)
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": num_acc.reshape(
                ins["in_num_accumulates"].shape),
            "out_old_num_accumulates": old_acc.reshape(
                ins["in_old_num_accumulates"].shape),
            "out_num_updates": num_upd.reshape(
                ins["in_num_updates"].shape)}


@register_op("lod_reset", inputs=("X", "Y"), outputs=("Out",),
             optional=("Y",), attrs={"target_lod": []})
def lod_reset(ins, attrs):
    """lod_reset_op.cc re-spec: under the padded [B,T,...]+Length
    representation the values are unchanged — sequence re-segmentation
    is carried by the explicit Length tensors produced by the sequence
    layers, so this is the identity on values (parity shim)."""
    return {"Out": ins["X"]}


# -- SelectedRows plumbing (host/interpreter path) -------------------------

@register_op("get_tensor_from_selected_rows", inputs=("X",),
             outputs=("Out",), differentiable=False, host_only=True)
def get_tensor_from_selected_rows(ins, attrs):
    """get_tensor_from_selected_rows_op.cc: expose the value tensor of
    a SelectedRows variable."""
    x = ins["X"]
    if isinstance(x, SelectedRows):
        return {"Out": x.values}
    return {"Out": x}


@register_op("merge_selected_rows", inputs=("X",), outputs=("Out",),
             differentiable=False, host_only=True)
def merge_selected_rows(ins, attrs):
    """merge_selected_rows_op.cc: sum duplicate rows so each row id
    appears once."""
    x = ins["X"]
    if not isinstance(x, SelectedRows):
        return {"Out": x}
    rows = np.asarray(x.rows)
    uniq, inv = np.unique(rows, return_inverse=True)
    vals = jnp.zeros((len(uniq),) + tuple(x.values.shape[1:]),
                     x.values.dtype).at[jnp.asarray(inv)].add(x.values)
    return {"Out": SelectedRows(jnp.asarray(uniq), vals, x.height)}


@register_op("recompute_segment_grad",
             inputs=("X", "OutGrad"), outputs=("XGrad",),
             duplicable=("X", "OutGrad", "XGrad"),
             attrs={"ops": REQUIRED, "in_names": REQUIRED,
                    "out_names": REQUIRED, "grad_in_names": REQUIRED},
             differentiable=False)
def recompute_segment_grad(ins, attrs):
    """Backward of one recompute segment (reference incubate
    RecomputeOptimizer; see backward.py _append_backward_recompute).

    Replays the serialized forward ops from the segment's boundary
    inputs inside jax.checkpoint and vjps the replay: residuals are the
    BOUNDARY values only, and the checkpoint's optimization barrier
    stops XLA from CSE-ing the replay against the forward pass — the
    intra-segment activations are genuinely not kept live between
    forward and backward."""
    from paddle_tpu.core.program import OpDesc
    from paddle_tpu.core.registry import get_op_def

    ops = [OpDesc.from_dict(d) for d in attrs["ops"]]
    in_names = list(attrs["in_names"])
    out_names = list(attrs["out_names"])
    grad_in = list(attrs["grad_in_names"])
    xs = dict(zip(in_names, ins["X"]))
    gs = dict(zip(out_names, ins["OutGrad"]))
    diff = {k: xs[k] for k in grad_in}
    nondiff = {k: v for k, v in xs.items() if k not in diff}

    def replay(d):
        env = dict(nondiff)
        env.update(d)
        for op in ops:
            od = get_op_def(op.type)
            op_ins = {}
            for slot, names in op.inputs.items():
                vals = [env.get(n) for n in names]
                if slot in od.duplicable:
                    op_ins[slot] = vals
                elif vals and vals[0] is not None:
                    op_ins[slot] = vals[0]
            outs = od.compute(op_ins, op.attrs) or {}
            for slot, names in op.outputs.items():
                if slot not in outs:
                    continue
                vals = outs[slot]
                if not isinstance(vals, (list, tuple)):
                    vals = [vals]
                for n, v in zip(names, vals):
                    env[n] = v
        return {n: env[n] for n in out_names}

    replay = jax.checkpoint(replay)
    primal, vjp = jax.vjp(replay, diff)

    def zero_ct(x):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jnp.zeros_like(x)
        return np.zeros(x.shape, dtype=jax.dtypes.float0)

    cts = {}
    for n in out_names:
        g = gs.get(n)
        p = primal[n]
        if g is None:
            cts[n] = zero_ct(p)
        else:
            if g.shape != p.shape and tuple(
                    d for d in g.shape if d != 1) == tuple(
                    d for d in p.shape if d != 1):
                g = jnp.reshape(g, p.shape)
            cts[n] = g
    (din,) = vjp(cts)
    return {"XGrad": [din[k] for k in grad_in]}


@register_op("fill_any_like", inputs=("X",), outputs=("Out",),
             attrs={"value": 0.0, "dtype": -1}, differentiable=False)
def fill_any_like(ins, attrs):
    """fill_any_like_op.cc: constant tensor with X's shape (dtype -1
    keeps X's dtype, like the reference's VarType -1 sentinel)."""
    x = ins["X"]
    dt = attrs.get("dtype", -1)
    if dt in (-1, None):
        dtype = x.dtype
    else:
        try:
            dtype = np.dtype(dt)
        except TypeError:
            raise ValueError(
                f"fill_any_like: unsupported dtype attr {dt!r} (use a "
                "numpy dtype name or -1 to keep X's dtype)") from None
    return {"Out": jnp.full(x.shape, attrs["value"], dtype)}


def _splitmix64(v):
    """Deterministic 64-bit mix (the role XXH64 plays in hash_op.h:40 —
    bucketing, not cryptography)."""
    v = (v + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    v = ((v ^ (v >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) \
        & np.uint64(0xFFFFFFFFFFFFFFFF)
    v = ((v ^ (v >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) \
        & np.uint64(0xFFFFFFFFFFFFFFFF)
    return v ^ (v >> np.uint64(31))


@register_op("hash", inputs=("X",), outputs=("Out",),
             attrs={"num_hash": 1, "mod_by": 100000},
             differentiable=False, host_only=True)
def hash_op(ins, attrs):
    """hash_op.cc: each row's ids hash to num_hash buckets in
    [0, mod_by); output [..., num_hash, 1] like HashOutputSize.
    XXH64(seed=ihash) becomes a splitmix64 over (row-digest, seed) —
    same contract (deterministic, seed-separated buckets)."""
    x = np.asarray(ins["X"]).astype(np.int64)
    rows = x.reshape(-1, x.shape[-1]).astype(np.uint64)
    num_hash = int(attrs["num_hash"])
    mod_by = np.uint64(int(attrs["mod_by"]))
    with np.errstate(over="ignore"):
        digest = np.zeros(rows.shape[0], np.uint64)
        for col in range(rows.shape[1]):
            digest = _splitmix64(digest ^ _splitmix64(rows[:, col]))
        out = np.empty((rows.shape[0], num_hash, 1), np.int64)
        for ihash in range(num_hash):
            out[:, ihash, 0] = (_splitmix64(digest ^ np.uint64(ihash))
                                % mod_by).astype(np.int64)
    return {"Out": out.reshape(x.shape[:-1] + (num_hash, 1))}


@register_op("unique", inputs=("X",), outputs=("Out", "Index"),
             attrs={"dtype": "int32"}, differentiable=False,
             host_only=True)
def unique_op(ins, attrs):
    """unique_op.cc: 1-D unique values in first-occurrence order + the
    index of each input element in Out.  Variable-length output keeps
    this a host op like the reference's CPU-only kernel."""
    x = np.asarray(ins["X"]).reshape(-1)
    _, first_idx, inverse = np.unique(x, return_index=True,
                                      return_inverse=True)
    order = np.argsort(first_idx)            # first-occurrence order
    out = x[np.sort(first_idx)]
    remap = np.empty_like(order)
    remap[order] = np.arange(len(order))
    index = remap[inverse].astype(np.dtype(attrs["dtype"]))
    return {"Out": out, "Index": index}
