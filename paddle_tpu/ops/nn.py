"""NN ops: convolution, pooling, normalization, rnn cells.

Reference parity:
  - conv: /root/reference/paddle/fluid/operators/conv_op.cc (+cudnn variants,
    subsumed by XLA:TPU convolution)
  - pool: operators/pool_op.cc
  - batch_norm: operators/batch_norm_op.cc; layer_norm: layer_norm_op.cc;
    group_norm: group_norm_op.cc
  - lstm/gru compute: operators/math/{lstm,gru}_compute.cc — here as fused
    cell ops used by layers.dynamic_lstm analogs and lax.scan loops.

Layout: ops honor the reference's ``data_format`` attr (NCHW default, like
conv_op.cc).  On TPU, NHWC is the fast path — XLA:TPU wants channels minor so
convs tile onto the MXU without relayouts; ``transpiler.nhwc_transpile``
rewrites a user-built NCHW program to NHWC internally.  Filters stay OIHW in
both layouts (user-visible param shape is layout-independent, matching the
reference); the O(kh*kw*C^2) transpose to HWIO is folded by XLA into the
weight's layout.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import REQUIRED, register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


@register_op("conv2d", inputs=("Input", "Filter"), outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "data_format": "NCHW", "use_cudnn": True})
def conv2d(ins, attrs):
    x, w = ins["Input"], ins["Filter"]
    s, p, d = _pair(attrs["strides"]), _pair(attrs["paddings"]), _pair(
        attrs["dilations"])
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NHWC" and attrs["groups"] == 1 and d == (1, 1) \
            and x.ndim == 4:
        # flag-gated Pallas fused-conv dispatch (default off -> this
        # branch is never taken): even an epilogue-less conv benefits
        # from the kernel's single-pass accumulator, and routing here
        # keeps the A/B honest — one flag flips EVERY conv in the
        # step, not just the rewritten chains
        from paddle_tpu.flags import get_flag

        if get_flag("conv_epilogue") != "off":
            from paddle_tpu.ops.pallas_conv import (_impl_from_flag,
                                                    conv2d_epilogue)

            return {"Output": conv2d_epilogue(
                x, w, strides=s, paddings=p, impl=_impl_from_flag())}
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (fmt, "OIHW", fmt))
    out = lax.conv_general_dilated(
        x, w, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d, dimension_numbers=dn,
        feature_group_count=attrs["groups"],
        preferred_element_type=None,
    )
    return {"Output": out}


@register_op("depthwise_conv2d", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "data_format": "NCHW", "use_cudnn": False})
def depthwise_conv2d(ins, attrs):
    x, w = ins["Input"], ins["Filter"]
    s, p, d = _pair(attrs["strides"]), _pair(attrs["paddings"]), _pair(
        attrs["dilations"])
    fmt = attrs.get("data_format", "NCHW")
    groups = attrs["groups"] or (x.shape[1] if fmt == "NCHW"
                                 else x.shape[-1])
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (fmt, "OIHW", fmt))
    out = lax.conv_general_dilated(
        x, w, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d, dimension_numbers=dn,
        feature_group_count=groups,
    )
    return {"Output": out}


@register_op("conv2d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "output_size": [], "data_format": "NCHW"})
def conv2d_transpose(ins, attrs):
    x, w = ins["Input"], ins["Filter"]  # w: [in, out/groups, kh, kw]
    s, p = _pair(attrs["strides"]), _pair(attrs["paddings"])
    d = _pair(attrs["dilations"])
    kh = (w.shape[2] - 1) * d[0] + 1
    kw = (w.shape[3] - 1) * d[1] + 1
    pad = [(kh - 1 - p[0], kh - 1 - p[0]), (kw - 1 - p[1], kw - 1 - p[1])]
    fmt = attrs.get("data_format", "NCHW")
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (fmt, "IOHW", fmt))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad,
        lhs_dilation=s, rhs_dilation=d, dimension_numbers=dn,
        feature_group_count=attrs["groups"],
    )
    return {"Output": out}


def _maxpool_cmp_bwd_impl(window, strides, pads, x, out, dy):
    """Compare-and-route max-pool backward: dx[i] = sum over window
    offsets o of dy[w]*(x[i] == out[w]) with w the window whose offset-o
    element is i.  Expressed as prod(window) shifted elementwise passes
    over stride-dilated out/dy — all fusable by XLA into one loop over
    dx, with no select_and_scatter (FLAGS maxpool_grad_algo=compare).
    Ties route to every maximum (the sas path routes once); identical
    on ties-free float data."""
    import itertools

    up_shape = tuple((o - 1) * s + 1
                     for o, s in zip(out.shape, strides))
    up_idx = tuple(slice(None, None, s) for s in strides)
    neg = jnp.asarray(-jnp.inf, out.dtype)
    out_up = jnp.full(up_shape, neg, out.dtype).at[up_idx].set(out)
    dy_up = jnp.zeros(up_shape, dy.dtype).at[up_idx].set(dy)
    base = tuple(k - 1 + s for k, s in zip(window, strides))
    wpad = [(b, b) for b in base]
    p_out = jnp.pad(out_up, wpad, constant_values=neg)
    p_dy = jnp.pad(dy_up, wpad)
    acc = jnp.zeros(x.shape, jnp.float32)
    for off in itertools.product(*[range(k) for k in window]):
        start = tuple(b + p[0] - o
                      for b, p, o in zip(base, pads, off))
        sl = tuple(slice(st, st + n) for st, n in zip(start, x.shape))
        acc = acc + jnp.where(x == p_out[sl], p_dy[sl], 0).astype(
            jnp.float32)
    return acc.astype(dy.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool_cmp(x, window, strides, pads):
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                             pads)


def _maxpool_cmp_fwd(x, window, strides, pads):
    out = _maxpool_cmp(x, window, strides, pads)
    return out, (x, out)


def _maxpool_cmp_bwd(window, strides, pads, res, dy):
    x, out = res
    return (_maxpool_cmp_bwd_impl(window, strides, pads, x, out, dy),)


_maxpool_cmp.defvjp(_maxpool_cmp_fwd, _maxpool_cmp_bwd)


@register_op("pool2d", inputs=("X",), outputs=("Out",),
             attrs={"pooling_type": "max", "ksize": REQUIRED,
                    "global_pooling": False, "strides": [1, 1],
                    "paddings": [0, 0], "exclusive": True,
                    "adaptive": False, "ceil_mode": False,
                    "data_format": "NCHW"})
def pool2d(ins, attrs):
    x = ins["X"]
    fmt = attrs.get("data_format", "NCHW")
    hw = (2, 3) if fmt == "NCHW" else (1, 2)
    if attrs["adaptive"]:
        oh, ow = _pair(attrs["ksize"])
        if fmt == "NCHW":
            n, c, h, wd = x.shape
            x6 = x.reshape(n, c, oh, h // oh, ow, wd // ow)
            red = (3, 5)
        else:
            n, h, wd, c = x.shape
            x6 = x.reshape(n, oh, h // oh, ow, wd // ow, c)
            red = (2, 4)
        if attrs["pooling_type"] == "max":
            return {"Out": jnp.max(x6, axis=red)}
        return {"Out": jnp.mean(x6, axis=red)}
    if attrs["global_pooling"]:
        k = (x.shape[hw[0]], x.shape[hw[1]])
        s, p = k, (0, 0)
    else:
        k = _pair(attrs["ksize"])
        s = _pair(attrs["strides"])
        p = _pair(attrs["paddings"])
    if fmt == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
    if attrs["pooling_type"] == "max":
        from paddle_tpu.flags import get_flag

        if get_flag("maxpool_grad_algo") == "compare":
            return {"Out": _maxpool_cmp(x, window, strides, pads)}
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                pads)
        return {"Out": out}
    out = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    if attrs["exclusive"] and (p[0] or p[1]):
        ones = jnp.ones((x.shape[hw[0]], x.shape[hw[1]]), x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, k, s,
                                ((p[0], p[0]), (p[1], p[1])))
        out = out / (cnt[None, None] if fmt == "NCHW"
                     else cnt[None, :, :, None])
    else:
        out = out / (k[0] * k[1])
    return {"Out": out}


def _moments_1pass(xf, axes):
    """Batch mean/variance as SIBLING reductions over one input.

    jnp.var's two-pass form (mean, then mean((x-mean)^2)) chains the
    second reduction on the first, forcing two HBM passes over x.
    Shifted one-pass moments — subtract a per-channel probe value
    near the data's scale, then sum(y) and sum(y*y) as independent
    siblings — let XLA multi-output-fuse both reductions into ONE
    read pass; the 2026-08-01 rn50 on-chip ablation priced BN
    batch-stats traffic at 9.3 ms of a 53.6 ms step.  The shift kills
    the E[x^2]-E[x]^2 cancellation blow-up for channels with
    |mean| >> std (the raw form loses all precision once mean^2
    dominates var in fp32).  Mean/var are shift-invariant, including
    their gradients, so exactness is preserved.  Both batch_norm and
    batch_norm_grad MUST build stats through this one helper so the
    backward's recompute CSEs with the forward under the one-module
    executor.

    Robustness (ADVICE r5): the shift is a SMALL-SLICE mean (up to 8
    elements along the first reduced axis), not one sampled element —
    a lone x[0,c,0,0] probe that happens to be ~0 on a post-ReLU
    sparse channel while |mean| >> std degrades to the raw
    cancellation-prone form.  And when E[y^2] and mean_y^2 still
    agree within a few ulps (shift missed the data's scale anyway),
    the affected channels fall back to an exact two-pass variance —
    the second read pass costs only when cancellation actually bites.
    """
    m = float(np.prod([xf.shape[a] for a in axes]))
    a0 = axes[0]
    k = min(8, xf.shape[a0])
    probe_idx = tuple(slice(0, k) if a == a0
                      else (slice(0, 1) if a in axes else slice(None))
                      for a in range(xf.ndim))
    # per-channel probe mean; stop_gradient: mean/var are
    # shift-invariant, so the shift must carry no gradient of its own
    shift = lax.stop_gradient(
        jnp.mean(xf[probe_idx], axis=axes, keepdims=False))
    shape = [1] * xf.ndim
    for a in range(xf.ndim):
        if a not in axes:
            shape[a] = xf.shape[a]
    y = xf - shift.reshape(shape)
    s1 = jnp.sum(y, axis=axes)
    s2 = jnp.sum(y * y, axis=axes)
    mean_y = s1 / m
    mean = shift + mean_y
    e2 = s2 / m
    var = e2 - mean_y * mean_y
    # cancellation guard: channels where the subtraction consumed all
    # but a few ulps of E[y^2] get the exact two-pass variance; the
    # cond skips the extra pass entirely on the (overwhelmingly
    # common) clean step
    eps = float(jnp.finfo(xf.dtype).eps) if jnp.issubdtype(
        xf.dtype, jnp.floating) else float(jnp.finfo(jnp.float32).eps)
    need = var <= 8.0 * eps * e2

    def _twopass(_):
        d = xf - lax.stop_gradient(mean).reshape(shape)
        return jnp.sum(d * d, axis=axes) / m

    var2 = lax.cond(jnp.any(need), _twopass, lambda _: var, None)
    var = jnp.maximum(jnp.where(need, var2, var), 0.0)
    return mean, var


@register_op("batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance",
                     "BatchMean", "BatchVariance"),
             outputs=("Y", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance"),
             optional=("BatchMean", "BatchVariance"),
             attrs={"epsilon": 1e-5, "momentum": 0.9, "is_test": False,
                    "data_layout": "NCHW", "use_global_stats": False})
def batch_norm(ins, attrs):
    """reference batch_norm_op.cc.  Running stats are data inputs/outputs so
    the op stays pure; the layer wires MeanOut/VarianceOut back onto the same
    persistable vars (in-place update, like the reference).

    Optional BatchMean/BatchVariance inputs supply PRECOMPUTED batch
    statistics for train mode, skipping the `_moments_1pass` reduction
    over X entirely — the consumer half of the conv+BN-stats fusion
    (ops/pallas_conv.py conv2d_bn_stats emits the moments as sibling
    outputs of the conv kernel, so the extra read pass over the conv
    output disappears from the HBM roofline).  Ignored in eval/global-
    stats mode, where the running stats already serve that role."""
    x = ins["X"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean, var = ins["Mean"], ins["Variance"]
    eps, mom = attrs["epsilon"], attrs["momentum"]
    axes = (0, 2, 3) if (x.ndim == 4 and attrs["data_layout"] == "NCHW") \
        else tuple(i for i in range(x.ndim) if i != x.ndim - 1) \
        if attrs["data_layout"] == "NHWC" else (0,) + tuple(range(2, x.ndim))
    # statistics in fp32 (bf16 accumulation loses too much), output in
    # x.dtype so an AMP-rewritten net stays low-precision through BN
    xf = x.astype(mean.dtype)
    if attrs["is_test"] or attrs["use_global_stats"]:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(var)
    else:
        if "BatchMean" in ins and "BatchVariance" in ins:
            use_mean = ins["BatchMean"].astype(mean.dtype)
            use_var = ins["BatchVariance"].astype(var.dtype)
        else:
            use_mean, use_var = _moments_1pass(xf, axes)
        mean_out = mean * mom + lax.stop_gradient(use_mean) * (1 - mom)
        var_out = var * mom + lax.stop_gradient(use_var) * (1 - mom)
        saved_mean = use_mean
        saved_var = 1.0 / jnp.sqrt(use_var + eps)
    shape = [1] * x.ndim
    c_axis = 1 if attrs["data_layout"] == "NCHW" else x.ndim - 1
    shape[c_axis] = x.shape[c_axis]
    rm = use_mean.reshape(shape)
    rv = use_var.reshape(shape)
    y = (xf - rm) * lax.rsqrt(rv + eps) * scale.reshape(shape) \
        + bias.reshape(shape)
    return {"Y": y.astype(x.dtype), "MeanOut": mean_out,
            "VarianceOut": var_out,
            "SavedMean": saved_mean, "SavedVariance": saved_var}


@register_op("batch_norm_grad",
             inputs=("X", "Scale", "Bias", "Mean", "Variance",
                     "BatchMean", "BatchVariance", "Y@GRAD",
                     "MeanOut@GRAD", "VarianceOut@GRAD", "SavedMean@GRAD",
                     "SavedVariance@GRAD"),
             outputs=("X@GRAD", "Scale@GRAD", "Bias@GRAD"),
             optional=("Bias", "Mean", "Variance", "BatchMean",
                       "BatchVariance", "MeanOut@GRAD",
                       "VarianceOut@GRAD", "SavedMean@GRAD",
                       "SavedVariance@GRAD"),
             attrs={"epsilon": 1e-5, "momentum": 0.9, "is_test": False,
                    "data_layout": "NCHW", "use_global_stats": False},
             differentiable=False)
def batch_norm_grad(ins, attrs):
    """Hand-written BN backward (reference batch_norm_op.cc *Grad kernels):

      dbias  = sum(dy)
      dscale = sum(dy * x_hat)
      dx     = scale*rstd * (dy - dbias/m - x_hat*dscale/m)    (train)
      dx     = scale*rstd * dy                                 (global stats)

    The auto-vjp grad would store fp32 intermediates of X's size (x_hat and
    the f32 upcast of x); this saves only X itself — mean/var recomputation
    CSEs with the forward pass under the compiled executor.  Statistics math
    in fp32, dx emitted in X's dtype (AMP-friendly).

    Optional BatchMean/BatchVariance mirror the forward op: when the
    forward consumed precomputed batch stats, the backward must use the
    SAME values (the train formula above already accounts for the
    stats' dependence on X analytically, so it applies unchanged) —
    and skips its own `_moments_1pass` recompute, the second read pass
    the conv+BN-stats fusion removes."""
    x, dy, scale = ins["X"], ins["Y@GRAD"], ins["Scale"]
    eps = attrs["epsilon"]
    axes = (0, 2, 3) if (x.ndim == 4 and attrs["data_layout"] == "NCHW") \
        else tuple(i for i in range(x.ndim) if i != x.ndim - 1) \
        if attrs["data_layout"] == "NHWC" else (0,) + tuple(range(2, x.ndim))
    shape = [1] * x.ndim
    c_axis = 1 if attrs["data_layout"] == "NCHW" else x.ndim - 1
    shape[c_axis] = x.shape[c_axis]
    f32 = scale.dtype
    xf = x.astype(f32)
    dyf = dy.astype(f32)
    if attrs["is_test"] or attrs["use_global_stats"]:
        mean, var = ins["Mean"], ins["Variance"]
        rstd = lax.rsqrt(var + eps)
        x_hat = (xf - mean.reshape(shape)) * rstd.reshape(shape)
        dbias = jnp.sum(dyf, axis=axes)
        dscale = jnp.sum(dyf * x_hat, axis=axes)
        dx = (scale * rstd).reshape(shape) * dyf
        return {"X@GRAD": dx.astype(x.dtype), "Scale@GRAD": dscale,
                "Bias@GRAD": dbias}
    m = float(np.prod([x.shape[a] for a in axes]))
    if "BatchMean" in ins and "BatchVariance" in ins:
        mean = ins["BatchMean"].astype(f32)
        var = ins["BatchVariance"].astype(f32)
    else:
        mean, var = _moments_1pass(xf, axes)
    rstd = lax.rsqrt(var + eps)
    x_hat = (xf - mean.reshape(shape)) * rstd.reshape(shape)
    dbias = jnp.sum(dyf, axis=axes)
    dscale = jnp.sum(dyf * x_hat, axis=axes)
    dx = (scale * rstd).reshape(shape) * (
        dyf - (dbias / m).reshape(shape)
        - x_hat * (dscale / m).reshape(shape))
    return {"X@GRAD": dx.astype(x.dtype), "Scale@GRAD": dscale,
            "Bias@GRAD": dbias}


@register_op("layer_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "Mean", "Variance"),
             optional=("Scale", "Bias"),
             attrs={"epsilon": 1e-5, "begin_norm_axis": 1})
def layer_norm(ins, attrs):
    x = ins["X"]
    a = attrs["begin_norm_axis"]
    axes = tuple(range(a, x.ndim))
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + attrs["epsilon"])
    norm_shape = x.shape[a:]
    if "Scale" in ins:
        y = y * ins["Scale"].reshape(norm_shape)
    if "Bias" in ins:
        y = y + ins["Bias"].reshape(norm_shape)
    return {"Y": y.astype(x.dtype), "Mean": jnp.squeeze(mean, axes),
            "Variance": jnp.squeeze(var, axes)}


@register_op("group_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "Mean", "Variance"),
             optional=("Scale", "Bias"),
             attrs={"epsilon": 1e-5, "groups": REQUIRED,
                    "data_layout": "NCHW"})
def group_norm(ins, attrs):
    x = ins["X"]
    n, c = x.shape[0], x.shape[1]
    g = attrs["groups"]
    xg = x.astype(jnp.promote_types(x.dtype, jnp.float32)).reshape(
        (n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + attrs["epsilon"])).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if "Scale" in ins:
        y = y * ins["Scale"].reshape(shape)
    if "Bias" in ins:
        y = y + ins["Bias"].reshape(shape)
    return {"Y": y.astype(x.dtype), "Mean": mean.reshape(n, g),
            "Variance": var.reshape(n, g)}


@register_op("instance_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "SavedMean", "SavedVariance"),
             optional=("Scale", "Bias"),
             attrs={"epsilon": 1e-5})
def instance_norm(ins, attrs):
    x = ins["X"]
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + attrs["epsilon"])
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if "Scale" in ins:
        y = y * ins["Scale"].reshape(shape)
    if "Bias" in ins:
        y = y + ins["Bias"].reshape(shape)
    return {"Y": y.astype(x.dtype), "SavedMean": jnp.squeeze(mean, axes),
            "SavedVariance": jnp.squeeze(var, axes)}


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"),
             attrs={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75})
def lrn(ins, attrs):
    x = ins["X"]
    n = attrs["n"]
    sq = jnp.square(x)
    pad = n // 2
    sq_p = jnp.pad(sq, ((0, 0), (pad, n - 1 - pad), (0, 0), (0, 0)))
    acc = sum(sq_p[:, i:i + x.shape[1]] for i in range(n))
    mid = attrs["k"] + attrs["alpha"] * acc
    return {"Out": x / jnp.power(mid, attrs["beta"]), "MidOut": mid}


# ---------------------------------------------------------------------------
# fused rnn cells (reference operators/math/lstm_compute, gru_compute) —
# single-step cells; layers build sequence loops with lax.scan around them.
# ---------------------------------------------------------------------------

@register_op("lstm_cell", inputs=("X", "HPrev", "CPrev", "W", "B"),
             outputs=("H", "C"), optional=("B",),
             attrs={"forget_bias": 0.0})
def lstm_cell(ins, attrs):
    """x:[N,D], h_prev/c_prev:[N,H], w:[D+H, 4H] (i,f,c,o), b:[4H]."""
    x, h_prev, c_prev, w = ins["X"], ins["HPrev"], ins["CPrev"], ins["W"]
    z = jnp.concatenate([x, h_prev], axis=-1) @ w
    if "B" in ins:
        z = z + ins["B"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f + attrs["forget_bias"]) * c_prev + \
        jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"H": h, "C": c}


@register_op("gru_cell", inputs=("X", "HPrev", "W", "B"),
             outputs=("H",), optional=("B",), attrs={})
def gru_cell(ins, attrs):
    """x:[N,D], h_prev:[N,H], w:[D+H, 3H] (r,u,c), b:[3H]."""
    x, h_prev, w = ins["X"], ins["HPrev"], ins["W"]
    d = x.shape[-1]
    h_dim = h_prev.shape[-1]
    w_ru = w[:, : 2 * h_dim]
    w_c = w[:, 2 * h_dim:]
    z = jnp.concatenate([x, h_prev], axis=-1) @ w_ru
    if "B" in ins:
        z = z + ins["B"][: 2 * h_dim]
    r, u = jnp.split(jax.nn.sigmoid(z), 2, axis=-1)
    c_in = jnp.concatenate([x, r * h_prev], axis=-1) @ w_c
    if "B" in ins:
        c_in = c_in + ins["B"][2 * h_dim:]
    c = jnp.tanh(c_in)
    h = u * h_prev + (1.0 - u) * c
    return {"H": h}


@register_op("im2sequence", inputs=("X",), outputs=("Out",),
             attrs={"kernels": REQUIRED, "strides": [1, 1],
                    "paddings": [0, 0, 0, 0]})
def im2sequence(ins, attrs):
    x = ins["X"]
    kh, kw = attrs["kernels"]
    sh, sw = _pair(attrs["strides"])
    p = attrs["paddings"]
    x = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, kh, kw), ("NCHW", "OIHW", "NCHW")),
    )
    out = patches.reshape(n, c * kh * kw, oh * ow)
    return {"Out": jnp.transpose(out, (0, 2, 1)).reshape(
        n * oh * ow, c * kh * kw)}


@register_op("sync_batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance"),
             outputs=("Y", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance"),
             attrs={"epsilon": 1e-5, "momentum": 0.9, "is_test": False,
                    "data_layout": "NCHW", "use_global_stats": False,
                    "sync_axis": "dp"})
def sync_batch_norm(ins, attrs):
    """sync_batch_norm_op.cu re-spec: batch norm whose statistics are
    the GLOBAL batch statistics across the data-parallel axis.

    Under the compiled GSPMD path (pjit over a sharded batch) plain
    batch_norm is ALREADY sync — jnp.mean sees the logical global batch
    and XLA inserts the cross-replica reduction.  This op exists for the
    explicit-SPMD path (shard_map / pmap), where shapes are per-shard:
    it pmeans count/sum/sum-of-squares over `sync_axis` (one psum, like
    the reference's ncclAllReduce of the packed stats vector).  Outside
    any named axis it degrades to local batch_norm."""
    x = ins["X"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean, var = ins["Mean"], ins["Variance"]
    eps, mom = attrs["epsilon"], attrs["momentum"]
    axes = (0, 2, 3) if (x.ndim == 4 and attrs["data_layout"] == "NCHW") \
        else tuple(i for i in range(x.ndim) if i != x.ndim - 1) \
        if attrs["data_layout"] == "NHWC" else (0,) + tuple(range(2, x.ndim))
    xf = x.astype(mean.dtype)
    if attrs["is_test"] or attrs["use_global_stats"]:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(var)
    else:
        s1 = jnp.mean(xf, axis=axes)
        s2 = jnp.mean(jnp.square(xf), axis=axes)
        axis = attrs.get("sync_axis") or "dp"
        try:
            s1 = lax.pmean(s1, axis)
            s2 = lax.pmean(s2, axis)
        except NameError:
            pass  # axis not bound: single-device or GSPMD global batch
        use_mean = s1
        use_var = jnp.maximum(s2 - jnp.square(s1), 0.0)
        mean_out = mean * mom + lax.stop_gradient(use_mean) * (1 - mom)
        var_out = var * mom + lax.stop_gradient(use_var) * (1 - mom)
        saved_mean = use_mean
        saved_var = 1.0 / jnp.sqrt(use_var + eps)
    shape = [1] * x.ndim
    c_axis = 1 if attrs["data_layout"] == "NCHW" else x.ndim - 1
    shape[c_axis] = x.shape[c_axis]
    y = (xf - use_mean.reshape(shape)) * lax.rsqrt(
        use_var.reshape(shape) + eps) * scale.reshape(shape) \
        + bias.reshape(shape)
    return {"Y": y.astype(x.dtype), "MeanOut": mean_out,
            "VarianceOut": var_out, "SavedMean": saved_mean,
            "SavedVariance": saved_var}


@register_op("spectral_norm", inputs=("Weight", "U", "V"),
             outputs=("Out", "UOut", "VOut"),
             attrs={"dim": 0, "power_iters": 1, "eps": 1e-12})
def spectral_norm(ins, attrs):
    """spectral_norm_op.cc: weight / sigma with sigma estimated by
    power iteration.  The reference mutates U/V in place so one
    iteration per step converges over training; here the updated
    vectors are outputs the layer wires back onto the same persistable
    U/V vars (the batch_norm MeanOut/VarianceOut idiom)."""
    w, u, v = ins["Weight"], ins["U"], ins["V"]
    dim = int(attrs["dim"])
    eps = attrs["eps"]
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
    for _ in range(int(attrs["power_iters"])):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    u = lax.stop_gradient(u)
    v = lax.stop_gradient(v)
    sigma = u @ wm @ v
    return {"Out": w / sigma, "UOut": u, "VOut": v}


@register_op("data_norm", inputs=("X", "BatchSize", "BatchSum",
                                  "BatchSquareSum"),
             outputs=("Y", "Means", "Scales"),
             attrs={"epsilon": 1e-4})
def data_norm(ins, attrs):
    """data_norm_op.cc (CTR feature normalization): normalize with the
    ACCUMULATED batch statistics (no scale/shift params); the layer
    wires accumulator updates separately.  Reference arithmetic
    (data_norm_op.cc:194): means = b_sum/b_size,
    scales = sqrt(b_size/b_square_sum) — no mean-centering of the
    square sum."""
    x = ins["X"]
    bsz, bsum, bsq = (ins["BatchSize"], ins["BatchSum"],
                      ins["BatchSquareSum"])
    means = bsum / bsz
    scales = jnp.sqrt(bsz / bsq)
    y = (x - means) * scales
    return {"Y": y.astype(x.dtype), "Means": means, "Scales": scales}
