"""Fused full-sequence RNN ops + CTC family.

Reference parity (/root/reference/paddle/fluid/operators/):
  gru_op.cc (gates u,r,c; h_t = (1-u)h_prev + u*c, origin_mode flips),
  gru_unit_op.cc, lstm_op.cc (Weight={W_ch,W_ih,W_fh,W_oh}, Bias 4D or
  7D with peepholes {b_c,b_i,b_f,b_o,W_ic,W_fc,W_oc}), lstm_unit_op.h
  (X gate order i,f,o,g with forget_bias), lstmp_op.cc (recurrent
  projection), cudnn_lstm_op.cc, fused/fusion_gru_op.cc,
  fused/fusion_lstm_op.cc, warpctc_op.cc, ctc_align_op.cc,
  edit_distance_op.cc.

TPU re-specification (SURVEY.md §5 LoD note): the reference's LoD
sequence inputs become padded [B, T, ...] plus an optional int Length
[B]; the time recursion is one lax.scan (XLA While) so the whole layer
stays inside the compiled program; grads come from jax.vjp through the
scan.  cudnn_lstm's opaque packed weight is re-specified as the
explicit concatenation [Wx | Wh | b] documented on the op.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import REQUIRED, register_op

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _length_mask(length, b, t):
    """[B, T] float mask from Length [B] (or None -> all ones)."""
    if length is None:
        return None
    steps = jnp.arange(t)[None, :]
    return (steps < length.reshape(b, 1)).astype(jnp.float32)


def _gru_step(g, h_prev, w, act, act_gate, origin_mode):
    """g: [B, 3D] pre-projected (u, r, c); w: [D, 3D]."""
    d = h_prev.shape[-1]
    uru = g[:, :2 * d] + h_prev @ w[:, :2 * d]
    u = act_gate(uru[:, :d])
    r = act_gate(uru[:, d:])
    c = act(g[:, 2 * d:] + (r * h_prev) @ w[:, 2 * d:])
    if origin_mode:
        return u * h_prev + (1.0 - u) * c
    return (1.0 - u) * h_prev + u * c


@register_op("gru", inputs=("Input", "H0", "Weight", "Bias", "Length"),
             outputs=("Hidden",), optional=("H0", "Bias", "Length"),
             attrs={"activation": "tanh", "gate_activation": "sigmoid",
                    "is_reverse": False, "origin_mode": False})
def gru(ins, attrs):
    """gru_op.cc on padded [B, T, 3D] input (pre-projected x@Wx, gate
    order u,r,c); Weight [D, 3D] = {W_u|W_r|W_c}."""
    x, w = ins["Input"], ins["Weight"]
    b, t, three_d = x.shape
    d = three_d // 3
    if ins.get("Bias") is not None:
        x = x + ins["Bias"].reshape(1, 1, 3 * d)
    h0 = ins.get("H0")
    if h0 is None:
        h0 = jnp.zeros((b, d), x.dtype)
    act = _ACT[attrs["activation"]]
    act_gate = _ACT[attrs["gate_activation"]]
    mask = _length_mask(ins.get("Length"), b, t)
    xs = jnp.swapaxes(x, 0, 1)              # [T, B, 3D]
    if attrs["is_reverse"]:
        xs = jnp.flip(xs, axis=0)
        if mask is not None:
            mask = jnp.flip(mask, axis=1)

    def step(h, inp):
        g, m = inp
        h_new = _gru_step(g, h, w, act, act_gate, attrs["origin_mode"])
        if m is not None:
            h_new = m[:, None] * h_new + (1.0 - m[:, None]) * h
        return h_new, h_new

    msec = jnp.swapaxes(mask, 0, 1) if mask is not None else \
        jnp.ones((t, b), jnp.float32)
    _, hs = lax.scan(lambda h, i: step(h, (i[0], i[1])), h0, (xs, msec))
    hs = jnp.swapaxes(hs, 0, 1)             # [B, T, D]
    if attrs["is_reverse"]:
        hs = jnp.flip(hs, axis=1)
    return {"Hidden": hs}


@register_op("gru_unit",
             inputs=("Input", "HiddenPrev", "Weight", "Bias"),
             outputs=("Gate", "ResetHiddenPrev", "Hidden"),
             optional=("Bias",),
             attrs={"activation": "tanh", "gate_activation": "sigmoid",
                    "origin_mode": False})
def gru_unit(ins, attrs):
    """gru_unit_op.cc single step; outputs cache the gate values the
    reference backward consumes."""
    g, h_prev, w = ins["Input"], ins["HiddenPrev"], ins["Weight"]
    d = h_prev.shape[-1]
    if ins.get("Bias") is not None:
        g = g + ins["Bias"].reshape(1, 3 * d)
    act = _ACT[attrs["activation"]]
    act_gate = _ACT[attrs["gate_activation"]]
    uru = g[:, :2 * d] + h_prev @ w[:, :2 * d]
    u = act_gate(uru[:, :d])
    r = act_gate(uru[:, d:])
    rhp = r * h_prev
    c = act(g[:, 2 * d:] + rhp @ w[:, 2 * d:])
    if attrs["origin_mode"]:
        h = u * h_prev + (1.0 - u) * c
    else:
        h = (1.0 - u) * h_prev + u * c
    return {"Gate": jnp.concatenate([u, r, c], axis=1),
            "ResetHiddenPrev": rhp, "Hidden": h}


def _lstm_scan(x, h0, c0, w, bias, use_peepholes, acts, is_reverse,
               mask, proj_w=None, proj_act=None):
    """Shared LSTM scan.  x: [B,T,4D] pre-projected, gate order
    c,i,f,o (lstm_op.cc Weight={W_ch,W_ih,W_fh,W_oh}); w: [R,4D] where
    R = D (lstm) or proj size (lstmp)."""
    b, t, four_d = x.shape
    d = four_d // 4
    act_g, act_gate, act_h = acts
    if bias is not None:
        x = x + bias[..., :4 * d].reshape(1, 1, 4 * d)
        peep = bias[..., 4 * d:].reshape(-1) if use_peepholes else None
    else:
        peep = None
    xs = jnp.swapaxes(x, 0, 1)
    msec = jnp.swapaxes(mask, 0, 1) if mask is not None else \
        jnp.ones((t, b), jnp.float32)
    if is_reverse:
        xs = jnp.flip(xs, axis=0)
        msec = jnp.flip(msec, axis=0)

    def step(carry, inp):
        h, c = carry
        g, m = inp
        z = g + h @ w
        zc, zi, zf, zo = (z[:, :d], z[:, d:2 * d], z[:, 2 * d:3 * d],
                          z[:, 3 * d:])
        if peep is not None:
            zi = zi + peep[:d] * c
            zf = zf + peep[d:2 * d] * c
        i = act_gate(zi)
        f = act_gate(zf)
        c_new = f * c + i * act_g(zc)
        if peep is not None:
            zo = zo + peep[2 * d:] * c_new
        o = act_gate(zo)
        h_new = o * act_h(c_new)
        if proj_w is not None:
            h_new = h_new @ proj_w
            if proj_act is not None:
                h_new = proj_act(h_new)
        mm = m[:, None]
        h_new = mm * h_new + (1 - mm) * h
        c_new = mm * c_new + (1 - mm) * c
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (xs, msec))
    hs = jnp.swapaxes(hs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, axis=1)
        cs = jnp.flip(cs, axis=1)
    return hs, cs


@register_op("lstm",
             inputs=("Input", "H0", "C0", "Weight", "Bias", "Length"),
             outputs=("Hidden", "Cell"),
             optional=("H0", "C0", "Bias", "Length"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"})
def lstm(ins, attrs):
    x, w = ins["Input"], ins["Weight"]
    b, t, four_d = x.shape
    d = four_d // 4
    h0 = ins.get("H0")
    c0 = ins.get("C0")
    if h0 is None:
        h0 = jnp.zeros((b, d), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, d), x.dtype)
    mask = _length_mask(ins.get("Length"), b, t)
    hs, cs = _lstm_scan(
        x, h0, c0, w, ins.get("Bias"), attrs["use_peepholes"],
        (_ACT[attrs["candidate_activation"]],
         _ACT[attrs["gate_activation"]],
         _ACT[attrs["cell_activation"]]),
        attrs["is_reverse"], mask)
    return {"Hidden": hs, "Cell": cs}


@register_op("lstmp",
             inputs=("Input", "H0", "C0", "Weight", "ProjWeight",
                     "Bias", "Length"),
             outputs=("Projection", "Cell"),
             optional=("H0", "C0", "Bias", "Length"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh",
                    "proj_activation": "tanh"})
def lstmp(ins, attrs):
    """lstmp_op.cc: LSTM with recurrent projection r_t =
    act_proj(h_t @ ProjWeight); the projection feeds the recurrence."""
    x, w, pw = ins["Input"], ins["Weight"], ins["ProjWeight"]
    b, t, four_d = x.shape
    d = four_d // 4
    p = pw.shape[1]
    h0 = ins.get("H0")
    c0 = ins.get("C0")
    if h0 is None:
        h0 = jnp.zeros((b, p), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, d), x.dtype)
    mask = _length_mask(ins.get("Length"), b, t)
    hs, cs = _lstm_scan(
        x, h0, c0, w, ins.get("Bias"), attrs["use_peepholes"],
        (_ACT[attrs["candidate_activation"]],
         _ACT[attrs["gate_activation"]],
         _ACT[attrs["cell_activation"]]),
        attrs["is_reverse"], mask, proj_w=pw,
        proj_act=_ACT[attrs["proj_activation"]])
    return {"Projection": hs, "Cell": cs}


@register_op("lstm_unit", inputs=("X", "C_prev"), outputs=("C", "H"),
             attrs={"forget_bias": 0.0})
def lstm_unit(ins, attrs):
    """lstm_unit_op.h: X [B, 4D] gate order i, f, o, g."""
    x, c_prev = ins["X"], ins["C_prev"]
    d = c_prev.shape[-1]
    i = jax.nn.sigmoid(x[:, :d])
    f = jax.nn.sigmoid(x[:, d:2 * d] + attrs["forget_bias"])
    o = jax.nn.sigmoid(x[:, 2 * d:3 * d])
    g = jnp.tanh(x[:, 3 * d:])
    c = f * c_prev + i * g
    return {"C": c, "H": o * jnp.tanh(c)}


@register_op("cudnn_lstm",
             inputs=("Input", "InitH", "InitC", "W", "Length"),
             outputs=("Out", "last_h", "last_c"),
             optional=("InitH", "InitC", "Length"),
             attrs={"hidden_size": REQUIRED, "is_bidirec": False,
                    "input_size": -1, "is_test": False, "seed": 0,
                    "dropout_prob": 0.0})
def cudnn_lstm(ins, attrs):
    """cudnn_lstm_op.cc re-spec: the cudnn packed weight blob becomes
    the explicit flat concatenation per direction of
    [Wx (I*4D) | Wh (D*4D) | b (4D)] (gate order c,i,f,o like lstm);
    bidirectional concatenates both directions' outputs on the feature
    axis.  XLA compiles the scan; there is no cudnn."""
    x = ins["Input"]                          # [B, T, I]
    b, t, isz = x.shape
    d = int(attrs["hidden_size"])
    dirs = 2 if attrs["is_bidirec"] else 1
    w = ins["W"].reshape(-1)
    per = isz * 4 * d + d * 4 * d + 4 * d
    outs, lhs, lcs = [], [], []
    mask = _length_mask(ins.get("Length"), b, t)
    for direction in range(dirs):
        off = direction * per
        wx = w[off:off + isz * 4 * d].reshape(isz, 4 * d)
        wh = w[off + isz * 4 * d:
               off + isz * 4 * d + d * 4 * d].reshape(d, 4 * d)
        bias = w[off + per - 4 * d:off + per].reshape(1, 4 * d)
        h0 = ins.get("InitH")
        c0 = ins.get("InitC")
        h0 = jnp.zeros((b, d), x.dtype) if h0 is None else \
            h0.reshape(dirs, b, d)[direction]
        c0 = jnp.zeros((b, d), x.dtype) if c0 is None else \
            c0.reshape(dirs, b, d)[direction]
        hs, cs = _lstm_scan(
            x @ wx, h0, c0, wh, bias, False,
            (jnp.tanh, jax.nn.sigmoid, jnp.tanh),
            direction == 1, mask)
        outs.append(hs)
        lhs.append(hs[:, -1])
        lcs.append(cs[:, -1])
    return {"Out": jnp.concatenate(outs, axis=-1),
            "last_h": jnp.stack(lhs, axis=0),
            "last_c": jnp.stack(lcs, axis=0)}


@register_op("fusion_gru",
             inputs=("X", "H0", "WeightX", "WeightH", "Bias", "Length"),
             outputs=("Hidden",),
             optional=("H0", "Bias", "Length"),
             attrs={"activation": "tanh", "gate_activation": "sigmoid",
                    "is_reverse": False, "origin_mode": False,
                    "use_seq": True})
def fusion_gru(ins, attrs):
    """fused/fusion_gru_op.cc: x-projection + gru in one op."""
    x = ins["X"] @ ins["WeightX"]
    sub = {"Input": x, "Weight": ins["WeightH"]}
    for k in ("H0", "Bias", "Length"):
        if ins.get(k) is not None:
            sub[k] = ins[k]
    return gru(sub, {k: attrs[k] for k in
                     ("activation", "gate_activation", "is_reverse",
                      "origin_mode")})


@register_op("fusion_lstm",
             inputs=("X", "H0", "C0", "WeightX", "WeightH", "Bias",
                     "Length"),
             outputs=("Hidden", "Cell"),
             optional=("H0", "C0", "Bias", "Length"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"})
def fusion_lstm(ins, attrs):
    """fused/fusion_lstm_op.cc: x-projection + lstm in one op."""
    x = ins["X"] @ ins["WeightX"]
    sub = {"Input": x, "Weight": ins["WeightH"]}
    for k in ("H0", "C0", "Bias", "Length"):
        if ins.get(k) is not None:
            sub[k] = ins[k]
    return lstm(sub, {k: attrs[k] for k in
                      ("use_peepholes", "is_reverse", "gate_activation",
                       "cell_activation", "candidate_activation")})


# ---------------------------------------------------------------------------
# CTC family
# ---------------------------------------------------------------------------

_NEG = -1e30


@register_op("warpctc",
             inputs=("Logits", "Label", "LogitsLength", "LabelLength"),
             outputs=("Loss",),
             optional=("LogitsLength", "LabelLength"),
             attrs={"blank": 0, "norm_by_times": False})
def warpctc(ins, attrs):
    """warpctc_op.cc re-spec: CTC negative log-likelihood via the
    standard log-space forward algorithm as one lax.scan over time
    (replaces the external warp-ctc library).  Logits [B, T, C]
    (unnormalized), Label [B, L] padded, lengths optional."""
    logits, label = ins["Logits"], ins["Label"]
    b, t, c = logits.shape
    if label.ndim > 2:
        label = label.reshape(b, -1)
    lmax = label.shape[1]
    blank = int(attrs["blank"])
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    llen = ins.get("LogitsLength")
    llen = jnp.full((b,), t, jnp.int32) if llen is None else \
        llen.reshape(b).astype(jnp.int32)
    tlen = ins.get("LabelLength")
    tlen = jnp.full((b,), lmax, jnp.int32) if tlen is None else \
        tlen.reshape(b).astype(jnp.int32)

    # extended label sequence: blank l1 blank l2 ... blank  [B, S=2L+1]
    s = 2 * lmax + 1
    ext = jnp.full((b, s), blank, label.dtype)
    ext = ext.at[:, 1::2].set(label)
    ext_valid = jnp.arange(s)[None, :] < (2 * tlen + 1)[:, None]
    # can we skip from s-2 to s (different labels, not blank)?
    skip_ok = jnp.zeros((b, s), bool)
    skip_ok = skip_ok.at[:, 2::2].set(False)
    same_prev = jnp.concatenate(
        [jnp.zeros((b, 1), bool),
         label[:, 1:] == label[:, :-1]], axis=1)       # [B, L]
    skip_ok = skip_ok.at[:, 3::2].set(~same_prev[:, 1:])
    ext_lp = jnp.take_along_axis(
        log_probs, jnp.broadcast_to(
            ext[:, None, :], (b, t, s)).astype(jnp.int32), axis=2)

    alpha0 = jnp.full((b, s), _NEG)
    alpha0 = alpha0.at[:, 0].set(ext_lp[:, 0, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(tlen > 0, ext_lp[:, 0, 1], _NEG))

    def step(alpha, inp):
        lp_t, t_idx = inp
        a_prev1 = jnp.concatenate(
            [jnp.full((b, 1), _NEG), alpha[:, :-1]], axis=1)
        a_prev2 = jnp.concatenate(
            [jnp.full((b, 2), _NEG), alpha[:, :-2]], axis=1)
        a_prev2 = jnp.where(skip_ok, a_prev2, _NEG)
        stacked = jnp.stack([alpha, a_prev1, a_prev2], axis=0)
        m = jnp.max(stacked, axis=0)
        summed = m + jnp.log(
            jnp.sum(jnp.exp(stacked - m[None]), axis=0))
        new = jnp.where(ext_valid, summed + lp_t, _NEG)
        # frozen past each sequence's logits length
        new = jnp.where((t_idx < llen)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(
        step, alpha0,
        (jnp.swapaxes(ext_lp, 0, 1)[1:], jnp.arange(1, t)))
    end1 = jnp.take_along_axis(alpha, (2 * tlen)[:, None], axis=1)
    end2 = jnp.take_along_axis(
        alpha, jnp.maximum(2 * tlen - 1, 0)[:, None], axis=1)
    m = jnp.maximum(end1, end2)
    ll = m + jnp.log(jnp.exp(end1 - m) + jnp.exp(end2 - m))
    loss = -ll.reshape(b, 1)
    if attrs["norm_by_times"]:
        loss = loss / llen.reshape(b, 1).astype(loss.dtype)
    return {"Loss": loss}


@register_op("ctc_align", inputs=("Input", "Length"),
             outputs=("Output", "OutLength"),
             optional=("Length",), differentiable=False,
             attrs={"blank": 0, "merge_repeated": True})
def ctc_align(ins, attrs):
    """ctc_align_op.cc re-spec: collapse repeats then strip blanks,
    left-packed into the padded output (pad value = blank); OutLength
    replaces the reference's LoD."""
    x = ins["Input"]
    if x.ndim == 1:
        x = x[None]
    b, t = x.shape
    blank = int(attrs["blank"])
    keep = x != blank
    if attrs["merge_repeated"]:
        prev = jnp.concatenate(
            [jnp.full((b, 1), -1, x.dtype), x[:, :-1]], axis=1)
        keep = keep & (x != prev)
    length = ins.get("Length")
    if length is not None:
        keep = keep & (jnp.arange(t)[None, :]
                       < length.reshape(b, 1))
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full((b, t), blank, x.dtype)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, t))
    out = out.at[rows, jnp.where(keep, pos, t)].set(
        jnp.where(keep, x, blank), mode="drop")
    return {"Output": out,
            "OutLength": keep.sum(axis=1).astype(jnp.int64)}


@register_op("edit_distance",
             inputs=("Hyps", "Refs", "HypsLength", "RefsLength"),
             outputs=("Out", "SequenceNum"),
             optional=("HypsLength", "RefsLength"),
             differentiable=False,
             attrs={"normalized": False})
def edit_distance(ins, attrs):
    """edit_distance_op.h: Levenshtein distance per (hyp, ref) pair;
    padded [B, L] + lengths re-spec of the LoD inputs.  DP over the
    hyp axis as a scan; the inner min-prefix recurrence is a second
    scan (wavefront form keeps everything static-shaped)."""
    hyp, ref = ins["Hyps"], ins["Refs"]
    if hyp.ndim > 2:
        hyp = hyp.reshape(hyp.shape[0], -1)
    if ref.ndim > 2:
        ref = ref.reshape(ref.shape[0], -1)
    b, m = hyp.shape
    n = ref.shape[1]
    hlen = ins.get("HypsLength")
    hlen = jnp.full((b,), m, jnp.int32) if hlen is None else \
        hlen.reshape(b).astype(jnp.int32)
    rlen = ins.get("RefsLength")
    rlen = jnp.full((b,), n, jnp.int32) if rlen is None else \
        rlen.reshape(b).astype(jnp.int32)

    def outer(row, inp):
        """row: dp[i-1, :] of shape [B, n+1]; returns dp[i, :]."""
        h_i, i_idx = inp
        sub = row[:, :-1] + (ref != h_i[:, None]).astype(jnp.float32)
        dele = row[:, 1:] + 1.0
        base = jnp.minimum(sub, dele)          # [B, n]

        def inner(left, vals):
            v = jnp.minimum(vals, left + 1.0)
            return v, v

        first = jnp.full((b,), i_idx, jnp.float32)
        _, cols = lax.scan(inner, first, jnp.swapaxes(base, 0, 1))
        new = jnp.concatenate(
            [first[:, None], jnp.swapaxes(cols, 0, 1)], axis=1)
        # rows past the hyp length keep the previous dp row
        new = jnp.where((i_idx <= hlen)[:, None], new, row)
        return new, None

    row0 = jnp.broadcast_to(
        jnp.arange(n + 1, dtype=jnp.float32)[None], (b, n + 1))
    final, _ = lax.scan(
        outer, row0,
        (jnp.swapaxes(hyp, 0, 1).astype(jnp.int32),
         jnp.arange(1, m + 1, dtype=jnp.float32)))
    dist = jnp.take_along_axis(final, rlen[:, None], axis=1)
    dist = jnp.where((hlen == 0)[:, None], rlen[:, None].astype(
        jnp.float32), dist)
    if attrs["normalized"]:
        dist = dist / jnp.maximum(rlen[:, None], 1).astype(jnp.float32)
    return {"Out": dist,
            "SequenceNum": jnp.asarray(
                b, jax.dtypes.canonicalize_dtype(jnp.int64)).reshape(1)}


@register_op("beam_search",
             inputs=("pre_ids", "pre_scores", "scores"),
             outputs=("selected_ids", "selected_scores", "parent_idx"),
             attrs={"beam_size": REQUIRED, "end_id": 0, "level": 0},
             differentiable=False)
def beam_search_op(ins, attrs):
    """beam_search_op.cc single decode step, batched re-spec:
    pre_ids [B, K], pre_scores [B, K], scores [B, K, V] (log-probs of
    the next token per beam).  Finished beams (pre_id == end_id)
    propagate with unchanged score.  Outputs the top-K continuations:
    ids [B, K], scores [B, K], parent beam indices [B, K]."""
    pre_ids, pre_scores, scores = (ins["pre_ids"], ins["pre_scores"],
                                   ins["scores"])
    k = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    b, kk, v = scores.shape
    finished = pre_ids == end_id
    # finished beams only continue as end_id with their frozen score
    cand = jnp.where(finished[..., None],
                     jnp.full_like(scores, -jnp.inf), scores)
    cand = cand.at[..., end_id].set(
        jnp.where(finished, 0.0, cand[..., end_id]))
    total = pre_scores[..., None] + cand                  # [B,K,V]
    flat = total.reshape(b, kk * v)
    top_s, top_i = jax.lax.top_k(flat, k)
    parent = (top_i // v).astype(jnp.int64)
    ids = (top_i % v).astype(jnp.int64)
    return {"selected_ids": ids, "selected_scores": top_s,
            "parent_idx": parent}


@register_op("beam_search_decode",
             inputs=("Ids", "Parents", "Scores"),
             outputs=("SentenceIds", "SentenceScores"),
             optional=("Scores",),
             attrs={"beam_size": 0, "end_id": 0},
             differentiable=False)
def beam_search_decode_op(ins, attrs):
    """beam_search_decode_op.cc re-spec: backtrack the per-step beam
    parents into full sequences.  Ids/Parents [T, B, K] (the stacked
    beam_search outputs); Scores [B, K] final beam scores.  Outputs
    SentenceIds [B, K, T] and SentenceScores [B, K]."""
    from paddle_tpu.core.registry import get_op_def

    ids, parents = ins["Ids"], ins["Parents"]
    seqs = get_op_def("gather_tree").compute(
        {"Ids": ids, "Parents": parents}, {})["Out"]
    out = jnp.transpose(seqs, (1, 2, 0))                  # [B,K,T]
    scores = ins.get("Scores")
    if scores is None:
        scores = jnp.zeros(out.shape[:2])
    return {"SentenceIds": out, "SentenceScores": scores}
