"""Host IO ops: save/load — checkpointing is itself graph execution, like
the reference (SURVEY.md §5 Checkpoint/resume).

Reference parity: /root/reference/paddle/fluid/operators/save_op.cc,
load_op.cc, save_combine_op.cc, load_combine_op.cc.

Format: one ``.npz``-style file per var (numpy save) or a combined archive;
arrays round-trip exactly.
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.executor import register_special_op
from paddle_tpu.core.registry import REQUIRED, register_op


def _ensure_dir(path):
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)


@register_special_op("save")
def save_op(op, block, scope, ctx):
    name = op.inputs["X"][0]
    path = op.attrs["file_path"]
    _ensure_dir(path)
    val = scope.find_var(name).get()
    np.save(path, np.asarray(val), allow_pickle=False)


@register_op("save", inputs=("X",), outputs=(),
             attrs={"file_path": REQUIRED, "overwrite": True},
             host_only=True, differentiable=False)
def _save_compute(ins, attrs):
    return {}


@register_special_op("load")
def load_op(op, block, scope, ctx):
    name = op.outputs["Out"][0]
    path = op.attrs["file_path"]
    if not os.path.exists(path) and os.path.exists(path + ".npy"):
        path = path + ".npy"
    scope.var(name).set(jnp.asarray(np.load(path, allow_pickle=False)))


@register_op("load", inputs=(), outputs=("Out",),
             attrs={"file_path": REQUIRED}, host_only=True,
             differentiable=False)
def _load_compute(ins, attrs):
    return {}


@register_special_op("save_combine")
def save_combine_op(op, block, scope, ctx):
    names = op.inputs["X"]
    path = op.attrs["file_path"]
    _ensure_dir(path)
    arrays = {n: np.asarray(scope.find_var(n).get()) for n in names}
    np.savez(path, **arrays)


@register_op("save_combine", inputs=("X",), outputs=(), duplicable=("X",),
             attrs={"file_path": REQUIRED, "overwrite": True},
             host_only=True, differentiable=False)
def _save_combine_compute(ins, attrs):
    return {}


@register_special_op("load_combine")
def load_combine_op(op, block, scope, ctx):
    names = op.outputs["Out"]
    path = op.attrs["file_path"]
    if not os.path.exists(path) and os.path.exists(path + ".npz"):
        path = path + ".npz"
    with np.load(path, allow_pickle=False) as data:
        for n in names:
            scope.var(n).set(jnp.asarray(data[n]))


@register_op("load_combine", inputs=(), outputs=("Out",),
             duplicable=("Out",),
             attrs={"file_path": REQUIRED}, host_only=True,
             differentiable=False)
def _load_combine_compute(ins, attrs):
    return {}


@register_special_op("read")
def read_op(op, block, scope, ctx):
    """Pop the next prefetched batch from the bound PyReader into the
    output vars (reference operators/reader/read_op.cc; EOF propagates as
    fluid.core.EOFException).  Mirrors the compiled path's feed-override
    semantics (reader.augment_feed_from_readers): a caller feeding ALL of
    the read op's outputs overrides the reader for this run."""
    from paddle_tpu import reader as reader_mod

    names = op.outputs["Out"]
    feed = ctx.feed or {}
    fed = [n for n in names if n in feed]
    if names and len(fed) == len(names):
        return  # _feed_data already set the vars
    if fed:
        raise ValueError(
            f"read op outputs partially fed ({fed}): feed all of "
            f"{names} to override the reader, or none to consume a batch")
    reader = reader_mod.get_py_reader(op.attrs["reader_name"])
    batch = reader._next_batch()
    for n in names:
        scope.var(n).set(batch[n])


@register_op("read", inputs=(), outputs=("Out",), duplicable=("Out",),
             attrs={"reader_name": REQUIRED}, host_only=True,
             differentiable=False)
def _read_compute(ins, attrs):
    return {}
