"""Quantization ops: fake quantize/dequantize with straight-through grads.

Reference parity:
  - fake_quantize_abs_max / fake_quantize_moving_average_abs_max /
    fake_channel_wise_quantize_abs_max / fake_dequantize_max_abs:
    /root/reference/paddle/fluid/operators/fake_quantize_op.cc,
    fake_dequantize_op.cc
  - used by the slim QAT passes
    (contrib/slim/quantization/quantization_pass.py).

TPU-first trick: the straight-through estimator is baked into the compute
as ``x + stop_gradient(q(x) - x)``, so the registry's generic vjp grad
(jax.vjp over the forward) automatically yields the identity backward the
reference implements as a separate grad kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op


def _quantize(x, scale, bits):
    """Symmetric uniform quantization to `bits` (dequantized domain)."""
    bnd = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * bnd), -bnd, bnd)
    return q * s / bnd


def _ste(x, q):
    return x + lax.stop_gradient(q - x)


@register_op("fake_quantize_abs_max", inputs=("X",),
             outputs=("Out", "OutScale"), attrs={"bit_length": 8})
def fake_quantize_abs_max(ins, attrs):
    x = ins["X"]
    scale = jnp.max(jnp.abs(x))
    q = _quantize(x, scale, attrs["bit_length"])
    return {"Out": _ste(x, q), "OutScale": scale.reshape((1,))}


@register_op("fake_channel_wise_quantize_abs_max", inputs=("X",),
             outputs=("Out", "OutScale"),
             attrs={"bit_length": 8, "quant_axis": 0})
def fake_channel_wise_quantize_abs_max(ins, attrs):
    x = ins["X"]
    ax = attrs["quant_axis"] % x.ndim
    red = tuple(i for i in range(x.ndim) if i != ax)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    q = _quantize(x, scale, attrs["bit_length"])
    return {"Out": _ste(x, q), "OutScale": scale.reshape(-1)}


@register_op("fake_quantize_moving_average_abs_max",
             inputs=("X", "InScale", "InState", "InAccum"),
             outputs=("Out", "OutScale", "OutState", "OutAccum"),
             optional=("InState", "InAccum"),
             attrs={"bit_length": 8, "moving_rate": 0.9,
                    "is_test": False},
             in_place={"OutScale": "InScale", "OutState": "InState",
                       "OutAccum": "InAccum"})
def fake_quantize_moving_average_abs_max(ins, attrs):
    """Activation quantization with an EMA of abs-max scales (reference
    fake_quantize_op.cc FakeQuantizeMovingAverageAbsMaxOp).  State/Accum
    implement the bias-corrected EMA exactly like the reference."""
    x = ins["X"]
    in_scale = ins["InScale"].reshape(())
    if attrs["is_test"]:
        q = _quantize(x, in_scale, attrs["bit_length"])
        return {"Out": _ste(x, q), "OutScale": in_scale.reshape((1,)),
                "OutState": ins.get("InState",
                                    jnp.ones((1,), x.dtype)),
                "OutAccum": ins.get("InAccum",
                                    in_scale.reshape((1,)))}
    cur = lax.stop_gradient(jnp.max(jnp.abs(x)))
    rate = attrs["moving_rate"]
    state = ins.get("InState", jnp.ones((1,), x.dtype)).reshape(())
    accum = ins.get("InAccum", in_scale.reshape((1,))).reshape(())
    state_out = rate * state + 1.0
    accum_out = rate * accum + cur
    # floor at WRITE time: an all-zero calibration batch would otherwise
    # persist a 0.0 OutScale, which downstream consumers
    # (convert_to_int8_execution) read as "never calibrated" and
    # silently route to the 2x-slower dynamic path (ISSUE 5 satellite)
    scale = jnp.maximum(accum_out / state_out, 1e-8)
    q = _quantize(x, scale, attrs["bit_length"])
    return {"Out": _ste(x, q), "OutScale": scale.reshape((1,)),
            "OutState": state_out.reshape((1,)),
            "OutAccum": accum_out.reshape((1,))}


@register_op("fake_dequantize_max_abs", inputs=("X", "Scale"),
             outputs=("Out",), attrs={"max_range": 127.0})
def fake_dequantize_max_abs(ins, attrs):
    return {"Out": ins["X"].astype(jnp.float32)
            * ins["Scale"].reshape(()) / attrs["max_range"]}


@register_op("quantize", inputs=("Input",), outputs=("Output",),
             attrs={"Scale": 1.0, "is_negative_input": True},
             differentiable=False)
def quantize(ins, attrs):
    """quantize_op.cc (INT8 execution path): y = round(scale * x) as
    int8 (uint8 when is_negative_input=False)."""
    x = ins["Input"]
    s = attrs["Scale"]
    if attrs["is_negative_input"]:
        return {"Output": jnp.clip(jnp.round(x * s), -128,
                                   127).astype(jnp.int8)}
    return {"Output": jnp.clip(jnp.round(x * s), 0,
                               255).astype(jnp.uint8)}


@register_op("dequantize", inputs=("Input",), outputs=("Output",),
             attrs={"Scale": 1.0}, differentiable=False)
def dequantize(ins, attrs):
    """dequantize_op.cc: y = x / scale as float32."""
    return {"Output": ins["Input"].astype(jnp.float32) / attrs["Scale"]}


@register_op("requantize",
             inputs=("Input", "InScale", "FilterScale", "Bias",
                     "OutScale"),
             outputs=("Output",),
             optional=("InScale", "FilterScale", "Bias", "OutScale"),
             attrs={"Scale_in": 1.0, "Scale_out": 1.0,
                    "max_range": 127.0, "fuse_relu": False,
                    "data_format": "NCHW", "bias_axis": -1,
                    "ref_dtype": "float32"},
             differentiable=False)
def requantize(ins, attrs):
    """Two modes.

    Legacy (no OutScale input, requantize_op.cc): rescale int8 between
    per-tensor quantization domains via the Scale_in/Scale_out attrs.

    Fused interlayer epilogue (OutScale wired; the ISSUE-5 int8
    activation-flow op): Input is a conv/mul int32 ACCUMULATOR and this
    op folds the producer's dequant (InScale x per-channel FilterScale),
    the folded-BN shift (Bias, broadcast exactly like elementwise_add's
    bias_axis), ReLU (fuse_relu — with symmetric quantization the zero
    point is 0, so ReLU IS the clamp-at-zero-point), and the consumer's
    quant (OutScale) into one pass — the tensor that leaves for HBM is
    int8, not bf16/f32.

    Bit-parity contract: every arithmetic step below mirrors the
    UNFUSED chain op for op — conv2d_int8's epilogue order
    (acc*(sx/bnd^2) then *scale), the cast to ref_dtype (the dtype the
    unfused graph flowed between layers, e.g. bfloat16), elementwise_add
    promotion, jax.nn.relu, then the consumer's astype(f32)/clip/round.
    tests/test_quantization.py asserts array_equal against the unfused
    dequant -> BN-shift -> ReLU -> quant chain AND end-to-end logits
    bit-identity of the interlayer-converted graph."""
    x = ins["Input"]
    if "OutScale" not in ins:
        xf = x.astype(jnp.float32)
        y = xf * (attrs["Scale_out"] / attrs["Scale_in"])
        return {"Output": jnp.clip(jnp.round(y), -128,
                                   127).astype(jnp.int8)}
    bnd = attrs["max_range"]
    sx = jnp.maximum(ins["InScale"].reshape(()).astype(jnp.float32),
                     1e-8)
    y = x.astype(jnp.float32) * (sx / (bnd * bnd))
    oscale = ins["FilterScale"].reshape(-1)
    if x.ndim == 4 and attrs["data_format"] == "NCHW":
        sc = oscale.reshape(1, -1, 1, 1)
    else:
        sc = oscale.reshape((1,) * (x.ndim - 1) + (-1,))
    y = y * sc
    y = y.astype(jnp.dtype(attrs.get("ref_dtype", "float32")))
    if "Bias" in ins:
        from paddle_tpu.ops.basic import _bcast_y

        y = y + _bcast_y(y, ins["Bias"], attrs.get("bias_axis", -1))
    if attrs.get("fuse_relu"):
        y = jax.nn.relu(y)
    from paddle_tpu.ops.epilogue import quantize_tail

    return {"Output": quantize_tail(y, ins["OutScale"], bnd)}


@register_op("dequantize_weight", inputs=("X", "Scale"),
             outputs=("Out",), attrs={"max_range": 127.0},
             differentiable=False)
def dequantize_weight(ins, attrs):
    """Dequantize-on-load for int8-stored weights (reference
    inference int8 path, inference/tests/api/int8_mkldnn_quantization.md):
    w = int8 * scale / max_range.  XLA fuses this into the consuming
    matmul/conv read, so the weight lives in HBM at 1 byte/elem."""
    return {"Out": ins["X"].astype(jnp.float32) * ins["Scale"]
            / attrs["max_range"]}


def _int8_conv_im2col(x8, q, strides, pads, dils, groups, fmt):
    """s8 conv as pad/slice/concat + ONE s8xs8->s32 dot_general.

    Alternative lowering for backends where an integer
    conv_general_dilated hits a bad compiler path (selected via
    FLAGS int8_conv_algo=im2col).  Patch extraction is pure data
    movement — pad, KhxKw strided slices, concat — so the only MXU op
    is the matmul; int32 accumulation of s8 products is exact, making
    this bit-identical to the conv lowering.  Cost: the activation is
    materialized Kh*Kw times (at 1 byte/elem).
    """
    if fmt == "NCHW":  # one internal layout; int8 transposes are cheap
        x8 = jnp.transpose(x8, (0, 2, 3, 1))
    O, I, KH, KW = q.shape
    N, H, W, C = x8.shape
    (sh, sw), (ph, pw), (dh, dw) = strides, pads, dils
    xp = jnp.pad(x8, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    OH = (H + 2 * ph - (KH - 1) * dh - 1) // sh + 1
    OW = (W + 2 * pw - (KW - 1) * dw - 1) // sw + 1
    cols = [lax.slice(xp, (0, kh * dh, kw * dw, 0),
                      (N, kh * dh + (OH - 1) * sh + 1,
                       kw * dw + (OW - 1) * sw + 1, C),
                      (1, sh, sw, 1))
            for kh in range(KH) for kw in range(KW)]
    # patches[..., (kh*KW+kw)*C + c] pairs with filter[o, c, kh, kw]
    patches = jnp.concatenate(cols, axis=-1)  # [N,OH,OW,KH*KW*C]
    # OIHW -> [KH*KW*I, O] in the same (kh, kw, c) minor order
    w = jnp.transpose(q, (2, 3, 1, 0)).reshape(KH * KW * I, O)
    if groups == 1:
        y32 = lax.dot_general(
            patches.reshape(N * OH * OW, KH * KW * C), w,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y32 = y32.reshape(N, OH, OW, O)
    else:
        ig, og = C // groups, O // groups  # ig == I
        # one batched dot over the group dim (not G unrolled matmuls)
        pg = patches.reshape(N * OH * OW, KH * KW, groups, ig)
        pg = jnp.transpose(pg, (2, 0, 1, 3)).reshape(
            groups, N * OH * OW, KH * KW * ig)
        wg = w.reshape(KH * KW, ig, groups, og)  # O = (g, og) split
        wg = jnp.transpose(wg, (2, 0, 1, 3)).reshape(
            groups, KH * KW * ig, og)
        y32 = lax.dot_general(pg, wg, (((2,), (1,)), ((0,), (0,))),
                              preferred_element_type=jnp.int32)
        # [G, N*OH*OW, og] -> [N, OH, OW, G*og] with O = g*og + o
        y32 = jnp.transpose(y32, (1, 0, 2)).reshape(N, OH, OW, O)
    if fmt == "NCHW":
        y32 = jnp.transpose(y32, (0, 3, 1, 2))
    return y32


@register_op("conv2d_int8", inputs=("Input", "Filter", "FilterScale",
                                    "InScale", "Bias", "Residual",
                                    "OutScale"),
             outputs=("Output",),
             optional=("InScale", "Bias", "Residual", "OutScale"),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1,
                    "data_format": "NCHW", "max_range": 127.0,
                    "out_dtype": "float32", "fuse_relu": False,
                    "bias_axis": -1, "epilogue": ""},
             differentiable=False)
def conv2d_int8(ins, attrs):
    """True-int8 convolution (reference int8 execution path,
    inference/tests/api/int8_mkldnn_quantization.md — there via mkldnn
    u8s8 kernels; here the MXU): quantize the activation per-tensor to
    int8, convolve int8 x int8 with int32 accumulation
    (lax.conv_general_dilated preferred_element_type=int32), then apply
    the combined activation x per-out-channel filter scale.  Unlike
    dequantize_weight (which saves bytes but computes in fp32/bf16),
    the MACs themselves run on 1-byte operands.

    The activation scale comes from the optional InScale input (a
    calibrated per-tensor abs-max, post_training_quantize) when wired;
    otherwise it is derived dynamically with a max-reduction.  On an
    HBM-bound chip the dynamic path costs an extra full read of the
    activation per conv (the 2026-08-01 on-chip int8 row ran 2x SLOWER
    than bf16 because of it), so the calibrated path is what the bench
    and any serious deployment should use.  out_dtype="bfloat16" halves
    inter-layer activation traffic; quantization noise (7-bit mantissa
    vs the int8 lattice) dwarfs the bf16 rounding.

    Interlayer extensions (ISSUE 5, all optional/off by default):
      * int8 INPUT: accepted as-is (the producer already quantized to
        this op's calibrated InScale — mandatory then);
      * Bias / fuse_relu: the requantize epilogue's folded-BN shift and
        ReLU ride inside the conv op, mirroring the unfused
        elementwise_add/relu chain's op order, dtypes and broadcast
        (bias_axis) bit-exactly;
      * Residual: the skip-connection add between bias and ReLU
        (ISSUE 17's residual-edge fold: the epilogue stage grammar's
        ``residual`` stage riding the existing kernel — mirrors the
        unfused elementwise_add's op order and dtype promotion);
      * OutScale: quantize the epilogue result to the CONSUMER's
        calibrated scale and emit int8 — the int8-out variant; the
        tensor crossing the op boundary is 1 byte/elem;
      * out_dtype="int32": emit the RAW accumulator (scales applied by
        a downstream standalone `requantize`)."""
    from paddle_tpu.ops.nn import _pair

    from paddle_tpu.flags import get_flag

    x, q, ws = ins["Input"], ins["Filter"], ins["FilterScale"]
    bnd = attrs["max_range"]
    if x.dtype == jnp.int8:
        # int8-in (interlayer mode): the producer's fused requantize
        # already quantized the activation to THIS op's calibrated
        # InScale — quantizing again would double-round.  A dynamic
        # scale is meaningless here (the int8 lattice was fixed by the
        # producer), so InScale is mandatory.
        if "InScale" not in ins:
            raise ValueError(
                "conv2d_int8: int8 input requires a calibrated InScale "
                "(the producer quantized to it); dynamic scaling of an "
                "already-quantized tensor is ill-defined")
        sx = jnp.maximum(ins["InScale"].reshape(()).astype(jnp.float32),
                         1e-8)
        x8 = x
    else:
        if "InScale" in ins:
            sx = jnp.maximum(
                ins["InScale"].reshape(()).astype(jnp.float32), 1e-8)
        else:
            sx = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
        xf = x.astype(jnp.float32)
        x8 = jnp.clip(jnp.round(xf / sx * bnd),
                      -bnd, bnd).astype(jnp.int8)
    s, p, d = (_pair(attrs["strides"]), _pair(attrs["paddings"]),
               _pair(attrs["dilations"]))
    fmt = attrs.get("data_format", "NCHW")
    if get_flag("int8_conv_algo") == "im2col":
        y32 = _int8_conv_im2col(x8, q, s, p, d, attrs["groups"], fmt)
    else:
        dn = lax.conv_dimension_numbers(x.shape, q.shape,
                                        (fmt, "OIHW", fmt))
        y32 = lax.conv_general_dilated(
            x8, q, window_strides=s,
            padding=[(p[0], p[0]), (p[1], p[1])],
            rhs_dilation=d, dimension_numbers=dn,
            feature_group_count=attrs["groups"],
            preferred_element_type=jnp.int32)
    if attrs["out_dtype"] == "int32":
        # int32-out (interlayer mode): hand the RAW accumulator to a
        # standalone requantize, which owns every scale/shift —
        # applying them here too would double-scale
        return {"Output": y32}
    oscale = ws.reshape(-1)  # per-out-channel (O,1,1,1) -> (O,)
    sc = (oscale.reshape(1, -1, 1, 1) if fmt == "NCHW"
          else oscale.reshape(1, 1, 1, -1))
    y = y32.astype(jnp.float32) * (sx / (bnd * bnd)) * sc
    y = y.astype(jnp.dtype(attrs["out_dtype"]))
    if "Bias" in ins:
        from paddle_tpu.ops.basic import _bcast_y

        # mirrors the unfused elementwise_add exactly, including its
        # dtype promotion (bf16 out + f32 bias -> f32) — bit-parity
        # with the never-folded chain is the contract
        y = y + _bcast_y(y, ins["Bias"], attrs.get("bias_axis", -1))
    if "Residual" in ins:
        # the residual stage: same-shape skip add between bias and
        # ReLU, with elementwise_add's promotion — exactly the op the
        # fold erased
        y = y + _bcast_y(y, ins["Residual"], -1)
    if attrs.get("fuse_relu"):
        y = jax.nn.relu(y)
    if "OutScale" in ins:
        from paddle_tpu.ops.epilogue import quantize_tail

        y = quantize_tail(y, ins["OutScale"], bnd)
    return {"Output": y}


@register_op("mul_int8", inputs=("X", "Y", "Scale", "InScale", "Bias",
                                 "OutScale"),
             outputs=("Out",), optional=("InScale", "Bias", "OutScale"),
             attrs={"x_num_col_dims": 1, "y_num_col_dims": 1,
                    "max_range": 127.0, "out_dtype": "float32",
                    "fuse_relu": False, "bias_axis": -1,
                    "epilogue": ""},
             differentiable=False)
def mul_int8(ins, attrs):
    """True-int8 mul: int8 x int8 matmul with int32 accumulation.
    Interlayer extensions mirror conv2d_int8's: int8-in (InScale
    mandatory), Bias/fuse_relu/OutScale requantize epilogue (int8-out),
    out_dtype="int32" raw accumulator — all except the per-input-row
    weight-scale convention, which folds into the activation BEFORE
    quantization and is therefore rejected in interlayer modes.

    Weight scale conventions (w ~= q * scale / max_range), decided by
    the scale's SHAPE so a square weight (K == N) stays unambiguous:
      - 2-D (K,1): per-input-row — folded into the activation BEFORE
        quantization so it factors out of the sum
      - 2-D (1,N): per-output-column — applied after the matmul
      - size 1: per-tensor
      - 1-D length-K/N falls back to the size heuristic (row wins on a
        square weight; pass a 2-D scale to disambiguate)
    """
    import numpy as np

    x, q, ws = ins["X"], ins["Y"], ins["Scale"]
    bnd = attrs["max_range"]
    xnc, ync = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
    x2 = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    q2 = q.reshape((int(np.prod(q.shape[:ync])), -1))
    k, n = q2.shape
    ws = jnp.asarray(ws)
    if ws.size == 1:
        per_row = per_col = False
    elif ws.ndim >= 2 and np.prod(ws.shape[1:]) == 1:  # (K,1,...)
        per_row, per_col = True, False
    elif ws.ndim >= 2 and ws.shape[0] == 1:            # (1,N)
        per_row, per_col = False, True
    else:  # 1-D: size heuristic, row convention wins when square
        per_row = ws.size == k
        per_col = not per_row and ws.size == n
    ws2 = ws.reshape(-1)
    post = None
    if per_row:             # fold into activation
        x2 = x2 * (ws2 / bnd).reshape(1, k)
    elif per_col:           # apply after
        post = (ws2 / bnd).reshape(1, n)
    else:                   # per-tensor
        post = ws2.reshape(()) / bnd
    if x.dtype == jnp.int8 or attrs["out_dtype"] == "int32":
        # interlayer mode (int8-in and/or raw-accumulator-out): the
        # per-row convention folds the weight scale into the ACTIVATION
        # before quantization, which is impossible once the activation
        # arrives pre-quantized (and makes a raw accumulator
        # scale-entangled) — the slim pass rejects such edges; the op
        # enforces the same contract
        if per_row:
            raise ValueError(
                "mul_int8: per-input-row weight scales are incompatible "
                "with int8-in/int32-out interlayer execution (the row "
                "scale folds into the activation pre-quantization)")
    if x.dtype == jnp.int8:
        if "InScale" not in ins:
            raise ValueError(
                "mul_int8: int8 input requires a calibrated InScale "
                "(the producer quantized to it)")
        sx = jnp.maximum(ins["InScale"].reshape(()).astype(jnp.float32),
                         1e-8)
        x8 = x2
    else:
        if "InScale" in ins:
            cal = jnp.maximum(
                ins["InScale"].reshape(()).astype(jnp.float32), 1e-8)
            if per_row:
                # the per-row weight scale folds into the activation
                # BEFORE quantization, so the calibrated raw-activation
                # scale must be widened by the largest row factor:
                # |x_k*s_k/bnd| <= cal*max(s)/bnd.  max over the
                # K-vector of weight scales is a trace-time-tiny
                # reduction, not an activation read — the whole point
                # of InScale is avoiding the latter.
                sx = cal * jnp.max(ws2) / bnd
            else:
                sx = cal
        else:
            sx = jnp.maximum(jnp.max(jnp.abs(x2)), 1e-8)
        x8 = jnp.clip(jnp.round(x2.astype(jnp.float32) / sx * bnd),
                      -bnd, bnd).astype(jnp.int8)
    y32 = lax.dot_general(x8, q2, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    if attrs["out_dtype"] == "int32":
        return {"Out": y32.reshape(x.shape[:xnc] + q.shape[ync:])}
    y = y32.astype(jnp.float32) * (sx / bnd)
    if post is not None:
        y = y * post
    y = y.astype(jnp.dtype(attrs["out_dtype"]))
    y = y.reshape(x.shape[:xnc] + q.shape[ync:])
    if "Bias" in ins:
        from paddle_tpu.ops.basic import _bcast_y

        y = y + _bcast_y(y, ins["Bias"], attrs.get("bias_axis", -1))
    if attrs.get("fuse_relu"):
        y = jax.nn.relu(y)
    if "OutScale" in ins:
        from paddle_tpu.ops.epilogue import quantize_tail

        y = quantize_tail(y, ins["OutScale"], bnd)
    return {"Out": y}


@register_op("fake_quantize_range_abs_max",
             inputs=("X", "InScale", "Iter"),
             outputs=("Out", "OutScale", "OutScales"),
             optional=("Iter",),
             attrs={"bit_length": 8, "window_size": 10000,
                    "is_test": False})
def fake_quantize_range_abs_max(ins, attrs):
    """fake_quantize_op.cc FakeQuantizeRangeAbsMax: scale = running max
    of abs-max over a window (window bookkeeping re-specified as simple
    running max — the training-time QAT estimator)."""
    x = ins["X"]
    bnd = float(2 ** (attrs["bit_length"] - 1) - 1)
    if attrs["is_test"]:
        scale = ins["InScale"].reshape(())
    else:
        scale = jnp.maximum(jnp.max(jnp.abs(x)),
                            ins["InScale"].reshape(()))
    scale = jnp.maximum(scale, 1e-8)  # dead activations: no 0/0 NaNs
    q = jnp.clip(jnp.round(x / scale * bnd), -bnd, bnd) * scale / bnd
    return {"Out": q, "OutScale": scale.reshape(1),
            "OutScales": scale.reshape(1)}


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=("X", "Scales"), outputs=("Out",),
             duplicable=("Scales",),
             attrs={"quant_bits": [8], "quant_axis": 0})
def fake_channel_wise_dequantize_max_abs(ins, attrs):
    """fake_dequantize_op.cc channel-wise: out = x * prod(scales)/prod(
    ranges) along quant_axis."""
    x = ins["X"]
    scales = ins["Scales"]
    bits = attrs["quant_bits"]
    ax = attrs["quant_axis"] % x.ndim
    shape = [1] * x.ndim
    shape[ax] = -1
    out = x.astype(jnp.float32)
    for s, b in zip(scales, list(bits) + [8] * (len(scales) - len(bits))):
        out = out * s.reshape(shape) / float(2 ** (b - 1) - 1)
        shape = [1] * x.ndim  # subsequent scales are scalars
    return {"Out": out}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             inputs=("X", "InScale", "InAccum", "InState"),
             outputs=("Out", "OutScale", "OutAccum", "OutState"),
             optional=("InAccum", "InState"),
             attrs={"bit_length": 8, "moving_rate": 0.9,
                    "is_test": False})
def fake_quantize_dequantize_moving_average_abs_max(ins, attrs):
    """fake_quantize_op.cc QuantizeDequantizeMovingAverageAbsMax (the
    QAT activation fake-quant with straight-through estimator)."""
    x = ins["X"]
    bnd = float(2 ** (attrs["bit_length"] - 1) - 1)
    rate = attrs["moving_rate"]
    cur = jnp.max(jnp.abs(x))
    if attrs["is_test"]:
        # pass the moving-average state THROUGH unchanged — these
        # outputs alias the persistent accum/state vars (the in-place
        # wiring convention), so writing the scale here would corrupt
        # them for a subsequent training resume
        scale = ins["InScale"].reshape(())
        accum = (ins["InAccum"] if ins.get("InAccum") is not None
                 else ins["InScale"])
        state = (ins["InState"] if ins.get("InState") is not None
                 else jnp.ones_like(ins["InScale"]))
    else:
        state0 = ins.get("InState")
        accum0 = ins.get("InAccum")
        state = (state0.reshape(()) * rate + 1.0
                 if state0 is not None else jnp.asarray(1.0))
        accum = (accum0.reshape(()) * rate + cur
                 if accum0 is not None else cur)
        scale = accum / state
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / scale * bnd), -bnd, bnd) * scale / bnd
    # straight-through estimator for grads
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": out, "OutScale": scale.reshape(1),
            "OutAccum": jnp.reshape(accum, (1,)),
            "OutState": jnp.reshape(state, (1,))}


@register_op("moving_average_abs_max_scale",
             inputs=("X", "InAccum", "InState"),
             outputs=("OutScale", "OutAccum", "OutState"),
             optional=("InAccum", "InState"),
             attrs={"moving_rate": 0.9, "is_test": False},
             differentiable=False)
def moving_average_abs_max_scale(ins, attrs):
    """fake_quantize_op.cc MovingAverageAbsMaxScale: scale observer
    without quantization (output-scale collection)."""
    x = ins["X"]
    rate = attrs["moving_rate"]
    cur = jnp.max(jnp.abs(x))
    state0, accum0 = ins.get("InState"), ins.get("InAccum")
    state = (state0.reshape(()) * rate + 1.0
             if state0 is not None else jnp.asarray(1.0))
    accum = (accum0.reshape(()) * rate + cur
             if accum0 is not None else cur)
    # write-time floor: a 0.0 scale recorded from an all-zero batch
    # reads as "uncalibrated" downstream (see
    # fake_quantize_moving_average_abs_max above)
    return {"OutScale": jnp.maximum(accum / state, 1e-8).reshape(1),
            "OutAccum": jnp.reshape(accum, (1,)),
            "OutState": jnp.reshape(state, (1,))}
