"""Pallas TPU kernels: fused flash attention.

Capability anchor: the reference computes attention as separate
matmul/softmax/matmul ops that materialize the [Tq, Tk] score matrix in
HBM (e.g. nets.py scaled_dot_product_attention,
/root/reference/python/paddle/fluid/nets.py:503-area; transformer tests
build it from `layers.matmul` + `layers.softmax`).  On TPU the score
matrix is the HBM-bandwidth bottleneck, so here attention is a single
Pallas kernel: blockwise QK^T on the MXU with online-softmax
accumulation in VMEM scratch — the [Tq, Tk] matrix never leaves VMEM
(FlashAttention pattern).

Layout: q/k/v are [B, H, T, D] (the transformer model's post-split-heads
layout).  Grid is (B*H/hpb, Tq/block_q, Tk/block_k) with the KV
dimension innermost so the (acc, m, l) scratch carries across KV steps;
hpb is the heads-per-block packing factor (1, or 2 under the
`flash_head_pack` flag — see below).

The public `flash_attention` is differentiable via custom_vjp: forward
runs the Pallas kernel on TPU (plain XLA path elsewhere) and saves
(q, k, v, o, lse); backward runs dedicated Pallas kernels (two-pass
FlashAttention bwd: a dq sweep and a dk/dv sweep that recompute P
blockwise from lse) — the [Tq, Tk] matrices stay in VMEM in both
directions.  The XLA impl keeps the plain einsum replay.

Memory-layout variants (docs/FLASH_ATTENTION.md; both default OFF until
the chip chaser validates them — zero behavior change under the
defaults):

* packed row-stats (`flash_packed_stats`): the per-row log-sum-exp is
  stored packed as [B*H, T/128, 128] f32 (row r -> (r//128, r%128))
  instead of 128x lane-replicated [B*H, T, 128], and the backward reads
  lse/delta through the same packed layout instead of materializing two
  more replicated broadcasts as kernel inputs.  At seq-1M x 8 heads the
  replicated layout is ~12 GB of pure replication — the OOM that capped
  the long-context ladder (docs/NEXT.md item 5).  Mosaic's f32 (8, 128)
  sublane rule makes the packed (bq/128, 128) output block legal only
  for block_q >= 1024; smaller blocks silently keep the replicated
  layout (the documented fallback).

* head packing (`flash_head_pack`): at head_dim <= 64 the MXU runs
  half-width (a d-64 contraction pads to the 128-deep systolic array),
  so d64 wall time equals d128's with half the useful FLOPs banked
  (16.46% vs 32.99% MFU at seq 32k).  With packing, TWO (batch, head)
  rows ride in each grid step (block leading dim 2, grid dim 0 halved):
  the two heads are independent MXU/VPU dependency chains inside one
  step, so the Mosaic scheduler can overlap head A's VPU softmax with
  head B's matmuls instead of serializing them across grid steps (the
  (m, l, acc) carry forces sequential KV steps per head).  Requires an
  even B*H; odd products fall back to one head per step.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.observability import device_trace as _obs_device
from paddle_tpu.observability import tracing as _obs_trace

_NEG_INF = -1e30
_MIN_LANES = 128  # TPU vector lane count; m/l scratch padded to this
_F32_SUBLANES = 8  # f32 min sublane tile — gates the packed-stats block

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; support
# both so the kernels lower under the CI jax as well as the chip
# host's (the TPU cross-lowering tests failed on exactly this drift)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


# ---------------------------------------------------------------------------
# reference (XLA) implementation — also the backward path
# ---------------------------------------------------------------------------

def _plain_attention(q, k, v, causal, scale):
    """q/k/v: [B, H, T, D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    p = None
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        qpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        mask = (qpos + (tk - tq) >= kpos)[None, None]
        s = jnp.where(mask, s, _NEG_INF)
        # fully-masked rows (tq > tk) output 0, matching the kernel
        p = jax.nn.softmax(s, axis=-1) * mask
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


# ---------------------------------------------------------------------------
# layout-variant gates + in-kernel row-stats relayout
# ---------------------------------------------------------------------------

def _packed_geom_ok(bq):
    """The packed [T/128, 128] row-stats block is (bq/128, 128): Mosaic
    requires the last two block dims to be (8k, 128m) for f32, so the
    packing is legal only when bq/128 >= 8 -> bq >= 1024."""
    return bq % _MIN_LANES == 0 and bq // _MIN_LANES >= _F32_SUBLANES


def _head_pack_geom_ok(bh, d):
    """Two heads per block: only profitable when the MXU runs
    half-width (d <= 64) and only legal when B*H pairs up evenly.
    Pairing is over the flattened B*H axis — any two rows are
    independent attention problems, so crossing a batch boundary is
    fine."""
    return d <= 64 and bh % 2 == 0


def _resolve_variants(packed_stats, head_pack):
    """None -> the typed flags; explicit bools win (tests, ring/Ulysses
    chunk dispatch)."""
    from paddle_tpu.flags import get_flag

    if packed_stats is None:
        packed_stats = get_flag("flash_packed_stats") == "on"
    if head_pack is None:
        head_pack = get_flag("flash_head_pack") == "on"
    return bool(packed_stats), bool(head_pack)


def _relayout_how():
    from paddle_tpu.flags import get_flag

    return get_flag("flash_relayout")


def _rows_to_packed(rows, bq):
    """Per-row vector [bq] -> packed [bq/128, 128] (row r -> (r//128,
    r%128)).  'reshape' lowers under Mosaic on jax 0.4.37 (verified via
    the cross-lowering gate); 'dot' is the guaranteed-lowerable escape
    hatch — iota/compare/select plus one indicator matmul (bq^2 MACs,
    once per q-block finalize, negligible)."""
    if _relayout_how() == "dot":
        rows_repl = jnp.broadcast_to(rows[:, None], (bq, _MIN_LANES))
        r = lax.broadcasted_iota(jnp.int32, (bq, _MIN_LANES), 0)
        c = lax.broadcasted_iota(jnp.int32, (bq, _MIN_LANES), 1)
        sel = jnp.where((r % _MIN_LANES) == c, rows_repl, 0.0)
        gi = lax.broadcasted_iota(jnp.int32, (bq // _MIN_LANES, bq), 0)
        gr = lax.broadcasted_iota(jnp.int32, (bq // _MIN_LANES, bq), 1)
        ind = ((gr // _MIN_LANES) == gi).astype(jnp.float32)
        return lax.dot_general(ind, sel, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    return rows.reshape(bq // _MIN_LANES, _MIN_LANES)


def _packed_to_rows(packed, bq):
    """Packed [bq/128, 128] -> per-row vector [bq] (inverse of
    _rows_to_packed; same strategy flag)."""
    if _relayout_how() == "dot":
        gr = lax.broadcasted_iota(jnp.int32, (bq, bq // _MIN_LANES), 0)
        gi = lax.broadcasted_iota(jnp.int32, (bq, bq // _MIN_LANES), 1)
        ind = ((gr // _MIN_LANES) == gi).astype(jnp.float32)
        u = lax.dot_general(ind, packed, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        r = lax.broadcasted_iota(jnp.int32, (bq, _MIN_LANES), 0)
        c = lax.broadcasted_iota(jnp.int32, (bq, _MIN_LANES), 1)
        return jnp.sum(jnp.where((r % _MIN_LANES) == c, u, 0.0), axis=1)
    return packed.reshape(bq)


def _stat_rows(ref, h, block_q, packed):
    """Per-row stats vector [bq] for head-slot h from a backward stats
    input block: [hpb, bq, 128] lane-replicated (read lane 0) or packed
    [hpb, bq/128, 128]."""
    if packed:
        return _packed_to_rows(ref[h], block_q)
    return ref[h, :, 0]


# ---------------------------------------------------------------------------
# pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                l_ref, *, scale, causal, block_q, block_k, kv_len,
                q_off, packed, hpb):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal:
        # skip KV blocks strictly above the diagonal of this Q block
        run = (ki * block_k) <= (q_off + qi * block_q + block_q - 1)
    else:
        run = True
    # interior blocks (every position valid, fully below the causal
    # diagonal) skip mask construction entirely: the two [bq, bk]
    # iotas + compares + selects are VPU work on par with the exp
    # itself at head_dim 64, so specializing nearly halves VPU cost
    # on the dominant block population
    interior = (ki + 1) * block_k <= kv_len
    if causal:
        interior &= (ki * block_k + block_k - 1) <= (q_off + qi * block_q)

    def _accumulate(masked):
        # the mask depends only on (qi, ki) geometry — one per step,
        # shared by every packed head
        mask = None
        if masked:
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = kpos < kv_len          # padded keys contribute nothing
            if causal:
                qpos = q_off + qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                mask = mask & (qpos >= kpos)
        # the heads are independent dependency chains — the scheduler
        # interleaves their MXU and VPU work within the step (the whole
        # point of hpb=2 at d<=64)
        for h in range(hpb):
            q = q_ref[h]                  # [bq, d]
            k = k_ref[h]                  # [bk, d]
            v = v_ref[h]
            s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
            if masked:
                s = jnp.where(mask, s, _NEG_INF)
            m_prev = m_ref[h, :, 0]
            m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_next[:, None])
            if masked:
                # explicit zero for masked entries: a fully-masked row
                # would otherwise see exp(-1e30 - (-1e30)) = 1 and
                # accumulate garbage
                p = jnp.where(mask, p, 0.0)
            alpha = jnp.exp(m_prev - m_next)
            l_next = l_ref[h, :, 0] * alpha + jnp.sum(p, axis=-1)
            acc_ref[h] = acc_ref[h] * alpha[:, None] + lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[h] = jnp.broadcast_to(m_next[:, None],
                                        m_ref.shape[1:])
            l_ref[h] = jnp.broadcast_to(l_next[:, None],
                                        l_ref.shape[1:])

    @pl.when(run & interior)
    def _compute_fast():
        _accumulate(masked=False)

    @pl.when(run & ~interior)
    def _compute_edge():
        _accumulate(masked=True)

    @pl.when(ki == nk - 1)
    def _finalize():
        for h in range(hpb):
            l = l_ref[h, :, 0]
            l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 out
            o_ref[h, ...] = (acc_ref[h] / l[:, None]).astype(o_ref.dtype)
            # log-sum-exp per row, consumed by the backward kernels; for
            # a fully-masked row m=-inf and l was clamped to 1 ->
            # lse=-inf, whose exp(s - lse) entries are all masked off in
            # backward.
            rows = m_ref[h, :, 0] + jnp.log(l)
            if packed:
                # packed [bq/128, 128] block (row r -> (r//128, r%128)):
                # 128x less HBM than the replicated layout; legal only
                # for bq >= 1024 (f32 (8,128) sublane rule)
                lse_ref[h, ...] = _rows_to_packed(rows, block_q)
            else:
                # lane-replicated ([bq, 128]): Mosaic requires the last
                # two block dims to be (8k, 128m) or full — a [1, bq]
                # block is rejected by the TPU lowering (caught on the
                # first real-chip bench run; interpret-mode tests never
                # enforce tiling)
                lse_ref[h, ...] = jnp.broadcast_to(rows[:, None],
                                                   lse_ref.shape[1:])


def _pad_axis(x, axis, mult):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                      interpret=False, packed_stats=False,
                      head_pack=False):
    """q/k/v: [B, H, T, D] -> ([B, H, Tq, D], lse [B*H, Tq_padded])."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq = min(block_q, max(tq, 8))
    bk = min(block_k, max(tk, 8))
    qp = _pad_axis(q.reshape(b * h, tq, d), 1, bq)
    kp = _pad_axis(k.reshape(b * h, tk, d), 1, bk)
    vp = _pad_axis(v.reshape(b * h, tk, d), 1, bk)
    tq_p, tk_p = qp.shape[1], kp.shape[1]
    packed = packed_stats and _packed_geom_ok(bq)
    hpb = 2 if (head_pack and _head_pack_geom_ok(b * h, d)) else 1
    grid = (b * h // hpb, tq_p // bq, tk_p // bk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=tk, q_off=tk - tq if causal else 0, packed=packed,
        hpb=hpb)
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    if packed:
        lse_shape = (b * h, tq_p // _MIN_LANES, _MIN_LANES)
        lse_block = (hpb, bq // _MIN_LANES, _MIN_LANES)
    else:
        lse_shape = (b * h, tq_p, _MIN_LANES)
        lse_block = (hpb, bq, _MIN_LANES)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((hpb, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((hpb, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((hpb, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((hpb, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec(lse_block, lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
            jax.ShapeDtypeStruct(lse_shape, jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((hpb, bq, d), jnp.float32),
            pltpu.VMEM((hpb, bq, _MIN_LANES), jnp.float32),
            pltpu.VMEM((hpb, bq, _MIN_LANES), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(qp, kp, vp)
    # callers see the documented [B*H, Tq_padded] lse in EVERY layout:
    # packed unpacks with a free row-major reshape at the XLA boundary,
    # replicated strips the lanes
    lse2 = lse.reshape(b * h, tq_p) if packed else lse[:, :, 0]
    return (out[:, :tq, :].reshape(b, h, tq, d), lse2)


# ---------------------------------------------------------------------------
# pallas backward kernels (standard two-pass FlashAttention bwd)
# ---------------------------------------------------------------------------
# Recompute P blockwise from (q, k, lse); with delta = rowsum(dO * O):
#   dV = P^T dO
#   dS = P * (dO V^T - delta) * scale
#   dQ = dS K ;  dK = dS^T Q
# The [Tq, Tk] matrices never leave VMEM — the previous bwd replayed
# plain attention in XLA, materializing P in HBM (docs/PROFILE_r4.md
# headroom #1).

def _bwd_interior(*, causal, block_q, block_k, kv_len, q_len, q_off,
                  qi, ki):
    """Traced predicate: this (qi, ki) block needs no mask — all kv
    and q positions valid, fully below the causal diagonal."""
    interior = ((ki + 1) * block_k <= kv_len) \
        & ((qi + 1) * block_q <= q_len)
    if causal:
        interior &= (ki * block_k + block_k - 1) <= (q_off + qi * block_q)
    return interior


def _bwd_p_ds_block(q, k, v, do, lse, delta, *, scale, causal,
                    block_q, block_k, kv_len, q_len, q_off, qi, ki,
                    masked=True):
    """Recompute the probability block P [bq, bk] (forward's mask plus
    a valid-q-row mask — padded q rows must contribute nothing to
    dk/dv) and the score gradient dS = P * (dO V^T - delta) * scale.
    With masked=False (interior blocks, see _bwd_interior) the mask
    iotas/compares/selects are skipped entirely."""
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if masked:
        kpos = ki * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        qrow = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = (kpos < kv_len) & (qrow < q_len)
        if causal:
            mask = mask & ((q_off + qrow) >= kpos)
        # masked entries (incl. fully-masked rows where lse=-1e30) -> 0
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
    else:
        p = jnp.exp(s - lse[:, None])
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref, *, scale, causal, block_q,
                   block_k, kv_len, q_len, q_off, packed, hpb):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if causal:
        run = (ki * block_k) <= (q_off + qi * block_q + block_q - 1)
    else:
        run = True
    interior = _bwd_interior(causal=causal, block_q=block_q,
                             block_k=block_k, kv_len=kv_len,
                             q_len=q_len, q_off=q_off, qi=qi, ki=ki)

    def _accumulate(masked):
        for h in range(hpb):
            q, k, v = q_ref[h], k_ref[h], v_ref[h]
            do = do_ref[h].astype(jnp.float32)
            _, ds = _bwd_p_ds_block(
                q, k, v, do,
                _stat_rows(lse_ref, h, block_q, packed),
                _stat_rows(delta_ref, h, block_q, packed),
                scale=scale,
                causal=causal, block_q=block_q, block_k=block_k,
                kv_len=kv_len, q_len=q_len, q_off=q_off, qi=qi, ki=ki,
                masked=masked)
            acc_ref[h] += lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(run & interior)
    def _compute_fast():
        _accumulate(masked=False)

    @pl.when(run & ~interior)
    def _compute_edge():
        _accumulate(masked=True)

    @pl.when(ki == nk - 1)
    def _finalize():
        for h in range(hpb):
            dq_ref[h, ...] = acc_ref[h].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, kv_len, q_len, q_off, packed,
                    hpb):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if causal:
        # q blocks entirely above the diagonal contribute nothing
        run = (ki * block_k) <= (q_off + qi * block_q + block_q - 1)
    else:
        run = True
    interior = _bwd_interior(causal=causal, block_q=block_q,
                             block_k=block_k, kv_len=kv_len,
                             q_len=q_len, q_off=q_off, qi=qi, ki=ki)

    def _accumulate(masked):
        for h in range(hpb):
            q, k, v = q_ref[h], k_ref[h], v_ref[h]
            do = do_ref[h].astype(jnp.float32)
            p, ds = _bwd_p_ds_block(
                q, k, v, do,
                _stat_rows(lse_ref, h, block_q, packed),
                _stat_rows(delta_ref, h, block_q, packed),
                scale=scale,
                causal=causal, block_q=block_q, block_k=block_k,
                kv_len=kv_len, q_len=q_len, q_off=q_off, qi=qi, ki=ki,
                masked=masked)
            dv_acc[h] += lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_acc[h] += lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(run & interior)
    def _compute_fast():
        _accumulate(masked=False)

    @pl.when(run & ~interior)
    def _compute_edge():
        _accumulate(masked=True)

    @pl.when(qi == nq - 1)
    def _finalize():
        for h in range(hpb):
            dk_ref[h, ...] = dk_acc[h].astype(dk_ref.dtype)
            dv_ref[h, ...] = dv_acc[h].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale, block_q,
                      block_k, interpret=False, dlse=None,
                      packed_stats=False, head_pack=False):
    """q/k/v: [B, H, T, D]; lse: [B*H, Tq_padded]; g = dO.

    dlse ([B*H, Tq] or None): cotangent of the lse output when the
    caller consumes it (ring attention's cross-chunk merge).  Since
    d lse_r / d s_rc = p_rc, it folds into the delta term:
    dS = P*(dO V^T - delta) + P*dlse = P*(dO V^T - (delta - dlse)).

    Under the packed-stats layout, lse and delta ride into the kernels
    as [B*H, Tq_p/128, 128] free reshapes of the per-row vectors; the
    replicated layout instead materializes TWO 128x lane-broadcasts in
    HBM as kernel inputs (~8 GB at seq-1M x 8 heads — with the fwd lse
    the third, the seq-1M OOM).
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    bq = min(block_q, max(tq, 8))
    bk = min(block_k, max(tk, 8))
    qp = _pad_axis(q.reshape(b * h, tq, d), 1, bq)
    kp = _pad_axis(k.reshape(b * h, tk, d), 1, bk)
    vp = _pad_axis(v.reshape(b * h, tk, d), 1, bk)
    gp = _pad_axis(g.reshape(b * h, tq, d), 1, bq)
    tq_p, tk_p = qp.shape[1], kp.shape[1]
    packed = packed_stats and _packed_geom_ok(bq)
    hpb = 2 if (head_pack and _head_pack_geom_ok(b * h, d)) else 1
    # delta = rowsum(dO * O): cheap elementwise+reduce, done in XLA;
    # an lse cotangent subtracts from it (see docstring)
    delta_full = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32),
        axis=-1).reshape(b * h, tq)
    if dlse is not None:
        # the lse output (and so its cotangent) is q-block padded;
        # only the first tq rows are real
        delta_full = delta_full - dlse.reshape(b * h, -1)[:, :tq] \
            .astype(jnp.float32)
    delta = _pad_axis(delta_full, 1, bq)
    if packed:
        # free row-major reshapes of the [B*H, Tq_p] vectors — nothing
        # is materialized beyond the vectors themselves
        lse3 = lse.reshape(b * h, tq_p // _MIN_LANES, _MIN_LANES)
        delta3 = delta.reshape(b * h, tq_p // _MIN_LANES, _MIN_LANES)
        lblk = (hpb, bq // _MIN_LANES, _MIN_LANES)
    else:
        # lane-replicate the per-row vectors: [B*H, Tq_p] ->
        # [B*H, Tq_p, 128] (2-D [1, bq] blocks violate Mosaic's
        # last-two-dims tiling rule; same layout the forward kernel
        # emits for lse)
        lse3 = jnp.broadcast_to(lse[:, :, None],
                                (b * h, tq_p, _MIN_LANES))
        delta3 = jnp.broadcast_to(delta[:, :, None],
                                  (b * h, tq_p, _MIN_LANES))
        lblk = (hpb, bq, _MIN_LANES)
    q_off = tk - tq if causal else 0
    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  kv_len=tk, q_len=tq, q_off=q_off, packed=packed,
                  hpb=hpb)
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    qspec = pl.BlockSpec((hpb, bq, d), lambda bh, i, j: (bh, i, 0))
    lspec = pl.BlockSpec(lblk, lambda bh, i, j: (bh, i, 0))
    kspec = pl.BlockSpec((hpb, bk, d), lambda bh, i, j: (bh, j, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b * h // hpb, tq_p // bq, tk_p // bk),
        in_specs=[qspec, kspec, kspec, qspec, lspec, lspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((hpb, bq, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(qp, kp, vp, gp, lse3, delta3)

    # dkv grid: kv blocks outer, q blocks inner (accumulator carries
    # across the q sweep); block index maps swap i<->j roles
    qspec2 = pl.BlockSpec((hpb, bq, d), lambda bh, j, i: (bh, i, 0))
    lspec2 = pl.BlockSpec(lblk, lambda bh, j, i: (bh, i, 0))
    kspec2 = pl.BlockSpec((hpb, bk, d), lambda bh, j, i: (bh, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b * h // hpb, tk_p // bk, tq_p // bq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, lspec2, lspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk_p, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((hpb, bk, d), jnp.float32),
                        pltpu.VMEM((hpb, bk, d), jnp.float32)],
        interpret=interpret,
        **params,
    )(qp, kp, vp, gp, lse3, delta3)
    return (dq[:, :tq, :].reshape(b, h, tq, d),
            dk[:, :tk, :].reshape(b, h, tk, d),
            dv[:, :tk, :].reshape(b, h, tk, d))


# ---------------------------------------------------------------------------
# public differentiable entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, scale, block_q, block_k, impl,
           packed_stats, head_pack):
    if impl == "pallas":
        return _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                 block_k, packed_stats=packed_stats,
                                 head_pack=head_pack)[0]
    if impl == "interpret":
        return _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                 block_k, interpret=True,
                                 packed_stats=packed_stats,
                                 head_pack=head_pack)[0]
    return _plain_attention(q, k, v, causal, scale)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, impl,
                    packed_stats, head_pack):
    if impl in ("pallas", "interpret"):
        out, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                     block_k,
                                     interpret=impl == "interpret",
                                     packed_stats=packed_stats,
                                     head_pack=head_pack)
        return out, (q, k, v, out, lse)
    out = _plain_attention(q, k, v, causal, scale)
    return out, (q, k, v, None, None)


def _flash_bwd_rule(causal, scale, block_q, block_k, impl,
                    packed_stats, head_pack, res, g):
    q, k, v, o, lse = res
    if impl in ("pallas", "interpret"):
        return _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale,
                                 block_q, block_k,
                                 interpret=impl == "interpret",
                                 packed_stats=packed_stats,
                                 head_pack=head_pack)
    _, vjp = jax.vjp(
        lambda a, b, c: _plain_attention(a, b, c, causal, scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# -- (out, lse) variant: the mergeable summary ring attention needs ----

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret,
               packed_stats, head_pack):
    return _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                             interpret=interpret,
                             packed_stats=packed_stats,
                             head_pack=head_pack)


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k,
                   interpret, packed_stats, head_pack):
    out, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                 block_k, interpret=interpret,
                                 packed_stats=packed_stats,
                                 head_pack=head_pack)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret,
                   packed_stats, head_pack, res, g):
    q, k, v, o, lse = res
    do, dlse = g
    return _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale,
                             block_q, block_k, interpret=interpret,
                             dlse=dlse, packed_stats=packed_stats,
                             head_pack=head_pack)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(q, k, v, *, causal=False, scale=None,
                        block_q=None, block_k=None, impl=None,
                        packed_stats=None, head_pack=None):
    """Like flash_attention but also returns the per-row log-sum-exp
    ([B*H, Tq_padded_to_block]): (out, lse) is a complete mergeable
    attention summary — two chunks combine as
      m = max(lse1, lse2); a_i = exp(lse_i - m)
      out = (out1*a1 + out2*a2) / (a1 + a2); lse = m + log(a1 + a2)
    which is what ring attention accumulates across KV rotations.
    Differentiable in q, k, v including through lse consumers.

    packed_stats/head_pack: None -> the `flash_packed_stats` /
    `flash_head_pack` flags; explicit bools override.  The returned lse
    is layout-independent ([B*H, Tq_padded]) in every mode."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl is None:
        impl = "pallas" if _on_tpu() else "interpret"
    block_q = block_q or _default_block(q.shape[-2])
    block_k = block_k or _default_block(k.shape[-2])
    packed_stats, head_pack = _resolve_variants(packed_stats, head_pack)
    return _flash_lse(q, k, v, causal, float(scale), block_q, block_k,
                      impl == "interpret", packed_stats, head_pack)


def _default_block(t):
    """Default tile edge for a sequence length of t.

    Pinned by the 2026-08-01 on-chip sweep (tools/flash_block_sweep.py,
    v5e, seq 32k d64): 1024x1024 ran fwd+bwd 1.5x faster than the old
    512x512 default (76.9 ms vs 116.8).  Short sequences keep 512 —
    the kernel clamps to T anyway and seq-512 shapes showed no win
    from smaller tiles."""
    return 1024 if t >= 1024 else 512


def flash_attention(q, k, v, *, causal=False, scale=None, block_q=None,
                    block_k=None, impl=None, packed_stats=None,
                    head_pack=None):
    """Fused attention. q/k/v: [B, H, T, D]; returns [B, H, Tq, D].

    impl: None (auto: pallas on TPU, XLA elsewhere), "pallas",
    "interpret" (pallas interpret mode, for CPU tests), or "xla".
    block_q/block_k default to a size picked by sequence length
    (_default_block).

    packed_stats / head_pack: memory-layout variants (module
    docstring, docs/FLASH_ATTENTION.md).  None defers to the
    `flash_packed_stats` / `flash_head_pack` flags (both default off);
    explicit bools override — outputs are identical in every mode, only
    the kernel's HBM layout and grid packing change.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    block_q = block_q or _default_block(q.shape[-2])
    block_k = block_k or _default_block(k.shape[-2])
    packed_stats, head_pack = _resolve_variants(packed_stats, head_pack)
    if _obs_trace._tracer is not None:
        # device-time attribution (ISSUE 10): annotate the entry with
        # the active trace id (runtime) or a named_scope (inside a jit
        # trace) — one module-global check when tracing is off
        with _obs_device.annotate("flash_attention"):
            return _flash(q, k, v, causal, float(scale), block_q,
                          block_k, impl, packed_stats, head_pack)
    return _flash(q, k, v, causal, float(scale), block_q, block_k, impl,
                  packed_stats, head_pack)


def _on_tpu():
    """True when the default device is a TPU chip.  Checked via the
    DEVICE, not jax.default_backend(): tunnel backends (e.g. the axon
    plugin) report their own platform name while the chip's
    device_kind still says 'TPU ...' — keying on the backend name
    would silently fall back to plain XLA attention on real hardware
    (round-3 verdict do-this #2)."""
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return dev.platform == "tpu" or \
        "tpu" in str(getattr(dev, "device_kind", "")).lower()


# ---------------------------------------------------------------------------
# flash decode: q_len=1 attention over a paged KV-cache (ISSUE 7)
# ---------------------------------------------------------------------------
# Decode-step attention for autoregressive serving: ONE query token per
# sequence attends over that sequence's whole cached prefix, with K/V
# streamed page-by-page from the ops/paged_kv.py pool through the
# per-sequence block table (vLLM PagedAttention shape).  The grid is
# (B, H/hpb, max_pages) — a split-K sweep over pages with the KV
# dimension innermost so the (acc, m, l) scratch carries across pages;
# each page's partial (out, lse) merges into the carry by EXACTLY the
# PR-2 mergeable-summary contract (m = max(m1, m2); a_i = exp(m_i - m);
# out = sum out_i*a_i / sum l_i*a_i) — the same formula ring attention
# uses across chunks, here applied page-by-page inside one kernel.
#
# Geometry notes (the Mosaic lessons from PR 1/2 applied):
#   * pages are [P, H, page_size, d] (head-major) so the per-step block
#     is (1, hpb, page_size, d) with legal trailing dims; a token-major
#     pool would put a size-1 head slice in the sublane position (the
#     rejected [1, bq] construct class).
#   * the single query row is sublane-replicated to 8 rows (16 for
#     bf16 — the (16, 128) bf16 tile rule) host-side; every row
#     computes the identical result and the caller takes row 0.  The
#     replication is ~B*H*16*d*4 bytes — noise next to the page
#     streaming this kernel exists to bound.
#   * the block table and sequence lengths ride in as SCALAR PREFETCH
#     (SMEM) so the K/V BlockSpec index maps can address physical pages
#     (blk[b, p]) before the body runs — the standard paged-attention
#     Pallas shape.
#   * head packing (flag `flash_head_pack`, same gate spirit as the
#     fwd kernel): at d <= 64 two heads of the SAME sequence ride per
#     grid step (block (1, 2, ...)), needing H even — the pairing must
#     not cross a batch boundary because both heads share one block
#     table entry.
#
# int8 KV (`kv_int8`): pages hold the PR-5 per-channel contract
# (q = clip(round(x/s*127))); the kernel dequantizes IN VMEM with the
# precomputed per-(head, dim) multiplier s/127, so what streams from
# HBM is int8 — the decode step's traffic is K/V-dominated, so this is
# the same structural cut int8-interlayer made for conv activations.
#
# Not differentiable (decode is inference); no custom_vjp.

_DECODE_VMEM_BUDGET = 12 * 2 ** 20  # conservative per-core VMEM cap
_SUBLANES_BY_DTYPE = {jnp.dtype(jnp.float32): 8,
                      jnp.dtype(jnp.bfloat16): 16,
                      jnp.dtype(jnp.int8): 32}


def _decode_qrows(dtype, q_len=1):
    """Sublane rows of the query block: the min sublane tile of the
    q/output dtype (f32 8, bf16 16) rounded up to hold q_len rows —
    q_len = 1 is the decode step (row 0 replicated), q_len = k+1 is
    the speculative verify step (ISSUE 11c: the last k+1 positions of
    each sequence ride as distinct rows, per-row causal masks)."""
    t = _SUBLANES_BY_DTYPE.get(jnp.dtype(dtype), 8)
    return -(-int(q_len) // t) * t


def _decode_hpb(head_pack, n_heads, d):
    """Heads per grid step: 2 when packing is on, profitable (d <= 64,
    the half-idle-MXU regime) and legal (H even — both packed heads
    share one block-table entry, so the pair must not straddle a
    sequence boundary)."""
    return 2 if (head_pack and d <= 64 and n_heads % 2 == 0) else 1


def _decode_geom_ok(q, k_pages, hpb, vmem_budget_bytes=None,
                    q_len=1):
    """True when the Pallas path is legal + fits VMEM; False routes to
    the gather+reference fallback (documented, silent — same shape as
    the packed-stats bq gate)."""
    d = q.shape[-1]
    ps = k_pages.shape[2]
    store = jnp.dtype(k_pages.dtype)
    if ps % _SUBLANES_BY_DTYPE.get(store, 8) != 0:
        return False
    qrows = _decode_qrows(jnp.float32 if store == jnp.int8
                          else q.dtype, q_len)
    budget = vmem_budget_bytes or _DECODE_VMEM_BUDGET
    # double-buffered K+V page blocks + q/o/acc + the two row-stat
    # scratches
    page_bytes = 2 * 2 * hpb * ps * d * store.itemsize
    row_bytes = hpb * qrows * (3 * d + 2 * _MIN_LANES) * 4
    return page_bytes + row_bytes <= budget


def _decode_kernel(blk_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page_size, hpb,
                   qrows, int8kv, q_len=1):
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    # pages at or past the sequence length contribute nothing — skip
    # them outright (their block-table entries point at valid page 0,
    # so the prefetch window stays in bounds either way)
    live = (p * page_size) < kv_len

    @pl.when(live)
    def _step():
        kpos = p * page_size + lax.broadcasted_iota(
            jnp.int32, (qrows, page_size), 1)
        if q_len == 1:
            # the decode step: every sublane row replicates the ONE
            # query, one shared mask (the validated PR-7 lowering —
            # this branch is byte-identical to it)
            mask = kpos < kv_len
        else:
            # speculative verify (ISSUE 11c): row r is the query at
            # position kv_len - q_len + r, causal WITHIN the window —
            # row r sees keys < kv_len - q_len + 1 + r.  Padding rows
            # (r >= q_len) clamp to kv_len; the caller discards them.
            row = lax.broadcasted_iota(
                jnp.int32, (qrows, page_size), 0)
            limit = jnp.minimum(kv_len,
                                kv_len - q_len + 1 + row)
            mask = kpos < limit
        for h in range(hpb):
            q = q_ref[0, h]                      # [qrows, d]
            k = k_ref[0, h]                      # [page_size, d]
            v = v_ref[0, h]
            if int8kv:
                # int8 pages convert in VMEM; the per-channel dequant
                # scales were algebraically relocated OFF the page by
                # the wrapper (sum_d q_d*(k_td*s_d) == sum_d
                # (q_d*s_d)*k_td, so the K scale pre-multiplied q
                # host-side; the per-output-channel V scale applies to
                # the final acc/l outside the kernel).  What streams
                # from HBM is the raw int8 page — and the kernel body
                # carries zero scale-multiply VPU work per page.
                k = k.astype(jnp.float32)
                v = v.astype(jnp.float32)
            s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
                * scale
            s = jnp.where(mask, s, _NEG_INF)
            m_prev = m_ref[h, :, 0]
            m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p_ = jnp.exp(s - m_next[:, None])
            # explicit zero for masked entries (a fully-masked row
            # would otherwise see exp(-1e30 - (-1e30)) = 1)
            p_ = jnp.where(mask, p_, 0.0)
            alpha = jnp.exp(m_prev - m_next)
            l_next = l_ref[h, :, 0] * alpha + jnp.sum(p_, axis=-1)
            acc_ref[h] = acc_ref[h] * alpha[:, None] + lax.dot_general(
                p_ if int8kv else p_.astype(v.dtype), v,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[h] = jnp.broadcast_to(m_next[:, None],
                                        m_ref.shape[1:])
            l_ref[h] = jnp.broadcast_to(l_next[:, None],
                                        l_ref.shape[1:])

    @pl.when(p == n_p - 1)
    def _finalize():
        for h in range(hpb):
            l = l_ref[h, :, 0]
            l = jnp.where(l == 0.0, 1.0, l)  # zero-length seq -> 0 out
            o_ref[0, h] = (acc_ref[h] / l[:, None]).astype(o_ref.dtype)


def _flash_decode_pallas(q, k_pages, v_pages, block_tables, seq_lens,
                         scale, hpb, interpret=False, q_len=1):
    """q: [B, H, d] (q_len 1) or [B, R, H, d] (q_len R — the verify
    step; K-scale pre-applied in int8 mode either way); pools
    [P, H, ps, d]; block_tables [B, MP] int32; seq_lens [B] int32
    (INCLUDING the R window tokens) -> out [B, H, d] / [B, R, H, d]
    (f32 in int8 mode — the V scale applies outside)."""
    ps = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    qrows = _decode_qrows(q.dtype, q_len)
    int8kv = jnp.dtype(k_pages.dtype) == jnp.int8
    if q_len == 1:
        b, h, d = q.shape
        q8 = jnp.broadcast_to(q[:, :, None, :], (b, h, qrows, d))
    else:
        b, _, h, d = q.shape
        # rows 0..R-1 are the R real queries; padding rows repeat the
        # last one (masked identically to it, discarded by the caller)
        qr = jnp.transpose(q, (0, 2, 1, 3))          # [B, H, R, d]
        pad = jnp.broadcast_to(qr[:, :, -1:, :],
                               (b, h, qrows - q_len, d))
        q8 = jnp.concatenate([qr, pad], axis=2) if qrows > q_len \
            else qr
    kernel = functools.partial(_decode_kernel, scale=scale,
                               page_size=ps, hpb=hpb, qrows=qrows,
                               int8kv=int8kv, q_len=q_len)
    in_specs = [
        pl.BlockSpec((1, hpb, qrows, d),
                     lambda bi, hi, pi, blk, ln: (bi, hi, 0, 0)),
        pl.BlockSpec((1, hpb, ps, d),
                     lambda bi, hi, pi, blk, ln: (blk[bi, pi], hi, 0,
                                                  0)),
        pl.BlockSpec((1, hpb, ps, d),
                     lambda bi, hi, pi, blk, ln: (blk[bi, pi], hi, 0,
                                                  0)),
    ]
    args = [q8, k_pages, v_pages]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h // hpb, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, hpb, qrows, d),
            lambda bi, hi, pi, blk, ln: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hpb, qrows, d), jnp.float32),
            pltpu.VMEM((hpb, qrows, _MIN_LANES), jnp.float32),
            pltpu.VMEM((hpb, qrows, _MIN_LANES), jnp.float32),
        ])
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (b, h, qrows, d),
            jnp.float32 if int8kv else q.dtype),
        interpret=interpret,
        **params,
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(seq_lens, jnp.int32), *args)
    if q_len == 1:
        return out[:, :, 0, :]
    return jnp.transpose(out[:, :, :q_len, :], (0, 2, 1, 3))


def flash_decode_reference(q, k_pages, v_pages, block_tables, seq_lens,
                           scale=None, kv_scales=None):
    """Gather + reference attention replay: the flash_decode fallback
    path (VMEM budget / geometry gate / off-TPU impl) AND the parity
    oracle.  It gathers the pages dense through the block table and
    replays the kernel's page-ordered online-softmax merge with the
    SAME op order, shapes and rounding points (q sublane-replicated,
    per-page dot/max/exp/fma in f32, post-exp masking), so
    flash_decode output is array_equal to this path in every mode —
    the bit-parity contract PR 4 established for fused-vs-unfused.
    Mathematically it equals plain softmax(QK^T)V over the first
    seq_len cached tokens (allclose; asserted in tests).

    Runs as ONE jitted computation on purpose: the interpret/pallas
    kernel executes its whole grid inside one XLA computation, where
    the compiler contracts ``acc*alpha + dot(...)`` into an FMA; an
    eager op-by-op replay rounds the multiply and add separately and
    drifts 1 ulp per page (measured) — jitting the replay restores
    the identical fusion, and the production fallback runs under the
    caller's jit anyway.  The int8-KV dequant multiplies stay EAGER
    and outside the jitted region in BOTH paths (pre-scaled q, V scale
    on the final output) for the same reason — inside, the compiler
    folds them into the dots differently per path."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    bt = jnp.asarray(block_tables, jnp.int32)
    sl = jnp.asarray(seq_lens, jnp.int32)
    q_len = 1 if q.ndim == 3 else int(q.shape[1])
    if jnp.dtype(k_pages.dtype) == jnp.int8:
        q_eff, vdq = _int8_pre(q, kv_scales)
        if q_len == 1:
            raw = _decode_reference_jit(q_eff, k_pages, v_pages, bt,
                                        sl, jnp.float32(scale))
        else:
            raw = _decode_reference_multi_jit(
                q_eff, k_pages, v_pages, bt, sl, jnp.float32(scale),
                q_len)
        return _int8_post(raw, vdq, q.dtype)
    if q_len == 1:
        return _decode_reference_jit(q, k_pages, v_pages, bt, sl,
                                     jnp.float32(scale))
    return _decode_reference_multi_jit(q, k_pages, v_pages, bt, sl,
                                       jnp.float32(scale), q_len)


def _int8_pre(q, kv_scales):
    """Eager int8-KV dequant prologue shared by kernel + reference:
    the per-channel K scale rides the contraction dim, so
    sum_d q_d*(k_td*s_d) == sum_d (q_d*s_d)*k_td — pre-scale q once
    ([B, H, d] or [B, R, H, d]) instead of dequantizing every page
    ([ps, d] per step)."""
    if kv_scales is None:
        raise ValueError("int8 k_pages/v_pages need kv_scales "
                         "(per-channel [H, d] — paged_kv.kv_scales())")
    kdq = kv_scales[0].astype(jnp.float32) / 127.0
    vdq = kv_scales[1].astype(jnp.float32) / 127.0
    kdq = kdq[None, :, :] if q.ndim == 3 else kdq[None, None, :, :]
    return q.astype(jnp.float32) * kdq, vdq


def _int8_post(raw, vdq, out_dtype):
    """Eager int8-KV epilogue: the V scale is per OUTPUT channel, so
    it moves out of the page accumulation onto the final
    [B, H, d] / [B, R, H, d]."""
    vdq = vdq[None, :, :] if raw.ndim == 3 else vdq[None, None, :, :]
    return (raw * vdq).astype(out_dtype)


def _decode_reference_impl(q, k_pages, v_pages, block_tables, seq_lens,
                           scale):
    b, h, d = q.shape
    ps = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    qrows = _decode_qrows(q.dtype)
    int8kv = jnp.dtype(k_pages.dtype) == jnp.int8
    q8 = jnp.broadcast_to(q[:, :, None, :], (b, h, qrows, d))
    # gather [B, MP, H, ps, d] (the dense copy the kernel avoids)
    kg = jnp.take(k_pages, jnp.asarray(block_tables, jnp.int32),
                  axis=0)
    vg = jnp.take(v_pages, jnp.asarray(block_tables, jnp.int32),
                  axis=0)
    lens = jnp.asarray(seq_lens, jnp.int32)
    m = jnp.full((b, h, qrows), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, qrows), jnp.float32)
    acc = jnp.zeros((b, h, qrows, d), jnp.float32)

    # ONE lax.scan over pages, not an unrolled python loop: the body
    # compiles once however wide the block table is (a 32k-token
    # sequence is a 512-wide table — unrolled, XLA's compile time
    # exploded on exactly that width, found by the chunked-join SLO
    # leg).  The per-page op order is unchanged, so kernel parity
    # holds bit-for-bit.
    def page_step(carry, inputs):
        m, l, acc = carry
        p, k, v = inputs                            # [B, H, ps, d]
        if int8kv:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
        kpos = p * ps + lax.broadcasted_iota(
            jnp.int32, (qrows, ps), 1)
        mask = kpos[None, None] < lens[:, None, None, None]
        s = jnp.einsum("bhqd,bhkd->bhqk", q8, k,
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask, s, _NEG_INF)
        m_next = jnp.maximum(m, jnp.max(s, axis=-1))
        p_ = jnp.exp(s - m_next[..., None])
        p_ = jnp.where(mask, p_, 0.0)
        alpha = jnp.exp(m - m_next)
        l = l * alpha + jnp.sum(p_, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_ if int8kv else p_.astype(v.dtype),
            v, preferred_element_type=jnp.float32)
        return (m_next, l, acc), None

    (m, l, acc), _ = lax.scan(
        page_step, (m, l, acc),
        (jnp.arange(max_pages, dtype=jnp.int32),
         jnp.moveaxis(kg, 1, 0), jnp.moveaxis(vg, 1, 0)))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l[..., None]).astype(q.dtype)
    return out[:, :, 0, :]


_decode_reference_jit = jax.jit(_decode_reference_impl)


def _decode_reference_multi_impl(q, k_pages, v_pages, block_tables,
                                 seq_lens, scale, q_len):
    """q-len-R twin of _decode_reference_impl (the verify-step oracle,
    ISSUE 11c): q [B, R, H, d], per-row causal masks mirroring the
    kernel's minimum(kv_len, kv_len - R + 1 + row) rule with the SAME
    op order / shapes / rounding points, so flash_decode at q_len > 1
    is array_equal to this in every mode."""
    b, rr, h, d = q.shape
    ps = k_pages.shape[2]
    max_pages = block_tables.shape[1]
    qrows = _decode_qrows(q.dtype, q_len)
    int8kv = jnp.dtype(k_pages.dtype) == jnp.int8
    qr = jnp.transpose(q, (0, 2, 1, 3))              # [B, H, R, d]
    if qrows > rr:
        pad = jnp.broadcast_to(qr[:, :, -1:, :],
                               (b, h, qrows - rr, d))
        q8 = jnp.concatenate([qr, pad], axis=2)
    else:
        q8 = qr
    kg = jnp.take(k_pages, jnp.asarray(block_tables, jnp.int32),
                  axis=0)
    vg = jnp.take(v_pages, jnp.asarray(block_tables, jnp.int32),
                  axis=0)
    lens = jnp.asarray(seq_lens, jnp.int32)
    m = jnp.full((b, h, qrows), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, qrows), jnp.float32)
    acc = jnp.zeros((b, h, qrows, d), jnp.float32)
    row = lax.broadcasted_iota(jnp.int32, (qrows, ps), 0)
    limit = jnp.minimum(
        lens[:, None, None, None],
        lens[:, None, None, None] - q_len + 1 + row[None, None])

    # same compile-scaling rule as the q-len-1 replay: ONE lax.scan
    # over pages, body compiled once however wide the table is
    def page_step(carry, inputs):
        m, l, acc = carry
        p, k, v = inputs                            # [B, H, ps, d]
        if int8kv:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
        kpos = p * ps + lax.broadcasted_iota(
            jnp.int32, (qrows, ps), 1)
        mask = kpos[None, None] < limit
        s = jnp.einsum("bhqd,bhkd->bhqk", q8, k,
                       preferred_element_type=jnp.float32) * scale
        s = jnp.where(mask, s, _NEG_INF)
        m_next = jnp.maximum(m, jnp.max(s, axis=-1))
        p_ = jnp.exp(s - m_next[..., None])
        p_ = jnp.where(mask, p_, 0.0)
        alpha = jnp.exp(m - m_next)
        l = l * alpha + jnp.sum(p_, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_ if int8kv else p_.astype(v.dtype),
            v, preferred_element_type=jnp.float32)
        return (m_next, l, acc), None

    (m, l, acc), _ = lax.scan(
        page_step, (m, l, acc),
        (jnp.arange(max_pages, dtype=jnp.int32),
         jnp.moveaxis(kg, 1, 0), jnp.moveaxis(vg, 1, 0)))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l[..., None]).astype(q.dtype)
    return jnp.transpose(out[:, :, :rr, :], (0, 2, 1, 3))


_decode_reference_multi_jit = jax.jit(_decode_reference_multi_impl,
                                      static_argnums=(6,))


def flash_decode(q, k_pages, v_pages, block_tables, seq_lens, *,
                 scale=None, impl=None, head_pack=None,
                 kv_scales=None, vmem_budget_bytes=None):
    """Paged-KV decode-step attention.  q: [B, H, d] (ONE query token
    per sequence) or [B, R, H, d] (the SPECULATIVE VERIFY step, ISSUE
    11c: the R = k+1 newest tokens of each sequence as distinct query
    rows, row r causally seeing keys < seq_len - R + 1 + r);
    k_pages/v_pages: [num_pages, H, page_size, d] pool
    (ops/paged_kv.PagedKVCache layout; int8 pools need kv_scales =
    (k_scale, v_scale) per-channel [H, d]); block_tables: [B,
    max_pages] int32; seq_lens: [B] int32 — the FULL cached length,
    including the R window tokens in verify mode.  Returns [B, H, d]
    or [B, R, H, d].

    impl: None (auto: pallas on TPU, reference replay elsewhere),
    "pallas", "interpret", or "xla" (the gather+reference path).
    head_pack: None defers to the `flash_head_pack` flag; needs
    d <= 64 and an even H.  Every mode is bit-identical (array_equal)
    to flash_decode_reference — the parity contract tests pin across
    page boundaries, ragged lengths, d in {64, 128}, f32/bf16/int8-KV,
    head-packed and not, q_len 1 and k+1.  Verify row r is ALSO
    bit-identical to a q-len-1 call at seq_len - R + 1 + r (masked
    pages are exact no-ops in the online-softmax merge) — the
    numerical half of the lossless-speculation contract."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scale = float(scale)
    if head_pack is None:
        head_pack = _resolve_variants(None, None)[1]
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    q_len = 1 if q.ndim == 3 else int(q.shape[1])
    int8kv = jnp.dtype(k_pages.dtype) == jnp.int8
    if int8kv and kv_scales is None:
        raise ValueError("int8 k_pages/v_pages need kv_scales "
                         "(per-channel [H, d] — paged_kv.kv_scales())")
    hpb = _decode_hpb(head_pack, q.shape[-2], q.shape[-1])
    if impl in ("pallas", "interpret") and not _decode_geom_ok(
            q, k_pages, hpb, vmem_budget_bytes, q_len):
        impl = "xla"   # documented fallback: gather + reference replay
    if _obs_trace._tracer is not None:
        with _obs_device.annotate("flash_decode"):
            return _flash_decode_entry(q, k_pages, v_pages,
                                       block_tables, seq_lens, scale,
                                       impl, hpb, int8kv, kv_scales,
                                       q_len)
    return _flash_decode_entry(q, k_pages, v_pages, block_tables,
                               seq_lens, scale, impl, hpb, int8kv,
                               kv_scales, q_len)


def _flash_decode_entry(q, k_pages, v_pages, block_tables, seq_lens,
                        scale, impl, hpb, int8kv, kv_scales, q_len=1):
    if impl in ("pallas", "interpret"):
        if int8kv:
            q_eff, vdq = _int8_pre(q, kv_scales)
            raw = _flash_decode_pallas(
                q_eff, k_pages, v_pages, block_tables, seq_lens,
                scale, hpb, interpret=impl == "interpret",
                q_len=q_len)
            return _int8_post(raw, vdq, q.dtype)
        return _flash_decode_pallas(
            q, k_pages, v_pages, block_tables, seq_lens, scale, hpb,
            interpret=impl == "interpret", q_len=q_len)
    return flash_decode_reference(q, k_pages, v_pages, block_tables,
                                  seq_lens, scale=scale,
                                  kv_scales=kv_scales)


# ---------------------------------------------------------------------------
# IR op registration
# ---------------------------------------------------------------------------

from paddle_tpu.core.registry import register_op  # noqa: E402


def _gspmd_flash_shard_map(attrs, q, k, v, call):
    """GSPMD front-end hook (parallel/gspmd.py tag_attention_ops):
    when the typed `gspmd` flag is on and the op carries
    gspmd_batch_axis / gspmd_head_axis attrs, run the kernel under
    shard_map on the current mesh — Mosaic kernels can't ride XLA's
    automatic partitioner, and attention is independent per
    (batch, head) row so the dp x tp split is exact.  Any gate failing
    (flag off, no mesh, axis missing, dim not divisible, axis size 1)
    returns None and the caller runs the plain single-program path —
    the same geometric-fallback spirit as the packed-stats gate."""
    from paddle_tpu.flags import get_flag

    if not get_flag("gspmd"):
        return None
    ba = attrs.get("gspmd_batch_axis") or None
    ha = attrs.get("gspmd_head_axis") or None
    if not (ba or ha):
        return None
    from paddle_tpu.parallel import env as penv

    mesh = penv.get_mesh()
    if mesh is None:
        return None
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    bsz, hsz = q.shape[0], q.shape[1]
    if ba and (sizes.get(ba, 1) <= 1 or bsz % sizes.get(ba, 1) != 0):
        ba = None
    if ha and (sizes.get(ha, 1) <= 1 or hsz % sizes.get(ha, 1) != 0):
        ha = None
    if not (ba or ha):
        return None
    from jax.sharding import PartitionSpec as P

    spec = P(ba, ha, None, None)
    f = penv.shard_map(call, mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return f(q, k, v)


@register_op("flash_attention", inputs=("Q", "K", "V"), outputs=("Out",),
             attrs={"causal": False, "scale": 0.0, "block_q": 0,
                    "block_k": 0, "gspmd_batch_axis": "",
                    "gspmd_head_axis": ""})
def _flash_attention_op(ins, attrs):
    scale = attrs.get("scale") or None

    def call(q, k, v):
        return flash_attention(q, k, v,
                               causal=bool(attrs.get("causal")),
                               scale=scale,
                               block_q=attrs.get("block_q") or None,
                               block_k=attrs.get("block_k") or None)

    out = _gspmd_flash_shard_map(attrs, ins["Q"], ins["K"], ins["V"],
                                 call)
    if out is None:
        out = call(ins["Q"], ins["K"], ins["V"])
    return {"Out": out}


@register_op("flash_decode",
             inputs=("Q", "KPages", "VPages", "BlockTables", "SeqLens",
                     "KScale", "VScale"),
             outputs=("Out",), optional=("KScale", "VScale"),
             attrs={"scale": 0.0})
def _flash_decode_op(ins, attrs):
    """IR surface of the paged decode-step attention (module section
    above); KScale/VScale are the int8-KV per-channel dequant scales."""
    kv_scales = None
    if "KScale" in ins:
        kv_scales = (ins["KScale"], ins["VScale"])
    return {"Out": flash_decode(ins["Q"], ins["KPages"], ins["VPages"],
                                ins["BlockTables"], ins["SeqLens"],
                                scale=attrs.get("scale") or None,
                                kv_scales=kv_scales)}
