"""Control-flow and feed/fetch ops.

Reference parity:
  - feed/fetch: /root/reference/paddle/fluid/operators/controlflow/feed_op.cc,
    fetch_op.cc, framework/feed_fetch_method.cc
  - while: operators/controlflow/while_op.cc (sub-block attr)
  - conditional_block: operators/controlflow/conditional_block_op.cc
  - tensor_array read/write: controlflow/tensor_array_read_write_op.cc
  - print: operators/print_op.cc

In interpreter mode while/cond run the sub-block through the executor with a
child scope (reference semantics).  In compiled mode compiler.py lowers them
to lax.while_loop / lax.cond with the scope-carried vars as loop state —
XLA-friendly control flow with static shapes.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.executor import register_special_op
from paddle_tpu.core.program import BlockRef
from paddle_tpu.core.registry import REQUIRED, register_op


@register_special_op("feed")
def feed_op(op, block, scope, ctx):
    name = op.outputs["Out"][0]
    col = op.attrs.get("col", 0)
    key = op.inputs["X"][0] if op.inputs.get("X") else name
    val = ctx.feed.get(key if key in ctx.feed else name)
    if val is None:
        raise RuntimeError(f"feed variable '{name}' was not provided")
    scope.var(name).set(jnp.asarray(np.asarray(val)))


@register_special_op("fetch")
def fetch_op(op, block, scope, ctx):
    name = op.inputs["X"][0]
    var = scope.find_var(name)
    if var is None:
        raise RuntimeError(f"fetch '{name}': variable not found")
    ctx.fetch_results[name] = var.get()


@register_special_op("print")
def print_op(op, block, scope, ctx):
    name = op.inputs["In"][0]
    var = scope.find_var(name)
    msg = op.attrs.get("message", "")
    print(f"{msg}{name} = {np.asarray(var.get()) if var else None}")
    out_names = op.outputs.get("Out")
    if out_names and var is not None:
        scope.var(out_names[0]).set(var.get())


@register_op("print", inputs=("In",), outputs=("Out",),
             attrs={"message": "", "first_n": -1, "print_phase": "both"},
             host_only=True, differentiable=False)
def _print_compute(ins, attrs):
    return {"Out": ins["In"]}


@register_special_op("while")
def while_op(op, block, scope, ctx):
    """Runs sub-block until Condition is false (reference while_op.cc).
    Carried vars live in the parent scope; the sub-block reads/writes them."""
    sub_idx = op.attrs["sub_block"].idx
    cond_name = op.inputs["Condition"][0]
    max_iters = op.attrs.get("max_iters", 10_000_000)
    it = 0
    while bool(np.asarray(scope.find_var(cond_name).get())):
        child = scope  # reference uses step scopes; flat is fine host-side
        ctx.run_block(sub_idx, child)
        it += 1
        if it >= max_iters:
            raise RuntimeError("while op exceeded max_iters")


@register_special_op("conditional_block")
def conditional_block(op, block, scope, ctx):
    cond_name = op.inputs["Cond"][0]
    if bool(np.asarray(scope.find_var(cond_name).get()).reshape(-1)[0]):
        ctx.run_block(op.attrs["sub_block"].idx, scope)


@register_special_op("write_to_array")
def write_to_array(op, block, scope, ctx):
    arr_name = op.outputs["Out"][0]
    x = scope.find_var(op.inputs["X"][0]).get()
    i = int(np.asarray(scope.find_var(op.inputs["I"][0]).get()))
    var = scope.var(arr_name)
    arr = var.get() or []
    while len(arr) <= i:
        arr.append(None)
    arr[i] = x
    var.set(arr)


@register_special_op("read_from_array")
def read_from_array(op, block, scope, ctx):
    arr = scope.find_var(op.inputs["X"][0]).get()
    i = int(np.asarray(scope.find_var(op.inputs["I"][0]).get()))
    scope.var(op.outputs["Out"][0]).set(arr[i])


@register_op("array_length", inputs=("X",), outputs=("Out",),
             differentiable=False, host_only=True)
def array_length(ins, attrs):
    return {"Out": jnp.asarray(len(ins["X"]), jnp.int64)}


# ---------------------------------------------------------------------------
# structural op registrations
# ---------------------------------------------------------------------------
# The special handlers above (and compiler.py's lowerings) own execution;
# these registry entries exist so Block.append_op can validate attrs and so
# program serialization round-trips.  Reference analog: while/conditional
# ops are real registered operators (operators/controlflow/while_op.cc:58
# REGISTER_OPERATOR) whose Run drives the executor on a sub-block.

def _structural(ins, attrs):  # pragma: no cover
    raise RuntimeError("structural op must run via executor/compiler")


register_op("while", inputs=("Condition", "X"), outputs=("Out",),
            attrs={"sub_block": REQUIRED, "max_iters": 10_000_000,
                   "is_test": False},
            duplicable=("X", "Out"), optional=("X", "Out"),
            differentiable=False, host_only=True)(_structural)

register_op("conditional_block", inputs=("Cond", "X"), outputs=("Out",),
            attrs={"sub_block": REQUIRED, "is_scalar_condition": True},
            duplicable=("X", "Out"), optional=("X", "Out"),
            differentiable=False, host_only=True)(_structural)

register_op("cond", inputs=("Cond",), outputs=("Out",),
            attrs={"true_block": REQUIRED, "false_block": REQUIRED,
                   "true_out_names": [], "false_out_names": []},
            duplicable=("Out",), optional=("Out",),
            differentiable=False, host_only=True)(_structural)

def _static_rnn_grad_maker(op, grad_out_slots, block, grad_map,
                           no_grad_set=frozenset()):
    """Emit a static_rnn_grad op (BPTT).  Reference analog: the
    RecurrentGradOp created by recurrent_op.cc's GradOpDescMaker; here
    the backward-through-time is jax.vjp over the scan (see
    _static_rnn_grad_impl)."""
    from paddle_tpu.backward import (_create_grad_var, _grad_name,
                                     _needs_grad)
    from paddle_tpu.core.program import OpDesc
    from paddle_tpu import unique_name

    inputs = {
        "StepInputs": list(op.inputs.get("StepInputs", [])),
        "InitMemories": list(op.inputs.get("InitMemories", [])),
        "OuterReads": list(op.inputs.get("OuterReads", [])),
    }
    inputs.update(grad_out_slots)  # StepOutputs@GRAD / FinalMemories@GRAD
    outputs = {}
    for slot in ("StepInputs", "InitMemories", "OuterReads"):
        names = op.inputs.get(slot, [])
        if not names:
            continue
        gnames = []
        any_needed = False
        for n in names:
            if _needs_grad(block, n, no_grad_set):
                any_needed = True
            g = (_grad_name(n) if n not in grad_map
                 else _grad_name(n, "@" + unique_name.generate("p")))
            gnames.append(g)
        if not any_needed:
            continue
        for n, g in zip(names, gnames):
            _create_grad_var(block, n, g)
            if _needs_grad(block, n, no_grad_set):
                grad_map.setdefault(n, []).append(g)
        outputs[slot + "@GRAD"] = gnames
    if not outputs:
        return []
    return [OpDesc("static_rnn_grad", inputs, outputs, dict(op.attrs))]


register_op("static_rnn",
            inputs=("StepInputs", "InitMemories", "OuterReads"),
            outputs=("StepOutputs", "FinalMemories"),
            attrs={"sub_block": REQUIRED, "seq_len": REQUIRED,
                   "step_input_names": [], "memory_pre_names": [],
                   "memory_update_names": [], "step_output_names": [],
                   "outer_read_names": []},
            duplicable=("StepInputs", "InitMemories", "OuterReads",
                        "StepOutputs", "FinalMemories"),
            optional=("StepInputs", "InitMemories", "OuterReads",
                      "StepOutputs", "FinalMemories"),
            grad_maker=_static_rnn_grad_maker,
            # host_only=False so append_backward reaches the grad_maker;
            # execution is still owned by the special handler / compiler
            # lowering (layers always append with infer_shape=False).
            differentiable=True, host_only=False)(_structural)

register_op("static_rnn_grad",
            inputs=("StepInputs", "InitMemories", "OuterReads",
                    "StepOutputs@GRAD", "FinalMemories@GRAD"),
            outputs=("StepInputs@GRAD", "InitMemories@GRAD",
                     "OuterReads@GRAD"),
            attrs={"sub_block": REQUIRED, "seq_len": REQUIRED,
                   "step_input_names": [], "memory_pre_names": [],
                   "memory_update_names": [], "step_output_names": [],
                   "outer_read_names": []},
            duplicable=("StepInputs", "InitMemories", "OuterReads",
                        "StepOutputs@GRAD", "FinalMemories@GRAD",
                        "StepInputs@GRAD", "InitMemories@GRAD",
                        "OuterReads@GRAD"),
            optional=("StepInputs", "InitMemories", "OuterReads",
                      "StepOutputs@GRAD", "FinalMemories@GRAD",
                      "StepInputs@GRAD", "InitMemories@GRAD",
                      "OuterReads@GRAD"),
            differentiable=False, host_only=True)(_structural)

register_op("write_to_array", inputs=("X", "I"), outputs=("Out",),
            differentiable=False, host_only=True)(_structural)

register_op("read_from_array", inputs=("X", "I"), outputs=("Out",),
            differentiable=False, host_only=True)(_structural)


@register_special_op("cond")
def cond_op(op, block, scope, ctx):
    """Functional two-branch cond (reference analog: the
    conditional_block pair built by layers.cond in later fluid;
    compiled mode lowers to lax.cond in compiler.py)."""
    pred = bool(np.asarray(
        scope.find_var(op.inputs["Cond"][0]).get()).reshape(-1)[0])
    which = "true" if pred else "false"
    ctx.run_block(op.attrs[f"{which}_block"].idx, scope)
    src_names = op.attrs[f"{which}_out_names"]
    for out_name, src in zip(op.outputs.get("Out", []), src_names):
        scope.var(out_name).set(scope.find_var(src).get())


def _static_rnn_pure(program, attrs, xs, init, reads):
    """(xs, init, reads) -> (ys, final) as a pure lax.scan — the single
    implementation behind the interpreter handler, the compiled lowering,
    and BPTT (jax.vjp over this function)."""
    from jax import lax

    from paddle_tpu.core.compiler import _run_block_symbolic

    def body(carry, x):
        benv = dict(zip(attrs["outer_read_names"], reads))
        benv.update(zip(attrs["memory_pre_names"], carry))
        benv.update(zip(attrs["step_input_names"], x))
        _run_block_symbolic(program, attrs["sub_block"].idx, benv)
        return ([benv[n] for n in attrs["memory_update_names"]],
                [benv[n] for n in attrs["step_output_names"]])

    final, ys = lax.scan(body, init, xs,
                         length=attrs["seq_len"] if not xs else None)
    return ys, final


def _scope_vals(scope, names):
    return [scope.find_var(n).get() for n in names]


@register_special_op("static_rnn")
def static_rnn_op(op, block, scope, ctx):
    """StaticRNN forward (reference: recurrent_op.cc per-step scopes —
    here one lax.scan, eager in interpreter mode)."""
    ys, final = _static_rnn_pure(
        ctx.program, op.attrs,
        _scope_vals(scope, op.inputs.get("StepInputs", [])),
        _scope_vals(scope, op.inputs.get("InitMemories", [])),
        _scope_vals(scope, op.inputs.get("OuterReads", [])))
    for name, v in zip(op.outputs.get("StepOutputs", []), ys):
        scope.var(name).set(v)
    for name, v in zip(op.outputs.get("FinalMemories", []), final):
        scope.var(name).set(v)


def _static_rnn_grad_impl(program, attrs, xs, init, reads, g_ys, g_final):
    import jax
    import jax.numpy as jnp

    (ys, final), vjp = jax.vjp(
        lambda a, b, c: _static_rnn_pure(program, attrs, a, b, c),
        xs, init, reads)
    cot_ys = [jnp.zeros_like(y) if g is None else g.astype(y.dtype)
              for g, y in zip(g_ys, ys)]
    cot_final = [jnp.zeros_like(c) if g is None else g.astype(c.dtype)
                 for g, c in zip(g_final, final)]
    return vjp((cot_ys, cot_final))


def _static_rnn_grad_apply(program, op, getv, setv):
    """Shared static_rnn_grad driver for both executors; getv/setv
    read/write values by name (scope in interpreter, env in trace)."""
    attrs = op.attrs
    g_ys_names = op.inputs.get("StepOutputs@GRAD", [])
    g_fin_names = op.inputs.get("FinalMemories@GRAD", [])
    g_ys = ([getv(n) for n in g_ys_names] if g_ys_names
            else [None] * len(attrs["step_output_names"]))
    g_final = ([getv(n) for n in g_fin_names] if g_fin_names
               else [None] * len(attrs["memory_pre_names"]))
    gxs, ginit, greads = _static_rnn_grad_impl(
        program, attrs,
        [getv(n) for n in op.inputs.get("StepInputs", [])],
        [getv(n) for n in op.inputs.get("InitMemories", [])],
        [getv(n) for n in op.inputs.get("OuterReads", [])],
        g_ys, g_final)
    for slot, vals in (("StepInputs@GRAD", gxs),
                       ("InitMemories@GRAD", ginit),
                       ("OuterReads@GRAD", greads)):
        for name, v in zip(op.outputs.get(slot, []), vals):
            setv(name, v)


@register_special_op("static_rnn_grad")
def static_rnn_grad_op(op, block, scope, ctx):
    _static_rnn_grad_apply(
        ctx.program, op,
        lambda n: scope.find_var(n).get(),
        lambda n, v: scope.var(n).set(v))


@register_op("gather_tree", inputs=("Ids", "Parents"), outputs=("Out",),
             differentiable=False)
def gather_tree(ins, attrs):
    """Beam-search finalization: walk parent pointers backwards to emit
    full sequences (reference: beam_search_decode_op.cc walks the
    LoD-linked per-step arrays; here it is a jittable reverse scan over
    dense [T, B, K] tensors — TPU-friendly, no host loop)."""
    from jax import lax

    ids, parents = ins["Ids"], ins["Parents"]
    k = ids.shape[2]
    init = jnp.broadcast_to(jnp.arange(k, dtype=parents.dtype),
                            ids.shape[1:])

    def body(parent, xs):
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, parent, axis=-1)
        return jnp.take_along_axis(step_parents, parent, axis=-1), out

    _, outs = lax.scan(body, init, (ids, parents), reverse=True)
    return {"Out": outs}


# ---------------------------------------------------------------------------
# py_func escape hatch
# ---------------------------------------------------------------------------

_PY_FUNC_REGISTRY: list = []


def register_py_func(fn) -> int:
    """Returns the id used by the py_func op's func_id attr (reference
    py_func_op.cc keeps a python-callable registry the same way)."""
    _PY_FUNC_REGISTRY.append(fn)
    return len(_PY_FUNC_REGISTRY) - 1


def _py_func_grad_maker(op, grad_out_slots, block, grad_map,
                        no_grad_set=frozenset()):
    """When a backward_func was registered, emit a py_func grad op
    running backward_func(*fwd_inputs, *out_grads) -> input grads
    (reference py_func_op.cc grad maker)."""
    if op.attrs.get("backward_func_id", -1) < 0:
        return []
    from paddle_tpu.backward import (_create_grad_var, _grad_name,
                                     _needs_grad)
    from paddle_tpu.core.program import OpDesc
    from paddle_tpu import unique_name

    fwd_in = list(op.inputs.get("X", []))
    g_outs = grad_out_slots.get("Out@GRAD", [])
    gnames = []
    any_needed = False
    for n in fwd_in:
        if _needs_grad(block, n, no_grad_set):
            any_needed = True
        g = (_grad_name(n) if n not in grad_map
             else _grad_name(n, "@" + unique_name.generate("p")))
        gnames.append(g)
    if not any_needed or not g_outs:
        return []
    for n, g in zip(fwd_in, gnames):
        _create_grad_var(block, n, g)
        if _needs_grad(block, n, no_grad_set):
            grad_map.setdefault(n, []).append(g)
    return [OpDesc("py_func", {"X": fwd_in + g_outs},
                   {"Out": gnames},
                   {"func_id": op.attrs["backward_func_id"],
                    "backward_func_id": -1})]


register_op("py_func", inputs=("X",), outputs=("Out",),
            duplicable=("X", "Out"), optional=("X", "Out"),
            attrs={"func_id": REQUIRED, "backward_func_id": -1},
            grad_maker=_py_func_grad_maker,
            differentiable=True, host_only=True)(
    lambda ins, attrs: (_ for _ in ()).throw(
        RuntimeError("py_func runs via the executor (host op)")))


@register_special_op("py_func")
def py_func_op(op, block, scope, ctx):
    """Host-python escape hatch (reference operators/py_func_op.cc):
    runs an arbitrary python callable over numpy inputs.  Host-only by
    nature — the compiled executor refuses it (keep py_func out of the
    jitted path; use it for IO/debug/metrics glue)."""
    fn = _PY_FUNC_REGISTRY[op.attrs["func_id"]]
    ins = [np.asarray(scope.find_var(n).get())
           for n in op.inputs.get("X", [])]
    outs = fn(*ins)
    if outs is None:
        outs = []
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    import jax.numpy as jnp

    for name, val in zip(op.outputs.get("Out", []), outs):
        scope.var(name).set(jnp.asarray(np.asarray(val)))


# ---------------------------------------------------------------------------
# LoD-era dynamic-RNN machinery, re-specified on the padded-batch +
# seq-len representation (SURVEY.md §5 "LoD / long-context": every
# sequence_* capability re-specified on segments).  Reference files:
# lod_rank_table_op.cc, reorder_lod_tensor_by_rank_op.cc,
# shrink_rnn_memory_op.cc, rnn_memory_helper_op.cc,
# split_lod_tensor_op.cc, merge_lod_tensor_op.cc,
# array_to_lod_tensor_op.cc / lod_tensor_to_array_op.cc,
# max_sequence_len_op.cc, lod_array_length_op.cc,
# tensor_array_to_tensor_op.cc.
# ---------------------------------------------------------------------------

@register_op("lod_rank_table", inputs=("X", "SeqLen"), outputs=("Out",),
             optional=("SeqLen",), attrs={"level": 0},
             differentiable=False)
def lod_rank_table(ins, attrs):
    """Rank table: sequence indices sorted by length descending
    (stable).  X [B, T, ...] padded; SeqLen [B] (defaults to full T).
    Out [B, 2]: (original_index, length) rows in rank order."""
    x = ins["X"]
    b = x.shape[0]
    seq = ins.get("SeqLen")
    lens = (seq.reshape(-1).astype(jnp.int64) if seq is not None
            else jnp.full((b,), x.shape[1], jnp.int64))
    # composite key keeps the sort stable for equal lengths (original
    # order preserved, like the reference rank table)
    order = jnp.argsort(-lens * b + jnp.arange(b))
    return {"Out": jnp.stack(
        [order.astype(jnp.int64), lens[order]], axis=1)}


@register_op("reorder_lod_tensor_by_rank",
             inputs=("X", "RankTable"), outputs=("Out",),
             differentiable=False)
def reorder_lod_tensor_by_rank(ins, attrs):
    return {"Out": jnp.take(ins["X"],
                            ins["RankTable"][:, 0].astype(jnp.int32),
                            axis=0)}


@register_op("max_sequence_len", inputs=("RankTable",),
             outputs=("Out",), differentiable=False)
def max_sequence_len(ins, attrs):
    return {"Out": jnp.max(ins["RankTable"][:, 1]).reshape(1)}


@register_op("shrink_rnn_memory", inputs=("X", "RankTable", "I"),
             outputs=("Out",),
             differentiable=False)
def shrink_rnn_memory(ins, attrs):
    """At step I only sequences with length > I are active; the
    reference shrinks the memory to the active prefix (rank-ordered).
    Fixed-shape re-spec: inactive rows are zeroed instead of dropped."""
    x, table = ins["X"], ins["RankTable"]
    i = ins["I"].reshape(()).astype(jnp.int64)
    active = table[:, 1] > i
    return {"Out": jnp.where(
        active.reshape((-1,) + (1,) * (x.ndim - 1)), x, 0.0)}


@register_op("rnn_memory_helper", inputs=("X",), outputs=("Out",),
             attrs={"dtype": "float32"})
def rnn_memory_helper(ins, attrs):
    return {"Out": ins["X"]}


@register_op("split_lod_tensor", inputs=("X", "Mask"),
             outputs=("OutTrue", "OutFalse"),
             attrs={"level": 0}, differentiable=False)
def split_lod_tensor(ins, attrs):
    """Mask-split (reference feeds IfElse).  Fixed-shape re-spec: both
    outputs keep X's shape with non-selected rows zeroed."""
    x = ins["X"]
    m = ins["Mask"].reshape((-1,) + (1,) * (x.ndim - 1)) != 0
    return {"OutTrue": jnp.where(m, x, 0.0),
            "OutFalse": jnp.where(m, 0.0, x)}


@register_op("merge_lod_tensor", inputs=("X", "Mask", "InTrue",
                                         "InFalse"),
             outputs=("Out",), attrs={"level": 0})
def merge_lod_tensor(ins, attrs):
    t, f = ins["InTrue"], ins["InFalse"]
    m = ins["Mask"].reshape((-1,) + (1,) * (t.ndim - 1)) != 0
    return {"Out": jnp.where(m, t, f)}


@register_op("array_to_lod_tensor", inputs=("X",), outputs=("Out",),
             duplicable=("X",), attrs={"axis": 0})
def array_to_lod_tensor(ins, attrs):
    """TensorArray (list of per-step tensors) -> stacked time-major
    tensor (the padded re-spec of the LoD concat)."""
    return {"Out": jnp.stack(ins["X"], axis=int(attrs["axis"]))}


@register_op("lod_tensor_to_array", inputs=("X",), outputs=("Out",),
             duplicable=("Out",), attrs={"axis": 0})
def lod_tensor_to_array(ins, attrs):
    x = ins["X"]
    ax = int(attrs["axis"])
    n = x.shape[ax]
    return {"Out": [jnp.take(x, i, axis=ax) for i in range(n)]}


@register_op("tensor_array_to_tensor", inputs=("X",),
             outputs=("Out", "OutIndex"), duplicable=("X",),
             attrs={"axis": 0, "use_stack": False})
def tensor_array_to_tensor(ins, attrs):
    xs = ins["X"]
    ax = int(attrs["axis"])
    if attrs["use_stack"]:
        out = jnp.stack(xs, axis=ax)
        idx = jnp.ones((len(xs),), jnp.int32)
    else:
        out = jnp.concatenate(xs, axis=ax)
        idx = jnp.asarray([x.shape[ax] for x in xs], jnp.int32)
    return {"Out": out, "OutIndex": idx}


@register_op("lod_array_length", inputs=("X",), outputs=("Out",),
             duplicable=("X",), differentiable=False)
def lod_array_length(ins, attrs):
    return {"Out": jnp.asarray([len(ins["X"])], jnp.int64)}


# program-compat host ops --------------------------------------------------
# (feed/fetch registry entries; their special handlers are defined at
# the top of this module)

@register_op("feed", inputs=("X",), outputs=("Out",),
             optional=("X",),
             attrs={"col": 0}, differentiable=False, host_only=True)
def _feed_structural(ins, attrs):
    return {}


@register_op("fetch", inputs=("X",), outputs=(),
             attrs={"col": 0}, differentiable=False, host_only=True)
def _fetch_structural(ins, attrs):
    return {}


@register_op("get_places", inputs=(), outputs=("Out",),
             attrs={"device_count": 0, "device_type": "AUTO"},
             differentiable=False, host_only=True)
def _get_places_structural(ins, attrs):
    return {}


@register_special_op("get_places")
def get_places_op(op, block, scope, ctx):
    """get_places_op.cc: the device list (as a count vector; Places are
    XLA devices here)."""
    import jax

    n = int(op.attrs["device_count"]) or len(jax.devices())
    scope.var(op.outputs["Out"][0]).set(jnp.arange(n, dtype=jnp.int64))


@register_op("delete_var", inputs=("X",), outputs=(),
             duplicable=("X",), optional=("X",),
             differentiable=False, host_only=True)
def _delete_var_structural(ins, attrs):
    return {}


@register_special_op("delete_var")
def delete_var_op(op, block, scope, ctx):
    """delete_var_op.cc (eager GC): drop scope references; XLA owns
    device memory so this only releases the host handle."""
    for n in op.inputs.get("X", []):
        var = scope.find_var(n)
        if var is not None:
            var.set(None)


# reference alias registrations -------------------------------------------

register_op("conditional_block_infer",
            inputs=("Cond", "X"), outputs=("Out",),
            attrs={"sub_block": REQUIRED, "is_scalar_condition": True},
            duplicable=("X", "Out"), optional=("X", "Out"),
            differentiable=False, host_only=True)(_structural)


@register_special_op("conditional_block_infer")
def conditional_block_infer_op(op, block, scope, ctx):
    """conditional_block_infer_op.cc: the inference-mode alias of
    conditional_block (no grad bookkeeping needed here — grads never
    flow in infer programs)."""
    from paddle_tpu.core.executor import _SPECIAL_OPS

    _SPECIAL_OPS["conditional_block"](op, block, scope, ctx)


register_op("recurrent",
            inputs=("StepInputs", "InitMemories", "OuterReads"),
            outputs=("StepOutputs", "FinalMemories"),
            attrs={"sub_block": REQUIRED, "seq_len": REQUIRED,
                   "step_input_names": [], "memory_pre_names": [],
                   "memory_update_names": [], "step_output_names": [],
                   "outer_read_names": []},
            duplicable=("StepInputs", "InitMemories", "OuterReads",
                        "StepOutputs", "FinalMemories"),
            differentiable=False, host_only=True)(_structural)


@register_special_op("recurrent")
def recurrent_op(op, block, scope, ctx):
    """recurrent_op.cc: the reference's dynamic-RNN-over-sub-block op;
    identical semantics to our static_rnn re-spec (lax.scan lowering in
    the compiled path)."""
    from paddle_tpu.core.executor import _SPECIAL_OPS

    _SPECIAL_OPS["static_rnn"](op, block, scope, ctx)
