"""Control-flow and feed/fetch ops.

Reference parity:
  - feed/fetch: /root/reference/paddle/fluid/operators/controlflow/feed_op.cc,
    fetch_op.cc, framework/feed_fetch_method.cc
  - while: operators/controlflow/while_op.cc (sub-block attr)
  - conditional_block: operators/controlflow/conditional_block_op.cc
  - tensor_array read/write: controlflow/tensor_array_read_write_op.cc
  - print: operators/print_op.cc

In interpreter mode while/cond run the sub-block through the executor with a
child scope (reference semantics).  In compiled mode compiler.py lowers them
to lax.while_loop / lax.cond with the scope-carried vars as loop state —
XLA-friendly control flow with static shapes.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.executor import register_special_op
from paddle_tpu.core.program import BlockRef
from paddle_tpu.core.registry import REQUIRED, register_op


@register_special_op("feed")
def feed_op(op, block, scope, ctx):
    name = op.outputs["Out"][0]
    col = op.attrs.get("col", 0)
    key = op.inputs["X"][0] if op.inputs.get("X") else name
    val = ctx.feed.get(key if key in ctx.feed else name)
    if val is None:
        raise RuntimeError(f"feed variable '{name}' was not provided")
    scope.var(name).set(jnp.asarray(np.asarray(val)))


@register_special_op("fetch")
def fetch_op(op, block, scope, ctx):
    name = op.inputs["X"][0]
    var = scope.find_var(name)
    if var is None:
        raise RuntimeError(f"fetch '{name}': variable not found")
    ctx.fetch_results[name] = var.get()


@register_special_op("print")
def print_op(op, block, scope, ctx):
    name = op.inputs["In"][0]
    var = scope.find_var(name)
    msg = op.attrs.get("message", "")
    print(f"{msg}{name} = {np.asarray(var.get()) if var else None}")
    out_names = op.outputs.get("Out")
    if out_names and var is not None:
        scope.var(out_names[0]).set(var.get())


@register_op("print", inputs=("In",), outputs=("Out",),
             attrs={"message": "", "first_n": -1, "print_phase": "both"},
             host_only=True, differentiable=False)
def _print_compute(ins, attrs):
    return {"Out": ins["In"]}


@register_special_op("while")
def while_op(op, block, scope, ctx):
    """Runs sub-block until Condition is false (reference while_op.cc).
    Carried vars live in the parent scope; the sub-block reads/writes them."""
    sub_idx = op.attrs["sub_block"].idx
    cond_name = op.inputs["Condition"][0]
    max_iters = op.attrs.get("max_iters", 10_000_000)
    it = 0
    while bool(np.asarray(scope.find_var(cond_name).get())):
        child = scope  # reference uses step scopes; flat is fine host-side
        ctx.run_block(sub_idx, child)
        it += 1
        if it >= max_iters:
            raise RuntimeError("while op exceeded max_iters")


@register_special_op("conditional_block")
def conditional_block(op, block, scope, ctx):
    cond_name = op.inputs["Cond"][0]
    if bool(np.asarray(scope.find_var(cond_name).get()).reshape(-1)[0]):
        ctx.run_block(op.attrs["sub_block"].idx, scope)


@register_special_op("write_to_array")
def write_to_array(op, block, scope, ctx):
    arr_name = op.outputs["Out"][0]
    x = scope.find_var(op.inputs["X"][0]).get()
    i = int(np.asarray(scope.find_var(op.inputs["I"][0]).get()))
    var = scope.var(arr_name)
    arr = var.get() or []
    while len(arr) <= i:
        arr.append(None)
    arr[i] = x
    var.set(arr)


@register_special_op("read_from_array")
def read_from_array(op, block, scope, ctx):
    arr = scope.find_var(op.inputs["X"][0]).get()
    i = int(np.asarray(scope.find_var(op.inputs["I"][0]).get()))
    scope.var(op.outputs["Out"][0]).set(arr[i])


@register_op("array_length", inputs=("X",), outputs=("Out",),
             differentiable=False, host_only=True)
def array_length(ins, attrs):
    return {"Out": jnp.asarray(len(ins["X"]), jnp.int64)}


# ---------------------------------------------------------------------------
# structural op registrations
# ---------------------------------------------------------------------------
# The special handlers above (and compiler.py's lowerings) own execution;
# these registry entries exist so Block.append_op can validate attrs and so
# program serialization round-trips.  Reference analog: while/conditional
# ops are real registered operators (operators/controlflow/while_op.cc:58
# REGISTER_OPERATOR) whose Run drives the executor on a sub-block.

def _structural(ins, attrs):  # pragma: no cover
    raise RuntimeError("structural op must run via executor/compiler")


register_op("while", inputs=("Condition", "X"), outputs=("Out",),
            attrs={"sub_block": REQUIRED, "max_iters": 10_000_000,
                   "is_test": False},
            duplicable=("X", "Out"), optional=("X", "Out"),
            differentiable=False, host_only=True)(_structural)

register_op("conditional_block", inputs=("Cond", "X"), outputs=("Out",),
            attrs={"sub_block": REQUIRED, "is_scalar_condition": True},
            duplicable=("X", "Out"), optional=("X", "Out"),
            differentiable=False, host_only=True)(_structural)

register_op("cond", inputs=("Cond",), outputs=("Out",),
            attrs={"true_block": REQUIRED, "false_block": REQUIRED,
                   "true_out_names": [], "false_out_names": []},
            duplicable=("Out",), optional=("Out",),
            differentiable=False, host_only=True)(_structural)

def _static_rnn_grad_maker(op, grad_out_slots, block, grad_map,
                           no_grad_set=frozenset()):
    """Emit a static_rnn_grad op (BPTT).  Reference analog: the
    RecurrentGradOp created by recurrent_op.cc's GradOpDescMaker; here
    the backward-through-time is jax.vjp over the scan (see
    _static_rnn_grad_impl)."""
    from paddle_tpu.backward import (_create_grad_var, _grad_name,
                                     _needs_grad)
    from paddle_tpu.core.program import OpDesc
    from paddle_tpu import unique_name

    inputs = {
        "StepInputs": list(op.inputs.get("StepInputs", [])),
        "InitMemories": list(op.inputs.get("InitMemories", [])),
        "OuterReads": list(op.inputs.get("OuterReads", [])),
    }
    inputs.update(grad_out_slots)  # StepOutputs@GRAD / FinalMemories@GRAD
    outputs = {}
    for slot in ("StepInputs", "InitMemories", "OuterReads"):
        names = op.inputs.get(slot, [])
        if not names:
            continue
        gnames = []
        any_needed = False
        for n in names:
            if _needs_grad(block, n, no_grad_set):
                any_needed = True
            g = (_grad_name(n) if n not in grad_map
                 else _grad_name(n, "@" + unique_name.generate("p")))
            gnames.append(g)
        if not any_needed:
            continue
        for n, g in zip(names, gnames):
            _create_grad_var(block, n, g)
            if _needs_grad(block, n, no_grad_set):
                grad_map.setdefault(n, []).append(g)
        outputs[slot + "@GRAD"] = gnames
    if not outputs:
        return []
    return [OpDesc("static_rnn_grad", inputs, outputs, dict(op.attrs))]


register_op("static_rnn",
            inputs=("StepInputs", "InitMemories", "OuterReads"),
            outputs=("StepOutputs", "FinalMemories"),
            attrs={"sub_block": REQUIRED, "seq_len": REQUIRED,
                   "step_input_names": [], "memory_pre_names": [],
                   "memory_update_names": [], "step_output_names": [],
                   "outer_read_names": []},
            duplicable=("StepInputs", "InitMemories", "OuterReads",
                        "StepOutputs", "FinalMemories"),
            optional=("StepInputs", "InitMemories", "OuterReads",
                      "StepOutputs", "FinalMemories"),
            grad_maker=_static_rnn_grad_maker,
            # host_only=False so append_backward reaches the grad_maker;
            # execution is still owned by the special handler / compiler
            # lowering (layers always append with infer_shape=False).
            differentiable=True, host_only=False)(_structural)

register_op("static_rnn_grad",
            inputs=("StepInputs", "InitMemories", "OuterReads",
                    "StepOutputs@GRAD", "FinalMemories@GRAD"),
            outputs=("StepInputs@GRAD", "InitMemories@GRAD",
                     "OuterReads@GRAD"),
            attrs={"sub_block": REQUIRED, "seq_len": REQUIRED,
                   "step_input_names": [], "memory_pre_names": [],
                   "memory_update_names": [], "step_output_names": [],
                   "outer_read_names": []},
            duplicable=("StepInputs", "InitMemories", "OuterReads",
                        "StepOutputs@GRAD", "FinalMemories@GRAD",
                        "StepInputs@GRAD", "InitMemories@GRAD",
                        "OuterReads@GRAD"),
            optional=("StepInputs", "InitMemories", "OuterReads",
                      "StepOutputs@GRAD", "FinalMemories@GRAD",
                      "StepInputs@GRAD", "InitMemories@GRAD",
                      "OuterReads@GRAD"),
            differentiable=False, host_only=True)(_structural)

register_op("write_to_array", inputs=("X", "I"), outputs=("Out",),
            differentiable=False, host_only=True)(_structural)

register_op("read_from_array", inputs=("X", "I"), outputs=("Out",),
            differentiable=False, host_only=True)(_structural)


@register_special_op("cond")
def cond_op(op, block, scope, ctx):
    """Functional two-branch cond (reference analog: the
    conditional_block pair built by layers.cond in later fluid;
    compiled mode lowers to lax.cond in compiler.py)."""
    pred = bool(np.asarray(
        scope.find_var(op.inputs["Cond"][0]).get()).reshape(-1)[0])
    which = "true" if pred else "false"
    ctx.run_block(op.attrs[f"{which}_block"].idx, scope)
    src_names = op.attrs[f"{which}_out_names"]
    for out_name, src in zip(op.outputs.get("Out", []), src_names):
        scope.var(out_name).set(scope.find_var(src).get())


def _static_rnn_pure(program, attrs, xs, init, reads):
    """(xs, init, reads) -> (ys, final) as a pure lax.scan — the single
    implementation behind the interpreter handler, the compiled lowering,
    and BPTT (jax.vjp over this function)."""
    from jax import lax

    from paddle_tpu.core.compiler import _run_block_symbolic

    def body(carry, x):
        benv = dict(zip(attrs["outer_read_names"], reads))
        benv.update(zip(attrs["memory_pre_names"], carry))
        benv.update(zip(attrs["step_input_names"], x))
        _run_block_symbolic(program, attrs["sub_block"].idx, benv)
        return ([benv[n] for n in attrs["memory_update_names"]],
                [benv[n] for n in attrs["step_output_names"]])

    final, ys = lax.scan(body, init, xs,
                         length=attrs["seq_len"] if not xs else None)
    return ys, final


def _scope_vals(scope, names):
    return [scope.find_var(n).get() for n in names]


@register_special_op("static_rnn")
def static_rnn_op(op, block, scope, ctx):
    """StaticRNN forward (reference: recurrent_op.cc per-step scopes —
    here one lax.scan, eager in interpreter mode)."""
    ys, final = _static_rnn_pure(
        ctx.program, op.attrs,
        _scope_vals(scope, op.inputs.get("StepInputs", [])),
        _scope_vals(scope, op.inputs.get("InitMemories", [])),
        _scope_vals(scope, op.inputs.get("OuterReads", [])))
    for name, v in zip(op.outputs.get("StepOutputs", []), ys):
        scope.var(name).set(v)
    for name, v in zip(op.outputs.get("FinalMemories", []), final):
        scope.var(name).set(v)


def _static_rnn_grad_impl(program, attrs, xs, init, reads, g_ys, g_final):
    import jax
    import jax.numpy as jnp

    (ys, final), vjp = jax.vjp(
        lambda a, b, c: _static_rnn_pure(program, attrs, a, b, c),
        xs, init, reads)
    cot_ys = [jnp.zeros_like(y) if g is None else g.astype(y.dtype)
              for g, y in zip(g_ys, ys)]
    cot_final = [jnp.zeros_like(c) if g is None else g.astype(c.dtype)
                 for g, c in zip(g_final, final)]
    return vjp((cot_ys, cot_final))


def _static_rnn_grad_apply(program, op, getv, setv):
    """Shared static_rnn_grad driver for both executors; getv/setv
    read/write values by name (scope in interpreter, env in trace)."""
    attrs = op.attrs
    g_ys_names = op.inputs.get("StepOutputs@GRAD", [])
    g_fin_names = op.inputs.get("FinalMemories@GRAD", [])
    g_ys = ([getv(n) for n in g_ys_names] if g_ys_names
            else [None] * len(attrs["step_output_names"]))
    g_final = ([getv(n) for n in g_fin_names] if g_fin_names
               else [None] * len(attrs["memory_pre_names"]))
    gxs, ginit, greads = _static_rnn_grad_impl(
        program, attrs,
        [getv(n) for n in op.inputs.get("StepInputs", [])],
        [getv(n) for n in op.inputs.get("InitMemories", [])],
        [getv(n) for n in op.inputs.get("OuterReads", [])],
        g_ys, g_final)
    for slot, vals in (("StepInputs@GRAD", gxs),
                       ("InitMemories@GRAD", ginit),
                       ("OuterReads@GRAD", greads)):
        for name, v in zip(op.outputs.get(slot, []), vals):
            setv(name, v)


@register_special_op("static_rnn_grad")
def static_rnn_grad_op(op, block, scope, ctx):
    _static_rnn_grad_apply(
        ctx.program, op,
        lambda n: scope.find_var(n).get(),
        lambda n, v: scope.var(n).set(v))


@register_op("gather_tree", inputs=("Ids", "Parents"), outputs=("Out",),
             differentiable=False)
def gather_tree(ins, attrs):
    """Beam-search finalization: walk parent pointers backwards to emit
    full sequences (reference: beam_search_decode_op.cc walks the
    LoD-linked per-step arrays; here it is a jittable reverse scan over
    dense [T, B, K] tensors — TPU-friendly, no host loop)."""
    from jax import lax

    ids, parents = ins["Ids"], ins["Parents"]
    k = ids.shape[2]
    init = jnp.broadcast_to(jnp.arange(k, dtype=parents.dtype),
                            ids.shape[1:])

    def body(parent, xs):
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, parent, axis=-1)
        return jnp.take_along_axis(step_parents, parent, axis=-1), out

    _, outs = lax.scan(body, init, (ids, parents), reverse=True)
    return {"Out": outs}


# ---------------------------------------------------------------------------
# py_func escape hatch
# ---------------------------------------------------------------------------

_PY_FUNC_REGISTRY: list = []


def register_py_func(fn) -> int:
    """Returns the id used by the py_func op's func_id attr (reference
    py_func_op.cc keeps a python-callable registry the same way)."""
    _PY_FUNC_REGISTRY.append(fn)
    return len(_PY_FUNC_REGISTRY) - 1


def _py_func_grad_maker(op, grad_out_slots, block, grad_map,
                        no_grad_set=frozenset()):
    """When a backward_func was registered, emit a py_func grad op
    running backward_func(*fwd_inputs, *out_grads) -> input grads
    (reference py_func_op.cc grad maker)."""
    if op.attrs.get("backward_func_id", -1) < 0:
        return []
    from paddle_tpu.backward import (_create_grad_var, _grad_name,
                                     _needs_grad)
    from paddle_tpu.core.program import OpDesc
    from paddle_tpu import unique_name

    fwd_in = list(op.inputs.get("X", []))
    g_outs = grad_out_slots.get("Out@GRAD", [])
    gnames = []
    any_needed = False
    for n in fwd_in:
        if _needs_grad(block, n, no_grad_set):
            any_needed = True
        g = (_grad_name(n) if n not in grad_map
             else _grad_name(n, "@" + unique_name.generate("p")))
        gnames.append(g)
    if not any_needed or not g_outs:
        return []
    for n, g in zip(fwd_in, gnames):
        _create_grad_var(block, n, g)
        if _needs_grad(block, n, no_grad_set):
            grad_map.setdefault(n, []).append(g)
    return [OpDesc("py_func", {"X": fwd_in + g_outs},
                   {"Out": gnames},
                   {"func_id": op.attrs["backward_func_id"],
                    "backward_func_id": -1})]


register_op("py_func", inputs=("X",), outputs=("Out",),
            duplicable=("X", "Out"), optional=("X", "Out"),
            attrs={"func_id": REQUIRED, "backward_func_id": -1},
            grad_maker=_py_func_grad_maker,
            differentiable=True, host_only=True)(
    lambda ins, attrs: (_ for _ in ()).throw(
        RuntimeError("py_func runs via the executor (host op)")))


@register_special_op("py_func")
def py_func_op(op, block, scope, ctx):
    """Host-python escape hatch (reference operators/py_func_op.cc):
    runs an arbitrary python callable over numpy inputs.  Host-only by
    nature — the compiled executor refuses it (keep py_func out of the
    jitted path; use it for IO/debug/metrics glue)."""
    fn = _PY_FUNC_REGISTRY[op.attrs["func_id"]]
    ins = [np.asarray(scope.find_var(n).get())
           for n in op.inputs.get("X", [])]
    outs = fn(*ins)
    if outs is None:
        outs = []
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    import jax.numpy as jnp

    for name, val in zip(op.outputs.get("Out", []), outs):
        scope.var(name).set(jnp.asarray(np.asarray(val)))
