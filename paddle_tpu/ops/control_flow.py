"""Control-flow and feed/fetch ops.

Reference parity:
  - feed/fetch: /root/reference/paddle/fluid/operators/controlflow/feed_op.cc,
    fetch_op.cc, framework/feed_fetch_method.cc
  - while: operators/controlflow/while_op.cc (sub-block attr)
  - conditional_block: operators/controlflow/conditional_block_op.cc
  - tensor_array read/write: controlflow/tensor_array_read_write_op.cc
  - print: operators/print_op.cc

In interpreter mode while/cond run the sub-block through the executor with a
child scope (reference semantics).  In compiled mode compiler.py lowers them
to lax.while_loop / lax.cond with the scope-carried vars as loop state —
XLA-friendly control flow with static shapes.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.executor import register_special_op
from paddle_tpu.core.program import BlockRef
from paddle_tpu.core.registry import REQUIRED, register_op


@register_special_op("feed")
def feed_op(op, block, scope, ctx):
    name = op.outputs["Out"][0]
    col = op.attrs.get("col", 0)
    key = op.inputs["X"][0] if op.inputs.get("X") else name
    val = ctx.feed.get(key if key in ctx.feed else name)
    if val is None:
        raise RuntimeError(f"feed variable '{name}' was not provided")
    scope.var(name).set(jnp.asarray(np.asarray(val)))


@register_special_op("fetch")
def fetch_op(op, block, scope, ctx):
    name = op.inputs["X"][0]
    var = scope.find_var(name)
    if var is None:
        raise RuntimeError(f"fetch '{name}': variable not found")
    ctx.fetch_results[name] = var.get()


@register_special_op("print")
def print_op(op, block, scope, ctx):
    name = op.inputs["In"][0]
    var = scope.find_var(name)
    msg = op.attrs.get("message", "")
    print(f"{msg}{name} = {np.asarray(var.get()) if var else None}")
    out_names = op.outputs.get("Out")
    if out_names and var is not None:
        scope.var(out_names[0]).set(var.get())


@register_op("print", inputs=("In",), outputs=("Out",),
             attrs={"message": "", "first_n": -1, "print_phase": "both"},
             host_only=True, differentiable=False)
def _print_compute(ins, attrs):
    return {"Out": ins["In"]}


@register_special_op("while")
def while_op(op, block, scope, ctx):
    """Runs sub-block until Condition is false (reference while_op.cc).
    Carried vars live in the parent scope; the sub-block reads/writes them."""
    sub_idx = op.attrs["sub_block"].idx
    cond_name = op.inputs["Condition"][0]
    max_iters = op.attrs.get("max_iters", 10_000_000)
    it = 0
    while bool(np.asarray(scope.find_var(cond_name).get())):
        child = scope  # reference uses step scopes; flat is fine host-side
        ctx.run_block(sub_idx, child)
        it += 1
        if it >= max_iters:
            raise RuntimeError("while op exceeded max_iters")


@register_special_op("conditional_block")
def conditional_block(op, block, scope, ctx):
    cond_name = op.inputs["Cond"][0]
    if bool(np.asarray(scope.find_var(cond_name).get()).reshape(-1)[0]):
        ctx.run_block(op.attrs["sub_block"].idx, scope)


@register_special_op("write_to_array")
def write_to_array(op, block, scope, ctx):
    arr_name = op.outputs["Out"][0]
    x = scope.find_var(op.inputs["X"][0]).get()
    i = int(np.asarray(scope.find_var(op.inputs["I"][0]).get()))
    var = scope.var(arr_name)
    arr = var.get() or []
    while len(arr) <= i:
        arr.append(None)
    arr[i] = x
    var.set(arr)


@register_special_op("read_from_array")
def read_from_array(op, block, scope, ctx):
    arr = scope.find_var(op.inputs["X"][0]).get()
    i = int(np.asarray(scope.find_var(op.inputs["I"][0]).get()))
    scope.var(op.outputs["Out"][0]).set(arr[i])


@register_op("array_length", inputs=("X",), outputs=("Out",),
             differentiable=False, host_only=True)
def array_length(ins, attrs):
    return {"Out": jnp.asarray(len(ins["X"]), jnp.int64)}
