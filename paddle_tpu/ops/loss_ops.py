"""Structured / sampled loss ops: linear-chain CRF, Viterbi decoding,
NCE, hierarchical sigmoid, sampled logits.

Reference parity:
  - linear_chain_crf / crf_decoding:
    /root/reference/paddle/fluid/operators/linear_chain_crf_op.cc,
    crf_decoding_op.cc (Transition layout: row0=start, row1=end,
    rows2..=pairwise weights; output is the per-sequence NEGATIVE
    log-likelihood used as a cost)
  - nce: operators/nce_op.cc (shared uniform negative samples,
    logistic NCE objective)
  - hierarchical_sigmoid: operators/hierarchical_sigmoid_op.cc
    (complete-binary-tree default paths)
  - sample_logits: operators/sample_logits_op.cc (sampled softmax)

TPU re-specification (SURVEY.md §7 hard part (a)): the reference's LoD
sequence inputs become padded [B, T, ...] + Length [B]; CRF
forward/Viterbi are lax.scan programs (static shapes, differentiable by
jax.vjp), and negative sampling is jit-deterministic via the SeedOffset
counter pattern shared with dropout.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import REQUIRED, register_op
from paddle_tpu.ops.rng import fold_seed_offset

_NEG_INF = -1e30


def _crf_unpack(transition):
    start = transition[0]          # [D]
    end = transition[1]            # [D]
    trans = transition[2:]         # [D, D]
    return start, end, trans


def _seq_mask(b, t, length):
    if length is None:
        return jnp.ones((b, t), jnp.float32)
    return (jnp.arange(t)[None, :] <
            length.reshape(-1)[:, None]).astype(jnp.float32)


@register_op("linear_chain_crf",
             inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("LogLikelihood",), optional=("Length",))
def linear_chain_crf(ins, attrs):
    """Cost[b] = logZ(x_b) - score(x_b, y_b)  (negative log-likelihood)."""
    em = ins["Emission"].astype(jnp.float32)       # [B, T, D]
    label = ins["Label"].reshape(em.shape[0], em.shape[1])  # [B, T]
    start, end, trans = _crf_unpack(ins["Transition"].astype(jnp.float32))
    b, t, d = em.shape
    length = ins.get("Length")
    mask = _seq_mask(b, t, length)                 # [B, T]
    lengths = mask.sum(axis=1).astype(jnp.int32)   # [B]

    # ---- gold score -------------------------------------------------------
    lab_e = jnp.take_along_axis(em, label[:, :, None], axis=2)[..., 0]
    gold = (lab_e * mask).sum(axis=1)
    gold = gold + start[label[:, 0]]
    pair = trans[label[:, :-1], label[:, 1:]]      # [B, T-1]
    gold = gold + (pair * mask[:, 1:]).sum(axis=1)
    last_idx = jnp.maximum(lengths - 1, 0)
    last_lab = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = gold + end[last_lab]

    # ---- partition function (forward algorithm as a scan) -----------------
    def step(alpha, xs):
        e_t, m_t = xs                              # [B, D], [B]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + e_t
        # masked steps carry alpha through unchanged
        return jnp.where(m_t[:, None] > 0, nxt, alpha), None

    alpha0 = start[None, :] + em[:, 0, :]
    xs = (jnp.moveaxis(em[:, 1:, :], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0))
    alpha, _ = lax.scan(step, alpha0, xs)
    logz = jax.nn.logsumexp(alpha + end[None, :], axis=1)
    return {"LogLikelihood": (logz - gold)[:, None]}


@register_op("crf_decoding",
             inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("ViterbiPath",), optional=("Label", "Length"),
             differentiable=False)
def crf_decoding(ins, attrs):
    """Viterbi decode; with Label given, outputs per-position correctness
    (reference semantics for evaluation)."""
    em = ins["Emission"].astype(jnp.float32)
    start, end, trans = _crf_unpack(ins["Transition"].astype(jnp.float32))
    b, t, d = em.shape
    length = ins.get("Length")
    mask = _seq_mask(b, t, length)
    lengths = mask.sum(axis=1).astype(jnp.int32)

    def fwd(carry, xs):
        alpha = carry
        e_t, m_t = xs
        scores = alpha[:, :, None] + trans[None, :, :]     # [B, D, D]
        best = jnp.max(scores, axis=1) + e_t
        ptr = jnp.argmax(scores, axis=1)                   # [B, D]
        nxt = jnp.where(m_t[:, None] > 0, best, alpha)
        ptr = jnp.where(
            m_t[:, None] > 0, ptr,
            jnp.broadcast_to(jnp.arange(d)[None, :], (b, d)))
        return nxt, ptr

    alpha0 = start[None, :] + em[:, 0, :]
    xs = (jnp.moveaxis(em[:, 1:, :], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0))
    alpha, ptrs = lax.scan(fwd, alpha0, xs)                # ptrs [T-1,B,D]
    last = jnp.argmax(alpha + end[None, :], axis=1)        # [B]

    def back(carry, ptr_t):
        cur = carry
        prev = jnp.take_along_axis(ptr_t, cur[:, None], axis=1)[:, 0]
        return prev, cur

    # ys[i] is the label at step i+1; the final carry is the label at
    # step 0 (backtrace runs T-1 .. 1)
    first, path_rev = lax.scan(back, last, ptrs, reverse=True)
    path = jnp.concatenate([first[None, :], path_rev], axis=0)  # [T, B]
    path = jnp.moveaxis(path, 0, 1) * mask.astype(jnp.int32)   # [B, T]
    if "Label" in ins:
        label = ins["Label"].reshape(b, t)
        return {"ViterbiPath": (path == label).astype(jnp.int64) *
                mask.astype(jnp.int64)}
    return {"ViterbiPath": path.astype(jnp.int64)}


def _sample_ids(seed, offset, k, num_classes):
    key = fold_seed_offset(jax.random.PRNGKey(seed), offset)
    return jax.random.randint(key, (k,), 0, num_classes)


@register_op("nce",
             inputs=("Input", "Label", "Weight", "Bias", "SampleWeight",
                     "SeedOffset"),
             outputs=("Cost",),
             optional=("Bias", "SampleWeight", "SeedOffset"),
             attrs={"num_total_classes": REQUIRED, "num_neg_samples": 10,
                    "seed": 0})
def nce(ins, attrs):
    """Noise-contrastive estimation with shared uniform negatives
    (reference nce_op.cc uniform sampler path)."""
    x = ins["Input"].astype(jnp.float32)           # [B, D]
    label = ins["Label"].reshape(x.shape[0], -1)   # [B, num_true]
    w = ins["Weight"].astype(jnp.float32)          # [C, D]
    bias = ins.get("Bias")
    c = attrs["num_total_classes"]
    k = attrs["num_neg_samples"]
    offset = ins.get("SeedOffset", 0)
    negs = _sample_ids(attrs["seed"], offset, k, c)        # [k]
    q = 1.0 / c                                             # uniform q

    def logits_for(ids2d):
        """ids2d: [B, M] -> per-example logits [B, M]."""
        s = jnp.einsum("bd,bmd->bm", x, w[ids2d])
        if bias is not None:
            s = s + bias[ids2d]
        return s

    s_true = logits_for(label)                              # [B, NT]
    s_neg = logits_for(jnp.broadcast_to(negs[None, :],
                                        (x.shape[0], k)))   # [B, k]
    # logistic NCE: sigmoid(s - log(k*q))
    corr = math.log(k * q)
    pos = jax.nn.softplus(-(s_true - corr)).sum(axis=1)
    neg = jax.nn.softplus(s_neg - corr).sum(axis=1)
    cost = pos + neg
    sw = ins.get("SampleWeight")
    if sw is not None:
        # reference nce_op.h: per-example weight scales its loss
        cost = cost * sw.reshape(-1).astype(cost.dtype)
    return {"Cost": cost[:, None]}


@register_op("hierarchical_sigmoid",
             inputs=("X", "Label", "W", "Bias"),
             outputs=("Out",), optional=("Bias",),
             attrs={"num_classes": REQUIRED})
def hierarchical_sigmoid(ins, attrs):
    """Complete-binary-tree hsigmoid (reference
    hierarchical_sigmoid_op.cc default tree): internal nodes are heap
    indices 0..C-2, leaf for class c is heap index c + C - 1."""
    x = ins["X"].astype(jnp.float32)               # [B, D]
    label = ins["Label"].reshape(-1)               # [B]
    w = ins["W"].astype(jnp.float32)               # [C-1, D]
    bias = ins.get("Bias")
    c = attrs["num_classes"]
    depth = max(1, math.ceil(math.log2(c)) + 1)  # leaf indices reach 2C-2
    node = label + (c - 1)                         # leaf heap index
    loss = jnp.zeros(x.shape[0], jnp.float32)
    for _ in range(depth):
        is_right = (node % 2 == 0) & (node > 0)    # right child is even
        parent = jnp.maximum((node - 1) // 2, 0)
        valid = node > 0
        s = jnp.einsum("bd,bd->b", x, w[parent])
        if bias is not None:
            s = s + bias[parent]
        # code +1 for left, -1 for right (sigmoid target)
        sign = jnp.where(is_right, -1.0, 1.0)
        step_loss = jax.nn.softplus(-sign * s)
        loss = loss + jnp.where(valid, step_loss, 0.0)
        node = jnp.where(valid, parent, node)
    return {"Out": loss[:, None]}


@register_op("sample_logits",
             inputs=("Logits", "Labels", "SeedOffset"),
             outputs=("SampledLogits", "Samples"),
             optional=("SeedOffset",),
             attrs={"num_samples": REQUIRED, "seed": 0,
                    "remove_accidental_hits": True,
                    "use_customized_samples": False})
def sample_logits(ins, attrs):
    """Sampled-softmax helper (reference sample_logits_op.cc): gather
    [true_logits, sampled_logits] with log-q correction; downstream
    softmax_with_cross_entropy over column 0 as the label."""
    logits = ins["Logits"].astype(jnp.float32)     # [B, C]
    labels = ins["Labels"].reshape(logits.shape[0], -1)  # [B, NT]
    b, c = logits.shape
    k = attrs["num_samples"]
    offset = ins.get("SeedOffset", 0)
    negs = _sample_ids(attrs["seed"], offset, k, c)        # [k]
    samples = jnp.concatenate(
        [labels, jnp.broadcast_to(negs[None, :], (b, k))], axis=1)
    picked = jnp.take_along_axis(logits, samples, axis=1)
    logq = math.log(1.0 / c)
    picked = picked - logq
    if attrs["remove_accidental_hits"]:
        nt = labels.shape[1]
        hit = (samples[:, nt:, None] == labels[:, None, :]).any(axis=-1)
        picked = picked.at[:, nt:].add(jnp.where(hit, _NEG_INF, 0.0))
    return {"SampledLogits": picked, "Samples": samples}


@register_op("sampled_uniform", inputs=("SeedOffset",),
             outputs=("Out",), optional=("SeedOffset",),
             attrs={"shape": REQUIRED, "min": 0.0, "max": 1.0, "seed": 0},
             differentiable=False)
def sampled_uniform(ins, attrs):
    """Jit-deterministic uniform sampling: unlike uniform_random (host
    numpy, startup-program initializer), this re-randomizes every step
    under jit via the SeedOffset counter (the dropout pattern)."""
    key = fold_seed_offset(jax.random.PRNGKey(attrs["seed"]),
                           ins.get("SeedOffset", 0))
    return {"Out": jax.random.uniform(
        key, tuple(attrs["shape"]), jnp.float32,
        attrs["min"], attrs["max"])}


@register_op("sampled_gaussian", inputs=("SeedOffset",),
             outputs=("Out",), optional=("SeedOffset",),
             attrs={"shape": REQUIRED, "mean": 0.0, "std": 1.0, "seed": 0},
             differentiable=False)
def sampled_gaussian(ins, attrs):
    key = fold_seed_offset(jax.random.PRNGKey(attrs["seed"]),
                           ins.get("SeedOffset", 0))
    return {"Out": attrs["mean"] + attrs["std"] * jax.random.normal(
        key, tuple(attrs["shape"]), jnp.float32)}


# ---------------------------------------------------------------------------
# loss zoo (reference operators/*_loss_op.cc family)
# ---------------------------------------------------------------------------

@register_op("hinge_loss", inputs=("Logits", "Labels"),
             outputs=("Loss",))
def hinge_loss(ins, attrs):
    """hinge_loss_op.h: loss = max(0, 1 - logits*(2*label-1))."""
    x, y = ins["Logits"], ins["Labels"]
    return {"Loss": jnp.maximum(0.0, 1.0 - x * (2.0 * y - 1.0))}


@register_op("rank_loss", inputs=("Label", "Left", "Right"),
             outputs=("Out",))
def rank_loss(ins, attrs):
    """rank_loss_op.h: out = log(1+exp(l-r)) - label*(l-r) (RankNet)."""
    o = ins["Left"] - ins["Right"]
    return {"Out": jnp.logaddexp(0.0, o) - ins["Label"] * o}


@register_op("margin_rank_loss", inputs=("X1", "X2", "Label"),
             outputs=("Out", "Activated"),
             attrs={"margin": 0.0})
def margin_rank_loss(ins, attrs):
    """margin_rank_loss_op.h: out = relu(-label*(x1-x2) + margin);
    Activated is the >0 mask reused by the backward."""
    d = -ins["Label"] * (ins["X1"] - ins["X2"]) + attrs["margin"]
    out = jnp.maximum(d, 0.0)
    return {"Out": out, "Activated": (d > 0).astype(d.dtype)}


@register_op("kldiv_loss", inputs=("X", "Target"), outputs=("Loss",),
             attrs={"reduction": "mean"})
def kldiv_loss(ins, attrs):
    """kldiv_loss_op.h: elementwise target*(log(target)-x), with
    none/batchmean/mean/sum reductions (x is log-prob input)."""
    x, t = ins["X"], ins["Target"]
    ele = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-38)) - x), 0.0)
    red = attrs["reduction"]
    if red == "none":
        return {"Loss": ele}
    if red == "batchmean":
        return {"Loss": ele.sum() / x.shape[0]}
    if red == "sum":
        return {"Loss": ele.sum()}
    return {"Loss": ele.mean()}


@register_op("smooth_l1_loss",
             inputs=("X", "Y", "InsideWeight", "OutsideWeight"),
             outputs=("Out", "Diff"),
             optional=("InsideWeight", "OutsideWeight"),
             attrs={"sigma": 1.0})
def smooth_l1_loss(ins, attrs):
    """smooth_l1_loss_op.h: Huber with transition at 1/sigma^2;
    Diff caches iw*(x-y) for the backward; Out is the row-summed
    weighted loss [N, 1]."""
    x, y = ins["X"], ins["Y"]
    s2 = attrs["sigma"] ** 2
    diff = x - y
    iw, ow = ins.get("InsideWeight"), ins.get("OutsideWeight")
    if iw is not None:
        diff = diff * iw
    a = jnp.abs(diff)
    ele = jnp.where(a < 1.0 / s2, 0.5 * diff * diff * s2, a - 0.5 / s2)
    if ow is not None:
        ele = ele * ow
    out = ele.reshape(x.shape[0], -1).sum(axis=1, keepdims=True)
    return {"Out": out, "Diff": diff}


@register_op("bpr_loss", inputs=("X", "Label"), outputs=("Y",))
def bpr_loss(ins, attrs):
    """bpr_loss_op.h (Bayesian Personalized Ranking): per row i with
    positive class y_i: mean_{j!=y} log(1+exp(x_j - x_y))."""
    x, label = ins["X"], ins["Label"]
    n, c = x.shape
    pos = jnp.take_along_axis(
        x, label.reshape(n, 1).astype(jnp.int32), axis=1)
    ele = jnp.logaddexp(0.0, x - pos)
    mask = jnp.arange(c)[None, :] != label.reshape(n, 1)
    out = (ele * mask).sum(axis=1, keepdims=True) / (c - 1)
    return {"Y": out}


@register_op("modified_huber_loss", inputs=("X", "Y"),
             outputs=("Out", "IntermediateVal"))
def modified_huber_loss(ins, attrs):
    """modified_huber_loss_op.h: z = (2y-1)*x; loss = -4z if z<-1,
    (1-z)^2 if z<1, else 0."""
    x, y = ins["X"], ins["Y"]
    z = (2.0 * y - 1.0) * x
    out = jnp.where(z < -1.0, -4.0 * z,
                    jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"Out": out, "IntermediateVal": z}


@register_op("teacher_student_sigmoid_loss", inputs=("X", "Label"),
             outputs=("Y",),
             attrs={"soft_max_up_bound": 15.0,
                    "soft_max_lower_bound": -15.0})
def teacher_student_sigmoid_loss(ins, attrs):
    """teacher_student_sigmoid_loss_op.h: CTR distillation; label
    encodes click z and teacher score z' as {-2, -1, [0,2)}:
      label < -1: bce(x, 0)
      label < 0 : bce(x, 1)
      label < 1 : bce(x, 0) + bce(x, label)
      else      : bce(x, 1) + bce(x, label-1)."""
    x, lbl = ins["X"], ins["Label"]
    bce0 = jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))
    bce1 = bce0 - x

    def soft(t):
        return bce0 - x * t

    out = jnp.where(
        lbl < -1.0, bce0,
        jnp.where(lbl < 0.0, bce1,
                  jnp.where(lbl < 1.0, bce0 + soft(lbl),
                            bce1 + soft(lbl - 1.0))))
    return {"Y": out}


@register_op("squared_l2_distance", inputs=("X", "Y"),
             outputs=("Out", "sub_result"))
def squared_l2_distance(ins, attrs):
    """squared_l2_distance_op.h: row-wise ||x-y||^2 (Y broadcasts over
    the batch when its first dim is 1)."""
    x, y = ins["X"], ins["Y"]
    sub = x - y
    return {"Out": (sub * sub).reshape(x.shape[0], -1).sum(
        axis=1, keepdims=True), "sub_result": sub}


@register_op("squared_l2_norm", inputs=("X",), outputs=("Out",))
def squared_l2_norm(ins, attrs):
    return {"Out": jnp.sum(ins["X"] ** 2).reshape(1)}


@register_op("l1_norm", inputs=("X",), outputs=("Out",))
def l1_norm(ins, attrs):
    return {"Out": jnp.sum(jnp.abs(ins["X"])).reshape(1)}


@register_op("cos_sim", inputs=("X", "Y"),
             outputs=("Out", "XNorm", "YNorm"))
def cos_sim(ins, attrs):
    """cos_sim_op.h: row-wise cosine similarity; Y may be [1, D]
    (broadcast against every row of X)."""
    x, y = ins["X"], ins["Y"]
    xn = jnp.sqrt((x * x).sum(axis=1, keepdims=True))
    yn = jnp.sqrt((y * y).sum(axis=1, keepdims=True))
    dot = (x * y).sum(axis=1, keepdims=True)
    return {"Out": dot / (xn * yn), "XNorm": xn, "YNorm": yn}
