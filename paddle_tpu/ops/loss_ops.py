"""Structured / sampled loss ops: linear-chain CRF, Viterbi decoding,
NCE, hierarchical sigmoid, sampled logits.

Reference parity:
  - linear_chain_crf / crf_decoding:
    /root/reference/paddle/fluid/operators/linear_chain_crf_op.cc,
    crf_decoding_op.cc (Transition layout: row0=start, row1=end,
    rows2..=pairwise weights; output is the per-sequence NEGATIVE
    log-likelihood used as a cost)
  - nce: operators/nce_op.cc (shared uniform negative samples,
    logistic NCE objective)
  - hierarchical_sigmoid: operators/hierarchical_sigmoid_op.cc
    (complete-binary-tree default paths)
  - sample_logits: operators/sample_logits_op.cc (sampled softmax)

TPU re-specification (SURVEY.md §7 hard part (a)): the reference's LoD
sequence inputs become padded [B, T, ...] + Length [B]; CRF
forward/Viterbi are lax.scan programs (static shapes, differentiable by
jax.vjp), and negative sampling is jit-deterministic via the SeedOffset
counter pattern shared with dropout.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import REQUIRED, register_op

_NEG_INF = -1e30


def _crf_unpack(transition):
    start = transition[0]          # [D]
    end = transition[1]            # [D]
    trans = transition[2:]         # [D, D]
    return start, end, trans


def _seq_mask(b, t, length):
    if length is None:
        return jnp.ones((b, t), jnp.float32)
    return (jnp.arange(t)[None, :] <
            length.reshape(-1)[:, None]).astype(jnp.float32)


@register_op("linear_chain_crf",
             inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("LogLikelihood",), optional=("Length",))
def linear_chain_crf(ins, attrs):
    """Cost[b] = logZ(x_b) - score(x_b, y_b)  (negative log-likelihood)."""
    em = ins["Emission"].astype(jnp.float32)       # [B, T, D]
    label = ins["Label"].reshape(em.shape[0], em.shape[1])  # [B, T]
    start, end, trans = _crf_unpack(ins["Transition"].astype(jnp.float32))
    b, t, d = em.shape
    length = ins.get("Length")
    mask = _seq_mask(b, t, length)                 # [B, T]
    lengths = mask.sum(axis=1).astype(jnp.int32)   # [B]

    # ---- gold score -------------------------------------------------------
    lab_e = jnp.take_along_axis(em, label[:, :, None], axis=2)[..., 0]
    gold = (lab_e * mask).sum(axis=1)
    gold = gold + start[label[:, 0]]
    pair = trans[label[:, :-1], label[:, 1:]]      # [B, T-1]
    gold = gold + (pair * mask[:, 1:]).sum(axis=1)
    last_idx = jnp.maximum(lengths - 1, 0)
    last_lab = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = gold + end[last_lab]

    # ---- partition function (forward algorithm as a scan) -----------------
    def step(alpha, xs):
        e_t, m_t = xs                              # [B, D], [B]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + e_t
        # masked steps carry alpha through unchanged
        return jnp.where(m_t[:, None] > 0, nxt, alpha), None

    alpha0 = start[None, :] + em[:, 0, :]
    xs = (jnp.moveaxis(em[:, 1:, :], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0))
    alpha, _ = lax.scan(step, alpha0, xs)
    logz = jax.nn.logsumexp(alpha + end[None, :], axis=1)
    return {"LogLikelihood": (logz - gold)[:, None]}


@register_op("crf_decoding",
             inputs=("Emission", "Transition", "Label", "Length"),
             outputs=("ViterbiPath",), optional=("Label", "Length"),
             differentiable=False)
def crf_decoding(ins, attrs):
    """Viterbi decode; with Label given, outputs per-position correctness
    (reference semantics for evaluation)."""
    em = ins["Emission"].astype(jnp.float32)
    start, end, trans = _crf_unpack(ins["Transition"].astype(jnp.float32))
    b, t, d = em.shape
    length = ins.get("Length")
    mask = _seq_mask(b, t, length)
    lengths = mask.sum(axis=1).astype(jnp.int32)

    def fwd(carry, xs):
        alpha = carry
        e_t, m_t = xs
        scores = alpha[:, :, None] + trans[None, :, :]     # [B, D, D]
        best = jnp.max(scores, axis=1) + e_t
        ptr = jnp.argmax(scores, axis=1)                   # [B, D]
        nxt = jnp.where(m_t[:, None] > 0, best, alpha)
        ptr = jnp.where(
            m_t[:, None] > 0, ptr,
            jnp.broadcast_to(jnp.arange(d)[None, :], (b, d)))
        return nxt, ptr

    alpha0 = start[None, :] + em[:, 0, :]
    xs = (jnp.moveaxis(em[:, 1:, :], 1, 0), jnp.moveaxis(mask[:, 1:], 1, 0))
    alpha, ptrs = lax.scan(fwd, alpha0, xs)                # ptrs [T-1,B,D]
    last = jnp.argmax(alpha + end[None, :], axis=1)        # [B]

    def back(carry, ptr_t):
        cur = carry
        prev = jnp.take_along_axis(ptr_t, cur[:, None], axis=1)[:, 0]
        return prev, cur

    # ys[i] is the label at step i+1; the final carry is the label at
    # step 0 (backtrace runs T-1 .. 1)
    first, path_rev = lax.scan(back, last, ptrs, reverse=True)
    path = jnp.concatenate([first[None, :], path_rev], axis=0)  # [T, B]
    path = jnp.moveaxis(path, 0, 1) * mask.astype(jnp.int32)   # [B, T]
    if "Label" in ins:
        label = ins["Label"].reshape(b, t)
        return {"ViterbiPath": (path == label).astype(jnp.int64) *
                mask.astype(jnp.int64)}
    return {"ViterbiPath": path.astype(jnp.int64)}


def _sample_ids(seed, offset, k, num_classes):
    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                             jnp.asarray(offset, jnp.int32).reshape(()))
    return jax.random.randint(key, (k,), 0, num_classes)


@register_op("nce",
             inputs=("Input", "Label", "Weight", "Bias", "SeedOffset"),
             outputs=("Cost",), optional=("Bias", "SeedOffset"),
             attrs={"num_total_classes": REQUIRED, "num_neg_samples": 10,
                    "seed": 0})
def nce(ins, attrs):
    """Noise-contrastive estimation with shared uniform negatives
    (reference nce_op.cc uniform sampler path)."""
    x = ins["Input"].astype(jnp.float32)           # [B, D]
    label = ins["Label"].reshape(x.shape[0], -1)   # [B, num_true]
    w = ins["Weight"].astype(jnp.float32)          # [C, D]
    bias = ins.get("Bias")
    c = attrs["num_total_classes"]
    k = attrs["num_neg_samples"]
    offset = ins.get("SeedOffset", 0)
    negs = _sample_ids(attrs["seed"], offset, k, c)        # [k]
    q = 1.0 / c                                             # uniform q

    def logits_for(ids2d):
        """ids2d: [B, M] -> per-example logits [B, M]."""
        s = jnp.einsum("bd,bmd->bm", x, w[ids2d])
        if bias is not None:
            s = s + bias[ids2d]
        return s

    s_true = logits_for(label)                              # [B, NT]
    s_neg = logits_for(jnp.broadcast_to(negs[None, :],
                                        (x.shape[0], k)))   # [B, k]
    # logistic NCE: sigmoid(s - log(k*q))
    corr = math.log(k * q)
    pos = jax.nn.softplus(-(s_true - corr)).sum(axis=1)
    neg = jax.nn.softplus(s_neg - corr).sum(axis=1)
    return {"Cost": (pos + neg)[:, None]}


@register_op("hierarchical_sigmoid",
             inputs=("X", "Label", "W", "Bias"),
             outputs=("Out",), optional=("Bias",),
             attrs={"num_classes": REQUIRED})
def hierarchical_sigmoid(ins, attrs):
    """Complete-binary-tree hsigmoid (reference
    hierarchical_sigmoid_op.cc default tree): internal nodes are heap
    indices 0..C-2, leaf for class c is heap index c + C - 1."""
    x = ins["X"].astype(jnp.float32)               # [B, D]
    label = ins["Label"].reshape(-1)               # [B]
    w = ins["W"].astype(jnp.float32)               # [C-1, D]
    bias = ins.get("Bias")
    c = attrs["num_classes"]
    depth = max(1, math.ceil(math.log2(c)) + 1)  # leaf indices reach 2C-2
    node = label + (c - 1)                         # leaf heap index
    loss = jnp.zeros(x.shape[0], jnp.float32)
    for _ in range(depth):
        is_right = (node % 2 == 0) & (node > 0)    # right child is even
        parent = jnp.maximum((node - 1) // 2, 0)
        valid = node > 0
        s = jnp.einsum("bd,bd->b", x, w[parent])
        if bias is not None:
            s = s + bias[parent]
        # code +1 for left, -1 for right (sigmoid target)
        sign = jnp.where(is_right, -1.0, 1.0)
        step_loss = jax.nn.softplus(-sign * s)
        loss = loss + jnp.where(valid, step_loss, 0.0)
        node = jnp.where(valid, parent, node)
    return {"Out": loss[:, None]}


@register_op("sample_logits",
             inputs=("Logits", "Labels", "SeedOffset"),
             outputs=("SampledLogits", "Samples"),
             optional=("SeedOffset",),
             attrs={"num_samples": REQUIRED, "seed": 0,
                    "remove_accidental_hits": True,
                    "use_customized_samples": False})
def sample_logits(ins, attrs):
    """Sampled-softmax helper (reference sample_logits_op.cc): gather
    [true_logits, sampled_logits] with log-q correction; downstream
    softmax_with_cross_entropy over column 0 as the label."""
    logits = ins["Logits"].astype(jnp.float32)     # [B, C]
    labels = ins["Labels"].reshape(logits.shape[0], -1)  # [B, NT]
    b, c = logits.shape
    k = attrs["num_samples"]
    offset = ins.get("SeedOffset", 0)
    negs = _sample_ids(attrs["seed"], offset, k, c)        # [k]
    samples = jnp.concatenate(
        [labels, jnp.broadcast_to(negs[None, :], (b, k))], axis=1)
    picked = jnp.take_along_axis(logits, samples, axis=1)
    logq = math.log(1.0 / c)
    picked = picked - logq
    if attrs["remove_accidental_hits"]:
        nt = labels.shape[1]
        hit = (samples[:, nt:, None] == labels[:, None, :]).any(axis=-1)
        picked = picked.at[:, nt:].add(jnp.where(hit, _NEG_INF, 0.0))
    return {"SampledLogits": picked, "Samples": samples}


@register_op("sampled_uniform", inputs=("SeedOffset",),
             outputs=("Out",), optional=("SeedOffset",),
             attrs={"shape": REQUIRED, "min": 0.0, "max": 1.0, "seed": 0},
             differentiable=False)
def sampled_uniform(ins, attrs):
    """Jit-deterministic uniform sampling: unlike uniform_random (host
    numpy, startup-program initializer), this re-randomizes every step
    under jit via the SeedOffset counter (the dropout pattern)."""
    key = jax.random.fold_in(
        jax.random.PRNGKey(attrs["seed"]),
        jnp.asarray(ins.get("SeedOffset", 0), jnp.int32).reshape(()))
    return {"Out": jax.random.uniform(
        key, tuple(attrs["shape"]), jnp.float32,
        attrs["min"], attrs["max"])}


@register_op("sampled_gaussian", inputs=("SeedOffset",),
             outputs=("Out",), optional=("SeedOffset",),
             attrs={"shape": REQUIRED, "mean": 0.0, "std": 1.0, "seed": 0},
             differentiable=False)
def sampled_gaussian(ins, attrs):
    key = jax.random.fold_in(
        jax.random.PRNGKey(attrs["seed"]),
        jnp.asarray(ins.get("SeedOffset", 0), jnp.int32).reshape(()))
    return {"Out": attrs["mean"] + attrs["std"] * jax.random.normal(
        key, tuple(attrs["shape"]), jnp.float32)}
