"""Sequence ops — the reference's LoD machinery re-specified for TPU.

Reference parity: /root/reference/paddle/fluid/operators/sequence_ops/
(sequence_pool_op.cc, sequence_softmax_op.cc, sequence_expand_op.cc,
sequence_reverse_op.cc, sequence_pad_op.cc ...) and framework/lod_tensor.h.

TPU-first difference (SURVEY.md §7 "hard parts" (a)): XLA needs static
shapes, so variable-length batches are padded [N, T, ...] tensors carried
with an explicit SeqLen [N] int tensor — the bucketed-padding + mask design
— instead of LoD offset vectors over a flattened [sum(T_i), ...] tensor.
Every sequence op here takes (X, SeqLen).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


def _mask(x, seq_len):
    """[N, T] bool validity mask broadcastable over x [N, T, ...]."""
    t = x.shape[1]
    m = jnp.arange(t)[None, :] < seq_len.reshape(-1, 1)
    return m.reshape(m.shape + (1,) * (x.ndim - 2))


@register_op("sequence_mask", inputs=("X",), outputs=("Y",),
             attrs={"maxlen": -1, "out_dtype": "float32"},
             differentiable=False)
def sequence_mask(ins, attrs):
    seq_len = ins["X"].reshape(-1)
    maxlen = attrs["maxlen"]
    if maxlen <= 0:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen attr (>0)"
        )
    m = jnp.arange(maxlen)[None, :] < seq_len[:, None]
    return {"Y": m.astype(attrs["out_dtype"])}


@register_op("sequence_pool", inputs=("X", "SeqLen"), outputs=("Out",),
             optional=("SeqLen",),
             attrs={"pooltype": "AVERAGE", "pad_value": 0.0})
def sequence_pool(ins, attrs):
    """X: [N, T, ...] padded; SeqLen: [N].  reference sequence_pool_op.cc."""
    x = ins["X"]
    if "SeqLen" in ins:
        seq_len = ins["SeqLen"].reshape(-1)
    else:
        seq_len = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    m = _mask(x, seq_len)
    lens = jnp.maximum(seq_len, 1).astype(x.dtype)
    lens = lens.reshape((-1,) + (1,) * (x.ndim - 2))
    pt = attrs["pooltype"].upper()
    if pt == "SUM":
        return {"Out": jnp.sum(jnp.where(m, x, 0), axis=1)}
    if pt == "AVERAGE":
        return {"Out": jnp.sum(jnp.where(m, x, 0), axis=1) / lens}
    if pt == "SQRT":
        return {"Out": jnp.sum(jnp.where(m, x, 0), axis=1)
                / jnp.sqrt(lens)}
    if pt == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        return {"Out": jnp.max(jnp.where(m, x, neg), axis=1)}
    if pt == "LAST":
        idx = jnp.maximum(seq_len - 1, 0)
        return {"Out": jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        )[:, 0]}
    if pt == "FIRST":
        return {"Out": x[:, 0]}
    raise ValueError(f"unknown pooltype {pt}")


@register_op("sequence_softmax", inputs=("X", "SeqLen"), outputs=("Out",),
             optional=("SeqLen",), attrs={})
def sequence_softmax(ins, attrs):
    x = ins["X"]
    if "SeqLen" in ins:
        m = _mask(x, ins["SeqLen"].reshape(-1))
        x = jnp.where(m, x, jnp.asarray(-1e30, x.dtype))
    return {"Out": jax.nn.softmax(x, axis=1)}


@register_op("sequence_reverse", inputs=("X", "SeqLen"), outputs=("Y",),
             optional=("SeqLen",), attrs={})
def sequence_reverse(ins, attrs):
    x = ins["X"]
    if "SeqLen" not in ins:
        return {"Y": jnp.flip(x, axis=1)}
    seq_len = ins["SeqLen"].reshape(-1)
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    rev = seq_len[:, None] - 1 - pos
    idx = jnp.where(pos < seq_len[:, None], rev, pos)
    return {"Y": jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)}


@register_op("sequence_expand", inputs=("X", "Y"), outputs=("Out",),
             attrs={"ref_level": 0})
def sequence_expand(ins, attrs):
    """Broadcast per-sequence rows X [N, ...] over time: Out[n, t] = X[n].
    Padded-form analog of reference sequence_expand_op.cc."""
    x, y = ins["X"], ins["Y"]
    t = y.shape[1]
    return {"Out": jnp.broadcast_to(
        x[:, None], (x.shape[0], t) + x.shape[1:])}


@register_op("sequence_concat", inputs=("X",), outputs=("Out",),
             duplicable=("X",), attrs={})
def sequence_concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=1)}


@register_op("sequence_slice", inputs=("X", "Offset", "Length"),
             outputs=("Out",), attrs={})
def sequence_slice(ins, attrs):
    x, off, length = ins["X"], ins["Offset"], ins["Length"]
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    idx = jnp.minimum(off.reshape(-1, 1) + pos, t - 1)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    valid = pos < length.reshape(-1, 1)
    return {"Out": jnp.where(
        valid.reshape(valid.shape + (1,) * (x.ndim - 2)), out, 0)}


@register_op("sequence_enumerate", inputs=("X",), outputs=("Out",),
             attrs={"win_size": REQUIRED, "pad_value": 0},
             differentiable=False)
def sequence_enumerate(ins, attrs):
    x = ins["X"]  # [N, T] ids
    w = attrs["win_size"]
    t = x.shape[1]
    pad = jnp.full((x.shape[0], w - 1), attrs["pad_value"], x.dtype)
    xp = jnp.concatenate([x, pad], axis=1)
    wins = jnp.stack([xp[:, i:i + t] for i in range(w)], axis=-1)
    return {"Out": wins}


@register_op("sequence_erase", inputs=("X", "SeqLen"), outputs=("Out",
             "SeqLenOut"), optional=("SeqLen",),
             attrs={"tokens": REQUIRED}, differentiable=False)
def sequence_erase(ins, attrs):
    """Mask erased tokens to pad and compact via sort (stable) — static
    shape version of reference sequence_erase_op.cc."""
    x = ins["X"]
    keep = jnp.ones_like(x, jnp.bool_)
    for tok in attrs["tokens"]:
        keep &= x != tok
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(x, order, axis=1)
    new_len = jnp.sum(keep, axis=1)
    out = jnp.where(jnp.arange(x.shape[1])[None, :] < new_len[:, None],
                    out, 0)
    return {"Out": out, "SeqLenOut": new_len}
