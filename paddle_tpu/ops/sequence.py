"""Sequence ops — the reference's LoD machinery re-specified for TPU.

Reference parity: /root/reference/paddle/fluid/operators/sequence_ops/
(sequence_pool_op.cc, sequence_softmax_op.cc, sequence_expand_op.cc,
sequence_reverse_op.cc, sequence_pad_op.cc ...) and framework/lod_tensor.h.

TPU-first difference (SURVEY.md §7 "hard parts" (a)): XLA needs static
shapes, so variable-length batches are padded [N, T, ...] tensors carried
with an explicit SeqLen [N] int tensor — the bucketed-padding + mask design
— instead of LoD offset vectors over a flattened [sum(T_i), ...] tensor.
Every sequence op here takes (X, SeqLen).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


def _mask(x, seq_len):
    """[N, T] bool validity mask broadcastable over x [N, T, ...]."""
    t = x.shape[1]
    m = jnp.arange(t)[None, :] < seq_len.reshape(-1, 1)
    return m.reshape(m.shape + (1,) * (x.ndim - 2))


@register_op("sequence_mask", inputs=("X",), outputs=("Y",),
             attrs={"maxlen": -1, "out_dtype": "float32"},
             differentiable=False)
def sequence_mask(ins, attrs):
    seq_len = ins["X"].reshape(-1)
    maxlen = attrs["maxlen"]
    if maxlen <= 0:
        raise ValueError(
            "sequence_mask on TPU needs a static maxlen attr (>0)"
        )
    m = jnp.arange(maxlen)[None, :] < seq_len[:, None]
    return {"Y": m.astype(attrs["out_dtype"])}


@register_op("sequence_pool", inputs=("X", "SeqLen"), outputs=("Out",),
             optional=("SeqLen",),
             attrs={"pooltype": "AVERAGE", "pad_value": 0.0})
def sequence_pool(ins, attrs):
    """X: [N, T, ...] padded; SeqLen: [N].  reference sequence_pool_op.cc."""
    x = ins["X"]
    if "SeqLen" in ins:
        seq_len = ins["SeqLen"].reshape(-1)
    else:
        seq_len = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    m = _mask(x, seq_len)
    lens = jnp.maximum(seq_len, 1).astype(x.dtype)
    lens = lens.reshape((-1,) + (1,) * (x.ndim - 2))
    pt = attrs["pooltype"].upper()
    if pt == "SUM":
        return {"Out": jnp.sum(jnp.where(m, x, 0), axis=1)}
    if pt == "AVERAGE":
        return {"Out": jnp.sum(jnp.where(m, x, 0), axis=1) / lens}
    if pt == "SQRT":
        return {"Out": jnp.sum(jnp.where(m, x, 0), axis=1)
                / jnp.sqrt(lens)}
    if pt == "MAX":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        return {"Out": jnp.max(jnp.where(m, x, neg), axis=1)}
    if pt == "LAST":
        idx = jnp.maximum(seq_len - 1, 0)
        return {"Out": jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        )[:, 0]}
    if pt == "FIRST":
        return {"Out": x[:, 0]}
    raise ValueError(f"unknown pooltype {pt}")


@register_op("sequence_softmax", inputs=("X", "SeqLen"), outputs=("Out",),
             optional=("SeqLen",), attrs={})
def sequence_softmax(ins, attrs):
    x = ins["X"]
    if "SeqLen" in ins:
        m = _mask(x, ins["SeqLen"].reshape(-1))
        x = jnp.where(m, x, jnp.asarray(-1e30, x.dtype))
    return {"Out": jax.nn.softmax(x, axis=1)}


@register_op("sequence_reverse", inputs=("X", "SeqLen"), outputs=("Y",),
             optional=("SeqLen",), attrs={})
def sequence_reverse(ins, attrs):
    x = ins["X"]
    if "SeqLen" not in ins:
        return {"Y": jnp.flip(x, axis=1)}
    seq_len = ins["SeqLen"].reshape(-1)
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    rev = seq_len[:, None] - 1 - pos
    idx = jnp.where(pos < seq_len[:, None], rev, pos)
    return {"Y": jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)}


@register_op("sequence_expand", inputs=("X", "Y"), outputs=("Out",),
             attrs={"ref_level": 0})
def sequence_expand(ins, attrs):
    """Broadcast per-sequence rows X [N, ...] over time: Out[n, t] = X[n].
    Padded-form analog of reference sequence_expand_op.cc."""
    x, y = ins["X"], ins["Y"]
    t = y.shape[1]
    return {"Out": jnp.broadcast_to(
        x[:, None], (x.shape[0], t) + x.shape[1:])}


@register_op("sequence_concat", inputs=("X",), outputs=("Out",),
             duplicable=("X",), attrs={})
def sequence_concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=1)}


@register_op("sequence_slice", inputs=("X", "Offset", "Length"),
             outputs=("Out",), attrs={})
def sequence_slice(ins, attrs):
    x, off, length = ins["X"], ins["Offset"], ins["Length"]
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    idx = jnp.minimum(off.reshape(-1, 1) + pos, t - 1)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    valid = pos < length.reshape(-1, 1)
    return {"Out": jnp.where(
        valid.reshape(valid.shape + (1,) * (x.ndim - 2)), out, 0)}


@register_op("sequence_enumerate", inputs=("X",), outputs=("Out",),
             attrs={"win_size": REQUIRED, "pad_value": 0},
             differentiable=False)
def sequence_enumerate(ins, attrs):
    x = ins["X"]  # [N, T] ids
    w = attrs["win_size"]
    t = x.shape[1]
    pad = jnp.full((x.shape[0], w - 1), attrs["pad_value"], x.dtype)
    xp = jnp.concatenate([x, pad], axis=1)
    wins = jnp.stack([xp[:, i:i + t] for i in range(w)], axis=-1)
    return {"Out": wins}


@register_op("sequence_erase", inputs=("X", "SeqLen"), outputs=("Out",
             "SeqLenOut"), optional=("SeqLen",),
             attrs={"tokens": REQUIRED}, differentiable=False)
def sequence_erase(ins, attrs):
    """Mask erased tokens to pad and compact via sort (stable) — static
    shape version of reference sequence_erase_op.cc."""
    x = ins["X"]
    keep = jnp.ones_like(x, jnp.bool_)
    for tok in attrs["tokens"]:
        keep &= x != tok
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(x, order, axis=1)
    new_len = jnp.sum(keep, axis=1)
    out = jnp.where(jnp.arange(x.shape[1])[None, :] < new_len[:, None],
                    out, 0)
    return {"Out": out, "SeqLenOut": new_len}


@register_op("sequence_conv", inputs=("X", "Filter", "SeqLen"),
             outputs=("Out",), optional=("SeqLen",),
             attrs={"contextLength": REQUIRED, "contextStart": None,
                    "contextStride": 1})
def sequence_conv(ins, attrs):
    """Context-window convolution over the time axis (reference
    sequence_conv_op.cc: im2col over the sequence then GEMM).
    X: [N, T, D]; Filter: [ctx_len * D, out_dim]."""
    x = ins["X"]
    w = ins["Filter"]
    ctx_len = attrs["contextLength"]
    start = attrs["contextStart"]
    if start is None:
        start = -(ctx_len // 2)
    n, t, d = x.shape
    cols = []
    for k in range(ctx_len):
        off = start + k
        shifted = jnp.roll(x, -off, axis=1)
        if off > 0:        # positions reading past the end -> 0
            m = jnp.arange(t)[None, :, None] < (t - off)
        elif off < 0:
            m = jnp.arange(t)[None, :, None] >= (-off)
        else:
            m = None
        cols.append(shifted * m if m is not None else shifted)
    col = jnp.concatenate(cols, axis=-1)        # [N, T, ctx*D]
    out = jnp.einsum("ntc,co->nto", col, w)
    if "SeqLen" in ins:
        out = out * _mask(out, ins["SeqLen"])
    return {"Out": out}


@register_op("sequence_expand_as", inputs=("X", "Y", "YSeqLen"),
             outputs=("Out",), optional=("YSeqLen",), attrs={})
def sequence_expand_as(ins, attrs):
    """Expand each row of X along a new time axis to match Y's T
    (reference sequence_expand_as_op.cc: per-sequence broadcast)."""
    x, y = ins["X"], ins["Y"]
    t = y.shape[1]
    out = jnp.broadcast_to(x[:, None, ...], (x.shape[0], t) + x.shape[1:])
    if "YSeqLen" in ins:
        out = out * _mask(out, ins["YSeqLen"]).astype(out.dtype)
    return {"Out": out}


@register_op("sequence_pad", inputs=("X", "SeqLen", "PadValue"),
             outputs=("Out", "Length"), optional=("PadValue",),
             attrs={"padded_length": -1})
def sequence_pad(ins, attrs):
    """Re-pad a padded batch to a given length with a pad value
    (reference sequence_pad_op.cc, LoD->padded; here padded->padded with
    explicit value/length)."""
    x, seq_len = ins["X"], ins["SeqLen"]
    pad_val = ins.get("PadValue", jnp.zeros((), x.dtype))
    target = attrs["padded_length"]
    t = x.shape[1]
    if target > t:
        widths = [(0, 0), (0, target - t)] + [(0, 0)] * (x.ndim - 2)
        x = jnp.pad(x, widths)
    elif 0 < target < t:
        x = x[:, :target]
    m = _mask(x, seq_len)
    out = jnp.where(m, x, jnp.asarray(pad_val, x.dtype).reshape(
        (1,) * x.ndim))
    return {"Out": out, "Length": seq_len}


@register_op("sequence_unpad", inputs=("X", "Length"), outputs=("Out",))
def sequence_unpad(ins, attrs):
    """Zero out positions past each row's Length (reference
    sequence_unpad_op.cc emits a LoD tensor; the padded analog keeps the
    static shape and re-masks)."""
    x = ins["X"]
    return {"Out": x * _mask(x, ins["Length"]).astype(x.dtype)}


@register_op("sequence_reshape", inputs=("X", "SeqLen"),
             outputs=("Out", "OutSeqLen"), optional=("SeqLen",),
             attrs={"new_dim": REQUIRED})
def sequence_reshape(ins, attrs):
    """Refold the time/feature axes so the feature dim becomes new_dim
    (reference sequence_reshape_op.cc)."""
    x = ins["X"]
    n, t, d = x.shape
    new_dim = attrs["new_dim"]
    new_t = t * d // new_dim
    out = x.reshape(n, new_t, new_dim)
    res = {"Out": out}
    if "SeqLen" in ins:
        res["OutSeqLen"] = (ins["SeqLen"] * d) // new_dim
    else:
        res["OutSeqLen"] = jnp.full((n,), new_t, jnp.int32)
    return res


@register_op("sequence_scatter", inputs=("X", "Ids", "Updates"),
             outputs=("Out",))
def sequence_scatter(ins, attrs):
    """Scatter per-sequence updates into X at time indices Ids
    (reference sequence_scatter_op.cc).  X: [N, T, ...] or [N, T];
    Ids/Updates: [N, K]."""
    x, ids, upd = ins["X"], ins["Ids"], ins["Updates"]
    n = x.shape[0]
    batch_idx = jnp.arange(n)[:, None]
    return {"Out": x.at[batch_idx, ids].add(upd.astype(x.dtype))}
