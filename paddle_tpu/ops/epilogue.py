"""Unified epilogue-fusion framework: ONE composable stage grammar for
conv, matmul, and decode kernels (ISSUE 17).

The repo rebuilt "fuse the elementwise tail into the producing op" four
separate times — conv-epilogue (PR 1), conv+BN-stats (PR 4), the int8
requantize epilogue (PR 5), and the decode logits tail (PR 7) — each
with its own transpiler pass, flag, and parity suite.  This module is
the consolidation: a declarative :class:`EpilogueSpec` (an ordered list
of STAGES applied to the VMEM-resident accumulator), the two evaluators
every kernel/reference pair shares, and the NEW fused matmul/fc
epilogue kernel the transformer train graph was missing.

Stage grammar
-------------
A spec is an ordered subset of registered stage names::

    bias        per-channel bias add (the conv2d layer / fc bias, or
                the conv-bn fold's folded shift)
    bn_apply    train-mode BN normalize + scale/shift (conv2d_bn_train)
    stats_tap   per-channel sum(y)/sum(y*y) sibling outputs reduced
                from the resident accumulator (conv2d_bn_stats)
    residual    same-shape skip-connection add
    relu/gelu   activation tail
    requantize  int8 interlayer quantize-to-consumer-scale tail
                (conv2d_int8 / mul_int8 OutScale)
    argmax      the decode engines' greedy logits tail

Canonical order is bias -> stats_tap/bn_apply -> residual -> act ->
requantize -> argmax; ``EpilogueSpec.validate`` rejects anything else,
and the IR verifier (analysis/verifier.py rule ``epilogue-spec``)
checks every ``epilogue`` op attr parses against this grammar, so a
transpiler can never emit a stage list no kernel implements.

Ordering/rounding contract (the bit-parity rule PRs 1/4/5 proved
stage by stage, now stated once):

* ACCUMULATOR order (inside Pallas kernels, ``apply_acc_stages``):
  every stage runs on the f32 accumulator — bias f32, residual f32,
  act f32 — and the single cast to the output dtype happens LAST.
* CHAIN order (the unfused graph / XLA fallback,
  ``apply_chain_stages``): each stage mirrors the discrete op it
  replaces — bias/residual added in the tensor's dtype (with
  elementwise_add's promotion), act last.
* BN tail (``apply_bn_tail``, identical in kernel and XLA): normalize
  in f32, cast to the conv dtype, residual add in that dtype, act.
* requantize tail (``quantize_tail``): astype(f32) / OutScale * bnd,
  round, clip, int8 — the consumer quant's exact rounding point.

For f32 the two orders coincide bitwise; fused-vs-unfused parity is
asserted per legal spec in tests/test_epilogue.py (generated FROM the
grammar, so adding a stage auto-extends the matrix).

Adding a stage = one ``_stage`` entry + an arm in the evaluators +
(optionally) a matcher arm in transpiler/epilogue_transpiler.py.  The
legacy typed flags (``conv_epilogue``, ``conv_bn_stats``,
``int8_interlayer``) are aliases resolving into this path — see
docs/EPILOGUE.md for the flag-alias table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.observability import device_trace as _obs_device
from paddle_tpu.observability import tracing as _obs_trace

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; support
# both (same shim as ops/pallas_conv.py / ops/pallas_kernels.py)
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

_VMEM_BUDGET_BYTES = 12 * 1024 * 1024
_FC_BLOCK_M = 256
_FC_BLOCK_N = 256


# ---------------------------------------------------------------------------
# stage registry
# ---------------------------------------------------------------------------

class EpilogueStage:
    """One registered stage: its canonical position, the operand slot
    it binds (if any), and whether it is an activation (at most one
    activation per spec)."""

    def __init__(self, name, order, operand=None, is_act=False,
                 terminal=False):
        self.name = name
        self.order = order
        self.operand = operand
        self.is_act = is_act
        self.terminal = terminal

    def __repr__(self):
        return f"EpilogueStage({self.name!r})"


# name -> EpilogueStage; canonical order index groups stages that can
# never co-occur at the same level (bn_apply vs stats_tap share a slot:
# conv2d_bn_train carries both semantics in one op)
STAGES = {
    "bias": EpilogueStage("bias", 0, operand="Bias"),
    "stats_tap": EpilogueStage("stats_tap", 1),
    "bn_apply": EpilogueStage("bn_apply", 1),
    "residual": EpilogueStage("residual", 2, operand="Residual"),
    "relu": EpilogueStage("relu", 3, is_act=True),
    "gelu": EpilogueStage("gelu", 3, is_act=True),
    "requantize": EpilogueStage("requantize", 4, operand="OutScale"),
    "argmax": EpilogueStage("argmax", 5, terminal=True),
}

_SEP = "+"


class EpilogueSpec:
    """An ordered, validated list of stage names — the value of the
    ``epilogue`` op attr (serialized via :meth:`to_attr`, a
    ``"bias+residual+relu"``-style string: JSON- and
    program-fingerprint-safe)."""

    def __init__(self, stages=()):
        self.stages = tuple(stages)
        self.validate()

    # -- construction / serialization -----------------------------------
    @classmethod
    def from_attr(cls, attr):
        """Parse the op-attr string form.  Empty string = empty spec
        (a fused op whose chain was all-default)."""
        if not attr:
            return cls(())
        return cls(tuple(attr.split(_SEP)))

    def to_attr(self):
        return _SEP.join(self.stages)

    # -- grammar --------------------------------------------------------
    def validate(self):
        """Raise ValueError unless the stage list is a legal epilogue:
        every name registered, canonical order respected, no duplicate
        stage, at most one activation, terminal stages last."""
        last_order = -1
        seen = set()
        n_act = 0
        for i, name in enumerate(self.stages):
            st = STAGES.get(name)
            if st is None:
                raise ValueError(
                    f"epilogue spec {self.stages!r}: unknown stage "
                    f"{name!r} (registered: {sorted(STAGES)})")
            if name in seen:
                raise ValueError(
                    f"epilogue spec {self.stages!r}: duplicate stage "
                    f"{name!r}")
            seen.add(name)
            if st.order < last_order:
                raise ValueError(
                    f"epilogue spec {self.stages!r}: stage {name!r} "
                    "out of canonical order (bias -> stats_tap/"
                    "bn_apply -> residual -> act -> requantize -> "
                    "argmax)")
            last_order = st.order
            if st.is_act:
                n_act += 1
                if n_act > 1:
                    raise ValueError(
                        f"epilogue spec {self.stages!r}: more than "
                        "one activation stage")
            if st.terminal and i != len(self.stages) - 1:
                raise ValueError(
                    f"epilogue spec {self.stages!r}: terminal stage "
                    f"{name!r} must come last")
        return self

    # -- queries --------------------------------------------------------
    def __contains__(self, name):
        return name in self.stages

    def __iter__(self):
        return iter(self.stages)

    def __len__(self):
        return len(self.stages)

    def __eq__(self, other):
        return isinstance(other, EpilogueSpec) and \
            self.stages == other.stages

    def __hash__(self):
        return hash(self.stages)

    def __repr__(self):
        return f"EpilogueSpec({self.to_attr()!r})"

    @property
    def act(self):
        """The activation stage name, or '' when none."""
        for name in self.stages:
            if STAGES[name].is_act:
                return name
        return ""


def spec_attr(*, bias=False, stats_tap=False, bn_apply=False,
              residual=False, act="", requantize=False, argmax=False):
    """Build the canonical attr string from the shape of a fused op —
    the one way transpilers stamp the ``epilogue`` attr, so emitted
    specs are valid by construction."""
    stages = []
    if bias:
        stages.append("bias")
    if stats_tap:
        stages.append("stats_tap")
    if bn_apply:
        stages.append("bn_apply")
    if residual:
        stages.append("residual")
    if act:
        if act not in STAGES or not STAGES[act].is_act:
            raise ValueError(f"unknown activation stage {act!r}")
        stages.append(act)
    if requantize:
        stages.append("requantize")
    if argmax:
        stages.append("argmax")
    return EpilogueSpec(stages).to_attr()


def enumerate_specs(anchor):
    """Every legal spec a given anchor can carry — drives the
    parametrized stage-matrix parity test (tests/test_epilogue.py), so
    a new stage extends the test matrix without hand-enumeration.

    anchors: 'conv' (conv2d_epilogue), 'conv_bn' (conv2d_bn_train),
    'fc' (fc_epilogue), 'int8' (conv2d_int8 interlayer fold)."""
    if anchor == "conv":
        choices = (("", "bias"), ("", "residual"), ("", "relu"))
    elif anchor == "conv_bn":
        # stats_tap+bn_apply always ride together on conv2d_bn_train
        choices = (("", "bias"), ("stats_tap",), ("bn_apply",),
                   ("", "residual"), ("", "relu"))
    elif anchor == "fc":
        choices = (("", "bias"), ("", "residual"),
                   ("", "relu", "gelu"))
    elif anchor == "int8":
        choices = (("", "bias"), ("", "residual"), ("", "relu"),
                   ("", "requantize"))
    else:
        raise ValueError(f"unknown epilogue anchor {anchor!r}")
    def _prod(choice_lists):
        if not choice_lists:
            yield ()
            return
        for rest in _prod(choice_lists[1:]):
            for c in choice_lists[0]:
                yield ((c,) if c else ()) + rest
    for stages in _prod(list(choices)):
        yield EpilogueSpec(stages)


# ---------------------------------------------------------------------------
# the two shared evaluators + tail helpers (the ordering/rounding
# contract, stated once and consumed by every kernel/reference pair)
# ---------------------------------------------------------------------------

def _act_fn_acc(act, approximate=False):
    """Activation on the f32 accumulator (kernel order)."""
    if not act:
        return lambda a: a
    if act == "relu":
        return lambda a: jnp.maximum(a, 0.0)
    if act == "gelu":
        return lambda a: jax.nn.gelu(a, approximate=approximate)
    raise ValueError(f"unknown activation stage {act!r}")


def _act_fn_chain(act, approximate=False):
    """Activation as the discrete op the chain ran (jax.nn.relu is
    jnp.maximum(x, 0); gelu is the registered gelu op's exact call)."""
    if not act:
        return lambda y: y
    if act == "relu":
        return lambda y: jnp.maximum(y, 0)
    if act == "gelu":
        return lambda y: jax.nn.gelu(y, approximate=approximate)
    raise ValueError(f"unknown activation stage {act!r}")


def apply_acc_stages(acc, *, bias=None, residual=None, act="",
                     approximate=False):
    """ACCUMULATOR-order epilogue: every stage on the f32 accumulator,
    caller casts to the output dtype afterwards.  ``bias``/``residual``
    must already be broadcastable against ``acc`` (the kernels hand in
    their VMEM-resident blocks); both are accumulated in f32.

    This is the in-kernel body of conv2d_epilogue's tail and the fc
    epilogue kernel — one definition, every kernel."""
    if bias is not None:
        acc = acc + bias.astype(jnp.float32)
    if residual is not None:
        acc = acc + residual.astype(jnp.float32)
    return _act_fn_acc(act, approximate)(acc)


def apply_chain_stages(y, *, bias=None, residual=None, act="",
                       approximate=False):
    """CHAIN-order epilogue: the exact op sequence the unfused graph
    runs (bias add in y's dtype, residual add in y's dtype, act last).
    This is the XLA fallback/reference every parity test compares the
    kernels against."""
    if bias is not None:
        y = y + bias.astype(y.dtype)
    if residual is not None:
        y = y + residual.astype(y.dtype)
    return _act_fn_chain(act, approximate)(y)


def apply_bn_tail(t, out_dtype, residual=None, act=""):
    """The BN-apply tail shared bit-for-bit by the Pallas normalize
    kernel and its XLA reference: cast the f32 normalized value to the
    conv dtype FIRST, then residual add in that dtype, then act — the
    unfused batch_norm -> elementwise_add -> relu chain's op order and
    rounding points."""
    t = t.astype(out_dtype)
    if residual is not None:
        t = t + residual.astype(out_dtype)
    return _act_fn_chain(act)(t)


def quantize_tail(y, out_scale, bnd):
    """The requantize stage: quantize the epilogue result to the
    CONSUMER's calibrated scale (symmetric, zero-point 0) — the int8
    interlayer boundary's exact rounding point, shared by conv2d_int8,
    mul_int8 and the standalone requantize op."""
    so = jnp.maximum(out_scale.reshape(()).astype(jnp.float32), 1e-8)
    return jnp.clip(jnp.round(y.astype(jnp.float32) / so * bnd),
                    -bnd, bnd).astype(jnp.int8)


def greedy_logits_tail(logits, axis=-1):
    """The argmax stage: the decode engines' greedy sampling tail over
    the model's logits — stated here so a future sampling flow
    (top-k/top-p) is a stage insertion, not a fourth copy of the
    decode loop (serving/decode_engine.py routes its step, draft, and
    verify-sweep tails through this)."""
    return jnp.argmax(logits, axis=axis)


# ---------------------------------------------------------------------------
# fused matmul/fc epilogue kernel (NEW kernel surface: the transformer
# Adam-tail sibling the batch-slide diagnosis needs)
# ---------------------------------------------------------------------------

def _fc_reference(x2, w2, bias, residual, act, approximate):
    """Unfused composite: exactly the op sequence the IR runs when the
    flag is off (mul -> elementwise_add(bias) -> elementwise_add(skip)
    -> act), on the 2-D flattened operands.  Elementwise adds commute
    bitwise with the surrounding reshapes, so 2-D parity IS graph
    parity."""
    return apply_chain_stages(x2 @ w2, bias=bias, residual=residual,
                              act=act, approximate=approximate)


def _fc_ep_kernel(*refs, act, approximate, has_bias, has_res):
    """One grid cell = one [bm, bn] output tile: full-K contraction on
    the MXU with an f32 accumulator, plus the whole epilogue while the
    tile is VMEM-resident.  refs: x[bm,K], w[K,bn], (bias[1,bn]),
    (residual[bm,bn]), out[bm,bn]."""
    x_ref, w_ref = refs[0], refs[1]
    i = 2
    b_ref = refs[i] if has_bias else None
    i += int(has_bias)
    r_ref = refs[i] if has_res else None
    o_ref = refs[-1]

    ct = jnp.promote_types(x_ref.dtype, w_ref.dtype)
    acc = lax.dot_general(
        x_ref[...].astype(ct), w_ref[...].astype(ct),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    acc = apply_acc_stages(
        acc,
        bias=b_ref[0][None, :] if has_bias else None,
        residual=r_ref[...] if has_res else None,
        act=act, approximate=approximate)
    o_ref[...] = acc.astype(o_ref.dtype)


def _fc_vmem_estimate(m, k, n, bm, bn, has_bias, has_res, x_item,
                      w_item, o_item):
    x_b = bm * k * x_item
    w_b = k * bn * w_item
    o_b = bm * bn * o_item
    b_b = bn * 4 if has_bias else 0
    r_b = bm * bn * o_item if has_res else 0
    acc_b = bm * bn * 4
    return 2 * (x_b + w_b + o_b + b_b + r_b) + acc_b


def _fc_ep_pallas(x2, w2, bias, residual, act, approximate,
                  interpret=False):
    """x2: [M, K]; w2: [K, N]; bias: [N] or None; residual: [M, N] or
    None.  Tiles M and N only (full-K contraction per cell), so the
    accumulation order matches the unfused matmul's."""
    m, k = x2.shape
    _, n = w2.shape
    out_dtype = jnp.promote_types(x2.dtype, w2.dtype)
    bm = min(m, _FC_BLOCK_M)
    bn = min(n, _FC_BLOCK_N)
    if not interpret:
        est = _fc_vmem_estimate(
            m, k, n, bm, bn, bias is not None, residual is not None,
            x2.dtype.itemsize, w2.dtype.itemsize,
            jnp.dtype(out_dtype).itemsize)
        if est > _VMEM_BUDGET_BYTES:
            return _fc_reference(x2, w2, bias, residual, act,
                                 approximate)

    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    in_specs = [
        pl.BlockSpec((bm, k), lambda mi, ni: (mi, 0)),
        pl.BlockSpec((k, bn), lambda mi, ni: (0, ni)),
    ]
    operands = [x2, w2]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, bn), lambda mi, ni: (0, ni)))
        operands.append(bias.reshape(1, n))
    if residual is not None:
        in_specs.append(pl.BlockSpec((bm, bn),
                                     lambda mi, ni: (mi, ni)))
        operands.append(residual)
    params = {}
    if not interpret:
        params["compiler_params"] = _CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    kernel = functools.partial(
        _fc_ep_kernel, act=act, approximate=approximate,
        has_bias=bias is not None, has_res=residual is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
        **params,
    )(*operands)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fc_ep(x2, w2, bias, residual, act, approximate, impl):
    if impl in ("pallas", "interpret"):
        return _fc_ep_pallas(x2, w2, bias, residual, act, approximate,
                             interpret=impl == "interpret")
    return _fc_reference(x2, w2, bias, residual, act, approximate)


def _fc_ep_fwd(x2, w2, bias, residual, act, approximate, impl):
    y = _fc_ep(x2, w2, bias, residual, act, approximate, impl)
    return y, (x2, w2, bias, residual)


def _fc_ep_bwd(act, approximate, impl, res, g):
    """Backward via jax.vjp of the exact unfused composite — under jit
    the recomputed primal is DCE'd and the grads are bit-identical to
    the unfused graph's by construction (the conv-epilogue idiom,
    without hand-deriving the gelu backward)."""
    x2, w2, bias, residual = res
    args = [x2, w2]
    if bias is not None:
        args.append(bias)
    if residual is not None:
        args.append(residual)

    def comp(*a):
        i = 2
        b = a[i] if bias is not None else None
        i += int(bias is not None)
        r = a[i] if residual is not None else None
        return _fc_reference(a[0], a[1], b, r, act, approximate)

    _, vjp = jax.vjp(comp, *args)
    grads = list(vjp(g))
    dx, dw = grads[0], grads[1]
    i = 2
    db = grads[i] if bias is not None else None
    i += int(bias is not None)
    dres = grads[i] if residual is not None else None
    return dx, dw, db, dres


_fc_ep.defvjp(_fc_ep_fwd, _fc_ep_bwd)


def fc_epilogue(x, w, bias=None, residual=None, *, act=None,
                approximate=False, impl=None):
    """Fused matmul + bias + residual + act in one VMEM pass — the
    matmul sibling of conv2d_epilogue, covering the transformer train
    graph's fc+bias+relu/gelu chains.

    x: [M, K] (callers flatten leading dims like the mul op); w:
    [K, N]; bias: [N]; residual: [M, N]; act: None, "relu" or "gelu"
    (``approximate`` as in the gelu op).

    impl: None (auto: pallas on TPU, the exact unfused composite
    elsewhere), "pallas", "interpret", or "xla".  Differentiable in
    x/w/bias/residual via custom_vjp; the backward is jax.vjp of the
    unfused composite, so grads match the flag-off graph bit for
    bit."""
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    if _obs_trace._tracer is not None:
        with _obs_device.annotate("fc_epilogue"):
            return _fc_ep(x, w, bias, residual, act or "",
                          bool(approximate), impl)
    return _fc_ep(x, w, bias, residual, act or "", bool(approximate),
                  impl)


def _on_tpu():
    from paddle_tpu.ops.pallas_kernels import _on_tpu as _chip

    return _chip()


def _fc_impl_from_flag():
    """Map the fc_epilogue flag to an impl name ("off" still returns
    the exact unfused composite — a rewritten program loaded under a
    different flag state must stay bit-identical to the original).
    Same alias contract as conv_epilogue/_impl_from_flag."""
    from paddle_tpu.flags import get_flag

    mode = get_flag("fc_epilogue")
    if mode in ("pallas", "interpret", "xla"):
        return mode
    if mode == "on":
        return None                     # auto: pallas on TPU else xla
    return "xla"                        # "off" (or unknown): unfused


# ---------------------------------------------------------------------------
# IR op registration — the target of the fc arm of
# transpiler.fuse_epilogue
# ---------------------------------------------------------------------------

from paddle_tpu.core.registry import register_op  # noqa: E402

import numpy as np  # noqa: E402


@register_op("fc_epilogue",
             inputs=("X", "Y", "Bias", "Residual"),
             outputs=("Out",),
             optional=("Bias", "Residual"),
             attrs={"x_num_col_dims": 1, "y_num_col_dims": 1,
                    "act": "", "approximate": False, "epilogue": ""})
def _fc_epilogue_op(ins, attrs):
    """mul + channel bias + residual add + activation as ONE op —
    flattening semantics exactly as the mul op's (X at x_num_col_dims,
    Y at y_num_col_dims); Residual is read in the OUTPUT's shape and
    flattened alongside."""
    x, w = ins["X"], ins["Y"]
    bias = ins.get("Bias")
    residual = ins.get("Residual")
    xnc, ync = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
    x2 = x.reshape((int(np.prod(x.shape[:xnc])), -1))
    w2 = w.reshape((int(np.prod(w.shape[:ync])), -1))
    out_shape = x.shape[:xnc] + w.shape[ync:]
    if residual is not None:
        residual = residual.reshape((x2.shape[0], w2.shape[1]))
    out = fc_epilogue(
        x2, w2, bias, residual,
        act=attrs.get("act") or None,
        approximate=attrs.get("approximate", False),
        impl=_fc_impl_from_flag())
    return {"Out": out.reshape(out_shape)}
