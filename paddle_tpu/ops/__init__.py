"""Operator library.

Reference parity: /root/reference/paddle/fluid/operators/ (~460 op types).
Each module registers pure-JAX compute functions with the registry
(paddle_tpu/core/registry.py); kernels, shape inference and gradients all
derive from the one function.
"""

from paddle_tpu.ops import basic  # noqa: F401
from paddle_tpu.ops import nn  # noqa: F401
from paddle_tpu.ops import optim  # noqa: F401
from paddle_tpu.ops import metrics  # noqa: F401
from paddle_tpu.ops import control_flow  # noqa: F401
from paddle_tpu.ops import sequence  # noqa: F401
from paddle_tpu.ops import collective  # noqa: F401
from paddle_tpu.ops import io_ops  # noqa: F401
from paddle_tpu.ops import detection  # noqa: F401
from paddle_tpu.ops import amp  # noqa: F401
from paddle_tpu.ops import parallel_ops  # noqa: F401
from paddle_tpu.ops import quant  # noqa: F401
from paddle_tpu.ops import pallas_kernels  # noqa: F401
from paddle_tpu.ops import pallas_conv  # noqa: F401
from paddle_tpu.ops import epilogue  # noqa: F401
from paddle_tpu.ops import ps_ops  # noqa: F401
from paddle_tpu.ops import loss_ops  # noqa: F401
from paddle_tpu.ops import vision  # noqa: F401
from paddle_tpu.ops import misc  # noqa: F401
from paddle_tpu.ops import rnn_ops  # noqa: F401
from paddle_tpu.ops import fused_ops  # noqa: F401
