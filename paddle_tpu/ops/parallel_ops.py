"""IR ops for sequence/expert parallelism.

These wrap the functional kernels in paddle_tpu/parallel/ so the Program IR
(layers -> CompiledProgram) can express ring attention, Ulysses attention
and Switch-MoE.  The mesh is picked up from parallel.env at trace time; on
a single device they degrade to the plain computation, so the same program
runs anywhere (capability anchor: SURVEY.md §5 long-context/§2.4 EP).
"""

from __future__ import annotations

import jax

from paddle_tpu.core.registry import REQUIRED, register_op


@register_op("ring_attention", inputs=("Q", "K", "V"), outputs=("Out",),
             attrs={"axis": "sp", "causal": False, "scale": -1.0})
def ring_attention_op(ins, attrs):
    from paddle_tpu.parallel.ring_attention import ring_attention

    scale = None if attrs["scale"] < 0 else attrs["scale"]
    return {"Out": ring_attention(ins["Q"], ins["K"], ins["V"],
                                  axis=attrs["axis"],
                                  causal=attrs["causal"], scale=scale)}


@register_op("ulysses_attention", inputs=("Q", "K", "V"), outputs=("Out",),
             attrs={"axis": "sp", "causal": False, "scale": -1.0})
def ulysses_attention_op(ins, attrs):
    from paddle_tpu.parallel.ulysses import ulysses_attention

    scale = None if attrs["scale"] < 0 else attrs["scale"]
    return {"Out": ulysses_attention(ins["Q"], ins["K"], ins["V"],
                                     axis=attrs["axis"],
                                     causal=attrs["causal"], scale=scale)}


@register_op("switch_moe",
             inputs=("X", "GateW", "W1", "B1", "W2", "B2"),
             outputs=("Out", "AuxLoss"),
             attrs={"axis": "ep", "capacity_factor": 1.25})
def switch_moe_op(ins, attrs):
    from paddle_tpu.parallel.moe import moe_ffn

    out, aux = moe_ffn(ins["X"], ins["GateW"], ins["W1"], ins["B1"],
                       ins["W2"], ins["B2"], axis=attrs["axis"],
                       capacity_factor=attrs["capacity_factor"])
    return {"Out": out, "AuxLoss": aux.reshape((1,))}
