"""Optimizer ops — pure value-in/value-out updates; the executor writes
ParamOut back onto the Param variable (declared via in_place), matching the
reference's in-place optimizer kernels.

Reference parity: /root/reference/paddle/fluid/operators/optimizers/
  sgd_op.cc, momentum_op.cc (+LARS), adam_op.cc, adamax_op.cc, adagrad_op.cc,
  adadelta_op.cc, rmsprop_op.cc, ftrl_op.cc, lamb_op.cc,
  decayed_adagrad_op.cc, proximal_gd_op.cc.

Sparse (SelectedRows) gradients are densified by the caller on TPU (dense
segment-sum beats scatter on the MXU-adjacent memory system); a row-sliced
sparse path exists for the PS-style embedding service.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op
from paddle_tpu.core.scope import SelectedRows


def _dense_grad(g):
    if isinstance(g, SelectedRows):
        return g.to_dense()
    return g


@register_op("sgd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), differentiable=False,
             in_place={"ParamOut": "Param"})
def sgd(ins, attrs):
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(ins["Param"].dtype)
    return {"ParamOut": ins["Param"] - lr * g}


@register_op("momentum",
             inputs=("Param", "Grad", "Velocity", "LearningRate"),
             outputs=("ParamOut", "VelocityOut"), differentiable=False,
             attrs={"mu": REQUIRED, "use_nesterov": False},
             in_place={"ParamOut": "Param", "VelocityOut": "Velocity"})
def momentum(ins, attrs):
    p, v = ins["Param"], ins["Velocity"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    mu = attrs["mu"]
    v_out = mu * v + g
    if attrs["use_nesterov"]:
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("lars_momentum",
             inputs=("Param", "Grad", "Velocity", "LearningRate"),
             outputs=("ParamOut", "VelocityOut"), differentiable=False,
             attrs={"mu": REQUIRED, "lars_coeff": 0.001,
                    "lars_weight_decay": 0.0005},
             in_place={"ParamOut": "Param", "VelocityOut": "Velocity"})
def lars_momentum(ins, attrs):
    p, v = ins["Param"], ins["Velocity"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    mu, coeff, wd = attrs["mu"], attrs["lars_coeff"], \
        attrs["lars_weight_decay"]
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * p_norm / (g_norm + wd * p_norm + 1e-12)
    v_out = mu * v + local_lr * (g + wd * p)
    return {"ParamOut": p - v_out, "VelocityOut": v_out}


@register_op("adam",
             inputs=("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
                     "Beta2Pow", "LearningRate"),
             outputs=("ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"),
             differentiable=False,
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                    "lazy_mode": False},
             in_place={"ParamOut": "Param", "Moment1Out": "Moment1",
                       "Moment2Out": "Moment2", "Beta1PowOut": "Beta1Pow",
                       "Beta2PowOut": "Beta2Pow"})
def adam(ins, attrs):
    p, m1, m2 = ins["Param"], ins["Moment1"], ins["Moment2"]
    b1p, b2p = ins["Beta1Pow"], ins["Beta2Pow"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    return {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register_op("fused_adam",
             inputs=("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
                     "Beta2Pow", "LearningRate"),
             outputs=("ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"),
             duplicable=("Param", "Grad", "Moment1", "Moment2",
                         "ParamOut", "Moment1Out", "Moment2Out"),
             differentiable=False,
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
             in_place={"ParamOut": "Param", "Moment1Out": "Moment1",
                       "Moment2Out": "Moment2", "Beta1PowOut": "Beta1Pow",
                       "Beta2PowOut": "Beta2Pow"})
def fused_adam(ins, attrs):
    """Multi-tensor Adam: ONE op over every (param, grad, m1, m2)
    tuple.  Each dtype group is flattened and concatenated so the whole
    optimizer tail is a single elementwise pass over one contiguous
    buffer instead of ~N small kernels XLA schedules independently —
    the Adam-tail A/B lever for the transformer batch-slide diagnosis
    (PROFILE_r4 §5.3 deferral; VERDICT r5 next-round #6).  The update
    math matches the per-param `adam` op (lr_t computed in f32, cast
    per dtype group); beta pows are shared — every param sees the same
    step count."""
    import numpy as np

    ps, gs = ins["Param"], ins["Grad"]
    m1s, m2s = ins["Moment1"], ins["Moment2"]
    b1p, b2p = ins["Beta1Pow"], ins["Beta2Pow"]
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    lr32 = ins["LearningRate"].astype(jnp.float32)
    lr_t = lr32 * jnp.sqrt(1 - b2p.astype(jnp.float32)) \
        / (1 - b1p.astype(jnp.float32))
    n = len(ps)
    p_out, m1_out, m2_out = [None] * n, [None] * n, [None] * n
    groups: dict = {}
    for i, p in enumerate(ps):
        groups.setdefault(jnp.dtype(p.dtype), []).append(i)
    for dt, idxs in groups.items():
        sizes = [max(int(np.prod(ps[i].shape)), 1) for i in idxs]
        pc = jnp.concatenate([ps[i].reshape(-1) for i in idxs])
        gc = jnp.concatenate([
            _dense_grad(gs[i]).reshape(-1).astype(dt) for i in idxs])
        m1c = jnp.concatenate([m1s[i].reshape(-1) for i in idxs])
        m2c = jnp.concatenate([m2s[i].reshape(-1) for i in idxs])
        m1n = b1 * m1c + (1 - b1) * gc
        m2n = b2 * m2c + (1 - b2) * jnp.square(gc)
        pn = pc - lr_t.astype(dt) * m1n / (jnp.sqrt(m2n) + eps)
        offs = np.cumsum([0] + sizes)
        for j, i in enumerate(idxs):
            sl = slice(int(offs[j]), int(offs[j + 1]))
            p_out[i] = pn[sl].reshape(ps[i].shape)
            m1_out[i] = m1n[sl].reshape(ps[i].shape)
            m2_out[i] = m2n[sl].reshape(ps[i].shape)
    return {"ParamOut": p_out, "Moment1Out": m1_out,
            "Moment2Out": m2_out, "Beta1PowOut": b1p * b1,
            "Beta2PowOut": b2p * b2}


@register_op("adamw",
             inputs=("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
                     "Beta2Pow", "LearningRate"),
             outputs=("ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"),
             differentiable=False,
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                    "weight_decay": 0.01},
             in_place={"ParamOut": "Param", "Moment1Out": "Moment1",
                       "Moment2Out": "Moment2", "Beta1PowOut": "Beta1Pow",
                       "Beta2PowOut": "Beta2Pow"})
def adamw(ins, attrs):
    p = ins["Param"]
    lr = ins["LearningRate"].astype(p.dtype)
    out = adam({**ins, "Param": p}, {k: attrs[k] for k in
                                     ("beta1", "beta2", "epsilon")}
               | {"lazy_mode": False})
    out["ParamOut"] = out["ParamOut"] - lr * attrs["weight_decay"] * p
    return out


@register_op("adagrad", inputs=("Param", "Grad", "Moment", "LearningRate"),
             outputs=("ParamOut", "MomentOut"), differentiable=False,
             attrs={"epsilon": 1e-6},
             in_place={"ParamOut": "Param", "MomentOut": "Moment"})
def adagrad(ins, attrs):
    p, m = ins["Param"], ins["Moment"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    m_out = m + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + attrs["epsilon"])
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adadelta",
             inputs=("Param", "Grad", "AvgSquaredGrad",
                     "AvgSquaredUpdate"),
             outputs=("ParamOut", "AvgSquaredGradOut",
                      "AvgSquaredUpdateOut"),
             differentiable=False,
             attrs={"rho": 0.95, "epsilon": 1e-6},
             in_place={"ParamOut": "Param",
                       "AvgSquaredGradOut": "AvgSquaredGrad",
                       "AvgSquaredUpdateOut": "AvgSquaredUpdate"})
def adadelta(ins, attrs):
    p, asg, asu = ins["Param"], ins["AvgSquaredGrad"], \
        ins["AvgSquaredUpdate"]
    g = _dense_grad(ins["Grad"])
    rho, eps = attrs["rho"], attrs["epsilon"]
    asg_out = rho * asg + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((asu + eps) / (asg_out + eps)) * g
    asu_out = rho * asu + (1 - rho) * jnp.square(update)
    return {"ParamOut": p + update, "AvgSquaredGradOut": asg_out,
            "AvgSquaredUpdateOut": asu_out}


@register_op("rmsprop",
             inputs=("Param", "Grad", "MeanSquare", "MeanGrad", "Moment",
                     "LearningRate"),
             outputs=("ParamOut", "MeanSquareOut", "MeanGradOut",
                      "MomentOut"),
             differentiable=False,
             attrs={"decay": 0.9, "momentum": 0.0, "epsilon": 1e-10,
                    "centered": False},
             in_place={"ParamOut": "Param", "MeanSquareOut": "MeanSquare",
                       "MeanGradOut": "MeanGrad", "MomentOut": "Moment"})
def rmsprop(ins, attrs):
    p, ms, mg, mom = ins["Param"], ins["MeanSquare"], ins["MeanGrad"], \
        ins["Moment"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    rho, eps = attrs["decay"], attrs["epsilon"]
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    if attrs["centered"]:
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - jnp.square(mg_out) + eps
    else:
        mg_out = mg
        denom = ms_out + eps
    mom_out = attrs["momentum"] * mom + lr * g / jnp.sqrt(denom)
    return {"ParamOut": p - mom_out, "MeanSquareOut": ms_out,
            "MeanGradOut": mg_out, "MomentOut": mom_out}


@register_op("adamax",
             inputs=("Param", "Grad", "Moment", "InfNorm", "Beta1Pow",
                     "LearningRate"),
             outputs=("ParamOut", "MomentOut", "InfNormOut"),
             differentiable=False,
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
             in_place={"ParamOut": "Param", "MomentOut": "Moment",
                       "InfNormOut": "InfNorm"})
def adamax(ins, attrs):
    p, m, inf = ins["Param"], ins["Moment"], ins["InfNorm"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    b1, b2, eps = attrs["beta1"], attrs["beta2"], attrs["epsilon"]
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    lr_t = lr / (1 - ins["Beta1Pow"])
    return {"ParamOut": p - lr_t * m_out / inf_out, "MomentOut": m_out,
            "InfNormOut": inf_out}


@register_op("ftrl",
             inputs=("Param", "Grad", "SquaredAccumulator",
                     "LinearAccumulator", "LearningRate"),
             outputs=("ParamOut", "SquaredAccumOut", "LinearAccumOut"),
             differentiable=False,
             attrs={"l1": 0.0, "l2": 0.0, "lr_power": -0.5},
             in_place={"ParamOut": "Param",
                       "SquaredAccumOut": "SquaredAccumulator",
                       "LinearAccumOut": "LinearAccumulator"})
def ftrl(ins, attrs):
    p, sq, lin = ins["Param"], ins["SquaredAccumulator"], \
        ins["LinearAccumulator"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    l1, l2, lrp = attrs["l1"], attrs["l2"], attrs["lr_power"]
    sq_out = sq + jnp.square(g)
    sigma = (jnp.power(sq_out, -lrp) - jnp.power(sq, -lrp)) / lr
    lin_out = lin + g - sigma * p
    x = -lin_out + jnp.clip(lin_out, -l1, l1)
    y = jnp.power(sq_out, -lrp) / lr + 2 * l2
    return {"ParamOut": x / y, "SquaredAccumOut": sq_out,
            "LinearAccumOut": lin_out}


@register_op("lamb",
             inputs=("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
                     "Beta2Pow", "LearningRate"),
             outputs=("ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"),
             differentiable=False,
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6,
                    "weight_decay": 0.01},
             in_place={"ParamOut": "Param", "Moment1Out": "Moment1",
                       "Moment2Out": "Moment2", "Beta1PowOut": "Beta1Pow",
                       "Beta2PowOut": "Beta2Pow"})
def lamb(ins, attrs):
    p, m1, m2 = ins["Param"], ins["Moment1"], ins["Moment2"]
    b1p, b2p = ins["Beta1Pow"], ins["Beta2Pow"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    b1, b2, eps, wd = attrs["beta1"], attrs["beta2"], attrs["epsilon"], \
        attrs["weight_decay"]
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    m1_hat = m1_out / (1 - b1p)
    m2_hat = m2_out / (1 - b2p)
    r = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where(
        (p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0
    )
    return {"ParamOut": p - lr * trust * r, "Moment1Out": m1_out,
            "Moment2Out": m2_out, "Beta1PowOut": b1p * b1,
            "Beta2PowOut": b2p * b2}


@register_op("decayed_adagrad",
             inputs=("Param", "Grad", "Moment", "LearningRate"),
             outputs=("ParamOut", "MomentOut"), differentiable=False,
             attrs={"decay": 0.95, "epsilon": 1e-6},
             in_place={"ParamOut": "Param", "MomentOut": "Moment"})
def decayed_adagrad(ins, attrs):
    p, m = ins["Param"], ins["Moment"]
    g = _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].astype(p.dtype)
    m_out = attrs["decay"] * m + (1 - attrs["decay"]) * jnp.square(g)
    return {"ParamOut": p - lr * g / (jnp.sqrt(m_out) + attrs["epsilon"]),
            "MomentOut": m_out}


@register_op("lookahead_update",
             inputs=("Param", "Slow", "Step"),
             outputs=("ParamOut", "SlowOut"), differentiable=False,
             attrs={"alpha": 0.5, "k": 5},
             in_place={"ParamOut": "Param", "SlowOut": "Slow"})
def lookahead_update(ins, attrs):
    """Every k steps: slow += alpha*(fast-slow); fast = slow.  The
    k-step schedule is a where() select so it compiles into the jitted
    step (reference incubate LookaheadOptimizer host-side variant)."""
    p, slow = ins["Param"], ins["Slow"]
    step = ins["Step"].reshape(()).astype(jnp.float32)
    k = float(attrs["k"])
    sync = jnp.mod(step, k) == 0.0
    new_slow = slow + attrs["alpha"] * (p - slow)
    slow_out = jnp.where(sync, new_slow, slow)
    p_out = jnp.where(sync, new_slow, p)
    return {"ParamOut": p_out, "SlowOut": slow_out}


@register_op("dgc_momentum",
             inputs=("Param", "Grad", "U", "V", "Velocity",
                     "LearningRate", "Step"),
             outputs=("ParamOut", "UOut", "VOut", "VelocityOut"),
             differentiable=False, optional=("Step",),
             attrs={"momentum": REQUIRED, "sparsity": 0.999,
                    "rampup_begin_step": 0, "use_nesterov": False},
             in_place={"ParamOut": "Param", "UOut": "U", "VOut": "V",
                       "VelocityOut": "Velocity"})
def dgc_momentum(ins, attrs):
    """DGC (reference dgc_op.cc + DGCMomentumOptimizer): local gradient
    accumulation u, error-feedback buffer v, top-k mask by |v|, masked
    momentum update; dense warmup until rampup_begin_step.  The
    'encoded' gradient stays dense (mask*value) — TPU prefers dense
    top-k over scatter."""
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    u, v, vel = ins["U"], ins["V"], ins["Velocity"]
    lr = ins["LearningRate"].astype(p.dtype)
    m = attrs["momentum"]
    u = m * u + g                      # momentum correction
    v = v + u
    flat = jnp.abs(v).reshape(-1)
    from paddle_tpu.parallel.dgc import dgc_top_k_count

    k = dgc_top_k_count(flat.shape[0], attrs["sparsity"])
    thresh = jax.lax.top_k(flat, k)[0][-1]
    mask = (jnp.abs(v) >= thresh).astype(p.dtype)
    if attrs["rampup_begin_step"] > 0 and "Step" not in ins:
        raise ValueError(
            "dgc_momentum: rampup_begin_step > 0 requires the Step "
            "input (the optimizer wires it automatically)")
    if "Step" in ins and attrs["rampup_begin_step"] > 0:
        # dense warmup: before rampup_begin_step every component passes
        step = ins["Step"].reshape(()).astype(jnp.float32)
        warm = step <= float(attrs["rampup_begin_step"])
        mask = jnp.where(warm, jnp.ones_like(mask), mask)
    sparse_grad = v * mask
    v = v * (1.0 - mask)               # error feedback: keep the rest
    u = u * (1.0 - mask)
    vel_out = m * vel + sparse_grad
    if attrs["use_nesterov"]:
        p_out = p - (sparse_grad + m * vel_out) * lr
    else:
        p_out = p - lr * vel_out
    return {"ParamOut": p_out, "UOut": u, "VOut": v,
            "VelocityOut": vel_out}


@register_op("model_average_update",
             inputs=("Params", "Sums", "Count", "Total"),
             outputs=("SumsOut", "CountOut"),
             duplicable=("Params", "Sums", "SumsOut"),
             differentiable=False,
             attrs={"average_window_rate": 0.15,
                    "min_average_window": 100,
                    "max_average_window": 10000},
             in_place={"SumsOut": "Sums", "CountOut": "Count"})
def model_average_update(ins, attrs):
    """Bounded-window parameter-sum accumulation (reference
    ModelAverage sum_1/2/3 rotation, optimizer.py:2244 — simplified to
    a single sum that restarts when the window limit is hit).  The
    effective window is max(min_w, min(max_w, rate * total_updates))."""
    params, sums = ins["Params"], ins["Sums"]
    count = ins["Count"].reshape(())
    total = ins["Total"].reshape(())
    window = jnp.clip(attrs["average_window_rate"] * total,
                      float(attrs["min_average_window"]),
                      float(attrs["max_average_window"]))
    restart = count >= window
    new_count = jnp.where(restart, 1.0, count + 1.0)
    new_sums = [jnp.where(restart, p, s + p)
                for p, s in zip(params, sums)]
    return {"SumsOut": new_sums, "CountOut": new_count.reshape(1)}


@register_op("proximal_gd",
             inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), differentiable=False,
             attrs={"l1": 0.0, "l2": 0.0},
             in_place={"ParamOut": "Param"})
def proximal_gd(ins, attrs):
    """optimizers/proximal_gd_op.h: prox_param = p - lr*g, then the
    l1 soft-threshold / l2 shrink proximal step."""
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    lr = ins["LearningRate"].reshape(()).astype(p.dtype)
    l1 = jnp.asarray(attrs["l1"], p.dtype)
    l2 = jnp.asarray(attrs["l2"], p.dtype)
    prox = p - lr * g
    if attrs["l1"] > 0:
        out = (jnp.sign(prox)
               * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
               / (1.0 + lr * l2))
    else:
        out = prox / (1.0 + lr * l2)
    return {"ParamOut": out}


@register_op("proximal_adagrad",
             inputs=("Param", "Moment", "Grad", "LearningRate"),
             outputs=("ParamOut", "MomentOut"), differentiable=False,
             attrs={"l1": 0.0, "l2": 0.0},
             in_place={"ParamOut": "Param", "MomentOut": "Moment"})
def proximal_adagrad(ins, attrs):
    """optimizers/proximal_adagrad_op.h: adagrad accumulator + the same
    proximal step with per-element lr/sqrt(m)."""
    p, g = ins["Param"], _dense_grad(ins["Grad"])
    m = ins["Moment"]
    lr = ins["LearningRate"].reshape(()).astype(p.dtype)
    l1 = jnp.asarray(attrs["l1"], p.dtype)
    l2 = jnp.asarray(attrs["l2"], p.dtype)
    m_out = m + g * g
    prox = p - lr * g / jnp.sqrt(m_out)
    if attrs["l1"] > 0:
        out = (jnp.sign(prox)
               * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
               / (1.0 + lr * l2))
    else:
        out = prox / (1.0 + lr * l2)
    return {"ParamOut": out, "MomentOut": m_out}


def _dgc_rampup_sparsity(step, sparsity_steps, rampup_step):
    """Sparsity warmup schedule, matching dgc_op.h get_period_sparcity:
    idx = int(cur_step * len(sparsity) / rampup_steps) over the ABSOLUTE
    step count, pinned to 0.999 once idx runs past the vector end."""
    phases = len(sparsity_steps)
    idx = (step * phases / max(rampup_step, 1.0)).astype(jnp.int32)
    in_vec = jnp.asarray(sparsity_steps)[
        jnp.clip(idx, 0, phases - 1)]
    return jnp.where(idx >= phases, 0.999, in_vec)


@register_op("dgc",
             inputs=("U", "V", "Grad", "current_step"),
             outputs=("U_out", "V_out", "EncodeGrad", "Grad_out", "k"),
             differentiable=False,
             attrs={"m": 0.9, "use_nesterov": False,
                    "sparsity": [0.999], "rampup_begin_step": 0.0,
                    "rampup_step": 1.0},
             in_place={"U_out": "U", "V_out": "V"})
def dgc(ins, attrs):
    """dgc_op.cc: the standalone sparsify stage (momentum correction +
    error feedback + top-k).  EncodeGrad is the dense masked gradient —
    the actual sparse wire exchange is parallel/dgc.py dgc_allreduce."""
    g = _dense_grad(ins["Grad"])
    u, v = ins["U"], ins["V"]
    step = ins["current_step"].reshape(()).astype(jnp.float32)
    m = attrs["m"]
    if attrs["use_nesterov"]:
        # dgc_op.h:89-97: u = m*(u+g); v = u + v + g (v_out aliases v,
        # so both adds read the freshly written u)
        u = m * (u + g)
        v = u + v + g
    else:
        # dgc_op.h:99-104: u = m*u + g; v = u + v
        u = m * u + g
        v = v + u
    sparsity = _dgc_rampup_sparsity(
        step, [float(s) for s in attrs["sparsity"]],
        float(attrs["rampup_step"]))
    n = v.size
    # the scheduled sparsity is a traced value, so k is dynamic: take
    # the threshold at the k-th largest |v| via a full descending sort
    # + dynamic_slice (static shapes throughout, jittable)
    flat = jnp.abs(v).reshape(-1)
    sorted_desc = jnp.sort(flat)[::-1]
    k_sched = jnp.clip(
        (n * (1.0 - sparsity)).astype(jnp.int32), 1, n)
    kth = jax.lax.dynamic_index_in_dim(sorted_desc, k_sched - 1,
                                       keepdims=False)
    warm = step < float(attrs["rampup_begin_step"])
    mask = jnp.where(warm, jnp.ones_like(v, dtype=bool),
                     jnp.abs(v) >= kth)
    encode = jnp.where(mask, v, 0.0)
    u_out = jnp.where(mask, 0.0, u)
    v_out = jnp.where(mask, 0.0, v)
    return {"U_out": u_out, "V_out": v_out, "EncodeGrad": encode,
            "Grad_out": encode,
            "k": k_sched.astype(jnp.float32).reshape(1)}


@register_op("dgc_clip_by_norm",
             inputs=("X", "current_step"), outputs=("Out",),
             differentiable=False,
             attrs={"max_norm": REQUIRED, "rampup_begin_step": 0.0})
def dgc_clip_by_norm(ins, attrs):
    """dgc_clip_by_norm_op.cc: clip_by_norm that only engages after
    rampup_begin_step (identity during dense warmup)."""
    x = ins["X"]
    step = ins["current_step"].reshape(()).astype(jnp.float32)
    norm = jnp.sqrt(jnp.sum(x * x))
    max_norm = jnp.asarray(attrs["max_norm"], x.dtype)
    clipped = x * jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return {"Out": jnp.where(step < float(attrs["rampup_begin_step"]),
                             x, clipped)}
