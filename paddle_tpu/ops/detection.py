"""Detection ops (subset; reference /root/reference/paddle/fluid/operators/
detection/ — anchors, boxes, iou, yolo_box; NMS variants follow in the
detection milestone)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


def _pairwise_iou(x, y, normalized=True):
    """x: [N,4], y: [M,4] (xmin,ymin,xmax,ymax) -> [N,M] IoU.  With
    normalized=False the reference adds a +1 pixel offset to widths
    and heights (multiclass_nms_op.cc:113-146 BBoxArea/JaccardOverlap)."""
    off = 0.0 if normalized else 1.0
    ax = (x[:, 2] - x[:, 0] + off) * (x[:, 3] - x[:, 1] + off)
    ay = (y[:, 2] - y[:, 0] + off) * (y[:, 3] - y[:, 1] + off)
    xmin = jnp.maximum(x[:, None, 0], y[None, :, 0])
    ymin = jnp.maximum(x[:, None, 1], y[None, :, 1])
    xmax = jnp.minimum(x[:, None, 2], y[None, :, 2])
    ymax = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(xmax - xmin + off, 0.0)
    ih = jnp.maximum(ymax - ymin + off, 0.0)
    inter = iw * ih
    return inter / (ax[:, None] + ay[None, :] - inter + 1e-10)


@register_op("iou_similarity", inputs=("X", "Y"), outputs=("Out",),
             attrs={"box_normalized": True})
def iou_similarity(ins, attrs):
    return {"Out": _pairwise_iou(ins["X"], ins["Y"],
                                 attrs["box_normalized"])}


@register_op("box_coder", inputs=("PriorBox", "PriorBoxVar", "TargetBox"),
             outputs=("OutputBox",), optional=("PriorBoxVar",),
             attrs={"code_type": "encode_center_size",
                    "box_normalized": True, "axis": 0})
def box_coder(ins, attrs):
    prior = ins["PriorBox"]
    target = ins["TargetBox"]
    var = ins.get("PriorBoxVar")
    off = 0.0 if attrs["box_normalized"] else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if attrs["code_type"] == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph[None, :]),
        ], axis=-1)
        if var is not None:
            out = out / var[None, :, :]
        return {"OutputBox": out}
    # decode_center_size: target [N, M, 4]
    t = target
    if var is not None:
        t = t * var[None, :, :]
    ocx = t[..., 0] * pw[None, :] + pcx[None, :]
    ocy = t[..., 1] * ph[None, :] + pcy[None, :]
    ow = jnp.exp(t[..., 2]) * pw[None, :]
    oh = jnp.exp(t[..., 3]) * ph[None, :]
    return {"OutputBox": jnp.stack(
        [ocx - ow / 2, ocy - oh / 2, ocx + ow / 2 - off,
         ocy + oh / 2 - off], axis=-1)}


@register_op("prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"),
             attrs={"min_sizes": REQUIRED, "max_sizes": [],
                    "aspect_ratios": [1.0], "variances": [0.1, 0.1, 0.2,
                                                          0.2],
                    "flip": False, "clip": False, "step_w": 0.0,
                    "step_h": 0.0, "offset": 0.5},
             differentiable=False)
def prior_box(ins, attrs):
    feat, img = ins["Input"], ins["Image"]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = attrs["step_w"] or iw / fw
    step_h = attrs["step_h"] or ih / fh
    ars = list(attrs["aspect_ratios"])
    if attrs["flip"]:
        ars = ars + [1.0 / a for a in attrs["aspect_ratios"] if a != 1.0]
    sizes = []
    for ms in attrs["min_sizes"]:
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
    for ms, mx in zip(attrs["min_sizes"], attrs["max_sizes"] or []):
        s = np.sqrt(ms * mx)
        sizes.append((s, s))
    cx = (jnp.arange(fw) + attrs["offset"]) * step_w
    cy = (jnp.arange(fh) + attrs["offset"]) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    boxes = []
    for bw, bh in sizes:
        boxes.append(jnp.stack([
            (cxg - bw / 2) / iw, (cyg - bh / 2) / ih,
            (cxg + bw / 2) / iw, (cyg + bh / 2) / ih], axis=-1))
    out = jnp.stack(boxes, axis=2)  # [fh, fw, nboxes, 4]
    if attrs["clip"]:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs["variances"]), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("yolo_box", inputs=("X", "ImgSize"),
             outputs=("Boxes", "Scores"),
             attrs={"anchors": REQUIRED, "class_num": REQUIRED,
                    "conf_thresh": 0.01, "downsample_ratio": 32},
             differentiable=False)
def yolo_box(ins, attrs):
    x, img_size = ins["X"], ins["ImgSize"]
    n, c, h, w = x.shape
    anchors = attrs["anchors"]
    na = len(anchors) // 2
    nc = attrs["class_num"]
    x = x.reshape(n, na, 5 + nc, h, w)
    grid_x = jnp.arange(w)[None, None, None, :]
    grid_y = jnp.arange(h)[None, None, :, None]
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]
    stride = attrs["downsample_ratio"]
    bw = jnp.exp(x[:, :, 2]) * aw / (w * stride)
    bh = jnp.exp(x[:, :, 3]) * ah / (h * stride)
    conf = jax.nn.sigmoid(x[:, :, 4])
    prob = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = (conf >= attrs["conf_thresh"]).astype(x.dtype)
    ih = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    iw_ = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([
        (bx - bw / 2) * iw_, (by - bh / 2) * ih,
        (bx + bw / 2) * iw_, (by + bh / 2) * ih], axis=-1)
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, -1, 4)
    scores = (prob * mask[:, :, None]).transpose(0, 1, 3, 4, 2)
    return {"Boxes": boxes, "Scores": scores.reshape(n, -1, nc)}


@register_op("box_clip", inputs=("Input", "ImInfo"), outputs=("Output",))
def box_clip(ins, attrs):
    """Clip boxes to image bounds (reference box_clip_op.cc).
    Input: [..., 4]; ImInfo: [N, 3] (h, w, scale)."""
    boxes = ins["Input"]
    im = ins["ImInfo"]
    h = (im[:, 0] / im[:, 2]) - 1.0
    w = (im[:, 1] / im[:, 2]) - 1.0
    shape = (-1,) + (1,) * (boxes.ndim - 2)
    xmin = jnp.clip(boxes[..., 0], 0.0, w.reshape(shape))
    ymin = jnp.clip(boxes[..., 1], 0.0, h.reshape(shape))
    xmax = jnp.clip(boxes[..., 2], 0.0, w.reshape(shape))
    ymax = jnp.clip(boxes[..., 3], 0.0, h.reshape(shape))
    return {"Output": jnp.stack([xmin, ymin, xmax, ymax], axis=-1)}


@register_op("sigmoid_focal_loss", inputs=("X", "Label", "FgNum"),
             outputs=("Out",), optional=("FgNum",),
             attrs={"gamma": 2.0, "alpha": 0.25})
def sigmoid_focal_loss(ins, attrs):
    """RetinaNet focal loss (reference sigmoid_focal_loss_op.cc).
    X: [N, C] logits; Label: [N, 1] in [0, C] (0 = background)."""
    x = ins["X"].astype(jnp.float32)
    label = ins["Label"].reshape(-1)
    n, c = x.shape
    fg = ins.get("FgNum")
    fg = jnp.maximum(fg.reshape(()).astype(jnp.float32), 1.0) \
        if fg is not None else 1.0
    gamma, alpha = attrs["gamma"], attrs["alpha"]
    # one-hot with class c meaning label-1 (0 is background)
    t = (label[:, None] == jnp.arange(1, c + 1)[None, :]).astype(
        jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jax.nn.softplus(-x) * t + jax.nn.softplus(x) * (1 - t)
    pt = p * t + (1 - p) * (1 - t)
    at = alpha * t + (1 - alpha) * (1 - t)
    return {"Out": at * (1 - pt) ** gamma * ce / fg}


@register_op("anchor_generator", inputs=("Input",),
             outputs=("Anchors", "Variances"),
             attrs={"anchor_sizes": REQUIRED, "aspect_ratios": REQUIRED,
                    "variances": [0.1, 0.1, 0.2, 0.2],
                    "stride": REQUIRED, "offset": 0.5})
def anchor_generator(ins, attrs):
    """Dense anchors over the feature map (reference
    anchor_generator_op.cc).  Input: [N, C, H, W] ->
    Anchors [H, W, A, 4] (xmin,ymin,xmax,ymax, image coords)."""
    _, _, h, w = ins["Input"].shape
    sizes = jnp.asarray(attrs["anchor_sizes"], jnp.float32)
    ratios = jnp.asarray(attrs["aspect_ratios"], jnp.float32)
    sw, sh = attrs["stride"]
    off = attrs["offset"]
    # reference anchor_generator_op.h:55,75: centers at
    # w*stride + offset*(stride-1); extents 0.5*(anchor_dim-1) with
    # rounded base widths/heights
    cx = jnp.arange(w) * sw + off * (sw - 1)
    cy = jnp.arange(h) * sh + off * (sh - 1)
    r = jnp.sqrt(ratios)
    area = sizes[None, :] ** 2
    ws = jnp.round(jnp.sqrt(area / ratios[:, None])).reshape(-1)  # [A]
    hs = jnp.round(ws.reshape(ratios.shape[0], -1)
                   * ratios[:, None]).reshape(-1)
    del r
    grid_cx = jnp.broadcast_to(cx[None, :, None], (h, w, ws.shape[0]))
    grid_cy = jnp.broadcast_to(cy[:, None, None], (h, w, ws.shape[0]))
    anchors = jnp.stack(
        [grid_cx - 0.5 * (ws - 1), grid_cy - 0.5 * (hs - 1),
         grid_cx + 0.5 * (ws - 1), grid_cy + 0.5 * (hs - 1)],
        axis=-1)
    var = jnp.broadcast_to(
        jnp.asarray(attrs["variances"], jnp.float32),
        anchors.shape)
    return {"Anchors": anchors, "Variances": var}


@register_op("density_prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"),
             attrs={"densities": REQUIRED, "fixed_sizes": REQUIRED,
                    "fixed_ratios": [1.0],
                    "variances": [0.1, 0.1, 0.2, 0.2],
                    "clip": False, "step_w": 0.0, "step_h": 0.0,
                    "offset": 0.5})
def density_prior_box(ins, attrs):
    """Densified SSD priors (reference density_prior_box_op.cc)."""
    _, _, h, w = ins["Input"].shape
    _, _, img_h, img_w = ins["Image"].shape
    step_w = attrs["step_w"] or img_w / w
    step_h = attrs["step_h"] or img_h / h
    off = attrs["offset"]
    # reference density_prior_box_op.h:91-101: sub-centers spread over
    # the STEP cell (spacing step_average/density), not over the box
    step_average = int((step_w + step_h) * 0.5)
    boxes_per_cell = []
    for density, size in zip(attrs["densities"], attrs["fixed_sizes"]):
        for ratio in attrs["fixed_ratios"]:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            shift = step_average / density
            for di in range(density):
                for dj in range(density):
                    cx_off = -step_average / 2.0 + shift / 2.0 \
                        + dj * shift
                    cy_off = -step_average / 2.0 + shift / 2.0 \
                        + di * shift
                    boxes_per_cell.append((cx_off, cy_off, bw, bh))
    cx = (jnp.arange(w) + off) * step_w
    cy = (jnp.arange(h) + off) * step_h
    grid_cx = jnp.broadcast_to(cx[None, :, None],
                               (h, w, len(boxes_per_cell)))
    grid_cy = jnp.broadcast_to(cy[:, None, None],
                               (h, w, len(boxes_per_cell)))
    offs = jnp.asarray(boxes_per_cell, jnp.float32)    # [K, 4]
    bx = grid_cx + offs[None, None, :, 0]
    by = grid_cy + offs[None, None, :, 1]
    bw = offs[None, None, :, 2]
    bh = offs[None, None, :, 3]
    boxes = jnp.stack([(bx - bw / 2.0) / img_w, (by - bh / 2.0) / img_h,
                       (bx + bw / 2.0) / img_w, (by + bh / 2.0) / img_h],
                      axis=-1)
    if attrs["clip"]:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs["variances"], jnp.float32),
                           boxes.shape)
    return {"Boxes": boxes, "Variances": var}


@register_op("target_assign",
             inputs=("X", "MatchIndices", "NegIndices"),
             outputs=("Out", "OutWeight"), optional=("NegIndices",),
             attrs={"mismatch_value": 0})
def target_assign(ins, attrs):
    """Assign per-prior targets by match indices (reference
    target_assign_op.cc).  X: [N, M, K] gt-entity features;
    MatchIndices: [N, P] (-1 = unmatched) -> Out [N, P, K]."""
    x, match = ins["X"], ins["MatchIndices"]
    n, p = match.shape
    safe = jnp.maximum(match, 0)
    batch = jnp.arange(n)[:, None]
    out = x[batch, safe]                              # [N, P, K]
    matched = (match >= 0)
    out = jnp.where(matched[..., None], out,
                    jnp.asarray(attrs["mismatch_value"], x.dtype))
    weight = matched.astype(jnp.float32)[..., None]
    neg = ins.get("NegIndices")
    if neg is not None:
        # reference NegTargetAssignFunctor (target_assign_op.h:59-72):
        # negatives get out=mismatch_value, weight=1
        neg = neg.reshape(n, -1)
        valid = neg >= 0
        neg_safe = jnp.maximum(neg, 0)
        is_neg = jnp.zeros((n, p), bool).at[batch, neg_safe].set(
            valid, mode="drop")
        out = jnp.where(is_neg[..., None],
                        jnp.asarray(attrs["mismatch_value"], x.dtype),
                        out)
        weight = jnp.where(is_neg[..., None], 1.0, weight)
    return {"Out": out, "OutWeight": weight}


def _nms_single(boxes, scores, iou_thresh, score_thresh, keep_k,
                normalized=True, eta=1.0):
    """Jittable NMS for one class: returns (keep_mask, order,
    top_scores) with a static keep_k budget.  eta < 1 shrinks the
    threshold after each kept box (reference NMSFast adaptive
    threshold, multiclass_nms_op.cc)."""
    k = min(keep_k, scores.shape[0])
    # reference multiclass_nms_op.cc filters by score_threshold BEFORE
    # suppression — sub-threshold boxes must not suppress anyone.
    # -inf sorts them last so they can only "suppress" other
    # sub-threshold boxes, all of which are dropped by the final mask.
    scores = jnp.where(scores > score_thresh, scores, -jnp.inf)
    top_scores, order = jax.lax.top_k(scores, k)
    cand = boxes[order]                               # [k, 4]
    iou = _pairwise_iou(cand, cand, normalized)

    def body(i, carry):
        keep, thresh = carry
        suppressed = jnp.any(
            jnp.where(jnp.arange(k) < i, iou[i] > thresh, False) & keep)
        keep = keep.at[i].set(~suppressed)
        if eta < 1.0:
            thresh = jnp.where(~suppressed & (thresh > 0.5),
                               thresh * eta, thresh)
        return keep, thresh

    keep = jnp.ones(k, bool)
    keep, _ = jax.lax.fori_loop(
        1, k, body, (keep, jnp.asarray(iou_thresh, jnp.float32)))
    keep = keep & (top_scores > score_thresh)
    return keep, order, top_scores


@register_op("multiclass_nms", inputs=("BBoxes", "Scores"),
             outputs=("Out",),
             attrs={"score_threshold": 0.01, "nms_top_k": 64,
                    "nms_threshold": 0.3, "keep_top_k": 32,
                    "background_label": 0, "normalized": True,
                    "nms_eta": 1.0})
def multiclass_nms(ins, attrs):
    """Per-class NMS with fixed output budget (reference
    multiclass_nms_op.cc emits a LoD tensor of variable detections; the
    TPU re-spec emits a static [N, keep_top_k, 6] tensor
    (class, score, x1, y1, x2, y2) padded with class=-1 rows).
    BBoxes: [N, M, 4]; Scores: [N, C, M]."""
    bboxes, scores = ins["BBoxes"], ins["Scores"]
    n, c, m = scores.shape
    keep_k = attrs["keep_top_k"]
    nms_k = min(attrs["nms_top_k"], m)

    def per_image(boxes_i, scores_i):
        all_cls = []
        for cls in range(c):
            if cls == attrs["background_label"]:
                continue
            keep, order, top_s = _nms_single(
                boxes_i, scores_i[cls], attrs["nms_threshold"],
                attrs["score_threshold"], nms_k,
                normalized=attrs["normalized"], eta=attrs["nms_eta"])
            sel_boxes = boxes_i[order]
            cls_col = jnp.full((order.shape[0], 1), float(cls))
            det = jnp.concatenate(
                [cls_col, top_s[:, None], sel_boxes], axis=1)
            det = jnp.where(keep[:, None], det,
                            jnp.full_like(det, -1.0))
            all_cls.append(det)
        dets = jnp.concatenate(all_cls, axis=0)
        # keep_top_k overall by score (invalid rows have score -1)
        k = min(keep_k, dets.shape[0])
        _, idx = jax.lax.top_k(dets[:, 1], k)
        out = dets[idx]
        if k < keep_k:
            out = jnp.pad(out, ((0, keep_k - k), (0, 0)),
                          constant_values=-1.0)
        return out

    # one traced program, vmapped over the batch (the per-class python
    # loop stays: classes need distinct score slices anyway)
    return {"Out": jax.vmap(per_image)(bboxes, scores)}


def _roi_sample(feat, roi, out_h, out_w, spatial_scale, align):
    """feat: [C, H, W]; roi: [4] (x1, y1, x2, y2)."""
    c, h, w = feat.shape
    x1, y1, x2, y2 = [roi[i] * spatial_scale for i in range(4)]
    if align:
        roi_w = jnp.maximum(x2 - x1, 1.0)
        roi_h = jnp.maximum(y2 - y1, 1.0)
        ys = y1 + (jnp.arange(out_h) + 0.5) * roi_h / out_h - 0.5
        xs = x1 + (jnp.arange(out_w) + 0.5) * roi_w / out_w - 0.5
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
        wy = jnp.clip(ys - y0, 0.0, 1.0)
        wx = jnp.clip(xs - x0, 0.0, 1.0)
        f00 = feat[:, y0i][:, :, x0i]
        f01 = feat[:, y0i][:, :, x1i]
        f10 = feat[:, y1i][:, :, x0i]
        f11 = feat[:, y1i][:, :, x1i]
        top = f00 * (1 - wx)[None, None, :] + f01 * wx[None, None, :]
        bot = f10 * (1 - wx)[None, None, :] + f11 * wx[None, None, :]
        return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]
    # roi_pool: MAX over each integer bin (reference roi_pool_op.h
    # hstart..hend x wstart..wend), via bin-membership masks
    x1i = jnp.round(x1).astype(jnp.int32)
    y1i = jnp.round(y1).astype(jnp.int32)
    x2i = jnp.round(x2).astype(jnp.int32)
    y2i = jnp.round(y2).astype(jnp.int32)
    roi_w = jnp.maximum(x2i - x1i + 1, 1)
    roi_h = jnp.maximum(y2i - y1i + 1, 1)
    bin_h = roi_h / out_h
    bin_w = roi_w / out_w
    rows = jnp.arange(h)
    cols = jnp.arange(w)
    # row r belongs to bin i iff floor((r-y1)/bin_h) == i within roi
    def bin_mask(coords, start, extent, bins, bin_sz):
        rel = coords[None, :] - start
        lo = jnp.floor(jnp.arange(bins)[:, None] * bin_sz)
        hi = jnp.ceil((jnp.arange(bins)[:, None] + 1) * bin_sz)
        return (rel >= lo) & (rel < hi) & (rel >= 0) & (rel < extent)

    row_m = bin_mask(rows, y1i, roi_h, out_h, bin_h)   # [oh, H]
    col_m = bin_mask(cols, x1i, roi_w, out_w, bin_w)   # [ow, W]
    mask = row_m[:, None, :, None] & col_m[None, :, None, :]
    neg = jnp.asarray(-3.4e38, feat.dtype)
    expanded = jnp.where(mask[None], feat[:, None, None, :, :], neg)
    out = jnp.max(expanded, axis=(3, 4))               # [C, oh, ow]
    return jnp.where(jnp.any(mask, axis=(2, 3))[None], out, 0.0)


def _register_roi(name, align):
    @register_op(name, inputs=("X", "ROIs", "RoisBatchIdx"),
                 outputs=("Out",), optional=("RoisBatchIdx",),
                 attrs={"pooled_height": REQUIRED,
                        "pooled_width": REQUIRED,
                        "spatial_scale": 1.0, "sampling_ratio": -1})
    def _fn(ins, attrs, align=align):
        """reference roi_align_op.cc / roi_pool_op.cc.  X: [N, C, H, W];
        ROIs: [R, 4]; RoisBatchIdx: [R] image index per roi."""
        x, rois = ins["X"], ins["ROIs"]
        batch_idx = ins.get("RoisBatchIdx")
        if batch_idx is None:
            batch_idx = jnp.zeros(rois.shape[0], jnp.int32)
        feats = x[batch_idx]                          # [R, C, H, W]
        fn = lambda f, r: _roi_sample(
            f, r, attrs["pooled_height"], attrs["pooled_width"],
            attrs["spatial_scale"], align)
        return {"Out": jax.vmap(fn)(feats, rois.astype(jnp.float32))}

    return _fn


_register_roi("roi_align", True)
_register_roi("roi_pool", False)


@register_op("ssd_loss",
             inputs=("Location", "Confidence", "GtBox", "GtLabel",
                     "PriorBox", "PriorBoxVar"),
             outputs=("Loss",), optional=("PriorBoxVar",),
             attrs={"background_label": 0, "overlap_threshold": 0.5,
                    "neg_pos_ratio": 3.0, "loc_loss_weight": 1.0,
                    "conf_loss_weight": 1.0})
def ssd_loss(ins, attrs):
    """SSD multibox loss (reference detection.py ssd_loss +
    mine_hard_examples_op.cc): argmax-IoU matching, center-size target
    encoding, smooth-L1 localization + softmax confidence loss with
    rank-based hard-negative mining — all static shapes.

    Location [N,P,4], Confidence [N,P,C], GtBox [N,G,4] padded,
    GtLabel [N,G] (<0 = padding), PriorBox [P,4].  Returns [N, 1]."""
    loc = ins["Location"].astype(jnp.float32)
    conf = ins["Confidence"].astype(jnp.float32)
    gt_box = ins["GtBox"].astype(jnp.float32)
    gt_label = ins["GtLabel"].reshape(gt_box.shape[0], -1)
    prior = ins["PriorBox"].astype(jnp.float32)
    pvar = ins.get("PriorBoxVar")
    n, p, _ = loc.shape
    g = gt_box.shape[1]
    bg = attrs["background_label"]

    gt_valid = gt_label >= 0                              # [N, G]
    iou = jax.vmap(lambda b: _pairwise_iou(b, prior))(gt_box)  # [N,G,P]
    iou = jnp.where(gt_valid[:, :, None], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)                     # [N, P]
    best_iou = jnp.max(iou, axis=1)
    matched = best_iou > attrs["overlap_threshold"]       # [N, P]

    batch = jnp.arange(n)[:, None]
    # bipartite step (reference bipartite_match_op.cc, run before the
    # thresholded argmax): every valid gt claims its best prior even
    # when that IoU is under the threshold
    best_prior = jnp.argmax(iou, axis=2)                  # [N, G]
    g_ids = jnp.broadcast_to(jnp.arange(g)[None, :], (n, g))
    best_gt = best_gt.at[batch, best_prior].set(
        jnp.where(gt_valid, g_ids, best_gt[batch, best_prior]))
    matched = matched.at[batch, best_prior].set(
        gt_valid | matched[batch, best_prior])
    m_box = gt_box[batch, best_gt]                        # [N, P, 4]
    m_label = jnp.where(matched, gt_label[batch, best_gt], bg)

    # ---- localization target: center-size encoding vs priors ----------
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    gw = m_box[..., 2] - m_box[..., 0]
    gh = m_box[..., 3] - m_box[..., 1]
    gcx = m_box[..., 0] + gw / 2
    gcy = m_box[..., 1] + gh / 2
    eps = 1e-8
    target = jnp.stack(
        [(gcx - pcx) / (pw + eps), (gcy - pcy) / (ph + eps),
         jnp.log(jnp.maximum(gw / (pw + eps), eps)),
         jnp.log(jnp.maximum(gh / (ph + eps), eps))], axis=-1)
    if pvar is not None:
        target = target / pvar[None, :, :]
    diff = jnp.abs(loc - target)
    smooth_l1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
    loc_loss = jnp.sum(smooth_l1, axis=-1) * matched      # [N, P]

    # ---- confidence loss + hard negative mining ------------------------
    logp = jax.nn.log_softmax(conf, axis=-1)
    ce = -jnp.take_along_axis(logp, m_label[..., None],
                              axis=-1)[..., 0]            # [N, P]
    num_pos = jnp.sum(matched, axis=1)                    # [N]
    # rank negatives by loss; keep top neg_pos_ratio * num_pos
    neg_score = jnp.where(matched, -jnp.inf, ce)
    order = jnp.argsort(-neg_score, axis=1)
    rank = jnp.argsort(order, axis=1)                     # rank of each
    keep_neg = (~matched) & (
        rank < (attrs["neg_pos_ratio"] * num_pos)[:, None])
    conf_loss = jnp.sum(ce * (matched | keep_neg), axis=1)

    denom = jnp.maximum(num_pos.astype(jnp.float32), 1.0)
    total = (attrs["loc_loss_weight"] * jnp.sum(loc_loss, axis=1)
             + attrs["conf_loss_weight"] * conf_loss) / denom
    return {"Loss": total[:, None]}


@register_op("yolov3_loss",
             inputs=("X", "GTBox", "GTLabel", "GTScore"),
             outputs=("Loss",), optional=("GTScore",),
             attrs={"anchors": REQUIRED, "anchor_mask": REQUIRED,
                    "class_num": REQUIRED, "ignore_thresh": 0.7,
                    "downsample_ratio": 32, "use_label_smooth": True})
def yolov3_loss(ins, attrs):
    """YOLOv3 training loss (reference yolov3_loss_op.h): per-gt
    best-anchor assignment, BCE on x/y/obj/class, L1 on w/h, objectness
    ignore-mask above ignore_thresh — all static shapes (gt padded with
    w<=0 or h<=0 rows).  Box/class losses are accumulated PER GT
    (gathered at each gt's cell, so two gts sharing a cell both count,
    matching the reference's per-gt loop); the reference's single
    input_size = downsample_ratio * h normalizes both dimensions.

    X: [N, A*(5+C), H, W]; GTBox: [N, B, 4] (cx, cy, w, h relative);
    GTLabel: [N, B]; GTScore: [N, B] (mixup weights)."""
    x = ins["X"].astype(jnp.float32)
    gt_box = ins["GTBox"].astype(jnp.float32)
    gt_label = ins["GTLabel"]
    n, _, h, w = x.shape
    nc = attrs["class_num"]
    mask = list(attrs["anchor_mask"])
    na = len(mask)
    anchors = np.asarray(attrs["anchors"], np.float32).reshape(-1, 2)
    m_anchors = jnp.asarray(anchors[mask])              # [A, 2]
    input_size = attrs["downsample_ratio"] * h          # reference quirk
    b = gt_box.shape[1]
    gt_score = ins.get("GTScore")
    if gt_score is None:
        gt_score = jnp.ones((n, b), jnp.float32)
    gt_score = gt_score.astype(jnp.float32)

    x = x.reshape(n, na, 5 + nc, h, w)
    px, py = x[:, :, 0], x[:, :, 1]                     # [N, A, H, W]
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]                                  # [N, A, C, H, W]

    def bce(logit, target):
        return jax.nn.softplus(logit) - logit * target

    gt_valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0) & \
        (gt_label >= 0)                                 # [N, B]
    # best anchor per gt: wh-IoU against ALL anchors (pixel units)
    gw = gt_box[:, :, 2] * input_size                   # [N, B]
    gh = gt_box[:, :, 3] * input_size
    all_anch = jnp.asarray(anchors)                     # [A_all, 2]
    inter = jnp.minimum(gw[:, :, None], all_anch[None, None, :, 0]) * \
        jnp.minimum(gh[:, :, None], all_anch[None, None, :, 1])
    union = gw[:, :, None] * gh[:, :, None] + \
        all_anch[None, None, :, 0] * all_anch[None, None, :, 1] - inter
    best_anchor = jnp.argmax(inter / (union + 1e-10), axis=2)  # [N, B]
    in_mask = jnp.zeros_like(best_anchor, bool)
    local_idx = jnp.zeros_like(best_anchor)
    for li, mi in enumerate(mask):
        hit = best_anchor == mi
        in_mask = in_mask | hit
        local_idx = jnp.where(hit, li, local_idx)
    responsible = gt_valid & in_mask                    # [N, B]

    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)
    tx = gt_box[:, :, 0] * w - gi
    ty = gt_box[:, :, 1] * h - gj
    tw = jnp.log(jnp.maximum(
        gw / jnp.maximum(m_anchors[local_idx, 0], 1e-10), 1e-10))
    th = jnp.log(jnp.maximum(
        gh / jnp.maximum(m_anchors[local_idx, 1], 1e-10), 1e-10))
    box_scale = 2.0 - gt_box[:, :, 2] * gt_box[:, :, 3]

    # ---- box + class losses: GATHER predictions per gt ----------------
    batch = jnp.arange(n)[:, None].repeat(b, axis=1)    # [N, B]
    sel = (batch, local_idx, gj, gi)
    coord = bce(px[batch, local_idx, gj, gi], tx) + \
        bce(py[batch, local_idx, gj, gi], ty)
    wh = jnp.abs(pw[sel] - tw) + jnp.abs(ph[sel] - th)
    per_gt_box = (coord + wh) * box_scale * gt_score
    loss_box = jnp.where(responsible, per_gt_box, 0.0).sum(axis=1)

    smooth = (min(1.0 / max(nc, 1), 1.0 / 40.0)
              if attrs["use_label_smooth"] else 0.0)
    lbl = jnp.clip(gt_label, 0, nc - 1)
    cls_pred = jnp.moveaxis(pcls, 2, -1)[sel]           # [N, B, C]
    one_hot = (lbl[:, :, None] ==
               jnp.arange(nc)[None, None, :]).astype(jnp.float32)
    cls_t = one_hot * (1.0 - smooth) + (1.0 - one_hot) * smooth
    per_gt_cls = bce(cls_pred, cls_t).sum(axis=2) * gt_score
    loss_cls = jnp.where(responsible, per_gt_cls, 0.0).sum(axis=1)

    # ---- objectness: target 1 at gt cells (score-weighted loss), ------
    # ignore non-gt cells whose decoded box overlaps any gt
    has_gt = jnp.zeros((n, na, h, w), bool).at[sel].set(
        responsible, mode="drop")
    score_g = jnp.ones((n, na, h, w)).at[sel].set(
        jnp.where(responsible, gt_score, 1.0), mode="drop")
    grid_x = (jnp.arange(w)[None, None, None, :] +
              jax.nn.sigmoid(px)) / w
    grid_y = (jnp.arange(h)[None, None, :, None] +
              jax.nn.sigmoid(py)) / h
    pbw = jnp.exp(pw) * m_anchors[None, :, 0, None, None] / input_size
    pbh = jnp.exp(ph) * m_anchors[None, :, 1, None, None] / input_size
    pred_flat = jnp.stack([
        grid_x - pbw / 2, grid_y - pbh / 2,
        grid_x + pbw / 2, grid_y + pbh / 2], axis=-1).reshape(n, -1, 4)
    gt_c = jnp.stack([
        gt_box[:, :, 0] - gt_box[:, :, 2] / 2,
        gt_box[:, :, 1] - gt_box[:, :, 3] / 2,
        gt_box[:, :, 0] + gt_box[:, :, 2] / 2,
        gt_box[:, :, 1] + gt_box[:, :, 3] / 2], axis=-1)  # [N, B, 4]
    ious = jax.vmap(_pairwise_iou)(pred_flat, gt_c)       # [N, P, B]
    ious = jnp.where(gt_valid[:, None, :], ious, 0.0)
    max_iou = jnp.max(ious, axis=2).reshape(n, na, h, w)
    ignore = (max_iou > attrs["ignore_thresh"]) & ~has_gt
    # reference yolov3_loss_op.h:196: positives use hard target 1 with
    # the loss WEIGHTED by the mixup score (obj_mask_ stores the score
    # only as that weight); negatives use target 0, weight 1.
    obj_t = has_gt.astype(jnp.float32)
    loss_obj = jnp.where(ignore, 0.0, bce(pobj, obj_t) * score_g)
    loss_obj = loss_obj.sum(axis=(1, 2, 3))

    return {"Loss": loss_box + loss_obj + loss_cls}


# ---------------------------------------------------------------------------
# RPN / FPN / RCNN family (reference operators/detection/
# generate_proposals_op.cc, rpn_target_assign_op.cc,
# distribute_fpn_proposals_op.cc, collect_fpn_proposals_op.cc,
# generate_proposal_labels_op.cc, generate_mask_labels_op.cc).
# LoD outputs are re-specified as fixed-budget padded tensors (invalid
# rows marked with score/label -1), the same convention as
# multiclass_nms above — XLA needs static shapes.
# ---------------------------------------------------------------------------

def _decode_center_size(anchors, deltas, variances=None):
    """box_coder decode_center_size (reference box_coder_op.cc)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    if variances is not None:
        deltas = deltas * variances
    cx = deltas[:, 0] * aw + acx
    cy = deltas[:, 1] * ah + acy
    clip = float(np.log(1000.0 / 16.0))  # kBBoxClipDefault
    w = jnp.exp(jnp.minimum(deltas[:, 2], clip)) * aw
    h = jnp.exp(jnp.minimum(deltas[:, 3], clip)) * ah
    return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                      cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], axis=1)


@register_op("generate_proposals",
             inputs=("Scores", "BboxDeltas", "ImInfo", "Anchors",
                     "Variances"),
             outputs=("RpnRois", "RpnRoiProbs"),
             optional=("Variances",),
             attrs={"pre_nms_topN": 6000, "post_nms_topN": 1000,
                    "nms_thresh": 0.5, "min_size": 0.1, "eta": 1.0},
             differentiable=False)
def generate_proposals(ins, attrs):
    """generate_proposals_op.cc: decode RPN deltas onto anchors, clip to
    the image, drop boxes smaller than min_size, take pre_nms_topN by
    score, NMS, emit post_nms_topN (padded, prob -1 on padding).
    Scores [N,A,H,W]; BboxDeltas [N,4A,H,W]; Anchors [H,W,A,4] (or
    [A*H*W,4]); ImInfo [N,3] (h, w, scale)."""
    scores, deltas, im_info = ins["Scores"], ins["BboxDeltas"], \
        ins["ImInfo"]
    anchors = ins["Anchors"].reshape(-1, 4)
    variances = ins.get("Variances")
    if variances is not None:
        variances = variances.reshape(-1, 4)
    n, a, h, w = scores.shape
    k = a * h * w
    post = int(attrs["post_nms_topN"])
    pre = min(int(attrs["pre_nms_topN"]), k)

    # [N,A,H,W] -> [N, H*W*A] matching anchors laid out [H,W,A,4]
    sc = jnp.transpose(scores, (0, 2, 3, 1)).reshape(n, k)
    dl = jnp.transpose(deltas.reshape(n, a, 4, h, w),
                       (0, 3, 4, 1, 2)).reshape(n, k, 4)

    def per_image(sc_i, dl_i, info_i):
        boxes = _decode_center_size(anchors, dl_i, variances)
        ih, iw = info_i[0], info_i[1]
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0.0, iw - 1.0),
            jnp.clip(boxes[:, 1], 0.0, ih - 1.0),
            jnp.clip(boxes[:, 2], 0.0, iw - 1.0),
            jnp.clip(boxes[:, 3], 0.0, ih - 1.0)], axis=1)
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        ms = attrs["min_size"] * info_i[2]
        valid = (ws >= ms) & (hs >= ms)
        s = jnp.where(valid, sc_i, -jnp.inf)
        top_s, order = jax.lax.top_k(s, pre)
        cand = boxes[order]
        keep, korder, kscores = _nms_single(
            cand, top_s, attrs["nms_thresh"], -jnp.inf,
            min(pre, post if post > 0 else pre), normalized=False,
            eta=attrs["eta"])
        out_boxes = cand[korder]
        out_scores = jnp.where(keep, kscores, -1.0)
        out_boxes = jnp.where(keep[:, None], out_boxes, 0.0)
        m = out_boxes.shape[0]
        if m < post:
            out_boxes = jnp.pad(out_boxes, ((0, post - m), (0, 0)))
            out_scores = jnp.pad(out_scores, (0, post - m),
                                 constant_values=-1.0)
        return out_boxes[:post], out_scores[:post]

    rois, probs = jax.vmap(per_image)(sc, dl, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": probs[..., None]}


@register_op("rpn_target_assign",
             inputs=("Anchor", "GtBoxes", "IsCrowd", "ImInfo"),
             outputs=("LocationIndex", "ScoreIndex", "TargetBBox",
                      "TargetLabel", "BBoxInsideWeight"),
             optional=("IsCrowd", "ImInfo"),
             attrs={"rpn_batch_size_per_im": 256,
                    "rpn_straddle_thresh": 0.0,
                    "rpn_fg_fraction": 0.5,
                    "rpn_positive_overlap": 0.7,
                    "rpn_negative_overlap": 0.3,
                    "use_random": False},
             differentiable=False)
def rpn_target_assign(ins, attrs):
    """rpn_target_assign_op.cc re-spec: per image, anchors with IoU >=
    positive_overlap vs any gt (or argmax per gt) are positive, IoU <
    negative_overlap negative; deterministic sampling keeps the
    highest-IoU positives and lowest-IoU negatives up to the batch
    budget (use_random=False path).  Anchor [A,4]; GtBoxes [N,G,4]
    (zero rows = padding).  Index outputs are [N, budget] padded -1
    (LoD flattening re-spec); TargetBBox are encoded regression targets
    for the sampled positives."""
    anchors = ins["Anchor"].reshape(-1, 4)
    gt = ins["GtBoxes"]
    n, g, _ = gt.shape
    a = anchors.shape[0]
    budget = int(attrs["rpn_batch_size_per_im"])
    n_fg = int(budget * attrs["rpn_fg_fraction"])
    n_bg = budget - n_fg

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah

    def per_image(gt_i):
        gt_valid = (gt_i[:, 2] > gt_i[:, 0]) & (gt_i[:, 3] > gt_i[:, 1])
        iou = _pairwise_iou(anchors, gt_i, normalized=False)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)
        best_iou = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        # anchors that are the best for some gt are positive too
        per_gt_best = jnp.max(iou, axis=0)
        is_gt_best = jnp.any(
            (iou >= per_gt_best[None, :] - 1e-6) & (iou > 0)
            & gt_valid[None, :], axis=1)
        pos = (best_iou >= attrs["rpn_positive_overlap"]) | is_gt_best
        neg = (best_iou < attrs["rpn_negative_overlap"]) & ~pos
        # deterministic sample: top IoU positives, lowest IoU negatives
        pos_score = jnp.where(pos, best_iou, -jnp.inf)
        _, pos_idx = jax.lax.top_k(pos_score, min(n_fg, a))
        pos_ok = pos[pos_idx]
        neg_score = jnp.where(neg, -best_iou, -jnp.inf)
        _, neg_idx = jax.lax.top_k(neg_score, min(n_bg, a))
        neg_ok = neg[neg_idx]
        loc_idx = jnp.where(pos_ok, pos_idx, -1)
        score_idx = jnp.concatenate([loc_idx,
                                     jnp.where(neg_ok, neg_idx, -1)])
        # regression targets for sampled positives
        tgt = gt_i[best_gt[pos_idx]]
        tw = tgt[:, 2] - tgt[:, 0] + 1.0
        th = tgt[:, 3] - tgt[:, 1] + 1.0
        tcx = tgt[:, 0] + 0.5 * tw
        tcy = tgt[:, 1] + 0.5 * th
        paw, pah = aw[pos_idx], ah[pos_idx]
        dx = (tcx - acx[pos_idx]) / paw
        dy = (tcy - acy[pos_idx]) / pah
        dw = jnp.log(tw / paw)
        dh = jnp.log(th / pah)
        tbox = jnp.stack([dx, dy, dw, dh], axis=1)
        tbox = jnp.where(pos_ok[:, None], tbox, 0.0)
        label = jnp.concatenate([
            jnp.where(pos_ok, 1, -1),
            jnp.where(neg_ok, 0, -1)]).astype(jnp.int32)
        inw = jnp.where(pos_ok[:, None], 1.0, 0.0)
        inw = jnp.broadcast_to(inw, tbox.shape)
        return loc_idx, score_idx, tbox, label, inw

    loc, sidx, tbox, lbl, inw = jax.vmap(per_image)(gt)
    return {"LocationIndex": loc, "ScoreIndex": sidx,
            "TargetBBox": tbox, "TargetLabel": lbl,
            "BBoxInsideWeight": inw}


@register_op("distribute_fpn_proposals",
             inputs=("FpnRois",),
             outputs=("MultiFpnRois", "RestoreIndex"),
             duplicable=("MultiFpnRois",),
             attrs={"min_level": 2, "max_level": 5, "refer_level": 4,
                    "refer_scale": 224},
             differentiable=False)
def distribute_fpn_proposals(ins, attrs):
    """distribute_fpn_proposals_op.cc: route each roi to the pyramid
    level log2(sqrt(area)/refer_scale)+refer_level.  FpnRois [R,4]
    (padding rows have zero area and land at min_level with a dead
    mark).  Each per-level output is [R,4] with non-member rows zeroed
    and compacted to the front; RestoreIndex[r] gives the row's position
    in the level-major concatenation."""
    rois = ins["FpnRois"].reshape(-1, 4)
    lo, hi = int(attrs["min_level"]), int(attrs["max_level"])
    ws = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    hs = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = jnp.sqrt(ws * hs)
    lvl = jnp.floor(jnp.log2(scale / attrs["refer_scale"] + 1e-6)
                    ) + attrs["refer_level"]
    lvl = jnp.clip(lvl, lo, hi).astype(jnp.int32)
    outs = []
    r = rois.shape[0]
    # rank of each roi within its level (stable original order)
    level_key = lvl * r + jnp.arange(r)
    rank_global = jnp.argsort(jnp.argsort(level_key))
    level_start_rank = jnp.take(
        jnp.concatenate([jnp.zeros((1,), jnp.int32),
                         jnp.cumsum(jnp.bincount(lvl - lo,
                                                 length=hi - lo + 1))
                         .astype(jnp.int32)[:-1]]), lvl - lo)
    rank_in_level = rank_global.astype(jnp.int32) - level_start_rank
    # RestoreIndex addresses the CONCATENATION OF THE (padded) OUTPUTS:
    # each level block is R rows, members compacted to its front
    restore = ((lvl - lo) * r + rank_in_level).astype(jnp.int32)
    for level in range(lo, hi + 1):
        member = lvl == level
        # compact members to the front (stable)
        key = jnp.where(member, jnp.arange(r), r + jnp.arange(r))
        idx = jnp.argsort(key)
        sel = rois[idx] * member[idx][:, None]
        outs.append(sel)
    return {"MultiFpnRois": outs, "RestoreIndex": restore[:, None]}


@register_op("collect_fpn_proposals",
             inputs=("MultiLevelRois", "MultiLevelScores"),
             outputs=("FpnRois",),
             duplicable=("MultiLevelRois", "MultiLevelScores"),
             attrs={"post_nms_topN": 1000},
             differentiable=False)
def collect_fpn_proposals(ins, attrs):
    """collect_fpn_proposals_op.cc: concat per-level rois, keep the
    overall top post_nms_topN by score.  Rois_l [R_l,4], Scores_l
    [R_l] (or [R_l,1]); padding has score -1."""
    rois = jnp.concatenate([r.reshape(-1, 4)
                            for r in ins["MultiLevelRois"]], axis=0)
    scores = jnp.concatenate([s.reshape(-1)
                              for s in ins["MultiLevelScores"]], axis=0)
    k = min(int(attrs["post_nms_topN"]), scores.shape[0])
    top_s, idx = jax.lax.top_k(scores, k)
    out = rois[idx] * (top_s >= 0)[:, None]
    return {"FpnRois": out}


@register_op("generate_proposal_labels",
             inputs=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                     "ImInfo"),
             outputs=("Rois", "LabelsInt32", "BboxTargets",
                      "BboxInsideWeights", "BboxOutsideWeights"),
             optional=("IsCrowd", "ImInfo"),
             attrs={"batch_size_per_im": 256, "fg_fraction": 0.25,
                    "fg_thresh": 0.5, "bg_thresh_hi": 0.5,
                    "bg_thresh_lo": 0.0, "class_nums": 81,
                    "use_random": False,
                    "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2]},
             differentiable=False)
def generate_proposal_labels(ins, attrs):
    """generate_proposal_labels_op.cc re-spec: per image, match rois to
    gt by IoU; fg rois (IoU>=fg_thresh) get the gt class and encoded
    regression targets placed in their class' 4-column slot; bg rois
    (bg_thresh_lo<=IoU<bg_thresh_hi) get label 0.  Deterministic
    top-IoU sampling to batch_size_per_im (use_random=False path).
    RpnRois [N,R,4]; GtClasses [N,G]; GtBoxes [N,G,4]."""
    rois, gtc, gtb = ins["RpnRois"], ins["GtClasses"], ins["GtBoxes"]
    n, r, _ = rois.shape
    budget = min(int(attrs["batch_size_per_im"]), r)
    n_fg = int(budget * attrs["fg_fraction"])
    cnum = int(attrs["class_nums"])
    wts = jnp.asarray(attrs["bbox_reg_weights"])

    def per_image(rois_i, gtc_i, gtb_i):
        gt_valid = (gtb_i[:, 2] > gtb_i[:, 0]) & \
                   (gtb_i[:, 3] > gtb_i[:, 1])
        iou = _pairwise_iou(rois_i, gtb_i)
        iou = jnp.where(gt_valid[None, :], iou, 0.0)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        fg = best >= attrs["fg_thresh"]
        bg = (best < attrs["bg_thresh_hi"]) & \
             (best >= attrs["bg_thresh_lo"]) & ~fg
        fg_score = jnp.where(fg, best, -jnp.inf)
        nf = min(n_fg, r)
        _, fg_idx = jax.lax.top_k(fg_score, nf)
        fg_ok = fg[fg_idx]
        nbg = max(budget - nf, 0)
        # bg refill (generate_proposal_labels_op.cc: background takes
        # whatever the actual fg count leaves of batch_size_per_im):
        # rank ALL bg candidates; unused fg slots pull extra bg rois
        bg_score = jnp.where(bg, best, -jnp.inf)
        _, bg_all = jax.lax.top_k(bg_score, min(budget, r))
        bg_all_ok = bg[bg_all]
        bg_idx = bg_all[:nbg]
        bg_ok = bg_all_ok[:nbg]
        # failed fg slot i takes the (nbg + rank)-th best bg
        fail_rank = jnp.cumsum(~fg_ok) - 1
        extra_pos = jnp.clip(nbg + fail_rank, 0, bg_all.shape[0] - 1)
        extra_idx = bg_all[extra_pos]
        extra_ok = bg_all_ok[extra_pos] & (nbg + fail_rank
                                           < bg_all.shape[0])
        fg_slot_idx = jnp.where(fg_ok, fg_idx, extra_idx)
        fg_slot_ok = fg_ok | (~fg_ok & extra_ok)
        fg_slot_is_fg = fg_ok
        sel = jnp.concatenate([fg_slot_idx, bg_idx])
        ok = jnp.concatenate([fg_slot_ok, bg_ok])
        out_rois = rois_i[sel] * ok[:, None]
        labels = jnp.where(
            jnp.concatenate([fg_slot_is_fg, jnp.zeros_like(bg_ok)]),
            gtc_i[best_gt[sel]].astype(jnp.int32), 0)
        labels = jnp.where(ok, labels, -1).astype(jnp.int32)
        # encoded targets scattered into the class slot
        tgt_box = gtb_i[best_gt[sel]]
        rw = out_rois[:, 2] - out_rois[:, 0] + 1.0
        rh = out_rois[:, 3] - out_rois[:, 1] + 1.0
        rcx = out_rois[:, 0] + 0.5 * rw
        rcy = out_rois[:, 1] + 0.5 * rh
        tw = tgt_box[:, 2] - tgt_box[:, 0] + 1.0
        th = tgt_box[:, 3] - tgt_box[:, 1] + 1.0
        tcx = tgt_box[:, 0] + 0.5 * tw
        tcy = tgt_box[:, 1] + 0.5 * th
        enc = jnp.stack([(tcx - rcx) / rw / wts[0],
                         (tcy - rcy) / rh / wts[1],
                         jnp.log(jnp.maximum(tw / rw, 1e-6)) / wts[2],
                         jnp.log(jnp.maximum(th / rh, 1e-6)) / wts[3]],
                        axis=1)
        is_fg = labels > 0
        targets = jnp.zeros((sel.shape[0], 4 * cnum))
        inside = jnp.zeros((sel.shape[0], 4 * cnum))
        col = jnp.clip(labels, 0, cnum - 1) * 4
        rows = jnp.arange(sel.shape[0])
        for j in range(4):
            targets = targets.at[rows, col + j].set(
                jnp.where(is_fg, enc[:, j], 0.0))
            # fg rois weight ALL 4 slots of their class
            # (generate_proposal_labels_op.cc:352-355), even
            # exactly-zero targets
            inside = inside.at[rows, col + j].set(
                jnp.where(is_fg, 1.0, 0.0))
        outside = inside
        return out_rois, labels, targets, inside, outside

    o = jax.vmap(per_image)(rois, gtc, gtb)
    return {"Rois": o[0], "LabelsInt32": o[1], "BboxTargets": o[2],
            "BboxInsideWeights": o[3], "BboxOutsideWeights": o[4]}


@register_op("generate_mask_labels",
             inputs=("ImInfo", "GtClasses", "IsCrowd", "GtSegms",
                     "Rois", "LabelsInt32"),
             outputs=("MaskRois", "RoiHasMaskInt32", "MaskInt32"),
             optional=("ImInfo", "IsCrowd"),
             attrs={"num_classes": 81, "resolution": 14},
             differentiable=False)
def generate_mask_labels(ins, attrs):
    """generate_mask_labels_op.cc re-spec: the reference rasterizes COCO
    polygons on host; here GtSegms arrives as ALREADY-RASTERIZED per-gt
    binary masks [N, G, S, S] in roi-normalized space is impractical, so
    the re-spec takes full-image masks [N, G, Hm, Wm] and crops+resizes
    each fg roi's matched gt mask to resolution x resolution (class-
    expanded, -1 on non-fg rois like the reference)."""
    gtsegms, rois, labels = ins["GtSegms"], ins["Rois"], \
        ins["LabelsInt32"]
    n, g, hm, wm = gtsegms.shape
    res = int(attrs["resolution"])

    def per_image(segs_i, rois_i, labels_i):
        is_fg = labels_i > 0
        # match each roi to the gt mask with max overlap of the mask's
        # bounding box; approximate by sampling the mask inside the roi
        ys = jnp.linspace(0.0, 1.0, res)
        xs = jnp.linspace(0.0, 1.0, res)

        def crop(roi, seg):
            y0, x0 = roi[1], roi[0]
            y1, x1 = roi[3], roi[2]
            gy = jnp.clip((y0 + ys * jnp.maximum(y1 - y0, 1.0))
                          .astype(jnp.int32), 0, hm - 1)
            gx = jnp.clip((x0 + xs * jnp.maximum(x1 - x0, 1.0))
                          .astype(jnp.int32), 0, wm - 1)
            return seg[gy][:, gx]

        def best_mask(roi):
            crops = jax.vmap(lambda s: crop(roi, s))(segs_i)  # [G,res,res]
            areas = crops.sum(axis=(1, 2))
            return crops[jnp.argmax(areas)]

        masks = jax.vmap(best_mask)(rois_i)                   # [R,res,res]
        flat = masks.reshape(masks.shape[0], -1) > 0.5
        out = jnp.where(is_fg[:, None], flat.astype(jnp.int32), -1)
        has = is_fg.astype(jnp.int32)
        return rois_i, has, out

    o = jax.vmap(per_image)(gtsegms, rois, labels)
    return {"MaskRois": o[0], "RoiHasMaskInt32": o[1], "MaskInt32": o[2]}


@register_op("bipartite_match", inputs=("DistMat",),
             outputs=("ColToRowMatchIndices", "ColToRowMatchDist"),
             attrs={"match_type": "bipartite",
                    "dist_threshold": 0.5},
             differentiable=False)
def bipartite_match(ins, attrs):
    """bipartite_match_op.cc: greedy global bipartite matching on a
    [B, R, C] distance (similarity) matrix: repeatedly take the global
    argmax, bind that (row, col), exclude both, until rows exhaust.
    match_type='per_prediction' additionally matches unmatched cols to
    their best row when dist > dist_threshold."""
    dist = ins["DistMat"]
    if dist.ndim == 2:
        dist = dist[None]
    b, r, c = dist.shape
    steps = min(r, c)

    def per_batch(d):
        def body(i, carry):
            match, mdist, dd = carry
            flat = jnp.argmax(dd)
            row, col = flat // c, flat % c
            ok = dd[row, col] > 0
            match = jnp.where(ok, match.at[col].set(row.astype(jnp.int32)),
                              match)
            mdist = jnp.where(ok, mdist.at[col].set(dd[row, col]), mdist)
            dd = jnp.where(ok, dd.at[row, :].set(-1.0), dd)
            dd = jnp.where(ok, dd.at[:, col].set(-1.0), dd)
            return match, mdist, dd

        match0 = jnp.full((c,), -1, jnp.int32)
        mdist0 = jnp.zeros((c,))
        match, mdist, _ = jax.lax.fori_loop(0, steps, body,
                                            (match0, mdist0, d))
        if attrs["match_type"] == "per_prediction":
            best_row = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_d = jnp.max(d, axis=0)
            extra = (match < 0) & (best_d > attrs["dist_threshold"])
            match = jnp.where(extra, best_row, match)
            mdist = jnp.where(extra, best_d, mdist)
        return match, mdist

    m, md = jax.vmap(per_batch)(dist)
    return {"ColToRowMatchIndices": m, "ColToRowMatchDist": md}


@register_op("mine_hard_examples",
             inputs=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"),
             outputs=("NegIndices", "UpdatedMatchIndices"),
             optional=("LocLoss",),
             attrs={"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
                    "mining_type": "max_negative", "sample_size": 0},
             differentiable=False)
def mine_hard_examples(ins, attrs):
    """mine_hard_examples_op.cc (max_negative mining): per row, negatives
    (match==-1, dist < neg_dist_threshold) ranked by cls loss; keep
    neg_pos_ratio * num_pos.  NegIndices re-spec: [B, P] int32 mask (1 =
    selected negative) instead of the reference's LoD index list."""
    cls_loss, match, mdist = ins["ClsLoss"], ins["MatchIndices"], \
        ins["MatchDist"]
    loss = cls_loss + (ins["LocLoss"] if ins.get("LocLoss") is not None
                       else 0.0)

    def per_row(l, m, d):
        is_neg = (m < 0) & (d < attrs["neg_dist_threshold"])
        npos = jnp.sum(m >= 0)
        budget = (npos * attrs["neg_pos_ratio"]).astype(jnp.int32)
        if int(attrs["sample_size"]):
            budget = jnp.minimum(budget, int(attrs["sample_size"]))
        neg_l = jnp.where(is_neg, l, -jnp.inf)
        order = jnp.argsort(-neg_l)
        rank = jnp.argsort(order)
        sel = is_neg & (rank < budget)
        return sel.astype(jnp.int32), m

    sel, m = jax.vmap(per_row)(loss, match, mdist)
    return {"NegIndices": sel, "UpdatedMatchIndices": m}


@register_op("detection_map",
             inputs=("DetectRes", "Label", "HasState", "PosCount",
                     "TruePos", "FalsePos"),
             outputs=("MAP", "AccumPosCount", "AccumTruePos",
                      "AccumFalsePos"),
             optional=("HasState", "PosCount", "TruePos", "FalsePos"),
             attrs={"overlap_threshold": 0.5, "evaluate_difficult": True,
                    "ap_type": "integral", "class_num": REQUIRED},
             host_only=True, differentiable=False)
def detection_map(ins, attrs):
    """detection_map_op.cc (host metric op): mean average precision over
    padded detections [N, D, 6] (label, score, x1,y1,x2,y2; label -1 =
    padding) vs ground truth [N, G, 6] (label, difficult, box).

    Streaming accumulation (the reference's PosCount/TruePos/FalsePos LoD
    states, detection_map_op.h GetInputPos/GetOutputPos) is re-specified on
    flat row tables — host ops run outside jit so the growing shapes are
    fine: PosCount [C, 1] int32; TruePos/FalsePos [M, 3] float32 rows of
    (class, score, flag).  When HasState is nonzero the batch statistics
    are merged into the input states, and MAP is computed over the merged
    tables (the evaluator.py DetectionMAP accumulative path)."""
    det = np.asarray(ins["DetectRes"])
    lab = np.asarray(ins["Label"])
    if det.ndim == 2:
        det, lab = det[None], lab[None]
    if lab.shape[-1] == 5:
        # no difficult column (reference detection_map_op.cc label width
        # check): insert an all-easy column so rows are (label, difficult,
        # x1, y1, x2, y2) below
        lab = np.concatenate(
            [lab[..., :1], np.zeros_like(lab[..., :1]), lab[..., 1:]],
            axis=-1)
    thr = attrs["overlap_threshold"]
    cnum = int(attrs["class_num"])

    # ---- per-class batch statistics --------------------------------------
    pos_count = np.zeros((cnum, 1), np.int32)
    tp_rows, fp_rows = [], []
    evaluate_difficult = bool(attrs["evaluate_difficult"])
    for cls in range(cnum):
        for i in range(det.shape[0]):
            gts = lab[i][(lab[i][:, 0] == cls)]
            # npos counts only non-difficult gts when not evaluating
            # difficult, but matching still sees ALL gts: a detection whose
            # best match is a difficult box is neither TP nor FP (reference
            # detection_map_op.h CalcTrueAndFalsePositive)
            if evaluate_difficult or not gts.size:
                pos_count[cls, 0] += len(gts)
            else:
                pos_count[cls, 0] += int((gts[:, 1] == 0).sum())
            dets = det[i][(det[i][:, 0] == cls)]
            dets = dets[np.argsort(-dets[:, 1])]
            used = np.zeros(len(gts), bool)
            for d in dets:
                best, bi = 0.0, -1
                for j, gt in enumerate(gts):
                    bx = gt[2:6]
                    ix1 = max(d[2], bx[0]); iy1 = max(d[3], bx[1])
                    ix2 = min(d[4], bx[2]); iy2 = min(d[5], bx[3])
                    iw = max(ix2 - ix1, 0); ih = max(iy2 - iy1, 0)
                    inter = iw * ih
                    ua = ((d[4] - d[2]) * (d[5] - d[3])
                          + (bx[2] - bx[0]) * (bx[3] - bx[1]) - inter)
                    ov = inter / ua if ua > 0 else 0.0
                    if ov > best:
                        best, bi = ov, j
                # strict > like the reference (IoU == threshold is no match)
                if best > thr and bi >= 0:
                    if not evaluate_difficult and gts[bi, 1] != 0:
                        continue  # matched a difficult gt: ignore detection
                    if not used[bi]:
                        used[bi] = True
                        tp_rows.append((cls, d[1], 1.0))
                    else:
                        fp_rows.append((cls, d[1], 1.0))
                else:
                    fp_rows.append((cls, d[1], 1.0))

    tp_tab = np.asarray(tp_rows, np.float32).reshape(-1, 3)
    fp_tab = np.asarray(fp_rows, np.float32).reshape(-1, 3)

    # ---- merge input state (reference GetInputPos) -----------------------
    has_state = ins.get("HasState")
    if has_state is not None and int(np.asarray(has_state).ravel()[0]) != 0:
        in_pos = ins.get("PosCount")
        if in_pos is not None and np.asarray(in_pos).size:
            pos_count += np.asarray(in_pos, np.int32).reshape(cnum, 1)
        for slot, tab in (("TruePos", "tp"), ("FalsePos", "fp")):
            prev = ins.get(slot)
            if prev is None:
                continue
            prev = np.asarray(prev, np.float32).reshape(-1, 3)
            if tab == "tp":
                tp_tab = np.concatenate([prev, tp_tab], 0)
            else:
                fp_tab = np.concatenate([prev, fp_tab], 0)

    # ---- AP over the (merged) tables -------------------------------------
    aps = []
    for cls in range(cnum):
        npos = int(pos_count[cls, 0])
        tp_s = tp_tab[tp_tab[:, 0] == cls, 1]
        fp_s = fp_tab[fp_tab[:, 0] == cls, 1]
        if npos == 0:
            continue
        if tp_s.size + fp_s.size == 0:
            # class has gt but no detections at all: the reference CalcMAP
            # skips it from the mean (no ++count), not AP=0
            continue
        scores = np.concatenate([tp_s, fp_s])
        tp = np.concatenate([np.ones_like(tp_s), np.zeros_like(fp_s)])
        order = np.argsort(-scores)
        tp = tp[order]
        fp = 1.0 - tp
        ctp, cfp = np.cumsum(tp), np.cumsum(fp)
        rec = ctp / npos
        prec = ctp / np.maximum(ctp + cfp, 1e-10)
        if attrs["ap_type"] == "11point":
            ap = float(np.mean([prec[rec >= t].max() if
                                (rec >= t).any() else 0.0
                                for t in np.linspace(0, 1, 11)]))
        else:
            ap = float(np.sum((rec[1:] - rec[:-1]) * prec[1:])
                       + rec[0] * prec[0] if len(rec) else 0.0)
        aps.append(ap)
    mmap = float(np.mean(aps)) if aps else 0.0
    return {"MAP": jnp.asarray([mmap], jnp.float32),
            "AccumPosCount": pos_count,
            "AccumTruePos": tp_tab, "AccumFalsePos": fp_tab}


@register_op("box_decoder_and_assign",
             inputs=("PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"),
             outputs=("DecodeBox", "OutputAssignBox"),
             optional=("PriorBoxVar",),
             attrs={"box_clip": 4.135},
             differentiable=False)
def box_decoder_and_assign(ins, attrs):
    """box_decoder_and_assign_op.cc (Cascade R-CNN): decode per-class
    deltas [N, 4*C] onto prior boxes, then assign each box its
    best-scoring class' decode.  BoxScore [N, C]."""
    prior = ins["PriorBox"]
    deltas = ins["TargetBox"]
    score = ins["BoxScore"]
    var = ins.get("PriorBoxVar")
    n, c4 = deltas.shape
    c = c4 // 4
    # one decode implementation for the whole file: priors repeated per
    # class, flattened through _decode_center_size
    d = deltas.reshape(n, c, 4)
    if var is not None:
        d = d * (var.reshape(1, 1, 4) if var.ndim == 1
                 else var.reshape(n, 1, 4))
    prior_rep = jnp.repeat(prior[:, None, :], c, axis=1).reshape(-1, 4)
    dec = _decode_center_size(prior_rep, d.reshape(-1, 4)) \
        .reshape(n, c, 4)
    best = jnp.argmax(score, axis=1)
    assign = jnp.take_along_axis(
        dec, best[:, None, None].repeat(4, axis=2), axis=1)[:, 0]
    return {"DecodeBox": dec.reshape(n, c4),
            "OutputAssignBox": assign}


@register_op("retinanet_target_assign",
             inputs=("Anchor", "GtBoxes", "GtLabels", "IsCrowd",
                     "ImInfo"),
             outputs=("LocationIndex", "ScoreIndex", "TargetBBox",
                      "TargetLabel", "BBoxInsideWeight", "ForegroundNumber"),
             optional=("IsCrowd", "ImInfo"),
             attrs={"positive_overlap": 0.5, "negative_overlap": 0.4},
             differentiable=False)
def retinanet_target_assign(ins, attrs):
    """retinanet_target_assign_op.cc: like rpn_target_assign but with
    ALL anchors labeled (focal loss needs no sampling) and class labels
    from the matched gt.  Fixed-shape re-spec: indices are [N, A]
    masks/labels instead of LoD index lists."""
    anchors = ins["Anchor"].reshape(-1, 4)
    gtb, gtl = ins["GtBoxes"], ins["GtLabels"]
    a = anchors.shape[0]

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah

    def per_image(gtb_i, gtl_i, crowd_i):
        gt_valid = (gtb_i[:, 2] > gtb_i[:, 0]) & \
                   (gtb_i[:, 3] > gtb_i[:, 1])
        is_crowd = crowd_i.reshape(-1) != 0
        # crowd gts never match positives (reference excludes them)
        matchable = gt_valid & ~is_crowd
        iou_all = _pairwise_iou(anchors, gtb_i, normalized=False)
        iou = jnp.where(matchable[None, :], iou_all, 0.0)
        best = jnp.max(iou, axis=1)
        best_gt = jnp.argmax(iou, axis=1)
        per_gt_best = jnp.max(iou, axis=0)
        is_gt_best = jnp.any(
            (iou >= per_gt_best[None, :] - 1e-6) & (iou > 0)
            & matchable[None, :], axis=1)
        pos = (best >= attrs["positive_overlap"]) | is_gt_best
        neg = (best < attrs["negative_overlap"]) & ~pos
        # anchors overlapping a crowd region are IGNORED, not negative
        crowd_iou = jnp.where((gt_valid & is_crowd)[None, :], iou_all,
                              0.0)
        in_crowd = jnp.max(crowd_iou, axis=1) >= \
            attrs["positive_overlap"]
        neg = neg & ~in_crowd
        label = jnp.where(pos, gtl_i[best_gt].reshape(-1),
                          jnp.where(neg, 0, -1)).astype(jnp.int32)
        tgt = gtb_i[best_gt]
        tw = tgt[:, 2] - tgt[:, 0] + 1.0
        th = tgt[:, 3] - tgt[:, 1] + 1.0
        tcx = tgt[:, 0] + 0.5 * tw
        tcy = tgt[:, 1] + 0.5 * th
        tbox = jnp.stack([(tcx - acx) / aw, (tcy - acy) / ah,
                          jnp.log(tw / aw), jnp.log(th / ah)], axis=1)
        tbox = jnp.where(pos[:, None], tbox, 0.0)
        inw = jnp.broadcast_to(
            jnp.where(pos[:, None], 1.0, 0.0), tbox.shape)
        fg = jnp.sum(pos).astype(jnp.int32).reshape(1)
        loc_idx = jnp.where(pos, jnp.arange(a), -1)
        score_idx = jnp.where(pos | neg, jnp.arange(a), -1)
        return loc_idx, score_idx, tbox, label, inw, fg

    crowd = ins.get("IsCrowd")
    if crowd is None:
        crowd = jnp.zeros(gtb.shape[:2], jnp.int32)
    o = jax.vmap(per_image)(gtb, gtl, crowd)
    return {"LocationIndex": o[0], "ScoreIndex": o[1],
            "TargetBBox": o[2], "TargetLabel": o[3],
            "BBoxInsideWeight": o[4], "ForegroundNumber": o[5]}


@register_op("retinanet_detection_output",
             inputs=("BBoxes", "Scores", "Anchors", "ImInfo"),
             outputs=("Out",),
             duplicable=("BBoxes", "Scores", "Anchors"),
             attrs={"score_threshold": 0.05, "nms_top_k": 1000,
                    "nms_threshold": 0.3, "keep_top_k": 100,
                    "nms_eta": 1.0},
             differentiable=False)
def retinanet_detection_output(ins, attrs):
    """retinanet_detection_output_op.cc: per FPN level decode deltas on
    anchors, take top nms_top_k by score, then class-wise NMS over the
    union.  BBoxes_l [N, A_l, 4] deltas; Scores_l [N, A_l, C];
    Anchors_l [A_l, 4].  Out [N, keep_top_k, 6] padded class=-1."""
    bboxes, scores, anchors = (ins["BBoxes"], ins["Scores"],
                               ins["Anchors"])
    im_info = ins["ImInfo"]
    n = bboxes[0].shape[0]
    c = scores[0].shape[-1]
    keep_k = int(attrs["keep_top_k"])

    dec_all, sc_all = [], []
    for dl, sc, an in zip(bboxes, scores, anchors):
        an = an.reshape(-1, 4)

        def dec_one(d_i):
            return _decode_center_size(an, d_i)

        dec_all.append(jax.vmap(dec_one)(dl))
        sc_all.append(sc)
    boxes = jnp.concatenate(dec_all, axis=1)               # [N, A, 4]
    scs = jnp.concatenate(sc_all, axis=1)                  # [N, A, C]

    def per_image(boxes_i, scores_i, info_i):
        ih, iw = info_i[0], info_i[1]
        boxes_i = jnp.stack([
            jnp.clip(boxes_i[:, 0], 0.0, iw - 1.0),
            jnp.clip(boxes_i[:, 1], 0.0, ih - 1.0),
            jnp.clip(boxes_i[:, 2], 0.0, iw - 1.0),
            jnp.clip(boxes_i[:, 3], 0.0, ih - 1.0)], axis=1)
        all_cls = []
        nms_k = min(int(attrs["nms_top_k"]), boxes_i.shape[0])
        for cls in range(c):
            keep, order, top_s = _nms_single(
                boxes_i, scores_i[:, cls], attrs["nms_threshold"],
                attrs["score_threshold"], nms_k, normalized=False,
                eta=attrs["nms_eta"])
            det = jnp.concatenate(
                [jnp.full((order.shape[0], 1), float(cls)),
                 top_s[:, None], boxes_i[order]], axis=1)
            det = jnp.where(keep[:, None], det,
                            jnp.full_like(det, -1.0))
            all_cls.append(det)
        dets = jnp.concatenate(all_cls, axis=0)
        k = min(keep_k, dets.shape[0])
        _, idx = jax.lax.top_k(dets[:, 1], k)
        out = dets[idx]
        if k < keep_k:
            out = jnp.pad(out, ((0, keep_k - k), (0, 0)),
                          constant_values=-1.0)
        return out

    return {"Out": jax.vmap(per_image)(boxes, scs, im_info)}
