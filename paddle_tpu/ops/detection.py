"""Detection ops (subset; reference /root/reference/paddle/fluid/operators/
detection/ — anchors, boxes, iou, yolo_box; NMS variants follow in the
detection milestone)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import REQUIRED, register_op


@register_op("iou_similarity", inputs=("X", "Y"), outputs=("Out",),
             attrs={"box_normalized": True})
def iou_similarity(ins, attrs):
    """X: [N,4], Y: [M,4] (xmin,ymin,xmax,ymax) -> [N,M] IoU."""
    x, y = ins["X"], ins["Y"]
    off = 0.0 if attrs["box_normalized"] else 1.0
    ax = (x[:, 2] - x[:, 0] + off) * (x[:, 3] - x[:, 1] + off)
    ay = (y[:, 2] - y[:, 0] + off) * (y[:, 3] - y[:, 1] + off)
    xmin = jnp.maximum(x[:, None, 0], y[None, :, 0])
    ymin = jnp.maximum(x[:, None, 1], y[None, :, 1])
    xmax = jnp.minimum(x[:, None, 2], y[None, :, 2])
    ymax = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(xmax - xmin + off, 0.0)
    ih = jnp.maximum(ymax - ymin + off, 0.0)
    inter = iw * ih
    return {"Out": inter / (ax[:, None] + ay[None, :] - inter + 1e-10)}


@register_op("box_coder", inputs=("PriorBox", "PriorBoxVar", "TargetBox"),
             outputs=("OutputBox",), optional=("PriorBoxVar",),
             attrs={"code_type": "encode_center_size",
                    "box_normalized": True, "axis": 0})
def box_coder(ins, attrs):
    prior = ins["PriorBox"]
    target = ins["TargetBox"]
    var = ins.get("PriorBoxVar")
    off = 0.0 if attrs["box_normalized"] else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if attrs["code_type"] == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph[None, :]),
        ], axis=-1)
        if var is not None:
            out = out / var[None, :, :]
        return {"OutputBox": out}
    # decode_center_size: target [N, M, 4]
    t = target
    if var is not None:
        t = t * var[None, :, :]
    ocx = t[..., 0] * pw[None, :] + pcx[None, :]
    ocy = t[..., 1] * ph[None, :] + pcy[None, :]
    ow = jnp.exp(t[..., 2]) * pw[None, :]
    oh = jnp.exp(t[..., 3]) * ph[None, :]
    return {"OutputBox": jnp.stack(
        [ocx - ow / 2, ocy - oh / 2, ocx + ow / 2 - off,
         ocy + oh / 2 - off], axis=-1)}


@register_op("prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"),
             attrs={"min_sizes": REQUIRED, "max_sizes": [],
                    "aspect_ratios": [1.0], "variances": [0.1, 0.1, 0.2,
                                                          0.2],
                    "flip": False, "clip": False, "step_w": 0.0,
                    "step_h": 0.0, "offset": 0.5},
             differentiable=False)
def prior_box(ins, attrs):
    feat, img = ins["Input"], ins["Image"]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = attrs["step_w"] or iw / fw
    step_h = attrs["step_h"] or ih / fh
    ars = list(attrs["aspect_ratios"])
    if attrs["flip"]:
        ars = ars + [1.0 / a for a in attrs["aspect_ratios"] if a != 1.0]
    sizes = []
    for ms in attrs["min_sizes"]:
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
    for ms, mx in zip(attrs["min_sizes"], attrs["max_sizes"] or []):
        s = np.sqrt(ms * mx)
        sizes.append((s, s))
    cx = (jnp.arange(fw) + attrs["offset"]) * step_w
    cy = (jnp.arange(fh) + attrs["offset"]) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    boxes = []
    for bw, bh in sizes:
        boxes.append(jnp.stack([
            (cxg - bw / 2) / iw, (cyg - bh / 2) / ih,
            (cxg + bw / 2) / iw, (cyg + bh / 2) / ih], axis=-1))
    out = jnp.stack(boxes, axis=2)  # [fh, fw, nboxes, 4]
    if attrs["clip"]:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(attrs["variances"]), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("yolo_box", inputs=("X", "ImgSize"),
             outputs=("Boxes", "Scores"),
             attrs={"anchors": REQUIRED, "class_num": REQUIRED,
                    "conf_thresh": 0.01, "downsample_ratio": 32},
             differentiable=False)
def yolo_box(ins, attrs):
    x, img_size = ins["X"], ins["ImgSize"]
    n, c, h, w = x.shape
    anchors = attrs["anchors"]
    na = len(anchors) // 2
    nc = attrs["class_num"]
    x = x.reshape(n, na, 5 + nc, h, w)
    grid_x = jnp.arange(w)[None, None, None, :]
    grid_y = jnp.arange(h)[None, None, :, None]
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]
    stride = attrs["downsample_ratio"]
    bw = jnp.exp(x[:, :, 2]) * aw / (w * stride)
    bh = jnp.exp(x[:, :, 3]) * ah / (h * stride)
    conf = jax.nn.sigmoid(x[:, :, 4])
    prob = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = (conf >= attrs["conf_thresh"]).astype(x.dtype)
    ih = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    iw_ = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    boxes = jnp.stack([
        (bx - bw / 2) * iw_, (by - bh / 2) * ih,
        (bx + bw / 2) * iw_, (by + bh / 2) * ih], axis=-1)
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, -1, 4)
    scores = (prob * mask[:, :, None]).transpose(0, 1, 3, 4, 2)
    return {"Boxes": boxes, "Scores": scores.reshape(n, -1, nc)}
