"""Shared SeedOffset fold for jit-deterministic randomness.

Sampling ops re-randomize under jit by folding a SeedOffset counter
into their PRNG key (the dropout-op pattern; reference ops instead
re-seed per execution on the host, e.g. dropout_op.cc's
std::minstd_rand).  Contract: SeedOffset is a small non-negative
integer scalar (a step position).  With jax x64 disabled an int64
offset silently narrows to int32, so a negative value would wrap
differently per x64 mode; the clamp pins the behavior (negatives fold
as 0) uniformly across every op that uses the pattern.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fold_seed_offset(key, off):
    """Fold a SeedOffset scalar (array or python int) into a PRNG key."""
    off = jnp.maximum(jnp.asarray(off).reshape(()), 0)
    return jax.random.fold_in(key, off.astype(jnp.uint32))
