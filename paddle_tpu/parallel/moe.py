"""Mixture-of-Experts with expert parallelism over a mesh axis.

Capability anchor (SURVEY.md §2.4 "What's absent... expert parallelism"):
Switch-Transformer-style top-1 routing.  Routing (gating, capacity,
dispatch/combine one-hots) is computed replicated — it is O(N·E) cheap —
while the expert FFNs (the FLOPs) run sharded over the 'ep' axis via
shard_map, so each device holds and computes only E/n experts.  With the
batch also sharded on 'dp', XLA partitions the dispatch einsums into the
all-to-all exchange pattern of DeepSpeed-MoE/GShard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def switch_gating(x2d, gate_w, capacity):
    """Top-1 gating with capacity dropping.

    x2d: [N, d]; gate_w: [d, E].
    Returns (dispatch [N, E, C] 0/1, combine [N, E, C] gate-weighted,
    aux_loss scalar).
    """
    n, _ = x2d.shape
    e = gate_w.shape[1]
    logits = x2d @ gate_w                          # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)            # [N]
    gate = jnp.max(probs, axis=-1)                 # [N]
    onehot = jax.nn.one_hot(expert, e, dtype=x2d.dtype)   # [N, E]

    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot    # [N, E], 0-based
    keep = (pos < capacity) * onehot                       # [N, E]
    pos_cap = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity,
                             dtype=x2d.dtype)              # [N, C]
    dispatch = keep[:, :, None] * pos_cap[:, None, :]      # [N, E, C]
    combine = dispatch * gate[:, None, None]

    # Switch load-balancing loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def moe_ffn(x, gate_w, w1, b1, w2, b2, mesh=None, axis="ep",
            capacity_factor=1.25, activation=jax.nn.gelu):
    """Switch MoE feed-forward.

    x: [..., d]; gate_w: [d, E]; w1: [E, d, dff]; b1: [E, dff];
    w2: [E, dff, d]; b2: [E, d].  Expert dim sharded over ``axis`` when a
    mesh is active.  Returns (out [..., d], aux_loss scalar).
    """
    from paddle_tpu.parallel import env as penv

    if mesh is None:
        mesh = penv.get_mesh()
    orig_shape = x.shape
    d = orig_shape[-1]
    x2d = x.reshape(-1, d)
    n = x2d.shape[0]
    e = gate_w.shape[1]
    # n and e are static shapes under jit tracing
    capacity = int(max(1, np.ceil(n / e * capacity_factor)))
    dispatch, combine, aux = switch_gating(x2d, gate_w, capacity)

    # expert inputs: [E, C, d]
    xe = jnp.einsum("nec,nd->ecd", dispatch, x2d)

    def experts(xe_l, w1_l, b1_l, w2_l, b2_l):
        h = activation(jnp.einsum("ecd,edf->ecf", xe_l, w1_l)
                       + b1_l[:, None, :])
        return jnp.einsum("ecf,efd->ecd", h, w2_l) + b2_l[:, None, :]

    if mesh is not None and axis in mesh.axis_names \
            and mesh.shape[axis] > 1 and e % mesh.shape[axis] == 0:
        from paddle_tpu.parallel.env import shard_map
        from jax.sharding import PartitionSpec as P

        es = P(axis)
        ye = shard_map(experts, mesh=mesh,
                       in_specs=(es, es, es, es, es), out_specs=es,
                       check_rep=False)(xe, w1, b1, w2, b2)
    else:
        ye = experts(xe, w1, b1, w2, b2)

    out = jnp.einsum("nec,ecd->nd", combine, ye)
    return out.reshape(orig_shape), aux
