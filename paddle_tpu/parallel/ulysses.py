"""Ulysses sequence parallelism: all-to-all head<->sequence resharding.

Capability anchor (SURVEY.md §2.4 "What's absent" / §5): DeepSpeed-Ulysses
pattern — activations arrive sharded on the sequence axis; an all-to-all
re-shards them on the *head* axis so each device runs full-sequence
attention for H/n heads, then a second all-to-all restores sequence
sharding.  Comm volume O(S·d/n) per device, riding ICI.

Complementary to ring attention: Ulysses needs H % n == 0 and moves
activations twice; ring keeps heads whole and pipelines K/V instead.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def ulysses_attention(q, k, v, mesh=None, axis="sp", causal=False,
                      scale=None, impl=None, block_q=None,
                      block_k=None, packed_stats=None, head_pack=None):
    """q/k/v: [B, S, H, D] global arrays, S sharded over ``axis``.

    impl: None (auto: 'flash' on TPU, 'xla' elsewhere) — after the
    all-to-all each device holds full-sequence H/n-head blocks, which
    run through the Pallas flash kernel ('flash'/'flash_interpret') or
    the plain einsum path ('xla').

    block_q/block_k pin the kernel tiles; packed_stats/head_pack are
    the flash memory-layout variants (None -> flags).  Ulysses is
    where head_pack composes naturally: each device runs FULL-sequence
    attention for H/n heads, so at d<=64 an even per-device head count
    pairs up inside the kernel."""
    from paddle_tpu.parallel import env as penv
    from paddle_tpu.parallel.ring_attention import _plain_attention

    if mesh is None:
        mesh = penv.get_mesh()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return _plain_attention(q, k, v, causal, scale)
    if impl is None:
        from paddle_tpu.ops.pallas_kernels import _on_tpu

        impl = "flash" if _on_tpu() else "xla"

    from jax import lax
    from paddle_tpu.parallel.env import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    b, s, h, d = q.shape
    assert s % n == 0, f"seq {s} % {axis}={n} != 0"
    assert h % n == 0, f"heads {h} % {axis}={n} != 0 (use ring attention)"
    spec = P(None, axis, None, None)

    def attend(qh, kh, vh):
        if impl in ("flash", "flash_interpret"):
            from paddle_tpu.ops.pallas_kernels import flash_attention

            o = flash_attention(
                jnp.swapaxes(qh, 1, 2), jnp.swapaxes(kh, 1, 2),
                jnp.swapaxes(vh, 1, 2), causal=causal, scale=scale,
                impl="interpret" if impl == "flash_interpret"
                else "pallas", block_q=block_q, block_k=block_k,
                packed_stats=packed_stats, head_pack=head_pack)
            return jnp.swapaxes(o, 1, 2)
        return _plain_attention(qh, kh, vh, causal, scale)

    def local(ql, kl, vl):
        # [B, S/n, H, D] --all_to_all--> [B, S, H/n, D]
        def seq2head(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def head2seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        qh, kh, vh = seq2head(ql), seq2head(kl), seq2head(vl)
        return head2seq(attend(qh, kh, vh))

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)
