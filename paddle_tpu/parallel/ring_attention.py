"""Ring attention: sequence-parallel exact attention over the ICI ring.

Capability anchor (SURVEY.md §5 "Long-context / sequence parallelism"): the
reference's LoD machinery handled variable-length sequences but had no way
to scale sequence *length* across devices; ring attention is the TPU-native
answer (Liu et al. 2023 pattern): Q stays sharded on the sequence axis while
K/V blocks rotate around the mesh axis via collective-permute, with
flash-style online-softmax accumulation so the full [S, S] score matrix is
never materialized.

Works under jit (CompiledProgram traces it like any op) via shard_map over
the current device mesh; with no mesh or a singleton axis it degrades to
plain attention.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _attention_block(q, k, v, bias, scale):
    """One [Sq, Sk] score block -> (unnormalized out, running max, denom).
    q: [B, H, Sq, D], k/v: [B, H, Sk, D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)                       # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B, H, Sq]
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Combine two online-softmax partial results."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _local_causal_bias(q_pos, k_pos):
    """bias[i, j] = 0 where k_pos[j] <= q_pos[i], else -inf."""
    mask = k_pos[None, :] <= q_pos[:, None]
    return jnp.where(mask, 0.0, _NEG_INF)


def ring_attention(q, k, v, mesh=None, axis="sp", causal=False,
                   scale=None, impl=None, block_q=None, block_k=None,
                   packed_stats=None, head_pack=None):
    """Exact attention with sequence sharded over ``axis``.

    q/k/v: [B, S, H, D] global arrays (S = full sequence).  Inside jit the
    shard_map sees per-device [B, S/n, H, D] blocks; K/V rotate n-1 times
    via lax.ppermute so every Q block attends to every K/V block while only
    ever holding one remote block — O(S/n) memory per chip, comm riding the
    ICI ring.

    impl: None (auto: 'flash' on TPU, 'xla' elsewhere), 'xla' (einsum
    per chunk — materializes the per-chunk [blk, blk] scores),
    'flash' / 'flash_interpret' (each chunk through the Pallas kernel
    via its (out, lse) mergeable summary — scores stay in VMEM even
    within a chunk, forward and backward).

    block_q/block_k: kernel tile override for the per-chunk flash
    calls — the chunk length is S/n, not S, so the kernel's
    seq-length-keyed default can land differently than a whole-seq
    call's; pin them when sweeping.  packed_stats/head_pack: the flash
    memory-layout variants (ops/pallas_kernels.py; None defers to the
    flags) — at ring scale the packed row-stats matter most, since
    every chunk of every rotation materializes its own lse.
    """
    from paddle_tpu.parallel import env as penv

    if mesh is None:
        mesh = penv.get_mesh()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return _plain_attention(q, k, v, causal, scale)
    if impl is None:
        from paddle_tpu.ops.pallas_kernels import _on_tpu

        impl = "flash" if _on_tpu() else "xla"

    from paddle_tpu.parallel.env import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    seq = q.shape[1]
    assert seq % n == 0, f"seq {seq} not divisible by {axis}={n}"
    blk = seq // n
    spec = P(None, axis, None, None)
    use_flash = impl in ("flash", "flash_interpret")
    flash_impl = "interpret" if impl == "flash_interpret" else "pallas"

    def _flash_chunk(qt, kc, vc, chunk_causal):
        """One chunk through the Pallas kernel; returns the same
        unnormalized-summary triple _merge consumes: with
        (o_norm, lse) the triple (o_norm, m=lse, l=1) merges exactly
        (merge then scales o by exp(lse-m) and sums the weights)."""
        from paddle_tpu.ops.pallas_kernels import flash_attention_lse

        o, lse = flash_attention_lse(qt, kc, vc, causal=chunk_causal,
                                     scale=scale, impl=flash_impl,
                                     block_q=block_q, block_k=block_k,
                                     packed_stats=packed_stats,
                                     head_pack=head_pack)
        b, h, t, _d = qt.shape
        lse = lse[:, :t].reshape(b, h, t).astype(jnp.float32)
        return o.astype(jnp.float32), lse, jnp.ones_like(lse)

    def local(q_blk, k_blk, v_blk):
        # [B, blk, H, D] -> [B, H, blk, D]
        qt = jnp.swapaxes(q_blk, 1, 2)
        kt = jnp.swapaxes(k_blk, 1, 2)
        vt = jnp.swapaxes(v_blk, 1, 2)
        my = lax.axis_index(axis)
        q_pos = my * blk + jnp.arange(blk)

        perm = [(i, (i + 1) % n) for i in range(n)]

        def block_summary(src, kc, vc):
            if not use_flash:
                if causal:
                    k_pos = src * blk + jnp.arange(blk)
                    bias = _local_causal_bias(q_pos, k_pos)
                else:
                    bias = None
                return _attention_block(
                    qt.astype(jnp.float32), kc.astype(jnp.float32),
                    vc.astype(jnp.float32), bias, scale)
            if not causal:
                return _flash_chunk(qt, kc, vc, False)
            # causal: the diagonal chunk masks within itself, chunks
            # before mine are fully visible, chunks after contribute
            # nothing (empty summary)
            empty = (jnp.zeros(qt.shape, jnp.float32),
                     jnp.full(qt.shape[:-1], _NEG_INF, jnp.float32),
                     jnp.zeros(qt.shape[:-1], jnp.float32))
            return lax.cond(
                src == my,
                lambda _: _flash_chunk(qt, kc, vc, True),
                lambda _: lax.cond(
                    src < my,
                    lambda __: _flash_chunk(qt, kc, vc, False),
                    lambda __: empty, None),
                None)

        def step(carry, i):
            o, m, l, kc, vc = carry
            src = (my - i) % n          # which block kc/vc currently is
            bo, bm, bl = block_summary(src, kc, vc)
            o, m, l = _merge(o, m, l, bo, bm, bl)
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (o, m, l, kc, vc), None

        o0 = jnp.zeros(qt.shape, jnp.float32)
        m0 = jnp.full(qt.shape[:-1], _NEG_INF, jnp.float32)
        l0 = jnp.zeros(qt.shape[:-1], jnp.float32)
        (o, m, l, _, _), _ = lax.scan(
            step, (o0, m0, l0, kt, vt), jnp.arange(n))
        out = (o / jnp.maximum(l[..., None], 1e-30)).astype(q_blk.dtype)
        return jnp.swapaxes(out, 1, 2)          # back to [B, blk, H, D]

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)


def _plain_attention(q, k, v, causal, scale):
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        seq = q.shape[1]
        pos = jnp.arange(seq)
        s = s + _local_causal_bias(pos, pos)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(o, 1, 2)
