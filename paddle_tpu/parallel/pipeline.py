"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Reference parity (SURVEY.md §2.4 "Pipeline parallelism (PP)"):
  - PipelineTrainer + SectionWorker scope-queues between sections:
    /root/reference/paddle/fluid/framework/trainer.h:95-120,
    section_worker.cc:141
  - PipelineOptimizer splitting the program into per-device sections:
    /root/reference/python/paddle/fluid/optimizer.py:2664,2924

TPU-first difference (SURVEY.md §7 hard part (c)): no host threads or scope
queues — stages are mesh shards running the same SPMD program, microbatch
activations hop stage->stage via lax.ppermute (collective-permute on ICI),
and the schedule is a lax.scan over M + S - 1 ticks.  Backward through the
scan gives the GPipe fwd-then-bwd schedule; XLA overlaps the permute with
stage compute.  Stages must be homogeneous (same stage_fn, stacked weights)
— the transformer-stack case the reference's SectionWorker was used for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, x, num_microbatches,
                   mesh=None, axis="pp"):
    """Run ``x`` through S homogeneous pipeline stages.

    stage_fn(params_leafwise, microbatch) -> microbatch (same shape).
    stage_params: pytree whose leaves have leading dim S (one slice per
    stage), sharded over ``axis``.
    x: [B, ...] global batch; B % num_microbatches == 0.
    Returns stage_fn composed S times over x, computed pipeline-parallel.
    """
    from paddle_tpu.parallel import env as penv

    if mesh is None:
        mesh = penv.get_mesh()
    M = num_microbatches
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # degenerate: sequential composition
        S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        out = x
        for i in range(S):
            p_i = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            out = stage_fn(p_i, out)
        return out

    from paddle_tpu.parallel.env import shard_map
    from jax.sharding import PartitionSpec as P

    S = mesh.shape[axis]
    b = x.shape[0]
    assert b % M == 0, f"batch {b} % microbatches {M} != 0"
    mb = b // M
    xmb = x.reshape((M, mb) + x.shape[1:])

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def local(params, xs):
        stage = lax.axis_index(axis)
        p_local = jax.tree_util.tree_map(lambda a: a[0], params)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(buf, t):
            # stage 0 injects microbatch t (clamped; ticks >= M feed
            # garbage that never reaches the collected outputs)
            inj = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, inj, buf)
            out = stage_fn(p_local, inp)
            nxt = lax.ppermute(out, axis, fwd_perm)
            return nxt, out

        buf0 = jnp.zeros_like(xs[0])
        _, outs = lax.scan(tick, buf0, jnp.arange(M + S - 1))
        # the last stage's outputs at ticks [S-1, S-1+M) are the results;
        # broadcast them to every shard (out_specs replicated)
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        mine = jnp.where(stage == S - 1, valid,
                         jnp.zeros_like(valid))
        return lax.psum(mine, axis)

    out = shard_map(local, mesh=mesh,
                    in_specs=(params_spec, P()),
                    out_specs=P(), check_rep=False)(stage_params, xmb)
    return out.reshape((b,) + out.shape[2:])


def stack_stage_params(per_stage_params):
    """[pytree_stage0, pytree_stage1, ...] -> one pytree with leading stage
    dim (what pipeline_apply consumes)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


# ---------------------------------------------------------------------------
# IR-level pipeline: PipelineOptimizer cuts the Program into per-stage
# sections at `fluid.pipeline_stage(i)` annotations (reference
# optimizer.py:2664,2924 PipelineOptimizer.minimize splitting into
# SectionConfigs) and a runner executes them GPipe-style with one jitted
# fwd/bwd/opt function per stage pinned to its own device — the
# SectionWorker (section_worker.cc:141) with XLA functions instead of host
# threads interpreting ops, and device-to-device activation hops instead
# of scope queues.
# ---------------------------------------------------------------------------

from paddle_tpu.core.program import (BACKWARD, FORWARD, LOSS, LRSCHED,
                                     OPTIMIZE)


class _StageSection:
    """One pipeline section: its op lists and dataflow interfaces."""

    def __init__(self, idx):
        self.idx = idx
        self.fwd_ops = []
        self.bwd_ops = []
        self.opt_ops = []
        # interfaces (ordered name lists)
        self.state = []        # persistables owned by this stage
        self.feeds = []        # data vars consumed by fwd ops
        self.fwd_in = []       # activations from earlier stages
        self.fwd_out = []      # activations for later stages
        self.saved = []        # fwd-env vars the bwd ops re-read
        self.bwd_in = []       # gradients from later stages
        self.bwd_out = []      # gradients for earlier stages
        self.param_grads = []  # canonical grads consumed by opt ops


def build_pipeline_plan(program, loss_name):
    """Assign every op a stage and compute the section interfaces.

    Forward ops carry explicit annotations (pipeline_stage ctx);
    unannotated ops inherit the max stage of their input producers
    (backward ops were pre-stamped with their forward op's stage by
    append_backward; optimizer ops land on their grad's stage)."""
    block = program.global_block()
    fwd_roles = (FORWARD, LOSS)
    loss_stage = max((op.stage or 0) for op in block.ops
                     if op.op_role in fwd_roles)
    producer = {}
    for op in block.ops:
        if op.stage is None:
            staged = [producer[n] for n in op.input_names()
                      if n in producer]
            if staged:
                op.stage = max(staged)
            elif op.op_role == BACKWARD:
                op.stage = loss_stage  # e.g. the loss-grad seed
            else:
                op.stage = 0
        for n in op.output_names():
            producer[n] = op.stage
    n_stages = max(op.stage for op in block.ops) + 1

    secs = [_StageSection(i) for i in range(n_stages)]
    lr_ops = [op for op in block.ops if op.op_role == LRSCHED]
    for op in block.ops:
        if op.op_role in fwd_roles:
            secs[op.stage].fwd_ops.append(op)
        elif op.op_role == BACKWARD:
            secs[op.stage].bwd_ops.append(op)
        elif op.op_role == OPTIMIZE:
            secs[op.stage].opt_ops.append(op)
    # lr-schedule ops replicate into every stage that optimizes
    for s in secs:
        if s.opt_ops and lr_ops:
            s.opt_ops = [OpDescCopy(o) for o in lr_ops] + s.opt_ops

    def is_persistable(n):
        return block.has_var(n) and block.var(n).persistable

    def is_data(n):
        return block.has_var(n) and block.var(n).is_data

    # a persistable WRITTEN on one stage but read on another would
    # silently desynchronize (each stage holds its own device copy and
    # only the owner's is updated) — reject weight sharing across stages.
    # Read-only persistables (constant lr) replicate safely.
    reads, writes = {}, {}
    lrsched_written = {n for op in lr_ops for n in op.output_names()}
    for s in secs:
        for op in s.fwd_ops + s.bwd_ops + s.opt_ops:
            if op.op_role == LRSCHED:
                continue  # replicated per stage by design, copies agree
            for n in op.input_names():
                if is_persistable(n):
                    reads.setdefault(n, set()).add(s.idx)
            for n in op.output_names():
                if is_persistable(n):
                    writes.setdefault(n, set()).add(s.idx)
    for n, wstages in writes.items():
        if n in lrsched_written:
            continue
        span = wstages | reads.get(n, set())
        if len(span) > 1:
            raise NotImplementedError(
                f"pipeline: persistable '{n}' is written on stage(s) "
                f"{sorted(wstages)} but used on stages {sorted(span)} — "
                "cross-stage weight sharing is not supported; keep each "
                "parameter inside one pipeline_stage block")

    fwd_producer = {}
    for s in secs:
        for op in s.fwd_ops:
            for n in op.output_names():
                fwd_producer[n] = s.idx
    bwd_producer = {}
    for s in secs:
        for op in s.bwd_ops:
            for n in op.output_names():
                bwd_producer[n] = s.idx

    for s in secs:
        state, feeds, fwd_in = [], [], []
        fwd_local = set()
        for op in s.fwd_ops + s.bwd_ops + s.opt_ops:
            for n in op.input_names() + op.output_names():
                if is_persistable(n) and n not in state:
                    state.append(n)
        for op in s.fwd_ops:
            for n in op.input_names():
                if is_persistable(n) or n in fwd_local:
                    continue
                if is_data(n) and n not in fwd_producer:
                    if n not in feeds:
                        feeds.append(n)
                elif fwd_producer.get(n, s.idx) < s.idx:
                    if n not in fwd_in:
                        fwd_in.append(n)
            fwd_local.update(op.output_names())
        s.state, s.feeds, s.fwd_in = state, feeds, fwd_in

    for s in secs:
        consumed_later = set()
        for t in secs[s.idx + 1:]:
            for op in t.fwd_ops:
                consumed_later.update(op.input_names())
        s.fwd_out = [n for n in dict.fromkeys(
            n for op in s.fwd_ops for n in op.output_names())
            if n in consumed_later]
        # what bwd re-reads from the fwd environment of this stage
        bwd_reads = {n for op in s.bwd_ops for n in op.input_names()}
        avail = set(s.fwd_in) | set(s.feeds) | {
            n for op in s.fwd_ops for n in op.output_names()}
        s.saved = sorted((bwd_reads & avail) -
                         {n for n in bwd_reads if is_persistable(n)})
        s.bwd_in = sorted(n for n in bwd_reads
                          if bwd_producer.get(n, s.idx) > s.idx)
        consumed_earlier = set()
        for t in secs[:s.idx]:
            for op in t.bwd_ops:
                consumed_earlier.update(op.input_names())
        s.bwd_out = [n for n in dict.fromkeys(
            n for op in s.bwd_ops for n in op.output_names())
            if n in consumed_earlier]
        grad_ins = {n for op in s.opt_ops
                    for slot, names in op.inputs.items()
                    if slot == "Grad" for n in names}
        s.param_grads = sorted(grad_ins)
    return secs, loss_stage


def OpDescCopy(op):
    from paddle_tpu.core.program import OpDesc

    return OpDesc.from_dict(op.to_dict())


class PipelineRunner:
    """GPipe executor over the cut sections: per-stage jitted fwd/bwd/opt
    functions, each pinned to its own device when enough exist; gradient
    accumulation over microbatches then one optimizer apply (reference
    PipelineTrainer/SectionWorker semantics)."""

    def __init__(self, program, sections, loss_stage, loss_name,
                 num_microbatches, scope):
        import types

        from paddle_tpu.core.compiler import (_TraceEnv,
                                              _run_block_symbolic)

        self.program = program
        self.sections = sections
        self.loss_stage = loss_stage
        self.loss_name = loss_name
        self.M = num_microbatches
        self.scope = scope
        devs = jax.devices()
        S = len(sections)
        self.devices = [devs[i % len(devs)] for i in range(S)] \
            if len(devs) > 1 else [None] * S

        def make_fn(ops, out_names):
            shim = types.SimpleNamespace(blocks=list(program.blocks))
            shim.blocks[0] = types.SimpleNamespace(ops=list(ops))

            def fn(env0):
                env = _TraceEnv()
                env.update(env0)
                _run_block_symbolic(shim, 0, env)
                return {n: env[n] for n in out_names if n in env}

            return jax.jit(fn)

        self._fwd = []
        self._bwd = []
        self._opt = []
        for s in sections:
            pers_out = [n for op in s.fwd_ops
                        for n in op.output_names()
                        if n in s.state]
            fwd_outs = list(dict.fromkeys(
                s.fwd_out + s.saved + pers_out +
                ([loss_name] if s.idx == loss_stage else [])))
            self._fwd.append(make_fn(s.fwd_ops, fwd_outs))
            bwd_outs = list(dict.fromkeys(s.bwd_out + s.param_grads))
            self._bwd.append(make_fn(s.bwd_ops, bwd_outs)
                             if s.bwd_ops else None)
            self._opt.append(make_fn(s.opt_ops, s.state)
                             if s.opt_ops else None)
        self._state = None

    def _pull_state(self):
        self._state = []
        for s, dev in zip(self.sections, self.devices):
            st = {}
            for n in s.state:
                var = self.scope.find_var(n)
                if var is None or var.get() is None:
                    raise RuntimeError(
                        f"pipeline: persistable '{n}' uninitialized — run"
                        " the startup program first")
                v = var.get()
                st[n] = jax.device_put(v, dev) if dev is not None else v
            self._state.append(st)

    def _push_state(self):
        for st in self._state:
            for n, v in st.items():
                self.scope.var(n).set(v)

    def _state_is_fresh(self):
        """True while the scope still holds exactly the arrays we pushed;
        an external write (reloaded checkpoint, re-run startup) breaks
        identity and forces a re-pull."""
        if self._state is None:
            return False
        for s, st in zip(self.sections, self._state):
            for n in s.state:
                var = self.scope.find_var(n)
                if var is None or var.get() is not st[n]:
                    return False
        return True

    def run(self, feed, fetch_list, return_numpy=True):
        import numpy as np

        if not self._state_is_fresh():
            self._pull_state()
        M = self.M
        S = len(self.sections)
        # split feeds into microbatches along dim 0
        mb_feeds = [{} for _ in range(M)]
        for name, val in feed.items():
            arr = jnp.asarray(np.asarray(val)) \
                if not isinstance(val, jax.Array) else val
            if arr.shape[0] % M != 0:
                raise ValueError(
                    f"pipeline: batch {arr.shape[0]} not divisible by "
                    f"num_microbatches={M} (feed '{name}')")
            for m, part in enumerate(jnp.split(arr, M, axis=0)):
                mb_feeds[m][name] = part

        saved = [[None] * S for _ in range(M)]
        losses = []
        # forward sweep (python drives; jax async dispatch pipelines the
        # per-device work like the reference's section scope-queues)
        for m in range(M):
            acts = {}
            for s, sec in enumerate(self.sections):
                dev = self.devices[s]
                env = dict(self._state[s])
                for n in sec.feeds:
                    v = mb_feeds[m][n]
                    env[n] = jax.device_put(v, dev) if dev is not None \
                        else v
                for n in sec.fwd_in:
                    v = acts[n]
                    env[n] = jax.device_put(v, dev) if dev is not None \
                        else v
                outs = self._fwd[s](env)
                for n in sec.state:
                    if n in outs:
                        self._state[s][n] = outs[n]
                saved[m][s] = {n: outs[n] for n in sec.saved
                               if n in outs}
                for n in sec.fwd_out:
                    acts[n] = outs[n]
                if s == self.loss_stage and self.loss_name in outs:
                    losses.append(outs[self.loss_name])
        # backward sweep with gradient accumulation
        grad_acc = [dict() for _ in range(S)]
        for m in range(M):
            grads = {}
            for s in range(S - 1, -1, -1):
                sec = self.sections[s]
                if self._bwd[s] is None:
                    continue
                dev = self.devices[s]
                env = dict(self._state[s])
                env.update(saved[m][s])
                for n in sec.bwd_in:
                    v = grads[n]
                    env[n] = jax.device_put(v, dev) if dev is not None \
                        else v
                outs = self._bwd[s](env)
                for n in sec.bwd_out:
                    grads[n] = outs[n]
                for n in sec.param_grads:
                    if n not in outs:
                        continue
                    if n in grad_acc[s]:
                        grad_acc[s][n] = grad_acc[s][n] + outs[n]
                    else:
                        grad_acc[s][n] = outs[n]
        # optimizer apply (mean of microbatch grads == full-batch grad)
        for s, sec in enumerate(self.sections):
            if self._opt[s] is None:
                continue
            env = dict(self._state[s])
            for n, g in grad_acc[s].items():
                env[n] = g / float(M)
            outs = self._opt[s](env)
            for n in sec.state:
                if n in outs:
                    self._state[s][n] = outs[n]
        self._push_state()

        results = []
        loss_val = None
        if losses:
            loss_val = sum(jnp.mean(v) for v in losses) / float(len(losses))
        for f in fetch_list or []:
            name = f if isinstance(f, str) else f.name
            if name == self.loss_name and loss_val is not None:
                val = loss_val
            else:
                var = self.scope.find_var(name)
                if var is None or var.get() is None:
                    raise RuntimeError(
                        f"pipeline fetch '{name}': only the loss and "
                        "persistable state are fetchable")
                val = var.get()
            results.append(np.asarray(val) if return_numpy else val)
        return results


class PipelineOptimizer:
    """reference optimizer.py:2664 PipelineOptimizer.

    minimize() runs the inner optimizer, then CUTS the program into
    per-stage sections at `fluid.pipeline_stage(i)` annotations
    (compile-time IR surgery, like the reference's section split at
    :2924) and attaches the plan; Executor.run detects it and drives the
    GPipe section runner.  Programs with no stage annotations fall back
    to plain single-section execution."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches

    @property
    def num_microbatches(self):
        return self._num_microbatches

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set,
                                          grad_clip)
        program = loss.block.program
        annotated = any(op.stage is not None
                        for op in program.global_block().ops)
        if annotated:
            sections, loss_stage = build_pipeline_plan(program, loss.name)
            program._pipeline_opt = {
                "sections": sections,
                "loss_stage": loss_stage,
                "loss_name": loss.name,
                "num_microbatches": self._num_microbatches,
            }
        return result
