"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Reference parity (SURVEY.md §2.4 "Pipeline parallelism (PP)"):
  - PipelineTrainer + SectionWorker scope-queues between sections:
    /root/reference/paddle/fluid/framework/trainer.h:95-120,
    section_worker.cc:141
  - PipelineOptimizer splitting the program into per-device sections:
    /root/reference/python/paddle/fluid/optimizer.py:2664,2924

TPU-first difference (SURVEY.md §7 hard part (c)): no host threads or scope
queues — stages are mesh shards running the same SPMD program, microbatch
activations hop stage->stage via lax.ppermute (collective-permute on ICI),
and the schedule is a lax.scan over M + S - 1 ticks.  Backward through the
scan gives the GPipe fwd-then-bwd schedule; XLA overlaps the permute with
stage compute.  Stages must be homogeneous (same stage_fn, stacked weights)
— the transformer-stack case the reference's SectionWorker was used for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, x, num_microbatches,
                   mesh=None, axis="pp"):
    """Run ``x`` through S homogeneous pipeline stages.

    stage_fn(params_leafwise, microbatch) -> microbatch (same shape).
    stage_params: pytree whose leaves have leading dim S (one slice per
    stage), sharded over ``axis``.
    x: [B, ...] global batch; B % num_microbatches == 0.
    Returns stage_fn composed S times over x, computed pipeline-parallel.
    """
    from paddle_tpu.parallel import env as penv

    if mesh is None:
        mesh = penv.get_mesh()
    M = num_microbatches
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # degenerate: sequential composition
        S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        out = x
        for i in range(S):
            p_i = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            out = stage_fn(p_i, out)
        return out

    from paddle_tpu.parallel.env import shard_map
    from jax.sharding import PartitionSpec as P

    S = mesh.shape[axis]
    b = x.shape[0]
    assert b % M == 0, f"batch {b} % microbatches {M} != 0"
    mb = b // M
    xmb = x.reshape((M, mb) + x.shape[1:])

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def local(params, xs):
        stage = lax.axis_index(axis)
        p_local = jax.tree_util.tree_map(lambda a: a[0], params)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(buf, t):
            # stage 0 injects microbatch t (clamped; ticks >= M feed
            # garbage that never reaches the collected outputs)
            inj = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, inj, buf)
            out = stage_fn(p_local, inp)
            nxt = lax.ppermute(out, axis, fwd_perm)
            return nxt, out

        buf0 = jnp.zeros_like(xs[0])
        _, outs = lax.scan(tick, buf0, jnp.arange(M + S - 1))
        # the last stage's outputs at ticks [S-1, S-1+M) are the results;
        # broadcast them to every shard (out_specs replicated)
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        mine = jnp.where(stage == S - 1, valid,
                         jnp.zeros_like(valid))
        return lax.psum(mine, axis)

    out = shard_map(local, mesh=mesh,
                    in_specs=(params_spec, P()),
                    out_specs=P(), check_rep=False)(stage_params, xmb)
    return out.reshape((b,) + out.shape[2:])


def stack_stage_params(per_stage_params):
    """[pytree_stage0, pytree_stage1, ...] -> one pytree with leading stage
    dim (what pipeline_apply consumes)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


class PipelineOptimizer:
    """API-parity wrapper (reference optimizer.py:2664).

    The reference cuts a Program into sections run by SectionWorker threads.
    The TPU design expresses the pipeline *inside* the jitted step via
    pipeline_apply; this wrapper carries the microbatch config and delegates
    minimize to the inner optimizer — models built with homogeneous stages
    (e.g. models/transformer.py blocks) route their stack through
    pipeline_apply when a 'pp' mesh axis is active."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches

    @property
    def num_microbatches(self):
        return self._num_microbatches

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        grad_clip)
