"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Reference parity (SURVEY.md §2.4 "Pipeline parallelism (PP)"):
  - PipelineTrainer + SectionWorker scope-queues between sections:
    /root/reference/paddle/fluid/framework/trainer.h:95-120,
    section_worker.cc:141
  - PipelineOptimizer splitting the program into per-device sections:
    /root/reference/python/paddle/fluid/optimizer.py:2664,2924

TPU-first difference (SURVEY.md §7 hard part (c)): no host threads or scope
queues — stages are mesh shards running the same SPMD program, microbatch
activations hop stage->stage via lax.ppermute (collective-permute on ICI),
and the schedule is a lax.scan over M + S - 1 ticks.  Backward through the
scan gives the GPipe fwd-then-bwd schedule; XLA overlaps the permute with
stage compute.  Stages must be homogeneous (same stage_fn, stacked weights)
— the transformer-stack case the reference's SectionWorker was used for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn, stage_params, x, num_microbatches,
                   mesh=None, axis="pp"):
    """Run ``x`` through S homogeneous pipeline stages.

    stage_fn(params_leafwise, microbatch) -> microbatch (same shape).
    stage_params: pytree whose leaves have leading dim S (one slice per
    stage), sharded over ``axis``.
    x: [B, ...] global batch; B % num_microbatches == 0.
    Returns stage_fn composed S times over x, computed pipeline-parallel.
    """
    from paddle_tpu.parallel import env as penv

    if mesh is None:
        mesh = penv.get_mesh()
    M = num_microbatches
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # degenerate: sequential composition
        S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        out = x
        for i in range(S):
            p_i = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            out = stage_fn(p_i, out)
        return out

    from paddle_tpu.parallel.env import shard_map
    from jax.sharding import PartitionSpec as P

    S = mesh.shape[axis]
    b = x.shape[0]
    assert b % M == 0, f"batch {b} % microbatches {M} != 0"
    mb = b // M
    xmb = x.reshape((M, mb) + x.shape[1:])

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def local(params, xs):
        stage = lax.axis_index(axis)
        p_local = jax.tree_util.tree_map(lambda a: a[0], params)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(buf, t):
            # stage 0 injects microbatch t (clamped; ticks >= M feed
            # garbage that never reaches the collected outputs)
            inj = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, inj, buf)
            out = stage_fn(p_local, inp)
            nxt = lax.ppermute(out, axis, fwd_perm)
            return nxt, out

        buf0 = jnp.zeros_like(xs[0])
        _, outs = lax.scan(tick, buf0, jnp.arange(M + S - 1))
        # the last stage's outputs at ticks [S-1, S-1+M) are the results;
        # broadcast them to every shard (out_specs replicated)
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        mine = jnp.where(stage == S - 1, valid,
                         jnp.zeros_like(valid))
        return lax.psum(mine, axis)

    out = shard_map(local, mesh=mesh,
                    in_specs=(params_spec, P()),
                    out_specs=P(), check_rep=False)(stage_params, xmb)
    return out.reshape((b,) + out.shape[2:])


def stack_stage_params(per_stage_params):
    """[pytree_stage0, pytree_stage1, ...] -> one pytree with leading stage
    dim (what pipeline_apply consumes)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def make_pipeline_schedule(kind, M, S):
    """Host dispatch order for the section runner: list of
    (stage, 'F'|'B', microbatch).

    "gpipe": all M forwards, then all M backwards (reference
    SectionWorker's queue-driven sweep) — every stage holds M saved
    activation sets at the fwd/bwd boundary.
    "1f1b": PipeDream-flush — stage i starts draining backwards once
    min(M, S - i) microbatches are in flight, bounding saved
    activations at min(M, S - i) instead of M.  Grad accumulation is
    order-independent, so numerics match gpipe exactly."""
    if kind == "gpipe":
        return ([(s, "F", m) for m in range(M) for s in range(S)] +
                [(s, "B", m) for m in range(M)
                 for s in range(S - 1, -1, -1)])
    if kind != "1f1b":
        raise ValueError(f"unknown pipeline schedule {kind!r}; "
                         "choose 'gpipe' or '1f1b'")
    sched = []
    fdone, bdone = [0] * S, [0] * S
    max_inflight = [min(M, S - i) for i in range(S)]
    while any(b < M for b in bdone):
        made = False
        for i in range(S):
            f_ready = fdone[i] < M and (i == 0 or fdone[i - 1] > fdone[i])
            b_ready = bdone[i] < fdone[i] and \
                (i == S - 1 or bdone[i + 1] > bdone[i])
            if f_ready and fdone[i] - bdone[i] < max_inflight[i]:
                sched.append((i, "F", fdone[i]))
                fdone[i] += 1
                made = True
            elif b_ready:
                sched.append((i, "B", bdone[i]))
                bdone[i] += 1
                made = True
        if not made:  # pragma: no cover - the policy above always moves
            raise RuntimeError("1f1b schedule deadlocked "
                               f"(M={M}, S={S}, f={fdone}, b={bdone})")
    return sched


def schedule_stats(sched, M, S):
    """Measure a schedule by unit-time simulation: stages run in
    parallel, each serially, F/B cost one tick, deps respected
    (F(s,m) after F(s-1,m); B(s,m) after F(s,m) and B(s+1,m)).
    Returns makespan, per-stage ideal work (2M), the bubble fraction
    idle/makespan, and the peak saved-activation count per stage.

    Scope (VERDICT r5 weak #6): every bubble fraction this repo quotes
    comes from THIS unit-time model — uniform per-microbatch cost, no
    communication, no real clock.  It verifies schedule SHAPE (the
    (S-1)/(M+S-1) law, 1F1B's memory bound), not wall-clock pipeline
    efficiency; no on-chip multi-stage measurement exists in the
    single-chip environment."""
    end = {}
    stage_free = [0] * S
    inflight = [0] * S
    peak = [0] * S
    for (s, kind, m) in sched:
        deps = []
        if kind == "F":
            if s > 0:
                deps.append(("F", s - 1, m))
        else:
            deps.append(("F", s, m))
            if s < S - 1:
                deps.append(("B", s + 1, m))
        start = max([stage_free[s]] + [end[d] for d in deps])
        end[(kind, s, m)] = stage_free[s] = start + 1
        if kind == "F":
            inflight[s] += 1
            peak[s] = max(peak[s], inflight[s])
        else:
            inflight[s] -= 1
    makespan = max(end.values())
    return {
        "makespan": makespan,
        "ideal": 2 * M,
        "bubble_frac": round((makespan - 2 * M) / makespan, 6),
        "peak_inflight": peak,
    }


# ---------------------------------------------------------------------------
# IR-level pipeline: PipelineOptimizer cuts the Program into per-stage
# sections at `fluid.pipeline_stage(i)` annotations (reference
# optimizer.py:2664,2924 PipelineOptimizer.minimize splitting into
# SectionConfigs) and a runner executes them GPipe-style with one jitted
# fwd/bwd/opt function per stage pinned to its own device — the
# SectionWorker (section_worker.cc:141) with XLA functions instead of host
# threads interpreting ops, and device-to-device activation hops instead
# of scope queues.
# ---------------------------------------------------------------------------

from paddle_tpu.core.program import (BACKWARD, FORWARD, LOSS, LRSCHED,
                                     OPTIMIZE)


class _StageSection:
    """One pipeline section: its op lists and dataflow interfaces."""

    def __init__(self, idx):
        self.idx = idx
        self.fwd_ops = []
        self.bwd_ops = []
        self.opt_ops = []
        # interfaces (ordered name lists)
        self.state = []        # persistables owned by this stage
        self.feeds = []        # data vars consumed by fwd ops
        self.fwd_in = []       # activations from earlier stages
        self.fwd_out = []      # activations for later stages
        self.saved = []        # fwd-env vars the bwd ops re-read
        self.bwd_in = []       # gradients from later stages
        self.bwd_out = []      # gradients for earlier stages
        self.param_grads = []  # canonical grads consumed by opt ops
        self.shared_partials = []  # partial grads of cross-stage params
        #                            produced by this stage's bwd ops


def build_pipeline_plan(program, loss_name):
    """Assign every op a stage and compute the section interfaces.

    Forward ops carry explicit annotations (pipeline_stage ctx);
    unannotated ops inherit the max stage of their input producers
    (backward ops were pre-stamped with their forward op's stage by
    append_backward; optimizer ops land on their grad's stage)."""
    block = program.global_block()
    fwd_roles = (FORWARD, LOSS)
    loss_stage = max((op.stage or 0) for op in block.ops
                     if op.op_role in fwd_roles)
    producer = {}
    for op in block.ops:
        if op.stage is None:
            staged = [producer[n] for n in op.input_names()
                      if n in producer]
            if staged:
                op.stage = max(staged)
            elif op.op_role == BACKWARD:
                op.stage = loss_stage  # e.g. the loss-grad seed
            else:
                op.stage = 0
        for n in op.output_names():
            producer[n] = op.stage
    n_stages = max(op.stage for op in block.ops) + 1

    secs = [_StageSection(i) for i in range(n_stages)]
    lr_ops = [op for op in block.ops if op.op_role == LRSCHED]
    for op in block.ops:
        if op.op_role in fwd_roles:
            secs[op.stage].fwd_ops.append(op)
        elif op.op_role == BACKWARD:
            secs[op.stage].bwd_ops.append(op)
        elif op.op_role == OPTIMIZE:
            secs[op.stage].opt_ops.append(op)
    # lr-schedule ops replicate into every stage that optimizes
    for s in secs:
        if s.opt_ops and lr_ops:
            s.opt_ops = [OpDescCopy(o) for o in lr_ops] + s.opt_ops

    def is_persistable(n):
        return block.has_var(n) and block.var(n).persistable

    def is_data(n):
        return block.has_var(n) and block.var(n).is_data

    # A persistable READ on several stages but UPDATED only by optimizer
    # ops on one stage is a shared parameter (tied embeddings): each
    # holding stage keeps a replica, partial grads are summed across
    # stages by the runner, and the updated value is re-broadcast after
    # the optimizer apply — the reference SectionWorker's cross-section
    # param sync (section_worker.cc:30).  Any OTHER cross-stage write
    # pattern (fwd/bwd ops mutating a persistable seen elsewhere) would
    # silently desynchronize the replicas and is rejected.
    reads, writes = {}, {}
    write_roles = {}
    lrsched_written = {n for op in lr_ops for n in op.output_names()}
    for s in secs:
        for op in s.fwd_ops + s.bwd_ops + s.opt_ops:
            if op.op_role == LRSCHED:
                continue  # replicated per stage by design, copies agree
            for n in op.input_names():
                if is_persistable(n):
                    reads.setdefault(n, set()).add(s.idx)
            for n in op.output_names():
                if is_persistable(n):
                    writes.setdefault(n, set()).add(s.idx)
                    write_roles.setdefault(n, set()).add(op.op_role)
    shared = {"params": {}, "owner": {}, "grads": {}}
    for n, wstages in writes.items():
        if n in lrsched_written:
            continue
        span = wstages | reads.get(n, set())
        if len(span) <= 1:
            continue
        if write_roles[n] == {OPTIMIZE} and len(wstages) == 1:
            shared["params"][n] = sorted(span)
            shared["owner"][n] = next(iter(wstages))
            continue
        raise NotImplementedError(
            f"pipeline: persistable '{n}' is written on stage(s) "
            f"{sorted(wstages)} (roles {sorted(write_roles[n])}) but "
            f"used on stages {sorted(span)} — only optimizer-updated "
            "shared parameters may span stages; keep other state "
            "inside one pipeline_stage block")

    # For each shared param whose partial grads come from different
    # stages, the merging `sum` op (backward.py merged_grad) is
    # unrunnable in-section: within a microbatch stages step backward
    # S-1 -> 0, so an earlier stage's partial doesn't exist yet when
    # the sum's (later) stage runs.  Strip it and let the runner do
    # the cross-stage accumulation instead.
    shared_grad_names = {p + "@GRAD": p for p in shared["params"]}
    for s in secs:
        kept = []
        for op in s.bwd_ops:
            outs = op.output_names()
            if op.type == "sum" and len(outs) == 1 \
                    and outs[0] in shared_grad_names:
                parts = [(producer[n], n) for n in op.input_names()]
                if len({st for st, _ in parts}) > 1:
                    shared["grads"][outs[0]] = sorted(parts)
                    continue  # stripped: runner sums across stages
            kept.append(op)
        s.bwd_ops = kept
    for gname, parts in shared["grads"].items():
        for st, pname in parts:
            if pname not in secs[st].shared_partials:
                secs[st].shared_partials.append(pname)

    fwd_producer = {}
    for s in secs:
        for op in s.fwd_ops:
            for n in op.output_names():
                fwd_producer[n] = s.idx
    bwd_producer = {}
    for s in secs:
        for op in s.bwd_ops:
            for n in op.output_names():
                bwd_producer[n] = s.idx

    for s in secs:
        state, feeds, fwd_in = [], [], []
        fwd_local = set()
        for op in s.fwd_ops + s.bwd_ops + s.opt_ops:
            for n in op.input_names() + op.output_names():
                if is_persistable(n) and n not in state:
                    state.append(n)
        for op in s.fwd_ops:
            for n in op.input_names():
                if is_persistable(n) or n in fwd_local:
                    continue
                if is_data(n) and n not in fwd_producer:
                    if n not in feeds:
                        feeds.append(n)
                elif fwd_producer.get(n, s.idx) < s.idx:
                    if n not in fwd_in:
                        fwd_in.append(n)
            fwd_local.update(op.output_names())
        s.state, s.feeds, s.fwd_in = state, feeds, fwd_in

    for s in secs:
        consumed_later = set()
        for t in secs[s.idx + 1:]:
            for op in t.fwd_ops:
                consumed_later.update(op.input_names())
        s.fwd_out = [n for n in dict.fromkeys(
            n for op in s.fwd_ops for n in op.output_names())
            if n in consumed_later]
        # what bwd re-reads from the fwd environment of this stage
        bwd_reads = {n for op in s.bwd_ops for n in op.input_names()}
        avail = set(s.fwd_in) | set(s.feeds) | {
            n for op in s.fwd_ops for n in op.output_names()}
        s.saved = sorted((bwd_reads & avail) -
                         {n for n in bwd_reads if is_persistable(n)})
        s.bwd_in = sorted(n for n in bwd_reads
                          if bwd_producer.get(n, s.idx) > s.idx)
        consumed_earlier = set()
        for t in secs[:s.idx]:
            for op in t.bwd_ops:
                consumed_earlier.update(op.input_names())
        s.bwd_out = [n for n in dict.fromkeys(
            n for op in s.bwd_ops for n in op.output_names())
            if n in consumed_earlier]
        grad_ins = {n for op in s.opt_ops
                    for slot, names in op.inputs.items()
                    if slot == "Grad" for n in names}
        s.param_grads = sorted(grad_ins)
    return secs, loss_stage, shared


def OpDescCopy(op):
    from paddle_tpu.core.program import OpDesc

    return OpDesc.from_dict(op.to_dict())


class PipelineRunner:
    """GPipe executor over the cut sections: per-stage jitted fwd/bwd/opt
    functions, each pinned to its own device when enough exist; gradient
    accumulation over microbatches then one optimizer apply (reference
    PipelineTrainer/SectionWorker semantics)."""

    def __init__(self, program, sections, loss_stage, loss_name,
                 num_microbatches, scope, shared=None, schedule="gpipe"):
        import types

        from paddle_tpu.core.compiler import (_TraceEnv,
                                              _run_block_symbolic)

        self.program = program
        self.sections = sections
        self.loss_stage = loss_stage
        self.loss_name = loss_name
        self.M = num_microbatches
        self.scope = scope
        self.shared = shared or {"params": {}, "owner": {}, "grads": {}}
        devs = jax.devices()
        S = len(sections)
        self.devices = [devs[i % len(devs)] for i in range(S)] \
            if len(devs) > 1 else [None] * S
        self.schedule_name = schedule
        self._sched = make_pipeline_schedule(schedule, self.M, S)
        self.schedule_stats = schedule_stats(self._sched, self.M, S)
        # how many stages consume each boundary activation / gradient —
        # run() frees the buffer after its last consumer so in-flight
        # memory actually honours the schedule bound
        self._act_consumers = {}
        self._grad_consumers = {}
        for s in sections:
            for n in s.fwd_in:
                self._act_consumers[n] = \
                    self._act_consumers.get(n, 0) + 1
            for n in s.bwd_in:
                self._grad_consumers[n] = \
                    self._grad_consumers.get(n, 0) + 1

        def make_fn(ops, out_names):
            shim = types.SimpleNamespace(blocks=list(program.blocks))
            shim.blocks[0] = types.SimpleNamespace(ops=list(ops))

            def fn(env0):
                env = _TraceEnv()
                env.update(env0)
                _run_block_symbolic(shim, 0, env)
                return {n: env[n] for n in out_names if n in env}

            return jax.jit(fn)

        self._fwd = []
        self._bwd = []
        self._opt = []
        for s in sections:
            pers_out = [n for op in s.fwd_ops
                        for n in op.output_names()
                        if n in s.state]
            fwd_outs = list(dict.fromkeys(
                s.fwd_out + s.saved + pers_out +
                ([loss_name] if s.idx == loss_stage else [])))
            self._fwd.append(make_fn(s.fwd_ops, fwd_outs))
            bwd_outs = list(dict.fromkeys(
                s.bwd_out + s.param_grads + s.shared_partials))
            self._bwd.append(make_fn(s.bwd_ops, bwd_outs)
                             if s.bwd_ops else None)
            self._opt.append(make_fn(s.opt_ops, s.state)
                             if s.opt_ops else None)
        self._state = None

    def _pull_state(self):
        self._pushed = None
        self._state = []
        for s, dev in zip(self.sections, self.devices):
            st = {}
            for n in s.state:
                var = self.scope.find_var(n)
                if var is None or var.get() is None:
                    raise RuntimeError(
                        f"pipeline: persistable '{n}' uninitialized — run"
                        " the startup program first")
                v = var.get()
                st[n] = jax.device_put(v, dev) if dev is not None else v
            self._state.append(st)

    def _push_state(self):
        # remember exactly which object landed in the scope per name: a
        # shared param holds per-stage replicas (distinct device arrays
        # with equal values), and freshness must compare against the
        # one that won the push, not against every replica
        self._pushed = {}
        for st in self._state:
            for n, v in st.items():
                self.scope.var(n).set(v)
                self._pushed[n] = v

    def _state_is_fresh(self):
        """True while the scope still holds exactly the arrays we pushed;
        an external write (reloaded checkpoint, re-run startup) breaks
        identity and forces a re-pull."""
        if self._state is None:
            return False
        pushed = getattr(self, "_pushed", None)
        for s, st in zip(self.sections, self._state):
            for n in s.state:
                var = self.scope.find_var(n)
                ref = pushed[n] if pushed and n in pushed else st[n]
                if var is None or var.get() is not ref:
                    return False
        return True

    def run(self, feed, fetch_list, return_numpy=True):
        import numpy as np

        if not self._state_is_fresh():
            self._pull_state()
        M = self.M
        S = len(self.sections)
        # split feeds into microbatches along dim 0
        mb_feeds = [{} for _ in range(M)]
        for name, val in feed.items():
            arr = jnp.asarray(np.asarray(val)) \
                if not isinstance(val, jax.Array) else val
            if arr.shape[0] % M != 0:
                raise ValueError(
                    f"pipeline: batch {arr.shape[0]} not divisible by "
                    f"num_microbatches={M} (feed '{name}')")
            for m, part in enumerate(jnp.split(arr, M, axis=0)):
                mb_feeds[m][name] = part

        # schedule-driven sweep (python drives; jax async dispatch
        # pipelines the per-device work like the reference's section
        # scope-queues).  saved activations live only between F(s,m)
        # and B(s,m) — under 1f1b that bounds them at min(M, S - s)
        # sets per stage instead of M.
        saved = {}
        acts = [dict() for _ in range(M)]
        grads = [dict() for _ in range(M)]
        act_left = [dict() for _ in range(M)]
        grad_left = [dict() for _ in range(M)]
        grad_acc = [dict() for _ in range(S)]
        losses = [None] * M
        inflight, peak_inflight = [0] * S, [0] * S

        def put(v, dev):
            return jax.device_put(v, dev) if dev is not None else v

        def consume(store, left, m, n):
            v = store[m][n]
            left[m][n] -= 1
            if left[m][n] == 0:
                del store[m][n], left[m][n]
            return v

        for (s, kind, m) in self._sched:
            sec = self.sections[s]
            dev = self.devices[s]
            if kind == "F":
                env = dict(self._state[s])
                for n in sec.feeds:
                    env[n] = put(mb_feeds[m][n], dev)
                for n in sec.fwd_in:
                    env[n] = put(consume(acts, act_left, m, n), dev)
                outs = self._fwd[s](env)
                for n in sec.state:
                    if n in outs:
                        self._state[s][n] = outs[n]
                saved[(m, s)] = {n: outs[n] for n in sec.saved
                                 if n in outs}
                inflight[s] += 1
                peak_inflight[s] = max(peak_inflight[s], inflight[s])
                for n in sec.fwd_out:
                    acts[m][n] = outs[n]
                    act_left[m][n] = self._act_consumers.get(n, 1)
                if s == self.loss_stage and self.loss_name in outs:
                    losses[m] = outs[self.loss_name]
            else:
                env_saved = saved.pop((m, s), {})
                inflight[s] -= 1
                if self._bwd[s] is None:
                    continue
                env = dict(self._state[s])
                env.update(env_saved)
                for n in sec.bwd_in:
                    env[n] = put(consume(grads, grad_left, m, n), dev)
                outs = self._bwd[s](env)
                for n in sec.bwd_out:
                    grads[m][n] = outs[n]
                    grad_left[m][n] = self._grad_consumers.get(n, 1)
                for n in sec.param_grads + sec.shared_partials:
                    if n not in outs:
                        continue
                    if n in grad_acc[s]:
                        grad_acc[s][n] = grad_acc[s][n] + outs[n]
                    else:
                        grad_acc[s][n] = outs[n]
        self.last_peak_inflight = peak_inflight
        # cross-stage shared-param grads: sum the per-stage partials
        # into the canonical grad on the owner's device (the stripped
        # `sum` op from build_pipeline_plan, done where data lives)
        shared_total = {}
        for gname, parts in self.shared["grads"].items():
            owner = self.shared["owner"].get(gname[:-len("@GRAD")])
            dev = self.devices[owner] if owner is not None else None
            tot = None
            for ps, pname in parts:
                v = grad_acc[ps].pop(pname, None)
                if v is None:
                    continue
                v = put(v, dev)
                tot = v if tot is None else tot + v
            if tot is not None:
                shared_total[gname] = tot
        # optimizer apply (mean of microbatch grads == full-batch grad)
        for s, sec in enumerate(self.sections):
            if self._opt[s] is None:
                continue
            env = dict(self._state[s])
            for n, g in grad_acc[s].items():
                env[n] = g / float(M)
            for n in sec.param_grads:
                if n in shared_total:
                    env[n] = shared_total[n] / float(M)
            outs = self._opt[s](env)
            for n in sec.state:
                if n in outs:
                    self._state[s][n] = outs[n]
        # re-broadcast updated shared params to every holding stage
        # (reference SectionWorker param sync, section_worker.cc:30)
        for p, holders in self.shared["params"].items():
            owner = self.shared["owner"][p]
            val = self._state[owner].get(p)
            if val is None:
                continue
            for h in holders:
                if h != owner:
                    self._state[h][p] = put(val, self.devices[h])
        self._push_state()

        results = []
        loss_val = None
        losses = [v for v in losses if v is not None]
        if losses:
            loss_val = sum(jnp.mean(v) for v in losses) / float(len(losses))
        for f in fetch_list or []:
            name = f if isinstance(f, str) else f.name
            if name == self.loss_name and loss_val is not None:
                val = loss_val
            else:
                var = self.scope.find_var(name)
                if var is None or var.get() is None:
                    raise RuntimeError(
                        f"pipeline fetch '{name}': only the loss and "
                        "persistable state are fetchable")
                val = var.get()
            results.append(np.asarray(val) if return_numpy else val)
        return results


class PipelineOptimizer:
    """reference optimizer.py:2664 PipelineOptimizer.

    minimize() runs the inner optimizer, then CUTS the program into
    per-stage sections at `fluid.pipeline_stage(i)` annotations
    (compile-time IR surgery, like the reference's section split at
    :2924) and attaches the plan; Executor.run detects it and drives the
    GPipe section runner.  Programs with no stage annotations fall back
    to plain single-section execution."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0,
                 schedule="gpipe"):
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                             "choose 'gpipe' or '1f1b'")
        self._schedule = schedule

    @property
    def num_microbatches(self):
        return self._num_microbatches

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set,
                                          grad_clip)
        program = loss.block.program
        annotated = any(op.stage is not None
                        for op in program.global_block().ops)
        if annotated:
            sections, loss_stage, shared = build_pipeline_plan(
                program, loss.name)
            program._pipeline_opt = {
                "sections": sections,
                "loss_stage": loss_stage,
                "loss_name": loss.name,
                "num_microbatches": self._num_microbatches,
                "shared": shared,
                "schedule": self._schedule,
            }
        return result
