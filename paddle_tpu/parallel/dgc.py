"""Deep Gradient Compression as a sparse-wire mesh collective (reference
details/sparse_all_reduce_op_handle.cc:43 RunImplEncoded + dgc_op.cc +
optimizer.py:787 DGCMomentumOptimizer; paper arXiv 1712.01887).

The reference encodes each worker's top-k gradient entries and
ncclAllGather's the encoded buffers; here the same exchange is a
shard_map-level function: per-worker momentum-corrected error feedback,
top-k selection, then `lax.all_gather` of exactly (k values + k indices)
per worker — 2k elements on the ICI wire instead of the full dense
gradient — scattered back into a dense sum on every worker.  Static k
keeps every shape compile-time fixed (the XLA requirement the
reference's variable-length encode path doesn't have).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["dgc_allreduce", "dgc_compress_ratio", "dgc_top_k_count"]


def dgc_top_k_count(numel, sparsity):
    """Elements kept per worker — the ONE k formula shared with the
    dgc_momentum kernel (ops/optim.py), truncating like the reference."""
    return max(1, int(numel * (1.0 - sparsity)))


def dgc_compress_ratio(numel, sparsity):
    """Wire elements per worker (2k) / dense numel."""
    return (2 * dgc_top_k_count(numel, sparsity)) / numel


def dgc_allreduce(grad, u, v, *, sparsity=0.999, momentum=0.9,
                  axis="dp"):
    """One DGC gradient exchange step.  Call INSIDE shard_map/pjit with
    `axis` bound to the data-parallel mesh axis.

    grad: this worker's local gradient (any shape).
    u, v: error-feedback accumulators, same shape as grad (persistent
        across steps; initialize to zeros).
    Returns (avg_grad, u_new, v_new): the mean of all workers' top-k
    sparsified gradients (dense, grad's shape) and the updated
    accumulators holding the unsent residual.

    Semantics follow dgc_op.cc: u = m*u + g (momentum correction),
    v = v + u, send top-k of |v|, clear the sent entries from u and v.
    """
    shape = grad.shape
    k = dgc_top_k_count(grad.size, sparsity)

    u_flat = (momentum * u + grad).reshape(-1)
    v_flat = v.reshape(-1) + u_flat

    _, top_idx = lax.top_k(jnp.abs(v_flat), k)
    sel_vals = jnp.take(v_flat, top_idx)

    # the sparse wire: 2k elements per worker ride the ICI
    all_vals = lax.all_gather(sel_vals, axis)        # [W, k]
    all_idx = lax.all_gather(top_idx, axis)          # [W, k]
    nranks = all_vals.shape[0]
    dense_sum = jnp.zeros_like(v_flat).at[
        all_idx.reshape(-1)].add(all_vals.reshape(-1))
    avg = (dense_sum / nranks).reshape(shape)

    # error feedback: sent entries leave the accumulators
    sent = jnp.zeros_like(v_flat, dtype=bool).at[top_idx].set(True)
    u_new = jnp.where(sent, 0.0, u_flat).reshape(shape)
    v_new = jnp.where(sent, 0.0, v_flat).reshape(shape)
    return avg, u_new, v_new
