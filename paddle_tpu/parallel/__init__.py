"""Parallelism: device meshes, sharding specs, and distributed strategies.

Reference parity (re-designed, not ported — SURVEY.md §2.4):
  - ParallelExecutor multi-device DP + NCCL (framework/parallel_executor.cc)
    -> CompiledProgram.with_data_parallel: batch-sharded pjit over a Mesh.
  - DistributeTranspiler / fleet -> fleet facade over sharding rules.
  - NCCLContextMap ring ids -> mesh axis names (env.ring_axis).
"""

from paddle_tpu.parallel import env
from paddle_tpu.parallel.env import (
    ring_axis,
    register_ring,
    make_mesh,
    get_mesh,
    set_mesh,
)
