"""Parallelism: device meshes, sharding specs, and distributed strategies.

Reference parity (re-designed, not ported — SURVEY.md §2.4):
  - ParallelExecutor multi-device DP + NCCL (framework/parallel_executor.cc)
    -> CompiledProgram.with_data_parallel: batch-sharded pjit over a Mesh.
  - DistributeTranspiler / fleet -> fleet facade over sharding rules.
  - NCCLContextMap ring ids -> mesh axis names (env.ring_axis).
"""

from paddle_tpu.parallel import env
from paddle_tpu.parallel.env import (
    ring_axis,
    register_ring,
    make_mesh,
    get_mesh,
    set_mesh,
)
from paddle_tpu.parallel.ring_attention import ring_attention
from paddle_tpu.parallel.ulysses import ulysses_attention
from paddle_tpu.parallel.pipeline import (
    PipelineOptimizer,
    pipeline_apply,
    stack_stage_params,
)
from paddle_tpu.parallel.dgc import (dgc_allreduce, dgc_compress_ratio,
                                     dgc_top_k_count)
from paddle_tpu.parallel.moe import moe_ffn, switch_gating
from paddle_tpu.parallel.zero import (
    is_optimizer_accumulator,
    zero_sharding_rules,
)
from paddle_tpu.parallel.gspmd import (
    MeshPlan,
    annotate_tp_transformer,
    annotate_var,
    annotate_zero3,
    partition_spec_of,
    tag_attention_ops,
)
