"""Parallel environment: the device mesh and ring-id -> mesh-axis mapping.

Reference parity: platform/nccl_helper.h NCCLContextMap (comm per ring_id &
device) and collective_helper.h NCCLCommContext.  On TPU a "ring" is a mesh
axis; collectives compile to XLA ops riding ICI (SURVEY.md §5 "Distributed
communication backend").
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_current_mesh = None
# ring_id -> mesh axis name; ring 0 defaults to the data axis
_rings: dict = {}


def make_mesh(shape=None, axis_names=None, devices=None):
    """Build a jax.sharding.Mesh.  Default: 1-D mesh named 'dp' over all
    devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
        axis_names = axis_names or ("dp",)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    for i, name in enumerate(mesh.axis_names):
        _rings.setdefault(i, name)
    return mesh


def get_mesh():
    return _current_mesh


def register_ring(ring_id: int, axis_name: str):
    _rings[ring_id] = axis_name


def ring_axis(ring_id: int) -> Optional[str]:
    return _rings.get(ring_id)


def reset():
    global _current_mesh
    _current_mesh = None
    _rings.clear()


def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
    """Version-compat wrapper: jax.shard_map (>=0.8, check_vma) vs the old
    jax.experimental.shard_map (check_rep).  Replication checking is off —
    our kernels use explicit collectives (ppermute/psum/all_to_all)."""
    try:
        from jax import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_rep)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_rep)
