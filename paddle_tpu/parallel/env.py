"""Parallel environment: the device mesh and ring-id -> mesh-axis mapping.

Reference parity: platform/nccl_helper.h NCCLContextMap (comm per ring_id &
device) and collective_helper.h NCCLCommContext.  On TPU a "ring" is a mesh
axis; collectives compile to XLA ops riding ICI (SURVEY.md §5 "Distributed
communication backend").
"""

from __future__ import annotations

from typing import Optional

import numpy as np

_current_mesh = None
# ring_id -> mesh axis name; ring 0 defaults to the data axis
_rings: dict = {}


def make_mesh(shape=None, axis_names=None, devices=None):
    """Build a jax.sharding.Mesh.  Default: 1-D mesh named 'dp' over all
    devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
        axis_names = axis_names or ("dp",)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def make_hybrid_mesh(dcn_axes, ici_axes, devices=None):
    """Multi-slice mesh: outer axes ride DCN (between slices), inner
    axes ride ICI (within a slice) — the TPU-native replacement for the
    reference's hierarchical allreduce (platform/nccl_helper.h
    h_inter/exter_ctxs_, SURVEY.md §5): put data parallelism on the
    slow DCN axes and model/tensor axes on fast ICI, and XLA's
    collectives decompose along the hierarchy automatically.

    dcn_axes / ici_axes: {name: size} dicts (ordered).  On real
    multi-slice TPU pods the devices' slice topology drives placement
    via mesh_utils.create_hybrid_device_mesh; on a flat topology
    (CPU mesh, single slice) the same mesh is built by reshaping —
    axis semantics and sharding rules stay identical, so programs
    written against the hybrid mesh run anywhere.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(dcn_axes) + tuple(ici_axes)
    return Mesh(_hybrid_device_array(
        tuple(dcn_axes.values()), tuple(ici_axes.values()), devices,
        err_ctx=(dict(dcn_axes), dict(ici_axes))), names)


def _hybrid_device_array(dcn_shape, ici_shape, devices, err_ctx=None):
    """Device ndarray for make_hybrid_mesh, [*dcn, *ici]-shaped with
    each dcn index holding exactly one slice.  Separate from the Mesh
    wrapper so the multi-slice branch is testable with fake devices."""
    err_ctx = err_ctx or (dcn_shape, ici_shape)
    n_needed = int(np.prod(dcn_shape + ici_shape, dtype=np.int64))
    if n_needed != len(devices):
        raise ValueError(
            "hybrid mesh %s x %s needs %d devices, have %d"
            % (err_ctx[0], err_ctx[1], n_needed, len(devices)))
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    n_slices = 1 if None in slice_ids else len(slice_ids)
    if n_slices > 1:
        # real multi-slice topology: placement errors must propagate,
        # not silently degrade to a DCN-oblivious reshape
        if int(np.prod(dcn_shape, dtype=np.int64)) != n_slices:
            raise ValueError(
                "dcn axes %s (product %d) must cover the %d slices"
                % (err_ctx[0],
                   int(np.prod(dcn_shape, dtype=np.int64)), n_slices))
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh takes SAME-RANK shapes whose
        # elementwise product is the final mesh shape: pad each side
        # with 1s so every axis is purely-DCN or purely-ICI and the
        # result comes out [*dcn, *ici]-ordered directly
        ici_full = (1,) * len(dcn_shape) + ici_shape
        dcn_full = dcn_shape + (1,) * len(ici_shape)
        return mesh_utils.create_hybrid_device_mesh(
            ici_full, dcn_full, devices=devices)
    # flat topology (CPU mesh / single slice): plain reshape keeps the
    # axis semantics; only the physical placement differs
    return np.asarray(devices).reshape(dcn_shape + ici_shape)


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    for i, name in enumerate(mesh.axis_names):
        _rings.setdefault(i, name)
    return mesh


def get_mesh():
    return _current_mesh


def register_ring(ring_id: int, axis_name: str):
    _rings[ring_id] = axis_name


def ring_axis(ring_id: int) -> Optional[str]:
    return _rings.get(ring_id)


def reset():
    global _current_mesh
    _current_mesh = None
    _rings.clear()


def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
    """Version-compat wrapper: jax.shard_map (>=0.8, check_vma) vs the old
    jax.experimental.shard_map (check_rep).  Replication checking is off —
    our kernels use explicit collectives (ppermute/psum/all_to_all)."""
    try:
        from jax import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_rep)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_rep)
