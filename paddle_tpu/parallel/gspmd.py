"""GSPMD pod-scale front-end: one mesh plan, per-var PartitionSpec
annotations on the Program IR, the whole train step as ONE pjit program.

ROADMAP "New directions" #3 (ISSUE 8): today mesh parallelism lives in
hand-rolled modules (parallel/zero.py rule closures, ring_attention /
ulysses shard_map wrappers, pipeline.py schedules) stitched around the
executor, so the compiler never sees the whole step.  This module is
the spec-carrying half of the replacement:

  * ``MeshPlan`` — named dp/tp/pp axes over ``jax.sharding.Mesh``
    (SNIPPETS [1] is the pjit/partitioning exemplar; [2]/[3] the
    NamedSharding idiom).  dp carries the batch, tp carries tensor
    splits, pp places stage-stacked pipeline params; any extra axes
    (sp/ep) ride along by name.
  * annotation passes — ``annotate_zero3`` (ZeRO-3 as a sharding SPEC:
    params + optimizer state dim-sharded over dp, all-gathered at use
    sites by the XLA SPMD partitioner — the communication pattern
    DeepSpeed implements by hand) and ``annotate_tp_transformer``
    (Megatron-style column/row splits as tp PartitionSpecs on the
    existing fc layers, keyed on the transformer models' deterministic
    param-prefix name grammar).  Annotations live on
    ``VarDesc.sharding`` (serialized with the program, hashed into the
    compiled-program fingerprint).
  * ``tag_attention_ops`` — flash_attention IR ops get
    ``gspmd_batch_axis``/``gspmd_head_axis`` attrs so the Pallas
    kernel runs under shard_map on the same mesh (attention is
    independent per (batch, head) row, so the dp x tp split is exact);
    divisibility is re-checked at trace time with a plain fallback.

``transpiler.sharding_transpiler.shard_program`` consumes all of this
and emits the one jitted train step.  Everything is gated by the typed
``gspmd`` flag (default off, flag-off bit-parity asserted in
tests/test_gspmd.py).  docs/GSPMD.md has the annotation grammar.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["MeshPlan", "annotate_var", "annotate_zero3",
           "annotate_tp_transformer", "annotate_tp_inference",
           "tag_attention_ops", "partition_spec_of", "carve_slices"]


class MeshPlan:
    """Named parallel axes over a device mesh.

    ``MeshPlan(dp=4, tp=2)`` = a (4, 2) mesh with axes ("dp", "tp").
    Size-1 axes are kept (a spec naming them is a no-op shard), so the
    same annotated program runs on any plan shape.  ``pp`` places
    stage-stacked pipeline parameters (parallel/pipeline.py
    stack_stage_params layout: stage axis leading).
    """

    def __init__(self, dp=1, tp=1, pp=1, extra=None, data_axis="dp"):
        axes = {"dp": int(dp), "tp": int(tp), "pp": int(pp)}
        for name, size in (extra or {}).items():
            if name in axes:
                raise ValueError(f"duplicate mesh axis '{name}'")
            axes[name] = int(size)
        for name, size in axes.items():
            if size < 1:
                raise ValueError(f"mesh axis '{name}': size {size} < 1")
        if data_axis not in axes:
            raise ValueError(f"data_axis '{data_axis}' not an axis "
                             f"of {tuple(axes)}")
        self.axes = axes
        self.data_axis = data_axis

    # -- introspection ----------------------------------------------------
    @property
    def axis_names(self):
        return tuple(self.axes)

    @property
    def shape(self):
        return tuple(self.axes.values())

    def size(self):
        n = 1
        for s in self.axes.values():
            n *= s
        return n

    def axis_size(self, name) -> int:
        """Size of an axis; 1 for axes the plan doesn't know (a spec
        naming them still validates — it shards by a factor of 1)."""
        return int(self.axes.get(name, 1))

    def __repr__(self):
        return "MeshPlan(%s)" % ", ".join(
            f"{k}={v}" for k, v in self.axes.items())

    def __eq__(self, other):
        return isinstance(other, MeshPlan) and \
            other.axes == self.axes and other.data_axis == self.data_axis

    # -- construction -----------------------------------------------------
    @staticmethod
    def from_mesh(mesh, data_axis=None):
        plan = MeshPlan.__new__(MeshPlan)
        plan.axes = {n: int(s) for n, s in
                     zip(mesh.axis_names, mesh.devices.shape)}
        plan.data_axis = data_axis or (
            "dp" if "dp" in plan.axes else mesh.axis_names[0])
        return plan

    def to_dict(self):
        return {"axes": dict(self.axes), "data_axis": self.data_axis}

    @staticmethod
    def from_dict(d):
        plan = MeshPlan.__new__(MeshPlan)
        plan.axes = {k: int(v) for k, v in d["axes"].items()}
        plan.data_axis = d.get("data_axis", "dp")
        return plan

    def build_mesh(self, devices=None):
        """jax.sharding.Mesh with this plan's axes over ``devices``
        (default: all).  The device count must equal the plan size."""
        import jax

        from paddle_tpu.parallel import env as penv

        if devices is None:
            devices = jax.devices()
        if len(devices) != self.size():
            raise ValueError(
                f"{self!r} needs {self.size()} devices, have "
                f"{len(devices)}; size the plan to the fleet "
                "(e.g. dp = n_devices // tp)")
        return penv.make_mesh(shape=self.shape,
                              axis_names=self.axis_names,
                              devices=devices)

    def spec(self, *entries):
        """PartitionSpec from per-dim entries, validated against the
        plan's axis names."""
        from jax.sharding import PartitionSpec as P

        for e in entries:
            for a in (e if isinstance(e, (list, tuple)) else (e,)):
                if a is not None and a not in self.axes:
                    raise ValueError(
                        f"spec axis '{a}' not in {self!r}")
        return P(*entries)


# ---------------------------------------------------------------------------
# annotation passes
# ---------------------------------------------------------------------------

def annotate_var(var, spec):
    """Write a PartitionSpec-like annotation onto a VarDesc (tuple per
    dim: None | axis name | tuple of axis names)."""
    return var.set_sharding(spec)


def _shard_factor(plan, entry):
    n = 1
    for a in (entry if isinstance(entry, (list, tuple)) else (entry,)):
        if a is not None:
            n *= plan.axis_size(a)
    return n


def partition_spec_of(var, plan, shape=None) -> Optional[object]:
    """The var's annotation as a jax PartitionSpec, validated against
    the plan: unknown axes raise; a dim the spec doesn't divide evenly
    (or a spec with more dims than the shape — e.g. a sharding rule
    queried for a beta-pow [1] accumulator through the param-prefix
    inheritance) returns None (replicated) — same fallback contract as
    CompiledProgram's rule validation, decided here so the transpiler
    can report it.  ``shape`` overrides the var's declared shape (rule
    queries pass the actual array shape)."""
    if getattr(var, "sharding", None) is None:
        return None
    from jax.sharding import PartitionSpec as P

    spec = var.sharding
    shape = var.shape if shape is None else tuple(shape)
    if shape is not None and len(spec) > len(shape):
        return None
    for entry in spec:
        for a in (entry if isinstance(entry, (list, tuple))
                  else (entry,)):
            if a is not None and a not in plan.axes:
                raise ValueError(
                    f"var '{var.name}': sharding axis '{a}' not in "
                    f"{plan!r}")
    if shape is not None:
        for dim, entry in zip(shape, spec):
            n = _shard_factor(plan, entry)
            if n > 1 and (dim is None or int(dim) < 0 or
                          int(dim) % n != 0):
                return None
    return P(*spec)


def annotate_zero3(program, plan, min_size=2 ** 12, axis="dp",
                   params=True, optimizer_state=True):
    """ZeRO-3 as a sharding spec: annotate parameters (stage 3) and
    optimizer-state vars (stages 1/2 fall out of the same rule — see
    parallel/zero.py's stage notes) with ``axis`` on their first
    free, evenly-divisible dim.  Small tensors (< min_size elements:
    biases, beta-pow scalars) stay replicated — sharding them costs
    more collective latency than it saves.  Composes with existing tp
    annotations: a dim already carrying an axis is skipped, so a
    row-parallel weight P("tp", None) becomes P("tp", "dp") —
    more sharding, same math.  Returns the annotated names.

    Optimizer state is detected EXACTLY via
    parallel.zero.collect_optimizer_state (the in-place-update op
    signature), so call this after minimize(); accumulators created
    later inherit their param's annotation at _add_accumulator time.
    """
    from paddle_tpu.parallel.zero import collect_optimizer_state

    nshard = plan.axis_size(axis)
    names = set()
    if optimizer_state:
        names |= collect_optimizer_state(program)
    if params:
        names |= {v.name for v in program.all_parameters()}
    gb = program.global_block()
    param_names = sorted((v.name for v in program.all_parameters()),
                         key=len, reverse=True)
    annotated = []
    for name in sorted(names):
        var = gb.vars.get(name)
        if var is None or var.shape is None:
            continue
        size = 1
        for d in var.shape:
            size *= max(int(d), 1)
        if not var.shape or size < min_size:
            continue
        if var.sharding is None:
            # an optimizer accumulator seeds from its param's (tp)
            # layout when shapes match, so moments shard exactly like
            # the weight they update (same rule _add_accumulator
            # applies for accumulators created after annotation)
            for pn in param_names:
                if name != pn and name.startswith(pn + "_"):
                    pv = gb.vars.get(pn)
                    if pv is not None and pv.sharding is not None \
                            and pv.shape == var.shape:
                        var.set_sharding(pv.sharding)
                    break
        spec = list(var.sharding) if var.sharding else \
            [None] * len(var.shape)
        while len(spec) < len(var.shape):
            spec.append(None)
        used = {a for e in spec
                for a in (e if isinstance(e, (list, tuple)) else (e,))}
        if axis in used:
            # already dp-sharded (seeded from an annotated param): a
            # mesh axis can map to at most one dim
            annotated.append(name)
            continue
        for i, (dim, entry) in enumerate(zip(var.shape, spec)):
            if entry is None and int(dim) % nshard == 0:
                spec[i] = axis
                var.set_sharding(tuple(spec))
                annotated.append(name)
                break
    return annotated


# the transformer models' deterministic param-name grammar
# (models/transformer.py _w/_b under a param_prefix): column-parallel
# weights split the OUTPUT dim (each tp shard computes its slice of
# heads / ffn hidden), row-parallel weights split the INPUT dim and
# the partitioner all-reduces the partial products — the Megatron-LM
# attention/MLP split expressed purely as PartitionSpecs.
_TP_COL_SUFFIXES = ("_q.w", "_k.w", "_v.w", "_fc1.w")
_TP_ROW_SUFFIXES = ("_out.w", "_fc2.w")
_TP_COL_BIAS_SUFFIXES = ("_fc1.b",)


def annotate_tp_transformer(program, plan, axis="tp"):
    """Tensor-parallel PartitionSpecs on the existing transformer
    layers, keyed on the deterministic name grammar the models emit
    under a ``param_prefix`` (q/k/v/fc1 column-parallel, out/fc2
    row-parallel, fc1 bias sharded with its column).  A model built
    without a prefix (auto fc_N.w_0 names) gets no tp annotations —
    build with ``param_prefix=...`` to opt in.  Returns
    {"column": [...], "row": [...]} of annotated names."""
    nshard = plan.axis_size(axis)
    out = {"column": [], "row": []}
    if nshard <= 1:
        return out
    for var in program.global_block().vars.values():
        if not (var.persistable and var.trainable) or var.shape is None:
            continue
        name, shape = var.name, var.shape
        if len(shape) == 2:
            if name.endswith(_TP_COL_SUFFIXES) and \
                    int(shape[1]) % nshard == 0:
                var.set_sharding((None, axis))
                out["column"].append(name)
            elif name.endswith(_TP_ROW_SUFFIXES) and \
                    int(shape[0]) % nshard == 0:
                var.set_sharding((axis, None))
                out["row"].append(name)
        elif len(shape) == 1:
            if name.endswith(_TP_COL_BIAS_SUFFIXES) and \
                    int(shape[0]) % nshard == 0:
                var.set_sharding((axis,))
                out["column"].append(name)
    return out


def carve_slices(devices, slice_size):
    """Partition a flat device list into consecutive ``slice_size``
    groups — the mesh slices a sharded ReplicaPool hands one replica
    each (ISSUE 14).  Consecutive carving matters on real topologies:
    jax.devices() orders by (host, chip) so a slice stays within one
    host/ICI domain whenever the size divides it.  Leftover devices
    (len % slice_size) are unused — a partial slice can't hold the
    plan.  Raises when not even one slice fits."""
    devices = list(devices)
    slice_size = int(slice_size)
    if slice_size < 1:
        raise ValueError(f"slice_size {slice_size} < 1")
    n = len(devices) // slice_size
    if n < 1:
        raise ValueError(
            f"{len(devices)} devices cannot hold one slice of "
            f"{slice_size} (size the MeshPlan to the fleet)")
    return [devices[i * slice_size:(i + 1) * slice_size]
            for i in range(n)]


# IR ops whose output carries its input's feature sharding unchanged
# (elementwise / shape-preserving): the column-parallel chain analysis
# may look THROUGH them.  Anything else consuming a feature-sharded
# activation (softmax over the sharded dim, pooling, reshapes) is a
# gather point and de-annotates its producer.
_TP_INFER_PASSTHROUGH = ("relu", "tanh", "sigmoid", "elementwise_add",
                         "fused_elemwise_activation", "scale",
                         "dropout")
# ops that consume activations against a 2-D persistable weight
_TP_INFER_MATMUL = ("mul", "matmul", "fc")


def _infer_fc_nodes(block):
    """(op, weight_var, bias_var_or_None, out_name) per fc-shaped op
    in the block — both the raw mul(+elementwise_add bias) form and
    the ir_optim-fused ``fc`` op."""
    nodes = []
    for i, op in enumerate(block.ops):
        if op.type in ("mul", "matmul"):
            wname = op.inputs.get("Y", [None])[0]
        elif op.type == "fc":
            wname = op.inputs.get("W", [None])[0]
        else:
            continue
        if wname is None:
            continue
        w = block.vars.get(wname)
        if w is None or not w.persistable or w.shape is None or \
                len(w.shape) != 2:
            continue
        out = op.outputs["Out"][0]
        bias = None
        if op.type == "fc":
            bnames = op.inputs.get("Bias", [])
            bias = block.vars.get(bnames[0]) if bnames else None
        else:
            # the raw form: a following elementwise_add with a 1-D
            # persistable Y of the weight's output width is the bias
            for later in block.ops[i + 1:]:
                if later.type == "elementwise_add" and \
                        later.inputs.get("X", [None])[0] == out:
                    cand = block.vars.get(
                        later.inputs.get("Y", [None])[0])
                    if cand is not None and cand.persistable and \
                            cand.shape is not None and \
                            len(cand.shape) == 1 and \
                            int(cand.shape[0]) == int(w.shape[1]):
                        bias = cand
                    break
        nodes.append((op, w, bias, out))
    return nodes


def annotate_tp_inference(program, plan, axis="tp"):
    """Column-parallel tp PartitionSpecs on an INFERENCE program's fc
    layers (ISSUE 14 — the sharded serving replica): every fc-shaped
    weight (raw ``mul`` or ir_optim-fused ``fc``) whose output dim
    divides the tp axis gets ``(None, axis)`` and its bias ``(axis,)``.

    Column-ONLY on purpose: an output-dim split keeps every matmul's
    contraction full-width (XLA all-gathers the activation between
    sharded layers instead of summing partial products), so the
    sharded replica's outputs are BIT-IDENTICAL (array_equal) to the
    unsharded predictor — the serving parity contract.  The Megatron
    column/row interleave (fewer gathers, partial-sum all-reduce,
    allclose-tight) stays opt-in via ``annotate_tp_transformer``.

    The bit-exactness guarantee needs the whole downstream chain to
    hold: a sharded activation reaching an UNSHARDED matmul would make
    XLA sum partial products over the sharded contraction.  So after
    the greedy pass, any annotated weight whose output chain (through
    elementwise pass-through ops) reaches an unannotated matmul — or
    any non-pass-through consumer — is DE-annotated, to a fixpoint.
    Returns the annotated weight/bias names."""
    nshard = plan.axis_size(axis)
    if nshard <= 1:
        return []
    block = program.global_block()
    nodes = _infer_fc_nodes(block)
    sharded = {}           # weight name -> (w, bias, out)
    for op, w, bias, out in nodes:
        if int(w.shape[1]) % nshard == 0 and \
                (bias is None or int(bias.shape[0]) % nshard == 0):
            sharded[w.name] = (w, bias, out)
    matmul_weight_of = {}  # activation name -> consuming weight name
    for op, w, bias, out in nodes:
        xkey = "Input" if op.type == "fc" else "X"
        xin = op.inputs.get(xkey, [None])[0]
        if xin is not None:
            matmul_weight_of.setdefault(xin, []).append(w.name)
    consumers = {}         # var name -> [op]
    for op in block.ops:
        for names in op.inputs.values():
            for n in names:
                consumers.setdefault(n, []).append(op)

    def chain_ok(out_name, seen):
        """True iff every consumer of a feature-sharded activation is
        a sharded matmul or a pass-through whose own chain holds."""
        if out_name in seen:
            return True
        seen.add(out_name)
        for op in consumers.get(out_name, ()):
            if op.type in _TP_INFER_MATMUL:
                wkey = "W" if op.type == "fc" else "Y"
                wn = op.inputs.get(wkey, [None])[0]
                if wn not in sharded:
                    return False
            elif op.type in _TP_INFER_PASSTHROUGH:
                for onames in op.outputs.values():
                    for on in onames:
                        if not chain_ok(on, seen):
                            return False
            else:
                return False       # unknown consumer = gather point
        return True

    changed = True
    while changed:
        changed = False
        for wn in list(sharded):
            _, _, out = sharded[wn]
            if not chain_ok(out, set()):
                del sharded[wn]
                changed = True
    annotated = []
    for wn, (w, bias, _) in sorted(sharded.items()):
        w.set_sharding((None, axis))
        annotated.append(wn)
        if bias is not None:
            bias.set_sharding((axis,))
            annotated.append(bias.name)
    # static legality check at annotate time (ISSUE 15): the pass
    # above only writes divisible specs, but composed annotations
    # (a pre-annotated program re-annotated for a different plan)
    # surface here instead of at predictor trace time
    from paddle_tpu.analysis.passes import verify_enabled

    if verify_enabled():
        from paddle_tpu.analysis.shape_check import check_sharding

        check_sharding(program, plan, label="annotate_tp_inference")
    return annotated


def tag_attention_ops(program, plan, batch_axis=None, head_axis=None):
    """Stamp ``gspmd_batch_axis``/``gspmd_head_axis`` attrs on every
    flash_attention op so its Pallas kernel runs under shard_map on
    the gspmd mesh (ops/pallas_kernels.py _flash_attention_op reads
    them; Mosaic kernels can't ride XLA's automatic partitioner, and
    attention is independent per (batch, head) row so the manual
    dp x tp split is exact).  Divisibility is re-checked against the
    traced shapes at compile time with a plain single-device fallback.
    Returns the number of ops tagged."""
    batch_axis = plan.data_axis if batch_axis is None else batch_axis
    head_axis = ("tp" if "tp" in plan.axes else None) \
        if head_axis is None else head_axis
    n = 0
    for block in program.blocks:
        for op in block.ops:
            # the _grad op re-traces the forward compute under jax.vjp
            # with its OWN attrs (registry._generic_grad_def), so the
            # backward kernels ride the same shard_map iff the grad op
            # is tagged too (append_backward copied the attrs before
            # this pass ran)
            if op.type not in ("flash_attention",
                               "flash_attention_grad"):
                continue
            if batch_axis and plan.axis_size(batch_axis) > 1:
                op.set_attr("gspmd_batch_axis", batch_axis)
            if head_axis and plan.axis_size(head_axis) > 1:
                op.set_attr("gspmd_head_axis", head_axis)
            n += 1
    return n
