"""YOLOv3 detector (reference model family: PaddleCV yolov3 on fluid —
DarkNet-53 backbone + 3-scale detection heads, trained with the
yolov3_loss op (operators/detection/yolov3_loss_op.cc) and decoded with
yolo_box + multiclass_nms).

Scale-parameterized DarkNet: `depths` picks the residual-stage depths so
tests can run a tiny (1,1,1,1,1) variant; default (1,2,8,8,4) is
DarkNet-53.  The whole net is static-shape NCHW conv+bn — one XLA
program for the fwd+bwd step.
"""

from __future__ import annotations

from paddle_tpu import layers

_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45,
            59, 119, 116, 90, 156, 198, 373, 326]
_ANCHOR_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


def _conv_bn(x, ch_out, filter_size, stride, padding, act="leaky_relu",
             is_test=False):
    conv = layers.conv2d(x, num_filters=ch_out, filter_size=filter_size,
                         stride=stride, padding=padding, bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _downsample(x, ch_out, is_test=False):
    return _conv_bn(x, ch_out, 3, 2, 1, is_test=is_test)


def _basic_block(x, ch_out, is_test=False):
    c1 = _conv_bn(x, ch_out, 1, 1, 0, is_test=is_test)
    c2 = _conv_bn(c1, ch_out * 2, 3, 1, 1, is_test=is_test)
    return layers.elementwise_add(x, c2)


def _stage(x, ch_out, count, is_test=False):
    for _ in range(count):
        x = _basic_block(x, ch_out, is_test=is_test)
    return x


def _darknet(image, depths, is_test=False):
    """Returns the three pyramid features (stride 8, 16, 32)."""
    x = _conv_bn(image, 32, 3, 1, 1, is_test=is_test)
    x = _downsample(x, 64, is_test)
    x = _stage(x, 32, depths[0], is_test)
    x = _downsample(x, 128, is_test)
    x = _stage(x, 64, depths[1], is_test)
    x = _downsample(x, 256, is_test)
    c3 = _stage(x, 128, depths[2], is_test)      # stride 8
    x = _downsample(c3, 512, is_test)
    c4 = _stage(x, 256, depths[3], is_test)      # stride 16
    x = _downsample(c4, 1024, is_test)
    c5 = _stage(x, 512, depths[4], is_test)      # stride 32
    return c3, c4, c5


def _yolo_detection_block(x, ch_out, is_test=False):
    for _ in range(2):
        x = _conv_bn(x, ch_out, 1, 1, 0, is_test=is_test)
        x = _conv_bn(x, ch_out * 2, 3, 1, 1, is_test=is_test)
    route = _conv_bn(x, ch_out, 1, 1, 0, is_test=is_test)
    tip = _conv_bn(route, ch_out * 2, 3, 1, 1, is_test=is_test)
    return route, tip


def yolov3(num_classes=80, img_size=416, depths=(1, 2, 8, 8, 4),
           max_gt=50, is_test=False):
    """Build the YOLOv3 program pieces.

    Train: `loss` (sum of the three scale losses).  Test: `boxes`
    [N, P, 4] + `scores` [N, C, P] + `nmsed_out` [N, keep_top_k, 6]."""
    image = layers.data(name="image",
                        shape=[3, img_size, img_size], dtype="float32")
    c3, c4, c5 = _darknet(image, depths, is_test=is_test)

    outputs = []
    route = None
    blocks = [c5, c4, c3]
    for i, block in enumerate(blocks):
        if i > 0:
            # lateral conv widths 256, 128 (reference PaddleCV yolov3:
            # the route conv of pyramid level i-1)
            route = _conv_bn(route, 256 // (2 ** (i - 1)), 1, 1, 0,
                             is_test=is_test)
            route = layers.resize_nearest(route, scale=2.0)
            block = layers.concat([route, block], axis=1)
        route, tip = _yolo_detection_block(block, 512 // (2 ** i),
                                           is_test=is_test)
        n_anchors = len(_ANCHOR_MASKS[i])
        head = layers.conv2d(
            tip, num_filters=n_anchors * (5 + num_classes),
            filter_size=1, stride=1, padding=0)
        outputs.append(head)

    out = {"image": image, "heads": outputs}
    if is_test:
        img_size_var = layers.data(name="img_shape", shape=[2],
                                   dtype="int32")
        all_boxes, all_scores = [], []
        for i, head in enumerate(outputs):
            anchors = [a for idx in _ANCHOR_MASKS[i]
                       for a in _ANCHORS[2 * idx:2 * idx + 2]]
            boxes, scores = layers.yolo_box(
                head, img_size_var, anchors=anchors,
                class_num=num_classes, conf_thresh=0.005,
                downsample_ratio=32 // (2 ** i))
            all_boxes.append(boxes)
            all_scores.append(layers.transpose(scores, perm=[0, 2, 1]))
        boxes = layers.concat(all_boxes, axis=1)
        scores = layers.concat(all_scores, axis=2)
        out["boxes"] = boxes
        out["scores"] = scores
        out["img_shape"] = img_size_var
        out["nmsed_out"] = layers.multiclass_nms(
            boxes, scores, score_threshold=0.01, nms_threshold=0.45,
            background_label=-1)
    else:
        gt_box = layers.data(name="gt_box", shape=[max_gt, 4],
                             dtype="float32")
        gt_label = layers.data(name="gt_label", shape=[max_gt],
                               dtype="int64")
        losses = []
        for i, head in enumerate(outputs):
            per_image = layers.yolov3_loss(
                head, gt_box, gt_label, anchors=_ANCHORS,
                anchor_mask=_ANCHOR_MASKS[i], class_num=num_classes,
                ignore_thresh=0.7, downsample_ratio=32 // (2 ** i))
            losses.append(layers.mean(per_image))
        out["gt_box"] = gt_box
        out["gt_label"] = gt_label
        out["loss"] = layers.sums(losses)
    return out
