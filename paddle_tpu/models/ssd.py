"""MobileNet-flavoured SSD detector (reference model family:
PaddlePaddle models/PaddleCV ssd/mobilenet_ssd.py built on
fluid layers multi_box_head :1737 + ssd_loss + detection_output —
the SSD paper's architecture over depthwise-separable conv blocks).

Exercises the detection zoo end to end THROUGH the IR: conv/depthwise
conv/bn backbone, multi_box_head prior+head conv pyramid, ssd_loss for
training and detection_output (box_coder + multiclass_nms) for
inference — all compiled as one XLA program.

`ssd_mobilenet(...)` is scale-parameterized so tests run a tiny config
(image 64, scale 0.25) while the full 300x300 model is the default.
"""

from __future__ import annotations

from paddle_tpu import layers


def _conv_bn(x, num_filters, filter_size, stride, padding, num_groups=1,
             act="relu", is_test=False):
    conv = layers.conv2d(
        input=x, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=padding, groups=num_groups,
        bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _depthwise_separable(x, num_filters1, num_filters2, num_groups,
                         stride, scale, is_test=False):
    """MobileNet block: depthwise 3x3 + pointwise 1x1."""
    dw = _conv_bn(x, int(num_filters1 * scale), 3, stride, 1,
                  num_groups=int(num_groups * scale), is_test=is_test)
    return _conv_bn(dw, int(num_filters2 * scale), 1, 1, 0,
                    is_test=is_test)


def _extra_block(x, num_filters1, num_filters2, scale, is_test=False):
    """SSD extra feature block: 1x1 squeeze + 3x3 stride-2."""
    p = _conv_bn(x, int(num_filters1 * scale), 1, 1, 0, is_test=is_test)
    return _conv_bn(p, int(num_filters2 * scale), 3, 2, 1,
                    is_test=is_test)


def ssd_mobilenet(num_classes=21, img_shape=(3, 300, 300), scale=1.0,
                  max_gt=50, is_test=False):
    """Build the SSD program pieces.

    Returns dict with image/gt inputs, per-image train `loss`, and the
    inference `nmsed_out` [N, keep_top_k, 6] detections."""
    c, h, w = img_shape
    image = layers.data(name="image", shape=[c, h, w], dtype="float32")

    # MobileNet backbone (conv1 + 13 depthwise blocks)
    tmp = _conv_bn(image, int(32 * scale), 3, 2, 1, is_test=is_test)
    tmp = _depthwise_separable(tmp, 32, 64, 32, 1, scale, is_test)
    tmp = _depthwise_separable(tmp, 64, 128, 64, 2, scale, is_test)
    tmp = _depthwise_separable(tmp, 128, 128, 128, 1, scale, is_test)
    tmp = _depthwise_separable(tmp, 128, 256, 128, 2, scale, is_test)
    tmp = _depthwise_separable(tmp, 256, 256, 256, 1, scale, is_test)
    tmp = _depthwise_separable(tmp, 256, 512, 256, 2, scale, is_test)
    for _ in range(5):
        tmp = _depthwise_separable(tmp, 512, 512, 512, 1, scale, is_test)
    module11 = tmp                                   # stride 16 map
    tmp = _depthwise_separable(tmp, 512, 1024, 512, 2, scale, is_test)
    module13 = _depthwise_separable(tmp, 1024, 1024, 1024, 1, scale,
                                    is_test)         # stride 32 map
    module14 = _extra_block(module13, 256, 512, scale, is_test)
    module15 = _extra_block(module14, 128, 256, scale, is_test)
    module16 = _extra_block(module15, 128, 256, scale, is_test)
    module17 = _extra_block(module16, 64, 128, scale, is_test)

    feats = [module11, module13, module14, module15, module16, module17]
    mbox_locs, mbox_confs, box, box_var = layers.multi_box_head(
        inputs=feats, image=image, num_classes=num_classes,
        base_size=h,
        min_ratio=20, max_ratio=90,
        aspect_ratios=[[2.0], [2.0, 3.0], [2.0, 3.0], [2.0, 3.0],
                       [2.0, 3.0], [2.0, 3.0]],
        offset=0.5, flip=True)

    out = {"image": image, "locs": mbox_locs, "confs": mbox_confs,
           "box": box, "box_var": box_var, "feats": feats}

    if is_test:
        # detection_output wants scores [N, C, P]
        scores = layers.transpose(
            layers.softmax(mbox_confs), perm=[0, 2, 1])
        out["nmsed_out"] = layers.detection_output(
            mbox_locs, scores, box, box_var,
            nms_threshold=0.45, background_label=0)
    else:
        gt_box = layers.data(name="gt_box", shape=[max_gt, 4],
                             dtype="float32")
        gt_label = layers.data(name="gt_label", shape=[max_gt, 1],
                               dtype="int64")
        per_image = layers.ssd_loss(mbox_locs, mbox_confs, gt_box,
                                    gt_label, box, box_var)
        out["gt_box"] = gt_box
        out["gt_label"] = gt_label
        out["loss"] = layers.mean(per_image)
    return out
