"""ResNet for ImageNet/CIFAR (reference model:
/root/reference/python/paddle/fluid/tests/book/test_image_classification.py
resnet_cifar10 and the fluid image_classification ResNet-50 config used by
BASELINE config 2).

NCHW layout, conv+bn blocks; bf16-friendly (cast input, fp32 master params
handled by the AMP decorator when enabled).
"""

from __future__ import annotations

from paddle_tpu import layers


def _conv_bn(x, num_filters, filter_size, stride=1, groups=1, act=None,
             is_test=False):
    conv = layers.conv2d(
        input=x, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _shortcut(x, ch_out, stride, is_test=False):
    ch_in = x.shape[1]
    if ch_in != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, is_test=is_test)
    return x


def _bottleneck(x, num_filters, stride, is_test=False):
    conv0 = _conv_bn(x, num_filters, 1, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, stride, act="relu",
                     is_test=is_test)
    conv2 = _conv_bn(conv1, num_filters * 4, 1, is_test=is_test)
    short = _shortcut(x, num_filters * 4, stride, is_test=is_test)
    return layers.elementwise_add(short, conv2, act="relu")


def _basic_block(x, num_filters, stride, is_test=False):
    conv0 = _conv_bn(x, num_filters, 3, stride, act="relu",
                     is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, is_test=is_test)
    short = _shortcut(x, num_filters, stride, is_test=is_test)
    return layers.elementwise_add(short, conv1, act="relu")


_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def resnet(depth=50, num_classes=1000, image_shape=(3, 224, 224),
           is_test=False, with_data_vars=True, image=None, label=None):
    block_type, counts = _DEPTH_CFG[depth]
    block = _bottleneck if block_type == "bottleneck" else _basic_block
    if image is None:
        image = layers.data("image", shape=list(image_shape),
                            dtype="float32")
    if label is None:
        label = layers.data("label", shape=[1], dtype="int64")
    x = _conv_bn(image, 64, 7, stride=2, act="relu", is_test=is_test)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    num_filters = [64, 128, 256, 512]
    for stage, count in enumerate(counts):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            x = block(x, num_filters[stage], stride, is_test=is_test)
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    logits = layers.fc(pool, size=num_classes)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return {"image": image, "label": label, "logits": logits,
            "loss": loss, "acc": acc}


def resnet50(**kwargs):
    return resnet(depth=50, **kwargs)


def resnet_cifar10(depth=32, num_classes=10, image_shape=(3, 32, 32),
                   is_test=False):
    """The classic CIFAR ResNet (reference resnet_cifar10,
    tests/book/test_image_classification.py:28 — also the ResNet32 row
    of contrib/float16/float16_benchmark.md:72-74): 3x3/16ch stem, three
    stages of (depth-2)/6 basic blocks at widths 16/32/64 with strides
    1/2/2, global average pool, fc head."""
    if (depth - 2) % 6 != 0:
        raise ValueError("cifar resnet depth must be 6n+2, got %d"
                         % depth)
    n = (depth - 2) // 6
    image = layers.data("image", shape=list(image_shape),
                        dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    x = _conv_bn(image, 16, 3, act="relu", is_test=is_test)
    for stage, width in enumerate((16, 32, 64)):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            x = _basic_block(x, width, stride, is_test=is_test)
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True)
    logits = layers.fc(pool, size=num_classes)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return {"image": image, "label": label, "logits": logits,
            "loss": loss, "acc": acc}
