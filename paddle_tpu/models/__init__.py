"""Model zoo mirroring the reference workload ladder (BASELINE.md):
MNIST MLP, ResNet-50, Transformer-base, BERT-base, DeepFM CTR, plus the
detection family (MobileNet-SSD, YOLOv3) exercising the detection zoo
through the IR.

Each builder constructs the IR into the current default programs and returns
the relevant vars; shapes/hyperparams follow the reference model configs
(e.g. /root/reference/python/paddle/fluid/tests/unittests/dist_mnist.py,
dist_se_resnext.py, dist_transformer.py, dist_ctr.py).
"""

from paddle_tpu.models.mlp import mnist_mlp
from paddle_tpu.models.resnet import resnet, resnet50
from paddle_tpu.models.transformer import transformer_encoder_model
from paddle_tpu.models.bert import bert_model
from paddle_tpu.models.deepfm import deepfm_model
from paddle_tpu.models.ssd import ssd_mobilenet
from paddle_tpu.models.yolov3 import yolov3
from paddle_tpu.models.vgg import vgg, vgg16
from paddle_tpu.models.se_resnext import se_resnext
