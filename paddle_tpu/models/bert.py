"""BERT-base pretraining model (BASELINE config 4: fused embedding +
seq-512, masked-LM + next-sentence-prediction heads; reference analog:
fused_embedding_seq_pool + adam_op workloads)."""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers
from paddle_tpu.models.transformer import (
    _ffn,
    _residual_norm,
    multi_head_attention,
)


def bert_model(
    vocab_size=30522, max_len=512, d_model=768, n_head=12, d_inner=3072,
    n_layer=12, type_vocab_size=2, dropout_rate=0.1, is_test=False,
):
    src = layers.data("src_ids", shape=[max_len, 1], dtype="int64")
    pos = layers.data("pos_ids", shape=[max_len, 1], dtype="int64")
    sent = layers.data("sent_ids", shape=[max_len, 1], dtype="int64")
    mask_pos = layers.data("mask_pos", shape=[max_len, 1], dtype="int64")
    mask_label = layers.data("mask_label", shape=[max_len, 1],
                             dtype="int64")
    mask_weight = layers.data("mask_weight", shape=[max_len, 1],
                              dtype="float32")
    nsp_label = layers.data("nsp_label", shape=[1], dtype="int64")

    emb = layers.embedding(src, size=[vocab_size, d_model])
    pos_emb = layers.embedding(pos, size=[max_len, d_model])
    sent_emb = layers.embedding(sent, size=[type_vocab_size, d_model])
    x = layers.elementwise_add(
        layers.elementwise_add(emb, pos_emb), sent_emb)
    x = layers.layer_norm(x, begin_norm_axis=2)
    if dropout_rate and not is_test:
        x = layers.dropout(x, dropout_rate,
                           dropout_implementation="upscale_in_train")
    for _ in range(n_layer):
        attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                    is_test=is_test)
        x = _residual_norm(x, attn, dropout_rate, is_test)
        ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test)
        x = _residual_norm(x, ffn, dropout_rate, is_test)

    # masked-LM head: gather masked positions per batch row
    mlm_h = layers.fc(x, d_model, num_flatten_dims=2, act="gelu")
    mlm_h = layers.layer_norm(mlm_h, begin_norm_axis=2)
    mlm_logits = layers.fc(mlm_h, vocab_size, num_flatten_dims=2,
                           bias_attr=False)
    # mask_pos selects positions: use one_hot matmul-free gather via
    # take_along on time axis (gather per row)
    mlm_sel = _gather_time(mlm_logits, mask_pos, max_len)
    mlm_loss_tok = layers.softmax_with_cross_entropy(mlm_sel, mask_label)
    weighted = layers.elementwise_mul(mlm_loss_tok, mask_weight)
    mlm_loss = layers.elementwise_div(
        layers.reduce_sum(weighted),
        layers.elementwise_add(layers.reduce_sum(mask_weight),
                               layers.fill_constant([], "float32", 1e-6)))

    # NSP head on [CLS]
    cls = layers.slice(x, axes=[1], starts=[0], ends=[1])
    cls = layers.reshape(cls, [-1, d_model])
    pooled = layers.fc(cls, d_model, act="tanh")
    nsp_logits = layers.fc(pooled, 2)
    nsp_loss = layers.mean(
        layers.softmax_with_cross_entropy(nsp_logits, nsp_label))

    loss = layers.elementwise_add(mlm_loss, nsp_loss)
    return {"src_ids": src, "pos_ids": pos, "sent_ids": sent,
            "mask_pos": mask_pos, "mask_label": mask_label,
            "mask_weight": mask_weight, "nsp_label": nsp_label,
            "loss": loss, "mlm_loss": mlm_loss, "nsp_loss": nsp_loss}


def _gather_time(x, idx, t):
    """x: [B, T, V]; idx: [B, T, 1] int64 positions -> [B, T, V] rows
    gathered along time (static-shape take_along_axis built from one_hot
    matmul — MXU-friendly, no dynamic gather)."""
    sel = layers.one_hot(idx, t)            # [B, T, T]
    return layers.matmul(sel, x)            # [B, T, V]


def bert_inputs_synthetic(batch, max_len=512, vocab_size=30522, seed=0):
    rng = np.random.RandomState(seed)
    n_mask = max(1, max_len // 7)
    mask_weight = np.zeros((batch, max_len, 1), np.float32)
    mask_weight[:, :n_mask] = 1.0
    return {
        "src_ids": rng.randint(0, vocab_size,
                               (batch, max_len, 1)).astype(np.int64),
        "pos_ids": np.tile(np.arange(max_len)[None, :, None],
                           (batch, 1, 1)).astype(np.int64),
        "sent_ids": np.zeros((batch, max_len, 1), np.int64),
        "mask_pos": rng.randint(0, max_len,
                                (batch, max_len, 1)).astype(np.int64),
        "mask_label": rng.randint(0, vocab_size,
                                  (batch, max_len, 1)).astype(np.int64),
        "mask_weight": mask_weight,
        "nsp_label": rng.randint(0, 2, (batch, 1)).astype(np.int64),
    }
