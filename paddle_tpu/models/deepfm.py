"""DeepFM CTR model (BASELINE config 5; reference analog:
tests/unittests/dist_ctr.py + ctr_dnn models with sparse lookup_table)."""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers


def deepfm_model(num_fields=26, vocab_size=100_000, embed_dim=16,
                 dense_dim=13, hidden=(400, 400, 400), is_test=False,
                 is_sparse=True, is_distributed=False):
    """is_distributed=True marks the tables for pserver sharding: the
    DistributeTranspiler replaces their lookups with prefetch RPCs and
    their grads with sparse rows/values pushes (see
    transpiler/distribute_transpiler.py _plan_dist_tables)."""
    sparse_ids = layers.data("sparse_ids", shape=[num_fields, 1],
                             dtype="int64")
    dense_x = layers.data("dense_x", shape=[dense_dim], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")

    # shared embedding table; field-wise lookup [B, F, E]
    emb = layers.embedding(sparse_ids, size=[vocab_size, embed_dim],
                           is_sparse=is_sparse,
                           is_distributed=is_distributed)

    # first-order terms
    first = layers.embedding(sparse_ids, size=[vocab_size, 1],
                             is_sparse=is_sparse,
                             is_distributed=is_distributed)
    first_sum = layers.reduce_sum(first, dim=[1, 2], keep_dim=False)
    first_sum = layers.reshape(first_sum, [-1, 1])

    # FM second-order: 0.5 * ((sum v)^2 - sum v^2)
    sum_emb = layers.reduce_sum(emb, dim=[1])            # [B, E]
    sum_sq = layers.square(sum_emb)
    sq_emb = layers.square(emb)
    sq_sum = layers.reduce_sum(sq_emb, dim=[1])
    fm = layers.scale(layers.elementwise_sub(sum_sq, sq_sum), scale=0.5)
    fm = layers.reduce_sum(fm, dim=[1], keep_dim=True)   # [B, 1]

    # deep part
    deep_in = layers.concat(
        [layers.reshape(emb, [-1, num_fields * embed_dim]), dense_x],
        axis=1)
    h = deep_in
    for width in hidden:
        h = layers.fc(h, size=width, act="relu")
    deep_out = layers.fc(h, size=1)

    logits = layers.elementwise_add(
        layers.elementwise_add(first_sum, fm), deep_out)
    predict = layers.sigmoid(logits)
    loss = layers.mean(layers.sigmoid_cross_entropy_with_logits(
        logits, layers.cast(label, "float32")))
    return {"sparse_ids": sparse_ids, "dense_x": dense_x, "label": label,
            "predict": predict, "loss": loss}


def deepfm_inputs_synthetic(batch, num_fields=26, vocab_size=100_000,
                            dense_dim=13, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "sparse_ids": rng.randint(
            0, vocab_size, (batch, num_fields, 1)).astype(np.int64),
        "dense_x": rng.rand(batch, dense_dim).astype(np.float32),
        "label": rng.randint(0, 2, (batch, 1)).astype(np.int64),
    }
