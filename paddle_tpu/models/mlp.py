"""MNIST MLP (reference: tests/book/test_recognize_digits.py mlp net)."""

from __future__ import annotations

from paddle_tpu import layers


def mnist_mlp(hidden=(128, 64), num_classes=10, img_dim=784):
    img = layers.data("img", shape=[img_dim], dtype="float32")
    label = layers.data("label", shape=[1], dtype="int64")
    h = img
    for width in hidden:
        h = layers.fc(h, size=width, act="relu")
    logits = layers.fc(h, size=num_classes)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return {"img": img, "label": label, "logits": logits, "loss": loss,
            "acc": acc}
