"""Transformer (reference model: the fluid transformer NMT config used by
tests/unittests/dist_transformer.py; BASELINE config 3 Transformer-base).

Built entirely from IR layers (matmul/softmax/layer_norm/fc) so the program
compiles to one XLA module; attention is batched [B, H, T, D/H] matmuls that
XLA tiles onto the MXU.  Sharding-friendly: the fc weights carry optional
tensor-parallel annotations set by parallel/strategies.py.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers
from paddle_tpu.param_attr import ParamAttr as _ParamAttr


def _w(pfx, part):
    """Deterministic weight name under a prefix (None -> auto names).
    Explicit names let a separately-built program (e.g. the KV-cache
    decode loop) share this model's trained parameters through the
    scope, the fluid ParamAttr(name=...) sharing idiom."""
    return _ParamAttr(name=f"{pfx}_{part}.w") if pfx else None


def _b(pfx, part):
    return _ParamAttr(name=f"{pfx}_{part}.b") if pfx else None


def _sub(pfx):
    """Sub-prefix builder: _sub(\"tfm_enc0\")(\"self\") -> \"tfm_enc0_self\";
    a None prefix propagates None (auto names)."""
    return (lambda s: f"{pfx}_{s}") if pfx else (lambda s: None)


def _positional_encoding(max_len, d_model, dtype="float32"):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.zeros((max_len, d_model), np.float64)
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc.astype(dtype)


def multi_head_attention(q_in, kv_in, d_model, n_head, dropout_rate=0.0,
                         causal=False, is_test=False, seq_len_q=None,
                         seq_len_kv=None, name=None, use_flash=True,
                         pfx=None, attn_bias=None):
    """q_in: [B, Tq, D]; kv_in: [B, Tk, D].

    When attention-weight dropout is off the score+softmax+weighted-sum is
    emitted as one fused `flash_attention` op (Pallas kernel on TPU) —
    the [Tq, Tk] matrix never touches HBM.  With weight dropout on, the
    unfused composition is kept so the reference's dropout-on-weights
    semantics hold exactly.

    attn_bias: optional additive score bias broadcastable to
    [B, H, Tq, Tk] (e.g. a [B, 1, 1, Tk] source-padding mask, the
    reference NMT decoders' LoD-derived attention bias); forces the
    unfused composition.
    """
    tq = q_in.shape[1]
    tk = kv_in.shape[1]
    head_dim = d_model // n_head
    q = layers.fc(q_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_w(pfx, "q"))
    k = layers.fc(kv_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_w(pfx, "k"))
    v = layers.fc(kv_in, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_w(pfx, "v"))

    q = _split_heads(q, tq, n_head, head_dim)
    k = _split_heads(k, tk, n_head, head_dim)
    v = _split_heads(v, tk, n_head, head_dim)
    weight_dropout = bool(dropout_rate) and not is_test
    if use_flash and not weight_dropout and attn_bias is None:
        out = layers.flash_attention(q, k, v, causal=causal)
    else:
        attn = layers.matmul(q, k, transpose_y=True,
                             alpha=float(head_dim) ** -0.5)  # [B,H,Tq,Tk]
        if causal:
            # bottom-right aligned (query i attends keys <= i + Tk - Tq),
            # matching the flash kernel's q_off convention
            mask = np.triu(np.full((tq, tk), -1e9, np.float32),
                           k=1 + tk - tq)
            mask_var = layers.assign(mask.reshape(1, 1, tq, tk))
            attn = layers.elementwise_add(attn, mask_var)
        if attn_bias is not None:
            attn = layers.elementwise_add(attn, attn_bias)
        weights = layers.softmax(attn)
        if weight_dropout:
            weights = layers.dropout(
                weights, dropout_rate,
                dropout_implementation="upscale_in_train")
        out = layers.matmul(weights, v)  # [B,H,Tq,hd]

    out = layers.transpose(out, [0, 2, 1, 3])
    out = layers.reshape(out, [-1, tq, d_model])
    return layers.fc(out, d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=_w(pfx, "out"))


def _ffn(x, d_model, d_inner, dropout_rate, is_test, pfx=None):
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu",
                  param_attr=_w(pfx, "fc1"), bias_attr=_b(pfx, "fc1"))
    if dropout_rate and not is_test:
        h = layers.dropout(h, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2,
                     param_attr=_w(pfx, "fc2"), bias_attr=_b(pfx, "fc2"))


def _residual_norm(x, sub, dropout_rate, is_test, pfx=None):
    if dropout_rate and not is_test:
        sub = layers.dropout(sub, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return layers.layer_norm(
        layers.elementwise_add(x, sub), begin_norm_axis=2,
        param_attr=(_ParamAttr(name=f"{pfx}.scale") if pfx else None),
        bias_attr=(_ParamAttr(name=f"{pfx}.bias") if pfx else None))


def encoder_layer(x, d_model, n_head, d_inner, dropout_rate=0.1,
                  is_test=False, pfx=None, attn_bias=None):
    sp = _sub(pfx)
    attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                is_test=is_test, pfx=sp("self"),
                                attn_bias=attn_bias)
    x = _residual_norm(x, attn, dropout_rate, is_test, pfx=sp("ln1"))
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
               pfx=sp("ffn"))
    return _residual_norm(x, ffn, dropout_rate, is_test, pfx=sp("ln2"))


def decoder_layer(x, enc_out, d_model, n_head, d_inner, dropout_rate=0.1,
                  is_test=False, pfx=None, cross_attn_bias=None):
    sp = _sub(pfx)
    self_attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                     causal=True, is_test=is_test,
                                     pfx=sp("self"))
    x = _residual_norm(x, self_attn, dropout_rate, is_test,
                       pfx=sp("ln1"))
    cross = multi_head_attention(x, enc_out, d_model, n_head,
                                 dropout_rate, is_test=is_test,
                                 pfx=sp("cross"),
                                 attn_bias=cross_attn_bias)
    x = _residual_norm(x, cross, dropout_rate, is_test, pfx=sp("ln2"))
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
               pfx=sp("ffn"))
    return _residual_norm(x, ffn, dropout_rate, is_test, pfx=sp("ln3"))


def _embed(ids, vocab_size, d_model, max_len, dropout_rate, is_test,
           scale_embedding=True, pfx=None):
    emb = layers.embedding(
        ids, size=[vocab_size, d_model],
        param_attr=(_ParamAttr(name=f"{pfx}.w") if pfx else None))
    if scale_embedding:
        emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    pe = layers.assign(
        _positional_encoding(max_len, d_model)[None, :, :])
    emb = layers.elementwise_add(emb, pe)
    if dropout_rate and not is_test:
        emb = layers.dropout(emb, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return emb


def transformer_encoder_model(
    vocab_size=32000, max_len=256, d_model=512, n_head=8, d_inner=2048,
    n_layer=6, dropout_rate=0.1, is_test=False, tie_embeddings=False,
    label_smooth_eps=0.0, param_prefix=None,
):
    """Encoder-only LM-style transformer: next-token prediction over a
    single stream (the flagship shape for bench/graft entry; the NMT
    encoder-decoder variant is `transformer_nmt_model`).  param_prefix:
    deterministic parameter names so `transformer_lm_sample_decode`
    shares the trained weights by name."""
    p = param_prefix
    sp = _sub(p)
    src = layers.data("src_ids", shape=[max_len, 1], dtype="int64")
    label = layers.data("tgt_label", shape=[max_len, 1], dtype="int64")
    x = _embed(src, vocab_size, d_model, max_len, dropout_rate, is_test,
               pfx=sp("emb"))
    # causal self-attention stack
    for li in range(n_layer):
        lp = _sub(sp(f"l{li}"))
        attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                    causal=True, is_test=is_test,
                                    pfx=lp("self"))
        x = _residual_norm(x, attn, dropout_rate, is_test,
                           pfx=lp("ln1"))
        ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test,
                   pfx=lp("ffn"))
        x = _residual_norm(x, ffn, dropout_rate, is_test, pfx=lp("ln2"))
    logits = layers.fc(x, vocab_size, num_flatten_dims=2,
                       bias_attr=False, param_attr=_w(p, "out_fc"))
    if label_smooth_eps:
        one_hot = layers.one_hot(label, vocab_size)
        smoothed = layers.label_smooth(one_hot, epsilon=label_smooth_eps)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            logits, smoothed, soft_label=True))
    else:
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
    return {"src_ids": src, "tgt_label": label, "logits": logits,
            "loss": loss}


def _src_pad_bias(src, max_len, pad_id):
    """[B, T, 1] int64 ids -> [B, 1, 1, T] additive attention bias:
    -1e9 on padding positions, 0 elsewhere (the reference NMT models'
    LoD-derived src_slf/src_attn bias, e.g.
    tests/unittests/dist_transformer.py pad-mask construction)."""
    ids = layers.reshape(src, [-1, max_len])
    pad = layers.fill_constant([1], "int64", float(pad_id))
    is_pad = layers.cast(layers.equal(ids, pad), "float32")
    return layers.reshape(layers.scale(is_pad, scale=-1e9),
                          [-1, 1, 1, max_len])


def transformer_nmt_model(
    src_vocab_size=32000, tgt_vocab_size=32000, max_len=256, d_model=512,
    n_head=8, d_inner=2048, n_layer=6, dropout_rate=0.1, is_test=False,
    param_prefix=None, use_src_pad_mask=False, pad_id=0,
):
    """Encoder-decoder NMT transformer (Transformer-base when defaults).

    param_prefix: when set, every parameter gets a deterministic name
    under the prefix so a separately-built program — the KV-cache
    `transformer_nmt_greedy_decode` loop — shares the trained weights
    through the scope.

    use_src_pad_mask: mask `pad_id` source positions out of encoder
    self-attention and decoder cross-attention with a -1e9 score bias,
    so variable-length padded batches don't attend padding.  Pass the
    same flag to the decode builders to keep train/decode parity."""
    p = param_prefix
    sp = _sub(p)
    src = layers.data("src_ids", shape=[max_len, 1], dtype="int64")
    tgt = layers.data("tgt_ids", shape=[max_len, 1], dtype="int64")
    label = layers.data("tgt_label", shape=[max_len, 1], dtype="int64")
    src_bias = _src_pad_bias(src, max_len, pad_id) \
        if use_src_pad_mask else None
    enc = _embed(src, src_vocab_size, d_model, max_len, dropout_rate,
                 is_test, pfx=sp("src_emb"))
    for li in range(n_layer):
        enc = encoder_layer(enc, d_model, n_head, d_inner, dropout_rate,
                            is_test, pfx=sp(f"enc{li}"),
                            attn_bias=src_bias)
    dec = _embed(tgt, tgt_vocab_size, d_model, max_len, dropout_rate,
                 is_test, pfx=sp("tgt_emb"))
    for li in range(n_layer):
        dec = decoder_layer(dec, enc, d_model, n_head, d_inner,
                            dropout_rate, is_test, pfx=sp(f"dec{li}"),
                            cross_attn_bias=src_bias)
    logits = layers.fc(dec, tgt_vocab_size, num_flatten_dims=2,
                       bias_attr=False, param_attr=_w(p, "out_fc"))
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return {"src_ids": src, "tgt_ids": tgt, "tgt_label": label,
            "logits": logits, "loss": loss}


def _split_heads(x, t, n_head, head_dim):
    x = layers.reshape(x, [-1, t, n_head, head_dim])
    return layers.transpose(x, [0, 2, 1, 3])          # [B, H, T, hd]


def _decode_encoder(p, src_vocab_size, max_len, d_model, n_head,
                    d_inner, n_layer, use_src_pad_mask=False, pad_id=0):
    """Encoder pass for the decode builders + per-layer cross-attention
    K/V, computed ONCE outside the decode loop (the KV-cache trick's
    encoder half) with the weight names the training build gave these
    fc's.  Returns (src data var, [(enc_k, enc_v)] per layer,
    each [B, H, Tsrc, hd], src_bias [B, 1, 1, Tsrc] or None)."""
    hd = d_model // n_head
    src = layers.data("src_ids", shape=[max_len, 1], dtype="int64")
    src_bias = _src_pad_bias(src, max_len, pad_id) \
        if use_src_pad_mask else None
    enc = _embed(src, src_vocab_size, d_model, max_len, 0.0, True,
                 pfx=f"{p}_src_emb")
    for li in range(n_layer):
        enc = encoder_layer(enc, d_model, n_head, d_inner, 0.0, True,
                            pfx=f"{p}_enc{li}", attn_bias=src_bias)
    cross_kv = []
    for li in range(n_layer):
        ck = layers.fc(enc, d_model, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=_w(f"{p}_dec{li}_cross", "k"))
        cv = layers.fc(enc, d_model, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=_w(f"{p}_dec{li}_cross", "v"))
        cross_kv.append((_split_heads(ck, max_len, n_head, hd),
                         _split_heads(cv, max_len, n_head, hd)))
    return src, cross_kv, src_bias


def _cache_attention(q, kc, vc, pos, kpos, decode_len, n_head, hd):
    """Single-query attention against a [T, N, D] cache: positions
    beyond the current step hold zeros and are masked off."""
    q_h = _split_heads(q, 1, n_head, hd)                  # [N, H, 1, hd]
    ck = layers.transpose(layers.reshape(
        kc, [decode_len, -1, n_head, hd]), [1, 2, 0, 3])
    cv = layers.transpose(layers.reshape(
        vc, [decode_len, -1, n_head, hd]), [1, 2, 0, 3])
    s = layers.matmul(q_h, ck, transpose_y=True,
                      alpha=float(hd) ** -0.5)            # [N, H, 1, T]
    valid = layers.cast(layers.less_equal(kpos, pos), "float32")
    s = layers.elementwise_add(s, layers.reshape(
        layers.scale(valid, scale=1e9, bias=-1e9),
        [1, 1, 1, decode_len]))
    o = layers.matmul(layers.softmax(s), cv)              # [N, H, 1, hd]
    return layers.reshape(layers.transpose(o, [0, 2, 1, 3]),
                          [-1, 1, hd * n_head])


def _decode_step(cur, pos, caches, cross_kv, p, tgt_vocab_size,
                 decode_len, d_model, n_head, d_inner, n_layer, kpos,
                 pe, src_bias=None):
    """One decoder-stack step on the current token(s): embeds `cur`
    ([N, 1, 1] ids), writes each layer's new K/V into its cache at
    `pos`, attends cache + precomputed cross K/V.  Returns
    ([N, 1, V] logits, [(kc, vc)] updated caches — the caller registers
    them as memory updates, possibly after beam reordering).  N is B
    for greedy decode, B*beam for beam search — every op is row-wise
    in N, so the same step serves both."""
    hd = d_model // n_head
    x = layers.embedding(
        cur, size=[tgt_vocab_size, d_model],
        param_attr=_ParamAttr(name=f"{p}_tgt_emb.w"))     # [N, 1, D]
    x = layers.scale(x, scale=float(d_model) ** 0.5)
    pe_t = layers.gather(pe, pos)                         # [1, D]
    x = layers.elementwise_add(
        x, layers.reshape(pe_t, [1, 1, d_model]))
    new_caches = []
    for li in range(n_layer):
        sp = f"{p}_dec{li}"
        kc_pre, vc_pre = caches[li]
        # self-attention: new token's q against the cache
        q = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{sp}_self", "q"))
        k = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{sp}_self", "k"))
        v = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{sp}_self", "v"))
        kc = layers.scatter(kc_pre, pos,
                            layers.transpose(k, [1, 0, 2]))
        vc = layers.scatter(vc_pre, pos,
                            layers.transpose(v, [1, 0, 2]))
        new_caches.append((kc, vc))
        o = _cache_attention(q, kc, vc, pos, kpos, decode_len, n_head,
                             hd)
        o = layers.fc(o, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{sp}_self", "out"))
        x = _residual_norm(x, o, 0.0, True, pfx=f"{sp}_ln1")
        # cross-attention against the precomputed encoder K/V
        q2 = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                       param_attr=_w(f"{sp}_cross", "q"))
        enc_k, enc_v = cross_kv[li]
        s2 = layers.matmul(_split_heads(q2, 1, n_head, hd), enc_k,
                           transpose_y=True, alpha=float(hd) ** -0.5)
        if src_bias is not None:
            s2 = layers.elementwise_add(s2, src_bias)
        o2 = layers.matmul(layers.softmax(s2), enc_v)
        o2 = layers.reshape(layers.transpose(o2, [0, 2, 1, 3]),
                            [-1, 1, d_model])
        o2 = layers.fc(o2, d_model, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=_w(f"{sp}_cross", "out"))
        x = _residual_norm(x, o2, 0.0, True, pfx=f"{sp}_ln2")
        ffn = _ffn(x, d_model, d_inner, 0.0, True, pfx=f"{sp}_ffn")
        x = _residual_norm(x, ffn, 0.0, True, pfx=f"{sp}_ln3")
    logits = layers.fc(x, tgt_vocab_size, num_flatten_dims=2,
                       bias_attr=False, param_attr=_w(p, "out_fc"))
    return logits, new_caches


def transformer_nmt_greedy_decode(
    src_vocab_size=32000, tgt_vocab_size=32000, max_len=256, d_model=512,
    n_head=8, d_inner=2048, n_layer=6, param_prefix=None,
    decode_len=32, bos_id=1, use_src_pad_mask=False, pad_id=0,
):
    """Autoregressive greedy decoding with per-layer KV caches — the
    modern TPU-native successor of the reference's RNN-era
    BeamSearchDecoder (contrib/decoder/beam_search_decoder.py:523): one
    `lax.scan` (via StaticRNN) whose carry holds the last token and the
    self-attention K/V caches, all static shapes.  Each step attends
    the single new query against the cache (O(T) per step instead of
    re-running the O(T^2) decoder stack), writes its K/V at the step
    index, and feeds the argmax token back.

    Build this in its OWN program (fresh program_guard) with the same
    `param_prefix` used for `transformer_nmt_model`: the deterministic
    parameter names make the decode program read the trained weights
    from the scope.  Do not run its startup program.

    Returns {"src_ids": data var, "out_ids": [B, decode_len, 1] int64,
    "step_logits": [B, decode_len, vocab]}.
    """
    from paddle_tpu.layers.control_flow import StaticRNN

    if not param_prefix:
        raise ValueError(
            "transformer_nmt_greedy_decode needs the param_prefix the "
            "training model was built with (weight sharing is by name)")
    p = param_prefix
    src, cross_kv, src_bias = _decode_encoder(
        p, src_vocab_size, max_len, d_model, n_head, d_inner, n_layer,
        use_src_pad_mask=use_src_pad_mask, pad_id=pad_id)
    pe = layers.assign(_positional_encoding(decode_len, d_model))
    pos_seq = layers.assign(
        np.arange(decode_len, dtype=np.int64)[:, None])   # [T, 1]
    kpos = layers.assign(np.arange(decode_len, dtype=np.int64))
    # ids stay 3-D [B, 1, 1] like the training feed: lookup_table's
    # 2-D-ids form returns [B, D] (reference semantics), which would
    # broadcast the positional add into the wrong rank
    bos = layers.fill_constant_batch_size_like(
        src, shape=[-1, 1, 1], dtype="int64", value=float(bos_id))
    cache_init = [
        (layers.fill_constant_batch_size_like(
            src, shape=[decode_len, -1, d_model], dtype="float32",
            value=0.0, output_dim_idx=1),
         layers.fill_constant_batch_size_like(
            src, shape=[decode_len, -1, d_model], dtype="float32",
            value=0.0, output_dim_idx=1))
        for _ in range(n_layer)]

    rnn = StaticRNN()
    with rnn.step():
        pos = rnn.step_input(pos_seq)                     # [1] int64
        cur = rnn.memory(init=bos)                        # [B, 1, 1]
        caches = [(rnn.memory(init=k0), rnn.memory(init=v0))
                  for k0, v0 in cache_init]               # [T, B, D]
        logits, new_caches = _decode_step(
            cur, pos, caches, cross_kv, p, tgt_vocab_size, decode_len,
            d_model, n_head, d_inner, n_layer, kpos, pe,
            src_bias=src_bias)
        for (kc_pre, vc_pre), (kc, vc) in zip(caches, new_caches):
            rnn.update_memory(kc_pre, kc)
            rnn.update_memory(vc_pre, vc)
        nxt = layers.argmax(logits, axis=-1)              # [B, 1] int64
        rnn.update_memory(cur, layers.reshape(nxt, [-1, 1, 1]))
        rnn.step_output(nxt)
        rnn.step_output(layers.reshape(logits, [-1, tgt_vocab_size]))
    ids_tm, logits_tm = rnn()            # [T, B, 1], [T, B, V]
    out_ids = layers.transpose(ids_tm, [1, 0, 2])         # [B, T, 1]
    step_logits = layers.transpose(logits_tm, [1, 0, 2])  # [B, T, V]
    return {"src_ids": src, "out_ids": out_ids,
            "step_logits": step_logits}


def transformer_nmt_beam_decode(
    src_vocab_size=32000, tgt_vocab_size=32000, max_len=256, d_model=512,
    n_head=8, d_inner=2048, n_layer=6, param_prefix=None,
    decode_len=32, beam_size=4, bos_id=1, eos_id=None,
    use_src_pad_mask=False, pad_id=0,
):
    """Beam-search decoding on the KV-cache loop (the transformer
    successor of the reference's dense `beam_search` op + RNN-era
    BeamSearchDecoder, contrib/decoder/beam_search_decoder.py:523) —
    still ONE lax.scan with static shapes.  Beams ride the batch axis
    (N = B*beam rows through the shared `_decode_step`); each step
    joint-scores [B, beam*V], takes the top `beam_size`, reorders every
    layer's K/V cache by the surviving parents with a one-hot batched
    matmul (gather-free, MXU-friendly), and `gather_tree` resolves the
    parent pointers into full sequences after the scan.

    EOS handling: once a beam emits `eos_id` its score freezes — the
    only continuation is another EOS at zero log-prob (the reference
    beam_search op's finished-hypothesis rule; no length normalization).

    Build in its own program with the training `param_prefix` (weight
    sharing by name; never run the decode startup program).  Returns
    {"src_ids", "out_ids": [B, beam, decode_len] int64 (best beam
    first), "scores": [B, beam] cumulative log-probs}.
    """
    from paddle_tpu.layers.control_flow import StaticRNN

    if not param_prefix:
        raise ValueError(
            "transformer_nmt_beam_decode needs the param_prefix the "
            "training model was built with (weight sharing is by name)")
    p = param_prefix
    K, V = beam_size, tgt_vocab_size
    src, cross_kv, src_bias = _decode_encoder(
        p, src_vocab_size, max_len, d_model, n_head, d_inner, n_layer,
        use_src_pad_mask=use_src_pad_mask, pad_id=pad_id)
    hd = d_model // n_head
    # replicate each batch row's encoder K/V across its K beams:
    # [B, H, T, hd] -> [B, K, H, T, hd] -> [B*K, H, T, hd]
    def _to_beams(t):
        t = layers.reshape(t, [-1, 1, n_head, max_len, hd])
        t = layers.expand(t, [1, K, 1, 1, 1])
        return layers.reshape(t, [-1, n_head, max_len, hd])

    cross_kv = [(_to_beams(ck), _to_beams(cv)) for ck, cv in cross_kv]
    if src_bias is not None:
        # beam rows share their batch row's mask: [B,1,1,T] -> [BK,1,1,T]
        src_bias = layers.reshape(
            layers.expand(src_bias, [1, K, 1, 1]),
            [-1, 1, 1, max_len])

    pe = layers.assign(_positional_encoding(decode_len, d_model))
    pos_seq = layers.assign(
        np.arange(decode_len, dtype=np.int64)[:, None])   # [T, 1]
    kpos = layers.assign(np.arange(decode_len, dtype=np.int64))
    # a [B*K, 1] reference var so every *K-batch init sizes off B*K
    bk_ref = layers.reshape(layers.expand(
        layers.fill_constant_batch_size_like(
            src, shape=[-1, 1], dtype="float32", value=0.0),
        [1, K]), [-1, 1])
    bos = layers.fill_constant_batch_size_like(
        bk_ref, shape=[-1, 1, 1], dtype="int64", value=float(bos_id))
    # step-0 collapse: only beam 0 live, so the K identical BOS rows
    # don't flood the first top-k with duplicates
    score_init = layers.elementwise_add(
        layers.fill_constant_batch_size_like(
            src, shape=[-1, K], dtype="float32", value=0.0),
        layers.assign(np.array(
            [[0.0] + [-1e9] * (K - 1)], np.float32)))
    cache_init = [
        (layers.fill_constant_batch_size_like(
            bk_ref, shape=[decode_len, -1, d_model], dtype="float32",
            value=0.0, output_dim_idx=1),
         layers.fill_constant_batch_size_like(
            bk_ref, shape=[decode_len, -1, d_model], dtype="float32",
            value=0.0, output_dim_idx=1))
        for _ in range(n_layer)]
    if eos_id is not None:
        # allowed continuation row for a finished beam: EOS at 0 logp
        eos_row = np.full((1, 1, V), -1e9, np.float32)
        eos_row[0, 0, eos_id] = 0.0
        eos_row = layers.assign(eos_row)

    rnn = StaticRNN()
    with rnn.step():
        pos = rnn.step_input(pos_seq)                     # [1] int64
        cur = rnn.memory(init=bos)                        # [BK, 1, 1]
        scores = rnn.memory(init=score_init)              # [B, K]
        caches = [(rnn.memory(init=k0), rnn.memory(init=v0))
                  for k0, v0 in cache_init]               # [T, BK, D]
        logits, new_caches = _decode_step(
            cur, pos, caches, cross_kv, p, tgt_vocab_size, decode_len,
            d_model, n_head, d_inner, n_layer, kpos, pe,
            src_bias=src_bias)
        # log_softmax, not log(softmax): softmax underflow would put
        # -inf in logp, and the done-mask's 0 * -inf would NaN-poison
        # topk for any finished beam
        logp = layers.log_softmax(logits)                 # [BK, 1, V]
        logp = layers.reshape(logp, [-1, K, V])           # [B, K, V]
        if eos_id is not None:
            done = layers.cast(layers.equal(
                layers.reshape(cur, [-1, K]),
                layers.fill_constant([1], "int64", eos_id)), "float32")
            d3 = layers.reshape(done, [-1, K, 1])
            logp = layers.elementwise_add(
                layers.elementwise_mul(logp, layers.scale(
                    d3, scale=-1.0, bias=1.0)),
                layers.elementwise_mul(
                    layers.expand(eos_row, [1, K, 1]), d3))
        total = layers.elementwise_add(
            logp, layers.reshape(scores, [-1, K, 1]))     # [B, K, V]
        val, idx = layers.topk(
            layers.reshape(total, [-1, K * V]), K)        # [B, K] both
        kv_const = layers.fill_constant([1], "int64", V)
        parent = layers.elementwise_floordiv(idx, kv_const)  # [B, K]
        token = layers.elementwise_mod(idx, kv_const)        # [B, K]
        rnn.update_memory(scores, val)
        rnn.update_memory(cur, layers.reshape(token, [-1, 1, 1]))
        # reorder every cache by the surviving parents: a one-hot
        # batched matmul (sel[b,k,j] picks old beam j for new beam k)
        sel = layers.one_hot(layers.reshape(parent, [-1, K, 1]), K)
        for (kc_pre, vc_pre), (kc, vc) in zip(caches, new_caches):
            for pre, upd in ((kc_pre, kc), (vc_pre, vc)):
                c = layers.reshape(layers.transpose(upd, [1, 0, 2]),
                                   [-1, K, decode_len * d_model])
                c = layers.matmul(sel, c)                 # [B, K, T*D]
                c = layers.transpose(layers.reshape(
                    c, [-1, decode_len, d_model]), [1, 0, 2])
                rnn.update_memory(pre, c)
        rnn.step_output(token)                            # [B, K]
        rnn.step_output(parent)
    tokens_tm, parents_tm = rnn()        # [T, B, K] each
    seqs = layers.gather_tree(tokens_tm, parents_tm)      # [T, B, K]
    out_ids = layers.transpose(seqs, [1, 2, 0])           # [B, K, T]
    return {"src_ids": src, "out_ids": out_ids,
            "scores": rnn.final(scores)}


def transformer_lm_sample_decode(
    vocab_size=32000, prompt_len=64, d_model=512, n_head=8,
    d_inner=2048, n_layer=6, param_prefix=None, gen_len=32,
    temperature=1.0, top_k=0, seed=0,
):
    """GPT-style generation for `transformer_encoder_model`: PREFILL
    the prompt through the causal stack once (full parallel attention,
    seeding every layer's K/V cache with the prompt rows), then one
    `lax.scan` samples `gen_len` tokens incrementally against the
    cache.  temperature=0 is greedy argmax; top_k>0 keeps only the k
    most likely tokens before sampling.  Each step's categorical draw
    folds the step position into the RNG key (`sampling_id` SeedOffset)
    so draws vary across scan iterations.

    Build in its own program with the `param_prefix` the training model
    used (weight sharing by name; never run the decode startup
    program).  Returns {"prompt_ids": data var [B, prompt_len, 1],
    "out_ids": [B, gen_len] int64 sampled continuation}.
    """
    from paddle_tpu.layers.control_flow import StaticRNN

    if not param_prefix:
        raise ValueError(
            "transformer_lm_sample_decode needs the param_prefix the "
            "training model was built with (weight sharing is by name)")
    p = param_prefix
    hd = d_model // n_head
    T = prompt_len + gen_len
    prompt = layers.data("prompt_ids", shape=[prompt_len, 1],
                         dtype="int64")

    def _lm_fcs(x, lp):
        q = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{lp}_self", "q"))
        k = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{lp}_self", "k"))
        v = layers.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
                      param_attr=_w(f"{lp}_self", "v"))
        return q, k, v

    def _lm_tail(x, attn_out, lp):
        o = layers.fc(attn_out, d_model, num_flatten_dims=2,
                      bias_attr=False,
                      param_attr=_w(f"{lp}_self", "out"))
        x = _residual_norm(x, o, 0.0, True, pfx=f"{lp}_ln1")
        ffn = _ffn(x, d_model, d_inner, 0.0, True, pfx=f"{lp}_ffn")
        return _residual_norm(x, ffn, 0.0, True, pfx=f"{lp}_ln2")

    # ---- prefill: full causal pass over the prompt, capturing K/V ----
    x = _embed(prompt, vocab_size, d_model, prompt_len, 0.0, True,
               pfx=f"{p}_emb")
    cache_init = []
    for li in range(n_layer):
        lp = f"{p}_l{li}"
        q, k, v = _lm_fcs(x, lp)
        # seed the cache: prompt rows first, zeros for the gen rows
        zeros = layers.fill_constant_batch_size_like(
            prompt, shape=[gen_len, -1, d_model], dtype="float32",
            value=0.0, output_dim_idx=1)
        cache_init.append(
            (layers.concat([layers.transpose(k, [1, 0, 2]), zeros],
                           axis=0),
             layers.concat([layers.transpose(v, [1, 0, 2]), zeros],
                           axis=0)))                      # [T, B, D]
        attn = layers.flash_attention(
            _split_heads(q, prompt_len, n_head, hd),
            _split_heads(k, prompt_len, n_head, hd),
            _split_heads(v, prompt_len, n_head, hd), causal=True)
        attn = layers.reshape(layers.transpose(attn, [0, 2, 1, 3]),
                              [-1, prompt_len, d_model])
        x = _lm_tail(x, attn, lp)
    # only the last prompt position seeds generation: slice BEFORE the
    # [D, vocab] projection so prefill doesn't pay prompt_len times the
    # logits matmul and a [B, P, vocab] intermediate
    x_last = layers.slice(x, axes=[1], starts=[prompt_len - 1],
                          ends=[prompt_len])              # [B, 1, D]
    last = layers.fc(x_last, vocab_size, num_flatten_dims=2,
                     bias_attr=False, param_attr=_w(p, "out_fc"))

    def _pick(logits3, off):
        """[N, 1, V] logits -> [N, 1] sampled/argmax ids."""
        if temperature == 0.0:
            return layers.argmax(logits3, axis=-1)
        lg = layers.scale(logits3, scale=1.0 / float(temperature))
        if top_k:
            vals, _ = layers.topk(lg, top_k)              # [N, 1, k]
            kth = layers.slice(vals, axes=[2], starts=[top_k - 1],
                               ends=[top_k])              # [N, 1, 1]
            keep = layers.cast(layers.less_equal(kth, lg), "float32")
            lg = layers.elementwise_add(lg, layers.scale(
                keep, scale=1e9, bias=-1e9))
        probs = layers.reshape(layers.softmax(lg), [-1, vocab_size])
        out = layers.sampling_id(probs, seedoffset=off, seed=int(seed))
        return layers.reshape(out, [-1, 1])

    pe = layers.assign(_positional_encoding(T, d_model))
    pos_seq = layers.assign(
        np.arange(prompt_len, T, dtype=np.int64)[:, None])  # [G, 1]
    kpos = layers.assign(np.arange(T, dtype=np.int64))
    first = layers.reshape(_pick(last, layers.assign(
        np.array([prompt_len - 1], np.int64))), [-1, 1, 1])

    rnn = StaticRNN()
    with rnn.step():
        pos = rnn.step_input(pos_seq)                     # [1] int64
        cur = rnn.memory(init=first)                      # [B, 1, 1]
        caches = [(rnn.memory(init=k0), rnn.memory(init=v0))
                  for k0, v0 in cache_init]
        x = layers.embedding(
            cur, size=[vocab_size, d_model],
            param_attr=_ParamAttr(name=f"{p}_emb.w"))     # [B, 1, D]
        x = layers.scale(x, scale=float(d_model) ** 0.5)
        x = layers.elementwise_add(
            x, layers.reshape(layers.gather(pe, pos), [1, 1, d_model]))
        for li in range(n_layer):
            lp = f"{p}_l{li}"
            kc_pre, vc_pre = caches[li]
            q, k, v = _lm_fcs(x, lp)
            kc = layers.scatter(kc_pre, pos,
                                layers.transpose(k, [1, 0, 2]))
            vc = layers.scatter(vc_pre, pos,
                                layers.transpose(v, [1, 0, 2]))
            rnn.update_memory(kc_pre, kc)
            rnn.update_memory(vc_pre, vc)
            o = _cache_attention(q, kc, vc, pos, kpos, T, n_head, hd)
            x = _lm_tail(x, o, lp)
        logits = layers.fc(x, vocab_size, num_flatten_dims=2,
                           bias_attr=False, param_attr=_w(p, "out_fc"))
        rnn.step_output(layers.reshape(cur, [-1, 1]))     # emit, then
        nxt = _pick(logits, pos)                          # pick next
        rnn.update_memory(cur, layers.reshape(nxt, [-1, 1, 1]))
    ids_tm = rnn()                                        # [G, B, 1]
    out_ids = layers.reshape(layers.transpose(ids_tm, [1, 0, 2]),
                             [-1, gen_len])               # [B, G]
    return {"prompt_ids": prompt, "out_ids": out_ids}
