"""Transformer (reference model: the fluid transformer NMT config used by
tests/unittests/dist_transformer.py; BASELINE config 3 Transformer-base).

Built entirely from IR layers (matmul/softmax/layer_norm/fc) so the program
compiles to one XLA module; attention is batched [B, H, T, D/H] matmuls that
XLA tiles onto the MXU.  Sharding-friendly: the fc weights carry optional
tensor-parallel annotations set by parallel/strategies.py.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import layers


def _positional_encoding(max_len, d_model, dtype="float32"):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.zeros((max_len, d_model), np.float64)
    enc[:, 0::2] = np.sin(angle[:, 0::2])
    enc[:, 1::2] = np.cos(angle[:, 1::2])
    return enc.astype(dtype)


def multi_head_attention(q_in, kv_in, d_model, n_head, dropout_rate=0.0,
                         causal=False, is_test=False, seq_len_q=None,
                         seq_len_kv=None, name=None, use_flash=True):
    """q_in: [B, Tq, D]; kv_in: [B, Tk, D].

    When attention-weight dropout is off the score+softmax+weighted-sum is
    emitted as one fused `flash_attention` op (Pallas kernel on TPU) —
    the [Tq, Tk] matrix never touches HBM.  With weight dropout on, the
    unfused composition is kept so the reference's dropout-on-weights
    semantics hold exactly.
    """
    tq = q_in.shape[1]
    tk = kv_in.shape[1]
    head_dim = d_model // n_head
    q = layers.fc(q_in, d_model, num_flatten_dims=2, bias_attr=False)
    k = layers.fc(kv_in, d_model, num_flatten_dims=2, bias_attr=False)
    v = layers.fc(kv_in, d_model, num_flatten_dims=2, bias_attr=False)

    def split_heads(x, t):
        x = layers.reshape(x, [-1, t, n_head, head_dim])
        return layers.transpose(x, [0, 2, 1, 3])  # [B, H, T, hd]

    q = split_heads(q, tq)
    k = split_heads(k, tk)
    v = split_heads(v, tk)
    weight_dropout = bool(dropout_rate) and not is_test
    if use_flash and not weight_dropout:
        out = layers.flash_attention(q, k, v, causal=causal)
    else:
        attn = layers.matmul(q, k, transpose_y=True,
                             alpha=float(head_dim) ** -0.5)  # [B,H,Tq,Tk]
        if causal:
            # bottom-right aligned (query i attends keys <= i + Tk - Tq),
            # matching the flash kernel's q_off convention
            mask = np.triu(np.full((tq, tk), -1e9, np.float32),
                           k=1 + tk - tq)
            mask_var = layers.assign(mask.reshape(1, 1, tq, tk))
            attn = layers.elementwise_add(attn, mask_var)
        weights = layers.softmax(attn)
        if weight_dropout:
            weights = layers.dropout(
                weights, dropout_rate,
                dropout_implementation="upscale_in_train")
        out = layers.matmul(weights, v)  # [B,H,Tq,hd]

    out = layers.transpose(out, [0, 2, 1, 3])
    out = layers.reshape(out, [-1, tq, d_model])
    return layers.fc(out, d_model, num_flatten_dims=2, bias_attr=False)


def _ffn(x, d_model, d_inner, dropout_rate, is_test):
    h = layers.fc(x, d_inner, num_flatten_dims=2, act="relu")
    if dropout_rate and not is_test:
        h = layers.dropout(h, dropout_rate,
                           dropout_implementation="upscale_in_train")
    return layers.fc(h, d_model, num_flatten_dims=2)


def _residual_norm(x, sub, dropout_rate, is_test):
    if dropout_rate and not is_test:
        sub = layers.dropout(sub, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, sub),
                             begin_norm_axis=2)


def encoder_layer(x, d_model, n_head, d_inner, dropout_rate=0.1,
                  is_test=False):
    attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                is_test=is_test)
    x = _residual_norm(x, attn, dropout_rate, is_test)
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test)
    return _residual_norm(x, ffn, dropout_rate, is_test)


def decoder_layer(x, enc_out, d_model, n_head, d_inner, dropout_rate=0.1,
                  is_test=False):
    self_attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                     causal=True, is_test=is_test)
    x = _residual_norm(x, self_attn, dropout_rate, is_test)
    cross = multi_head_attention(x, enc_out, d_model, n_head,
                                 dropout_rate, is_test=is_test)
    x = _residual_norm(x, cross, dropout_rate, is_test)
    ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test)
    return _residual_norm(x, ffn, dropout_rate, is_test)


def _embed(ids, vocab_size, d_model, max_len, dropout_rate, is_test,
           scale_embedding=True):
    emb = layers.embedding(ids, size=[vocab_size, d_model])
    if scale_embedding:
        emb = layers.scale(emb, scale=float(d_model) ** 0.5)
    pe = layers.assign(
        _positional_encoding(max_len, d_model)[None, :, :])
    emb = layers.elementwise_add(emb, pe)
    if dropout_rate and not is_test:
        emb = layers.dropout(emb, dropout_rate,
                             dropout_implementation="upscale_in_train")
    return emb


def transformer_encoder_model(
    vocab_size=32000, max_len=256, d_model=512, n_head=8, d_inner=2048,
    n_layer=6, dropout_rate=0.1, is_test=False, tie_embeddings=False,
    label_smooth_eps=0.0,
):
    """Encoder-only LM-style transformer: next-token prediction over a
    single stream (the flagship shape for bench/graft entry; the NMT
    encoder-decoder variant is `transformer_nmt_model`)."""
    src = layers.data("src_ids", shape=[max_len, 1], dtype="int64")
    label = layers.data("tgt_label", shape=[max_len, 1], dtype="int64")
    x = _embed(src, vocab_size, d_model, max_len, dropout_rate, is_test)
    # causal self-attention stack
    for _ in range(n_layer):
        attn = multi_head_attention(x, x, d_model, n_head, dropout_rate,
                                    causal=True, is_test=is_test)
        x = _residual_norm(x, attn, dropout_rate, is_test)
        ffn = _ffn(x, d_model, d_inner, dropout_rate, is_test)
        x = _residual_norm(x, ffn, dropout_rate, is_test)
    logits = layers.fc(x, vocab_size, num_flatten_dims=2,
                       bias_attr=False)
    if label_smooth_eps:
        one_hot = layers.one_hot(label, vocab_size)
        smoothed = layers.label_smooth(one_hot, epsilon=label_smooth_eps)
        loss = layers.mean(layers.softmax_with_cross_entropy(
            logits, smoothed, soft_label=True))
    else:
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
    return {"src_ids": src, "tgt_label": label, "logits": logits,
            "loss": loss}


def transformer_nmt_model(
    src_vocab_size=32000, tgt_vocab_size=32000, max_len=256, d_model=512,
    n_head=8, d_inner=2048, n_layer=6, dropout_rate=0.1, is_test=False,
):
    """Encoder-decoder NMT transformer (Transformer-base when defaults)."""
    src = layers.data("src_ids", shape=[max_len, 1], dtype="int64")
    tgt = layers.data("tgt_ids", shape=[max_len, 1], dtype="int64")
    label = layers.data("tgt_label", shape=[max_len, 1], dtype="int64")
    enc = _embed(src, src_vocab_size, d_model, max_len, dropout_rate,
                 is_test)
    for _ in range(n_layer):
        enc = encoder_layer(enc, d_model, n_head, d_inner, dropout_rate,
                            is_test)
    dec = _embed(tgt, tgt_vocab_size, d_model, max_len, dropout_rate,
                 is_test)
    for _ in range(n_layer):
        dec = decoder_layer(dec, enc, d_model, n_head, d_inner,
                            dropout_rate, is_test)
    logits = layers.fc(dec, tgt_vocab_size, num_flatten_dims=2,
                       bias_attr=False)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    return {"src_ids": src, "tgt_ids": tgt, "tgt_label": label,
            "logits": logits, "loss": loss}
