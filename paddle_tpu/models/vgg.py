"""VGG for ImageNet/CIFAR (reference models: the float16 benchmark's
headline network — paddle/contrib/float16/float16_benchmark.md:23-33
VGG16 ImageNet fp32/fp16 latencies — and the book test's vgg16_bn
variant, tests/book/test_image_classification.py vgg16_bn_drop).

Plain conv(3x3)+bn stacks with maxpool between groups, two fc-4096
heads.  Static NCHW; the bench applies nhwc_transpile + bf16 the same
way the reference benchmark ran fp16.
"""

from __future__ import annotations

from paddle_tpu import layers

_CFGS = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


def _conv_block(x, num_filter, groups, is_test=False):
    for _ in range(groups):
        x = layers.conv2d(x, num_filters=num_filter, filter_size=3,
                          stride=1, padding=1, bias_attr=False)
        x = layers.batch_norm(x, act="relu", is_test=is_test)
    return layers.pool2d(x, pool_size=2, pool_type="max", pool_stride=2)


def vgg(depth=16, class_dim=1000, img_shape=(3, 224, 224),
        is_test=False, with_head_dropout=True):
    """Build VGG-{11,13,16,19}; returns image/logits (+label/loss when
    training)."""
    if depth not in _CFGS:
        raise ValueError(f"depth must be one of {sorted(_CFGS)}")
    groups = _CFGS[depth]
    widths = (64, 128, 256, 512, 512)
    image = layers.data(name="image", shape=list(img_shape),
                        dtype="float32")
    x = image
    for width, g in zip(widths, groups):
        x = _conv_block(x, width, g, is_test=is_test)
    if with_head_dropout:
        x = layers.dropout(x, dropout_prob=0.5, is_test=is_test)
    x = layers.fc(x, size=4096, act=None, num_flatten_dims=1)
    x = layers.batch_norm(x, act="relu", is_test=is_test)
    if with_head_dropout:
        x = layers.dropout(x, dropout_prob=0.5, is_test=is_test)
    x = layers.fc(x, size=4096, act="relu")
    logits = layers.fc(x, size=class_dim)
    out = {"image": image, "logits": logits}
    if not is_test:
        label = layers.data(name="label", shape=[1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            logits, label))
        out["label"] = label
        out["loss"] = loss
    return out


def vgg16(class_dim=1000, img_shape=(3, 224, 224), is_test=False):
    """The float16_benchmark.md headline network."""
    return vgg(16, class_dim, img_shape, is_test=is_test)
