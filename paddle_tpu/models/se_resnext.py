"""SE-ResNeXt for ImageNet (reference model:
python/paddle/fluid/tests/unittests/dist_se_resnext.py:49 SE_ResNeXt —
the reference's distributed-training image workload).

ResNeXt grouped-conv bottlenecks with squeeze-excitation channel gating;
depths 50/101/152 follow the reference configs (cardinality 32/32/64,
reduction 16).  Static NCHW; grouped convs lower to a single
`conv_general_dilated` with feature_group_count, which XLA tiles onto the
MXU without the per-group loop the reference's cuDNN path uses.
"""

from __future__ import annotations

from paddle_tpu import layers

_CFGS = {
    # depth: (stage depths, cardinality, reduction)
    50: ((3, 4, 6, 3), 32, 16),
    101: ((3, 4, 23, 3), 32, 16),
    152: ((3, 8, 36, 3), 64, 16),
}
_NUM_FILTERS = (128, 256, 512, 1024)


def _conv_bn(x, num_filters, filter_size, stride=1, groups=1, act=None,
             is_test=False):
    conv = layers.conv2d(
        input=x, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        bias_attr=False)
    return layers.batch_norm(conv, act=act, is_test=is_test)


def _squeeze_excitation(x, num_channels, reduction_ratio, is_test=False):
    pool = layers.pool2d(x, pool_size=0, pool_type="avg",
                         global_pooling=True)
    squeeze = layers.fc(pool, size=num_channels // reduction_ratio,
                        act="relu")
    excitation = layers.fc(squeeze, size=num_channels, act="sigmoid")
    # broadcast the [N, C] gate over H, W (reference elementwise_mul axis=0)
    gate = layers.reshape(excitation, shape=[0, num_channels, 1, 1])
    return layers.elementwise_mul(x, gate)


def _shortcut(x, ch_out, stride, is_test=False):
    ch_in = int(x.shape[1])
    if ch_in != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, is_test=is_test)
    return x


def _bottleneck(x, num_filters, stride, cardinality, reduction_ratio,
                is_test=False):
    conv0 = _conv_bn(x, num_filters, 1, act="relu", is_test=is_test)
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride,
                     groups=cardinality, act="relu", is_test=is_test)
    conv2 = _conv_bn(conv1, num_filters * 2, 1, act=None, is_test=is_test)
    scale = _squeeze_excitation(conv2, num_filters * 2, reduction_ratio,
                                is_test=is_test)
    short = _shortcut(x, num_filters * 2, stride, is_test=is_test)
    return layers.relu(layers.elementwise_add(short, scale))


def se_resnext(depth=50, class_dim=1000, img_shape=(3, 224, 224),
               is_test=False, stage_depths=None):
    """Build SE-ResNeXt-{50,101,152}.  stage_depths overrides the per-stage
    block counts for tiny test configs."""
    if depth not in _CFGS:
        raise ValueError(f"supported layers are {sorted(_CFGS)} but "
                         f"input layer is {depth}")
    depths, cardinality, reduction = _CFGS[depth]
    if stage_depths is not None:
        depths = tuple(stage_depths)

    image = layers.data(name="image", shape=list(img_shape),
                        dtype="float32")
    if depth == 152:
        conv = _conv_bn(image, 64, 3, 2, act="relu", is_test=is_test)
        conv = _conv_bn(conv, 64, 3, 1, act="relu", is_test=is_test)
        conv = _conv_bn(conv, 128, 3, 1, act="relu", is_test=is_test)
    else:
        conv = _conv_bn(image, 64, 7, 2, act="relu", is_test=is_test)
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    for block, count in enumerate(depths):
        for i in range(count):
            conv = _bottleneck(
                conv, _NUM_FILTERS[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality, reduction_ratio=reduction,
                is_test=is_test)
    pool = layers.pool2d(conv, pool_size=7, pool_type="avg",
                         global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.2, is_test=is_test)
    logits = layers.fc(drop, size=class_dim)
    out = {"image": image, "logits": logits}
    if not is_test:
        label = layers.data(name="label", shape=[1], dtype="int64")
        out["label"] = label
        out["loss"] = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
    return out
