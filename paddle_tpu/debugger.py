"""Program graphviz dumps.

Reference parity: python/paddle/fluid/debugger.py draw_block_graphviz +
net_drawer.py + framework/ir/graph_viz_pass.cc.  Emits .dot text (render
with `dot -Tpng` where graphviz is installed).
"""

from __future__ import annotations


def _esc(s):
    return str(s).replace('"', '\\"')


def draw_program(program, path=None, block_idx=0):
    """Write (or return) a graphviz dot of a block: op nodes (boxes) wired
    through var nodes (ellipses)."""
    block = program.blocks[block_idx]
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}

    def var_node(name):
        if name not in var_ids:
            var_ids[name] = f"var_{len(var_ids)}"
            persist = ""
            if block.has_var(name) and block.var(name).persistable:
                persist = ", style=filled, fillcolor=lightblue"
            lines.append(
                f'  {var_ids[name]} [label="{_esc(name)}", '
                f'shape=ellipse{persist}];')
        return var_ids[name]

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}"
        lines.append(
            f'  {op_id} [label="{_esc(op.type)}", shape=box, '
            f'style=filled, fillcolor=lightgray];')
        for names in op.inputs.values():
            for n in names:
                lines.append(f"  {var_node(n)} -> {op_id};")
        for names in op.outputs.values():
            for n in names:
                lines.append(f"  {op_id} -> {var_node(n)};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
