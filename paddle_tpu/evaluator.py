"""Program-state evaluators (reference python/paddle/fluid/evaluator.py:45
Evaluator + ChunkEvaluator :127 / EditDistance :218 / DetectionMAP :299).

Deprecated in the reference in favour of metrics.* (same warning kept here);
each evaluator plants accumulator state vars + update ops into the main
program, and reset()/eval() run tiny throwaway programs against the same
scope — the pattern works unchanged on TPU because state vars are
persistable scope entries and the update ops ride the compiled step.
"""

from __future__ import annotations

import warnings

import numpy as np

from paddle_tpu import layers, unique_name
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.initializer import Constant
from paddle_tpu.layers.helper import LayerHelper

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _clone_var_(block, var):
    """reference evaluator.py:34 — mirror a var desc into another block."""
    assert var.name is not None
    return block.create_var(
        name=var.name, shape=var.shape, dtype=var.dtype,
        persistable=var.persistable)


class Evaluator:
    """reference evaluator.py:45.  states: persistable accumulators reset
    by reset(); metrics: per-minibatch metric vars."""

    def __init__(self, name, **kwargs):
        warnings.warn(
            "The %s is deprecated, please use metrics.%s instead."
            % (self.__class__.__name__, self.__class__.__name__), Warning)
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        """Zero all state vars (reference evaluator.py:77)."""
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            for var in self.states:
                g_var = _clone_var_(reset_program.current_block(), var)
                layers.fill_constant(
                    shape=g_var.shape, value=0.0, dtype=g_var.dtype,
                    out=g_var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def _create_state(self, suffix, dtype, shape):
        """Persistable accumulator var, zero-initialized in the startup
        program (reference evaluator.py:106)."""
        block = self.helper.main_program.global_block()
        state = block.create_var(
            name="_".join([unique_name.generate(self.helper.name), suffix]),
            persistable=True, dtype=dtype, shape=shape)
        startup = self.helper.startup_program.global_block()
        s_var = startup.create_var(
            name=state.name, shape=shape, dtype=dtype, persistable=True)
        startup.append_op(
            type="fill_constant",
            inputs={}, outputs={"Out": [s_var.name]},
            attrs={"shape": list(shape or [1]), "dtype": dtype,
                   "value": 0.0})
        self.states.append(state)
        return state


class ChunkEvaluator(Evaluator):
    """Accumulated chunk precision/recall/F1 (reference evaluator.py:127):
    plants a chunk_eval op + running sums of the three chunk counters."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_length=None):
        super().__init__("chunk_eval")
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")

        self.num_infer_chunks = self._create_state(
            dtype="int64", shape=[1], suffix="num_infer_chunks")
        self.num_label_chunks = self._create_state(
            dtype="int64", shape=[1], suffix="num_label_chunks")
        self.num_correct_chunks = self._create_state(
            dtype="int64", shape=[1], suffix="num_correct_chunks")
        (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
            input, label, seqlength=seq_length,
            chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types or [])
        layers.sums(input=[self.num_infer_chunks, num_infer_chunks],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label_chunks],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct_chunks],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1_score])

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        block = eval_program.current_block()
        num_infer, num_label, num_correct = executor.run(
            eval_program,
            fetch_list=[_clone_var_(block, state) for state in self.states])
        num_infer = float(np.asarray(num_infer).ravel()[0])
        num_label = float(np.asarray(num_label).ravel()[0])
        num_correct = float(np.asarray(num_correct).ravel()[0])
        precision = num_correct / num_infer if num_infer else 0.0
        recall = num_correct / num_label if num_label else 0.0
        f1_score = (2 * precision * recall / (precision + recall)
                    if num_correct else 0.0)
        return (np.array([precision], dtype="float32"),
                np.array([recall], dtype="float32"),
                np.array([f1_score], dtype="float32"))


class EditDistance(Evaluator):
    """Accumulated average edit distance + instance error rate (reference
    evaluator.py:218)."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super().__init__("edit_distance", **kwargs)
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")

        self.total_distance = self._create_state(
            dtype="float32", shape=[1], suffix="total_distance")
        self.seq_num = self._create_state(
            dtype="int64", shape=[1], suffix="seq_num")
        self.instance_error = self._create_state(
            dtype="int64", shape=[1], suffix="instance_error")
        if ignored_tokens:
            input = layers.sequence_erase(input, tokens=ignored_tokens)[0]
            label = layers.sequence_erase(label, tokens=ignored_tokens)[0]
        distances, seq_num = layers.edit_distance(input, label)
        zero = layers.fill_constant(shape=[1], value=0.0, dtype="float32")
        compare_result = layers.equal(distances, zero)
        compare_result_int = layers.cast(x=compare_result, dtype="int64")
        seq_right_count = layers.reduce_sum(compare_result_int)
        instance_error_count = layers.elementwise_sub(
            x=seq_num, y=seq_right_count)
        total_distance = layers.reduce_sum(distances)
        layers.sums(input=[self.total_distance, total_distance],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        layers.sums(input=[self.instance_error, instance_error_count],
                    out=self.instance_error)
        self.metrics.append(total_distance)
        self.metrics.append(instance_error_count)

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        block = eval_program.current_block()
        with program_guard(main_program=eval_program):
            total_distance = _clone_var_(block, self.total_distance)
            seq_num = _clone_var_(block, self.seq_num)
            instance_error = _clone_var_(block, self.instance_error)
            seq_num_f = layers.cast(x=seq_num, dtype="float32")
            instance_error_f = layers.cast(x=instance_error,
                                           dtype="float32")
            avg_distance = layers.elementwise_div(
                x=total_distance, y=seq_num_f)
            avg_instance_error = layers.elementwise_div(
                x=instance_error_f, y=seq_num_f)
            result = executor.run(
                eval_program, fetch_list=[avg_distance, avg_instance_error])
        return np.asarray(result[0]), np.asarray(result[1])


class DetectionMAP(Evaluator):
    """Accumulated detection mAP (reference evaluator.py:299): one
    detection_map op for the batch mAP, a second streaming one that merges
    into persistable row-table states (ops/detection.py detection_map)."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__("map_eval")

        gt_label = layers.cast(x=gt_label, dtype=gt_box.dtype)
        if gt_difficult is not None:
            gt_difficult = layers.cast(x=gt_difficult, dtype=gt_box.dtype)
            label = layers.concat([gt_label, gt_difficult, gt_box], axis=-1)
        else:
            label = layers.concat([gt_label, gt_box], axis=-1)

        # batch mAP
        map = layers.detection_map(
            input, label, class_num=class_num,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_type=ap_version)

        states = [
            self._create_state(dtype="int32", shape=[class_num, 1],
                               suffix="accum_pos_count"),
            self._create_state(dtype="float32", shape=[0, 3],
                               suffix="accum_true_pos"),
            self._create_state(dtype="float32", shape=[0, 3],
                               suffix="accum_false_pos"),
        ]
        self.has_state = self.helper.main_program.global_block().create_var(
            name=unique_name.generate("map_eval_has_state"),
            persistable=True, dtype="int32", shape=[1])
        startup = self.helper.startup_program.global_block()
        startup.create_var(name=self.has_state.name, shape=[1],
                           dtype="int32", persistable=True)
        startup.append_op(
            type="fill_constant", inputs={},
            outputs={"Out": [self.has_state.name]},
            attrs={"shape": [1], "dtype": "int32", "value": 0.0})

        # accumulative mAP: read + write back the same state vars
        helper = LayerHelper("map_eval")
        accum_map = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="detection_map",
            inputs={"DetectRes": input, "Label": label,
                    "HasState": self.has_state,
                    "PosCount": states[0], "TruePos": states[1],
                    "FalsePos": states[2]},
            outputs={"MAP": accum_map, "AccumPosCount": states[0],
                     "AccumTruePos": states[1],
                     "AccumFalsePos": states[2]},
            attrs={"overlap_threshold": overlap_threshold,
                   "evaluate_difficult": evaluate_difficult,
                   "ap_type": ap_version, "class_num": class_num},
            infer_shape=False)
        layers.fill_constant(shape=[1], value=1, dtype="int32",
                             out=self.has_state)

        self.cur_map = map
        self.accum_map = accum_map

    def get_map_var(self):
        """(batch mAP var, accumulative mAP var) — reference :421."""
        return self.cur_map, self.accum_map

    def reset(self, executor, reset_program=None):
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            var = _clone_var_(reset_program.current_block(), self.has_state)
            layers.fill_constant(
                shape=var.shape, value=0, dtype=var.dtype, out=var)
        executor.run(reset_program)
