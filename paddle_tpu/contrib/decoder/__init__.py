"""Contrib decoder package (reference
python/paddle/fluid/contrib/decoder/__init__.py)."""

from paddle_tpu.contrib.decoder import beam_search_decoder  # noqa: F401
from paddle_tpu.contrib.decoder.beam_search_decoder import *  # noqa: F401,F403

__all__ = list(beam_search_decoder.__all__)
