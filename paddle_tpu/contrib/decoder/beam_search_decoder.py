"""Training and beam-search decoders over a user-defined state cell
(reference python/paddle/fluid/contrib/decoder/beam_search_decoder.py:43
InitState, :159 StateCell, :384 TrainingDecoder, :523 BeamSearchDecoder).

TPU re-specification: the reference drives the state cell through
DynamicRNN (training) and a While loop over LoD tensor arrays (decoding).
Here TrainingDecoder rides the framework's DynamicRNN (which lowers to one
lax.scan), and BeamSearchDecoder statically unrolls `max_len` decode steps
over DENSE [batch*beam] state — per step: embed prev ids, run the user's
state updater, project to vocab, and call the dense `beam_search` op
(ops/rnn_ops.py:513), gathering states by parent beam with gather_nd.
The unrolled program is a single XLA computation; no host-side loop runs
at execution time.
"""

from __future__ import annotations

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class InitState:
    """reference beam_search_decoder.py:43."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        from paddle_tpu import layers

        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of "
                "InitState.\n")
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape or [-1],
                dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """reference beam_search_decoder.py:159 — named states + inputs and a
    user-registered updater run once per decode step."""

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._state_names = list(states)
        self._cur_states = {k: v.value for k, v in states.items()}
        self._out_state = out_state
        self._state_updater = None
        self.name = name

    def state_updater(self, updater):
        """Decorator registering the per-step updater (reference :314)."""
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell == self:
                raise TypeError("Updater should only accept a StateCell "
                                "object as argument.")
            updater(state_cell)

        return _decorator

    def get_input(self, input_name):
        if input_name not in self._inputs:
            raise ValueError(f"Unknown input {input_name}")
        return self._inputs[input_name]

    def get_state(self, state_name):
        if state_name not in self._cur_states:
            raise ValueError(f"Unknown state {state_name}")
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def compute_state(self, inputs):
        """Feed the step inputs and run the updater (reference :335)."""
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    f"Unknown input {input_name}. Please make sure "
                    f"{input_name} in input place holder.")
            self._inputs[input_name] = input_value
        if self._state_updater is None:
            raise RuntimeError("no state_updater registered")
        self._state_updater(self)

    def update_states(self):
        """Record the new states on the enclosing decoder (reference
        :360).  The TrainingDecoder wires this to DynamicRNN
        update_memory; BeamSearchDecoder snapshots dense states."""
        if getattr(self, "_update_hook", None) is not None:
            self._update_hook()

    def out_state(self):
        return self._cur_states[self._out_state]

    def _reset(self):
        self._cur_states = {k: v.value
                            for k, v in self._init_states.items()}


class TrainingDecoder:
    """Teacher-forced decoder over DynamicRNN (reference :384)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        from paddle_tpu.layers.control_flow import DynamicRNN

        self._state_cell = state_cell
        self._dynamic_rnn = DynamicRNN()
        self._status = TrainingDecoder.BEFORE_DECODER
        self.name = name
        self._mems = {}

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def _block():
            if self._status != TrainingDecoder.BEFORE_DECODER:
                raise ValueError("decoder.block() can only be invoked once")
            self._status = TrainingDecoder.IN_DECODER
            sc = self._state_cell
            with self._dynamic_rnn.block():
                # states become rnn memories boot-strapped from InitState
                for name in sc._state_names:
                    mem = self._dynamic_rnn.memory(
                        init=sc._init_states[name].value)
                    self._mems[name] = mem
                    sc._cur_states[name] = mem
                sc._update_hook = self._update_states
                yield
            sc._update_hook = None
            self._status = TrainingDecoder.AFTER_DECODER
        return _block()

    def _update_states(self):
        sc = self._state_cell
        for name, mem in self._mems.items():
            self._dynamic_rnn.update_memory(mem, sc._cur_states[name])

    def step_input(self, x):
        self._assert_in_decoder_block("step_input")
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        # dense re-spec: static inputs need no LoD re-rank; pass through
        return x

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._dynamic_rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError(
                "Output of training decoder can only be visited outside "
                "the block.")
        return self._dynamic_rnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError(
                f"{method} should be invoked inside block of "
                "TrainingDecoder object.")


class BeamSearchDecoder:
    """Beam-search decode driven by the same state cell (reference :523).

    Dense re-spec: init_ids [B, 1] int64 and init_scores [B, 1] float32
    (one live beam per batch element to start); states are kept flat
    [B*beam, D].  decode() unrolls max_len steps; __call__() returns
    (translation_ids [B, beam, T], translation_scores [B, beam])."""

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self.name = name
        self._decoded = None

    def decode(self):
        """Build the unrolled decode program (reference :653).

        Parameter sharing across the unrolled steps: each step is built
        under an identical unique_name counter snapshot, so every step
        regenerates the SAME parameter names (embedding table, score fc,
        and whatever the user's state updater creates) — one shared set
        of weights, like ops re-executing inside the reference's While
        block — and those names are the updater's NATURAL names (no
        decoder prefix), so params line up with a training program built
        in the same order, the fluid load-by-name idiom the reference
        decode test relies on.  Cross-step plumbing (state snapshots,
        selected ids/parents, the final backtrack) is built under a
        'bsd/' name prefix so it can never collide with step names."""
        from paddle_tpu import layers, unique_name
        from paddle_tpu.framework import name_scope
        from paddle_tpu.layers.helper import LayerHelper

        sc = self._state_cell
        sc._reset()
        K = self._beam_size
        # expand the single live beam to K beams: ids/scores [B, K]
        prev_ids = layers.expand(
            layers.reshape(self._init_ids, shape=[-1, 1]),
            expand_times=[1, K])
        # only beam 0 is live initially; others at -inf so the first
        # beam_search step selects from beam 0's continuations
        neg = layers.fill_constant_batch_size_like(
            input=self._init_scores, shape=[-1, K], value=-1e9,
            dtype="float32")
        first = layers.reshape(self._init_scores, shape=[-1, 1])
        prev_scores = layers.concat(
            [first, layers.slice(neg, axes=[1], starts=[1], ends=[K])],
            axis=1)
        # states: expand [B, D] -> [B*K, D]
        for name in sc._state_names:
            st = sc.get_state(name)
            st = layers.expand(layers.unsqueeze(st, axes=[1]),
                               expand_times=[1, K, 1])
            sc.set_state(name, layers.reshape(
                st, shape=[-1, int(st.shape[-1])]))

        step_ids, step_parents = [], []
        # every step rebuilds from this exact counter state, so all
        # steps regenerate identical, NATURAL names (params shared
        # across the unroll AND matchable against a training program);
        # cross-step plumbing accumulates in outer_counters under the
        # 'bsd/' prefix, disjoint from the repeating step names
        entry_counters = dict(unique_name._counters)
        outer_counters = dict(entry_counters)
        step_end_counters = {}
        for _ in range(self._max_len):
            unique_name.switch(dict(entry_counters))
            ids_flat = layers.reshape(prev_ids, shape=[-1, 1])
            emb = layers.embedding(
                ids_flat, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=None)
            feed = {}
            for input_name in sc._inputs:
                feed[input_name] = self._input_var_dict.get(
                    input_name, emb)
            sc.compute_state(inputs=feed)
            cur = sc.out_state()
            scores = layers.fc(cur, size=self._target_dict_dim,
                               act="softmax")
            log_probs = layers.log(scores)
            probs_bkv = layers.reshape(
                log_probs, shape=[-1, K, self._target_dict_dim])
            helper = LayerHelper("beam_search_step")
            sel_ids = helper.create_variable_for_type_inference("int64")
            sel_scores = helper.create_variable_for_type_inference(
                "float32")
            parent_idx = helper.create_variable_for_type_inference(
                "int64")
            helper.append_op(
                type="beam_search",
                inputs={"pre_ids": prev_ids, "pre_scores": prev_scores,
                        "scores": probs_bkv},
                outputs={"selected_ids": sel_ids,
                         "selected_scores": sel_scores,
                         "parent_idx": parent_idx},
                attrs={"beam_size": K, "end_id": self._end_id,
                       "level": 0})
            # gather states by parent beam: [B, K, D] indexed at parent
            gathered = {}
            for name in sc._state_names:
                st = sc.get_state(name)
                d = int(st.shape[-1])
                st_bkd = layers.reshape(st, shape=[-1, K, d])
                picked = _gather_by_parent(st_bkd, parent_idx)
                gathered[name] = layers.reshape(picked, shape=[-1, d])
            step_end_counters = dict(unique_name._counters)
            # cross-step snapshots: outer_counters persists across the
            # loop so each step's 'bsd/assign_*' names stay distinct
            unique_name.switch(outer_counters)
            with name_scope("bsd"):
                for name, val in gathered.items():
                    sc.set_state(name, layers.assign(val))
                sel_ids = layers.assign(sel_ids)
                sel_scores = layers.assign(sel_scores)
                parent_idx = layers.assign(parent_idx)
            step_ids.append(sel_ids)
            step_parents.append(parent_idx)
            prev_ids, prev_scores = sel_ids, sel_scores

        # post-loop: advance past one full step's names so anything the
        # CALLER builds after decode() cannot collide with (or silently
        # share) the step-internal layers — outer_counters only knows
        # the entry snapshot + 'bsd/' names
        for key, count in step_end_counters.items():
            if outer_counters.get(key, 0) < count:
                outer_counters[key] = count
        with name_scope("bsd"):
            ids_tbk = layers.stack(step_ids, axis=0)    # [T, B, K]
            parents_tbk = layers.stack(step_parents, axis=0)
        helper = LayerHelper("beam_search_decode")
        sent_ids = helper.create_variable_for_type_inference("int64")
        sent_scores = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            type="beam_search_decode",
            inputs={"Ids": ids_tbk, "Parents": parents_tbk,
                    "Scores": prev_scores},
            outputs={"SentenceIds": sent_ids,
                     "SentenceScores": sent_scores},
            attrs={"beam_size": K, "end_id": self._end_id})
        self._decoded = (sent_ids, sent_scores)

    def early_stop(self):
        """No-op in the dense re-spec: finished beams freeze inside the
        beam_search op (the reference short-circuits its While loop)."""

    def __call__(self):
        if self._decoded is None:
            raise ValueError("decode() must be called before the decoder")
        return self._decoded


def _gather_by_parent(st_bkd, parent_idx):
    """new_state[b, k] = st_bkd[b, parent_idx[b, k]] via gather_nd."""
    from paddle_tpu import layers

    b_idx = layers.expand(
        layers.unsqueeze(_batch_range_like(parent_idx), axes=[1]),
        expand_times=[1, int(parent_idx.shape[1])])
    idx = layers.stack([b_idx, parent_idx], axis=-1)   # [B, K, 2]
    return layers.gather_nd(st_bkd, idx)


def _batch_range_like(x):
    """[B] int64 0..B-1 with the batch size of x (dense helper)."""
    from paddle_tpu import layers

    ones = layers.fill_constant_batch_size_like(
        input=x, shape=[-1], value=1, dtype="int64")
    csum = layers.cumsum(ones, axis=0)
    return layers.elementwise_sub(
        csum, layers.fill_constant(shape=[1], dtype="int64", value=1))
