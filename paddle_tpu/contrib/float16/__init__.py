"""bf16 inference transpiler (reference:
/root/reference/paddle/contrib/float16/float16_transpiler.py — casts
weights and activations to half precision for inference; the repo's
headline benchmark table float16_benchmark.md is produced with it).

TPU-first: the half type is bfloat16 (native on the MXU; fp16 is not),
and no op rewriting is needed — XLA type-propagates once the param
values and the program's float var dtypes are bf16.  Measured effect on
the bench workload (ResNet-50 mb=128 inference, one v5e-class chip):
~16.7 ms/batch fp32 -> ~10.0 ms/batch bf16.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.analysis.passes import checked_pass

__all__ = ["bf16_transpile", "float16_transpile"]


@checked_pass("bf16_transpile")
def bf16_transpile(program, place=None, scope=None):
    """Cast every float32 var of `program` (and its scope values) to
    bfloat16.  Returns the program (modified in place).

    Reference parity: Float16Transpiler.transpile(program, place, scope)
    — same argument order; theirs rewrites tensors + inserts cast ops;
    here dtype metadata + scope values are enough because XLA propagates
    types.  `place` is accepted for signature parity (XLA owns
    placement).  Only vars DECLARED IN `program` are touched — training
    state coexisting in the scope (optimizer moments, master weights)
    is left alone.
    """
    prog_var_names = set()
    for block in program.blocks:
        for var in block.vars.values():
            prog_var_names.add(var.name)
            if var.dtype == "float32":
                var.dtype = "bfloat16"
    if scope is not None:
        for name, var in list(scope.vars.items()):
            if name not in prog_var_names:
                continue
            v = var.get()
            if v is not None and hasattr(v, "dtype") and \
                    v.dtype == np.float32:
                var.set(jnp.asarray(v).astype(jnp.bfloat16))
    return program


# reference-compatible alias (the reference casts to fp16; on TPU the
# native half type is bf16)
float16_transpile = bf16_transpile
