"""Contrib readers (reference python/paddle/fluid/contrib/reader/):
distributed_batch_reader (multi-process sharding decorator) and ctr_reader
(threaded csv/svm file reader feeding a PyReader-style queue; the
reference backs it with the C++ ctr_reader operator, here the native
blocking queue + reader threads play that role).
"""

from __future__ import annotations

import gzip
import os
import threading

import numpy as np

__all__ = ["distributed_batch_reader", "ctr_reader"]


def distributed_batch_reader(batch_reader):
    """Shard a batch reader across PADDLE_TRAINERS_NUM processes by
    round-robin batch ownership (reference distributed_reader.py:21)."""
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    trainer_id = int(os.getenv("PADDLE_TRAINER_ID", 0))
    assert trainer_id < trainers_num

    def decorate_for_multi_process():
        for batch_id, data in enumerate(batch_reader()):
            if trainers_num > 1:
                if batch_id % trainers_num == trainer_id:
                    yield data
            else:
                yield data

    return decorate_for_multi_process


def _parse_csv(line, dense_slot_index, sparse_slot_index):
    """csv: comma-separated; dense slots are floats, sparse slots are
    space-separated id lists (reference ctr_reader csv format)."""
    cols = line.rstrip("\n").split(",")
    sample = []
    for i, col in enumerate(cols):
        if i in dense_slot_index:
            sample.append(np.asarray([float(col)], np.float32))
        elif i in sparse_slot_index:
            ids = [int(t) for t in col.split()] or [0]
            sample.append(np.asarray(ids, np.int64))
    return sample


def _parse_svm(line, *_):
    """svm: `label idx:val idx:val ...` — label + sparse feature ids
    (reference ctr_reader svm format)."""
    parts = line.rstrip("\n").split()
    label = np.asarray([float(parts[0])], np.float32)
    ids = [int(p.split(":")[0]) for p in parts[1:]] or [0]
    return [np.asarray(ids, np.int64), label]


def ctr_reader(feed_dict, file_type, file_format, dense_slot_index,
               sparse_slot_index, capacity, thread_num, batch_size,
               file_list, slots=None, name=None):
    """Threaded CTR file reader (reference ctr_reader.py:53): `thread_num`
    reader threads parse gzip/plain csv/svm files into a bounded queue;
    the returned object yields {var_name: batch} dicts like the PyReader
    iterable mode.

    Returns an iterable with .start()/.reset() like the reference reader
    variable contract.
    """
    if file_type not in ("gzip", "plain"):
        raise ValueError("file_type must be gzip or plain")
    if file_format not in ("csv", "svm"):
        raise ValueError("file_format must be csv or svm")
    parse = _parse_csv if file_format == "csv" else _parse_svm
    import queue as _pyqueue

    _EOF = object()

    class _CtrReader:
        def __init__(self):
            self._queue = None
            self._threads = []
            self._files = list(file_list)
            self._stop = threading.Event()

        def start(self):
            self._stop.clear()
            self._queue = _pyqueue.Queue(maxsize=capacity)
            shards = [self._files[i::thread_num]
                      for i in range(thread_num)]
            self._threads = [
                threading.Thread(target=self._read_shard, args=(sh,),
                                 daemon=True) for sh in shards]
            for t in self._threads:
                t.start()
            # the closer captures this generation's queue + producer
            # list so a concurrent reset() (which nulls self._queue)
            # can't crash it or let it poison a later generation's queue
            self._closer = threading.Thread(
                target=self._close_when_done,
                args=(self._queue, list(self._threads)), daemon=True)
            self._closer.start()

        def _read_shard(self, files):
            pending = []
            for path in files:
                opener = gzip.open if file_type == "gzip" else open
                with opener(path, "rt") as f:
                    for line in f:
                        if self._stop.is_set():
                            return
                        pending.append(parse(line, dense_slot_index,
                                             sparse_slot_index))
                        if len(pending) == batch_size:
                            self._push(pending)
                            pending = []
            if pending:
                self._push(pending)

        def _push(self, samples):
            feed = {}
            for si, var in enumerate(feed_dict):
                vals = [s[si] for s in samples]
                maxlen = max(len(v) for v in vals)
                if maxlen == min(len(v) for v in vals):
                    arr = np.stack(vals)
                else:  # ragged sparse ids: zero-pad (segment re-spec)
                    arr = np.zeros((len(vals), maxlen), vals[0].dtype)
                    for i, v in enumerate(vals):
                        arr[i, :len(v)] = v
                feed[var.name] = arr
            while not self._stop.is_set():
                try:
                    self._queue.put(feed, timeout=0.1)
                    return
                except _pyqueue.Full:
                    continue

        def _close_when_done(self, q, producers):
            for t in producers:
                t.join()
            # unconditional: a consumer blocked in q.get() must always
            # be woken, even when reset() raced us (q is this
            # generation's queue, so a late EOF can't poison the next)
            q.put(_EOF)

        def reset(self):
            self._stop.set()
            if self._queue is not None:
                # drain so blocked producers can exit
                try:
                    while True:
                        self._queue.get_nowait()
                except _pyqueue.Empty:
                    pass
            for t in self._threads:
                t.join(timeout=5)
            closer = getattr(self, "_closer", None)
            if closer is not None:
                closer.join(timeout=5)
            self._threads = []
            self._queue = None

        def __iter__(self):
            if self._queue is None:
                self.start()
            while True:
                item = self._queue.get()
                if item is _EOF:
                    self._queue = None
                    return
                yield item

    return _CtrReader()
