"""Inferencer high-level API (reference
python/paddle/fluid/contrib/inferencer.py:31): rebuild the inference net
from a function, load params saved by Trainer.save_params / io.save_params,
and run feeds through it.  `parallel=True` compiles the program through
CompiledProgram (whole-net XLA jit) instead of the interpreted path.
"""

from __future__ import annotations

import contextlib

__all__ = ["Inferencer"]


class Inferencer:
    def __init__(self, infer_func, param_path, place=None, parallel=False):
        from paddle_tpu import framework, io, unique_name
        from paddle_tpu.core.executor import Executor
        from paddle_tpu.core.scope import Scope

        self.param_path = param_path
        self.scope = Scope()
        self.parallel = parallel
        self.place = place

        self.inference_program = framework.Program()
        startup = framework.Program()
        with framework.program_guard(self.inference_program, startup):
            with unique_name.guard():
                self.predict_var = infer_func()

        with self._prog_and_scope_guard():
            io.load_params(Executor(self.place), param_path,
                           main_program=self.inference_program)

        self.exe = Executor(self.place)
        self.inference_program = self.inference_program.clone(for_test=True)
        if parallel:
            from paddle_tpu.core.compiler import CompiledProgram

            self._run_program = CompiledProgram(self.inference_program)
        else:
            self._run_program = self.inference_program

    def infer(self, inputs, return_numpy=True):
        """inputs: {feed_name: ndarray} -> [predict] (reference :80)."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with self._prog_and_scope_guard():
            return self.exe.run(self._run_program, feed=inputs,
                                fetch_list=[self.predict_var.name],
                                return_numpy=return_numpy)

    @contextlib.contextmanager
    def _prog_and_scope_guard(self):
        from paddle_tpu import framework
        from paddle_tpu.core.scope import scope_guard

        with framework.program_guard(main_program=self.inference_program):
            with scope_guard(self.scope):
                yield
