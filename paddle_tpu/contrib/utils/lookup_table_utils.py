"""Distributed-lookup-table persistence helpers (reference
python/paddle/fluid/contrib/utils/lookup_table_utils.py:84
convert_dist_to_sparse_program, :135 load_persistables_for_increment,
:259 load_persistables_for_inference).

A trainer program produced by DistributeTranspiler with a distributed
table replaces lookup_table ops with `prefetch` RPC ops (transpiler
_rewrite_dist_lookups); these helpers turn that program back into a
locally-runnable one (prefetch -> lookup_sparse_table over a local table
var) and load pserver-saved shards into it.
"""

from __future__ import annotations

import logging
import os

import numpy as np

__all__ = ["convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]

_logger = logging.getLogger(__name__)


def _dist_table_info(program):
    """(table_name, emb_dim, prefetch op list) from the trainer program's
    prefetch ops; the transpiler stores the table name on the program."""
    table = getattr(program, "_distributed_lookup_table", None)
    prefetch_ops = [op for op in program.global_block().ops
                    if op.type == "prefetch"]
    if table is None and prefetch_ops:
        # fall back: derive from the first prefetch's table sections
        names = prefetch_ops[0].attrs.get("table_names") or []
        if names:
            table = names[0].rsplit(".block", 1)[0] \
                if ".block" in names[0] else names[0]
    return table, prefetch_ops


def convert_dist_to_sparse_program(program):
    """Replace prefetch RPC ops with local lookup_sparse_table ops over a
    persistable table var (reference :84).  Mutates and returns the
    program; returns None if there is no distributed table, like the
    reference's warning path."""
    from paddle_tpu.core.program import OpDesc

    table, prefetch_ops = _dist_table_info(program)
    if not prefetch_ops or table is None:
        _logger.warning(
            "There are no distributed lookup tables need to be converted")
        return None
    block = program.global_block()
    emb_dim = int(prefetch_ops[0].attrs["emb_dim"])
    height = max(int(sec[1]) for op in prefetch_ops
                 for sec in op.attrs["sections"])
    if not block.has_var(table):
        block.create_var(name=table, shape=[height, emb_dim],
                         dtype="float32", persistable=True)
    new_ops = []
    for op in block.ops:
        if op.type == "prefetch":
            new_ops.append(OpDesc(
                "lookup_sparse_table",
                {"W": [table], "Ids": list(op.inputs["Ids"])},
                {"Out": list(op.outputs["Out"])},
                {"padding_idx": int(op.attrs.get("padding_idx", -1)),
                 "auto_grown_table": False}, op.op_role))
        elif op.type == "send_sparse_grad":
            continue  # local program trains densely or not at all
        else:
            new_ops.append(op)
    block.ops = new_ops
    return program


def _load_table_shards(dirname, table):
    """Concatenate pserver-saved table shard files `<table>.block<i>` (or
    the whole table file) back into one [height, dim] array."""
    for whole in (os.path.join(dirname, table),
                  os.path.join(dirname, table + ".npy")):
        if os.path.exists(whole):
            return np.load(whole, allow_pickle=False)
    shards = sorted(
        (f for f in os.listdir(dirname)
         if f.startswith(table + ".block")),
        key=lambda f: int(f.rsplit("block", 1)[1].removesuffix(".npy")))
    if not shards:
        raise FileNotFoundError(
            f"no saved table '{table}' (or shards) under {dirname}")
    return np.concatenate(
        [np.load(os.path.join(dirname, f), allow_pickle=False)
         for f in shards], axis=0)


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var_name=None,
                                    lookup_table_var_path=None):
    """Load a PS checkpoint for continued training (reference :135):
    ordinary persistables through io.load_persistables, the table from its
    shard files into the scope."""
    from paddle_tpu import io
    from paddle_tpu.core.scope import global_scope

    table, _ = _dist_table_info(program)
    table = lookup_table_var_name or table
    io.load_persistables(executor, dirname, main_program=program)
    if table:
        src = lookup_table_var_path or dirname
        arr = _load_table_shards(os.path.dirname(src)
                                 if os.path.isfile(src) else src,
                                 os.path.basename(src)
                                 if os.path.isfile(src) else table)
        global_scope().var(table).set(arr)
    return program


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name=None):
    """Load params + table for a converted inference program
    (reference :259).  Convert first with
    convert_dist_to_sparse_program."""
    return load_persistables_for_increment(
        dirname, executor, program,
        lookup_table_var_name=lookup_table_var_name)
