"""Contrib utils (reference python/paddle/fluid/contrib/utils/):
HDFSClient shell wrapper + multi_download/multi_upload, and the
distributed-lookup-table persistence helpers.
"""

from paddle_tpu.contrib.utils.hdfs_utils import (HDFSClient,  # noqa: F401
                                                 getfilelist,
                                                 multi_download,
                                                 multi_upload)
from paddle_tpu.contrib.utils.lookup_table_utils import (  # noqa: F401
    convert_dist_to_sparse_program, load_persistables_for_increment,
    load_persistables_for_inference)
