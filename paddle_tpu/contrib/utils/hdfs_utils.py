"""HDFS client as a `hadoop fs` shell wrapper (reference
python/paddle/fluid/contrib/utils/hdfs_utils.py:35 HDFSClient + :437
multi_download / :518 multi_upload).

The reference shells out to `hadoop fs -D... -ls/-put/-get`; this does the
same through subprocess, so it works wherever a hadoop binary is on PATH
and degrades to a clear error where it isn't (zero-egress TPU pods).
Local-path helpers (getfilelist) need no hadoop at all.
"""

from __future__ import annotations

import logging
import os
import subprocess
import time

__all__ = ["HDFSClient", "multi_download", "multi_upload", "getfilelist"]

_logger = logging.getLogger(__name__)


class HDFSClient:
    """reference hdfs_utils.py:35 — every method is one `hadoop fs`
    invocation with the configured fs.default.name / ugi."""

    def __init__(self, hadoop_home, configs):
        self.pre_commands = []
        hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
        self.pre_commands.append(hadoop_bin)
        self.pre_commands.append("fs")
        for key, value in (configs or {}).items():
            self.pre_commands.append("-D%s=%s" % (key, value))

    def __run_hdfs_cmd(self, commands, retry_times=5):
        whole = self.pre_commands + commands
        ret_code, output, errors = 1, b"", b""
        for x in range(retry_times + 1):
            proc = subprocess.Popen(whole, stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE)
            output, errors = proc.communicate()
            ret_code = proc.returncode
            if ret_code == 0:
                break
            time.sleep(0.5)
        _logger.info("run hdfs command: %s (ret=%s)",
                     " ".join(commands), ret_code)
        return ret_code, output.decode(errors="replace"), \
            errors.decode(errors="replace")

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        cmd = ["-put", local_path, hdfs_path]
        if overwrite:
            self.delete(hdfs_path)
        ret, _, _ = self.__run_hdfs_cmd(cmd, retry_times)
        return ret == 0

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        if overwrite and os.path.exists(local_path):
            os.remove(local_path)
        ret, _, _ = self.__run_hdfs_cmd(["-get", hdfs_path, local_path])
        return ret == 0

    def is_exist(self, hdfs_path=None):
        ret, _, _ = self.__run_hdfs_cmd(["-test", "-e", hdfs_path],
                                        retry_times=1)
        return ret == 0

    def is_dir(self, hdfs_path=None):
        ret, _, _ = self.__run_hdfs_cmd(["-test", "-d", hdfs_path],
                                        retry_times=1)
        return ret == 0

    def delete(self, hdfs_path):
        ret, _, _ = self.__run_hdfs_cmd(["-rm", "-r", hdfs_path],
                                        retry_times=1)
        return ret == 0

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False):
        if overwrite:
            self.delete(hdfs_dst_path)
        ret, _, _ = self.__run_hdfs_cmd(["-mv", hdfs_src_path,
                                         hdfs_dst_path])
        return ret == 0

    def makedirs(self, hdfs_path):
        ret, _, _ = self.__run_hdfs_cmd(["-mkdir", "-p", hdfs_path])
        return ret == 0

    def ls(self, hdfs_path):
        ret, out, _ = self.__run_hdfs_cmd(["-ls", hdfs_path],
                                          retry_times=1)
        if ret != 0:
            return []
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return files

    def lsr(self, hdfs_path, excludes=()):
        ret, out, _ = self.__run_hdfs_cmd(["-lsr", hdfs_path],
                                          retry_times=1)
        if ret != 0:
            return []
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8 and not parts[0].startswith("d"):
                path = parts[-1]
                if not any(e in path for e in excludes):
                    files.append(path)
        return files


def getfilelist(path):
    """Recursive local file list (reference :508) — no hadoop needed."""
    rlist = []
    for dir_, _, file_names in os.walk(path):
        for name in file_names:
            rlist.append(os.path.join(dir_, name))
    return rlist


def _download_one(args):
    client, remote, local = args
    return client.download(remote, local)


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """Download this trainer's shard of the files under hdfs_path
    (reference :437: round-robin by trainer_id over the sorted list)."""
    files = sorted(client.lsr(hdfs_path))
    my_files = files[trainer_id::trainers]
    os.makedirs(local_path, exist_ok=True)
    tasks = [(client, f, os.path.join(local_path, os.path.basename(f)))
             for f in my_files]
    if multi_processes <= 1:
        results = [_download_one(t) for t in tasks]
    else:
        from multiprocessing.pool import ThreadPool

        with ThreadPool(multi_processes) as pool:
            results = pool.map(_download_one, tasks)
    return [t[2] for t, ok in zip(tasks, results) if ok]


def _upload_one(args):
    client, local, remote = args
    return client.upload(remote, local)


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """Upload every file under local_path (reference :518)."""
    files = getfilelist(local_path)
    client.makedirs(hdfs_path)
    tasks = [(client, f,
              os.path.join(hdfs_path, os.path.relpath(f, local_path)))
             for f in files]
    if multi_processes <= 1:
        results = [_upload_one(t) for t in tasks]
    else:
        from multiprocessing.pool import ThreadPool

        with ThreadPool(multi_processes) as pool:
            results = pool.map(_upload_one, tasks)
    return sum(bool(r) for r in results)
