"""Event-driven high-level Trainer / checkpoint config (reference
python/paddle/fluid/contrib/trainer.py:169 Trainer, :40-100 events,
:100 CheckpointConfig) and its companion Inferencer lives in
contrib/inferencer.py.

The reference drives Executor or ParallelExecutor per device; here the
parallel path is the CompiledProgram data-parallel step (XLA shards the
batch over the mesh).  PS-mode env-var bootstrapping uses the same
PADDLE_TRAINING_ROLE/PSERVER env contract via DistributeTranspiler.
"""

from __future__ import annotations

import os

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer",
           "build_feed_var_list"]


class BeginEpochEvent:
    """reference trainer.py:40."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    """reference trainer.py:52."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    """reference trainer.py:64; set fetch_metrics False to skip fetches
    for speed."""

    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    """reference trainer.py:83."""

    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference trainer.py:100 — periodic persistable snapshots with
    epoch/step resume bookkeeping."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None
        self.pserver_id = None
        self.lookup_table_name = None


def build_feed_var_list(program, feed_order):
    """reference trainer.py:630 — resolve feed var descs from a name list
    or {name: position} dict."""
    from paddle_tpu.framework import Program

    if not isinstance(program, Program):
        raise TypeError("The 'program' should be an object of Program")
    if feed_order is None:
        raise ValueError("feed_order=None requires explicit feed names "
                         "in this implementation — pass a list or dict")
    if isinstance(feed_order, list):
        return [program.global_block().var(name) for name in feed_order]
    if not isinstance(feed_order, dict):
        raise TypeError("The 'feed_order' should be either None, list or "
                        "dict.")
    if sorted(feed_order.values()) != list(range(len(feed_order))):
        raise ValueError("The values of 'feed_order' should be a "
                         "permutation of [0, len(feed_order))")
    return [program.global_block().var(name) for name, _ in
            sorted(feed_order.items(), key=lambda item: item[1])]


class Trainer:
    """reference trainer.py:169.

    train_func() -> loss var (or [loss, metrics...]); optimizer_func() ->
    Optimizer.  Events fire around every epoch/step; `parallel=True` runs
    the step through CompiledProgram.with_data_parallel (XLA mesh DP).
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        from paddle_tpu import framework, io, unique_name
        from paddle_tpu.core.executor import Executor
        from paddle_tpu.core.scope import Scope
        from paddle_tpu.optimizer import Optimizer

        self.__stop = False
        self.parallel = parallel
        self.trainer_id = 0
        self.checkpoint_cfg = checkpoint_config
        if self.checkpoint_cfg is not None:
            assert isinstance(self.checkpoint_cfg, CheckpointConfig)
            serial = _get_latest_checkpoint_serial(
                self.checkpoint_cfg.checkpoint_dir)
            self.checkpoint_cfg.load_serial = serial if serial >= 0 else None

        self.scope = Scope()
        self.place = place
        self.startup_program = framework.Program()
        self.train_program = framework.Program()

        with framework.program_guard(self.train_program,
                                     self.startup_program):
            with unique_name.guard():
                outs = train_func()
                self.train_func_outputs = outs if isinstance(outs, list) \
                    else [outs]
                self.test_program = self.train_program.clone(for_test=True)
                loss = self.train_func_outputs[0]
                optimizer = optimizer_func()
                if not isinstance(optimizer, Optimizer):
                    raise TypeError(
                        "The optimizer should be an instance of Optimizer")
                optimize_ops, params_grads = optimizer.minimize(loss)

        self._dist_transpile_if_necessary(optimize_ops, params_grads)

        with self._prog_and_scope_guard():
            exe = Executor(self.place)
            exe.run(self.startup_program)
            if self.checkpoint_cfg and \
                    self.checkpoint_cfg.load_serial is not None:
                self._load_checkpoint(exe)
            if param_path and os.path.isdir(param_path):
                io.load_persistables(exe, dirname=param_path,
                                     main_program=self.train_program)
        self._compiled = None

    # -- distributed bootstrap (reference :324) ---------------------------
    def _dist_transpile_if_necessary(self, optimize_ops, params_grads):
        if "PADDLE_TRAINING_ROLE" not in os.environ:
            return
        from paddle_tpu.transpiler import DistributeTranspiler

        port = os.getenv("PADDLE_PSERVER_PORT", "6174")
        pserver_ips = os.getenv("PADDLE_PSERVER_IPS", "")
        eplist = [f"{ip}:{port}" for ip in pserver_ips.split(",") if ip]
        pserver_endpoints = ",".join(eplist)
        trainers = int(os.getenv("PADDLE_TRAINERS", "1"))
        current_endpoint = os.getenv("PADDLE_CURRENT_IP", "") + ":" + port
        self.trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        training_role = os.getenv("PADDLE_TRAINING_ROLE")
        with self._prog_and_scope_guard():
            t = DistributeTranspiler()
            t.transpile(self.trainer_id, program=self.train_program,
                        pservers=pserver_endpoints, trainers=trainers)
            if training_role == "PSERVER":
                self.train_program = t.get_pserver_program(current_endpoint)
                self.startup_program = t.get_startup_program(
                    current_endpoint, self.train_program)
            elif training_role == "TRAINER":
                self.train_program = t.get_trainer_program()
            else:
                raise ValueError(
                    "TRAINING_ROLE environment variable must be either "
                    "TRAINER or PSERVER")

    def stop(self):
        self.__stop = True

    # -- train/test (reference :379,:407) ---------------------------------
    def train(self, num_epochs, event_handler, reader=None,
              feed_order=None):
        from paddle_tpu.core.executor import Executor

        if os.getenv("PADDLE_TRAINING_ROLE", "") == "PSERVER":
            with self._prog_and_scope_guard():
                exe = Executor(self.place)
                exe.run(self.train_program)
                return
        self._train_by_executor(num_epochs, event_handler, reader,
                                feed_order)

    def test(self, reader, feed_order):
        from paddle_tpu.core.executor import Executor
        from paddle_tpu.core.scope import scope_guard
        from paddle_tpu.data_feeder import DataFeeder

        with scope_guard(self.scope):
            feed_vars = build_feed_var_list(self.test_program, feed_order)
            feeder = DataFeeder(feed_list=feed_vars, place=self.place)
            exe = Executor(self.place)
            import numpy as np

            fetch = [v.name for v in self.train_func_outputs]
            accumulated = [0.0] * len(fetch)
            count = 0
            for data in reader():
                outs = exe.run(program=self.test_program,
                               feed=feeder.feed(data), fetch_list=fetch)
                accumulated = [a + float(np.ravel(o)[0])
                               for a, o in zip(accumulated, outs)]
                count += 1
            return [a / max(count, 1) for a in accumulated]

    def save_params(self, param_path):
        from paddle_tpu import io
        from paddle_tpu.core.executor import Executor

        with self._prog_and_scope_guard():
            io.save_persistables(Executor(self.place), dirname=param_path,
                                 main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        from paddle_tpu import io
        from paddle_tpu.core.executor import Executor

        with self._prog_and_scope_guard():
            targets = [self.train_func_outputs[i]
                       for i in target_var_indexes]
            io.save_inference_model(param_path, feeded_var_names, targets,
                                    Executor(self.place),
                                    main_program=self.test_program)

    # -- internals --------------------------------------------------------
    def _prog_and_scope_guard(self):
        import contextlib

        from paddle_tpu import framework
        from paddle_tpu.core.scope import scope_guard

        @contextlib.contextmanager
        def guard():
            with framework.program_guard(self.train_program,
                                         self.startup_program):
                with scope_guard(self.scope):
                    yield

        return guard()

    def _step_program(self):
        if not self.parallel:
            return self.train_program
        if self._compiled is None:
            from paddle_tpu.core.compiler import CompiledProgram

            self._compiled = CompiledProgram(
                self.train_program).with_data_parallel(
                    loss_name=self.train_func_outputs[0].name)
        return self._compiled

    def _train_by_executor(self, num_epochs, event_handler, reader,
                           feed_order):
        from paddle_tpu.core.executor import Executor
        from paddle_tpu.data_feeder import DataFeeder

        with self._prog_and_scope_guard():
            feed_vars = build_feed_var_list(self.train_program, feed_order)
            feeder = DataFeeder(feed_list=feed_vars, place=self.place)
            exe = Executor(self.place)
            cfg = self.checkpoint_cfg
            start_epoch = cfg.epoch_id if cfg and cfg.load_serial is not \
                None else 0
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stop:
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = [v.name for v in self.train_func_outputs] \
                        if begin.fetch_metrics else []
                    metrics = exe.run(self._step_program(),
                                      feed=feeder.feed(data),
                                      fetch_list=fetch)
                    if cfg and step_id % cfg.step_interval == 0 and \
                            epoch_id % cfg.epoch_interval == 0:
                        self._save_checkpoint(exe, epoch_id, step_id)
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                event_handler(EndEpochEvent(epoch_id))

    # -- checkpoints (reference trainer.py:655+ private checkpoint API) ---
    def _ckpt_dir(self, serial):
        return os.path.join(self.checkpoint_cfg.checkpoint_dir,
                            str(serial))

    def _save_checkpoint(self, exe, epoch_id, step_id):
        from paddle_tpu import io

        cfg = self.checkpoint_cfg
        serial = (cfg.load_serial or 0) + 1
        d = self._ckpt_dir(serial)
        os.makedirs(d, exist_ok=True)
        io.save_persistables(exe, dirname=d,
                             main_program=self.train_program)
        with open(os.path.join(d, "_SUCCESS"), "w") as f:
            f.write(f"{epoch_id} {step_id}")
        cfg.load_serial = serial
        cfg.epoch_id, cfg.step_id = epoch_id, step_id
        # retention: keep the newest max_num_checkpoints
        serials = sorted(
            (int(s) for s in os.listdir(cfg.checkpoint_dir)
             if s.isdigit()), reverse=True)
        for old in serials[cfg.max_num_checkpoints:]:
            import shutil

            shutil.rmtree(self._ckpt_dir(old), ignore_errors=True)

    def _load_checkpoint(self, exe):
        from paddle_tpu import io

        cfg = self.checkpoint_cfg
        d = self._ckpt_dir(cfg.load_serial)
        io.load_persistables(exe, dirname=d,
                             main_program=self.train_program)
        marker = os.path.join(d, "_SUCCESS")
        if os.path.exists(marker):
            with open(marker) as f:
                parts = f.read().split()
            if len(parts) == 2:
                cfg.epoch_id, cfg.step_id = int(parts[0]), int(parts[1])


def _get_latest_checkpoint_serial(checkpoint_dir):
    """Largest serial subdir containing a _SUCCESS marker, else -1
    (reference trainer.py _get_latest_checkpoint_serial)."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return -1
    best = -1
    for name in os.listdir(checkpoint_dir):
        if name.isdigit() and os.path.exists(
                os.path.join(checkpoint_dir, name, "_SUCCESS")):
            best = max(best, int(name))
    return best
