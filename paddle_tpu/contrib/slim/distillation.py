"""Knowledge distillation (reference:
/root/reference/python/paddle/fluid/contrib/slim/distillation/ —
merge teacher graph into student graph, soft-label / FSP / L2 losses).
"""

from __future__ import annotations


def merge(teacher_program, student_program, data_name_map, place=None,
          scope=None, name_prefix="teacher_"):
    """Clone the teacher's ops/vars into the student program under a
    name prefix; data vars are unified per data_name_map
    {teacher_data_name: student_data_name}.  Teacher vars are frozen
    (stop_gradient).  Reference: slim/distillation/distiller graph
    merge."""
    t_block = teacher_program.global_block()
    s_block = student_program.global_block()

    def rename(n):
        if n in data_name_map:
            return data_name_map[n]
        return name_prefix + n

    for var in t_block.vars.values():
        if var.name in data_name_map:
            continue
        new_name = rename(var.name)
        if not s_block.has_var(new_name):
            nv = s_block.create_var(
                name=new_name, shape=var.shape, dtype=var.dtype,
                persistable=var.persistable, stop_gradient=True)
            nv.trainable = False
    for op in t_block.ops:
        if op.type in ("feed", "fetch"):
            continue
        ins = {s: [rename(n) for n in ns] for s, ns in op.inputs.items()}
        outs = {s: [rename(n) for n in ns]
                for s, ns in op.outputs.items()}
        s_block.append_op(type=op.type, inputs=ins, outputs=outs,
                          attrs=dict(op.attrs), op_role=op.op_role,
                          infer_shape=False)
    # teacher params must be initialized: copy values if a scope given
    if scope is not None:
        import jax.numpy as jnp
        import numpy as np

        for var in t_block.vars.values():
            if not var.persistable or var.name in data_name_map:
                continue
            src = scope.find_var(var.name)
            if src is not None and src.get() is not None:
                scope.var(rename(var.name)).set(
                    jnp.asarray(np.asarray(src.get())))


def soft_label_loss(teacher_logits, student_logits,
                    teacher_temperature=1.0, student_temperature=1.0):
    """KL(teacher_T || student_T) soft-label loss (reference
    slim/distillation soft_label_loss)."""
    from paddle_tpu import layers

    t = layers.softmax(layers.scale(teacher_logits,
                                    scale=1.0 / teacher_temperature))
    s = layers.log_softmax(layers.scale(student_logits,
                                        scale=1.0 / student_temperature))
    return layers.scale(
        layers.mean(layers.reduce_sum(
            layers.elementwise_mul(t, s), dim=-1)), scale=-1.0)


def l2_loss(teacher_feature, student_feature):
    from paddle_tpu import layers

    return layers.mean(layers.square_error_cost(student_feature,
                                                teacher_feature))


def fsp_loss(teacher_a, teacher_b, student_a, student_b):
    """Flow-of-solution-procedure loss: L2 between layer-pair Gram
    matrices (reference slim/distillation fsp_loss)."""
    from paddle_tpu import layers

    def fsp_matrix(a, b):
        # a: [B, Ca, H, W], b: [B, Cb, H, W] -> [B, Ca, Cb]
        ba = layers.reshape(a, [0, int(a.shape[1]), -1])
        bb = layers.reshape(b, [0, int(b.shape[1]), -1])
        m = layers.matmul(ba, bb, transpose_y=True)
        hw = float(int(a.shape[2]) * int(a.shape[3]))
        return layers.scale(m, scale=1.0 / hw)

    tm = fsp_matrix(teacher_a, teacher_b)
    sm = fsp_matrix(student_a, student_b)
    return layers.mean(layers.square_error_cost(sm, tm))
