"""slim — model compression: quantization (QAT + PTQ), filter pruning,
knowledge distillation, SA-NAS.

Reference parity: /root/reference/python/paddle/fluid/contrib/slim/
(quantization/, prune/, distillation/, nas/ sub-packages).
"""

from paddle_tpu.contrib.slim.quantization import (
    QuantizationFreezePass,
    QuantizationTransformPass,
    convert_to_int8_execution,
    convert_to_int8_inference,
    post_training_quantize,
    quant_aware,
)

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "quant_aware", "post_training_quantize",
           "convert_to_int8_execution", "convert_to_int8_inference",
           "Pruner", "flops",
           "SAController", "distillation", "nas", "prune"]

from paddle_tpu.contrib.slim import distillation  # noqa: F401
from paddle_tpu.contrib.slim import nas  # noqa: F401
from paddle_tpu.contrib.slim import prune  # noqa: F401
from paddle_tpu.contrib.slim.nas import SAController  # noqa: F401
from paddle_tpu.contrib.slim.prune import Pruner, flops  # noqa: F401
