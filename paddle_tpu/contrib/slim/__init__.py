"""slim — quantization (QAT + PTQ).

Reference parity: /root/reference/python/paddle/fluid/contrib/slim/
(quantization passes; the NAS/pruning/distillation sub-packages of the
reference are orthogonal training recipes, not runtime components).
"""

from paddle_tpu.contrib.slim.quantization import (
    QuantizationFreezePass,
    QuantizationTransformPass,
    post_training_quantize,
    quant_aware,
)

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "quant_aware", "post_training_quantize"]
